"""Detection op tests (reference unittests/test_prior_box_op.py,
test_bipartite_match_op.py, test_multiclass_nms_op.py, test_roi_pool_op.py,
test_iou_similarity_op.py, test_ssd_loss.py family) — numpy references."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid.lod import create_lod_tensor


def _run(build_fn, feed):
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        fetches = build_fn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=list(fetches))


def _np_iou(a, b):
    xmin = np.maximum(a[:, None, 0], b[None, :, 0])
    ymin = np.maximum(a[:, None, 1], b[None, :, 1])
    xmax = np.minimum(a[:, None, 2], b[None, :, 2])
    ymax = np.minimum(a[:, None, 3], b[None, :, 3])
    inter = np.maximum(xmax - xmin, 0) * np.maximum(ymax - ymin, 0)
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    union = area_a[:, None] + area_b[None, :] - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-10), 0.0)


def test_iou_similarity():
    rng = np.random.RandomState(0)
    a = np.sort(rng.rand(4, 4).astype(np.float32), axis=-1)[:, [0, 2, 1, 3]]
    b = np.sort(rng.rand(6, 4).astype(np.float32), axis=-1)[:, [0, 2, 1, 3]]
    # canonical (xmin, ymin, xmax, ymax)
    a = np.stack([a[:, 0], a[:, 1], a[:, 2], a[:, 3]], axis=1)

    def build():
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[4], dtype="float32",
                              append_batch_size=False)
        return [fluid.layers.iou_similarity(x, y)]

    (out,) = _run(build, {"x": a, "y": b})
    np.testing.assert_allclose(np.asarray(out), _np_iou(a, b), atol=1e-5)


def test_prior_box_values():
    im = np.zeros((1, 3, 32, 32), np.float32)
    feat = np.zeros((1, 8, 4, 4), np.float32)

    def build():
        f = fluid.layers.data("feat", shape=[8, 4, 4], dtype="float32")
        i = fluid.layers.data("im", shape=[3, 32, 32], dtype="float32")
        boxes, var = fluid.layers.prior_box(
            f, i, min_sizes=[8.0], max_sizes=[16.0],
            aspect_ratios=[1.0, 2.0], flip=True, clip=True)
        return [boxes, var]

    boxes, var = _run(build, {"feat": feat, "im": im})
    boxes, var = np.asarray(boxes), np.asarray(var)
    # aspect ratios expand to [1, 2, 0.5] -> 3 + 1 max_size prior = 4
    assert boxes.shape == (4, 4, 4, 4)
    assert var.shape == (4, 4, 4, 4)
    # cell (0,0): center (0.5*8, 0.5*8) = (4, 4); ar=1 prior is 8x8
    np.testing.assert_allclose(
        boxes[0, 0, 0], [0.0, 0.0, 8.0 / 32, 8.0 / 32], atol=1e-5)
    # max_size prior: sqrt(8*16) = 11.31
    s = np.sqrt(8.0 * 16.0) / 2
    np.testing.assert_allclose(
        boxes[0, 0, 3], [0.0, 0.0, (4 + s) / 32, (4 + s) / 32], atol=1e-4)
    np.testing.assert_allclose(var[0, 0, 0], [0.1, 0.1, 0.2, 0.2])
    assert boxes.min() >= 0.0 and boxes.max() <= 1.0


def test_box_coder_encode_decode_roundtrip():
    rng = np.random.RandomState(1)
    prior = np.array([[0.1, 0.1, 0.5, 0.5], [0.2, 0.3, 0.7, 0.8]],
                     np.float32)
    var = np.array([[0.1, 0.1, 0.2, 0.2]] * 2, np.float32)
    gt = np.array([[0.15, 0.2, 0.45, 0.6], [0.3, 0.3, 0.6, 0.7],
                   [0.05, 0.1, 0.4, 0.45]], np.float32)

    def build():
        p = fluid.layers.data("p", shape=[2, 4], dtype="float32",
                              append_batch_size=False)
        pv = fluid.layers.data("pv", shape=[2, 4], dtype="float32",
                               append_batch_size=False)
        t = fluid.layers.data("t", shape=[4], dtype="float32")
        enc = fluid.layers.box_coder(p, pv, t, "encode_center_size")
        dec = fluid.layers.box_coder(p, pv, enc, "decode_center_size")
        return [enc, dec]

    enc, dec = _run(build, {"p": prior, "pv": var, "t": gt})
    enc, dec = np.asarray(enc), np.asarray(dec)
    assert enc.shape == (3, 2, 4)
    # numpy reference encode for (gt0, prior0)
    pw, ph = 0.4, 0.4
    pcx, pcy = 0.3, 0.3
    tw, th = 0.3, 0.4
    tcx, tcy = 0.3, 0.4
    ref = [(tcx - pcx) / pw / 0.1, (tcy - pcy) / ph / 0.1,
           np.log(tw / pw) / 0.2, np.log(th / ph) / 0.2]
    np.testing.assert_allclose(enc[0, 0], ref, atol=1e-5)
    # decode(encode(gt)) == gt for every (gt, prior) pair
    np.testing.assert_allclose(dec, np.broadcast_to(gt[:, None, :], dec.shape),
                               atol=1e-5)


def _np_bipartite(dist):
    d = dist.copy()
    M = d.shape[1]
    midx = np.full(M, -1, np.int32)
    mdist = np.zeros(M, np.float32)
    while True:
        r, c = np.unravel_index(np.argmax(d), d.shape)
        if d[r, c] <= 0:
            break
        midx[c] = r
        mdist[c] = d[r, c]
        d[r, :] = -1
        d[:, c] = -1
    return midx, mdist


def test_bipartite_match():
    rng = np.random.RandomState(2)
    dist = rng.rand(2, 3, 5).astype(np.float32)

    def build():
        d = fluid.layers.data("d", shape=[3, 5], dtype="float32")
        mi, md = fluid.layers.bipartite_match(d)
        return [mi, md]

    mi, md = _run(build, {"d": dist})
    for b in range(2):
        ref_i, ref_d = _np_bipartite(dist[b])
        np.testing.assert_array_equal(np.asarray(mi)[b], ref_i)
        np.testing.assert_allclose(np.asarray(md)[b], ref_d, atol=1e-6)


def test_bipartite_match_per_prediction():
    dist = np.array([[[0.9, 0.1, 0.6, 0.55],
                      [0.2, 0.8, 0.3, 0.1]]], np.float32)

    def build():
        d = fluid.layers.data("d", shape=[2, 4], dtype="float32")
        mi, md = fluid.layers.bipartite_match(d, "per_prediction", 0.5)
        return [mi, md]

    mi, md = _run(build, {"d": dist})
    mi = np.asarray(mi)[0]
    # bipartite: col0->row0 (0.9), col1->row1 (0.8); per_prediction fills
    # col2 (best row 0, 0.6>0.5) and col3 (0.55>0.5)
    np.testing.assert_array_equal(mi, [0, 1, 0, 0])


def test_target_assign():
    # X [B=2, G=2, K=1] labels; matches [B, P=3]
    x = np.array([[[1.0], [2.0]], [[3.0], [4.0]]], np.float32)
    midx = np.array([[0, -1, 1], [1, 0, -1]], np.int32)

    def build():
        xv = fluid.layers.data("x", shape=[2, 1], dtype="float32")
        mv = fluid.layers.data("m", shape=[3], dtype="int32")
        out, w = fluid.layers.target_assign(xv, mv, mismatch_value=9)
        return [out, w]

    out, w = _run(build, {"x": x, "m": midx})
    np.testing.assert_allclose(np.asarray(out)[..., 0],
                               [[1, 9, 2], [4, 3, 9]])
    np.testing.assert_allclose(np.asarray(w)[..., 0],
                               [[1, 0, 1], [1, 1, 0]])


def _np_nms(boxes, scores, thresh, top_k):
    order = np.argsort(-scores)
    if top_k > 0:
        order = order[:top_k]
    keep = []
    sup = np.zeros(len(order), bool)
    for ii, i in enumerate(order):
        if sup[ii]:
            continue
        keep.append(i)
        for jj in range(ii + 1, len(order)):
            if _np_iou(boxes[i:i + 1], boxes[order[jj]:order[jj] + 1])[0, 0] \
                    > thresh:
                sup[jj] = True
    return keep


def test_multiclass_nms():
    rng = np.random.RandomState(3)
    M, C = 12, 3
    boxes = np.zeros((1, M, 4), np.float32)
    centers = rng.rand(M, 2) * 0.8 + 0.1
    wh = rng.rand(M, 2) * 0.2 + 0.05
    boxes[0, :, 0] = centers[:, 0] - wh[:, 0]
    boxes[0, :, 1] = centers[:, 1] - wh[:, 1]
    boxes[0, :, 2] = centers[:, 0] + wh[:, 0]
    boxes[0, :, 3] = centers[:, 1] + wh[:, 1]
    scores = rng.rand(1, C, M).astype(np.float32)

    def build():
        b = fluid.layers.data("b", shape=[M, 4], dtype="float32")
        s = fluid.layers.data("s", shape=[C, M], dtype="float32")
        out = fluid.layers.multiclass_nms(b, s, score_threshold=0.3,
                                          nms_top_k=10, keep_top_k=8,
                                          nms_threshold=0.4,
                                          background_label=0)
        return [out]

    (out,) = _run(build, {"b": boxes, "s": scores})
    # NMS output is ragged: fetched as a packed LoDTensor (valid rows only)
    got_valid = out.numpy() if hasattr(out, "numpy") else np.asarray(out)
    # numpy reference: classes 1..C-1, score>0.3, NMS 0.4, keep top 8
    cand = []
    for c in range(1, C):
        sc = scores[0, c].copy()
        valid = sc > 0.3
        sc_m = np.where(valid, sc, -np.inf)
        keep = _np_nms(boxes[0], sc_m, 0.4, 10)
        for i in keep:
            if valid[i]:
                cand.append((c, sc[i], boxes[0, i]))
    cand.sort(key=lambda t: -t[1])
    cand = cand[:8]
    assert len(got_valid) == len(cand)
    for row, (c, sc, bx) in zip(got_valid, cand):
        assert int(row[0]) == c
        np.testing.assert_allclose(row[1], sc, atol=1e-5)
        np.testing.assert_allclose(row[2:], bx, atol=1e-5)


def test_roi_pool():
    x = np.arange(1 * 1 * 6 * 6, dtype=np.float32).reshape(1, 1, 6, 6)
    rois = np.array([[[0.0, 0.0, 3.0, 3.0], [2.0, 2.0, 5.0, 5.0]]],
                    np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[1, 6, 6], dtype="float32")
        rv = fluid.layers.data("r", shape=[2, 4], dtype="float32")
        out = fluid.layers.roi_pool(xv, rv, pooled_height=2, pooled_width=2,
                                    spatial_scale=1.0)
        return [out]

    (out,) = _run(build, {"x": x, "r": rois})
    out = np.asarray(out)
    assert out.shape == (1, 2, 1, 2, 2)
    # roi0 covers rows 0..3, cols 0..3 (4x4), 2x2 max pool of x[0..3,0..3]
    img = x[0, 0]
    np.testing.assert_allclose(
        out[0, 0, 0], [[img[0:2, 0:2].max(), img[0:2, 2:4].max()],
                       [img[2:4, 0:2].max(), img[2:4, 2:4].max()]])


def test_roi_align_constant_map():
    # constant feature map -> every aligned value equals the constant
    x = np.full((1, 2, 8, 8), 5.0, np.float32)
    rois = np.array([[[1.0, 1.0, 6.0, 6.0]]], np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[2, 8, 8], dtype="float32")
        rv = fluid.layers.data("r", shape=[1, 4], dtype="float32")
        out = fluid.layers.roi_align(xv, rv, pooled_height=3, pooled_width=3,
                                     spatial_scale=1.0, sampling_ratio=2)
        return [out]

    (out,) = _run(build, {"x": x, "r": rois})
    np.testing.assert_allclose(np.asarray(out), 5.0, atol=1e-5)


def test_anchor_generator():
    feat = np.zeros((1, 8, 2, 2), np.float32)

    def build():
        f = fluid.layers.data("f", shape=[8, 2, 2], dtype="float32")
        a, v = fluid.layers.anchor_generator(
            f, anchor_sizes=[32.0, 64.0], aspect_ratios=[1.0],
            stride=[16.0, 16.0])
        return [a, v]

    a, v = _run(build, {"f": feat})
    a = np.asarray(a)
    assert a.shape == (2, 2, 2, 4)
    # reference pixel-grid convention (anchor_generator_op.h:55,:74):
    # center = 0*16 + 0.5*(16-1) = 7.5; size-32 extents are
    # +/-(32-1)/2 = +/-15.5 -> inclusive widths of 31
    np.testing.assert_allclose(a[0, 0, 0], [7.5 - 15.5, 7.5 - 15.5,
                                            7.5 + 15.5, 7.5 + 15.5])
    widths = a[..., 2] - a[..., 0]
    assert set(np.unique(widths)) == {31.0, 63.0}


def test_generate_proposals_shapes():
    rng = np.random.RandomState(4)
    B, A, H, W = 1, 3, 4, 4
    scores = rng.rand(B, A, H, W).astype(np.float32)
    deltas = (rng.rand(B, 4 * A, H, W).astype(np.float32) - 0.5) * 0.2
    im_info = np.array([[64.0, 64.0, 1.0]], np.float32)
    anchors = np.zeros((H, W, A, 4), np.float32)
    for h in range(H):
        for w in range(W):
            for a in range(A):
                cx, cy = w * 16 + 8, h * 16 + 8
                s = 8 * (a + 1)
                anchors[h, w, a] = [cx - s, cy - s, cx + s, cy + s]
    variances = np.full((H, W, A, 4), 0.1, np.float32)

    def build():
        s = fluid.layers.data("s", shape=[A, H, W], dtype="float32")
        d = fluid.layers.data("d", shape=[4 * A, H, W], dtype="float32")
        ii = fluid.layers.data("ii", shape=[3], dtype="float32")
        an = fluid.layers.data("an", shape=[H, W, A, 4], dtype="float32",
                               append_batch_size=False)
        va = fluid.layers.data("va", shape=[H, W, A, 4], dtype="float32",
                               append_batch_size=False)
        rois, probs = fluid.layers.generate_proposals(
            s, d, ii, an, va, pre_nms_top_n=30, post_nms_top_n=10,
            nms_thresh=0.7, min_size=4.0)
        return [rois, probs]

    rois, probs = _run(build, {"s": scores, "d": deltas, "ii": im_info,
                               "an": anchors, "va": variances})
    # ragged outputs fetched as packed LoDTensors (valid rows only)
    rois = rois.numpy() if hasattr(rois, "numpy") else np.asarray(rois)
    probs = probs.numpy() if hasattr(probs, "numpy") else np.asarray(probs)
    assert rois.shape[1] == 4 and 1 <= rois.shape[0] <= 10
    assert probs.shape == (rois.shape[0], 1)
    # all boxes inside image
    assert rois.min() >= 0.0 and rois.max() <= 63.0
    # probs sorted desc
    assert np.all(np.diff(probs[:, 0]) <= 1e-6)


def test_polygon_box_transform():
    x = np.ones((1, 4, 2, 3), np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[4, 2, 3], dtype="float32")
        return [fluid.layers.polygon_box_transform(xv)]

    (out,) = _run(build, {"x": x})
    out = np.asarray(out)
    for h in range(2):
        for w in range(3):
            np.testing.assert_allclose(out[0, 0, h, w], 4 * w - 1)  # even c
            np.testing.assert_allclose(out[0, 1, h, w], 4 * h - 1)  # odd c


def test_box_clip():
    boxes = np.array([[[-5.0, -5.0, 70.0, 30.0]]], np.float32)
    im_info = np.array([[40.0, 60.0, 1.0]], np.float32)

    def build():
        b = fluid.layers.data("b", shape=[1, 4], dtype="float32")
        ii = fluid.layers.data("ii", shape=[3], dtype="float32")
        return [fluid.layers.box_clip(b, ii)]

    (out,) = _run(build, {"b": boxes, "ii": im_info})
    np.testing.assert_allclose(np.asarray(out)[0, 0], [0, 0, 59, 30])


def test_ssd_loss_end_to_end():
    """Full SSD loss: match + mine + assign + losses; check finite loss and
    that gradients flow to the conv head params (reference test_ssd_loss)."""
    rng = np.random.RandomState(5)
    N, P, C, G = 2, 10, 4, 3
    prior = np.sort(rng.rand(P, 4).astype(np.float32), axis=1)
    pvar = np.full((P, 4), 0.1, np.float32)
    gt_rows = np.sort(rng.rand(5, 4).astype(np.float32), axis=1)
    gt_label_rows = rng.randint(1, C, (5, 1)).astype(np.int32)
    lens = [2, 3]

    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        feat = fluid.layers.data("feat", shape=[8], dtype="float32")
        loc = fluid.layers.fc(feat, size=P * 4)
        loc = fluid.layers.reshape(loc, [-1, P, 4])
        conf = fluid.layers.fc(feat, size=P * C)
        conf = fluid.layers.reshape(conf, [-1, P, C])
        gt_box = fluid.layers.data("gt_box", shape=[4], dtype="float32",
                                   lod_level=1)
        gt_label = fluid.layers.data("gt_label", shape=[1], dtype="int32",
                                     lod_level=1)
        pb = fluid.layers.data("pb", shape=[P, 4], dtype="float32",
                               append_batch_size=False)
        pbv = fluid.layers.data("pbv", shape=[P, 4], dtype="float32",
                                append_batch_size=False)
        loss = fluid.layers.ssd_loss(loc, conf, gt_box, gt_label, pb, pbv)
        avg = fluid.layers.mean(loss)
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        opt.minimize(avg)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"feat": rng.randn(N, 8).astype(np.float32),
            "gt_box": create_lod_tensor(gt_rows, [lens]),
            "gt_label": create_lod_tensor(gt_label_rows, [lens]),
            "pb": prior, "pbv": pvar}
    losses = []
    for _ in range(4):
        (lv,) = exe.run(main, feed=feed, fetch_list=[avg])
        lv = float(np.asarray(lv))
        assert np.isfinite(lv)
        losses.append(lv)
    assert losses[-1] < losses[0]  # training reduces the loss


def test_detection_output_end_to_end():
    rng = np.random.RandomState(6)
    N, P, C = 1, 6, 3
    prior = np.zeros((P, 4), np.float32)
    centers = rng.rand(P, 2) * 0.6 + 0.2
    prior[:, 0] = centers[:, 0] - 0.1
    prior[:, 1] = centers[:, 1] - 0.1
    prior[:, 2] = centers[:, 0] + 0.1
    prior[:, 3] = centers[:, 1] + 0.1
    pvar = np.full((P, 4), 0.1, np.float32)
    loc = (rng.rand(N, P, 4).astype(np.float32) - 0.5) * 0.1
    scores = rng.rand(N, P, C).astype(np.float32)

    def build():
        l = fluid.layers.data("l", shape=[P, 4], dtype="float32")
        s = fluid.layers.data("s", shape=[P, C], dtype="float32")
        pb = fluid.layers.data("pb", shape=[P, 4], dtype="float32",
                               append_batch_size=False)
        pbv = fluid.layers.data("pbv", shape=[P, 4], dtype="float32",
                                append_batch_size=False)
        out = fluid.layers.detection_output(l, s, pb, pbv,
                                            score_threshold=0.01,
                                            nms_threshold=0.45,
                                            keep_top_k=5)
        return [out]

    (out,) = _run(build, {"l": loc, "s": scores, "pb": prior, "pbv": pvar})
    valid = out.numpy() if hasattr(out, "numpy") else np.asarray(out)
    assert valid.shape[1] == 6 and 1 <= valid.shape[0] <= 5
    # labels exclude background 0; scores in (0, 1)
    assert np.all(valid[:, 0] >= 1)
    assert np.all((valid[:, 1] > 0) & (valid[:, 1] <= 1))


def test_density_prior_box():
    im = np.zeros((1, 3, 32, 32), np.float32)
    feat = np.zeros((1, 8, 4, 4), np.float32)

    def build():
        f = fluid.layers.data("feat", shape=[8, 4, 4], dtype="float32")
        i = fluid.layers.data("im", shape=[3, 32, 32], dtype="float32")
        boxes, var = fluid.layers.density_prior_box(
            f, i, densities=[2], fixed_sizes=[8.0], fixed_ratios=[1.0],
            clip=True)
        return [boxes, var]

    boxes, var = _run(build, {"feat": feat, "im": im})
    boxes = np.asarray(boxes)
    # density 2 * 1 ratio -> 4 priors per cell
    assert boxes.shape == (4, 4, 4, 4)
    assert boxes.min() >= 0.0 and boxes.max() <= 1.0
    # all priors are 8x8 (before clipping) centred on a 2x2 sub-grid
    w = (boxes[1, 1, :, 2] - boxes[1, 1, :, 0]) * 32
    np.testing.assert_allclose(w, 8.0, atol=1e-4)


def test_box_coder_decode_2d():
    prior = np.array([[0.1, 0.1, 0.5, 0.5], [0.2, 0.3, 0.7, 0.8]],
                     np.float32)
    deltas = np.zeros((2, 4), np.float32)   # zero deltas -> decode == prior

    def build():
        p = fluid.layers.data("p", shape=[2, 4], dtype="float32",
                              append_batch_size=False)
        t = fluid.layers.data("t", shape=[2, 4], dtype="float32",
                              append_batch_size=False)
        dec = fluid.layers.box_coder(p, [0.1, 0.1, 0.2, 0.2], t,
                                     "decode_center_size")
        return [dec]

    (dec,) = _run(build, {"p": prior, "t": deltas})
    dec = np.asarray(dec)
    assert dec.shape == (2, 4)   # no spurious leading dim
    np.testing.assert_allclose(dec, prior, atol=1e-5)


def test_box_coder_pixel_roundtrip():
    # non-normalized (pixel) boxes: +1 widths on encode, -1 on decode
    prior = np.array([[4.0, 4.0, 11.0, 11.0]], np.float32)
    gt = np.array([[2.0, 3.0, 9.0, 12.0]], np.float32)

    def build():
        p = fluid.layers.data("p", shape=[1, 4], dtype="float32",
                              append_batch_size=False)
        t = fluid.layers.data("t", shape=[4], dtype="float32")
        enc = fluid.layers.box_coder(p, None, t, "encode_center_size",
                                     box_normalized=False)
        dec = fluid.layers.box_coder(p, None, enc, "decode_center_size",
                                     box_normalized=False)
        return [enc, dec]

    enc, dec = _run(build, {"p": prior, "t": gt})
    enc, dec = np.asarray(enc), np.asarray(dec)
    # reference semantics: tw = xmax-xmin+1 = 8, pw = 8
    np.testing.assert_allclose(enc[0, 0, 2], np.log(8.0 / 8.0), atol=1e-5)
    # the REFERENCE coder's pixel-box roundtrip is intentionally NOT
    # exact: centers are (min+max)/2 while widths carry the +1, so
    # decode(encode(gt)) lands half a pixel low (box_coder_op.h:55,:139
    # — enc: ox = (5.5-7.5)/8; dec: xmin = 5.5-4 = 1.5, xmax = 5.5+4-1).
    # Bug-for-bug parity here is what reference-trained SSD checkpoints
    # decode with.
    np.testing.assert_allclose(dec[0, 0], [1.5, 2.5, 8.5, 11.5],
                               atol=1e-4)


def test_rpn_target_assign():
    rng = np.random.RandomState(7)
    N, A, G, S = 1, 20, 2, 8
    anchors = np.zeros((A, 4), np.float32)
    c = rng.rand(A, 2).astype(np.float32)
    anchors[:, :2] = c - 0.1
    anchors[:, 2:] = c + 0.1
    avar = np.full((A, 4), 0.1, np.float32)
    # gt boxes exactly equal to two anchors -> those anchors are fg
    gt = np.stack([anchors[3], anchors[11]])[None]
    loc = rng.randn(N, A, 4).astype(np.float32)
    scores = rng.rand(N, A, 1).astype(np.float32)

    def build():
        l = fluid.layers.data("l", shape=[A, 4], dtype="float32")
        s = fluid.layers.data("s", shape=[A, 1], dtype="float32")
        ab = fluid.layers.data("ab", shape=[A, 4], dtype="float32",
                               append_batch_size=False)
        av = fluid.layers.data("av", shape=[A, 4], dtype="float32",
                               append_batch_size=False)
        g = fluid.layers.data("g", shape=[G, 4], dtype="float32")
        return fluid.layers.rpn_target_assign(
            l, s, ab, av, g, rpn_batch_size_per_im=S, fg_fraction=0.25,
            rpn_positive_overlap=0.7, rpn_negative_overlap=0.3)

    pl, ps, lab, tb = _run(build, {"l": loc, "s": scores, "ab": anchors,
                                   "av": avar, "g": gt})
    lab_np = lab.numpy() if hasattr(lab, "numpy") else np.asarray(lab)
    tb_np = tb.numpy() if hasattr(tb, "numpy") else np.asarray(tb)
    pl_np = pl.numpy() if hasattr(pl, "numpy") else np.asarray(pl)
    n_fg = int((lab_np[:, 0] == 1).sum())
    assert n_fg == 2                       # both gt-matching anchors sampled
    assert lab_np.shape[0] <= S
    # fg rows decode to (near-)zero offsets since gt == anchor
    np.testing.assert_allclose(tb_np[:n_fg], 0.0, atol=1e-4)
    assert pl_np.shape[1] == 4


# ---------------------------------------------------------------------------
# multiclass_nms randomized oracle audit (r5): restatement of
# multiclass_nms_op.cc NMSFast (adaptive eta) + keep_top_k
# ---------------------------------------------------------------------------

def _ref_iou(a, b):
    if b[0] > a[2] or b[2] < a[0] or b[1] > a[3] or b[3] < a[1]:
        return 0.0
    ix = min(a[2], b[2]) - max(a[0], b[0])
    iy = min(a[3], b[3]) - max(a[1], b[1])
    inter = ix * iy
    ua = ((a[2] - a[0]) * (a[3] - a[1])
          + (b[2] - b[0]) * (b[3] - b[1]) - inter)
    return inter / ua if ua > 0 else 0.0


def _ref_nms_fast(boxes, scores, score_thr, nms_thr, eta, top_k):
    cand = [(s, i) for i, s in enumerate(scores) if s > score_thr]
    cand.sort(key=lambda t: -t[0])
    if top_k > -1:
        cand = cand[:top_k]
    selected = []
    thr = nms_thr
    for _, idx in cand:
        keep = all(_ref_iou(boxes[idx], boxes[k]) <= thr
                   for k in selected)
        if keep:
            selected.append(idx)
            if eta < 1 and thr > 0.5:
                thr *= eta
    return selected


def _ref_multiclass_nms(scores, boxes, bg, score_thr, nms_top_k, nms_thr,
                        keep_top_k, eta):
    C, M = scores.shape
    rows = []
    for c in range(C):
        if c == bg:
            continue
        for i in _ref_nms_fast(boxes, scores[c], score_thr, nms_thr, eta,
                               nms_top_k):
            rows.append((c, float(scores[c, i]), i))
    if keep_top_k > -1 and len(rows) > keep_top_k:
        rows.sort(key=lambda r: -r[1])
        rows = rows[:keep_top_k]
    return {(c, round(s, 5)) + tuple(np.round(boxes[i], 5))
            for c, s, i in rows}


@pytest.mark.parametrize("eta", [1.0, 0.9])
def test_multiclass_nms_matches_reference_oracle(eta):
    from paddle_tpu.ops.registry import get_op_def, ExecContext
    import jax.numpy as jnp
    rng = np.random.RandomState(31 if eta == 1.0 else 37)
    B, C, M = 2, 4, 12
    for trial in range(4):
        boxes = np.zeros((B, M, 4), np.float32)
        xy = rng.rand(B, M, 2) * 3
        wh = 0.5 + rng.rand(B, M, 2) * 1.5
        boxes[..., :2] = xy
        boxes[..., 2:] = xy + wh
        scores = rng.rand(B, C, M).astype(np.float32)

        class _Op:
            type = "multiclass_nms"
            outputs = {}
            attrs = {"background_label": 0, "score_threshold": 0.1,
                     "nms_top_k": 8, "nms_threshold": 0.45,
                     "keep_top_k": 6, "normalized": True, "nms_eta": eta}
        vals = {"Scores": [jnp.asarray(scores)],
                "BBoxes": [jnp.asarray(boxes)]}
        r = get_op_def("multiclass_nms").lower(ExecContext(_Op(), vals))
        out = np.asarray(r["Out"])
        cnt = np.asarray(r["Out@LOD_LEN"])
        for b in range(B):
            want = _ref_multiclass_nms(scores[b], boxes[b], 0, 0.1, 8,
                                       0.45, 6, eta)
            got = {(int(row[0]), round(float(row[1]), 5))
                   + tuple(np.round(row[2:6], 5))
                   for row in out[b][:cnt[b]]}
            assert got == want, (eta, trial, b, got, want)


def _ref_bipartite(dist, match_type, thr):
    """bipartite_match_op.cc restated (small-N greedy path + ArgMaxMatch)."""
    N, M = dist.shape
    midx = np.full(M, -1, np.int32)
    mdist = np.zeros(M, np.float32)
    row_used = np.zeros(N, bool)
    while True:
        best, bi, bj = -1.0, -1, -1
        for j in range(M):
            if midx[j] != -1:
                continue
            for i in range(N):
                if row_used[i] or dist[i, j] < 1e-6:
                    continue
                if dist[i, j] > best:
                    best, bi, bj = dist[i, j], i, j
        if bi < 0:
            break
        midx[bj], mdist[bj] = bi, best
        row_used[bi] = True
    if match_type == "per_prediction":
        for j in range(M):
            if midx[j] != -1:
                continue
            best, bi = -1.0, -1
            for i in range(N):
                d = dist[i, j]
                if d >= 1e-6 and d >= thr and d > best:
                    best, bi = d, i
            if bi != -1:
                midx[j], mdist[j] = bi, best
    return midx, mdist


@pytest.mark.parametrize("match_type", ["bipartite", "per_prediction"])
def test_bipartite_match_matches_reference_oracle(match_type):
    from paddle_tpu.ops.registry import get_op_def, ExecContext
    import jax.numpy as jnp
    rng = np.random.RandomState(41)
    for trial in range(5):
        N, M = rng.randint(2, 6), rng.randint(2, 8)
        dist = (rng.rand(N, M) * 0.9).astype(np.float32)
        dist[rng.rand(N, M) < 0.3] = 0.0          # no-edge entries
        dist[0, 0] = 0.5                          # exact threshold row
        want_i, want_d = _ref_bipartite(dist, match_type, 0.5)

        class _Op:
            type = "bipartite_match"
            outputs = {}
            attrs = {"match_type": match_type, "dist_threshold": 0.5}
        vals = {"DistMat": [jnp.asarray(dist[None])]}
        r = get_op_def("bipartite_match").lower(ExecContext(_Op(), vals))
        got_i = np.asarray(r["ColToRowMatchIndices"])[0]
        got_d = np.asarray(r["ColToRowMatchDist"])[0]
        np.testing.assert_array_equal(got_i, want_i,
                                      err_msg=str((match_type, trial,
                                                   dist)))
        np.testing.assert_allclose(got_d, want_d, atol=1e-6)


def _ref_mine(cls_loss, loc_loss, midx, mdist, mining_type, ratio,
              dist_thr, sample_size):
    """mine_hard_examples_op.cc restated for one image."""
    P = len(midx)
    if mining_type == "max_negative":
        elig = [(cls_loss[m], m) for m in range(P)
                if midx[m] == -1 and mdist[m] < dist_thr]
        num_pos = sum(1 for m in midx if m != -1)
        neg_sel = min(int(num_pos * ratio), len(elig))
        if sample_size > 0:
            neg_sel = min(sample_size, len(elig))
        elig.sort(key=lambda t: -t[0])
        sel = sorted(m for _, m in elig[:neg_sel])
        return sel, list(midx)
    # hard_example: all priors eligible, loss = cls + loc
    loss = [cls_loss[m] + (loc_loss[m] if loc_loss is not None else 0.0)
            for m in range(P)]
    elig = sorted(((loss[m], m) for m in range(P)), key=lambda t: -t[0])
    neg_sel = min(sample_size if sample_size > 0 else P, P)
    sel = {m for _, m in elig[:neg_sel]}
    updated = [(-1 if (midx[m] > -1 and m not in sel) else midx[m])
               for m in range(P)]
    negs = sorted(m for m in sel if midx[m] == -1)
    return negs, updated


@pytest.mark.parametrize("mining_type", ["max_negative", "hard_example"])
def test_mine_hard_examples_matches_reference_oracle(mining_type):
    from paddle_tpu.ops.registry import get_op_def, ExecContext
    import jax.numpy as jnp
    rng = np.random.RandomState(43)
    for trial in range(5):
        B, P = 2, 12
        cls = rng.rand(B, P).astype(np.float32)
        loc = rng.rand(B, P).astype(np.float32)
        midx = np.where(rng.rand(B, P) < 0.3,
                        rng.randint(0, 4, (B, P)), -1).astype(np.int32)
        mdist = (rng.rand(B, P) * 0.8).astype(np.float32)
        ss = 5 if mining_type == "hard_example" else 0

        class _Op:
            type = "mine_hard_examples"
            outputs = {}
            attrs = {"mining_type": mining_type, "neg_pos_ratio": 2.0,
                     "neg_dist_threshold": 0.5, "sample_size": ss}
        vals = {"ClsLoss": [jnp.asarray(cls)],
                "LocLoss": [jnp.asarray(loc)],
                "MatchIndices": [jnp.asarray(midx)],
                "MatchDist": [jnp.asarray(mdist)]}
        r = get_op_def("mine_hard_examples").lower(ExecContext(_Op(), vals))
        negs = np.asarray(r["NegIndices"])
        lens = np.asarray(r["NegIndices@LOD_LEN"])
        upd = np.asarray(r["UpdatedMatchIndices"])
        for b in range(B):
            want_negs, want_upd = _ref_mine(
                cls[b], loc[b], midx[b], mdist[b], mining_type, 2.0,
                0.5, ss)
            assert list(negs[b][:lens[b]]) == want_negs, \
                (mining_type, trial, b, list(negs[b][:lens[b]]),
                 want_negs)
            np.testing.assert_array_equal(upd[b], want_upd,
                                          err_msg=str((mining_type,
                                                       trial, b)))


def test_roi_pool_matches_reference_oracle():
    """roi_pool_op.h restated: round-half-away quantization (the .5 cases
    from spatial_scale=0.5 with odd coords), floor/ceil bin grids, empty
    bins -> 0."""
    from paddle_tpu.ops.registry import get_op_def, ExecContext
    import jax.numpy as jnp
    rng = np.random.RandomState(47)
    B, C, H, W, R = 1, 2, 8, 8, 4
    ph_, pw_ = 2, 2
    scale = 0.5
    x = rng.randn(B, C, H, W).astype(np.float32)
    rois = np.array([[1, 1, 5, 5], [3, 1, 5, 7],
                     [0, 0, 15, 15], [7, 7, 7, 7]], np.float32)[None]

    def ref_one(feat, roi):
        import math
        rs = [int(math.floor(v * scale + 0.5)) for v in roi]
        x1, y1, x2, y2 = rs
        rh = max(y2 - y1 + 1, 1)
        rw = max(x2 - x1 + 1, 1)
        out = np.zeros((C, ph_, pw_), np.float32)
        for i in range(ph_):
            for j in range(pw_):
                hs = min(max(int(math.floor(i * rh / ph_)) + y1, 0), H)
                he = min(max(int(math.ceil((i + 1) * rh / ph_)) + y1, 0), H)
                ws = min(max(int(math.floor(j * rw / pw_)) + x1, 0), W)
                we = min(max(int(math.ceil((j + 1) * rw / pw_)) + x1, 0), W)
                if he <= hs or we <= ws:
                    out[:, i, j] = 0.0
                else:
                    out[:, i, j] = feat[:, hs:he, ws:we].max(axis=(1, 2))
        return out

    class _Op:
        type = "roi_pool"
        outputs = {}
        attrs = {"pooled_height": ph_, "pooled_width": pw_,
                 "spatial_scale": scale}
    vals = {"X": [jnp.asarray(x)], "ROIs": [jnp.asarray(rois)]}
    r = get_op_def("roi_pool").lower(ExecContext(_Op(), vals))
    got = np.asarray(r["Out"])[0]
    for k in range(R):
        np.testing.assert_allclose(got[k], ref_one(x[0], rois[0, k]),
                                   atol=1e-5, err_msg="roi %d" % k)


def test_anchor_generator_matches_reference_oracle():
    """anchor_generator_op.h restated: centers at idx*stride +
    offset*(stride-1), extents +/-(w-1)/2, rounded base sizes,
    ar-major anchor order."""
    from paddle_tpu.ops.registry import get_op_def, ExecContext
    import jax.numpy as jnp
    H, W = 3, 4
    sizes, ars, stride, offset = [32.0, 64.0], [0.5, 1.0, 1.5, 2.0], \
        [18.0, 18.0], 0.5
    feat = np.zeros((1, 8, H, W), np.float32)

    want = np.zeros((H, W, len(ars) * len(sizes), 4), np.float32)
    for hi in range(H):
        for wi in range(W):
            xc = wi * stride[0] + offset * (stride[0] - 1)
            yc = hi * stride[1] + offset * (stride[1] - 1)
            idx = 0
            for ar in ars:
                for s in sizes:
                    area = stride[0] * stride[1]
                    # C round(): half-away-from-zero (python round()
                    # is half-to-even and would hide the divergence)
                    base_w = np.floor(np.sqrt(area / ar) + 0.5)
                    base_h = np.floor(base_w * ar + 0.5)
                    aw = s / stride[0] * base_w
                    ah = s / stride[1] * base_h
                    want[hi, wi, idx] = [xc - 0.5 * (aw - 1),
                                         yc - 0.5 * (ah - 1),
                                         xc + 0.5 * (aw - 1),
                                         yc + 0.5 * (ah - 1)]
                    idx += 1

    class _Op:
        type = "anchor_generator"
        outputs = {}
        attrs = {"anchor_sizes": sizes, "aspect_ratios": ars,
                 "stride": stride, "offset": offset,
                 "variances": [0.1, 0.1, 0.2, 0.2]}
    vals = {"Input": [jnp.asarray(feat)]}
    r = get_op_def("anchor_generator").lower(ExecContext(_Op(), vals))
    np.testing.assert_allclose(np.asarray(r["Anchors"]), want, atol=1e-4)


def test_density_prior_box_matches_reference_oracle():
    """density_prior_box_op.h restated: integer step_average window,
    integer shift quotient, unconditional [0,1] clamp."""
    from paddle_tpu.ops.registry import get_op_def, ExecContext
    import jax.numpy as jnp
    H, W, im_h, im_w = 3, 3, 24, 24
    fixed_sizes, fixed_ratios, densities = [8.0, 4.0], [1.0, 2.0], [2, 3]
    offset = 0.5
    step_w, step_h = im_w / W, im_h / H
    step_avg = int((step_w + step_h) * 0.5)

    P = sum(len(fixed_ratios) * d * d for d in densities)
    want = np.zeros((H, W, P, 4), np.float32)
    for h in range(H):
        for w in range(W):
            cx = (w + offset) * step_w
            cy = (h + offset) * step_h
            idx = 0
            for fs, d in zip(fixed_sizes, densities):
                shift = step_avg // d
                for ar in fixed_ratios:
                    bw = fs * np.sqrt(ar)
                    bh = fs / np.sqrt(ar)
                    for di in range(d):
                        for dj in range(d):
                            cxt = cx - step_avg / 2. + shift / 2. + \
                                dj * shift
                            cyt = cy - step_avg / 2. + shift / 2. + \
                                di * shift
                            want[h, w, idx] = [
                                max((cxt - bw / 2.) / im_w, 0),
                                max((cyt - bh / 2.) / im_h, 0),
                                min((cxt + bw / 2.) / im_w, 1),
                                min((cyt + bh / 2.) / im_h, 1)]
                            idx += 1

    class _Op:
        type = "density_prior_box"
        outputs = {}
        attrs = {"fixed_sizes": fixed_sizes, "fixed_ratios": fixed_ratios,
                 "densities": densities, "offset": offset,
                 "variances": [0.1, 0.1, 0.2, 0.2], "clip": False}
    vals = {"Input": [jnp.asarray(np.zeros((1, 4, H, W), np.float32))],
            "Image": [jnp.asarray(np.zeros((1, 3, im_h, im_w),
                                           np.float32))]}
    r = get_op_def("density_prior_box").lower(ExecContext(_Op(), vals))
    np.testing.assert_allclose(np.asarray(r["Boxes"]), want, atol=1e-5)


@pytest.mark.parametrize("min_max_order", [False, True])
def test_prior_box_matches_reference_oracle(min_max_order):
    """prior_box_op.h restated full-grid (ExpandAspectRatios + both
    emission orders + clip)."""
    from paddle_tpu.ops.registry import get_op_def, ExecContext
    import jax.numpy as jnp
    H, W, im_h, im_w = 2, 3, 24, 24
    min_sizes, max_sizes = [4.0, 8.0], [8.0, 16.0]
    in_ars, flip, offset, clip = [1.0, 2.0], True, 0.5, True
    step_w, step_h = im_w / W, im_h / H

    ars = [1.0]
    for ar in in_ars:
        if all(abs(ar - e) > 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    ars = [a for i, a in enumerate(ars)
           if all(abs(a - b) > 1e-6 for b in ars[:i])]

    rows = []
    for s, ms in enumerate(min_sizes):
        if min_max_order:
            rows.append((ms / 2., ms / 2.))
            rows.append((np.sqrt(ms * max_sizes[s]) / 2.,) * 2)
            for ar in ars:
                if abs(ar - 1.) < 1e-6:
                    continue
                rows.append((ms * np.sqrt(ar) / 2., ms / np.sqrt(ar) / 2.))
        else:
            for ar in ars:
                rows.append((ms * np.sqrt(ar) / 2., ms / np.sqrt(ar) / 2.))
            rows.append((np.sqrt(ms * max_sizes[s]) / 2.,) * 2)
    P = len(rows)
    want = np.zeros((H, W, P, 4), np.float32)
    for h in range(H):
        for w in range(W):
            cx, cy = (w + offset) * step_w, (h + offset) * step_h
            for i, (bw, bh) in enumerate(rows):
                want[h, w, i] = [(cx - bw) / im_w, (cy - bh) / im_h,
                                 (cx + bw) / im_w, (cy + bh) / im_h]
    want = np.clip(want, 0.0, 1.0)

    class _Op:
        type = "prior_box"
        outputs = {}
        attrs = {"min_sizes": min_sizes, "max_sizes": max_sizes,
                 "aspect_ratios": in_ars, "flip": flip, "clip": clip,
                 "offset": offset,
                 "min_max_aspect_ratios_order": min_max_order,
                 "variances": [0.1, 0.1, 0.2, 0.2]}
    vals = {"Input": [jnp.asarray(np.zeros((1, 4, H, W), np.float32))],
            "Image": [jnp.asarray(np.zeros((1, 3, im_h, im_w),
                                           np.float32))]}
    r = get_op_def("prior_box").lower(ExecContext(_Op(), vals))
    np.testing.assert_allclose(np.asarray(r["Boxes"]), want, atol=1e-5)
