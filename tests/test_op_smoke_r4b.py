"""Numeric oracles for the op tail with no direct test coverage (r4b).

An audit of the 326-op registry against the test corpus found ~100 op
types never named in any test (most are reached indirectly through
layers; some were not exercised at all). This file pins the pure-math
tail — activations, elementwise variants, comparisons, reductions,
tensor manipulation, RNG moments — to numpy oracles through the same
direct-lowering harness as test_op_tail. Reference kernels:
activation_op.cc, elementwise ops, reduce_op.cc, compare_op.cc,
gaussian_random_op.cc, uniform_random_op.cc, pad2d_op.cc etc.
"""

import numpy as np
import pytest

from tests.test_op_tail import run_op

RNG = np.random.RandomState(7)
X = RNG.randn(3, 5).astype(np.float32)
Y = (RNG.randn(3, 5) + 1.1).astype(np.float32)


def _one(op, inputs, attrs=None, out="Out", **kw):
    r = run_op(op, inputs, attrs or {}, **kw)
    return np.asarray(r[out])


ACTIVATIONS = [
    ("brelu", {"t_min": -0.5, "t_max": 0.5},
     lambda x: np.clip(x, -0.5, 0.5)),
    ("relu6", {}, lambda x: np.clip(x, 0, 6)),
    ("soft_relu", {"threshold": 40.0},
     lambda x: np.log1p(np.exp(np.clip(x, -40, 40)))),
    ("softplus", {}, lambda x: np.log1p(np.exp(x))),
    ("logsigmoid", {}, lambda x: -np.log1p(np.exp(-x))),
    ("reciprocal", {}, lambda x: 1.0 / x),
    ("rsqrt", {}, None),   # positive-shifted oracle in the test body
    ("cos", {}, np.cos),
    ("erf", {}, None),   # math.erf oracle in the test body (scipy-free)
    ("gelu", {}, None),   # math.erf-based oracle in the test body
    ("hard_sigmoid", {"slope": 0.2, "offset": 0.5},
     lambda x: np.clip(0.2 * x + 0.5, 0, 1)),
    ("hard_shrink", {"threshold": 0.5},
     lambda x: np.where(np.abs(x) > 0.5, x, 0)),
    ("softshrink", {"lambda": 0.5},
     lambda x: np.where(x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0))),
    ("tanh_shrink", {}, lambda x: x - np.tanh(x)),
    ("thresholded_relu", {"threshold": 1.0},
     lambda x: np.where(x > 1.0, x, 0)),
    ("swish", {"beta": 1.0}, lambda x: x / (1.0 + np.exp(-x))),
]


@pytest.mark.parametrize("op,attrs,ref",
                         ACTIVATIONS, ids=[a[0] for a in ACTIVATIONS])
def test_activation_tail(op, attrs, ref):
    x = X + 2.0 if op == "rsqrt" else X   # rsqrt needs positive input
    got = _one(op, {"X": x}, attrs)
    if op == "rsqrt":
        np.testing.assert_allclose(got, 1.0 / np.sqrt(x), rtol=1e-5)
        return
    if op in ("erf", "gelu"):
        import math
        erf = np.vectorize(math.erf)(x / (np.sqrt(2.0) if op == "gelu"
                                          else 1.0))
        ref_v = (erf if op == "erf"
                 else 0.5 * x * (1.0 + erf)).astype(np.float32)
    else:
        ref_v = ref(x).astype(np.float32)
    np.testing.assert_allclose(got, ref_v, rtol=1e-5, atol=1e-6)


ELEMENTWISE = [
    ("elementwise_max", np.maximum),
    ("elementwise_min", np.minimum),
    ("elementwise_pow", np.power),
    ("elementwise_mod", None),
    ("elementwise_floordiv", None),
]


@pytest.mark.parametrize("op,ref", ELEMENTWISE,
                         ids=[e[0] for e in ELEMENTWISE])
def test_elementwise_tail(op, ref):
    if op in ("elementwise_mod", "elementwise_floordiv"):
        a = RNG.randint(1, 50, (3, 5)).astype(np.int64)
        b = RNG.randint(1, 7, (3, 5)).astype(np.int64)
        got = _one(op, {"X": a, "Y": b})
        want = np.mod(a, b) if op == "elementwise_mod" \
            else np.floor_divide(a, b)
        np.testing.assert_array_equal(got, want)
        return
    a = np.abs(X) + 0.5 if op == "elementwise_pow" else X
    got = _one(op, {"X": a, "Y": Y})
    np.testing.assert_allclose(got, ref(a, Y), rtol=1e-5)


COMPARE = [
    ("greater_than", np.greater),
    ("greater_equal", np.greater_equal),
    ("less_equal", np.less_equal),
    ("not_equal", np.not_equal),
]


@pytest.mark.parametrize("op,ref", COMPARE, ids=[c[0] for c in COMPARE])
def test_compare_tail(op, ref):
    a = RNG.randint(0, 3, (4, 4)).astype(np.int64)
    b = RNG.randint(0, 3, (4, 4)).astype(np.int64)
    got = _one(op, {"X": a, "Y": b})
    np.testing.assert_array_equal(got.astype(bool), ref(a, b))


def test_logical_tail():
    a = np.array([[True, False], [True, True]])
    b = np.array([[False, False], [True, False]])
    np.testing.assert_array_equal(
        _one("logical_not", {"X": a}).astype(bool), ~a)
    np.testing.assert_array_equal(
        _one("logical_xor", {"X": a, "Y": b}).astype(bool), a ^ b)


REDUCE = [
    ("reduce_min", np.min),
    ("reduce_prod", np.prod),
    ("reduce_any", np.any),
]


@pytest.mark.parametrize("op,ref", REDUCE, ids=[r[0] for r in REDUCE])
def test_reduce_tail(op, ref):
    x = (np.abs(X) > 1.0) if op == "reduce_any" else np.abs(X) + 0.5
    got = _one(op, {"X": x.astype(np.float32) if op != "reduce_any"
                    else x}, {"dim": [1], "keep_dim": False})
    want = ref(x, axis=1)
    if op == "reduce_any":
        np.testing.assert_array_equal(got.astype(bool), want)
    else:
        np.testing.assert_allclose(got, want.astype(np.float32), rtol=1e-5)


def test_arg_max_min():
    got = _one("arg_max", {"X": X}, {"axis": 1})
    np.testing.assert_array_equal(got, np.argmax(X, axis=1))
    got = _one("arg_min", {"X": X}, {"axis": 0})
    np.testing.assert_array_equal(got, np.argmin(X, axis=0))


def test_tensor_manipulation_tail():
    # gather_nd / scatter_nd
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    idx = np.array([[0, 2], [1, 0]], np.int64)
    np.testing.assert_array_equal(
        _one("gather_nd", {"X": x, "Index": idx}), x[[0, 1], [2, 0]])
    upd = np.ones((2, 4), np.float32)
    got = _one("scatter_nd", {"Index": idx, "Updates": upd},
               {"shape": [2, 3, 4]})
    want = np.zeros((2, 3, 4), np.float32)
    want[0, 2] += 1
    want[1, 0] += 1
    np.testing.assert_array_equal(got, want)
    # strided_slice
    got = _one("strided_slice", {"Input": x},
               {"axes": [1], "starts": [0], "ends": [3], "strides": [2]})
    np.testing.assert_array_equal(got, x[:, 0:3:2])
    # unstack
    r = run_op("unstack", {"X": x}, {"axis": 0, "num": 2}, )
    outs = [np.asarray(v) for v in r["Y"]]
    np.testing.assert_array_equal(outs[0], x[0])
    np.testing.assert_array_equal(outs[1], x[1])
    # space_to_depth
    s = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    got = _one("space_to_depth", {"X": s}, {"blocksize": 2})
    assert got.shape == (1, 4, 2, 2)
    # pad2d
    p = _one("pad2d", {"X": s}, {"paddings": [1, 1, 2, 2],
                                 "mode": "constant", "pad_value": 0.0})
    assert p.shape == (1, 1, 6, 8)
    np.testing.assert_array_equal(p[0, 0, 1:5, 2:6], s[0, 0])
    # pixel_shuffle
    ps_in = np.arange(16, dtype=np.float32).reshape(1, 4, 2, 2)
    ps = _one("pixel_shuffle", {"X": ps_in}, {"upscale_factor": 2})
    assert ps.shape == (1, 1, 4, 4)
    # linspace
    ls = _one("linspace", {"Start": np.float32(0.0),
                           "Stop": np.float32(1.0),
                           "Num": np.int32(5)})
    np.testing.assert_allclose(ls, np.linspace(0, 1, 5), rtol=1e-6)


def test_fill_zeros_like_and_is_empty():
    z = _one("fill_zeros_like", {"X": X})
    np.testing.assert_array_equal(z, np.zeros_like(X))
    assert not bool(_one("is_empty", {"X": X}))


def test_rng_moments():
    """Distribution sanity for the random tail: mean/std within loose
    bounds (deterministic seeds — exact reproducibility is covered by
    the framework RNG tests)."""
    g = _one("gaussian_random", {}, {"shape": [2000], "mean": 1.0,
                                     "std": 2.0, "seed": 3})
    assert abs(g.mean() - 1.0) < 0.2 and abs(g.std() - 2.0) < 0.2
    u = _one("uniform_random", {}, {"shape": [2000], "min": -1.0,
                                    "max": 3.0, "seed": 3})
    assert u.min() >= -1.0 and u.max() <= 3.0
    assert abs(u.mean() - 1.0) < 0.2
    t = _one("truncated_gaussian_random", {},
             {"shape": [2000], "mean": 0.0, "std": 1.0, "seed": 3})
    assert np.abs(t).max() <= 2.0 + 1e-5   # truncated at 2 std
    ub = _one("uniform_random_batch_size_like", {"Input": X},
              {"shape": [0, 7], "min": 0.0, "max": 1.0, "seed": 1})
    assert ub.shape == (3, 7)


def test_norm_tail():
    got = _one("squared_l2_norm", {"X": X})
    np.testing.assert_allclose(np.asarray(got).ravel()[0],
                               (X ** 2).sum(), rtol=1e-5)
    got = _one("clip_by_norm", {"X": X}, {"max_norm": 1.0})
    np.testing.assert_allclose(
        got, X * (1.0 / max(1.0, np.sqrt((X ** 2).sum()))), rtol=1e-5)
    g = RNG.randn(4, 6).astype(np.float32)
    gn = _one("group_norm", {"X": g.reshape(1, 4, 6, 1),
                             "Scale": np.ones(4, np.float32),
                             "Bias": np.zeros(4, np.float32)},
              {"groups": 2, "epsilon": 1e-5}, out="Y")
    grp = g.reshape(2, 12)
    want = ((grp - grp.mean(1, keepdims=True))
            / np.sqrt(grp.var(1, keepdims=True) + 1e-5)).reshape(1, 4, 6, 1)
    np.testing.assert_allclose(gn, want, rtol=1e-4, atol=1e-5)
