"""Observability tests (paddle_tpu/obs — OBSERVABILITY.md).

Pins the tracing + telemetry contracts: the span ring never blocks or
grows, a served request's reply-visible trace_id resolves to a span
tree whose stages tile the root and land within 10% of the measured
client latency, the structured event log rotates atomically and
records the lifecycle events (hot swaps, sheds, sentinel actions,
checkpoint commits), the MetricsRegistry renders one Prometheus-style
surface across serving + training, and the CLIs (metrics_dump,
trace_top, serving_top --json) keep their schemas.  Everything
CPU-safe under JAX_PLATFORMS=cpu.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.obs as obs
from paddle_tpu.flags import FLAGS, set_flags
from paddle_tpu.obs import events as obs_events
from paddle_tpu.obs import tracing as obs_tracing
from paddle_tpu.serving import (InferenceServer, ServerOverloaded,
                                ServingClient, ServingMetrics,
                                set_dispatch_delay)
from paddle_tpu.serving.metrics import ReservoirHistogram

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, os.path.join(REPO, "tools"))

import trace_top  # noqa: E402  (tools/trace_top.py)


@pytest.fixture(autouse=True)
def _obs_reset():
    """Every test starts with a fresh ring, default flags, and a
    memory-only event sink; chaos hooks cleared."""
    set_flags({"trace": True, "trace_buffer_events": 4096,
               "trace_slow_ms": 0.0, "event_log": "",
               "event_log_max_kb": 1024})
    obs_tracing.configure()
    obs_tracing.clear()
    obs_events.configure()
    yield
    set_dispatch_delay(0.0)
    set_flags({"trace": True, "trace_buffer_events": 4096,
               "trace_slow_ms": 0.0, "event_log": "",
               "event_log_max_kb": 1024})
    obs_tracing.configure()
    obs_tracing.clear()
    obs_events.configure()


def _export_fc(tmp_path, seed=3, name="m", size=6):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        h = fluid.layers.fc(input=x, size=size, act="relu")
        pred = fluid.layers.fc(input=h, size=size, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / name)
        fluid.save_inference_model(md, ["x"], [pred], exe,
                                   main_program=main)
    return md


# ---------------------------------------------------------------------------
# tracing core
# ---------------------------------------------------------------------------

class TestTracing:
    def test_ring_wraps_at_capacity(self):
        set_flags({"trace_buffer_events": 16})
        for i in range(50):
            with obs.trace("t", i=i):
                pass
        st = obs_tracing.stats()
        assert st["buffered"] == 16
        assert st["spans_total"] == 50
        assert st["dropped"] == 34
        # the ring keeps the most recent spans
        kept = [s["attrs"]["i"] for s in obs.recent_spans()]
        assert kept == list(range(34, 50))

    def test_disabled_tracing_is_noop(self):
        set_flags({"trace": False})
        before = obs_tracing.stats()["spans_total"]
        with obs.trace("t") as s:
            assert s is None
        assert obs_tracing.stats()["spans_total"] == before
        set_flags({"trace": True})
        with obs.trace("t") as s:
            assert s is not None
        assert obs_tracing.stats()["spans_total"] == before + 1

    def test_exception_records_span_with_error_and_propagates(self):
        with pytest.raises(ValueError):
            with obs.trace("boom", kind="train"):
                raise ValueError("x")
        (span,) = obs.recent_spans(name="boom")
        assert span["attrs"]["error"] == "ValueError"

    def test_trace_ids_unique_hex(self):
        ids = {obs.new_trace_id() for _ in range(256)}
        assert len(ids) == 256
        assert all(len(t) == 16 and int(t, 16) >= 0 for t in ids)

    def test_spans_for_trace_filters(self):
        with obs.trace("a", trace_id="t1"):
            pass
        with obs.trace("b", trace_id="t2"):
            pass
        assert [s["name"] for s in obs.spans_for_trace("t1")] == ["a"]

    def test_concurrent_emitters_never_lose_the_ring(self):
        """Hot-path safety: hammering from threads neither raises nor
        corrupts the ring bookkeeping."""
        set_flags({"trace_buffer_events": 32})
        errs = []

        def hammer(k):
            try:
                for i in range(300):
                    with obs.trace("h%d" % k, i=i):
                        pass
            except BaseException as e:  # must never happen
                errs.append(e)

        ts = [threading.Thread(target=hammer, args=(k,))
              for k in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
        assert not errs
        st = obs_tracing.stats()
        assert st["spans_total"] == 1200
        assert st["buffered"] == 32

    def test_chrome_events_merge_format(self):
        with obs.trace("serving/x", kind="serving", trace_id="tid1"):
            pass
        evs = obs_tracing.chrome_events()
        xs = [e for e in evs if e.get("ph") == "X"]
        assert xs and all(isinstance(e["tid"], int) for e in xs)
        assert any(e["args"].get("trace_id") == "tid1" for e in xs)
        assert any(e.get("ph") == "M" and e["name"] == "thread_name"
                   for e in evs)


# ---------------------------------------------------------------------------
# event log
# ---------------------------------------------------------------------------

class TestEventLog:
    def test_emit_schema_and_file_sink(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        set_flags({"event_log": path})
        obs.emit("hot_swap", model="m", version=2, trace_id="abc")
        obs_events.get_log().flush()
        (rec,) = [json.loads(l) for l in open(path)]
        assert rec["kind"] == "hot_swap" and rec["model"] == "m"
        assert rec["version"] == 2 and rec["trace_id"] == "abc"
        assert isinstance(rec["ts"], float)
        assert obs.recent_events(kind="hot_swap")[-1]["version"] == 2

    def test_rotation_keeps_every_generation_valid(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        set_flags({"event_log_max_kb": 1, "event_log": path})
        for i in range(200):   # ~60 bytes/line -> several rotations
            obs.emit("k", i=i)
        obs_events.get_log().flush()
        assert os.path.exists(path + ".1")
        seen = []
        for p in (path + ".1", path):   # rotated generation is older
            if os.path.exists(p):
                for line in open(p):
                    seen.append(json.loads(line)["i"])
        assert seen == sorted(seen)   # append-only, no tearing

    def test_sink_failure_is_memory_only_never_raises(self, tmp_path):
        # a path that cannot be opened: points INTO a regular file
        blocker = tmp_path / "f"
        blocker.write_text("x")
        set_flags({"event_log": str(blocker / "nope.jsonl")})
        with pytest.warns(UserWarning, match="memory-only"):
            obs.emit("k", i=1)
        obs.emit("k", i=2)   # sink dead: no second warning, no raise
        assert [e["i"] for e in obs.recent_events(kind="k")] == [1, 2]


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counters_gauges_histograms_render(self):
        reg = obs.MetricsRegistry()
        reg.counter("train_steps_total").add(3)
        reg.gauge("inflight", lambda: 2)
        h = reg.histogram("step_ms")
        for v in (1.0, 2.0, 3.0):
            h.record(v)
        txt = reg.prometheus_text()
        assert "paddle_tpu_train_steps_total 3" in txt
        assert "paddle_tpu_inflight 2" in txt
        assert 'paddle_tpu_step_ms{quantile="p50"} 2.0' in txt
        assert "paddle_tpu_step_ms_count 3" in txt

    def test_absorbs_serving_metrics(self):
        reg = obs.MetricsRegistry()
        sm = ServingMetrics()
        m = sm.model("zoo")
        m.requests.add(5)
        m.note_completion(latency_ms=10.0, queue_wait_ms=1.0)
        m.note_shed(priority=2)
        reg.attach_serving(sm)
        txt = reg.prometheus_text()
        assert 'paddle_tpu_serving_requests_total{model="zoo"} 5' in txt
        assert 'paddle_tpu_serving_latency_ms{model="zoo",' \
               'quantile="p50"} 10.0' in txt
        assert 'paddle_tpu_serving_shed_by_priority_total' \
               '{model="zoo",priority="2"} 1' in txt
        reg.detach_serving(sm)
        assert "zoo" not in reg.prometheus_text()

    def test_span_listener_aggregates_train_breakdown(self):
        reg = obs.default_registry()
        before = reg.span_totals().get(("train", "train/dispatch"),
                                       {"count": 0})["count"]
        with obs.trace("train/dispatch", kind="train", step=1):
            pass
        with obs.trace("train/dispatch", kind="train", step=2):
            pass
        agg = reg.span_totals(kind="train")[("train", "train/dispatch")]
        assert agg["count"] == before + 2
        assert agg["total_ms"] >= 0.0
        assert 'paddle_tpu_span_count_total{kind="train",' \
               'span="train/dispatch"}' in reg.prometheus_text()


class TestReservoirHistogramEdges:
    def test_empty_percentile_and_summary(self):
        h = ReservoirHistogram()
        assert h.percentile(50) is None
        assert h.summary() == {"count": 0}

    def test_capacity_one_keeps_a_valid_sample(self):
        h = ReservoirHistogram(capacity=1, seed=7)
        for v in range(100):
            h.record(float(v))
        assert h.count == 100
        s = h.summary()
        assert s["min"] == 0.0 and s["max"] == 99.0
        assert s["mean"] == pytest.approx(49.5)
        # the single reservoir slot holds SOME observed value, and every
        # percentile collapses to it
        assert 0.0 <= s["p50"] <= 99.0
        assert s["p50"] == s["p99"] == h.percentile(0)

    def test_single_value_every_percentile(self):
        h = ReservoirHistogram()
        h.record(42.0)
        for q in (0, 50, 99, 100):
            assert h.percentile(q) == 42.0
        s = h.summary()
        assert s["count"] == 1 and s["p95"] == 42.0


# ---------------------------------------------------------------------------
# serving end to end
# ---------------------------------------------------------------------------

@pytest.fixture()
def fc_server(tmp_path):
    md = _export_fc(tmp_path)
    srv = InferenceServer(endpoint="127.0.0.1:0").start()
    srv.registry.load_model("m", md, buckets=[2, 4, 8])
    cli = ServingClient(srv.endpoint)
    try:
        yield srv, cli, md
    finally:
        cli.close()
        srv.shutdown(drain=False, timeout=5.0)


class TestServingTracing:
    def test_trace_id_resolves_to_stage_tree_within_client_latency(
            self, fc_server):
        """THE acceptance criterion: the reply-visible trace_id
        resolves (trace RPC / ring) to a span tree whose stage
        durations tile the root exactly and land within 10% of the
        measured client latency; replies stay bit-exact vs a direct
        predictor run."""
        srv, cli, md = fc_server
        x = np.random.RandomState(0).randn(1, 4).astype(np.float32)
        cli.infer("m", {"x": x}, deadline_ms=10000)  # warm the wire
        set_dispatch_delay(0.15)   # compute dominates: 10% ≫ overhead
        t0 = time.monotonic()
        fetches, info = cli.infer("m", {"x": x}, deadline_ms=10000,
                                  debug=True)
        client_ms = (time.monotonic() - t0) * 1e3
        set_dispatch_delay(0.0)
        from paddle_tpu.inference import AnalysisConfig, Predictor
        cfg = AnalysisConfig(model_dir=md)
        cfg.batch_size_buckets = (2, 4, 8)
        ref = Predictor(cfg).run({"x": x})[0]
        assert np.array_equal(fetches[0], ref), "tracing changed bits"

        spans = cli.trace(trace_id=info["trace_id"])["spans"]
        stages = {s["name"]: s["dur_ms"] for s in spans}
        root = stages["serving/request"]
        stage_sum = sum(v for k, v in stages.items()
                        if k not in ("serving/request", "serving/rpc"))
        assert stage_sum == pytest.approx(root, rel=1e-6), \
            "stages must tile the root span"
        assert abs(stage_sum - client_ms) <= 0.10 * client_ms, \
            "span tree (%.1fms) vs client latency (%.1fms)" \
            % (stage_sum, client_ms)
        # the dominant stage is the injected dispatch stall
        assert stages["serving/dispatch"] >= 140.0

    def test_carried_wire_trace_id_is_echoed_and_used(self, fc_server):
        srv, cli, md = fc_server
        x = np.zeros((1, 4), np.float32)
        mine = "feedfacefeedface"
        fetches, info = cli.infer("m", {"x": x}, deadline_ms=10000,
                                  debug=True, trace_id=mine)
        assert info["trace_id"] == mine
        assert cli.last_trace_id == mine
        names = {s["name"] for s in cli.trace(trace_id=mine)["spans"]}
        assert "serving/request" in names and "serving/compute" in names

    def test_debug_reply_fields_and_plain_reply_shape(self, fc_server):
        srv, cli, md = fc_server
        x = np.zeros((2, 4), np.float32)
        fetches, info = cli.infer("m", {"x": x}, deadline_ms=10000,
                                  debug=True)
        for key in ("trace_id", "queue_wait_ms", "compute_ms",
                    "batch_fill", "batch_rows", "replica",
                    "server_ms"):
            assert key in info, key
        assert info["batch_rows"] >= 2
        # plain infer: list return unchanged, trace_id on the client
        out = cli.infer("m", {"x": x}, deadline_ms=10000)
        assert isinstance(out, list) and out[0].shape[0] == 2
        assert cli.last_trace_id

    def test_trace_off_still_serves_and_echoes_ids(self, fc_server):
        srv, cli, md = fc_server
        set_flags({"trace": False})
        before = obs_tracing.stats()["spans_total"]
        x = np.zeros((1, 4), np.float32)
        fetches, info = cli.infer("m", {"x": x}, deadline_ms=10000,
                                  debug=True)
        assert info["trace_id"]            # correlation id survives
        assert cli.trace(trace_id=info["trace_id"])["spans"] == []
        assert obs_tracing.stats()["spans_total"] == before

    def test_metrics_rpc_one_surface(self, fc_server):
        srv, cli, md = fc_server
        cli.infer("m", {"x": np.zeros((1, 4), np.float32)},
                  deadline_ms=10000)
        txt = cli.metrics_text()
        assert 'paddle_tpu_serving_requests_total{model="m"}' in txt
        assert "paddle_tpu_trace_spans_total" in txt
        assert "paddle_tpu_events_total" in txt
        assert 'span="serving/compute"' in txt

    def test_trace_rpc_kind_filter_and_limit(self, fc_server):
        srv, cli, md = fc_server
        for _ in range(3):
            cli.infer("m", {"x": np.zeros((1, 4), np.float32)},
                      deadline_ms=10000)
        spans = cli.trace(kind="serving", limit=5)["spans"]
        assert len(spans) == 5
        assert all(s["kind"] == "serving" for s in spans)

    def test_hot_swap_and_shed_events(self, tmp_path):
        md = _export_fc(tmp_path)
        srv = InferenceServer(endpoint="127.0.0.1:0",
                              max_queue=1).start()
        cli = ServingClient(srv.endpoint)
        try:
            srv.registry.load_model("m", md, buckets=[2, 4])
            srv.registry.load_model("m", md, buckets=[2, 4])  # hot swap
            swaps = obs.recent_events(kind="hot_swap")
            assert len(swaps) >= 2
            assert swaps[-1]["model"] == "m"
            assert swaps[-1]["from_version"] == 1
            assert swaps[-1]["version"] == 2
            ccs = obs.recent_events(kind="compile_cache_delta")
            assert ccs and ccs[-1]["model"] == "m"
            # overload a 1-deep queue with a concurrent burst: at least
            # one shed event with the priority class recorded
            set_dispatch_delay(0.2)
            x = np.zeros((1, 4), np.float32)
            sheds = []

            def one():
                c = ServingClient(srv.endpoint)
                try:
                    c.infer("m", {"x": x}, priority=1)
                except ServerOverloaded:
                    sheds.append(1)
                finally:
                    c.close()

            ts = [threading.Thread(target=one) for _ in range(12)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(30)
            assert len(sheds) >= 1
            evs = obs.recent_events(kind="shed")
            assert evs and evs[-1]["priority"] == 1
            assert "trace_id" in evs[-1]
        finally:
            set_dispatch_delay(0.0)
            cli.close()
            srv.shutdown(drain=False, timeout=5.0)

    def test_slow_request_log_gated_by_flag(self, fc_server):
        srv, cli, md = fc_server
        set_flags({"trace_slow_ms": 50.0})
        x = np.zeros((1, 4), np.float32)
        cli.infer("m", {"x": x}, deadline_ms=10000)   # fast: no event
        assert not obs.recent_events(kind="slow")
        set_dispatch_delay(0.12)
        fetches, info = cli.infer("m", {"x": x}, deadline_ms=10000,
                                  debug=True)
        set_dispatch_delay(0.0)
        (ev,) = obs.recent_events(kind="slow")
        assert ev["trace_id"] == info["trace_id"]
        assert ev["total_ms"] >= 50.0


# ---------------------------------------------------------------------------
# training spans + events
# ---------------------------------------------------------------------------

def _regression_net():
    def train_func():
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, size=1)
        return fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))

    def optimizer_func():
        return fluid.optimizer.SGD(learning_rate=0.05)

    return train_func, optimizer_func


def _train(data, depth=0, prefetch=0, ckpt_dir=None, num_epochs=1,
           step_interval=4, sentinel=False):
    train_func, optimizer_func = _regression_net()

    def reader():
        for x, y in data:
            yield [(x, y)]

    flags = {"async_dispatch_depth": depth,
             "reader_prefetch_depth": prefetch,
             "sentinel_nan_check": sentinel}
    fluid.set_flags(flags)
    try:
        with fluid.scope_guard(fluid.Scope()):
            cfg = None
            if ckpt_dir is not None:
                cfg = fluid.contrib.CheckpointConfig(
                    checkpoint_dir=ckpt_dir,
                    step_interval=step_interval)
            trainer = fluid.contrib.Trainer(
                train_func, optimizer_func, place=fluid.CPUPlace(),
                checkpoint_config=cfg)
            losses = []

            def handler(ev):
                if isinstance(ev, fluid.contrib.EndStepEvent):
                    losses.append(np.asarray(ev.metrics[0]).copy())

            trainer.train(num_epochs=num_epochs, event_handler=handler,
                          reader=reader, feed_order=["x", "y"])
            return losses
    finally:
        fluid.set_flags({"async_dispatch_depth": 0,
                         "reader_prefetch_depth": 0,
                         "sentinel_nan_check": False})


def _regression_data(n=8, seed=0, poison_at=None):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        x = rng.randn(4).astype(np.float32)
        y = np.array([x.sum()], np.float32)
        if poison_at is not None and i == poison_at:
            y[:] = np.nan
        out.append((x, y))
    return out


class TestTrainingSpans:
    def test_sync_loop_emits_per_step_spans(self):
        _train(_regression_data(6), depth=0)
        spans = obs.recent_spans(kind="train", name="train/step")
        assert len(spans) == 6
        assert [s["attrs"]["step"] for s in spans] == list(range(6))

    def test_async_loop_emits_dispatch_drain_ckpt_breakdown(
            self, tmp_path):
        _train(_regression_data(8), depth=3,
               ckpt_dir=str(tmp_path / "ckpt"), step_interval=4)
        names = [s["name"] for s in obs.recent_spans(kind="train")]
        assert names.count("train/dispatch") == 8
        assert names.count("train/drain") == 8
        assert "train/ckpt" in names
        # trace_top's per-step aggregation: each step shows dispatch
        # AND drain milliseconds (the per-step breakdown of the issue)
        steps = trace_top.group_steps(obs.recent_spans(kind="train"))
        by_step = {r["step"]: r for r in steps}
        # every dispatched step shows dispatch AND drain milliseconds
        # (ckpt spans carry GLOBAL step ids, so they may land in their
        # own rows — the breakdown still attributes them)
        for i in range(8):
            assert {"dispatch", "drain"} <= set(by_step[i]["stages"])
        assert any("ckpt" in r["stages"] for r in steps)

    def test_prefetch_wait_spans_recorded(self):
        _train(_regression_data(6), depth=0, prefetch=2)
        waits = obs.recent_spans(kind="train",
                                 name="train/prefetch_wait")
        assert len(waits) == 6

    def test_checkpoint_commit_event_stamped_with_step(self, tmp_path):
        _train(_regression_data(8), depth=0,
               ckpt_dir=str(tmp_path / "ckpt"), step_interval=4)
        evs = obs.recent_events(kind="checkpoint_committed")
        assert evs and evs[-1]["step"] >= 4
        assert "path" in evs[-1]

    def test_sentinel_skip_event_stamped_with_step(self):
        _train(_regression_data(8, poison_at=3), sentinel=True)
        evs = obs.recent_events(kind="sentinel_skip")
        assert evs and evs[-1]["step"] == 3
        assert "y" in evs[-1]["bad"] or evs[-1]["bad"]

    def test_drain_span_from_raw_fetchfuture(self):
        """fluid/pipeline.py instrumentation holds without the Trainer:
        any FetchFuture.result lands a train/drain span."""
        from paddle_tpu.fluid.pipeline import FetchFuture
        fut = FetchFuture([np.float32(1.0)])
        fut.result(step=7)
        (s,) = obs.recent_spans(kind="train", name="train/drain")
        assert s["attrs"]["step"] == 7


# ---------------------------------------------------------------------------
# profiler merge
# ---------------------------------------------------------------------------

class TestChromeMerge:
    def test_export_chrome_tracing_merges_obs_spans(self, tmp_path):
        import gzip
        d = tmp_path / "plugins" / "profile" / "run1"
        d.mkdir(parents=True)
        device = {"traceEvents": [
            {"ph": "X", "pid": 1, "tid": 1, "name": "xla::fusion",
             "ts": 0, "dur": 5}]}
        with gzip.open(str(d / "host.trace.json.gz"), "wb") as f:
            f.write(json.dumps(device).encode())
        with obs.trace("serving/compute", kind="serving",
                       trace_id="zz"):
            pass
        out = fluid.profiler.export_chrome_tracing(
            trace_dir=str(tmp_path),
            output_path=str(tmp_path / "merged.json"))
        data = json.load(open(out))
        names = {e.get("name") for e in data["traceEvents"]}
        assert "xla::fusion" in names          # device timeline kept
        assert "serving/compute" in names      # obs spans merged in


# ---------------------------------------------------------------------------
# CLIs + chaos (tier-1 smokes)
# ---------------------------------------------------------------------------

def _run_cli(args, timeout=120):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable] + args, capture_output=True, text=True,
        timeout=timeout, cwd=REPO, env=env)


# serving_top --json top-level + per-model keys dashboards depend on;
# additive evolution only — removing/renaming breaks consumers silently
SERVING_TOP_MODEL_KEYS = {
    "model", "uptime_sec", "requests", "responses", "errors", "shed",
    "deadline_expired", "dispatches", "qps_recent", "qps_lifetime",
    "batch_fill", "bucket_fill_ratio", "latency_ms", "queue_wait_ms",
    "compile_cache", "queue_depth", "replicas"}


class TestCLIs:
    def test_serving_top_json_schema_pinned(self, fc_server):
        srv, cli, md = fc_server
        cli.infer("m", {"x": np.zeros((1, 4), np.float32)},
                  deadline_ms=10000)
        proc = _run_cli(["tools/serving_top.py", srv.endpoint,
                         "--json"])
        assert proc.returncode == 0, proc.stderr
        reply = json.loads(proc.stdout)
        assert {"ok", "stats", "models"} <= set(reply)
        assert {"uptime_sec", "models"} <= set(reply["stats"])
        m = reply["stats"]["models"]["m"]
        missing = SERVING_TOP_MODEL_KEYS - set(m)
        assert not missing, "snapshot keys went missing: %s" % missing
        assert {"count", "mean", "p50", "p95", "p99", "min", "max"} \
            <= set(m["latency_ms"])

    def test_metrics_dump_cli_smoke(self, fc_server):
        srv, cli, md = fc_server
        cli.infer("m", {"x": np.zeros((1, 4), np.float32)},
                  deadline_ms=10000)
        proc = _run_cli(["tools/metrics_dump.py", srv.endpoint])
        assert proc.returncode == 0, proc.stderr
        assert 'paddle_tpu_serving_requests_total{model="m"}' \
            in proc.stdout
        assert "# TYPE" in proc.stdout

    def test_trace_top_cli_smoke(self, fc_server):
        srv, cli, md = fc_server
        x = np.zeros((1, 4), np.float32)
        fetches, info = cli.infer("m", {"x": x}, deadline_ms=10000,
                                  debug=True)
        top = _run_cli(["tools/trace_top.py", srv.endpoint, "-n", "5"])
        assert top.returncode == 0, top.stderr
        assert info["trace_id"] in top.stdout
        assert "queue_wait=" in top.stdout
        tree = _run_cli(["tools/trace_top.py", srv.endpoint,
                         "--trace_id", info["trace_id"]])
        assert tree.returncode == 0, tree.stderr
        assert "serving/request" in tree.stdout
        js = _run_cli(["tools/trace_top.py", srv.endpoint, "--json"])
        recs = json.loads(js.stdout)
        assert recs and {"trace_id", "total_ms", "stages"} \
            <= set(recs[0])

    def test_chaos_trace_overflow_scenario(self, tmp_path):
        """The hot path never blocks or crashes under ring overflow +
        event-log rotation faults (satellite: chaos scenario)."""
        import chaos
        out = chaos.scenario_trace_overflow(str(tmp_path / "ov"),
                                            verbose=False)
        assert out["dropped"] > 0
        assert out["max_emit_ms"] < 250.0
