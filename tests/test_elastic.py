"""Elastic/EDL layer (VERDICT r2 task #3; reference go/master/service.go +
go/pserver/service.go + listen_and_serv_op.cc:172 NeedResetAllVars):
master task queue with lease/timeout/retry/failure-cap and disk snapshot,
pserver CRC checkpoints, trainer rejoin reset, and an end-to-end run that
kills trainer + pserver mid-training and resumes from checkpoint to the
same loss trajectory."""

import os
import tempfile
import time

import numpy as np
import pytest

from paddle_tpu.distributed.elastic import (
    MasterService, MasterClient, save_state_snapshot, load_state_snapshot)
from paddle_tpu.distributed.rpc import (
    VariableServer, RPCClient, wait_server_ready)


def _master(**kw):
    m = MasterService("127.0.0.1:0", **kw).start()
    wait_server_ready([m.endpoint])
    return m


def _retry_bind(factory, timeout=5.0):
    """Rebinding a just-stopped server's endpoint can race its socket
    close; retry briefly."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            return factory()
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.1)


def test_master_lease_timeout_requeues():
    """service.go:341 checkTimeoutFunc: an expired lease fails over to
    another worker."""
    m = _master(lease_timeout=0.2, failure_max=5, check_interval=0.05)
    try:
        c = MasterClient(m.endpoint, worker="w0")
        c.set_dataset(["a", "b"])
        tid, payload = c.get_task()
        assert payload == "a"
        # don't finish it; lease expires, the task re-queues with
        # failures+1 and another worker picks it up
        time.sleep(0.5)
        c2 = MasterClient(m.endpoint, worker="w1")
        got = {c2.get_task()[1], c2.get_task()[1]}
        assert got == {"a", "b"}
        st = c.state()
        # the expired lease's failure was recorded on task 'a'
        by_payload = {p: f for (_, p, f) in st["pending"]}
        assert by_payload["a"] == 1 and by_payload["b"] == 0, by_payload
    finally:
        m.stop()


def test_master_failure_cap_discards():
    """service.go:455 TaskFailed -> :313: too many failures discards the
    task instead of retrying forever."""
    m = _master(lease_timeout=30.0, failure_max=2)
    try:
        c = MasterClient(m.endpoint, worker="w0")
        c.set_dataset(["only"])
        for _ in range(2):
            tid, _ = c.get_task()
            c.task_failed(tid)
        assert c.get_task(block=False) is None      # discarded
        st = c.state()
        assert len(st["discarded"]) == 1
    finally:
        m.stop()


def test_master_pass_rollover():
    """service.go:411: when todo+pending drain, the done queue recycles
    and the pass counter advances."""
    m = _master(lease_timeout=30.0)
    try:
        c = MasterClient(m.endpoint, worker="w0")
        c.set_dataset(["x", "y"])
        for _ in range(2):
            tid, _ = c.get_task()
            c.task_finished(tid)
        tid, payload = c.get_task()      # next pass begins
        assert payload in ("x", "y")
        st = c.state()
        assert st["num_passes"] == 1
    finally:
        m.stop()


def test_master_snapshot_recovery():
    """service.go:207 snapshot / :237 recover: a restarted master
    continues from disk state; leases do not survive (pending -> todo);
    set_dataset after recovery is a no-op."""
    snap = os.path.join(tempfile.mkdtemp(), "master.snap")
    m1 = _master(snapshot_path=snap, lease_timeout=30.0)
    c = MasterClient(m1.endpoint, worker="w0")
    c.set_dataset(["t0", "t1", "t2"])
    tid, _ = c.get_task()
    c.task_finished(tid)
    c.get_task()                     # leased, never finished
    m1.stop()
    time.sleep(0.1)

    m2 = _master(snapshot_path=snap, lease_timeout=30.0)
    try:
        c2 = MasterClient(m2.endpoint, worker="w1")
        c2.set_dataset(["IGNORED"])  # must be a no-op
        st = c2.state()
        assert st["dataset_set"]
        payloads = {p for (_, p, _) in st["todo"]}
        # the unfinished lease came back as todo; t0 stays done
        assert payloads == {"t1", "t2"}
        assert {p for (_, p, _) in st["done"]} == {"t0"}
    finally:
        m2.stop()


def test_snapshot_crc_detects_corruption():
    path = os.path.join(tempfile.mkdtemp(), "s.snap")
    save_state_snapshot(path, {"hello": np.arange(5)})
    raw = bytearray(open(path, "rb").read())
    raw[10] ^= 0xFF
    with open(path, "wb") as f:
        f.write(raw)
    with pytest.raises(ValueError, match="CRC32"):
        load_state_snapshot(path)


def test_master_boots_past_corrupted_snapshot():
    """A master restarting onto a corrupt/truncated snapshot must warn
    and start a FRESH queue (go/master proceeds when the etcd snapshot
    is unusable), then overwrite the bad snapshot on first mutation."""
    snap = os.path.join(tempfile.mkdtemp(), "master.snap")
    with open(snap, "wb") as f:
        f.write(b"\x00\x01garbage-that-is-not-a-snapshot")
    with pytest.warns(UserWarning, match="unreadable master snapshot"):
        m = _master(snapshot_path=snap)
    try:
        c = MasterClient(m.endpoint, worker="w0")
        c.set_dataset(["a", "b"])          # fresh queue accepts a dataset
        tid, p = c.get_task()
        assert p in ("a", "b")
        c.task_finished(tid)
    finally:
        m.stop()
    # the rewrite is loadable again (atomic temp+fsync+rename path)
    st = load_state_snapshot(snap)
    assert st["dataset_set"]


def test_snapshot_interrupted_writer_cannot_corrupt():
    """A writer killed mid-write leaves only its unique temp file; the
    committed snapshot keeps serving, and a later writer is unaffected
    by the stale temp (satellite: atomicity of save_state_snapshot)."""
    d = tempfile.mkdtemp()
    path = os.path.join(d, "s.snap")
    save_state_snapshot(path, {"v": 1})
    # a killed writer's half-written temp (pid that can't collide)
    with open(path + ".tmp.99999999.dead", "wb") as f:
        f.write(b"\xde\xad partial")
    assert load_state_snapshot(path)["v"] == 1
    save_state_snapshot(path, {"v": 2})
    assert load_state_snapshot(path)["v"] == 2


def test_pserver_checkpoint_crc_and_restore():
    """go/pserver/service.go:145 parameterCheckpoint + :174
    LoadCheckpoint: CRC-verified save/restore of the full store."""
    d = tempfile.mkdtemp()
    srv = VariableServer("127.0.0.1:0").start()
    wait_server_ready([srv.endpoint])
    cli = RPCClient()
    try:
        cli.put_var(srv.endpoint, "w", np.arange(6, dtype=np.float32))
        cli.put_var(srv.endpoint, "w_velocity",
                    np.full(6, 0.5, np.float32))
        r = cli.checkpoint_notify(srv.endpoint, d)
        assert r["ok"]
        path = r["path"]
    finally:
        cli.send_exit(srv.endpoint)
        cli.close()
        srv.stop()

    # restore into a fresh server AT THE SAME endpoint-derived path
    srv2 = _retry_bind(lambda: VariableServer(srv.endpoint).start())
    wait_server_ready([srv2.endpoint])
    cli2 = RPCClient()
    try:
        meta = srv2.load_checkpoint(d)
        assert meta["endpoint"] == srv2.endpoint
        got = cli2.async_get_var(srv2.endpoint, "w_velocity")
        np.testing.assert_array_equal(got, np.full(6, 0.5, np.float32))
    finally:
        cli2.send_exit(srv2.endpoint)
        cli2.close()
        srv2.stop()

    # corruption must be detected
    raw = bytearray(open(path, "rb").read())
    raw[20] ^= 0xFF
    with open(path, "wb") as f:
        f.write(raw)
    srv3 = VariableServer(srv.endpoint)
    with pytest.raises(ValueError, match="CRC32"):
        srv3.load_checkpoint(d)


def test_trainer_rejoin_resets_sync_state():
    """listen_and_serv_op.cc:172: a rejoining trainer (same id, higher
    incarnation) resets pending grad buffers + barrier counts so the
    sync loop cannot deadlock on the dead incarnation's barrier."""
    srv = VariableServer("127.0.0.1:0", fanin=2).start()
    wait_server_ready([srv.endpoint])
    cli = RPCClient()
    try:
        cli.register_trainer(srv.endpoint, 0, incarnation=0)
        cli.register_trainer(srv.endpoint, 1, incarnation=0)
        # trainer 0 sends a grad, then dies before its barrier
        cli.async_send_var(srv.endpoint, "g", np.ones(3, np.float32))
        assert srv._grad_buffers            # partial state pending
        r = cli.register_trainer(srv.endpoint, 0, incarnation=1)
        assert r["rejoin"]
        assert not srv._grad_buffers        # reset
        assert srv._send_barriers == 0
    finally:
        cli.send_exit(srv.endpoint)
        cli.close()
        srv.stop()


# ---------------------------------------------------------------------------
# end-to-end: kill trainer AND pserver mid-run, resume from checkpoint,
# trajectory must match an uninterrupted run
# ---------------------------------------------------------------------------

LR = 0.1


def _make_tasks(n_tasks, bs=8):
    rng = np.random.RandomState(42)
    w_true = np.array([2.0, -1.0, 0.5, 3.0], np.float32)
    tasks = []
    for _ in range(n_tasks):
        x = rng.randn(bs, 4).astype(np.float32)
        y = x @ w_true + 0.01 * rng.randn(bs).astype(np.float32)
        tasks.append((x, y))
    return tasks


def _sgd_optimize(pname, gname, grad, store):
    store[pname] = store[pname] - LR * grad


def _train_tasks(master_c, rpc_c, ps_ep, ckpt_dir, die_after=None,
                 max_tasks=8):
    """Lease tasks from the master, one PS update per task, checkpoint
    after every applied update; run ONE pass (the master recycles the
    done queue into a new pass, so the trainer bounds its own epoch).
    Returns per-task (task_id, loss)."""
    out = []
    done = 0
    while done < max_tasks:
        if die_after is not None and done >= die_after:
            return out                      # simulated crash
        t = master_c.get_task(block=False)
        if t is None:
            return out
        tid, (x, y) = t
        w = rpc_c.async_get_var(ps_ep, "w")
        pred = x @ w
        loss = float(np.mean((pred - y) ** 2))
        grad = (2.0 / len(x)) * x.T @ (pred - y)
        rpc_c.async_send_var(ps_ep, "w@GRAD", grad.astype(np.float32))
        rpc_c.async_send_barrier(ps_ep)
        rpc_c.checkpoint_notify(ps_ep, ckpt_dir)
        master_c.task_finished(tid)
        out.append((tid, loss))
        done += 1
    return out


def _start_ps(endpoint="127.0.0.1:0"):
    srv = VariableServer(endpoint, fanin=1, sync_mode=True,
                         optimize_fn=_sgd_optimize,
                         grad_to_param={"w@GRAD": "w"}).start()
    wait_server_ready([srv.endpoint])
    return srv


def test_elastic_end_to_end_failure_recovery():
    tasks = _make_tasks(8)
    w0 = np.zeros(4, np.float32)

    # ---- uninterrupted baseline ----
    snap1 = os.path.join(tempfile.mkdtemp(), "m1.snap")
    d1 = tempfile.mkdtemp()
    m = _master(snapshot_path=snap1, lease_timeout=30.0)
    ps = _start_ps()
    cli = RPCClient()
    try:
        cli.put_var(ps.endpoint, "w", w0)
        mc = MasterClient(m.endpoint, worker="base")
        mc.set_dataset(tasks)
        base = _train_tasks(mc, cli, ps.endpoint, d1)
    finally:
        cli.send_exit(ps.endpoint)
        cli.close()
        ps.stop()
        m.stop()
    assert len(base) == 8
    assert base[-1][1] < base[0][1]        # it actually learns

    # ---- elastic run: trainer + pserver die after 3 tasks ----
    snap2 = os.path.join(tempfile.mkdtemp(), "m2.snap")
    d2 = tempfile.mkdtemp()
    m2 = _master(snapshot_path=snap2, lease_timeout=30.0)
    ps2 = _start_ps()
    ps2_ep = ps2.endpoint
    cli2 = RPCClient()
    try:
        cli2.put_var(ps2_ep, "w", w0)
        cli2.register_trainer(ps2_ep, 0, incarnation=0)
        mc2 = MasterClient(m2.endpoint, worker="t0-inc0")
        mc2.set_dataset(tasks)
        part1 = _train_tasks(mc2, cli2, ps2_ep, d2, die_after=3)
        assert len(part1) == 3
    finally:
        # kill BOTH the trainer (by abandoning its state) and the pserver
        cli2.send_exit(ps2_ep)
        cli2.close()
        ps2.stop()
    m2.stop()                               # master dies too
    time.sleep(0.1)

    # ---- recovery: all three restart; pserver restores its checkpoint,
    # master recovers its queue from the snapshot, the trainer rejoins
    # with a higher incarnation ----
    m3 = _master(snapshot_path=snap2, lease_timeout=30.0)
    ps3 = _retry_bind(lambda: _start_ps(ps2_ep))  # same ep -> same ckpt
    cli3 = RPCClient()
    try:
        meta = ps3.load_checkpoint(d2)
        assert meta["endpoint"] == ps2_ep
        r = cli3.register_trainer(ps2_ep, 0, incarnation=1)
        assert r["ok"]
        mc3 = MasterClient(m3.endpoint, worker="t0-inc1")
        mc3.set_dataset(tasks)              # no-op: recovered state wins
        part2 = _train_tasks(mc3, cli3, ps2_ep, d2, max_tasks=5)
    finally:
        cli3.send_exit(ps2_ep)
        cli3.close()
        ps3.stop()
        m3.stop()

    resumed = part1 + part2
    assert len(resumed) == 8, resumed
    # same tasks in the same order, and the SAME loss trajectory: the
    # restored parameters are bit-identical to the baseline's at step 3
    assert [t for t, _ in resumed] == [t for t, _ in base]
    np.testing.assert_allclose([l for _, l in resumed],
                               [l for _, l in base], rtol=1e-6,
                               err_msg="post-recovery trajectory diverged")
