"""SE-ResNeXt + Transformer model tests, and book-style end-to-end
round-trips (reference tests/book/test_word2vec.py,
test_recommender_system.py; unittests/dist_se_resnext.py,
dist_transformer.py)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program


def test_se_resnext_trains():
    from paddle_tpu.models import se_resnext
    main, startup, feeds, loss, acc, prob = se_resnext.get_model(
        batch_size=2, class_dim=8, layers=50, img_size=64, lr=0.01)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    img = rng.randn(2, 3, 64, 64).astype(np.float32)
    lab = rng.randint(0, 8, (2, 1)).astype(np.int64)
    for _ in range(2):
        (lv,) = exe.run(main, feed={"data": img, "label": lab},
                        fetch_list=[loss])
        assert np.isfinite(float(np.asarray(lv).flatten()[0]))
    # structural parity: depth-50 uses cardinality 32 (dist_se_resnext.py:60)
    gops = [op for op in main.global_block().ops
            if op.type == "conv2d" and op.attrs.get("groups", 1) == 32]
    assert len(gops) == 16   # one per bottleneck block [3,4,6,3]


def test_transformer_lm_converges():
    from paddle_tpu.models import transformer
    S, V = 16, 50
    main, startup, feeds, loss, _, logits = transformer.get_model(
        batch_size=4, seq_len=S, vocab_size=V, d_model=32, n_heads=2,
        n_layers=2, d_ff=64, lr=3e-3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(1)
    # learnable pattern: tokens cycle, label = next token
    seq = (np.arange(4 * (S + 1)).reshape(4, S + 1) % V).astype(np.int64)
    tokens, labels = seq[:, :-1], seq[:, 1:]
    losses = []
    for _ in range(8):
        (lv,) = exe.run(main, feed={"tokens": tokens, "labels": labels},
                        fetch_list=[loss])
        losses.append(float(np.asarray(lv).flatten()[0]))
        assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0] * 0.8


def test_transformer_uses_flash_attention():
    from paddle_tpu.models import transformer
    main, startup, *_ = transformer.get_model(
        batch_size=2, seq_len=8, vocab_size=20, d_model=16, n_heads=2,
        n_layers=1, d_ff=32)
    ops = [op.type for op in main.global_block().ops]
    assert "flash_attention" in ops


def test_book_word2vec_round_trip(tmp_path):
    """book/test_word2vec.py shape: N-gram next-word prediction with shared
    embeddings, train -> save_inference_model -> load -> infer."""
    N_GRAM, V, EMB = 4, 40, 16
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        words = [fluid.layers.data("w%d" % i, shape=[1], dtype="int64")
                 for i in range(N_GRAM)]
        embs = [fluid.layers.embedding(
            w, size=[V, EMB], dtype="float32",
            param_attr=fluid.ParamAttr(name="shared_emb"))
            for w in words]
        concat = fluid.layers.concat(
            [fluid.layers.reshape(e, [-1, EMB]) for e in embs], axis=1)
        hidden = fluid.layers.fc(concat, size=64, act="sigmoid")
        predict = fluid.layers.fc(hidden, size=V, act="softmax")
        nxt = fluid.layers.data("next", shape=[1], dtype="int64")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=predict, label=nxt))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(2)
    data = (np.arange(32 * 5).reshape(32, 5) % V).astype(np.int64)
    feed = {("w%d" % i): data[:, i:i + 1] for i in range(N_GRAM)}
    feed["next"] = data[:, 4:5]
    losses = []
    for _ in range(10):
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(lv).flatten()[0]))
    assert losses[-1] < losses[0]

    d = str(tmp_path / "w2v")
    fluid.io.save_inference_model(
        d, ["w%d" % i for i in range(N_GRAM)], [predict], exe,
        main_program=main)
    infer_prog, feed_names, fetch_vars = fluid.io.load_inference_model(
        d, exe)
    res = exe.run(infer_prog,
                  feed={n: feed[n] for n in feed_names},
                  fetch_list=fetch_vars)
    probs = np.asarray(res[0])
    assert probs.shape == (32, V)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)


def test_book_recommender_system():
    """book/test_recommender_system.py shape: user/item embeddings -> cos
    similarity scaled to a 1..5 rating, square error loss."""
    N_USERS, N_ITEMS, EMB = 30, 50, 8
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        uid = fluid.layers.data("uid", shape=[1], dtype="int64")
        mid = fluid.layers.data("mid", shape=[1], dtype="int64")
        u = fluid.layers.embedding(uid, size=[N_USERS, EMB],
                                   dtype="float32")
        m = fluid.layers.embedding(mid, size=[N_ITEMS, EMB],
                                   dtype="float32")
        u = fluid.layers.fc(fluid.layers.reshape(u, [-1, EMB]), size=16)
        m = fluid.layers.fc(fluid.layers.reshape(m, [-1, EMB]), size=16)
        sim = fluid.layers.cos_sim(u, m)
        pred = fluid.layers.scale(sim, scale=5.0)
        rating = fluid.layers.data("score", shape=[1], dtype="float32")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, rating))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(3)
    uids = rng.randint(0, N_USERS, (64, 1)).astype(np.int64)
    mids = rng.randint(0, N_ITEMS, (64, 1)).astype(np.int64)
    scores = ((uids * 7 + mids * 3) % 5 + 1).astype(np.float32)
    losses = []
    for _ in range(10):
        (lv,) = exe.run(main, feed={"uid": uids, "mid": mids,
                                    "score": scores}, fetch_list=[loss])
        losses.append(float(np.asarray(lv).flatten()[0]))
        assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0]
