"""Whole-graph AD mode (functionalizer.build_whole_graph_step_fn).

The per-op interpreter stashes a jax.vjp per forward op, so fwd+bwd are one
dataflow graph and a jax.checkpoint around the step cannot rematerialize
anything. Whole-graph mode serves the program's backward section with ONE
jax.vjp over the forward region — the formulation under which
save_only_these_names("conv_out") (tagged at ops/nn_ops.py:72) is real.

Parity contract: bitwise-equal losses/grads/updated state vs the per-op
path in fp32; bf16-rounding-schedule-level differences under AMP (each
path materializes cotangents at different op boundaries).
"""

import numpy as np
import pytest

import jax

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import functionalizer


def _conv_model(lr=0.1, with_while=False):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[8, 8, 3], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        conv = fluid.layers.conv2d(
            input=img, num_filters=8, filter_size=3, padding=1, act=None,
            data_format="NHWC")
        bn = fluid.layers.batch_norm(input=conv, act="relu",
                                     data_layout="NHWC")
        pool = fluid.layers.pool2d(input=bn, pool_size=2, pool_stride=2,
                                   pool_type="max", data_format="NHWC")
        if with_while:
            i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
            n = fluid.layers.fill_constant(shape=[1], dtype="int64", value=2)
            w = fluid.layers.While(cond=fluid.layers.less_than(i, n))
            with w.block():
                fluid.layers.increment(i, in_place=True)
        fc = fluid.layers.fc(input=pool, size=10)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(fc, label))
        fluid.optimizer.MomentumOptimizer(
            learning_rate=lr, momentum=0.9).minimize(loss)
    return main, startup, loss


def _setup(main, startup):
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        sn = tuple(functionalizer.persistable_names(main))
        state = {n: scope.get(n) for n in sn if scope.get(n) is not None}
    return sn, state


def _batch(rng, bs=4):
    return {"img": rng.randn(bs, 8, 8, 3).astype(np.float32),
            "label": rng.randint(0, 10, (bs, 1)).astype(np.int64)}


def test_whole_graph_matches_per_op_fp32_exactly():
    fluid.set_amp(False)
    main, startup, loss = _conv_model()
    sn, state = _setup(main, startup)
    gname = main.global_block().all_parameters()[0].name + "@GRAD"
    fetches = (loss.name, gname)

    per_op = functionalizer.build_step_fn(main, ("img", "label"), fetches, sn)
    wg = functionalizer.build_whole_graph_step_fn(
        main, ("img", "label"), fetches, sn)
    assert wg is not None

    rng = np.random.RandomState(0)
    batches = [_batch(rng) for _ in range(3)]
    st_a, st_b = dict(state), dict(state)
    for i, b in enumerate(batches):
        fa, st_a = jax.jit(per_op)(st_a, b, np.uint32(i))
        fb, st_b = jax.jit(wg)(st_b, b, np.uint32(i))
        np.testing.assert_array_equal(np.asarray(fa[0]), np.asarray(fb[0]))
        np.testing.assert_array_equal(np.asarray(fa[1]), np.asarray(fb[1]))
    for n in sn:
        if st_a.get(n) is not None:
            np.testing.assert_array_equal(
                np.asarray(st_a[n]), np.asarray(st_b[n]), err_msg=n)


def test_whole_graph_amp_parity_within_bf16_noise():
    fluid.set_amp(True)
    try:
        main, startup, loss = _conv_model()
        sn, state = _setup(main, startup)
        per_op = functionalizer.build_step_fn(
            main, ("img", "label"), (loss.name,), sn)
        wg = functionalizer.build_whole_graph_step_fn(
            main, ("img", "label"), (loss.name,), sn)
        assert wg is not None
        rng = np.random.RandomState(1)
        b = _batch(rng)
        st_a, st_b = dict(state), dict(state)
        la = lb = None
        for i in range(3):
            fa, st_a = jax.jit(per_op)(st_a, b, np.uint32(i))
            fb, st_b = jax.jit(wg)(st_b, b, np.uint32(i))
            la, lb = float(np.asarray(fa[0])), float(np.asarray(fb[0]))
            np.testing.assert_allclose(la, lb, rtol=5e-2)
    finally:
        fluid.set_amp(False)


def test_remat_policy_recomputes_bn_not_conv():
    """save_only_these_names('conv_out') must add recompute (BN sqrt /
    relu+pool maximum ops duplicated into the backward) while convs stay
    saved (count fixed)."""
    fluid.set_amp(False)
    main, startup, loss = _conv_model()
    sn, state = _setup(main, startup)
    wg = functionalizer.build_whole_graph_step_fn(
        main, ("img", "label"), (loss.name,), sn)
    wg_remat = functionalizer.build_whole_graph_step_fn(
        main, ("img", "label"), (loss.name,), sn, remat_policy="conv_out")
    rng = np.random.RandomState(2)
    b = _batch(rng)
    texts = {}
    for name, fn in (("plain", wg), ("remat", wg_remat)):
        texts[name] = jax.jit(fn).lower(
            state, b, np.uint32(0)).as_text()
    assert (texts["plain"].count("stablehlo.convolution")
            == texts["remat"].count("stablehlo.convolution"))
    for recomputed in ("stablehlo.sqrt", "stablehlo.maximum"):
        assert (texts["remat"].count(recomputed)
                > texts["plain"].count(recomputed)), recomputed
    # and the numbers still match (recompute is exact: deterministic RNG)
    f_a, _ = jax.jit(wg)(state, b, np.uint32(0))
    f_b, _ = jax.jit(wg_remat)(state, b, np.uint32(0))
    np.testing.assert_array_equal(np.asarray(f_a[0]), np.asarray(f_b[0]))


def test_control_flow_program_is_ineligible():
    fluid.set_amp(False)
    main, startup, loss = _conv_model(with_while=True)
    sn = tuple(functionalizer.persistable_names(main))
    assert functionalizer.build_whole_graph_step_fn(
        main, ("img", "label"), (loss.name,), sn) is None
    # and build_step_fn silently falls back to the per-op path
    fn = functionalizer.build_step_fn(
        main, ("img", "label"), (loss.name,), sn, whole_graph_ad=True)
    assert fn is not None


def test_executor_flag_path():
    from paddle_tpu.flags import FLAGS
    fluid.set_amp(False)
    main, startup, loss = _conv_model()
    rng = np.random.RandomState(3)
    b = _batch(rng)

    def run(flag):
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            FLAGS.whole_graph_ad = flag
            try:
                out, = exe.run(main, feed=dict(b),
                               fetch_list=[loss.name])
            finally:
                FLAGS.whole_graph_ad = False
        return np.asarray(out)

    np.testing.assert_array_equal(run(False), run(True))


@pytest.mark.parametrize("amp", [False, True])
def test_remat_step_lowers_for_tpu_offchip(amp):
    """The BENCH_REMAT step must LOWER for TPU — checkable without a
    chip via cross-platform jax.export (the full ResNet-50 variant was
    validated the same way; this keeps a fast guard in the suite, in
    BOTH precisions since bench runs bf16 AMP). The r03/r04 transport
    wedges during the remat compile were load failures, not lowering
    failures — this test pins that."""
    fluid.set_amp(amp)
    try:
        main, startup, loss = _conv_model()
        sn, state = _setup(main, startup)
        step_fn = functionalizer.build_whole_graph_step_fn(
            main, ("img", "label"), (loss.name,), sn,
            remat_policy="conv_out")
        assert step_fn is not None
        exp = functionalizer.export_step_for_tpu(
            step_fn, state,
            {"img": ((4, 8, 8, 3), np.float32),
             "label": ((4, 1), np.int64)})
        assert len(exp.mlir_module_serialized) > 0
    finally:
        fluid.set_amp(False)

def test_block_out_remat_recomputes_convs():
    """remat_policy='block_out' saves only the residual-block boundary
    tags (models/resnet.py _tag_block_out) and recomputes block
    INTERIORS — so conv ops must be duplicated into the backward (unlike
    'conv_out', which pins every conv output), while numerics stay
    exact."""
    fluid.set_amp(False)
    from paddle_tpu.models import resnet
    with fluid.unique_name.guard():
        main, startup, feeds, loss, acc, predict = resnet.get_model(
            batch_size=4, class_dim=10, depth=20, dataset="cifar10",
            lr=0.1, is_train=True, layout="NHWC")
    assert any(op.type == "remat_tag"
               for op in main.global_block().ops), "blocks must be tagged"
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        sn = tuple(functionalizer.persistable_names(main))
        state = {n: scope.get(n) for n in sn if scope.get(n) is not None}
    wg = functionalizer.build_whole_graph_step_fn(
        main, ("data", "label"), (loss.name,), sn)
    wg_blk = functionalizer.build_whole_graph_step_fn(
        main, ("data", "label"), (loss.name,), sn,
        remat_policy="block_out")
    assert wg is not None and wg_blk is not None
    rng = np.random.RandomState(3)
    b = {"data": rng.randn(4, 32, 32, 3).astype(np.float32),
         "label": rng.randint(0, 10, (4, 1)).astype(np.int64)}
    n_plain = jax.jit(wg).lower(state, b, np.uint32(0)).as_text().count(
        "stablehlo.convolution")
    n_blk = jax.jit(wg_blk).lower(state, b, np.uint32(0)).as_text().count(
        "stablehlo.convolution")
    assert n_blk > n_plain, (n_plain, n_blk)
    # recompute is exact math, but the different save-set changes XLA's
    # fusion schedule, so parity is float-rounding-tight, not bitwise
    f_a, _ = jax.jit(wg)(state, b, np.uint32(0))
    f_b, _ = jax.jit(wg_blk)(state, b, np.uint32(0))
    np.testing.assert_allclose(np.asarray(f_a[0]), np.asarray(f_b[0]),
                               rtol=1e-5, atol=1e-6)


def test_remat_tag_transparent_to_per_op_and_inference():
    """The remat_tag identity must not change per-op execution, and the
    is_train=False graph must not contain it."""
    from paddle_tpu.models import resnet
    with fluid.unique_name.guard():
        main, startup, feeds, loss, acc, predict = resnet.get_model(
            batch_size=2, class_dim=10, depth=20, dataset="cifar10",
            is_train=False, layout="NHWC")
    assert not any(op.type == "remat_tag"
                   for op in main.global_block().ops)
    with fluid.unique_name.guard():
        main_t, startup_t, _, loss_t, _, _ = resnet.get_model(
            batch_size=2, class_dim=10, depth=20, dataset="cifar10",
            is_train=True, layout="NHWC")
    scope = fluid.Scope()
    rng = np.random.RandomState(4)
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup_t)
        (lv,) = exe.run(main_t, feed={
            "data": rng.randn(2, 32, 32, 3).astype(np.float32),
            "label": rng.randint(0, 10, (2, 1)).astype(np.int64)},
            fetch_list=[loss_t])
        assert np.isfinite(float(np.asarray(lv).flatten()[0]))


def test_remat_policy_typos_rejected():
    """A typo'd policy string must raise, not silently compile a
    save-nothing policy recorded under a remat label."""
    from paddle_tpu.fluid.functionalizer import _resolve_remat_policy
    for bad in ("blockout", "conv-out", "conv_out,typo", ""):
        with pytest.raises(ValueError):
            _resolve_remat_policy(bad)
    for good in ("conv_out", "block_out", "conv_out,block_out",
                 "nothing", "dots", None):
        _resolve_remat_policy(good)
