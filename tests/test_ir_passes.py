"""ir pass framework tests (reference ir/pass_test.cc, fc_fuse_pass /
fuse_elewise_add_act_pass testers)."""

import os

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid import ir_passes


def _mlp_program():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu")
        p = fluid.layers.fc(h, size=4)
    return main, startup, p


def test_registry_lists_passes():
    names = ir_passes.registered_passes()
    for n in ("graph_viz_pass", "is_test_pass",
              "fuse_elewise_add_act_pass", "fc_fuse_pass"):
        assert n in names


def test_fc_fuse_and_elewise_act_fuse_preserve_results():
    rng = np.random.RandomState(0)
    main, startup, out = _mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(4, 8).astype(np.float32)
    (ref,) = exe.run(main, feed={"x": xs}, fetch_list=[out])

    before = [op.type for op in main.global_block().ops]
    ir_passes.apply_passes(main, ["fuse_elewise_add_act_pass",
                                  "fc_fuse_pass"])
    after = [op.type for op in main.global_block().ops]
    assert "fused_elemwise_activation" in after
    assert "fc" in after
    assert len(after) < len(before)
    (got,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_is_test_pass():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        d = fluid.layers.dropout(x, dropout_prob=0.5)
        b = fluid.layers.batch_norm(fluid.layers.reshape(d, [-1, 8, 1, 1]))
    ir_passes.get_pass("is_test_pass").apply(main)
    for op in main.global_block().ops:
        if op.type in ("dropout", "batch_norm"):
            assert op.attrs.get("is_test") is True


def test_graph_viz_pass(tmp_path):
    main, startup, _ = _mlp_program()
    path = str(tmp_path / "g.dot")
    ir_passes.get_pass("graph_viz_pass", graph_viz_path=path).apply(main)
    assert os.path.exists(path)
    assert "digraph" in open(path).read()


def test_build_strategy_applies_fusion():
    # fusion only fires when no grad op consumes the intermediate (the
    # training program keeps add/act separate so the vjp wiring stays
    # valid) — so exercise it on an inference program, like the
    # reference's inference-time pass pipeline
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu")
        loss = fluid.layers.mean(h)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    bs = fluid.BuildStrategy()
    bs.fuse_elewise_add_act_ops = True
    pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name,
                                main_program=main, build_strategy=bs)
    types = [op.type for op in main.global_block().ops]
    assert "fused_elemwise_activation" in types
    rng = np.random.RandomState(1)
    (lv,) = pe.run(fetch_list=[loss],
                   feed={"x": rng.randn(8, 8).astype(np.float32)})
    assert np.isfinite(float(np.asarray(lv).flatten()[0]))


def test_fusion_declines_on_training_program():
    """With backward ops referencing the intermediates, the fusion pass
    must leave the program untouched (grad wiring stays valid)."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu")
        loss = fluid.layers.mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)
    before = [op.type for op in main.global_block().ops]
    ir_passes.get_pass("fuse_elewise_add_act_pass").apply(main)
    after = [op.type for op in main.global_block().ops]
    assert before == after


def test_graph_pattern_detector_matches_dataflow():
    """GraphPatternDetector (reference ir/graph_pattern_detector.h):
    symbol-linked op patterns match via dataflow, not adjacency."""
    from paddle_tpu.fluid.ir_passes import GraphPatternDetector
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.fc(x, size=8)          # mul + elementwise_add
        # unrelated op BETWEEN producer and consumer
        side = fluid.layers.scale(x, scale=2.0)
        r = fluid.layers.relu(h)
    blk = main.global_block()
    d = GraphPatternDetector()
    d.add_op("add", types=["elementwise_add"], outputs={"Out": "v"})
    d.add_op("act", types=["relu"], inputs={"X": "v"}, single_use={"v"})
    matches = d.detect(blk)
    assert len(matches) == 1
    assert matches[0]["add"].type == "elementwise_add"
    assert matches[0]["act"].type == "relu"
    # single_use constraint: a second consumer kills the match
    main2, startup2 = Program(), Program()
    with fluid.program_guard(main2, startup2):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.fc(x, size=8)
        r1 = fluid.layers.relu(h)
        r2 = fluid.layers.sigmoid(h)            # second consumer
    d2 = GraphPatternDetector()
    d2.add_op("add", types=["elementwise_add"], outputs={"Out": "v"})
    d2.add_op("act", types=["relu"], inputs={"X": "v"}, single_use={"v"})
    assert d2.detect(main2.global_block()) == []


def test_fc_lstm_fuse_pass_preserves_numerics():
    """fc + lstm -> fusion_lstm rewrite (ir/fc_lstm_fuse_pass.cc): same
    outputs before and after the pass."""
    from paddle_tpu.fluid import ir_passes
    from paddle_tpu.fluid.lod import LoDTensor

    def build():
        main, startup = Program(), Program()
        with fluid.program_guard(main, startup):
            w = fluid.layers.data("w", shape=[6], dtype="float32",
                                  lod_level=1)
            proj = fluid.layers.fc(w, size=4 * 8)
            h, c = fluid.layers.dynamic_lstm(proj, size=4 * 8,
                                             use_peepholes=False)
            out = fluid.layers.sequence_pool(h, pool_type="sum")
        return main, startup, out

    rng = np.random.RandomState(3)
    seqs = [rng.randn(n, 6).astype(np.float32) for n in (3, 5)]
    flat = np.concatenate(seqs)
    t = LoDTensor(flat)
    t.set_lod([[0, 3, 8]])

    results = {}
    for fuse in (False, True):
        with fluid.unique_name.guard():
            main, startup, out = build()
        if fuse:
            n_before = len(main.global_block().ops)
            ir_passes.get_pass("fc_lstm_fuse_pass").apply(main)
            ops = [o.type for o in main.global_block().ops]
            assert "fusion_lstm" in ops and "lstm" not in ops, ops
            assert len(main.global_block().ops) < n_before
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            (res,) = exe.run(main, feed={"w": t}, fetch_list=[out])
            results[fuse] = np.asarray(res)
    np.testing.assert_allclose(results[True], results[False], atol=1e-5)


def test_fuse_elewise_add_act_keeps_act_attrs():
    """The fusion must carry the activation op's own attrs (e.g. gelu's
    'approximate') onto fused_elemwise_activation, or the fused lowering
    reads defaults the unfused program would not have used."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import ir_passes

    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            a = fluid.layers.data("a", shape=[6], dtype="float32")
            b = fluid.layers.data("b", shape=[6], dtype="float32")
            s = fluid.layers.elementwise_add(a, b)
            blk = main.global_block()
            out = blk.create_var(name="gelu_out", shape=[-1, 6],
                                 dtype="float32")
            blk.append_op(type="gelu", inputs={"X": [s.name]},
                          outputs={"Out": [out.name]},
                          attrs={"approximate": True})
        return main, startup, out

    x = np.random.RandomState(0).randn(2, 6).astype("float32")
    y = np.random.RandomState(1).randn(2, 6).astype("float32")

    main, startup, out = build()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        unfused = exe.run(main, feed={"a": x, "b": y},
                          fetch_list=[out])[0]

    main2, startup2, out2 = build()
    ir_passes.get_pass("fuse_elewise_add_act_pass").apply(main2)
    ops = [op.type for op in main2.global_block().ops]
    assert "fused_elemwise_activation" in ops and "gelu" not in ops
    fused_op = [op for op in main2.global_block().ops
                if op.type == "fused_elemwise_activation"][0]
    assert fused_op.attrs.get("approximate") is True
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup2)
        fused = exe.run(main2, feed={"a": x, "b": y},
                        fetch_list=[out2])[0]
    np.testing.assert_allclose(unfused, fused, rtol=1e-6)


# ---------------------------------------------------------------------------
# cross-block def/use (the analysis verifier passes lean on use_count and
# apply_passes being right over control-flow programs — ANALYSIS.md)
# ---------------------------------------------------------------------------

def _two_block_program():
    """Parent: add -> relu chain; sub-block (conditional) ALSO reads the
    add's intermediate output."""
    from paddle_tpu.fluid.framework import Operator
    p = Program()
    blk = p.global_block()
    blk.create_var(name="cond", shape=[1], dtype="bool", is_data=True)
    blk.create_var(name="a", shape=[4], dtype="float32", is_data=True)
    blk.create_var(name="b", shape=[4], dtype="float32", is_data=True)
    blk.create_var(name="mid", shape=[4], dtype="float32")
    blk.create_var(name="out", shape=[4], dtype="float32")
    sub = p._create_block()
    sub.create_var(name="sub_out", shape=[4], dtype="float32")
    sub.append_op(type="scale", inputs={"X": ["mid"]},
                  outputs={"Out": ["sub_out"]}, attrs={"scale": 2.0},
                  infer_shape=False)
    p._rollback()
    blk.append_op(type="elementwise_add",
                  inputs={"X": ["a"], "Y": ["b"]},
                  outputs={"Out": ["mid"]}, infer_shape=False)
    blk.append_op(type="relu", inputs={"X": ["mid"]},
                  outputs={"Out": ["out"]}, infer_shape=False)
    blk.append_op(type="conditional_block", inputs={"Cond": ["cond"]},
                  outputs={}, attrs={"sub_block": sub},
                  infer_shape=False)
    return p


def test_use_count_sees_sub_block_reads():
    """use_count must count reads hidden inside nested sub-blocks — a
    fusion deleting an op whose output a sub-block still reads would
    produce an undefined-var at runtime."""
    p = _two_block_program()
    blk = p.global_block()
    # 1 parent read (relu) + 1 sub-block read (scale)
    assert ir_passes.use_count(blk, "mid") == 2
    # counting from the sub-block itself sees only its own read
    assert ir_passes.use_count(p.blocks[1], "mid") == 1
    # a name nobody reads
    assert ir_passes.use_count(blk, "out") == 0


def test_use_count_handles_sub_block_cycles():
    """A sub-block graph with a shared (diamond) sub-block reference
    must not double-count or loop (the _seen guard)."""
    p = _two_block_program()
    blk = p.global_block()
    sub = p.blocks[1]
    # second control-flow op sharing the SAME sub-block object
    blk.append_op(type="conditional_block", inputs={"Cond": ["cond"]},
                  outputs={}, attrs={"sub_block": sub},
                  infer_shape=False)
    # the shared sub-block's read counts ONCE (id-based _seen set)
    assert ir_passes.use_count(blk, "mid") == 2


def test_fusion_declines_when_sub_block_reads_intermediate():
    """fuse_elewise_add_act must NOT fuse add+relu here: the add's
    output 'mid' is also read by the conditional sub-block, so deleting
    the intermediate would break the sub-block (single-use rule across
    blocks)."""
    p = _two_block_program()
    before = [op.type for op in p.global_block().ops]
    ir_passes.get_pass("fuse_elewise_add_act_pass").apply(p)
    after = [op.type for op in p.global_block().ops]
    assert before == after, "fused across a live sub-block read"


def test_apply_passes_multi_block_is_test():
    """apply_passes drives passes over EVERY block: is_test_pass must
    flip dropout/batch_norm inside control-flow sub-blocks too."""
    p = Program()
    blk = p.global_block()
    blk.create_var(name="cond", shape=[1], dtype="bool", is_data=True)
    blk.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    sub = p._create_block()
    sub.create_var(name="d", shape=[4], dtype="float32")
    sub.append_op(type="dropout", inputs={"X": ["x"]},
                  outputs={"Out": ["d"]},
                  attrs={"dropout_prob": 0.5}, infer_shape=False)
    p._rollback()
    blk.append_op(type="conditional_block", inputs={"Cond": ["cond"]},
                  outputs={}, attrs={"sub_block": sub},
                  infer_shape=False)
    v0 = p._version
    ir_passes.apply_passes(p, ["is_test_pass"])
    assert p.blocks[1].ops[0].attrs.get("is_test") is True
    assert p._version > v0     # rewrite passes bump the version...


def test_analysis_passes_ride_pass_registry_without_version_bump():
    """...while the read-only analysis passes are registered on the same
    substrate but must NOT bump the version (a verify must never
    invalidate the executor's compiled-step cache)."""
    for name in ("verify_use_before_def_pass", "verify_shapes_pass",
                 "verify_dead_code_pass",
                 "verify_fetch_reachability_pass",
                 "verify_aot_export_pass"):
        assert name in ir_passes.registered_passes()
    p = _two_block_program()
    v0 = p._version
    pas = ir_passes.get_pass("verify_dead_code_pass",
                             feeds=("a", "b", "cond"), fetches=("out",))
    pas.apply(p)
    assert p._version == v0
    assert isinstance(pas.diagnostics(), list)
