"""ir pass framework tests (reference ir/pass_test.cc, fc_fuse_pass /
fuse_elewise_add_act_pass testers)."""

import os

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid import ir_passes


def _mlp_program():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu")
        p = fluid.layers.fc(h, size=4)
    return main, startup, p


def test_registry_lists_passes():
    names = ir_passes.registered_passes()
    for n in ("graph_viz_pass", "is_test_pass",
              "fuse_elewise_add_act_pass", "fc_fuse_pass"):
        assert n in names


def test_fc_fuse_and_elewise_act_fuse_preserve_results():
    rng = np.random.RandomState(0)
    main, startup, out = _mlp_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(4, 8).astype(np.float32)
    (ref,) = exe.run(main, feed={"x": xs}, fetch_list=[out])

    before = [op.type for op in main.global_block().ops]
    ir_passes.apply_passes(main, ["fuse_elewise_add_act_pass",
                                  "fc_fuse_pass"])
    after = [op.type for op in main.global_block().ops]
    assert "fused_elemwise_activation" in after
    assert "fc" in after
    assert len(after) < len(before)
    (got,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_is_test_pass():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        d = fluid.layers.dropout(x, dropout_prob=0.5)
        b = fluid.layers.batch_norm(fluid.layers.reshape(d, [-1, 8, 1, 1]))
    ir_passes.get_pass("is_test_pass").apply(main)
    for op in main.global_block().ops:
        if op.type in ("dropout", "batch_norm"):
            assert op.attrs.get("is_test") is True


def test_graph_viz_pass(tmp_path):
    main, startup, _ = _mlp_program()
    path = str(tmp_path / "g.dot")
    ir_passes.get_pass("graph_viz_pass", graph_viz_path=path).apply(main)
    assert os.path.exists(path)
    assert "digraph" in open(path).read()


def test_build_strategy_applies_fusion():
    # fusion only fires when no grad op consumes the intermediate (the
    # training program keeps add/act separate so the vjp wiring stays
    # valid) — so exercise it on an inference program, like the
    # reference's inference-time pass pipeline
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu")
        loss = fluid.layers.mean(h)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    bs = fluid.BuildStrategy()
    bs.fuse_elewise_add_act_ops = True
    pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name,
                                main_program=main, build_strategy=bs)
    types = [op.type for op in main.global_block().ops]
    assert "fused_elemwise_activation" in types
    rng = np.random.RandomState(1)
    (lv,) = pe.run(fetch_list=[loss],
                   feed={"x": rng.randn(8, 8).astype(np.float32)})
    assert np.isfinite(float(np.asarray(lv).flatten()[0]))


def test_fusion_declines_on_training_program():
    """With backward ops referencing the intermediates, the fusion pass
    must leave the program untouched (grad wiring stays valid)."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu")
        loss = fluid.layers.mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)
    before = [op.type for op in main.global_block().ops]
    ir_passes.get_pass("fuse_elewise_add_act_pass").apply(main)
    after = [op.type for op in main.global_block().ops]
    assert before == after
