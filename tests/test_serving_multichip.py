"""Multi-chip serving tests (SERVING.md "Multi-chip serving").

Pins the replica-per-device contracts: placement spec resolution,
least-loaded routing that starves no replica under skewed request
sizes, bit-exact replies regardless of which replica served them, hot
swap of a whole replica set under concurrent load with zero dropped or
double-answered requests, lowest-priority-first admission shedding
with the shed class on the reply, the warn-once overflow fix under
concurrent lanes, and a tier-1 smoke of the serving_mc_r1 bench lane.
Everything CPU-safe under JAX_PLATFORMS=cpu + the conftest's 8 forced
host devices.
"""

import json
import os
import subprocess
import sys
import threading
import time
import warnings

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.serving import (
    DynamicBatcher, InferenceServer, ModelRegistry, ServerOverloaded,
    ServingClient, ServingMetrics, resolve_placement,
    set_dispatch_delay)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture(autouse=True)
def _clear_chaos():
    yield
    set_dispatch_delay(0.0)


def _export_fc(tmp_path, seed, name="m", size=6):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        h = fluid.layers.fc(input=x, size=size, act="relu")
        pred = fluid.layers.fc(input=h, size=size, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / name)
        fluid.save_inference_model(md, ["x"], [pred], exe,
                                   main_program=main)
    return md


def _direct(md, buckets=(2, 4)):
    from paddle_tpu.inference import AnalysisConfig, Predictor
    cfg = AnalysisConfig(model_dir=md)
    cfg.batch_size_buckets = tuple(buckets)
    return Predictor(cfg)


# ---------------------------------------------------------------------------
# placement spec
# ---------------------------------------------------------------------------

class TestPlacement:
    def test_auto_is_one_replica_per_local_device(self):
        import jax
        devs = resolve_placement("auto")
        assert devs == list(jax.local_devices())
        assert len(devs) >= 4  # conftest forces 8 host devices

    def test_count_round_robins_and_one_stays_default(self):
        import jax
        assert resolve_placement(1) == [None]  # pre-multichip behavior
        assert resolve_placement("1") == [None]
        devs = resolve_placement(3)
        assert devs == list(jax.local_devices())[:3]

    def test_explicit_device_lists(self):
        import jax
        local = list(jax.local_devices())
        assert resolve_placement("cpu:1,cpu:3") == [local[1], local[3]]
        assert resolve_placement([0, 2]) == [local[0], local[2]]
        with pytest.raises(ValueError, match="out of range"):
            resolve_placement([len(local)])
        with pytest.raises(ValueError):
            resolve_placement(0)

    def test_replicas_live_on_their_devices(self, tmp_path):
        """Each replica's params are committed to its assigned device
        — the thing that makes this multi-CHIP and not just
        multi-thread."""
        import jax
        md = _export_fc(tmp_path, seed=1)
        reg = ModelRegistry(deadline_ms=1)
        try:
            entry = reg.load_model("m", md, buckets=(2,), replicas=4)
            local = list(jax.local_devices())
            for pred, want in zip(entry.replicas, local[:4]):
                devs = {next(iter(v.devices())) if hasattr(v, "devices")
                        else None for v in pred._state.values()}
                assert devs == {want}, \
                    "replica state not on %r: %r" % (want, devs)
        finally:
            reg.close_all()


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------

class TestRouting:
    def test_skewed_sizes_no_starved_replica(self, tmp_path):
        """Skewed request sizes across 4 replicas: every replica lane
        executes batches (no starved replica) and the least-loaded
        policy keeps the spread bounded — no lane hoards the work."""
        md = _export_fc(tmp_path, seed=2)
        metrics = ServingMetrics().model("m")
        batcher = DynamicBatcher(
            _direct(md), max_queue=256, deadline_ms=0,
            metrics=metrics,
            replicas=[_direct(md).clone_to(d)
                      for d in resolve_placement(4)])
        set_dispatch_delay(0.01)  # uniform per-dispatch lane cost
        rng = np.random.RandomState(3)
        try:
            futs = []
            for i in range(48):
                b = [1, 1, 1, 2, 3, 4][i % 6]  # skewed toward tiny
                futs.append(batcher.submit(
                    {"x": rng.randn(b, 4).astype(np.float32)}))
            outs = [f.result(timeout=60) for f in futs]
            assert all(o is not None for o in outs)
            stats = batcher.replica_stats()
            batches = [s["batches"] for s in stats]
            total = sum(batches)
            assert min(batches) >= 1, \
                "starved replica under skewed sizes: %r" % (stats,)
            # least-loaded invariant, observed statistically: with
            # uniform per-batch cost no lane may take more than half
            # of all groups while another idles
            assert max(batches) <= max(total - 3, total // 2 + 3), \
                "load hoarding across lanes: %r" % (stats,)
            assert {s["device"] for s in stats} == \
                {"cpu:0", "cpu:1", "cpu:2", "cpu:3"}
        finally:
            set_dispatch_delay(0.0)
            batcher.close()

    def test_replies_bit_exact_vs_direct_on_every_replica(self,
                                                          tmp_path):
        """Whatever lane a group lands on, the reply bits must equal a
        direct single-predictor run — device placement and routing are
        invisible in the payload."""
        md = _export_fc(tmp_path, seed=4)
        direct = _direct(md)
        reg = ModelRegistry(deadline_ms=2)
        rng = np.random.RandomState(5)
        try:
            entry = reg.load_model("m", md, buckets=(2, 4),
                                   replicas="auto")
            assert len(entry.replicas) >= 4
            inputs = [rng.randn(1 + i % 4, 4).astype(np.float32)
                      for i in range(24)]
            refs = [direct.run({"x": x})[0] for x in inputs]
            futs = [reg.submit("m", {"x": x}) for x in inputs]
            for f, ref in zip(futs, refs):
                out = f.result(timeout=60)[0]
                assert np.array_equal(out, ref), \
                    "replica reply differs from direct Predictor.run"
            stats = entry.batcher.replica_stats()
            assert sum(s["batches"] for s in stats) >= 1
        finally:
            reg.close_all()


# ---------------------------------------------------------------------------
# hot swap under multi-replica load (acceptance pin)
# ---------------------------------------------------------------------------

class TestHotSwapMultiReplica:
    def test_swap_under_4_replica_traffic_no_drops_no_doubles(
            self, tmp_path):
        """Hammer one model from 6 threads while hot-swapping a
        4-replica set for another 4-replica set: every request
        resolves exactly once (zero dropped), every answer is exactly
        v1's or v2's output (zero mixed/double-answered), and post-swap
        traffic serves v2."""
        md1 = _export_fc(tmp_path, seed=31, name="v1")
        md2 = _export_fc(tmp_path, seed=32, name="v2")
        x = np.random.RandomState(6).randn(2, 4).astype(np.float32)
        r1 = _direct(md1).run({"x": x})[0]
        r2 = _direct(md2).run({"x": x})[0]
        reg = ModelRegistry(deadline_ms=2)
        reg.load_model("m", md1, buckets=(2, 4), replicas=4)
        stop = threading.Event()
        wrong, errors, answered = [], [], [0]
        lock = threading.Lock()

        def hammer():
            while not stop.is_set():
                try:
                    out = reg.infer("m", {"x": x}, timeout=30)[0]
                except Exception as e:  # no exception is acceptable
                    errors.append(e)
                    return
                with lock:
                    answered[0] += 1
                    if not (np.array_equal(out, r1)
                            or np.array_equal(out, r2)):
                        wrong.append(out)

        threads = [threading.Thread(target=hammer) for _ in range(6)]
        try:
            for t in threads:
                t.start()
            time.sleep(0.25)
            reg.load_model("m", md2, buckets=(2, 4), replicas=4)
            time.sleep(0.25)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        assert not errors, errors[:3]
        assert not wrong, "%d responses matched neither version" \
            % len(wrong)
        assert answered[0] > 20
        out_after = reg.infer("m", {"x": x}, timeout=30)[0]
        assert np.array_equal(out_after, r2), \
            "post-swap traffic must serve the new replica set"
        entry = reg._models["m"]["versions"][2]
        assert len(entry.replicas) == 4
        reg.close_all()


# ---------------------------------------------------------------------------
# priority classes in admission control
# ---------------------------------------------------------------------------

class TestPriorityShedding:
    def test_lowest_priority_first_shed_ordering(self, tmp_path):
        """Full queue + arriving priorities: each higher-priority
        arrival evicts the earliest lowest strictly-lower-priority
        queued request; equal-or-lower arrivals shed themselves; the
        ServerOverloaded always names the class actually dropped."""
        md = _export_fc(tmp_path, seed=7)
        metrics = ServingMetrics().model("m")
        batcher = DynamicBatcher(_direct(md), max_queue=3,
                                 deadline_ms=5, metrics=metrics)
        set_dispatch_delay(0.5)  # pin the lane so the queue stays full
        x = np.zeros((1, 4), np.float32)
        try:
            head = batcher.submit({"x": x})        # occupies the lane
            time.sleep(0.1)
            a0 = batcher.submit({"x": x}, priority=0)
            b0 = batcher.submit({"x": x}, priority=0)
            c1 = batcher.submit({"x": x}, priority=1)
            # queue full: a priority-2 arrival evicts a0 (earliest of
            # the lowest class), NOT c1
            d2 = batcher.submit({"x": x}, priority=2)
            with pytest.raises(ServerOverloaded) as ei:
                a0.result(timeout=5)
            assert ei.value.priority == 0
            assert not b0.done() and not c1.done()
            # another priority-1 arrival evicts b0 (still a 0 queued)
            e1 = batcher.submit({"x": x}, priority=1)
            with pytest.raises(ServerOverloaded):
                b0.result(timeout=5)
            # a priority-0 arrival has no lower class to evict: it
            # sheds itself, synchronously, carrying its own class
            with pytest.raises(ServerOverloaded) as ei:
                batcher.submit({"x": x}, priority=0)
            assert ei.value.priority == 0
            # an arrival equal to the lowest queued class also sheds
            # itself (only STRICTLY lower classes are evicted)
            with pytest.raises(ServerOverloaded) as ei:
                batcher.submit({"x": x}, priority=1)
            assert ei.value.priority == 1
            set_dispatch_delay(0.0)
            for f in (head, c1, d2, e1):
                assert f.result(timeout=30) is not None
            snap = metrics.snapshot()
            assert snap["shed_by_priority"] == {"0": 3, "1": 1}
            assert snap["shed"] == 4
        finally:
            set_dispatch_delay(0.0)
            batcher.close()

    def test_priority_rides_the_wire_and_shed_class_returns(
            self, tmp_path):
        """ServingClient forwards `priority`; an overloaded reply
        carries the shed class and the client re-raises with it."""
        md = _export_fc(tmp_path, seed=8)
        server = InferenceServer(max_queue=2, buckets=(2,)).start()
        x = np.zeros((1, 4), np.float32)
        boot = ServingClient(server.endpoint)
        try:
            boot.load_model("m", md, buckets=[2])
            boot.infer("m", {"x": x})  # warm
            set_dispatch_delay(0.4)
            sheds = []
            lock = threading.Lock()

            def one(prio):
                cli = ServingClient(server.endpoint)
                try:
                    cli.infer("m", {"x": x}, priority=prio,
                              retry_sheds=False)
                except ServerOverloaded as e:
                    with lock:
                        sheds.append(e.priority)
                except Exception:
                    pass
                finally:
                    cli.close()

            threads = [threading.Thread(target=one, args=(i % 3,))
                       for i in range(16)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            set_dispatch_delay(0.0)
            assert sheds, "no shed under a 2-deep queue and 16 clients"
            assert all(p is not None for p in sheds), \
                "shed reply lost its priority class: %r" % (sheds,)
            # lowest-priority-first: the majority of dropped classes
            # must be the lowest offered
            assert min(sheds) == 0
        finally:
            set_dispatch_delay(0.0)
            boot.close()
            server.shutdown(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# warn-once overflow under concurrent lanes (bugfix pin)
# ---------------------------------------------------------------------------

def test_overflow_warns_exactly_once_across_threads(tmp_path):
    """Concurrent lanes hitting the same unlisted bucket size must
    produce exactly ONE overflow warning (the warn-once set is checked
    under the predictor lock)."""
    md = _export_fc(tmp_path, seed=9)
    pred = _direct(md, buckets=(2,))
    calls = []
    lock = threading.Lock()
    barrier = threading.Barrier(8)

    def counting_warn(*a, **k):
        with lock:
            calls.append(a[0] if a else k)

    def hit():
        barrier.wait()
        pred._bucket_cap(9)

    orig = warnings.warn
    warnings.warn = counting_warn
    try:
        threads = [threading.Thread(target=hit) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
    finally:
        warnings.warn = orig
    assert len(calls) == 1, \
        "overflow size 9 warned %d times across 8 lanes" % len(calls)
    assert pred._overflow_warned == {9}


# ---------------------------------------------------------------------------
# stats / tools surfaces
# ---------------------------------------------------------------------------

def test_stats_and_serving_top_show_replica_lanes(tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import serving_top
    md = _export_fc(tmp_path, seed=10)
    server = InferenceServer(buckets=(2,), deadline_ms=1).start()
    cli = ServingClient(server.endpoint)
    try:
        reply = cli.load_model("demo", md, buckets=[2], replicas=2)
        assert reply["replicas"] == 2
        assert reply["devices"] == ["cpu:0", "cpu:1"]
        for _ in range(4):
            cli.infer("demo", {"x": np.zeros((1, 4), np.float32)})
        stats = cli.stats()
        lanes = stats["stats"]["models"]["demo"]["replicas"]
        assert [r["device"] for r in lanes] == ["cpu:0", "cpu:1"]
        assert sum(r["batches"] for r in lanes) >= 1
        assert stats["models"]["demo"]["replicas"] == 2
        serving_top.main([server.endpoint])
        out = capsys.readouterr().out
        assert "r0" in out and "cpu:0" in out and "replicas=2" in out
    finally:
        cli.close()
        server.shutdown(drain=True)


def test_bench_serving_mc_smoke_subprocess():
    """Tier-1 smoke of the serving_mc bench lane: fresh process, 4
    forced host devices, 4 replicas, per-dispatch cost stand-in —
    JSON record with all requests answered and bit_exact vs direct."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_serving.py"),
         "--smoke", "--replicas", "4", "--force_host_devices", "4",
         "--dispatch_cost_ms", "10", "--qps", "120", "--duration", "2",
         "--max_bucket", "1", "--max_queue", "64",
         "--deadline_ms", "5000"],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert lines, proc.stdout[-500:]
    rec = json.loads(lines[-1])
    assert rec["metric"] == "serving_qps"
    assert rec["replicas"] == 4
    assert rec["bit_exact"] is True
    assert rec["ok"] > 0 and rec["errors"] == 0
    assert len(rec["replica_stats"]) == 4
    assert {r["device"] for r in rec["replica_stats"]} == \
        {"cpu:0", "cpu:1", "cpu:2", "cpu:3"}
