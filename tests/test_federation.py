"""Federated serving tests (paddle_tpu/federation — SERVING.md
"Federated serving").

Pins the new global tier's contracts: heartbeat-TTL membership with
expiry/rejoin and a monotonic revision counter, the front-door
router's bit-exactness vs direct backends (one-shot AND streaming),
deterministic spillover-before-shed, the typed StreamBroken client
surface (a mid-stream reconnect must never silently restart a stream
from token 0), drain-vs-dead disambiguation, and the fleet-of-fleets
controller's pure decision core + capacity-directed page/fault cycle.
Everything CPU-safe under JAX_PLATFORMS=cpu; socket servers bind
127.0.0.1:0.
"""

import socket
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.distributed.rpc import _recv_msg, _send_msg
from paddle_tpu.federation import (FrontendServer, GlobalFleetController,
                                   GlobalSensors, MembershipRegistry,
                                   decide_global, place_by_capacity)
from paddle_tpu.flags import FLAGS
from paddle_tpu.obs import events as obs_events
from paddle_tpu.serving import (FleetPolicy, InferenceServer,
                                ServerOverloaded, ServingClient,
                                StreamBroken)

TTL = 0.8
BEAT_MS = 100.0


@pytest.fixture(autouse=True)
def _fed_flags():
    ttl, beat = FLAGS.federation_ttl_s, FLAGS.federation_heartbeat_ms
    FLAGS.federation_ttl_s = TTL
    FLAGS.federation_heartbeat_ms = BEAT_MS
    yield
    FLAGS.federation_ttl_s = ttl
    FLAGS.federation_heartbeat_ms = beat


def _export_fc(tmp_path, seed, name="m"):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        h = fluid.layers.fc(input=x, size=6, act="relu")
        pred = fluid.layers.fc(input=h, size=6, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / name)
        fluid.save_inference_model(md, ["x"], [pred], exe,
                                   main_program=main)
    return md


def _direct(md, buckets=(2, 4)):
    from paddle_tpu.inference import AnalysisConfig, Predictor
    cfg = AnalysisConfig(model_dir=md)
    cfg.batch_size_buckets = tuple(buckets)
    return Predictor(cfg)


def _events_since(mark, kind):
    return [e for e in obs_events.recent_events(kind=kind)
            if e["ts"] >= mark]


# ---------------------------------------------------------------------------
# membership registry (no sockets)
# ---------------------------------------------------------------------------

class TestMembership:
    def test_lease_lifecycle_expiry_and_rejoin(self):
        mark = time.time()
        reg = MembershipRegistry(ttl_s=0.15)
        g = reg.register("127.0.0.1", 9001, backend_id="b1",
                         models={"m": {"replicas": 2,
                                       "est_peak_mb": 10.0}},
                         capacity_mb=100.0)
        assert g["backend_id"] == "b1" and g["ttl_s"] == 0.15
        rev0 = g["revision"]
        assert reg.heartbeat("b1", g["lease_id"],
                             load={"queue_depth": 3})
        lease = reg.backends()["b1"]
        assert lease["resident_mb"] == 20.0
        assert lease["load"]["queue_depth"] == 3
        # a stale lease id is refused -> the backend must re-register
        assert not reg.heartbeat("b1", "ls-999")
        time.sleep(0.2)
        assert reg.backends() == {}
        assert reg.lost()["b1"]["reason"] == "ttl"
        assert reg.revision > rev0
        lost = _events_since(mark, "backend_lost")
        assert lost and lost[-1]["backend"] == "b1"
        # rejoin: same id, fresh lease, evented with rejoin=True
        g2 = reg.register("127.0.0.1", 9001, backend_id="b1")
        assert g2["lease_id"] != g["lease_id"]
        assert "b1" in reg.backends() and "b1" not in reg.lost()
        joins = _events_since(mark, "backend_joined")
        assert joins[-1]["rejoin"] is True

    def test_draining_leaves_placement_set_but_stays_leased(self):
        reg = MembershipRegistry(ttl_s=5.0)
        reg.register("127.0.0.1", 1, backend_id="a")
        reg.register("127.0.0.1", 2, backend_id="b")
        assert reg.mark_draining("a")
        assert sorted(reg.backends()) == ["a", "b"]
        assert sorted(reg.backends(accepting_only=True)) == ["b"]
        assert reg.backends()["a"]["draining"] is True
        assert reg.mark_draining("a", False)  # resume
        assert sorted(reg.backends(accepting_only=True)) == ["a", "b"]

    def test_suspect_expires_immediately(self):
        reg = MembershipRegistry(ttl_s=60.0)
        reg.register("127.0.0.1", 1, backend_id="a")
        assert reg.suspect("a", "conn_refused")
        assert reg.backends() == {}
        assert reg.lost()["a"]["reason"] == "conn_refused"

    def test_place_by_capacity_ranking(self):
        leases = {
            "tight": {"capacity_mb": 100.0, "resident_mb": 90.0,
                      "models": {}},
            "roomy": {"capacity_mb": 1000.0, "resident_mb": 10.0,
                      "models": {}},
            "unknown": {"capacity_mb": 0.0, "resident_mb": 0.0,
                        "models": {}},
        }
        # declared capacity beats undeclared; most free wins
        assert place_by_capacity(leases) == "roomy"
        # spread: a host NOT holding the model outranks the roomy
        # holder when capacities tie closely enough in rank class
        leases["roomy"]["models"] = {"m": {}}
        leases["tight"]["resident_mb"] = 0.0
        assert place_by_capacity(leases, prefer_absent="m") == "tight"


# ---------------------------------------------------------------------------
# global decision core (pure)
# ---------------------------------------------------------------------------

class TestDecideGlobal:
    POL = FleetPolicy(min_replicas=1, max_replicas=4, scale_up_queue=4,
                      scale_down_idle_s=10.0, page_ttl_s=30.0,
                      scale_cooldown_s=5.0, page_cooldown_s=5.0)

    def test_paged_everywhere_faults_in_on_demand(self):
        s = GlobalSensors("m", total_replicas=0, paged_on=["b1"],
                          requests_delta=3)
        acts = decide_global(s, self.POL, {}, now=100.0)
        assert [a.kind for a in acts] == ["fault_in"]
        assert acts[0].signal["tier"] == "global"
        # no demand -> stays cold
        s2 = GlobalSensors("m", total_replicas=0, paged_on=["b1"])
        assert decide_global(s2, self.POL, {}, now=100.0) == []

    def test_scale_up_on_queue_within_budget_and_cooldown(self):
        s = GlobalSensors("m", total_replicas=2,
                          resident={"b1": 2}, queue_depth=9)
        acts = decide_global(s, self.POL, {}, now=100.0)
        assert [a.kind for a in acts] == ["scale_up"]
        assert acts[0].params["to"] == 3
        # cooldown holds it back
        assert decide_global(s, self.POL, {"last_scale_t": 98.0},
                             100.0) == []
        # at the global budget ceiling: no action
        s.total_replicas = 4
        assert decide_global(s, self.POL, {}, 100.0) == []

    def test_scale_down_and_page_out_on_idle(self):
        s = GlobalSensors("m", total_replicas=2, resident={"b1": 2},
                          idle_s=50.0)
        acts = decide_global(s, self.POL, {}, now=100.0)
        assert [a.kind for a in acts] == ["scale_down", "page_out"]
        assert acts[0].params["to"] == 1
        # min_replicas floors the shrink; paging still fires
        s2 = GlobalSensors("m", total_replicas=1, resident={"b1": 1},
                          idle_s=50.0)
        assert [a.kind for a in decide_global(s2, self.POL, {},
                                              100.0)] == ["page_out"]


# ---------------------------------------------------------------------------
# stub backends (deterministic overload / mid-stream death)
# ---------------------------------------------------------------------------

class _StubBackend:
    """Minimal wire peer: registers with a frontend and answers every
    verb from a scripted table — the deterministic stand-in for 'this
    backend sheds' / 'this backend dies mid-stream'."""

    def __init__(self, script):
        self.script = script  # callable(msg, sock) -> reply dict|None
        self._srv = socket.socket()
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(8)
        self.host, self.port = self._srv.getsockname()
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                msg = _recv_msg(conn)
                reply = self.script(msg, conn)
                if reply is not None:
                    _send_msg(conn, reply)
        except Exception:
            pass
        finally:
            conn.close()

    def close(self):
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass


def _register_stub(fe, stub, backend_id, models=("m",), queue_depth=0):
    g = fe.membership.register(
        stub.host, stub.port, backend_id=backend_id,
        models={n: {"replicas": 1} for n in models})
    fe.membership.heartbeat(g["backend_id"], g["lease_id"],
                            load={"queue_depth": queue_depth})
    return g


# ---------------------------------------------------------------------------
# frontend routing over real backends
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fc_md(tmp_path_factory):
    return _export_fc(tmp_path_factory.mktemp("fed_fc"), seed=3)


class TestFrontendRouting:
    def test_three_backend_mixed_traffic_bit_exact(self, fc_md):
        fe = FrontendServer().start()
        backs = [InferenceServer(buckets=(2, 4), federation=fe.endpoint,
                                 backend_id="b%d" % i).start()
                 for i in range(3)]
        cli = ServingClient(fe.endpoint)
        try:
            r = cli.load_model("m", fc_md, buckets=[2, 4])
            assert r["loaded"] == 3
            deadline = time.monotonic() + 5
            while (len(fe._candidates("m")) < 3
                   and time.monotonic() < deadline):
                time.sleep(0.05)  # heartbeats deliver the model payload
            assert len(fe._candidates("m")) == 3
            direct = _direct(fc_md)
            rng = np.random.RandomState(0)
            xs = [rng.randn(b, 4).astype(np.float32)
                  for b in (1, 3, 2, 1, 4, 2)]
            for x in xs:
                out = cli.infer("m", {"x": x}, deadline_ms=30000)
                ref = direct.run({"x": x})[0]
                assert np.array_equal(out[0], ref), \
                    "federated reply differs from direct run"
            assert sum(fe._placed.values()) == len(xs)
            # merged stats: request total spans the whole federation
            st = cli.stats()
            assert st["stats"]["models"]["m"]["requests"] == len(xs)
            fed = st["federation"]
            assert len(fed["backends"]) == 3
            assert fed["counters"]["shed"] == 0
        finally:
            cli.close()
            for b in backs:
                b.shutdown()
            fe.shutdown()

    def test_spillover_before_shed_is_deterministic(self, fc_md):
        """An always-overloaded best-scored backend spills to the next
        candidate (same trace_id); only all-overloaded sheds."""
        relayed_traces = []

        def overloaded(msg, sock):
            if msg.get("cmd") == "infer":
                relayed_traces.append(msg.get("trace_id"))
                return {"error": "full", "code": "overloaded"}
            return {"ok": True}

        fe = FrontendServer().start()
        stub = _StubBackend(overloaded)
        real = InferenceServer(buckets=(2, 4), federation=fe.endpoint,
                               backend_id="zz-real").start()
        cli = ServingClient(fe.endpoint)
        try:
            cli.call({"cmd": "load_model", "name": "m",
                      "path": fc_md, "buckets": [2, 4],
                      "backend": "zz-real"})
            deadline = time.monotonic() + 5
            while (len(fe._candidates("m")) < 1
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            # the stub scores better (queue 0, registered id 'aa-')
            _register_stub(fe, stub, "aa-stub", queue_depth=0)
            assert fe._candidates("m")[0] == "aa-stub"
            x = np.zeros((1, 4), np.float32)
            out = cli.infer("m", {"x": x}, trace_id="t-spill",
                            deadline_ms=30000)
            assert out[0].shape == (1, 6)
            # the shed backend saw the SAME trace the winner served
            assert relayed_traces == ["t-spill"]
            assert fe._counters["spillover"] == 1
            assert fe._counters["shed"] == 0
            assert fe._placed == {"zz-real": 1}
            # every candidate overloaded -> typed shed to the caller
            fe.membership.mark_draining("zz-real")
            with pytest.raises(ServerOverloaded):
                cli.call({"cmd": "infer", "model": "m",
                          "feeds": {"x": x}})
            assert fe._counters["shed"] == 1
        finally:
            cli.close()
            stub.close()
            real.shutdown()
            fe.shutdown()

    def test_dead_backend_suspected_and_routed_around(self, fc_md):
        """Hard connect evidence expires the lease immediately — the
        next candidate answers, nothing hangs, nothing is lost."""
        fe = FrontendServer().start()
        real = InferenceServer(buckets=(2, 4), federation=fe.endpoint,
                               backend_id="zz-real").start()
        cli = ServingClient(fe.endpoint)
        try:
            cli.load_model("m", fc_md, buckets=[2, 4])
            deadline = time.monotonic() + 5
            while (len(fe._candidates("m")) < 1
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            dead = _StubBackend(lambda m, s: {"ok": True})
            dead.close()  # port is now refused
            _register_stub(fe, dead, "aa-dead")
            assert fe._candidates("m")[0] == "aa-dead"
            out = cli.infer("m", {"x": np.zeros((1, 4), np.float32)},
                            deadline_ms=30000)
            assert out[0].shape == (1, 6)
            assert "aa-dead" in fe.membership.lost()
        finally:
            cli.close()
            real.shutdown()
            fe.shutdown()

    def test_lost_heartbeat_expires_within_ttl_and_rejoins(self, fc_md):
        mark = time.time()
        fe = FrontendServer().start()
        b0 = InferenceServer(buckets=(2, 4), federation=fe.endpoint,
                             backend_id="b0").start()
        b1 = InferenceServer(buckets=(2, 4), federation=fe.endpoint,
                             backend_id="b1").start()
        cli = ServingClient(fe.endpoint)
        try:
            cli.load_model("m", fc_md, buckets=[2, 4])
            deadline = time.monotonic() + 5
            while (len(fe._candidates("m")) < 2
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            # stop b1's heartbeats WITHOUT deregistering (a hang, not
            # a clean leave); keep the link object for the server
            link, b1._fed_link = b1._fed_link, None
            link.stop(deregister=False)
            deadline = time.monotonic() + 3 * TTL
            while ("b1" not in fe.membership.lost()
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            assert "b1" in fe.membership.lost(), \
                "lease should expire within one TTL of silence"
            # traffic keeps flowing, placed only on the survivor
            x = np.zeros((2, 4), np.float32)
            for _ in range(3):
                cli.infer("m", {"x": x}, deadline_ms=30000)
            assert fe._placed.get("b1") is None
            assert fe._placed["b0"] == 3
            assert _events_since(mark, "backend_lost")
        finally:
            cli.close()
            b0.shutdown()
            b1.shutdown()
            fe.shutdown()

    def test_drain_stops_placement_then_deleases(self, fc_md):
        mark = time.time()
        fe = FrontendServer().start()
        b0 = InferenceServer(buckets=(2, 4), federation=fe.endpoint,
                             backend_id="b0").start()
        b1 = InferenceServer(buckets=(2, 4), federation=fe.endpoint,
                             backend_id="b1").start()
        cli = ServingClient(fe.endpoint)
        try:
            cli.load_model("m", fc_md, buckets=[2, 4])
            deadline = time.monotonic() + 5
            while (len(fe._candidates("m")) < 2
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            r = cli.call({"cmd": "drain", "backend": "b1"})
            assert r["draining"] is True
            # placement excludes the draining lease IMMEDIATELY
            assert fe._candidates("m") == ["b0"]
            # ... and the backend itself reports not-accepting while
            # still answering (draining != dead)
            direct = ServingClient(b1.endpoint)
            try:
                assert direct.health()["accepting"] is False
            finally:
                direct.close()
            x = np.zeros((1, 4), np.float32)
            for _ in range(2):
                cli.infer("m", {"x": x}, deadline_ms=30000)
            assert fe._placed == {"b0": 2}
            # no in-flight work -> the sweeper de-leases it
            deadline = time.monotonic() + 3 * TTL
            while (not _events_since(mark, "backend_drained")
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            drained = _events_since(mark, "backend_drained")
            assert drained and drained[-1]["backend"] == "b1"
        finally:
            cli.close()
            b0.shutdown()
            b1.shutdown()
            fe.shutdown()


# ---------------------------------------------------------------------------
# streaming: relay, affinity, StreamBroken
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def decode_md(tmp_path_factory):
    from paddle_tpu.inference.decode import build_tiny_decode_model
    md = str(tmp_path_factory.mktemp("fed_gen") / "gen")
    build_tiny_decode_model(md, vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, max_seq_len=64, eos_id=-1,
                            seed=21)
    return md


class TestStreaming:
    def test_stream_relay_bit_exact_with_affinity(self, decode_md):
        from paddle_tpu.inference.decode import (GenerativePredictor,
                                                 greedy_decode)
        fe = FrontendServer().start()
        backs = [InferenceServer(federation=fe.endpoint,
                                 backend_id="b%d" % i).start()
                 for i in range(2)]
        cli = ServingClient(fe.endpoint)
        try:
            cli.load_model("gen", decode_md, decode_slots=4)
            deadline = time.monotonic() + 5
            while (len(fe._candidates("gen")) < 2
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            pred = GenerativePredictor(decode_md)
            prompt = [5, 9, 11]
            ref = list(greedy_decode(pred, prompt, 12)[0])
            toks = []
            for chunk in cli.infer_stream("gen", prompt,
                                          max_new_tokens=12,
                                          deadline_ms=30000):
                toks.extend(chunk)
            assert toks == ref[:len(toks)] and len(toks) == 12
            info = cli.last_stream_info
            first = info["backend"]
            assert first in ("b0", "b1")
            # session affinity: the same trace_id lands on the backend
            # holding the session (its KV locality)
            for _ in range(2):
                list(cli.infer_stream("gen", prompt, max_new_tokens=4,
                                      trace_id=info["trace_id"],
                                      deadline_ms=30000))
                assert cli.last_stream_info["backend"] == first
        finally:
            cli.close()
            for b in backs:
                b.shutdown()
            fe.shutdown()

    def test_client_raises_stream_broken_on_dead_socket(self):
        """Satellite bugfix contract: a connection dying mid-stream is
        a typed StreamBroken carrying the committed token count — not a
        silent reconnect-and-restart from token 0."""
        def die_after_two(msg, sock):
            _send_msg(sock, {"chunk": True, "seq": 0, "tokens": [7],
                             "trace_id": "t1"})
            _send_msg(sock, {"chunk": True, "seq": 1, "tokens": [8, 9],
                             "trace_id": "t1"})
            sock.close()  # hard death, no terminal frame
            raise ConnectionError

        stub = _StubBackend(die_after_two)
        cli = ServingClient("%s:%d" % (stub.host, stub.port))
        try:
            got = []
            with pytest.raises(StreamBroken) as ei:
                for chunk in cli.infer_stream("gen", [1],
                                              max_new_tokens=8):
                    got.extend(chunk)
            assert got == [7, 8, 9]
            assert ei.value.received == 3
            assert cli.last_stream_info["code"] == "stream_broken"
        finally:
            cli.close()
            stub.close()

    def test_typed_stream_broken_frame_from_frontend(self):
        """The frontend's terminal stream_broken frame surfaces as the
        same typed exception, naming the lost backend."""
        def typed_break(msg, sock):
            _send_msg(sock, {"chunk": True, "seq": 0, "tokens": [4],
                             "trace_id": "t2", "backend": "bX"})
            _send_msg(sock, {"error": "backend bX lost mid-stream",
                             "code": "stream_broken", "done": True,
                             "trace_id": "t2", "backend": "bX",
                             "chunks": 1})
            raise ConnectionError

        stub = _StubBackend(typed_break)
        cli = ServingClient("%s:%d" % (stub.host, stub.port))
        try:
            got = []
            with pytest.raises(StreamBroken) as ei:
                for chunk in cli.infer_stream("gen", [1],
                                              max_new_tokens=8):
                    got.extend(chunk)
            assert got == [4]
            assert ei.value.backend == "bX"
            assert ei.value.received == 1
        finally:
            cli.close()
            stub.close()

    def test_frontend_converts_backend_death_to_typed_frame(
            self, decode_md):
        """A backend socket dying mid-relay surfaces to the CLIENT as
        one typed stream_broken frame naming the lost backend and the
        committed chunk count (zero hangs); the frontend suspects the
        backend and the next stream completes on the survivor."""
        calls = []

        def victim_script(msg, sock):
            if msg.get("cmd") != "infer_stream":
                return {"ok": True}
            calls.append(msg["trace_id"])
            tid = msg["trace_id"]
            _send_msg(sock, {"chunk": True, "seq": 0, "tokens": [1],
                             "trace_id": tid})
            if len(calls) == 1:
                # first stream completes cleanly -> pin lands here
                _send_msg(sock, {"chunk": True, "seq": 1,
                                 "tokens": [2], "trace_id": tid})
                _send_msg(sock, {"ok": True, "done": True,
                                 "trace_id": tid, "new_tokens": 2,
                                 "finish_reason": "length"})
                return None
            sock.close()  # second stream: die mid-relay
            raise ConnectionError

        fe = FrontendServer().start()
        survivor = InferenceServer(federation=fe.endpoint,
                                   backend_id="zz-survivor").start()
        cli = ServingClient(fe.endpoint)
        stub = _StubBackend(victim_script)
        try:
            cli.load_model("gen", decode_md, decode_slots=4)
            deadline = time.monotonic() + 5
            while (len(fe._candidates("gen")) < 1
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            _register_stub(fe, stub, "aa-victim", models=("gen",))
            assert fe._candidates("gen")[0] == "aa-victim"
            got = list(cli.infer_stream("gen", [5, 9],
                                        max_new_tokens=8,
                                        trace_id="t-kill",
                                        deadline_ms=30000))
            assert got == [[1], [2]]
            assert cli.last_stream_info["backend"] == "aa-victim"
            # stream 2, same trace: affinity routes back, backend dies
            got = []
            with pytest.raises(StreamBroken) as ei:
                for chunk in cli.infer_stream("gen", [5, 9],
                                              max_new_tokens=8,
                                              trace_id="t-kill",
                                              deadline_ms=30000):
                    got.extend(chunk)
            assert got == [1]  # the committed chunk stands
            assert ei.value.received == 1
            assert ei.value.backend == "aa-victim"
            assert fe._counters["streams_broken"] == 1
            assert "aa-victim" in fe.membership.lost()
            # stream 3: the lost pin is gone, the survivor answers a
            # REAL stream end to end — zero wedged lanes
            toks = []
            for chunk in cli.infer_stream("gen", [5, 9],
                                          max_new_tokens=4,
                                          trace_id="t-kill",
                                          deadline_ms=60000):
                toks.extend(chunk)
            assert len(toks) == 4
            assert cli.last_stream_info["backend"] == "zz-survivor"
        finally:
            cli.close()
            stub.close()
            survivor.shutdown()
            fe.shutdown()

    def test_repin_counter_on_silent_backend_loss(self, decode_md):
        """A pin onto a lease that silently expired re-pins onto the
        survivor set (counted): the KV slots are gone with the
        backend, the trace is not."""
        def completing(msg, sock):
            if msg.get("cmd") != "infer_stream":
                return {"ok": True}
            tid = msg["trace_id"]
            _send_msg(sock, {"chunk": True, "seq": 0, "tokens": [3],
                             "trace_id": tid})
            _send_msg(sock, {"ok": True, "done": True,
                             "trace_id": tid, "new_tokens": 1,
                             "finish_reason": "length"})
            return None

        fe = FrontendServer().start()
        survivor = InferenceServer(federation=fe.endpoint,
                                   backend_id="zz-survivor").start()
        cli = ServingClient(fe.endpoint)
        stub = _StubBackend(completing)
        try:
            cli.load_model("gen", decode_md, decode_slots=4)
            deadline = time.monotonic() + 5
            while (len(fe._candidates("gen")) < 1
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            _register_stub(fe, stub, "aa-stub", models=("gen",))
            got = list(cli.infer_stream("gen", [5], max_new_tokens=8,
                                        trace_id="t-a",
                                        deadline_ms=30000))
            assert got == [[3]]
            assert fe._pinned("t-a") == "aa-stub"
            fe.membership.suspect("aa-stub", "test")  # silent loss
            toks = []
            for chunk in cli.infer_stream("gen", [5],
                                          max_new_tokens=4,
                                          trace_id="t-a",
                                          deadline_ms=60000):
                toks.extend(chunk)
            assert len(toks) == 4
            assert cli.last_stream_info["backend"] == "zz-survivor"
            assert fe._counters["repins"] == 1
            assert fe._pinned("t-a") == "zz-survivor"
        finally:
            cli.close()
            stub.close()
            survivor.shutdown()
            fe.shutdown()


# ---------------------------------------------------------------------------
# global fleet over the wire: page-out / fault-in by capacity
# ---------------------------------------------------------------------------

class TestGlobalFleet:
    def test_cluster_page_out_then_fault_in_lands_on_capacity(
            self, fc_md):
        """Idle past page_ttl everywhere -> paged on EVERY backend;
        demand faults it back in on the host with the most declared
        free capacity (acceptance: lands on the capacity host)."""
        mark = time.time()
        fe = FrontendServer().start()
        small = InferenceServer(buckets=(2, 4), federation=fe.endpoint,
                                backend_id="aa-small",
                                capacity_mb=50.0).start()
        big = InferenceServer(buckets=(2, 4), federation=fe.endpoint,
                              backend_id="zz-big",
                              capacity_mb=10000.0).start()
        gf = GlobalFleetController(
            fe, policies={"*": FleetPolicy(
                min_replicas=1, max_replicas=2, page_ttl_s=0.2,
                scale_down_idle_s=9999.0, page_cooldown_s=0.0)},
            dry_run=False)
        cli = ServingClient(fe.endpoint)
        try:
            cli.load_model("m", fc_md, buckets=[2, 4])
            deadline = time.monotonic() + 5
            while (len(fe._candidates("m")) < 2
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            cli.infer("m", {"x": np.zeros((1, 4), np.float32)},
                      deadline_ms=30000)
            time.sleep(0.25)  # heartbeats carry the request count
            gf.tick()  # baseline: request deltas + idle clocks
            time.sleep(0.35)  # idle past page_ttl_s
            processed = gf.tick()
            kinds = [a.kind for a, out in processed if out == "ok"]
            assert kinds == ["page_out"], processed
            # paged on EVERY backend; heartbeats propagate the flip
            deadline = time.monotonic() + 3 * TTL
            while time.monotonic() < deadline:
                leases = fe.membership.backends()
                if all("m" in (l.get("paged") or [])
                       and "m" not in l["models"]
                       for l in leases.values()):
                    break
                time.sleep(0.05)
            leases = fe.membership.backends()
            assert all("m" in (l.get("paged") or [])
                       for l in leases.values()), leases
            assert fe._candidates("m") == []
            # demand: the frontend faults in where capacity lives
            before = dict(fe._placed)
            out = cli.infer("m", {"x": np.zeros((1, 4), np.float32)},
                            deadline_ms=60000)
            assert out[0].shape == (1, 6)
            assert fe._placed.get("zz-big", 0) \
                == before.get("zz-big", 0) + 1
            faults = _events_since(mark, "global_fault_in")
            assert faults and faults[-1]["backend"] == "zz-big"
            assert faults[-1]["warm"] is True
            # the small host is untouched: still paged there
            deadline = time.monotonic() + 2
            while ("m" in fe.membership.backends()["aa-small"]["models"]
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            assert "m" in (fe.membership.backends()["aa-small"]
                           .get("paged") or [])
        finally:
            cli.close()
            gf.stop()
            small.shutdown()
            big.shutdown()
            fe.shutdown()


# ---------------------------------------------------------------------------
# health surface
# ---------------------------------------------------------------------------

class TestHealthAccepting:
    def test_accepting_flag_tracks_drain_and_resume(self, fc_md):
        srv = InferenceServer(buckets=(2, 4)).start()
        cli = ServingClient(srv.endpoint)
        try:
            cli.load_model("m", fc_md, buckets=[2, 4])
            h = cli.health()
            assert h["accepting"] is True and h["draining"] is False
            cli.drain()
            h = cli.health()
            assert h["accepting"] is False and h["draining"] is True
            cli.drain(resume=True)
            assert cli.health()["accepting"] is True
        finally:
            cli.close()
            srv.shutdown()
