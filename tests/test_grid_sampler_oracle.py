"""grid_sampler reference oracle (grid_sampler_op.h restated):
coords unnormalized by 0.5*(size-1)*(g+1) (align-corners), bilinear
with each corner fetched through the isInBound zero-padding check —
including grids beyond [-1,1] and exact-edge samples."""

import numpy as np

from tests.test_op_tail import run_op


def oracle(x, grid):
    N, C, H, W = x.shape
    _, Hg, Wg, _ = grid.shape
    out = np.zeros((N, C, Hg, Wg), x.dtype)

    def at(n, yy, xx):
        if yy < 0 or yy > H - 1 or xx < 0 or xx > W - 1:
            return np.zeros(C, x.dtype)
        return x[n, :, int(yy), int(xx)]

    for n in range(N):
        for i in range(Hg):
            for j in range(Wg):
                gx = 0.5 * (W - 1) * (grid[n, i, j, 0] + 1.0)
                gy = 0.5 * (H - 1) * (grid[n, i, j, 1] + 1.0)
                x_w, y_n = np.floor(gx), np.floor(gy)
                dw, dn = gx - x_w, gy - y_n
                out[n, :, i, j] = (
                    at(n, y_n, x_w) * (1 - dw) * (1 - dn)
                    + at(n, y_n, x_w + 1) * dw * (1 - dn)
                    + at(n, y_n + 1, x_w) * (1 - dw) * dn
                    + at(n, y_n + 1, x_w + 1) * dw * dn)
    return out


def test_grid_sampler_matches_reference():
    rng = np.random.RandomState(4)
    x = rng.randn(2, 3, 5, 7).astype(np.float32)
    grid = rng.uniform(-1.4, 1.4, (2, 4, 6, 2)).astype(np.float32)
    # plant exact corners/edges and fully out-of-range points
    grid[0, 0, 0] = [-1.0, -1.0]
    grid[0, 0, 1] = [1.0, 1.0]
    grid[0, 1, 0] = [2.5, 0.0]
    grid[1, 0, 0] = [0.0, -2.5]
    out = run_op("grid_sampler", {"X": x, "Grid": grid}, {})
    np.testing.assert_allclose(np.asarray(out["Output"]),
                               oracle(x, grid), atol=1e-4, rtol=1e-4)
