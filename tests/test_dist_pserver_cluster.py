"""Full parameter-server topology as a 4-process localhost cluster:
2 pservers + 2 trainers launched with subprocess.Popen, sync AND async
modes (VERDICT r3 #9; reference test_dist_base.py:219 start_pserver,
:299 _run_cluster + test_dist_mnist.py check_with_place loss parity)."""

import json
import os
import socket
import subprocess
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
MODEL = os.path.join(HERE, "dist_pserver_model.py")
STEPS = 5


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(args):
    env = dict(os.environ)
    # single-device CPU per process: the PS path is host-side
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    repo = os.path.dirname(HERE)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, MODEL] + [str(a) for a in args],
        env=env, cwd=os.path.dirname(HERE),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _run_cluster(sync):
    eps = "127.0.0.1:%d,127.0.0.1:%d" % (_free_port(), _free_port())
    ep_list = eps.split(",")
    pservers = [_spawn(["PSERVER", ep, eps, 2, int(sync)])
                for ep in ep_list]
    trainers = [_spawn(["TRAINER", tid, eps, 2, int(sync), STEPS])
                for tid in range(2)]
    outs = []
    try:
        for p in trainers:
            out, err = p.communicate(timeout=420)
            assert p.returncode == 0, "trainer failed:\n%s\n%s" % (out,
                                                                   err)
            outs.append(out)
    finally:
        # tell both pservers to exit (reference Executor.close notify)
        from paddle_tpu.distributed.rpc import RPCClient
        cli = RPCClient()
        for ep in ep_list:
            cli.send_exit(ep)
        cli.close()
        for p in pservers:
            try:
                p.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
        for p in trainers:
            if p.poll() is None:
                p.kill()
    losses = []
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("LOSSES ")][0]
        losses.append(json.loads(line[len("LOSSES "):]))
    return losses


def _run_local():
    p = _spawn(["LOCAL", STEPS])
    out, err = p.communicate(timeout=300)
    assert p.returncode == 0, "local failed:\n%s\n%s" % (out, err)
    line = [l for l in out.splitlines() if l.startswith("LOSSES ")][0]
    return json.loads(line[len("LOSSES "):])


def test_sync_pserver_cluster_matches_local():
    """Sync mode: the distributed step IS the full-batch step (grads
    averaged across trainers on the pservers), so per-step losses match
    the local run within delta (test_dist_mnist.py:26 delta=1e-5 spirit;
    two fc layers ensure both pservers own param blocks)."""
    local = _run_local()
    dist = _run_cluster(sync=True)
    assert len(dist) == 2 and all(len(l) == STEPS for l in dist)
    # step 0 runs on identical init; later steps on pserver-updated params
    for i in range(STEPS):
        dist_loss = 0.5 * (dist[0][i] + dist[1][i])
        assert abs(dist_loss - local[i]) < 1e-3, (i, dist_loss, local[i])
    assert local[-1] < local[0]   # the task actually trains


def test_async_pserver_cluster_trend():
    """Async mode: no barriers — updates interleave nondeterministically,
    so assert the TREND (loss decreases), not per-step parity (the
    reference's async dist tests also only check convergence)."""
    dist = _run_cluster(sync=False)
    for traj in dist:
        assert len(traj) == STEPS
        assert all(np.isfinite(traj))
        assert traj[-1] < traj[0], traj
