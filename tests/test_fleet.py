"""Fleet-controller tests (paddle_tpu/serving/fleet.py — SERVING.md
"Fleet controller").

The policy core is pinned as a PURE function: seeded ModelSensors
snapshots + controller state -> expected FleetAction lists (scale up
on breach and on queue pressure, scale down on idle, page on TTL,
degrade-BEFORE-shed ordering, restore hysteresis, cooldown
suppression) — no server, no threads, no sleeps.  The actuator layer
is pinned on a live registry: unload persists the load spec and
fault_in reconstructs the exact lane set bit-exactly (the PR's bugfix
satellite), a paged model faults in on the next request with the
rebuild time measured, resize rides the hot-swap discipline and the
resource fit check gates every grow, and dry_run decides without
acting.  The wire surfaces (fleet RPC, set_fleet_policy, serving_top
REPL/FLEET columns + --json "fleet" key, Prometheus fleet_* families)
are pinned through one in-process server.  Everything CPU-safe under
JAX_PLATFORMS=cpu.
"""

import json
import os
import sys
import tempfile
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.flags import FLAGS, set_flags
from paddle_tpu.obs import events as obs_events
from paddle_tpu.obs import tracing as obs_tracing
from paddle_tpu.serving import (InferenceServer, ModelRegistry,
                                ServingClient, ServingError,
                                ServingMetrics)
from paddle_tpu.serving.fleet import (FLEET_ACTIVE, FLEET_PAGED,
                                      FleetController, FleetPolicy,
                                      ModelSensors, decide,
                                      parse_fleet_spec)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, os.path.join(REPO, "tools"))

import serving_top  # noqa: E402

_DEFAULTS = {"serving_slo": "", "slo_monitor": True,
             "slo_eval_interval_ms": 1000.0,
             "fleet_controller": False,
             "fleet_eval_interval_ms": 1000.0,
             "fleet_policy": "", "fleet_dry_run": False,
             "serving_device_mem_mb": 0}


@pytest.fixture(autouse=True)
def _fleet_reset():
    set_flags(dict(_DEFAULTS))
    obs_events.configure()
    obs_tracing.configure()
    yield
    set_flags(dict(_DEFAULTS))
    obs_events.configure()


def _save_fc(tag, seed=5):
    """Tiny fc artifact; distinct seeds give distinct weights."""
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = seed
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        pred = fluid.layers.fc(input=x, size=4, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = os.path.join(tempfile.mkdtemp(prefix="fleet_t_"), tag)
        fluid.save_inference_model(md, ["x"], [pred], exe,
                                   main_program=main_p)
    return md


@pytest.fixture(scope="module")
def fc_dir():
    return _save_fc("m", seed=5)


@pytest.fixture(scope="module")
def fc_big_dir():
    """~1.5 MiB of weights — big enough that a 1 MiB device budget
    (the serving_device_mem_mb floor) rejects it in the fit check."""
    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 7
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data(name="x", shape=[256], dtype="float32")
        h = fluid.layers.fc(input=x, size=512, act="relu")
        h = fluid.layers.fc(input=h, size=512, act="relu")
        pred = fluid.layers.fc(input=h, size=4, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = os.path.join(tempfile.mkdtemp(prefix="fleet_big_"), "big")
        fluid.save_inference_model(md, ["x"], [pred], exe,
                                   main_program=main_p)
    return md


X = np.linspace(-1, 1, 8, dtype=np.float32).reshape(1, 8)


# ---------------------------------------------------------------------------
# policy spec grammar
# ---------------------------------------------------------------------------

class TestPolicySpec:
    def test_parse_default_and_per_model(self):
        out = parse_fleet_spec(
            "max_replicas=4;llm:page_ttl_s=600,scale_up_queue=8")
        assert out["*"].max_replicas == 4
        assert out["*"].page_ttl_s == 0.0
        assert out["llm"].page_ttl_s == 600.0
        assert out["llm"].scale_up_queue == 8
        assert out["llm"].max_replicas == 1  # per-model, not inherited

    def test_bad_key_raises(self):
        with pytest.raises(ValueError, match="bad fleet policy"):
            parse_fleet_spec("llm:replica_count=4")

    def test_bounds(self):
        p = FleetPolicy(min_replicas=3, max_replicas=1,
                        degrade_weight=7.0)
        assert p.max_replicas >= p.min_replicas == 3
        assert p.degrade_weight == 1.0


# ---------------------------------------------------------------------------
# the pure decision core: seeded sensors -> expected actions
# ---------------------------------------------------------------------------

_POL = dict(min_replicas=1, max_replicas=3, page_ttl_s=5.0,
            scale_up_queue=4, scale_down_idle_s=2.0,
            degrade_weight=0.9, restore_evals=3)


class TestDecide:
    def test_scale_up_on_breach(self):
        acts = decide(ModelSensors("m", replicas=1, slo_state="breach"),
                      FleetPolicy(**_POL), {}, 100.0)
        assert [a.kind for a in acts] == ["scale_up"]
        assert acts[0].params["replicas"] == 2
        assert acts[0].signal["trigger"] == "slo"

    def test_scale_up_on_queue_pressure(self):
        # queue >= scale_up_queue * replicas trips without any SLO
        acts = decide(ModelSensors("m", replicas=2, queue_depth=8),
                      FleetPolicy(**_POL), {}, 100.0)
        assert [a.kind for a in acts] == ["scale_up"]
        assert acts[0].params["replicas"] == 3
        assert acts[0].signal["trigger"] == "queue"
        # one short of the threshold: no action
        assert decide(ModelSensors("m", replicas=2, queue_depth=7),
                      FleetPolicy(**_POL), {}, 100.0) == []

    def test_scale_up_respects_max(self):
        acts = decide(ModelSensors("m", replicas=3, slo_state="breach"),
                      FleetPolicy(**_POL), {}, 100.0)
        assert acts == []

    def test_scale_down_on_idle(self):
        acts = decide(ModelSensors("m", replicas=2, idle_s=3.0),
                      FleetPolicy(**_POL), {}, 100.0)
        assert [a.kind for a in acts] == ["scale_down"]
        assert acts[0].params["replicas"] == 1
        # min_replicas floors the shrink
        assert decide(ModelSensors("m", replicas=1, idle_s=3.0),
                      FleetPolicy(**dict(_POL, page_ttl_s=0.0)),
                      {}, 100.0) == []

    def test_page_on_ttl_supersedes_scale_down(self):
        acts = decide(ModelSensors("m", replicas=2, idle_s=6.0),
                      FleetPolicy(**_POL), {}, 100.0)
        assert [a.kind for a in acts] == ["page_out"]
        assert acts[0].signal["trigger"] == "idle_ttl"

    def test_page_ttl_zero_never_pages(self):
        pol = FleetPolicy(**dict(_POL, page_ttl_s=0.0,
                                 scale_down_idle_s=0.5))
        acts = decide(ModelSensors("m", replicas=1, idle_s=1e6),
                      pol, {}, 100.0)
        assert acts == []

    def test_degrade_before_shed_ordering(self):
        """Under breach with a quantized peer, the FIRST action is the
        ab-weight shift toward int8 — the cheap capacity engages
        before a new replica set is built (and before admission would
        shed)."""
        acts = decide(ModelSensors("m", replicas=1, slo_state="breach",
                                   has_int8_peer=True),
                      FleetPolicy(**_POL), {}, 100.0)
        assert [a.kind for a in acts] == ["degrade", "scale_up"]
        assert acts[0].params["weight"] == 0.9
        assert acts[0].signal["trigger"] == "sustained_burn"

    def test_no_degrade_without_int8_peer(self):
        acts = decide(ModelSensors("m", replicas=1, slo_state="breach"),
                      FleetPolicy(**_POL), {}, 100.0)
        assert [a.kind for a in acts] == ["scale_up"]

    def test_restore_needs_clean_streak(self):
        pol = FleetPolicy(**_POL)
        st = {"degraded": True, "saved_ab": {"int8": 0.1}}
        # still burning: anything but a restore (the scale-up half of
        # the response is free to proceed)
        kinds = [a.kind for a in
                 decide(ModelSensors("m", slo_state="breach",
                                     has_int8_peer=True,
                                     ab={"int8": 0.9}),
                        pol, dict(st, clean_streak=0), 100.0)]
        assert "restore" not in kinds
        # clean but under the hysteresis streak: no restore
        assert decide(ModelSensors("m", slo_state="ok",
                                   has_int8_peer=True,
                                   ab={"int8": 0.9}),
                      pol, dict(st, clean_streak=2), 100.0) == []
        acts = decide(ModelSensors("m", slo_state="ok",
                                   has_int8_peer=True,
                                   ab={"int8": 0.9}),
                      pol, dict(st, clean_streak=3), 100.0)
        assert [a.kind for a in acts] == ["restore"]
        assert acts[0].params["ab"] == {"int8": 0.1}

    def test_cooldown_suppression(self):
        pol = FleetPolicy(**dict(_POL, scale_cooldown_s=15.0,
                                 page_cooldown_s=30.0))
        s_up = ModelSensors("m", replicas=1, slo_state="breach")
        assert decide(s_up, pol, {"last_scale_t": 90.0}, 100.0) == []
        assert [a.kind for a in decide(s_up, pol,
                                       {"last_scale_t": 80.0},
                                       100.0)] == ["scale_up"]
        s_page = ModelSensors("m", replicas=1, idle_s=6.0)
        assert decide(s_page, pol, {"last_page_t": 80.0}, 100.0) == []

    def test_paged_model_faults_in_on_demand_only(self):
        pol = FleetPolicy(**_POL)
        idle = ModelSensors("m", paged=True)
        assert decide(idle, pol, {}, 100.0) == []
        for kw in ({"requests_delta": 2}, {"shed_delta": 1},
                   {"slo_state": "breach"}):
            acts = decide(ModelSensors("m", paged=True, **kw),
                          pol, {}, 100.0)
            assert [a.kind for a in acts] == ["fault_in"], kw

    def test_no_policy_no_actions(self):
        assert decide(ModelSensors("m", slo_state="breach"),
                      None, {}, 100.0) == []


# ---------------------------------------------------------------------------
# unload-to-spec + fault-in (the bugfix satellite): round trip bit-exact
# ---------------------------------------------------------------------------

class TestUnloadFaultInRoundTrip:
    def test_unload_persists_spec_and_fault_in_rebuilds_lanes(self,
                                                             fc_dir):
        reg = ModelRegistry(metrics=ServingMetrics())
        try:
            reg.load_model("m", fc_dir, buckets=[2])
            reg.load_model("m", fc_dir, buckets=[2], precision="int8",
                           ab_weight=0.25)
            ref_fp = reg.infer("m", {"x": X}, precision="fp32")
            ref_i8 = reg.infer("m", {"x": X}, precision="int8")
            d0 = reg.describe()["m"]
            reg.unload_model("m")
            # unloaded = gone: traffic must NOT resurrect it
            with pytest.raises(KeyError):
                reg.infer("m", {"x": X})
            assert "m" not in reg.paged_models()
            # ... but the spec survived: fault_in rebuilds the EXACT
            # lane set — precisions, buckets, ab split — bit-exactly
            reg.fault_in("m", trigger="manual")
            d1 = reg.describe()["m"]
            assert d1["precisions"].keys() == d0["precisions"].keys()
            assert d1["ab_weights"] == d0["ab_weights"] == {
                "int8": 0.25}
            assert d1["buckets"] == d0["buckets"]
            out_fp = reg.infer("m", {"x": X}, precision="fp32")
            out_i8 = reg.infer("m", {"x": X}, precision="int8")
            assert np.array_equal(out_fp[0], ref_fp[0])
            assert np.array_equal(out_i8[0], ref_i8[0])
        finally:
            reg.close_all(drain=False)

    def test_paged_model_faults_in_on_request(self, fc_dir):
        reg = ModelRegistry(metrics=ServingMetrics())
        try:
            reg.load_model("m", fc_dir, buckets=[2])
            ref = reg.infer("m", {"x": X})
            reqs_before = reg.metrics.model("m").requests.value
            reg.page_out("m")
            assert reg.paged_models()["m"]["lanes"] == 1
            assert reg.describe()["m"]["paged"]
            # the next request faults the model back in transparently
            out = reg.infer("m", {"x": X})
            assert np.array_equal(out[0], ref[0])
            assert "m" not in reg.paged_models()
            fi = reg.last_fault_in["m"]
            assert fi["trigger"] == "request" and fi["ms"] > 0
            mm = reg.metrics.model("m")
            # metrics lane SURVIVED the page (counters never reset)
            # and carries the fault-in telemetry
            assert mm.requests.value > reqs_before
            assert mm.fault_ins.value == 1
            assert mm.snapshot()["fault_in_ms"]["count"] == 1
            ev = obs_events.recent_events(kind="fleet_fault_in")
            assert ev and ev[-1]["model"] == "m"
            assert ev[-1]["fault_in_ms"] == fi["ms"]
            assert obs_events.recent_events(kind="fleet_paged_out")
        finally:
            reg.close_all(drain=False)

    def test_decode_spec_round_trip(self):
        """A decode artifact's spec (slots, kv dtype) survives the
        page/fault cycle — greedy streams bit-exact."""
        from paddle_tpu.inference.decode import build_tiny_decode_model
        md = os.path.join(tempfile.mkdtemp(prefix="fleet_dec_"), "d")
        build_tiny_decode_model(md, seed=7)
        reg = ModelRegistry(metrics=ServingMetrics())
        try:
            reg.load_model("d", md, decode_slots=2)
            prompt = [3, 1, 4]
            ref = reg.infer("d", {"tokens": prompt})
            reg.page_out("d")
            out = reg.infer("d", {"tokens": prompt})
            assert np.array_equal(out[0], ref[0])
            d = reg.describe()["d"]
            assert d["decode"] and d["decode_slots"] == 2
        finally:
            reg.close_all(drain=False)


# ---------------------------------------------------------------------------
# resize: hot-swap discipline + the fit gate on growth
# ---------------------------------------------------------------------------

class TestResize:
    def test_resize_up_down_bit_exact(self, fc_dir):
        reg = ModelRegistry(metrics=ServingMetrics())
        try:
            reg.load_model("m", fc_dir, buckets=[2])
            ref = reg.infer("m", {"x": X})
            e2 = reg.resize_model("m", 2)
            assert len(e2.replicas) == 2
            assert np.array_equal(reg.infer("m", {"x": X})[0], ref[0])
            ups = obs_events.recent_events(kind="fleet_scale_up")
            assert ups[-1]["from_replicas"] == 1
            assert ups[-1]["to_replicas"] == 2
            e1 = reg.resize_model("m", 1)
            assert len(e1.replicas) == 1
            assert np.array_equal(reg.infer("m", {"x": X})[0], ref[0])
            assert obs_events.recent_events(kind="fleet_scale_down")
            # no-op resize returns the live entry untouched
            assert reg.resize_model("m", 1) is e1
        finally:
            reg.close_all(drain=False)

    def test_fit_check_gates_growth(self, fc_big_dir):
        from paddle_tpu.analysis import ResourceFitError
        reg = ModelRegistry(metrics=ServingMetrics())
        xb = np.zeros((1, 256), np.float32)
        try:
            reg.load_model("m", fc_big_dir, buckets=[2])
            ref = reg.infer("m", {"x": xb})
            # a 1 MiB budget cannot hold the ~1.5 MiB replica set: the
            # grow must be REJECTED before any build work, with the
            # live single-replica set untouched
            set_flags({"serving_device_mem_mb": 1})
            with pytest.raises(ResourceFitError):
                reg.resize_model("m", 2)
            set_flags({"serving_device_mem_mb": 0})
            assert len(reg._entry_locked("m", None).replicas) == 1
            assert np.array_equal(reg.infer("m", {"x": xb})[0], ref[0])
        finally:
            set_flags({"serving_device_mem_mb": 0})
            reg.close_all(drain=False)


# ---------------------------------------------------------------------------
# the live controller: tick-driven actuation, hysteresis, dry-run
# ---------------------------------------------------------------------------

class _FakeSLO:
    """Stands in for SLOMonitor: state() returns whatever the test
    scripts — the controller only reads state/burn."""

    def __init__(self):
        self.states = {}

    def state(self):
        return {k: {"state": v, "monitored": True,
                    "burn": {"p95_ms": {"fast": 12.0, "slow": None}}}
                for k, v in self.states.items()}


def _mk_controller(reg, slo=None, **policy):
    ctl = FleetController(reg, reg.metrics, slo=slo, interval_s=999.0)
    if policy:
        ctl.set_policy("m", **policy)
    return ctl


class TestControllerLive:
    def test_queue_pressure_scales_up_with_cooldown(self, fc_dir):
        from paddle_tpu.serving import set_dispatch_delay
        reg = ModelRegistry(metrics=ServingMetrics(), max_queue=64)
        ctl = _mk_controller(reg, max_replicas=2, scale_up_queue=2,
                             scale_cooldown_s=3600.0)
        try:
            reg.load_model("m", fc_dir, buckets=[1])
            set_dispatch_delay(0.2)
            futs = [reg.submit("m", {"x": X}) for _ in range(6)]
            out = ctl.tick()
            assert [a.kind for a, err in out] == ["scale_up"]
            assert out[0][1] is None, out
            assert len(reg._entry_locked("m", None).replicas) == 2
            # signal rides the event: which sensor pulled the trigger
            ev = obs_events.recent_events(kind="fleet_scale_up")[-1]
            assert ev["trigger"] == "queue"
            assert ev["queue_depth"] >= 2
            # the cooldown rate-limits: an immediate second tick under
            # the same pressure decides NOTHING
            assert ctl.tick() == []
            set_dispatch_delay(0.0)
            for f in futs:
                f.result(timeout=30)
        finally:
            set_dispatch_delay(0.0)
            reg.close_all(drain=False)

    def test_degrade_then_restore_with_hysteresis(self, fc_dir):
        reg = ModelRegistry(metrics=ServingMetrics())
        slo = _FakeSLO()
        ctl = _mk_controller(reg, slo=slo, max_replicas=1,
                             degrade_weight=0.8, restore_evals=2,
                             degrade_cooldown_s=0.0)
        try:
            reg.load_model("m", fc_dir, buckets=[2])
            reg.load_model("m", fc_dir, buckets=[2], precision="int8")
            slo.states["m"] = "breach"
            out = ctl.tick()
            kinds = [a.kind for a, _ in out]
            assert kinds == ["degrade"], out
            assert reg.describe()["m"]["ab_weights"] == {"int8": 0.8}
            assert obs_events.recent_events(kind="fleet_degraded")
            # recovery: the weight must NOT flap back on the first
            # clean tick (restore_evals=2 hysteresis)
            slo.states["m"] = "ok"
            assert ctl.tick() == []
            out = ctl.tick()
            assert [a.kind for a, _ in out] == ["restore"]
            assert not reg.describe()["m"].get("ab_weights")
            assert obs_events.recent_events(kind="fleet_restored")
        finally:
            reg.close_all(drain=False)

    def test_dry_run_decides_without_acting(self, fc_dir):
        reg = ModelRegistry(metrics=ServingMetrics())
        ctl = _mk_controller(reg, page_ttl_s=0.01, page_cooldown_s=0.0)
        ctl.dry_run = True
        try:
            reg.load_model("m", fc_dir, buckets=[2])
            ctl.tick()
            time.sleep(0.05)  # idle past the TTL
            out = ctl.tick()
            assert out and all(err == "dry_run" for _, err in out)
            # decisions are EVENTED ...
            ev = obs_events.recent_events(kind="fleet_decision")
            assert ev and ev[-1]["action"] == "page_out"
            assert ev[-1]["dry_run"] is True
            # ... but NOTHING acted: still resident, not paged
            assert not reg.paged_models()
            assert not reg.describe()["m"].get("paged")
            assert not obs_events.recent_events(kind="fleet_paged_out")
            # flipping dry_run off: the same decision now actuates
            ctl.dry_run = False
            out = ctl.tick()
            assert [a.kind for a, err in out] == ["page_out"]
            assert reg.paged_models()
        finally:
            reg.close_all(drain=False)

    def test_fit_rejected_grow_events_and_cools_down(self, fc_big_dir):
        reg = ModelRegistry(metrics=ServingMetrics(), max_queue=64)
        slo = _FakeSLO()
        ctl = _mk_controller(reg, slo=slo, max_replicas=4,
                             scale_cooldown_s=3600.0)
        try:
            reg.load_model("m", fc_big_dir, buckets=[2])
            slo.states["m"] = "breach"
            set_flags({"serving_device_mem_mb": 1})
            out = ctl.tick()
            assert len(out) == 1 and "fit_rejected" in out[0][1]
            ev = obs_events.recent_events(kind="fleet_scale_rejected")
            assert ev and ev[-1]["model"] == "m"
            # registry untouched, cooldown stamped (no hammering)
            assert len(reg._entry_locked("m", None).replicas) == 1
            set_flags({"serving_device_mem_mb": 0})
            assert ctl.tick() == []
        finally:
            set_flags({"serving_device_mem_mb": 0})
            reg.close_all(drain=False)


# ---------------------------------------------------------------------------
# wire + tools: fleet RPC, policy fields, serving_top, Prometheus
# ---------------------------------------------------------------------------

class TestWireAndTools:
    def test_fleet_rpc_policy_and_surfaces(self, fc_dir):
        set_flags({"fleet_controller": True,
                   "fleet_eval_interval_ms": 50.0})
        server = InferenceServer(max_queue=32).start()
        cli = ServingClient(server.endpoint)
        try:
            cli.load_model("m", fc_dir, buckets=[2],
                           fleet_policy="max_replicas=2,page_ttl_s=600")
            cli.infer("m", {"x": X}, deadline_ms=10000)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                st = cli.fleet()
                if st["models"].get("m"):
                    break
                time.sleep(0.05)
            assert st["enabled"] and st["running"]
            assert st["policies"]["m"]["max_replicas"] == 2
            m = st["models"]["m"]
            assert m["state"] == FLEET_ACTIVE
            assert m["replicas"] == 1 and m["paged"] is False
            # set_fleet_policy updates the declared envelope
            cli.set_fleet_policy("m", "min_replicas=1,max_replicas=3")
            assert cli.fleet()["policies"]["m"]["max_replicas"] == 3
            # dry-run flips over the wire
            assert cli.fleet(dry_run=True)["dry_run"] is True
            assert cli.fleet(dry_run=False)["dry_run"] is False
            # health carries the controller readout too
            assert cli.health()["fleet"]["enabled"]
            # Prometheus families (obs/registry.py render)
            text = cli.metrics_text()
            assert 'paddle_tpu_fleet_replicas{model="m"} 1' in text
            assert 'paddle_tpu_fleet_state{model="m"} 0' in text
            # serving_top: REPL/FLEET columns + the --json fleet key
            reply = cli.stats()
            out = serving_top.render(reply, health=cli.health(),
                                     fleet=cli.fleet())
            hdr = out.splitlines()[2]
            assert "REPL" in hdr and "FLEET" in hdr
            row = [l for l in out.splitlines()
                   if l.startswith("m ")][0]
            assert " act" in row
            # page it server-side: the row flips to PAGED, 0 replicas
            server.registry.page_out("m")
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                fst = cli.fleet()  # next controller tick sees the page
                if (fst["models"].get("m") or {}).get("paged"):
                    break
                time.sleep(0.05)
            assert fst["models"]["m"]["state"] == FLEET_PAGED
            out = serving_top.render(cli.stats(), health=cli.health(),
                                     fleet=fst)
            row = [l for l in out.splitlines()
                   if l.startswith("m ")][0]
            assert "PAGED" in row
            text = cli.metrics_text()
            assert 'paddle_tpu_fleet_replicas{model="m"} 0' in text
            assert 'paddle_tpu_fleet_state{model="m"} 2' in text
        finally:
            cli.close()
            server.shutdown(drain=False, timeout=5.0)

    def test_serving_top_json_fleet_key(self, fc_dir, capsys):
        set_flags({"fleet_controller": True,
                   "fleet_eval_interval_ms": 50.0})
        server = InferenceServer(max_queue=32).start()
        try:
            boot = ServingClient(server.endpoint)
            boot.load_model("m", fc_dir, buckets=[2])
            boot.close()
            assert serving_top.main([server.endpoint, "--json"]) == 0
            blob = json.loads(capsys.readouterr().out)
            # sibling keys: pinned stats schema untouched
            assert "stats" in blob and "health" in blob
            assert blob["fleet"]["enabled"] is True
            assert "policies" in blob["fleet"]
        finally:
            server.shutdown(drain=False, timeout=5.0)

    def test_fleet_policy_rejected_without_controller(self, fc_dir):
        server = InferenceServer(max_queue=32).start()  # fleet off
        cli = ServingClient(server.endpoint)
        try:
            st = cli.fleet()
            assert st == {"enabled": False}
            with pytest.raises(ServingError, match="disabled"):
                cli.load_model("m", fc_dir, buckets=[2],
                               fleet_policy="max_replicas=2")
            with pytest.raises(ServingError, match="disabled"):
                cli.set_fleet_policy("m", "max_replicas=2")
            # the typed rejection left nothing half-loaded
            assert "m" not in server.registry.model_names()
        finally:
            cli.close()
            server.shutdown(drain=False, timeout=5.0)
