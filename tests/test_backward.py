"""append_backward / calc_gradient tests (reference unittests/test_backward.py,
test_calc_gradient.py): program structure + analytic-vs-numeric values."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid.backward import append_backward, calc_gradient


def test_append_backward_creates_grads():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(input=x, size=3)
        loss = fluid.layers.mean(y)
        p_g = append_backward(loss)
    names = {p.name for p, g in p_g}
    params = {p.name for p in main.global_block().all_parameters()}
    assert names == params
    for p, g in p_g:
        assert g.name == p.name + "@GRAD"
    types = [op.type for op in main.global_block().ops]
    assert "mean_grad" in types and "mul_grad" in types


def test_grad_values_linear():
    """loss = mean(x @ w + b); dloss/dw = x^T . 1/N, dloss/db = 1"""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(input=x, size=3,
                            param_attr=fluid.ParamAttr(name="w"),
                            bias_attr=fluid.ParamAttr(name="b"))
        loss = fluid.layers.mean(y)
        p_g = append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.random.RandomState(0).randn(6, 4).astype("float32")
    grads = {p.name: g for p, g in p_g}
    gw, gb = exe.run(main, feed={"x": xv},
                     fetch_list=[grads["w"], grads["b"]])
    expect_gw = np.repeat(xv.mean(axis=0).reshape(4, 1) / 3.0, 3, axis=1)
    np.testing.assert_allclose(gw, expect_gw, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(gb, np.full(3, 1.0 / 3.0), atol=1e-5)


def test_fanin_accumulation():
    """x used twice -> grads from both paths must sum."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        blk = main.global_block()
        x = blk.create_var(name="x", shape=[3], dtype="float32")
        a = fluid.layers.scale(x, scale=2.0)
        b = fluid.layers.scale(x, scale=3.0)
        s = fluid.layers.elementwise_add(a, b)
        loss = fluid.layers.reduce_sum(s)
        (gx,) = calc_gradient(loss, [x])
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones(3, dtype="float32")
    (g,) = exe.run(main, feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(g, np.full(3, 5.0), atol=1e-6)


def test_stop_gradient():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h1 = fluid.layers.fc(input=x, size=4,
                             param_attr=fluid.ParamAttr(name="w1"))
        h1.stop_gradient = True
        h2 = fluid.layers.fc(input=h1, size=2,
                             param_attr=fluid.ParamAttr(name="w2"))
        loss = fluid.layers.mean(h2)
        p_g = append_backward(loss)
    names = {p.name for p, g in p_g}
    assert "w2" in names
    assert "w1" not in names


def test_calc_gradient_chain():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        blk = main.global_block()
        x = blk.create_var(name="x", shape=[5], dtype="float32")
        y = fluid.layers.square(x)
        z = fluid.layers.reduce_sum(y)
        (gx,) = calc_gradient(z, [x])
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.arange(5, dtype="float32")
    (g,) = exe.run(main, feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(g, 2 * xv, atol=1e-6)
