"""Flag registry tests (reference config surface: gflags DEFINE_* +
python/paddle/fluid/__init__.py:114-134 read_env_flags allowlist)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu import flags


def test_set_get_and_types():
    assert fluid.get_flags("check_nan_inf") == {"check_nan_inf": False}
    fluid.set_flags({"check_nan_inf": True})
    assert fluid.FLAGS.check_nan_inf is True
    fluid.set_flags({"check_nan_inf": "0"})      # string coercion
    assert fluid.FLAGS.check_nan_inf is False
    with pytest.raises(KeyError):
        fluid.set_flags({"no_such_flag": 1})
    info = flags.flag_info()
    assert "rpc_deadline" in info and info["rpc_deadline"][0] == "float"


def test_amp_flag_wires_registry():
    from paddle_tpu.ops.registry import amp_enabled
    was = amp_enabled()
    try:
        fluid.set_flags({"use_bf16_amp": True})
        assert amp_enabled()
        fluid.set_flags({"use_bf16_amp": False})
        assert not amp_enabled()
    finally:
        fluid.set_amp(was)


def test_env_ingestion():
    """PADDLE_TPU_FLAGS_* env vars override defaults at import."""
    code = (
        "import paddle_tpu.flags as f; "
        "assert f.FLAGS.check_nan_inf is True, f.FLAGS.check_nan_inf; "
        "assert f.FLAGS.rpc_deadline == 7.5; print('OK')")
    env = dict(os.environ)
    env["PADDLE_TPU_FLAGS_check_nan_inf"] = "true"
    env["FLAGS_rpc_deadline"] = "7.5"           # reference-style name
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))),
                         capture_output=True, text=True)
    assert out.returncode == 0 and "OK" in out.stdout, out.stderr


def test_check_nan_inf_jitted_step():
    """VERDICT r2 task #9: under check_nan_inf an ordinarily-JITTED
    program runs eagerly so the first non-finite op is NAMED (reference
    FLAGS_check_nan_inf re-checks every op output, operator.cc:29, at
    per-op-sync cost — same debugging-mode tradeoff here)."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2], dtype="float32")
        y = fluid.layers.log(x)          # log(-1) -> nan
        loss = fluid.layers.mean(y)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    fluid.set_flags({"check_nan_inf": True})
    try:
        with pytest.raises(FloatingPointError, match="op 'log'"):
            exe.run(main, feed={"x": np.array([[-1.0, 2.0]], np.float32)},
                    fetch_list=[loss])
        # healthy values pass
        (lv,) = exe.run(main,
                        feed={"x": np.array([[1.0, 2.0]], np.float32)},
                        fetch_list=[loss])
        assert np.isfinite(float(np.asarray(lv).flatten()[0]))
    finally:
        fluid.set_flags({"check_nan_inf": False})


def test_check_nan_inf_eager_per_op_attribution():
    """Host-op programs run eagerly: the failing op is named."""
    import tempfile
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2], dtype="float32")
        y = fluid.layers.log(x)
        loss = fluid.layers.mean(y)
        # a save op forces the eager host path
        gb = main.global_block()
        gb.append_op(type="save", inputs={"X": [loss.name]},
                     outputs={},
                     attrs={"file_path": tempfile.mktemp()},
                     infer_shape=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    fluid.set_flags({"check_nan_inf": True})
    try:
        with pytest.raises(FloatingPointError, match="op 'log'"):
            exe.run(main, feed={"x": np.array([[-1.0, 2.0]], np.float32)},
                    fetch_list=[loss])
    finally:
        fluid.set_flags({"check_nan_inf": False})


def test_enable_rpc_profiler_records_events():
    """FLAGS_enable_rpc_profiler (reference profiler.cc:33): RPC calls
    appear as profiler events when the flag is on."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    from paddle_tpu.distributed.rpc import VariableServer, RPCClient
    from paddle_tpu.fluid import profiler

    server = VariableServer("127.0.0.1:0").start()
    try:
        fluid.set_flags({"enable_rpc_profiler": True})
        profiler.reset_profiler()
        client = RPCClient()
        client.put_var(server.endpoint, "w", np.ones(3, np.float32))
        out = client.async_get_var(server.endpoint, "w")
        np.testing.assert_allclose(np.asarray(out), np.ones(3))
        assert any(k.startswith("rpc/")
                   for k in profiler._host_events), \
            list(profiler._host_events)
    finally:
        fluid.set_flags({"enable_rpc_profiler": False})
        server.stop()
