"""Round-4b gserver tail: the remaining reference v1 __all__ names
(tensor/conv_shift/selective_fc/spp/recurrent/lstm_step/lambda_cost/...)
built through v1 spellings and executed, with numpy cross-checks where
the semantics are cheap to restate (reference
python/paddle/trainer_config_helpers/layers.py; legacy/gserver/layers/)."""

import numpy as np

import paddle_tpu.v2 as paddle
from paddle_tpu.trainer_config_helpers import layers as v1


def _run(layer, vals):
    topo = paddle.topology.Topology([layer])
    names = [n for n, _ in topo.data_type()]
    p = paddle.parameters.create(layer)
    return np.asarray(paddle.infer(
        output_layer=layer, parameters=p,
        input=[tuple(vals[n] for n in names)]))


def test_elementwise_tail_cross_checked():
    rng = np.random.RandomState(3)
    xv = rng.randn(8).astype(np.float32)
    x = v1.data_layer(name="xb", size=8)

    got = _run(v1.row_l2_norm_layer(input=x), {"xb": xv})
    np.testing.assert_allclose(got.ravel(), xv / np.linalg.norm(xv),
                               rtol=1e-5)

    # circular correlation vs direct sum
    bv = rng.randn(3).astype(np.float32)
    b = v1.data_layer(name="bb", size=3)
    got = _run(v1.conv_shift_layer(a=x, b=b), {"xb": xv, "bb": bv})
    want = np.zeros(8, np.float32)
    for i in range(8):
        for j in range(-1, 2):
            want[i] += xv[(i + j) % 8] * bv[j + 1]
    np.testing.assert_allclose(got.ravel(), want, rtol=1e-4)


def test_tensor_and_fm_shapes():
    rng = np.random.RandomState(4)
    a = v1.data_layer(name="ta", size=5)
    b = v1.data_layer(name="tb", size=7)
    vals = {"ta": rng.randn(5).astype(np.float32),
            "tb": rng.randn(7).astype(np.float32)}
    got = _run(v1.tensor_layer(a=a, b=b, size=4), vals)
    assert got.ravel().shape == (4,) and np.all(np.isfinite(got))

    got = _run(v1.factorization_machine(input=a, factor_size=3),
               {"ta": vals["ta"]})
    assert got.ravel().shape == (1,) and np.all(np.isfinite(got))


def test_image_tail_shapes():
    rng = np.random.RandomState(5)
    img = v1.data_layer(name="im", size=2 * 4 * 4, height=4, width=4)
    iv = rng.rand(2 * 4 * 4).astype(np.float32)

    got = _run(v1.switch_order_layer(input=img), {"im": iv})
    np.testing.assert_allclose(
        got.ravel(), iv.reshape(2, 4, 4).transpose(1, 2, 0).ravel(),
        rtol=1e-6)

    got = _run(v1.upsample_layer(input=img, scale=2), {"im": iv})
    assert got.ravel().shape == (2 * 8 * 8,)

    # spp over pyramid height 2: 1x1 + 2x2 bins per channel = 5C
    got = _run(v1.spp_layer(input=img, pyramid_height=2), {"im": iv})
    assert got.ravel().shape == (2 * 5,)
    np.testing.assert_allclose(got.ravel()[0],
                               iv.reshape(2, 16)[0].max(), rtol=1e-6)

    # scale the full channel-0 box by 3
    idx = v1.data_layer(name="ix", size=6)
    ixv = np.array([1, 1, 1, 4, 1, 4], np.float32)
    got = _run(v1.scale_sub_region_layer(input=img, indices=idx, value=3.0),
               {"im": iv, "ix": ixv})
    want = iv.reshape(2, 4, 4).copy()
    want[0] *= 3.0
    np.testing.assert_allclose(got.ravel(), want.ravel(), rtol=1e-6)


def test_selective_fc_masks_columns():
    rng = np.random.RandomState(6)
    x = v1.data_layer(name="sx", size=6)
    sel = v1.data_layer(name="ss", size=4)
    sv = np.array([1, 0, 1, 0], np.float32)
    got = _run(v1.selective_fc_layer(input=x, size=4, select=sel,
                                     bias_attr=False),
               {"sx": rng.randn(6).astype(np.float32), "ss": sv})
    assert got.ravel()[1] == 0.0 and got.ravel()[3] == 0.0


def test_kmax_seq_score_and_printer():
    x = v1.data_layer(name="ks", size=6)
    xv = np.array([0.1, 0.9, 0.3, 0.7, 0.2, 0.5], np.float32)
    got = _run(v1.kmax_seq_score_layer(input=x, beam_size=2), {"ks": xv})
    assert set(got.ravel().astype(int)) == {1, 3}

    got = _run(v1.print_layer(input=x), {"ks": xv})
    np.testing.assert_allclose(got.ravel(), xv)


def test_costs_run_and_rank_sensitivity():
    rng = np.random.RandomState(7)

    # modified Huber: correct confident scores cost ~0, wrong ones > 1
    f = v1.data_layer(name="hf", size=1)
    y = v1.data_layer(name="hy", size=1)
    cost = v1.huber_classification_cost(input=f, label=y)
    good = _run(cost, {"hf": np.array([2.0], np.float32),
                       "hy": np.array([1.0], np.float32)})
    bad = _run(cost, {"hf": np.array([-2.0], np.float32),
                      "hy": np.array([1.0], np.float32)})
    assert float(good) == 0.0 and float(bad) >= 4.0

    # selfnorm CE: Z=1 distribution has no selfnorm penalty
    p = v1.data_layer(name="sp", size=3)
    lb = v1.data_layer(name="sl",
                       type=paddle.data_type.integer_value(3))
    cost = v1.cross_entropy_with_selfnorm(input=p, label=lb,
                                          softmax_selfnorm_alpha=10.0)
    z1 = _run(cost, {"sp": np.array([0.2, 0.3, 0.5], np.float32),
                     "sl": np.array([2], np.int64)})
    z4 = _run(cost, {"sp": 4 * np.array([0.2, 0.3, 0.5], np.float32),
                     "sl": np.array([2], np.int64)})
    assert float(z4) > float(z1)

    # lambda_cost: perfectly-ranked scores cost less than inverted ones
    sc = v1.data_layer(name="lsc",
                       type=paddle.data_type.dense_vector_sequence(1))
    rel = v1.data_layer(name="lrl",
                        type=paddle.data_type.dense_vector_sequence(1))
    cost = v1.lambda_cost(input=sc, score=rel, NDCG_num=3)
    rels = np.array([[2.0], [1.0], [0.0]], np.float32)
    good = _run(cost, {"lsc": np.array([[3.], [2.], [1.]], np.float32),
                       "lrl": rels})
    bad = _run(cost, {"lsc": np.array([[1.], [2.], [3.]], np.float32),
                      "lrl": rels})
    assert 0.0 <= float(good) < float(bad)


def test_recurrent_layer_runs_and_respects_lengths():
    rng = np.random.RandomState(8)
    x = v1.data_layer(name="rx",
                      type=paddle.data_type.dense_vector_sequence(4))
    out = v1.recurrent_layer(input=x, bias_attr=False)
    xv = rng.randn(3, 4).astype(np.float32)
    got = _run(out, {"rx": xv})
    assert got.shape[-1] == 4 and np.all(np.isfinite(got))


def test_lstm_step_get_output_and_gru_step_in_group():
    rng = np.random.RandomState(9)
    x = v1.data_layer(name="gx",
                      type=paddle.data_type.dense_vector_sequence(8))

    def lstm_step(inp):
        c_mem = v1.memory(name="c_state", size=2)
        gates = v1.mixed_layer(
            size=8, input=[v1.full_matrix_projection(input=inp)],
            bias_attr=False, name="gate_proj")
        step = v1.lstm_step_layer(input=gates, state=c_mem,
                                  name="the_step")
        cell = v1.get_output_layer(input=step, arg_name="state",
                                   name="c_state")
        return step, cell

    h, _c = v1.recurrent_group(step=lstm_step, input=x)
    last = v1.last_seq(input=h)
    got = _run(last, {"gx": rng.randn(3, 8).astype(np.float32)})
    assert got.ravel().shape == (2,) and np.all(np.isfinite(got))


def test_enums_and_layer_support():
    assert v1.AggregateLevel.TO_NO_SEQUENCE == "non-seq"
    assert v1.ExpandLevel.FROM_NO_SEQUENCE == "non-seq"
    assert v1.LayerType.FC_LAYER == "fc"

    @v1.layer_support("drop_rate")
    def my_layer(x):
        return x

    assert my_layer(5) == 5


def test_spp_non_divisible_input():
    rng = np.random.RandomState(10)
    img = v1.data_layer(name="im5", size=2 * 5 * 5, height=5, width=5)
    iv = rng.rand(2 * 5 * 5).astype(np.float32)
    got = _run(v1.spp_layer(input=img, pyramid_height=2), {"im5": iv})
    assert got.ravel().shape == (2 * 5,)
    np.testing.assert_allclose(got.ravel()[0],
                               iv.reshape(2, 25)[0].max(), rtol=1e-6)


def test_kmax_seq_score_ignores_padding():
    x = v1.data_layer(name="kp",
                      type=paddle.data_type.dense_vector_sequence(1))
    layer = v1.kmax_seq_score_layer(input=x, beam_size=1)
    topo = paddle.topology.Topology([layer])
    p = paddle.parameters.create(layer)
    # batch of 2 ragged sequences: len 2 (all negative) and len 4 — the
    # len-2 row's padded zeros must NOT outrank its real scores
    seqs = [
        (np.array([[-5.0], [-1.0]], np.float32),),
        (np.array([[0.1], [0.9], [0.3], [0.2]], np.float32),),
    ]
    got = np.asarray(paddle.infer(output_layer=layer, parameters=p,
                                  input=seqs))
    assert got.ravel()[0] == 1     # argmax of [-5, -1] within length 2
    assert got.ravel()[1] == 1     # argmax of the len-4 row


def test_recurrent_linear_activation_is_identity():
    x = v1.data_layer(name="rl",
                      type=paddle.data_type.dense_vector_sequence(4))
    out = v1.recurrent_layer(input=x, bias_attr=False,
                             act=paddle.activation.Linear())
    big = 10.0 * np.ones((2, 4), np.float32)
    got = _run(out, {"rl": big})
    # tanh would cap |h| at 1; identity lets x_t pass through
    assert np.abs(got).max() > 1.5


def test_detection_pipeline_builds_and_runs():
    rng = np.random.RandomState(11)
    feat = v1.data_layer(name="df", size=3 * 2 * 2, height=2, width=2)
    img = v1.data_layer(name="di", size=3 * 8 * 8, height=8, width=8)
    pb = v1.priorbox_layer(input=feat, image=img,
                           aspect_ratio=[2.0], variance=[0.1] * 4,
                           min_size=[4.0], max_size=[6.0])
    n_priors_per_cell = 4        # 1 min + 1 max + 2 aspect flips
    n_priors = 2 * 2 * n_priors_per_cell
    loc = v1.data_layer(name="dl", size=n_priors * 4)
    conf = v1.data_layer(name="dc", size=n_priors * 2)
    det = v1.detection_output_layer(
        input_loc=loc, input_conf=conf, priorbox=pb, num_classes=2,
        confidence_threshold=0.0)
    vals = {"df": rng.rand(3 * 2 * 2).astype(np.float32),
            "di": rng.rand(3 * 8 * 8).astype(np.float32),
            "dl": 0.1 * rng.randn(n_priors * 4).astype(np.float32),
            "dc": rng.randn(n_priors * 2).astype(np.float32)}
    got = _run(det, vals)
    # [N, 6] detections: label, score in (0,1] once (no double softmax
    # squashing everything toward 0.5), xmin/ymin/xmax/ymax
    assert got.shape[-1] == 6
    scores = got[..., 1].ravel()
    assert np.all((scores > 0) & (scores <= 1.0))


def test_conv3d_pool3d_shapes():
    rng = np.random.RandomState(12)
    # NCDHW volume [2, 4, 4, 4] fed flat through depth/height/width
    vol = v1.data_layer(name="v3", size=2 * 4 * 4 * 4,
                        depth=4, height=4, width=4)
    conv = v1.img_conv3d_layer(input=vol, filter_size=3, num_filters=3,
                               padding=1, bias_attr=False)
    pool = v1.img_pool3d_layer(input=conv, pool_size=2, stride=2)
    iv = rng.rand(2 * 4 * 4 * 4).astype(np.float32)
    got = _run(pool, {"v3": iv})
    assert got.ravel().shape == (3 * 2 * 2 * 2,)
    assert np.all(np.isfinite(got))


def test_beam_search_generates_ranked_hypotheses():
    """v1 beam_search drives a user step (memory + gru_step + softmax)
    over an unrolled beam frontier and emits ranked hypotheses."""
    vocab, emb, hid, W, maxlen = 10, 6, 8, 3, 4

    enc = v1.data_layer(name="enc_ctx", size=hid)

    def step(word_emb, enc_ctx):
        mem = v1.memory(name="dec_state", size=hid, boot_layer=enc_ctx)
        gates = v1.mixed_layer(
            size=hid * 3,
            input=[v1.full_matrix_projection(input=word_emb),
                   v1.full_matrix_projection(input=enc_ctx)],
            bias_attr=False)
        nxt = v1.gru_step_layer(input=gates, output_mem=mem,
                                name="dec_state")
        probs = v1.fc_layer(input=nxt, size=vocab,
                            act=paddle.activation.Softmax())
        return probs

    gen = v1.beam_search(
        step=step,
        input=[v1.GeneratedInput(size=vocab, embedding_name="gen_emb",
                                 embedding_size=emb),
               v1.StaticInput(input=enc)],
        bos_id=0, eos_id=1, beam_size=W, max_length=maxlen)

    rng = np.random.RandomState(13)
    p = paddle.parameters.create(gen)
    got = paddle.infer(output_layer=gen, parameters=p,
                       input=[(rng.randn(hid).astype(np.float32),),
                              (rng.randn(hid).astype(np.float32),)])
    ids = np.asarray(got).ravel()
    # 2 sources x W beams, each hypothesis 1..maxlen tokens of the vocab
    assert ids.size >= 2 * W and np.all((ids >= 0) & (ids < vocab))


def test_cross_entropy_over_beam_prefers_gold_on_beam():
    scores = v1.data_layer(name="cs", size=4)
    cand = v1.data_layer(name="cc", size=4)
    gold = v1.data_layer(name="cg", size=1)
    cost = v1.cross_entropy_over_beam(
        input=[v1.BeamInput(candidate_scores=scores,
                            selected_candidates=cand, gold=gold)])
    cand_v = np.array([3, 7, 5, 2], np.float32)
    on = _run(cost, {"cs": np.array([4.0, 1.0, 1.0, 1.0], np.float32),
                     "cc": cand_v, "cg": np.array([3.0], np.float32)})
    off = _run(cost, {"cs": np.array([4.0, 1.0, 1.0, 1.0], np.float32),
                      "cc": cand_v, "cg": np.array([9.0], np.float32)})
    # gold=3 is candidate 0 (high score) -> small loss; gold=9 fell off
    # the beam -> floor-probability loss
    assert float(on) < 1.0 < float(off)


def test_beam_search_binds_generated_input_in_place():
    """GeneratedInput after StaticInput binds the word embedding to the
    SECOND step argument (v1 substitutes it positionally)."""
    vocab, emb, hid, W = 8, 4, 4, 2
    enc = v1.data_layer(name="enc2", size=hid)

    def step(enc_ctx, word_emb):
        # enc_ctx must be the encoder context (hid), word_emb the
        # embedding (emb) — a swap would flip these widths
        mem = v1.memory(name="st2", size=hid)
        gates = v1.mixed_layer(
            size=hid * 3,
            input=[v1.full_matrix_projection(input=word_emb),
                   v1.full_matrix_projection(input=enc_ctx)],
            bias_attr=False)
        nxt = v1.gru_step_layer(input=gates, output_mem=mem, name="st2")
        return v1.fc_layer(input=nxt, size=vocab,
                           act=paddle.activation.Softmax())

    gen = v1.beam_search(
        step=step,
        input=[v1.StaticInput(input=enc),
               v1.GeneratedInput(size=vocab, embedding_name="e2",
                                 embedding_size=emb)],
        bos_id=0, eos_id=1, beam_size=W, max_length=3)
    rng = np.random.RandomState(14)
    p = paddle.parameters.create(gen)
    # the trg embedding table must exist with the declared shape — a
    # swapped binding would build it against the encoder width
    assert tuple(p.get_shape("e2")) == (vocab, emb)
    got = paddle.infer(output_layer=gen, parameters=p,
                       input=[(rng.randn(hid).astype(np.float32),)])
    ids = np.asarray(got).ravel()
    assert ids.size >= W and np.all((ids >= 0) & (ids < vocab))


def test_kmax_seq_score_fills_unfilled_slots_with_minus_one():
    # reference KmaxSeqScoreLayer: output is always [B, beam_size]
    # pre-filled with -1; a sequence shorter than the beam must NOT
    # surface padding-position indices in the tail slots
    x = v1.data_layer(name="km1",
                      type=paddle.data_type.dense_vector_sequence(1))
    layer = v1.kmax_seq_score_layer(input=x, beam_size=3)
    topo = paddle.topology.Topology([layer])
    p = paddle.parameters.create(layer)
    seqs = [
        (np.array([[0.2], [0.8]], np.float32),),       # len 2 < beam 3
        (np.array([[0.1], [0.9], [0.3], [0.4]], np.float32),),
    ]
    got = np.asarray(paddle.infer(output_layer=layer, parameters=p,
                                  input=seqs)).reshape(2, 3).astype(int)
    assert got[0].tolist() == [1, 0, -1], got
    assert got[1].tolist() == [1, 3, 2], got


def test_beam_search_lstm_decoder_cell_state_advances():
    """An LSTM decoder's cell memory links to a get_output SIDE layer
    that is unreachable from the step's output — beam_search must still
    update it every timestep (frozen-at-zero cell state regression)."""
    import paddle_tpu.v2.networks as networks
    vocab, hid, W, maxlen = 8, 4, 2, 3
    emb = 4 * hid       # lstmemory_unit identity-projects the input
    enc = v1.data_layer(name="enc_l", size=hid)

    def step(word_emb, enc_ctx):
        h = networks.lstmemory_unit(input=word_emb, name="dec_lstm",
                                    size=hid)
        return v1.fc_layer(input=h, size=vocab,
                           act=paddle.activation.Softmax())

    gen = v1.beam_search(
        step=step,
        input=[v1.GeneratedInput(size=vocab, embedding_name="lemb",
                                 embedding_size=emb),
               v1.StaticInput(input=enc)],
        bos_id=0, eos_id=1, beam_size=W, max_length=maxlen)

    topo = paddle.topology.Topology([gen])
    ops = topo.main_program.global_block().ops
    lstm_ops = [op for op in ops if op.type == "lstm_unit"]
    assert len(lstm_ops) == maxlen, len(lstm_ops)
    c_prevs = [op.inputs["C_prev"][0] for op in lstm_ops]
    # frozen-state bug: every timestep read the SAME zeros var; the
    # fixed path threads each step's C output (beam-gathered) forward
    assert len(set(c_prevs)) == maxlen, c_prevs

    rng = np.random.RandomState(15)
    p = paddle.parameters.create(gen)
    got = paddle.infer(output_layer=gen, parameters=p,
                       input=[(rng.randn(hid).astype(np.float32),)])
    ids = np.asarray(got).ravel()
    assert ids.size >= W and np.all((ids >= 0) & (ids < vocab))
