"""Numeric-gradient audit across the op corpus (SURVEY §4: the
reference's OpTest check_grad is the workhorse — analytic gradients vs
central finite differences). One parametrized sweep covers a
representative op per family through the PUBLIC layers API, so the
generic-vjp autodiff path is validated per family, not just on the
handful of ops with dedicated OpTest subclasses."""

import zlib

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import backward as backward_mod
from paddle_tpu.fluid.framework import Program

F = fluid.layers


def _audit(build, shapes, delta=1e-3, atol=5e-3, rtol=5e-3, seed=0,
           positive=False, check=None):
    """build(*vars) -> output var. Compares calc_gradient of
    sum(output) against central finite differences for every input in
    `check` (default: all — a None analytic grad is a failure)."""
    rng = np.random.RandomState(seed)
    feed = {}
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        in_vars = []
        for i, shape in enumerate(shapes):
            name = "gx%d" % i
            v = F.data(name=name, shape=list(shape[1:]), dtype="float32")
            v.stop_gradient = False     # F.data defaults to True
            arr = rng.randn(*shape).astype(np.float32)
            if positive:
                arr = np.abs(arr) + 0.5
            feed[name] = arr
            in_vars.append(v)
        out = build(*in_vars)
        target = F.reduce_sum(out)
        check_idx = list(range(len(in_vars))) if check is None \
            else list(check)
        grads = backward_mod.calc_gradient(
            target, [in_vars[i] for i in check_idx])
    assert all(g is not None for g in grads), \
        "input off the grad path — case does not exercise its gradient"
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    analytic = exe.run(main, feed=feed, fetch_list=list(grads))

    def fwd(feed_override):
        f = dict(feed)
        f.update(feed_override)
        r = exe.run(main, feed=f, fetch_list=[target])
        return float(np.asarray(r[0], dtype=np.float64).sum())

    for i, g in zip(check_idx, analytic):
        name = "gx%d" % i
        base = feed[name].astype(np.float64)
        num = np.zeros_like(base)
        for j in range(base.size):
            plus, minus = base.flatten(), base.flatten()
            plus[j] += delta
            minus[j] -= delta
            num.flat[j] = (
                fwd({name: plus.reshape(base.shape).astype(np.float32)})
                - fwd({name: minus.reshape(base.shape).astype(
                    np.float32)})) / (2 * delta)
        np.testing.assert_allclose(
            np.asarray(g, np.float64), num, atol=atol, rtol=rtol,
            err_msg="gradient mismatch for input %d" % i)


CASES = {
    # activations
    "relu": (lambda x: F.relu(x), [(3, 4)]),
    "tanh_stanh": (lambda x: F.stanh(x), [(3, 4)]),
    "leaky_relu": (lambda x: F.leaky_relu(x, alpha=0.1), [(3, 4)]),
    "elu": (lambda x: F.elu(x), [(3, 4)]),
    "selu": (lambda x: F.selu(x), [(3, 4)]),
    "softmax": (lambda x: F.softmax(x), [(3, 5)]),
    "log_pos": (lambda x: F.log(x), [(3, 4)]),
    "sigmoid_xe": (
        lambda x, y: F.sigmoid_cross_entropy_with_logits(
            x, F.sigmoid(y)), [(3, 4), (3, 4)]),
    # elementwise + broadcast
    "elementwise_add_bcast": (
        lambda x, y: F.elementwise_add(x, y, axis=0), [(4, 3), (4, 1)]),
    "elementwise_mul": (
        lambda x, y: F.elementwise_mul(x, y), [(3, 4), (3, 4)]),
    "elementwise_div": (
        lambda x, y: F.elementwise_div(x, F.scale(F.sigmoid(y),
                                                  bias=0.5)),
        [(3, 4), (3, 4)]),
    # matmul family
    "matmul": (lambda x, y: F.matmul(x, y), [(3, 4), (4, 5)]),
    "matmul_trans": (
        lambda x, y: F.matmul(x, y, transpose_y=True), [(3, 4), (5, 4)]),
    "mul": (lambda x, y: F.mul(x, y), [(3, 4), (4, 2)]),
    "bilinear_tensor_product": (
        lambda x, y: F.bilinear_tensor_product(x, y, size=3),
        [(2, 3), (2, 4)]),
    # reductions (distinct values keep max subgradients unique)
    "reduce_mean": (lambda x: F.reduce_mean(x, dim=1), [(3, 4)]),
    "reduce_max": (lambda x: F.reduce_max(x, dim=1), [(3, 4)]),
    # conv / pool
    "conv2d": (
        lambda x: F.conv2d(x, num_filters=2, filter_size=3, padding=1),
        [(1, 2, 4, 4)]),
    "conv2d_transpose": (
        lambda x: F.conv2d_transpose(x, num_filters=2, filter_size=3,
                                     padding=1), [(1, 2, 4, 4)]),
    "conv3d": (
        lambda x: F.conv3d(x, num_filters=2, filter_size=3, padding=1),
        [(1, 1, 3, 3, 3)]),
    "pool2d_avg": (
        lambda x: F.pool2d(x, pool_size=2, pool_type="avg",
                           pool_stride=2), [(1, 2, 4, 4)]),
    "pool2d_max": (
        lambda x: F.pool2d(x, pool_size=2, pool_type="max",
                           pool_stride=2), [(1, 2, 4, 4)]),
    # norm
    "layer_norm": (lambda x: F.layer_norm(x), [(3, 4)]),
    "l2_normalize": (lambda x: F.l2_normalize(x, axis=-1), [(3, 4)]),
    "lrn": (lambda x: F.lrn(x, n=3), [(1, 4, 3, 3)]),
    # losses
    "cross_entropy": (
        lambda x, y: F.cross_entropy(
            F.softmax(x), F.softmax(y), soft_label=True),
        [(3, 4), (3, 4)]),
    "smooth_l1": (lambda x, y: F.smooth_l1(x, y), [(3, 4), (3, 4)]),
    "huber_loss": (
        lambda x, y: F.huber_loss(x, y, delta=1.0), [(3, 1), (3, 1)]),
    "log_loss": (
        lambda x, y: F.log_loss(F.sigmoid(x), F.sigmoid(y)),
        [(3, 1), (3, 1)]),
    "hinge_loss": (
        lambda x, y: F.hinge_loss(x, F.cast(
            F.less_than(y, F.scale(y, scale=0.0)), "float32")),
        [(3, 1), (3, 1)], (0,)),     # the 0/1 label is non-differentiable
    # shape manipulation
    "transpose": (lambda x: F.transpose(x, perm=[1, 0]), [(3, 4)]),
    "reshape_slice": (
        lambda x: F.slice(F.reshape(x, shape=[2, 6]), axes=[1],
                          starts=[1], ends=[5]), [(3, 4)]),
    "concat": (lambda x, y: F.concat([x, y], axis=1),
               [(3, 2), (3, 3)]),
    "pad": (lambda x: F.pad(x, paddings=[0, 0, 1, 2]), [(3, 4)]),
    "gather": (
        lambda x: F.gather(x, F.cast(F.argmax(x, axis=1), "int64")),
        [(3, 4)]),
    "expand": (lambda x: F.expand(x, expand_times=[2, 1]), [(2, 3)]),
    "maxout": (lambda x: F.maxout(x, groups=2), [(1, 4, 3, 3)]),
    # sequence (dense full-length path)
    "sequence_softmax": (lambda x: F.sequence_softmax(x), [(2, 3, 1)]),
    "row_conv": (lambda x: F.row_conv(x, future_context_size=2),
                 [(2, 3, 4)]),
    "im2sequence": (
        lambda x: F.im2sequence(x, filter_size=2, stride=2),
        [(1, 1, 4, 4)]),
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_numeric_gradient(name):
    case = CASES[name]
    build, shapes = case[0], case[1]
    check = case[2] if len(case) > 2 else None
    _audit(build, shapes, check=check,
           positive=name in ("log_pos",),
           seed=zlib.crc32(name.encode()) % 1000)
