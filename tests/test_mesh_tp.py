"""Tensor-parallel mesh compute tests (SERVING.md "Tensor-parallel
compute").

With FLAGS.mesh_tp, a mesh replica stops gathering its sharded params
per step and runs ONE partitioned executable over the member mesh:
fc/mul column->row-parallel pairs closed by a single psum, attention
head-parallel on each member's resident KV shard, long-prompt prefill
sequence-parallel (parallel/ulysses.py).  Pins:

* head-parallel decode attention is EXACT: per-member
  `decode_attention_head_slice` on the resident head block equals the
  full-table kernel, per mesh size 1/2/4, fp32 and int8 (the [2, H]
  scale table windows per member, dequant stays local);
* decode streams are top-1 identical to the single-device oracle AND
  to the gather-mesh lane, across fp32, int8 KV, sequence-parallel
  prefill, fused multi-step, and the speculative twin;
* the documented tolerance point — the psum closing a column->row
  pair reorders one reduction — stays within the pinned bound and
  never moves top-1 on the pinned logits;
* per-member roofline: per_device_step_bytes is total/m only under
  tp (the gather lane still moves every byte through each member);
* the partitioned executable rides the persistent compile cache —
  warm process-equivalent reload is hits:N misses:0, and the mesh
  shape is a fingerprint field (a (2,)-mesh blob never serves a
  (4,) mesh);
* unsupported geometry falls back to the gather lane with a
  RuntimeWarning (never silently wrong), and member loss under TP
  still raises the TYPED MeshMemberLost naming the member.

Everything CPU-safe under JAX_PLATFORMS=cpu + the conftest's 8 forced
host devices.
"""

import numpy as np
import pytest

from paddle_tpu import compile_cache as cc
from paddle_tpu.analysis.resources import analyze_artifact
from paddle_tpu.flags import get_flags, set_flags
from paddle_tpu.inference.decode import (GenerativePredictor,
                                         SpeculativeDecodeSession,
                                         build_tiny_decode_model,
                                         greedy_decode)
from paddle_tpu.ops.pallas_kernels import (decode_attention,
                                           decode_attention_head_slice)
from paddle_tpu.parallel.mesh import (MeshGroup, MeshMemberLost,
                                      set_member_poison, tp_supported)

import jax

PROMPT = [3, 5, 7, 9, 11]
BUDGET = 12

_FLAGS = ["mesh_tp", "mesh_tp_prefill_seq", "serving_decode_fuse_steps",
          "compile_cache_dir"]


@pytest.fixture(autouse=True)
def _tp_flags():
    saved = get_flags(_FLAGS)
    set_flags({"mesh_tp": True})
    yield
    set_flags(saved)
    set_member_poison(None)


def _lm(tmp_path, name="lm", seed=7, **kw):
    """TP-able geometry: every partitioned dim divides by 4, so the
    same artifact exercises m=2 and m=4."""
    kw.setdefault("vocab_size", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("eos_id", -1)
    return build_tiny_decode_model(str(tmp_path / name), seed=seed, **kw)


def _stream(md, device, budget=BUDGET, **kw):
    pred = GenerativePredictor(md, device=device, **kw)
    out, _ = greedy_decode(pred, PROMPT, budget, n_slots=4, slot=1)
    return out, pred


# ---------------------------------------------------------------------------
# head-parallel decode attention: exact per member, per mesh size
# ---------------------------------------------------------------------------

class TestHeadSliceParity:
    N, S, H, D = 3, 16, 4, 8

    def _case(self, rng, dtype=np.float32):
        q = rng.standard_normal((self.N, self.H, self.D)).astype(
            np.float32)
        k = rng.standard_normal((self.N, self.S, self.H, self.D))
        v = rng.standard_normal((self.N, self.S, self.H, self.D))
        if dtype == np.int8:
            k = np.clip(k * 40, -127, 127).astype(np.int8)
            v = np.clip(v * 40, -127, 127).astype(np.int8)
        else:
            k, v = k.astype(dtype), v.astype(dtype)
        lengths = np.array([16, 9, 1], np.int32)
        return q, k, v, lengths

    @staticmethod
    def _pin(got, full, m):
        """Heads are independent, so the per-head math is identical —
        but XLA schedules the narrower [N, Hl, ...] contraction of a
        1-head block differently, so bit-exactness holds only while
        the compiled reduction shape is preserved (m <= 2 here).  At
        m=4 pin the ULP-level bound instead."""
        if m <= 2:
            assert np.array_equal(got, full)
        else:
            np.testing.assert_allclose(got.astype(np.float64),
                                       full.astype(np.float64),
                                       rtol=1e-6, atol=1e-7)

    @pytest.mark.parametrize("m", [1, 2, 4])
    def test_matches_full_kernel(self, m):
        q, k, v, lengths = self._case(np.random.default_rng(3))
        full = np.asarray(decode_attention(q, k, v, lengths))
        hl = self.H // m
        parts = []
        for i in range(m):
            sl = slice(i * hl, (i + 1) * hl)
            parts.append(np.asarray(decode_attention_head_slice(
                q[:, sl], k[:, :, sl], v[:, :, sl], lengths,
                head_offset=i * hl, n_local_heads=hl)))
        self._pin(np.concatenate(parts, axis=1), full, m)

    @pytest.mark.parametrize("m", [2, 4])
    def test_int8_scale_window_per_member(self, m):
        q, k, v, lengths = self._case(np.random.default_rng(5),
                                      dtype=np.int8)
        scales = np.linspace(0.01, 0.08, 2 * self.H).reshape(
            2, self.H).astype(np.float32)
        full = np.asarray(decode_attention(q, k, v, lengths,
                                           kv_scales=scales))
        hl = self.H // m
        parts = []
        for i in range(m):
            sl = slice(i * hl, (i + 1) * hl)
            # each member receives the FULL [2, H] table and slices
            # its own window at the traced head offset
            parts.append(np.asarray(decode_attention_head_slice(
                q[:, sl], k[:, :, sl], v[:, :, sl], lengths,
                head_offset=i * hl, n_local_heads=hl,
                kv_scales=scales)))
        self._pin(np.concatenate(parts, axis=1), full, m)


# ---------------------------------------------------------------------------
# partitioned decode vs the single-device oracle and the gather lane
# ---------------------------------------------------------------------------

class TestTPDecodeParity:
    def test_tp_stream_top1_identical(self, tmp_path):
        md = _lm(tmp_path)
        devs = jax.devices()
        ref, _ = _stream(md, devs[0])
        set_flags({"mesh_tp": False})
        gather, pg = _stream(md, MeshGroup(devs[:2]))
        assert not pg.tp_active
        assert gather == ref
        set_flags({"mesh_tp": True})
        for m in (2, 4):
            out, pm = _stream(md, MeshGroup(devs[:m]))
            assert pm.tp_active and pm.tp_size == m
            assert out == ref, \
                "TP m=%d diverged from single-device top-1" % m

    def test_int8_kv_tp_parity(self, tmp_path):
        md = _lm(tmp_path)
        devs = jax.devices()
        ref, _ = _stream(md, devs[0], kv_cache_dtype="int8")
        out, pm = _stream(md, MeshGroup(devs[:2]),
                          kv_cache_dtype="int8")
        assert pm.tp_active
        assert out == ref

    def test_seqpar_prefill_bit_exact(self, tmp_path):
        md = _lm(tmp_path)
        devs = jax.devices()
        ref, _ = _stream(md, devs[0])
        # drop the activation threshold so the bucket-8 prefill takes
        # the sequence-parallel (ulysses) path
        set_flags({"mesh_tp_prefill_seq": 8})
        out, pm = _stream(md, MeshGroup(devs[:2]))
        assert pm.tp_active and pm._tp_prefill_seq == 8
        assert out == ref

    def test_fused_multistep_tp(self, tmp_path):
        md = _lm(tmp_path)
        devs = jax.devices()
        ref, _ = _stream(md, devs[0])
        set_flags({"serving_decode_fuse_steps": 4})
        out, pm = _stream(md, MeshGroup(devs[:2]))
        assert pm.tp_active
        assert out == ref

    def test_spec_twin_accepts_everything_under_tp(self, tmp_path):
        md = _lm(tmp_path)
        devs = jax.devices()
        ref, _ = _stream(md, devs[0])
        group = MeshGroup(devs[:2])
        target = GenerativePredictor(md, device=group)
        draft = GenerativePredictor(md, device=group,
                                    kv_cache_dtype="int8")
        assert target.tp_active and draft.tp_active
        spec = SpeculativeDecodeSession(target, draft, 4, 2)
        got = [spec.prefill(1, PROMPT)]
        while len(got) < BUDGET and got[-1] != target.eos_id:
            toks, counts = spec.step()
            got.extend(int(t) for t in toks[1][:counts[1]])
        assert got[:BUDGET] == ref
        assert spec.proposed > 0 and spec.accepted == spec.proposed

    def test_unsupported_geometry_falls_back_with_warning(self,
                                                          tmp_path):
        # n_heads=2 does not divide by 4 -> tp_supported is False and
        # the predictor must drop to the gather lane, loudly
        md = _lm(tmp_path, name="small", n_heads=2, d_model=16,
                 vocab_size=32)
        devs = jax.devices()
        assert not tp_supported(4, 2, 16, 32)
        with pytest.warns(RuntimeWarning, match="mesh_tp"):
            pred = GenerativePredictor(md, device=MeshGroup(devs[:4]))
        assert not pred.tp_active
        ref, _ = _stream(md, devs[0])
        out, _ = greedy_decode(pred, PROMPT, BUDGET, n_slots=4, slot=1)
        assert out == ref


# ---------------------------------------------------------------------------
# the tolerance point: one psum closes each column->row pair
# ---------------------------------------------------------------------------

class TestTolerancePin:
    def test_psum_reorder_stays_in_bound_and_top1_stable(self):
        """The ONLY inexact point of the TP lowering: the row-parallel
        matmul contracts [in/m] per member and psum adds m partials,
        reordering one fp32 reduction.  Pin the documented bound
        (SERVING.md "Tensor-parallel compute": rtol 1e-5 / atol 1e-6
        on fp32 activations) and that top-1 never moves on a
        logits-shaped output."""
        rng = np.random.default_rng(11)
        x = rng.standard_normal((4, 32)).astype(np.float32)
        w1 = rng.standard_normal((32, 64)).astype(np.float32)  # column
        w2 = rng.standard_normal((64, 64)).astype(np.float32)  # row
        ref = np.maximum(x @ w1, 0.0) @ w2
        for m in (2, 4):
            cols = np.split(w1, m, axis=1)   # [in, out/m] per member
            rows = np.split(w2, m, axis=0)   # [in/m, out] per member
            partial = [np.maximum(x @ cols[i], 0.0) @ rows[i]
                       for i in range(m)]
            got = np.sum(np.stack(partial), axis=0)  # the psum
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
            assert np.array_equal(got.argmax(-1), ref.argmax(-1)), \
                "psum reorder moved top-1 at m=%d" % m


# ---------------------------------------------------------------------------
# per-member roofline
# ---------------------------------------------------------------------------

class TestPerMemberBytes:
    def test_per_device_step_bytes_scales_only_under_tp(self,
                                                        tmp_path):
        md = _lm(tmp_path)
        base = analyze_artifact(md, decode_slots=8)
        total = base.per_device_step_bytes()
        assert total == base.total_bytes
        for m, bound in ((2, 0.6), (4, 0.35)):
            tp = analyze_artifact(md, decode_slots=8, mesh_size=m,
                                  tp=True)
            gather = analyze_artifact(md, decode_slots=8, mesh_size=m,
                                      tp=False)
            # the gather lane still moves EVERY param byte through
            # every member each step; only tp divides the roofline
            assert gather.per_device_step_bytes() == total
            ratio = tp.per_device_step_bytes() / float(total)
            assert ratio <= bound, \
                "per-member bytes at m=%d: %.3f > %.2f" % (m, ratio,
                                                           bound)
            assert tp.per_device_step_bytes() == -(-total // m)
        assert "per member" in analyze_artifact(
            md, decode_slots=8, mesh_size=2, tp=True).render()


# ---------------------------------------------------------------------------
# compile cache: warm reload of the partitioned executable
# ---------------------------------------------------------------------------

class TestTPCompileCache:
    def test_warm_reload_and_mesh_shape_fingerprint(self, tmp_path):
        md = _lm(tmp_path)
        devs = jax.devices()
        set_flags({"compile_cache_dir": str(tmp_path / "cache")})

        before = cc.stats()
        ref, _ = _stream(md, MeshGroup(devs[:2]), budget=6)
        cold = cc.stats_delta(before)
        assert cold["puts"] >= 2 and cold["misses"] >= 2, cold

        # a FRESH predictor instance is the in-process stand-in for a
        # process restart: its export memo starts empty, so every
        # phase must come back from the persisted blobs
        before = cc.stats()
        warm, _ = _stream(md, MeshGroup(devs[:2]), budget=6)
        d = cc.stats_delta(before)
        assert d["hits"] >= 2 and d["misses"] == 0, d
        assert warm == ref

        # mesh shape is a fingerprint field: the (2,)-mesh blobs must
        # NOT serve a (4,) mesh
        before = cc.stats()
        out4, _ = _stream(md, MeshGroup(devs[:4]), budget=6)
        d4 = cc.stats_delta(before)
        assert d4["hits"] == 0 and d4["misses"] >= 2, d4
        assert out4 == ref


# ---------------------------------------------------------------------------
# member loss under TP stays typed
# ---------------------------------------------------------------------------

class TestTPMemberLoss:
    def test_member_loss_typed_mid_decode(self, tmp_path):
        md = _lm(tmp_path)
        devs = jax.devices()
        pred = GenerativePredictor(md, device=MeshGroup(devs[:2]))
        assert pred.tp_active
        session = pred.new_session(4)
        session.prefill(1, PROMPT)
        session.decode()
        set_member_poison("cpu:1")
        with pytest.raises(MeshMemberLost, match="cpu:1"):
            session.decode()
