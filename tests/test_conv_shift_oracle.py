"""conv_shift reference oracle (conv_shift_op.cc): circular
correlation out[k,i] = sum_j x[k, (i + j - (M-1)//2) mod N] * y[k,j].
The half-width floors (M-1)/2 — off by one from M//2 for even M."""

import numpy as np
import pytest

from tests.test_op_tail import run_op


def oracle(x, y):
    B, N = x.shape
    M = y.shape[1]
    half = (M - 1) // 2
    out = np.zeros_like(x)
    for k in range(B):
        for i in range(N):
            for j in range(M):
                out[k, i] += x[k, (i + j - half) % N] * y[k, j]
    return out


@pytest.mark.parametrize("M", [3, 4, 5])   # odd and EVEN widths
def test_conv_shift_matches_reference(M):
    rng = np.random.RandomState(M)
    x = rng.randn(2, 7).astype(np.float32)
    y = rng.randn(2, M).astype(np.float32)
    out = run_op("conv_shift", {"X": x, "Y": y}, {})
    np.testing.assert_allclose(np.asarray(out["Out"]), oracle(x, y),
                               atol=1e-5)
