"""Structured losses + metrics tests (reference unittests
test_linear_chain_crf_op.py, test_crf_decoding_op.py, test_warpctc_op.py,
test_ctc_align.py, test_edit_distance_op.py, test_auc_op.py,
test_mean_iou.py, test_chunk_eval_op.py, test_nce.py, test_hsigmoid_op.py,
test_multiplex_op.py, test_rank_loss_op.py)."""

import itertools

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid.lod import create_lod_tensor, LoDTensor


def _logsumexp(xs):
    m = np.max(xs)
    return m + np.log(np.sum(np.exp(np.asarray(xs) - m)))


# ---------------------------------------------------------------------------
# CRF
# ---------------------------------------------------------------------------

def _crf_brute(e_seq, w, labels):
    """Brute-force logZ and gold score for one sequence [n, D]."""
    n, D = e_seq.shape
    start, end, pair = w[0], w[1], w[2:]

    def score(path):
        s = start[path[0]] + end[path[-1]] + sum(e_seq[t, path[t]]
                                                 for t in range(n))
        s += sum(pair[path[t - 1], path[t]] for t in range(1, n))
        return s

    log_z = _logsumexp([score(p)
                        for p in itertools.product(range(D), repeat=n)])
    return log_z - score(tuple(labels)), None


def _crf_viterbi_brute(e_seq, w):
    n, D = e_seq.shape
    start, end, pair = w[0], w[1], w[2:]
    best, best_p = -1e30, None
    for p in itertools.product(range(D), repeat=n):
        s = start[p[0]] + end[p[-1]] + sum(e_seq[t, p[t]] for t in range(n))
        s += sum(pair[p[t - 1], p[t]] for t in range(1, n))
        if s > best:
            best, best_p = s, p
    return list(best_p)


def _build_crf_program(D):
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        emission = fluid.layers.data("emission", shape=[D], dtype="float32",
                                     lod_level=1)
        label = fluid.layers.data("label", shape=[1], dtype="int64",
                                  lod_level=1)
        nll = fluid.layers.linear_chain_crf(
            emission, label, param_attr=fluid.ParamAttr(name="crfw"))
        decoded = fluid.layers.crf_decoding(
            emission, param_attr=fluid.ParamAttr(name="crfw"))
    return main, startup, nll, decoded


def test_linear_chain_crf_matches_brute_force():
    rng = np.random.RandomState(7)
    D = 3
    lens = [2, 3]
    rows = sum(lens)
    e = rng.randn(rows, D).astype(np.float32)
    labels = rng.randint(0, D, (rows, 1)).astype(np.int64)
    main, startup, nll, decoded = _build_crf_program(D)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    w = np.asarray(fluid.global_scope().get("crfw"))
    res, dec = exe.run(
        main,
        feed={"emission": create_lod_tensor(e, [lens]),
              "label": create_lod_tensor(labels, [lens])},
        fetch_list=[nll, decoded])
    res = np.asarray(res.numpy() if isinstance(res, LoDTensor) else res)
    offs = [0, 2, 5]
    for b in range(2):
        seg = slice(offs[b], offs[b + 1])
        expect, _ = _crf_brute(e[seg], w, labels[seg, 0])
        np.testing.assert_allclose(res[b, 0], expect, rtol=1e-4,
                                   err_msg="seq %d" % b)
    dec = np.asarray(dec.numpy() if isinstance(dec, LoDTensor) else dec)
    dec = dec.reshape(-1)
    for b in range(2):
        seg = slice(offs[b], offs[b + 1])
        np.testing.assert_array_equal(dec[seg], _crf_viterbi_brute(e[seg], w))


def test_crf_trains():
    """nll decreases under SGD on a toy tagging problem."""
    rng = np.random.RandomState(0)
    D = 4
    lens = [3, 4, 2]
    rows = sum(lens)
    e = rng.randn(rows, D).astype(np.float32)
    labels = rng.randint(0, D, (rows, 1)).astype(np.int64)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        emission = fluid.layers.data("emission", shape=[D], dtype="float32",
                                     lod_level=1)
        label = fluid.layers.data("label", shape=[1], dtype="int64",
                                  lod_level=1)
        feat = fluid.layers.fc(emission, D)
        nll = fluid.layers.linear_chain_crf(
            feat, label, param_attr=fluid.ParamAttr(name="crfw2"))
        avg = fluid.layers.mean(nll)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(avg)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"emission": create_lod_tensor(e, [lens]),
            "label": create_lod_tensor(labels, [lens])}
    losses = []
    for _ in range(25):
        (l,) = exe.run(main, feed=feed, fetch_list=[avg])
        losses.append(float(np.asarray(l)))
    assert losses[-1] < losses[0] * 0.8, losses


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------

def _ctc_brute(logits, label, blank=0):
    """-log p(label) by enumerating all alignment paths. logits [T, C]."""
    T, C = logits.shape
    m = logits.max(axis=1, keepdims=True)
    logp = logits - m - np.log(np.exp(logits - m).sum(1, keepdims=True))

    def collapse(path):
        out, prev = [], None
        for p in path:
            if p != prev and p != blank:
                out.append(p)
            prev = p
        return tuple(out)

    total = -np.inf
    for path in itertools.product(range(C), repeat=T):
        if collapse(path) == tuple(label):
            total = np.logaddexp(total,
                                 sum(logp[t, path[t]] for t in range(T)))
    return -total


def test_warpctc_matches_brute_force():
    rng = np.random.RandomState(3)
    C = 3
    in_lens = [4, 3]
    lab_lens = [2, 1]
    logits = rng.randn(sum(in_lens), C).astype(np.float32)
    label = np.array([[1], [2], [1]], dtype=np.int64)  # seqs: [1,2], [1]
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[C], dtype="float32", lod_level=1)
        y = fluid.layers.data("y", shape=[1], dtype="int64", lod_level=1)
        loss = fluid.layers.warpctc(x, y, blank=0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    res, = exe.run(main,
                   feed={"x": create_lod_tensor(logits, [in_lens]),
                         "y": create_lod_tensor(label, [lab_lens])},
                   fetch_list=[loss])
    res = np.asarray(res.numpy() if isinstance(res, LoDTensor) else res)
    expect0 = _ctc_brute(logits[:4], [1, 2])
    expect1 = _ctc_brute(logits[4:7], [1])
    np.testing.assert_allclose(res.reshape(-1), [expect0, expect1],
                               rtol=1e-4)


def test_warpctc_trains():
    rng = np.random.RandomState(1)
    C = 4
    in_lens = [5, 5]
    lab_lens = [2, 2]
    feats = rng.randn(sum(in_lens), 6).astype(np.float32)
    label = rng.randint(1, C, (sum(lab_lens), 1)).astype(np.int64)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[6], dtype="float32", lod_level=1)
        y = fluid.layers.data("y", shape=[1], dtype="int64", lod_level=1)
        logits = fluid.layers.fc(x, C)
        loss = fluid.layers.mean(fluid.layers.warpctc(logits, y))
        fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": create_lod_tensor(feats, [in_lens]),
            "y": create_lod_tensor(label, [lab_lens])}
    losses = [float(np.asarray(exe.run(main, feed=feed,
                                       fetch_list=[loss])[0]))
              for _ in range(20)]
    assert losses[-1] < losses[0], losses


def test_ctc_align():
    x = np.array([[0, 1, 1, 0, 2, 2, 0]], dtype=np.int64).T  # one seq len 7
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        inp = fluid.layers.data("x", shape=[1], dtype="int64", lod_level=1)
        onehot = fluid.layers.one_hot(inp, 3)
        decoded = fluid.layers.ctc_greedy_decoder(onehot, blank=0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    res, = exe.run(main, feed={"x": create_lod_tensor(x, [[7]])},
                   fetch_list=[decoded])
    assert isinstance(res, LoDTensor)
    np.testing.assert_array_equal(res.numpy().reshape(-1), [1, 2])
    assert res.recursive_sequence_lengths() == [[2]]


# ---------------------------------------------------------------------------
# edit distance
# ---------------------------------------------------------------------------

def _lev(a, b):
    d = np.zeros((len(a) + 1, len(b) + 1))
    d[:, 0] = np.arange(len(a) + 1)
    d[0, :] = np.arange(len(b) + 1)
    for i in range(1, len(a) + 1):
        for j in range(1, len(b) + 1):
            d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1,
                          d[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
    return d[-1, -1]


def test_edit_distance():
    hyp = np.array([[1], [2], [3], [4], [5]], dtype=np.int64)
    ref = np.array([[1], [3], [3], [7], [8], [9]], dtype=np.int64)
    hyp_lens, ref_lens = [2, 3], [3, 3]
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        h = fluid.layers.data("h", shape=[1], dtype="int64", lod_level=1)
        r = fluid.layers.data("r", shape=[1], dtype="int64", lod_level=1)
        dist, seq_num = fluid.layers.edit_distance(h, r, normalized=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    d, n = exe.run(main,
                   feed={"h": create_lod_tensor(hyp, [hyp_lens]),
                         "r": create_lod_tensor(ref, [ref_lens])},
                   fetch_list=[dist, seq_num])
    d = np.asarray(d.numpy() if isinstance(d, LoDTensor) else d)
    expect = [_lev([1, 2], [1, 3, 3]), _lev([3, 4, 5], [7, 8, 9])]
    np.testing.assert_allclose(d.reshape(-1), expect)
    assert int(np.asarray(n)[0]) == 2


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def test_auc_streaming():
    rng = np.random.RandomState(0)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        pred = fluid.layers.data("pred", shape=[2], dtype="float32")
        lab = fluid.layers.data("lab", shape=[1], dtype="int64")
        auc_out, _ = fluid.layers.auc(pred, lab, num_thresholds=4096)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    # perfectly separable -> AUC ~ 1
    p = np.array([[0.1, 0.9]] * 5 + [[0.9, 0.1]] * 5, dtype=np.float32)
    y = np.array([[1]] * 5 + [[0]] * 5, dtype=np.int64)
    (a,) = exe.run(main, feed={"pred": p, "lab": y}, fetch_list=[auc_out])
    assert float(np.asarray(a)[0]) > 0.99
    # feed opposite labels -> streaming AUC drops towards 0.5
    (a2,) = exe.run(main, feed={"pred": p, "lab": 1 - y},
                    fetch_list=[auc_out])
    assert 0.3 < float(np.asarray(a2)[0]) < 0.7


def test_mean_iou():
    pred = np.array([0, 1, 1, 2], dtype=np.int32)
    lab = np.array([0, 1, 2, 2], dtype=np.int32)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        p = fluid.layers.data("p", shape=[4], dtype="int32",
                              append_batch_size=False)
        l = fluid.layers.data("l", shape=[4], dtype="int32",
                              append_batch_size=False)
        miou, wrong, correct = fluid.layers.mean_iou(p, l, num_classes=3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    m, w, c = exe.run(main, feed={"p": pred, "l": lab},
                      fetch_list=[miou, wrong, correct])
    # class ious: 0: 1/1, 1: 1/2, 2: 1/2 -> mean 2/3
    np.testing.assert_allclose(float(np.asarray(m)[0]), 2.0 / 3, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(c), [1, 1, 1])


def test_chunk_eval_iob():
    # IOB, 1 chunk type: tags B=0, I=1, O=2(outside, >= num_types*2)
    # label:  B I O B I  -> chunks (0-1), (3-4)
    # infer:  B I O B O  -> chunks (0-1), (3-3)
    lab = np.array([[0], [1], [2], [0], [1]], dtype=np.int64)
    inf = np.array([[0], [1], [2], [0], [2]], dtype=np.int64)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.data("i", shape=[1], dtype="int64", lod_level=1)
        l = fluid.layers.data("l", shape=[1], dtype="int64", lod_level=1)
        prec, rec, f1, ni, nl, nc = fluid.layers.chunk_eval(
            i, l, chunk_scheme="IOB", num_chunk_types=1)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    res = exe.run(main, feed={"i": create_lod_tensor(inf, [[5]]),
                              "l": create_lod_tensor(lab, [[5]])},
                  fetch_list=[prec, rec, f1, ni, nl, nc])
    prec_v, rec_v = float(np.asarray(res[0])[0]), float(np.asarray(res[1])[0])
    assert int(np.asarray(res[3])[0]) == 2     # inferred chunks
    assert int(np.asarray(res[4])[0]) == 2     # label chunks
    assert int(np.asarray(res[5])[0]) == 1     # correct (first chunk)
    np.testing.assert_allclose([prec_v, rec_v], [0.5, 0.5])


# ---------------------------------------------------------------------------
# sampled / pairwise losses and selection ops
# ---------------------------------------------------------------------------

def test_rank_loss():
    left = np.array([[0.5], [2.0]], dtype=np.float32)
    right = np.array([[1.0], [1.0]], dtype=np.float32)
    lab = np.array([[1.0], [0.0]], dtype=np.float32)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        l = fluid.layers.data("l", shape=[1], dtype="float32")
        r = fluid.layers.data("r", shape=[1], dtype="float32")
        t = fluid.layers.data("t", shape=[1], dtype="float32")
        out = fluid.layers.rank_loss(t, l, r)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (res,) = exe.run(main, feed={"l": left, "r": right, "t": lab},
                     fetch_list=[out])
    o = left - right
    expect = np.log1p(np.exp(o)) - lab * o
    np.testing.assert_allclose(np.asarray(res), expect, rtol=1e-5)


def test_multiplex():
    x1 = np.arange(6, dtype=np.float32).reshape(3, 2)
    x2 = -np.arange(6, dtype=np.float32).reshape(3, 2)
    ids = np.array([[0], [1], [0]], dtype=np.int32)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        a = fluid.layers.data("a", shape=[2], dtype="float32")
        b = fluid.layers.data("b", shape=[2], dtype="float32")
        i = fluid.layers.data("i", shape=[1], dtype="int32")
        out = fluid.layers.multiplex([a, b], i)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (res,) = exe.run(main, feed={"a": x1, "b": x2, "i": ids},
                     fetch_list=[out])
    expect = np.stack([x1[0], x2[1], x1[2]])
    np.testing.assert_allclose(np.asarray(res), expect)


def test_nce_and_hsigmoid_train():
    rng = np.random.RandomState(0)
    B, D, C = 8, 6, 10
    x_np = rng.randn(B, D).astype(np.float32)
    y_np = rng.randint(0, C, (B, 1)).astype(np.int64)
    for which in ("nce", "hsigmoid"):
        main, startup = Program(), Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[D], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            if which == "nce":
                cost = fluid.layers.nce(x, y, num_total_classes=C,
                                        num_neg_samples=4)
            else:
                cost = fluid.layers.hsigmoid(x, y, num_classes=C)
            loss = fluid.layers.mean(cost)
            fluid.optimizer.SGD(learning_rate=0.2).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = [float(np.asarray(
            exe.run(main, feed={"x": x_np, "y": y_np},
                    fetch_list=[loss])[0])) for _ in range(15)]
        assert np.isfinite(losses).all(), (which, losses)
        assert losses[-1] < losses[0], (which, losses)


def test_hsigmoid_matches_simple_code_reference():
    rng = np.random.RandomState(2)
    B, D, C = 4, 5, 6
    x_np = rng.randn(B, D).astype(np.float32)
    y_np = rng.randint(0, C, (B, 1)).astype(np.int64)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[D], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        cost = fluid.layers.hsigmoid(
            x, y, num_classes=C, param_attr=fluid.ParamAttr(name="hs_w"),
            bias_attr=fluid.ParamAttr(name="hs_b"))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (res,) = exe.run(main, feed={"x": x_np, "y": y_np}, fetch_list=[cost])
    w = np.asarray(fluid.global_scope().get("hs_w"))
    b = np.asarray(fluid.global_scope().get("hs_b")).reshape(-1)

    def ref_one(xv, lab):
        c = lab + C
        code_len = int(np.floor(np.log2(c)))
        loss = 0.0
        for shift in range(code_len - 1, -1, -1):
            node = (c >> (shift + 1)) - 1       # SimpleCode calc_index
            bit = (c >> shift) & 1              # SimpleCode calc_bit
            pre = xv @ w[node] + b[node]
            loss += np.logaddexp(0.0, pre) - bit * pre
        return loss

    expect = [ref_one(x_np[i], int(y_np[i, 0])) for i in range(B)]
    np.testing.assert_allclose(np.asarray(res).reshape(-1), expect,
                               rtol=1e-4)


def test_edit_distance_ignored_tokens():
    hyp = np.array([[0], [1], [2]], dtype=np.int64)   # -> [1,2] after erase
    ref = np.array([[1], [0], [2]], dtype=np.int64)   # -> [1,2]
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        h = fluid.layers.data("h", shape=[1], dtype="int64", lod_level=1)
        r = fluid.layers.data("r", shape=[1], dtype="int64", lod_level=1)
        dist, _ = fluid.layers.edit_distance(h, r, normalized=False,
                                             ignored_tokens=[0])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (d,) = exe.run(main, feed={"h": create_lod_tensor(hyp, [[3]]),
                               "r": create_lod_tensor(ref, [[3]])},
                   fetch_list=[dist])
    d = np.asarray(d.numpy() if isinstance(d, LoDTensor) else d)
    assert float(d.reshape(-1)[0]) == 0.0


def test_auc_pr_curve_runs():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        pred = fluid.layers.data("pred", shape=[2], dtype="float32")
        lab = fluid.layers.data("lab", shape=[1], dtype="int64")
        auc_out, _ = fluid.layers.auc(pred, lab, curve="PR",
                                      num_thresholds=1024)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    p = np.array([[0.1, 0.9]] * 5 + [[0.9, 0.1]] * 5, dtype=np.float32)
    y = np.array([[1]] * 5 + [[0]] * 5, dtype=np.int64)
    (a,) = exe.run(main, feed={"pred": p, "lab": y}, fetch_list=[auc_out])
    assert float(np.asarray(a)[0]) > 0.95


def test_sampling_id():
    p = np.array([[0.0, 1.0, 0.0], [1.0, 0.0, 0.0]], dtype=np.float32)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3], dtype="float32")
        out = fluid.layers.sampling_id(x)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (res,) = exe.run(main, feed={"x": p}, fetch_list=[out])
    np.testing.assert_array_equal(np.asarray(res).reshape(-1), [1, 0])




def test_nce_cost_matches_reference_formula():
    """nce_op.h:140-151: o = sigmoid(sample logit), b = num_neg * q(y);
    per-sample cost = -log(o/(o+b)) (true) / -log(b/(o+b)) (negative).
    Recomputed in numpy from the op's own sampled labels."""
    from tests.test_op_tail import run_op
    rng2 = np.random.RandomState(3)
    B, D, C, K = 4, 5, 11, 6
    x = rng2.randn(B, D).astype(np.float32)
    w = rng2.randn(C, D).astype(np.float32)
    bias = rng2.randn(C).astype(np.float32)
    lab = rng2.randint(0, C, (B, 1)).astype(np.int64)
    out = run_op("nce", {"Input": x, "Label": lab, "Weight": w,
                         "Bias": bias},
                 {"num_neg_samples": K, "num_total_classes": C,
                  "sampler": 0})
    samples = np.asarray(out["SampleLabels"])            # [B, 1+K]
    cost = np.asarray(out["Cost"]).ravel()
    b_const = K / float(C)                               # uniform q
    ref = np.zeros(B)
    for i in range(B):
        for j, t in enumerate(samples[i]):
            o = 1.0 / (1.0 + np.exp(-(x[i] @ w[t] + bias[t])))
            ref[i] += (-np.log(o / (o + b_const)) if j < 1
                       else -np.log(b_const / (o + b_const)))
    np.testing.assert_allclose(cost, ref, rtol=1e-5)
    # SampleLogits holds post-sigmoid outputs (nce_op.h:141)
    sl = np.asarray(out["SampleLogits"])
    assert np.all(sl > 0) and np.all(sl < 1)


def test_hsigmoid_preout_holds_softrelu_values():
    """PreOut mirrors the reference's in-place softrelu(clip(pre))
    (hierarchical_sigmoid_op.h:66-75): always >= 0, log(1+e^pre) at
    valid path positions, 0 padding beyond each label's code length."""
    from tests.test_op_tail import run_op
    rng = np.random.RandomState(5)
    B, D, C = 3, 4, 6
    x = rng.randn(B, D).astype(np.float32)
    w = rng.randn(C - 1, D).astype(np.float32)
    lab = rng.randint(0, C, (B, 1)).astype(np.int64)
    out = run_op("hierarchical_sigmoid", {"X": x, "W": w, "Label": lab},
                 {"num_classes": C})
    pre_out = np.asarray(out["PreOut"])
    assert np.all(pre_out >= 0)
    for i in range(B):
        c = int(lab[i, 0]) + C
        code_len = int(np.floor(np.log2(c)))
        for j, shift in enumerate(range(code_len - 1, -1, -1)):
            node = (c >> (shift + 1)) - 1
            pre = float(x[i] @ w[node])
            np.testing.assert_allclose(pre_out[i, j],
                                       np.logaddexp(0.0, pre), rtol=1e-5)
        assert np.all(pre_out[i, code_len:] == 0)
