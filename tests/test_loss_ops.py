"""Structured losses + metrics tests (reference unittests
test_linear_chain_crf_op.py, test_crf_decoding_op.py, test_warpctc_op.py,
test_ctc_align.py, test_edit_distance_op.py, test_auc_op.py,
test_mean_iou.py, test_chunk_eval_op.py, test_nce.py, test_hsigmoid_op.py,
test_multiplex_op.py, test_rank_loss_op.py)."""

import itertools

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid.lod import create_lod_tensor, LoDTensor


def _logsumexp(xs):
    m = np.max(xs)
    return m + np.log(np.sum(np.exp(np.asarray(xs) - m)))


# ---------------------------------------------------------------------------
# CRF
# ---------------------------------------------------------------------------

def _crf_brute(e_seq, w, labels):
    """Brute-force logZ and gold score for one sequence [n, D]."""
    n, D = e_seq.shape
    start, end, pair = w[0], w[1], w[2:]

    def score(path):
        s = start[path[0]] + end[path[-1]] + sum(e_seq[t, path[t]]
                                                 for t in range(n))
        s += sum(pair[path[t - 1], path[t]] for t in range(1, n))
        return s

    log_z = _logsumexp([score(p)
                        for p in itertools.product(range(D), repeat=n)])
    return log_z - score(tuple(labels)), None


def _crf_viterbi_brute(e_seq, w):
    n, D = e_seq.shape
    start, end, pair = w[0], w[1], w[2:]
    best, best_p = -1e30, None
    for p in itertools.product(range(D), repeat=n):
        s = start[p[0]] + end[p[-1]] + sum(e_seq[t, p[t]] for t in range(n))
        s += sum(pair[p[t - 1], p[t]] for t in range(1, n))
        if s > best:
            best, best_p = s, p
    return list(best_p)


def _build_crf_program(D):
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        emission = fluid.layers.data("emission", shape=[D], dtype="float32",
                                     lod_level=1)
        label = fluid.layers.data("label", shape=[1], dtype="int64",
                                  lod_level=1)
        nll = fluid.layers.linear_chain_crf(
            emission, label, param_attr=fluid.ParamAttr(name="crfw"))
        decoded = fluid.layers.crf_decoding(
            emission, param_attr=fluid.ParamAttr(name="crfw"))
    return main, startup, nll, decoded


def test_linear_chain_crf_matches_brute_force():
    rng = np.random.RandomState(7)
    D = 3
    lens = [2, 3]
    rows = sum(lens)
    e = rng.randn(rows, D).astype(np.float32)
    labels = rng.randint(0, D, (rows, 1)).astype(np.int64)
    main, startup, nll, decoded = _build_crf_program(D)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    w = np.asarray(fluid.global_scope().get("crfw"))
    res, dec = exe.run(
        main,
        feed={"emission": create_lod_tensor(e, [lens]),
              "label": create_lod_tensor(labels, [lens])},
        fetch_list=[nll, decoded])
    res = np.asarray(res.numpy() if isinstance(res, LoDTensor) else res)
    offs = [0, 2, 5]
    for b in range(2):
        seg = slice(offs[b], offs[b + 1])
        expect, _ = _crf_brute(e[seg], w, labels[seg, 0])
        np.testing.assert_allclose(res[b, 0], expect, rtol=1e-4,
                                   err_msg="seq %d" % b)
    dec = np.asarray(dec.numpy() if isinstance(dec, LoDTensor) else dec)
    dec = dec.reshape(-1)
    for b in range(2):
        seg = slice(offs[b], offs[b + 1])
        np.testing.assert_array_equal(dec[seg], _crf_viterbi_brute(e[seg], w))


def test_crf_trains():
    """nll decreases under SGD on a toy tagging problem."""
    rng = np.random.RandomState(0)
    D = 4
    lens = [3, 4, 2]
    rows = sum(lens)
    e = rng.randn(rows, D).astype(np.float32)
    labels = rng.randint(0, D, (rows, 1)).astype(np.int64)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        emission = fluid.layers.data("emission", shape=[D], dtype="float32",
                                     lod_level=1)
        label = fluid.layers.data("label", shape=[1], dtype="int64",
                                  lod_level=1)
        feat = fluid.layers.fc(emission, D)
        nll = fluid.layers.linear_chain_crf(
            feat, label, param_attr=fluid.ParamAttr(name="crfw2"))
        avg = fluid.layers.mean(nll)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(avg)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"emission": create_lod_tensor(e, [lens]),
            "label": create_lod_tensor(labels, [lens])}
    losses = []
    for _ in range(25):
        (l,) = exe.run(main, feed=feed, fetch_list=[avg])
        losses.append(float(np.asarray(l)))
    assert losses[-1] < losses[0] * 0.8, losses


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------

def _ctc_brute(logits, label, blank=0):
    """-log p(label) by enumerating all alignment paths. logits [T, C]."""
    T, C = logits.shape
    m = logits.max(axis=1, keepdims=True)
    logp = logits - m - np.log(np.exp(logits - m).sum(1, keepdims=True))

    def collapse(path):
        out, prev = [], None
        for p in path:
            if p != prev and p != blank:
                out.append(p)
            prev = p
        return tuple(out)

    total = -np.inf
    for path in itertools.product(range(C), repeat=T):
        if collapse(path) == tuple(label):
            total = np.logaddexp(total,
                                 sum(logp[t, path[t]] for t in range(T)))
    return -total


def test_warpctc_matches_brute_force():
    rng = np.random.RandomState(3)
    C = 3
    in_lens = [4, 3]
    lab_lens = [2, 1]
    logits = rng.randn(sum(in_lens), C).astype(np.float32)
    label = np.array([[1], [2], [1]], dtype=np.int64)  # seqs: [1,2], [1]
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[C], dtype="float32", lod_level=1)
        y = fluid.layers.data("y", shape=[1], dtype="int64", lod_level=1)
        loss = fluid.layers.warpctc(x, y, blank=0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    res, = exe.run(main,
                   feed={"x": create_lod_tensor(logits, [in_lens]),
                         "y": create_lod_tensor(label, [lab_lens])},
                   fetch_list=[loss])
    res = np.asarray(res.numpy() if isinstance(res, LoDTensor) else res)
    expect0 = _ctc_brute(logits[:4], [1, 2])
    expect1 = _ctc_brute(logits[4:7], [1])
    np.testing.assert_allclose(res.reshape(-1), [expect0, expect1],
                               rtol=1e-4)


def test_warpctc_trains():
    rng = np.random.RandomState(1)
    C = 4
    in_lens = [5, 5]
    lab_lens = [2, 2]
    feats = rng.randn(sum(in_lens), 6).astype(np.float32)
    label = rng.randint(1, C, (sum(lab_lens), 1)).astype(np.int64)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[6], dtype="float32", lod_level=1)
        y = fluid.layers.data("y", shape=[1], dtype="int64", lod_level=1)
        logits = fluid.layers.fc(x, C)
        loss = fluid.layers.mean(fluid.layers.warpctc(logits, y))
        fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": create_lod_tensor(feats, [in_lens]),
            "y": create_lod_tensor(label, [lab_lens])}
    losses = [float(np.asarray(exe.run(main, feed=feed,
                                       fetch_list=[loss])[0]))
              for _ in range(20)]
    assert losses[-1] < losses[0], losses


def test_ctc_align():
    x = np.array([[0, 1, 1, 0, 2, 2, 0]], dtype=np.int64).T  # one seq len 7
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        inp = fluid.layers.data("x", shape=[1], dtype="int64", lod_level=1)
        onehot = fluid.layers.one_hot(inp, 3)
        decoded = fluid.layers.ctc_greedy_decoder(onehot, blank=0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    res, = exe.run(main, feed={"x": create_lod_tensor(x, [[7]])},
                   fetch_list=[decoded])
    assert isinstance(res, LoDTensor)
    np.testing.assert_array_equal(res.numpy().reshape(-1), [1, 2])
    assert res.recursive_sequence_lengths() == [[2]]


# ---------------------------------------------------------------------------
# edit distance
# ---------------------------------------------------------------------------

def _lev(a, b):
    d = np.zeros((len(a) + 1, len(b) + 1))
    d[:, 0] = np.arange(len(a) + 1)
    d[0, :] = np.arange(len(b) + 1)
    for i in range(1, len(a) + 1):
        for j in range(1, len(b) + 1):
            d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1,
                          d[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
    return d[-1, -1]


def test_edit_distance():
    hyp = np.array([[1], [2], [3], [4], [5]], dtype=np.int64)
    ref = np.array([[1], [3], [3], [7], [8], [9]], dtype=np.int64)
    hyp_lens, ref_lens = [2, 3], [3, 3]
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        h = fluid.layers.data("h", shape=[1], dtype="int64", lod_level=1)
        r = fluid.layers.data("r", shape=[1], dtype="int64", lod_level=1)
        dist, seq_num = fluid.layers.edit_distance(h, r, normalized=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    d, n = exe.run(main,
                   feed={"h": create_lod_tensor(hyp, [hyp_lens]),
                         "r": create_lod_tensor(ref, [ref_lens])},
                   fetch_list=[dist, seq_num])
    d = np.asarray(d.numpy() if isinstance(d, LoDTensor) else d)
    expect = [_lev([1, 2], [1, 3, 3]), _lev([3, 4, 5], [7, 8, 9])]
    np.testing.assert_allclose(d.reshape(-1), expect)
    assert int(np.asarray(n)[0]) == 2


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def test_auc_streaming():
    rng = np.random.RandomState(0)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        pred = fluid.layers.data("pred", shape=[2], dtype="float32")
        lab = fluid.layers.data("lab", shape=[1], dtype="int64")
        auc_out, batch_auc_out, _ = fluid.layers.auc(
            pred, lab, num_thresholds=4096)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    # perfectly separable -> AUC ~ 1
    p = np.array([[0.1, 0.9]] * 5 + [[0.9, 0.1]] * 5, dtype=np.float32)
    y = np.array([[1]] * 5 + [[0]] * 5, dtype=np.int64)
    (a,) = exe.run(main, feed={"pred": p, "lab": y}, fetch_list=[auc_out])
    assert float(np.asarray(a)[0]) > 0.99
    # feed opposite labels -> streaming AUC drops towards 0.5
    (a2,) = exe.run(main, feed={"pred": p, "lab": 1 - y},
                    fetch_list=[auc_out])
    assert 0.3 < float(np.asarray(a2)[0]) < 0.7


def test_mean_iou():
    pred = np.array([0, 1, 1, 2], dtype=np.int32)
    lab = np.array([0, 1, 2, 2], dtype=np.int32)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        p = fluid.layers.data("p", shape=[4], dtype="int32",
                              append_batch_size=False)
        l = fluid.layers.data("l", shape=[4], dtype="int32",
                              append_batch_size=False)
        miou, wrong, correct = fluid.layers.mean_iou(p, l, num_classes=3)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    m, w, c = exe.run(main, feed={"p": pred, "l": lab},
                      fetch_list=[miou, wrong, correct])
    # class ious: 0: 1/1, 1: 1/2, 2: 1/2 -> mean 2/3
    np.testing.assert_allclose(float(np.asarray(m)[0]), 2.0 / 3, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(c), [1, 1, 1])


def test_chunk_eval_iob():
    # IOB, 1 chunk type: tags B=0, I=1, O=2(outside, >= num_types*2)
    # label:  B I O B I  -> chunks (0-1), (3-4)
    # infer:  B I O B O  -> chunks (0-1), (3-3)
    lab = np.array([[0], [1], [2], [0], [1]], dtype=np.int64)
    inf = np.array([[0], [1], [2], [0], [2]], dtype=np.int64)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.data("i", shape=[1], dtype="int64", lod_level=1)
        l = fluid.layers.data("l", shape=[1], dtype="int64", lod_level=1)
        prec, rec, f1, ni, nl, nc = fluid.layers.chunk_eval(
            i, l, chunk_scheme="IOB", num_chunk_types=1)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    res = exe.run(main, feed={"i": create_lod_tensor(inf, [[5]]),
                              "l": create_lod_tensor(lab, [[5]])},
                  fetch_list=[prec, rec, f1, ni, nl, nc])
    prec_v, rec_v = float(np.asarray(res[0])[0]), float(np.asarray(res[1])[0])
    assert int(np.asarray(res[3])[0]) == 2     # inferred chunks
    assert int(np.asarray(res[4])[0]) == 2     # label chunks
    assert int(np.asarray(res[5])[0]) == 1     # correct (first chunk)
    np.testing.assert_allclose([prec_v, rec_v], [0.5, 0.5])


# ---------------------------------------------------------------------------
# sampled / pairwise losses and selection ops
# ---------------------------------------------------------------------------

def test_rank_loss():
    left = np.array([[0.5], [2.0]], dtype=np.float32)
    right = np.array([[1.0], [1.0]], dtype=np.float32)
    lab = np.array([[1.0], [0.0]], dtype=np.float32)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        l = fluid.layers.data("l", shape=[1], dtype="float32")
        r = fluid.layers.data("r", shape=[1], dtype="float32")
        t = fluid.layers.data("t", shape=[1], dtype="float32")
        out = fluid.layers.rank_loss(t, l, r)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (res,) = exe.run(main, feed={"l": left, "r": right, "t": lab},
                     fetch_list=[out])
    o = left - right
    expect = np.log1p(np.exp(o)) - lab * o
    np.testing.assert_allclose(np.asarray(res), expect, rtol=1e-5)


def test_multiplex():
    x1 = np.arange(6, dtype=np.float32).reshape(3, 2)
    x2 = -np.arange(6, dtype=np.float32).reshape(3, 2)
    ids = np.array([[0], [1], [0]], dtype=np.int32)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        a = fluid.layers.data("a", shape=[2], dtype="float32")
        b = fluid.layers.data("b", shape=[2], dtype="float32")
        i = fluid.layers.data("i", shape=[1], dtype="int32")
        out = fluid.layers.multiplex([a, b], i)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (res,) = exe.run(main, feed={"a": x1, "b": x2, "i": ids},
                     fetch_list=[out])
    expect = np.stack([x1[0], x2[1], x1[2]])
    np.testing.assert_allclose(np.asarray(res), expect)


def test_nce_and_hsigmoid_train():
    rng = np.random.RandomState(0)
    B, D, C = 8, 6, 10
    x_np = rng.randn(B, D).astype(np.float32)
    y_np = rng.randint(0, C, (B, 1)).astype(np.int64)
    for which in ("nce", "hsigmoid"):
        main, startup = Program(), Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[D], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="int64")
            if which == "nce":
                cost = fluid.layers.nce(x, y, num_total_classes=C,
                                        num_neg_samples=4)
            else:
                cost = fluid.layers.hsigmoid(x, y, num_classes=C)
            loss = fluid.layers.mean(cost)
            fluid.optimizer.SGD(learning_rate=0.2).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        losses = [float(np.asarray(
            exe.run(main, feed={"x": x_np, "y": y_np},
                    fetch_list=[loss])[0])) for _ in range(15)]
        assert np.isfinite(losses).all(), (which, losses)
        assert losses[-1] < losses[0], (which, losses)


def test_hsigmoid_matches_simple_code_reference():
    rng = np.random.RandomState(2)
    B, D, C = 4, 5, 6
    x_np = rng.randn(B, D).astype(np.float32)
    y_np = rng.randint(0, C, (B, 1)).astype(np.int64)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[D], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        cost = fluid.layers.hsigmoid(
            x, y, num_classes=C, param_attr=fluid.ParamAttr(name="hs_w"),
            bias_attr=fluid.ParamAttr(name="hs_b"))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (res,) = exe.run(main, feed={"x": x_np, "y": y_np}, fetch_list=[cost])
    w = np.asarray(fluid.global_scope().get("hs_w"))
    b = np.asarray(fluid.global_scope().get("hs_b")).reshape(-1)

    def ref_one(xv, lab):
        c = lab + C
        code_len = int(np.floor(np.log2(c)))
        loss = 0.0
        for shift in range(code_len - 1, -1, -1):
            node = (c >> (shift + 1)) - 1       # SimpleCode calc_index
            bit = (c >> shift) & 1              # SimpleCode calc_bit
            pre = xv @ w[node] + b[node]
            loss += np.logaddexp(0.0, pre) - bit * pre
        return loss

    expect = [ref_one(x_np[i], int(y_np[i, 0])) for i in range(B)]
    np.testing.assert_allclose(np.asarray(res).reshape(-1), expect,
                               rtol=1e-4)


def test_edit_distance_ignored_tokens():
    hyp = np.array([[0], [1], [2]], dtype=np.int64)   # -> [1,2] after erase
    ref = np.array([[1], [0], [2]], dtype=np.int64)   # -> [1,2]
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        h = fluid.layers.data("h", shape=[1], dtype="int64", lod_level=1)
        r = fluid.layers.data("r", shape=[1], dtype="int64", lod_level=1)
        dist, _ = fluid.layers.edit_distance(h, r, normalized=False,
                                             ignored_tokens=[0])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (d,) = exe.run(main, feed={"h": create_lod_tensor(hyp, [[3]]),
                               "r": create_lod_tensor(ref, [[3]])},
                   fetch_list=[dist])
    d = np.asarray(d.numpy() if isinstance(d, LoDTensor) else d)
    assert float(d.reshape(-1)[0]) == 0.0


def test_auc_pr_curve_runs():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        pred = fluid.layers.data("pred", shape=[2], dtype="float32")
        lab = fluid.layers.data("lab", shape=[1], dtype="int64")
        auc_out, _batch, _ = fluid.layers.auc(pred, lab, curve="PR",
                                      num_thresholds=1024)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    p = np.array([[0.1, 0.9]] * 5 + [[0.9, 0.1]] * 5, dtype=np.float32)
    y = np.array([[1]] * 5 + [[0]] * 5, dtype=np.int64)
    (a,) = exe.run(main, feed={"pred": p, "lab": y}, fetch_list=[auc_out])
    assert float(np.asarray(a)[0]) > 0.95


def test_sampling_id():
    p = np.array([[0.0, 1.0, 0.0], [1.0, 0.0, 0.0]], dtype=np.float32)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3], dtype="float32")
        out = fluid.layers.sampling_id(x)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (res,) = exe.run(main, feed={"x": p}, fetch_list=[out])
    np.testing.assert_array_equal(np.asarray(res).reshape(-1), [1, 0])




def test_nce_cost_matches_reference_formula():
    """nce_op.h:140-151: o = sigmoid(sample logit), b = num_neg * q(y);
    per-sample cost = -log(o/(o+b)) (true) / -log(b/(o+b)) (negative).
    Recomputed in numpy from the op's own sampled labels."""
    from tests.test_op_tail import run_op
    rng2 = np.random.RandomState(3)
    B, D, C, K = 4, 5, 11, 6
    x = rng2.randn(B, D).astype(np.float32)
    w = rng2.randn(C, D).astype(np.float32)
    bias = rng2.randn(C).astype(np.float32)
    lab = rng2.randint(0, C, (B, 1)).astype(np.int64)
    out = run_op("nce", {"Input": x, "Label": lab, "Weight": w,
                         "Bias": bias},
                 {"num_neg_samples": K, "num_total_classes": C,
                  "sampler": 0})
    samples = np.asarray(out["SampleLabels"])            # [B, 1+K]
    cost = np.asarray(out["Cost"]).ravel()
    b_const = K / float(C)                               # uniform q
    ref = np.zeros(B)
    for i in range(B):
        for j, t in enumerate(samples[i]):
            o = 1.0 / (1.0 + np.exp(-(x[i] @ w[t] + bias[t])))
            ref[i] += (-np.log(o / (o + b_const)) if j < 1
                       else -np.log(b_const / (o + b_const)))
    np.testing.assert_allclose(cost, ref, rtol=1e-5)
    # SampleLogits holds post-sigmoid outputs (nce_op.h:141)
    sl = np.asarray(out["SampleLogits"])
    assert np.all(sl > 0) and np.all(sl < 1)


def test_hsigmoid_preout_holds_softrelu_values():
    """PreOut mirrors the reference's in-place softrelu(clip(pre))
    (hierarchical_sigmoid_op.h:66-75): always >= 0, log(1+e^pre) at
    valid path positions, 0 padding beyond each label's code length."""
    from tests.test_op_tail import run_op
    rng = np.random.RandomState(5)
    B, D, C = 3, 4, 6
    x = rng.randn(B, D).astype(np.float32)
    w = rng.randn(C - 1, D).astype(np.float32)
    lab = rng.randint(0, C, (B, 1)).astype(np.int64)
    out = run_op("hierarchical_sigmoid", {"X": x, "W": w, "Label": lab},
                 {"num_classes": C})
    pre_out = np.asarray(out["PreOut"])
    assert np.all(pre_out >= 0)
    for i in range(B):
        c = int(lab[i, 0]) + C
        code_len = int(np.floor(np.log2(c)))
        for j, shift in enumerate(range(code_len - 1, -1, -1)):
            node = (c >> (shift + 1)) - 1
            pre = float(x[i] @ w[node])
            np.testing.assert_allclose(pre_out[i, j],
                                       np.logaddexp(0.0, pre), rtol=1e-5)
        assert np.all(pre_out[i, code_len:] == 0)


# ---------------------------------------------------------------------------
# chunk_eval randomized oracle audit (r5): faithful python restatement of
# chunk_eval_op.h GetSegments/ChunkBegin/ChunkEnd/EvalOneSeq
# ---------------------------------------------------------------------------

_SCHEMES = {
    # scheme: (num_tag_types, tag_begin, tag_inside, tag_end, tag_single)
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _ref_segments(labels, scheme, num_types):
    ntag, tb, ti, te, ts = _SCHEMES[scheme]
    other = num_types

    def chunk_end(ptag, ptype, tag, typ):
        if ptype == other:
            return False
        if typ == other or typ != ptype:
            return True
        if ptag == tb or ptag == ti:
            return tag == tb or tag == ts
        return ptag in (te, ts)

    def chunk_begin(ptag, ptype, tag, typ):
        if ptype == other:
            return typ != other
        if typ == other:
            return False
        if typ != ptype:
            return True
        if tag == tb or tag == ts:
            return True
        if tag in (ti, te):
            return ptag in (te, ts)
        return False

    segs, in_chunk, start = [], False, 0
    tag, typ = -1, other
    for i, lab in enumerate(labels):
        ptag, ptype = tag, typ
        tag, typ = lab % ntag, lab // ntag
        if in_chunk and chunk_end(ptag, ptype, tag, typ):
            segs.append((start, i - 1, ptype))
            in_chunk = False
        if chunk_begin(ptag, ptype, tag, typ):
            start, in_chunk = i, True
    if in_chunk:
        segs.append((start, len(labels) - 1, typ))
    return segs


def _ref_chunk_eval(seqs_inf, seqs_lab, scheme, num_types, excluded):
    ex = set(excluded)
    ni = nl = nc = 0
    for inf, lab in zip(seqs_inf, seqs_lab):
        si = _ref_segments(inf, scheme, num_types)
        sl = _ref_segments(lab, scheme, num_types)
        i = j = 0
        while i < len(si) and j < len(sl):
            if si[i] == sl[j] and si[i][2] not in ex:
                nc += 1
            if si[i][1] < sl[j][1]:
                i += 1
            elif si[i][1] > sl[j][1]:
                j += 1
            else:
                i += 1
                j += 1
        nl += sum(1 for s in sl if s[2] not in ex)
        ni += sum(1 for s in si if s[2] not in ex)
    prec = 0.0 if not ni else nc / ni
    rec = 0.0 if not nl else nc / nl
    f1 = 0.0 if not nc else 2 * prec * rec / (prec + rec)
    return prec, rec, f1, ni, nl, nc


@pytest.mark.parametrize("scheme", ["IOB", "IOE", "IOBES", "plain"])
def test_chunk_eval_matches_reference_oracle(scheme):
    """Randomized parity vs the reference C++ algorithm restated in
    python (chunk_eval_op.h:41-239): multi-sequence LoD, 'other' tags,
    excluded chunk types."""
    rng = np.random.RandomState(hash(scheme) % (2 ** 31))
    ntag = _SCHEMES[scheme][0]
    for trial in range(8):
        num_types = int(rng.randint(1, 4))
        max_label = num_types * ntag          # == the 'other' label
        lens = [int(rng.randint(1, 9)) for _ in range(rng.randint(1, 4))]
        seqs_i = [rng.randint(0, max_label + 1, (n,)).tolist()
                  for n in lens]
        seqs_l = [rng.randint(0, max_label + 1, (n,)).tolist()
                  for n in lens]
        excluded = ([0] if num_types > 1 and trial % 2 else [])

        want = _ref_chunk_eval(seqs_i, seqs_l, scheme, num_types,
                               excluded)

        flat_i = np.concatenate(seqs_i).reshape(-1, 1).astype(np.int64)
        flat_l = np.concatenate(seqs_l).reshape(-1, 1).astype(np.int64)
        main, startup = Program(), Program()
        with fluid.program_guard(main, startup):
            iv = fluid.layers.data("i", shape=[1], dtype="int64",
                                   lod_level=1)
            lv = fluid.layers.data("l", shape=[1], dtype="int64",
                                   lod_level=1)
            outs = fluid.layers.chunk_eval(
                iv, lv, chunk_scheme=scheme, num_chunk_types=num_types,
                excluded_chunk_types=excluded)
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            res = exe.run(main,
                          feed={"i": create_lod_tensor(flat_i, [lens]),
                                "l": create_lod_tensor(flat_l, [lens])},
                          fetch_list=list(outs))
        got = (float(np.asarray(res[0])[0]), float(np.asarray(res[1])[0]),
               float(np.asarray(res[2])[0]), int(np.asarray(res[3])[0]),
               int(np.asarray(res[4])[0]), int(np.asarray(res[5])[0]))
        assert got[3:] == want[3:], (scheme, trial, seqs_i, seqs_l,
                                     excluded, got, want)
        np.testing.assert_allclose(got[:3], want[:3], atol=1e-6,
                                   err_msg=str((scheme, trial)))


def _ref_auc(batches, n, slide_steps):
    """Python restatement of metrics/auc_op.h statAuc+calcAuc."""
    window = []
    global_pos = np.zeros(n + 1, np.int64)
    global_neg = np.zeros(n + 1, np.int64)
    out = []
    for preds, labels in batches:
        hp = np.zeros(n + 1, np.int64)
        hn = np.zeros(n + 1, np.int64)
        for p, l in zip(preds, labels):
            b = int(p * n)
            if l:
                hp[b] += 1
            else:
                hn[b] += 1
        if slide_steps == 0:
            global_pos += hp
            global_neg += hn
            sp, sn = global_pos, global_neg
        else:
            window.append((hp, hn))
            window = window[-slide_steps:]
            sp = np.sum([w[0] for w in window], axis=0)
            sn = np.sum([w[1] for w in window], axis=0)
        tot_pos = tot_neg = auc = 0.0
        pp = nn_ = 0.0
        for idx in range(n, -1, -1):
            pp, nn_ = tot_pos, tot_neg
            tot_pos += sp[idx]
            tot_neg += sn[idx]
            auc += abs(tot_neg - nn_) * (tot_pos + pp) / 2.0
        out.append(auc / tot_pos / tot_neg
                   if tot_pos > 0 and tot_neg > 0 else auc)
    return out


@pytest.mark.parametrize("slide_steps", [0, 1, 3])
def test_auc_matches_reference_oracle(slide_steps):
    """Randomized parity vs metrics/auc_op.h across batches, including
    predictions that hit bucket n exactly (the top trapezoid) and the
    sliding-window batch-AUC mode."""
    rng = np.random.RandomState(7 + slide_steps)
    n = 32
    batches = []
    for _ in range(5):
        preds = rng.rand(16)
        preds[rng.rand(16) < 0.1] = 1.0       # exercise bucket n
        labels = (rng.rand(16) < 0.5).astype(np.int64)
        batches.append((preds, labels))

    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        pred = fluid.layers.data("pred", shape=[2], dtype="float32")
        lab = fluid.layers.data("lab", shape=[1], dtype="int64")
        g_auc, b_auc, _states = fluid.layers.auc(
            pred, lab, num_thresholds=n, slide_steps=slide_steps)
    exe = fluid.Executor(fluid.CPUPlace())
    want_global = _ref_auc(batches, n, 0)
    want_batch = _ref_auc(batches, n, slide_steps)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for i, (preds, labels) in enumerate(batches):
            p2 = np.stack([1 - preds, preds], axis=1).astype(np.float32)
            g, b = exe.run(main,
                           feed={"pred": p2,
                                 "lab": labels.reshape(-1, 1)},
                           fetch_list=[g_auc, b_auc])
            np.testing.assert_allclose(float(np.asarray(g)[0]),
                                       want_global[i], atol=1e-5,
                                       err_msg="global step %d" % i)
            np.testing.assert_allclose(float(np.asarray(b)[0]),
                                       want_batch[i], atol=1e-5,
                                       err_msg="batch step %d" % i)


def _ref_precision_recall(samples, C, prior=None):
    """precision_recall_op.h restated: samples = (idx, label, w)."""
    st = np.zeros((C, 4))                 # TP FP TN FN
    TP, FP, TN, FN = 0, 1, 2, 3
    for idx, lab, w in samples:
        if idx == lab:
            st[idx, TP] += w
            st[:, TN] += w
            st[idx, TN] -= w
        else:
            st[lab, FN] += w
            st[idx, FP] += w
            st[:, TN] += w
            st[idx, TN] -= w
            st[lab, TN] -= w

    def compute(states):
        def p(tp, fp):
            return tp / (tp + fp) if tp > 0 or fp > 0 else 1.0
        mp = np.mean([p(states[i, TP], states[i, FP]) for i in range(C)])
        mr = np.mean([p(states[i, TP], states[i, FN]) for i in range(C)])
        mf = 2 * mp * mr / (mp + mr) if mp > 0 or mr > 0 else 0.0
        tp_, fp_, fn_ = states[:, TP].sum(), states[:, FP].sum(), \
            states[:, FN].sum()
        up, ur = p(tp_, fp_), p(tp_, fn_)
        uf = 2 * up * ur / (up + ur) if up > 0 or ur > 0 else 0.0
        return [mp, mr, mf, up, ur, uf]

    batch = compute(st)
    if prior is not None:
        st = st + prior
    return batch, compute(st), st


def test_precision_recall_matches_reference_oracle():
    rng = np.random.RandomState(17)
    C = 4
    prior = None
    for step in range(3):
        n = 20
        idx = rng.randint(0, C, n)
        lab = rng.randint(0, C, n)
        w = rng.rand(n).astype(np.float32)
        want_b, want_a, want_st = _ref_precision_recall(
            list(zip(idx, lab, w)), C,
            prior if prior is not None else np.zeros((C, 4)))
        from paddle_tpu.ops.registry import get_op_def, ExecContext
        import jax.numpy as jnp

        class _Op:
            type = "precision_recall"
            outputs = {}
            attrs = {"class_number": C}
        vals = {"Indices": [jnp.asarray(idx.reshape(-1, 1))],
                "Labels": [jnp.asarray(lab.reshape(-1, 1))],
                "Weights": [jnp.asarray(w.reshape(-1, 1))],
                "StatesInfo": [jnp.asarray(
                    prior if prior is not None
                    else np.zeros((C, 4), np.float32))]}
        r = get_op_def("precision_recall").lower(ExecContext(_Op(), vals))
        np.testing.assert_allclose(np.asarray(r["BatchMetrics"]), want_b,
                                   atol=1e-5, err_msg="batch %d" % step)
        np.testing.assert_allclose(np.asarray(r["AccumMetrics"]), want_a,
                                   atol=1e-5, err_msg="accum %d" % step)
        prior = np.asarray(r["AccumStatesInfo"])
        np.testing.assert_allclose(prior, want_st, atol=1e-4)


def test_positive_negative_pair_matches_reference_oracle():
    """positive_negative_pair_op.h: same-query different-label pairs;
    ties add to neutral AND negative."""
    rng = np.random.RandomState(19)
    n = 24
    score = rng.rand(n).astype(np.float32)
    score[rng.rand(n) < 0.2] = 0.5                  # force ties
    label = rng.randint(0, 3, n).astype(np.float32)
    query = rng.randint(0, 4, n).astype(np.int64)
    w = rng.rand(n).astype(np.float32)
    pos = neg = neu = 0.0
    for i in range(n):
        for j in range(i + 1, n):
            if query[i] != query[j] or label[i] == label[j]:
                continue
            pw = (w[i] + w[j]) / 2.0
            if score[i] == score[j]:
                neu += pw
            if (score[i] - score[j]) * (label[i] - label[j]) > 0:
                pos += pw
            else:
                neg += pw
    from paddle_tpu.ops.registry import get_op_def, ExecContext
    import jax.numpy as jnp

    class _Op:
        type = "positive_negative_pair"
        outputs = {}
        attrs = {"column": 0}
    vals = {"Score": [jnp.asarray(score.reshape(-1, 1))],
            "Label": [jnp.asarray(label.reshape(-1, 1))],
            "QueryID": [jnp.asarray(query.reshape(-1, 1))],
            "Weight": [jnp.asarray(w.reshape(-1, 1))]}
    r = get_op_def("positive_negative_pair").lower(ExecContext(_Op(), vals))
    np.testing.assert_allclose(
        [float(np.asarray(r["PositivePair"]).reshape(-1)[0]),
         float(np.asarray(r["NegativePair"]).reshape(-1)[0]),
         float(np.asarray(r["NeutralPair"]).reshape(-1)[0])],
        [pos, neg, neu], atol=1e-4)


def test_mean_iou_streaming_inputs_match_reference():
    """mean_iou_op.h: InWrongs/InCorrects fold into the counts BEFORE
    the divide; InMeanIou sums ADD to the output mean."""
    from paddle_tpu.ops.registry import get_op_def, ExecContext
    import jax.numpy as jnp
    rng = np.random.RandomState(23)
    C = 5
    pred = rng.randint(0, C, 40)
    lab = rng.randint(0, C, 40)
    in_wrong = rng.randint(0, 6, C).astype(np.int32)
    in_correct = rng.randint(0, 6, C).astype(np.int32)
    in_mean = np.array([0.25], np.float32)

    wrong = in_wrong.copy()
    correct = in_correct.copy()
    for p, l in zip(pred, lab):
        if p == l:
            correct[p] += 1
        else:
            wrong[l] += 1
            wrong[p] += 1
    denom = wrong + correct
    valid = (denom > 0).sum()
    iou_sum = float(np.sum(correct / np.maximum(denom, 1)))
    want = in_mean[0] + iou_sum / valid

    class _Op:
        type = "mean_iou"
        outputs = {}
        attrs = {"num_classes": C}
    vals = {"Predictions": [jnp.asarray(pred.astype(np.int32))],
            "Labels": [jnp.asarray(lab.astype(np.int32))],
            "InWrongs": [jnp.asarray(in_wrong)],
            "InCorrects": [jnp.asarray(in_correct)],
            "InMeanIou": [jnp.asarray(in_mean)]}
    r = get_op_def("mean_iou").lower(ExecContext(_Op(), vals))
    np.testing.assert_allclose(
        float(np.asarray(r["OutMeanIou"])[0]), want, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(r["OutWrong"]), wrong)
    np.testing.assert_array_equal(np.asarray(r["OutCorrect"]), correct)


def _levenshtein(a, b):
    m, n = len(a), len(b)
    d = np.zeros((m + 1, n + 1), np.int64)
    d[:, 0] = np.arange(m + 1)
    d[0, :] = np.arange(n + 1)
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1,
                          d[i - 1, j - 1] + cost)
    return d[m, n]


@pytest.mark.parametrize("normalized", [False, True])
def test_edit_distance_matches_levenshtein_oracle(normalized):
    from paddle_tpu.ops.registry import get_op_def, ExecContext
    import jax.numpy as jnp
    rng = np.random.RandomState(29 + normalized)
    B, Th, Tr = 6, 9, 8
    hl = rng.randint(0, Th + 1, B)
    rl = rng.randint(1, Tr + 1, B)          # refs non-empty like the ref op
    hyp = rng.randint(0, 5, (B, Th)).astype(np.int64)
    ref = rng.randint(0, 5, (B, Tr)).astype(np.int64)
    ignored = [0]

    want = []
    for b in range(B):
        h = [t for t in hyp[b, :hl[b]] if t not in ignored]
        r = [t for t in ref[b, :rl[b]] if t not in ignored]
        d = float(len(h) if not r else
                  (len(r) if not h else _levenshtein(h, r)))
        if normalized and r:
            d /= len(r)
        want.append(d)

    class _Op:
        type = "edit_distance"
        outputs = {}
        attrs = {"normalized": normalized, "ignored_tokens": ignored}
    vals = {"Hyps": [jnp.asarray(hyp)], "Refs": [jnp.asarray(ref)],
            "Hyps@LOD_LEN": [jnp.asarray(hl.astype(np.int32))],
            "Refs@LOD_LEN": [jnp.asarray(rl.astype(np.int32))]}
    r = get_op_def("edit_distance").lower(ExecContext(_Op(), vals))
    got = np.asarray(r["Out"]).reshape(-1)
    np.testing.assert_allclose(got, want, atol=1e-5)
    assert int(np.asarray(r["SequenceNum"])[0]) == B


def test_linear_chain_crf_bruteforce_oracle():
    """Exact nll via path enumeration: logZ - score over ALL tag paths
    (the reference's scaled forward algorithm computes the same
    quantity, linear_chain_crf_op.h ll accumulation)."""
    from paddle_tpu.ops.registry import get_op_def, ExecContext
    import itertools
    import jax.numpy as jnp
    rng = np.random.RandomState(53)
    B, T, D = 3, 4, 3
    lens = np.array([4, 2, 3], np.int32)
    e = rng.randn(B, T, D).astype(np.float32)
    w = rng.randn(D + 2, D).astype(np.float32)
    lab = rng.randint(0, D, (B, T, 1)).astype(np.int64)

    start, end, pair = w[0], w[1], w[2:]
    want = []
    for b in range(B):
        L = lens[b]
        def path_score(path):
            s = start[path[0]] + end[path[-1]]
            for t, tag in enumerate(path):
                s += e[b, t, tag]
            for t in range(1, L):
                s += pair[path[t - 1], path[t]]
            return s
        scores = [path_score(p)
                  for p in itertools.product(range(D), repeat=int(L))]
        m = max(scores)
        log_z = m + np.log(sum(np.exp(s - m) for s in scores))
        gold = path_score(tuple(lab[b, :L, 0]))
        want.append(log_z - gold)

    class _Op:
        type = "linear_chain_crf"
        outputs = {}
        attrs = {}
    vals = {"Emission": [jnp.asarray(e)],
            "Emission@LOD_LEN": [jnp.asarray(lens)],
            "Transition": [jnp.asarray(w)],
            "Label": [jnp.asarray(lab)]}
    r = get_op_def("linear_chain_crf").lower(ExecContext(_Op(), vals))
    got = np.asarray(r["LogLikelihood"]).reshape(-1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_crf_decoding_bruteforce_oracle():
    """Viterbi path == brute-force argmax over all paths (ragged lens;
    padded positions emit 0)."""
    from paddle_tpu.ops.registry import get_op_def, ExecContext
    import itertools
    import jax.numpy as jnp
    rng = np.random.RandomState(59)
    B, T, D = 3, 4, 3
    lens = np.array([4, 1, 3], np.int32)
    e = rng.randn(B, T, D).astype(np.float32)
    w = rng.randn(D + 2, D).astype(np.float32)
    start, end, pair = w[0], w[1], w[2:]

    want = np.zeros((B, T), np.int64)
    for b in range(B):
        L = int(lens[b])
        best, best_p = -np.inf, None
        for p in itertools.product(range(D), repeat=L):
            s = start[p[0]] + end[p[-1]]
            for t, tag in enumerate(p):
                s += e[b, t, tag]
            for t in range(1, L):
                s += pair[p[t - 1], p[t]]
            if s > best:
                best, best_p = s, p
        want[b, :L] = best_p

    class _Op:
        type = "crf_decoding"
        outputs = {}
        attrs = {}
    vals = {"Emission": [jnp.asarray(e)],
            "Emission@LOD_LEN": [jnp.asarray(lens)],
            "Transition": [jnp.asarray(w)]}
    r = get_op_def("crf_decoding").lower(ExecContext(_Op(), vals))
    got = np.asarray(r["ViterbiPath"]).reshape(B, T)
    np.testing.assert_array_equal(got, want)


def test_warpctc_norm_by_times_scales_grad_not_loss():
    """warpctc_op.h: norm_by_times scales the GRADIENT by 1/T in the
    grad kernel; the Loss output stays raw."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.registry import get_op_def, ExecContext
    rng = np.random.RandomState(61)
    B, T, D = 2, 6, 5
    logits = rng.randn(B, T, D).astype(np.float32)
    labels = rng.randint(1, D, (B, 3)).astype(np.int32)
    in_lens = np.array([6, 4], np.int32)
    lab_lens = np.array([3, 2], np.int32)

    def run(x, norm):
        class _Op:
            type = "warpctc"
            outputs = {}
            attrs = {"norm_by_times": norm, "blank": 0}
        vals = {"Logits": [x], "Label": [jnp.asarray(labels)],
                "Logits@LOD_LEN": [jnp.asarray(in_lens)],
                "Label@LOD_LEN": [jnp.asarray(lab_lens)]}
        return get_op_def("warpctc").lower(
            ExecContext(_Op(), vals))["Loss"]

    raw = np.asarray(run(jnp.asarray(logits), False))
    normed = np.asarray(run(jnp.asarray(logits), True))
    np.testing.assert_allclose(normed, raw, atol=1e-5)   # value unscaled

    g_raw = jax.grad(lambda x: jnp.sum(run(x, False)))(jnp.asarray(logits))
    g_norm = jax.grad(lambda x: jnp.sum(run(x, True)))(jnp.asarray(logits))
    for b in range(B):
        np.testing.assert_allclose(np.asarray(g_norm[b]),
                                   np.asarray(g_raw[b]) / in_lens[b],
                                   atol=1e-6)
