"""hierarchical_sigmoid reference oracle (hierarchical_sigmoid_op.h +
matrix_bit_code.h SimpleCode restated): node id c = label +
num_classes, path length = FindLastSet(c) - 1, edge j has internal
node (c >> (j+1)) - 1 and branch bit c & (1 << j); per-edge loss is
softplus(pre) - bit*pre with pre clipped to [-40, 40]."""

import numpy as np
import pytest

from tests.test_op_tail import run_op


def oracle(x, w, bias, labels, C):
    B = x.shape[0]
    loss = np.zeros(B, np.float64)
    for b in range(B):
        c = int(labels[b]) + C
        length = c.bit_length() - 1          # FindLastSet(c) - 1
        for j in range(length):
            node = (c >> (j + 1)) - 1
            bit = 1 if (c & (1 << j)) else 0
            pre = float(x[b] @ w[node])
            if bias is not None:
                pre += float(bias[node])
            pre = np.clip(pre, -40.0, 40.0)
            loss[b] += np.log1p(np.exp(pre)) - bit * pre
    return loss.astype(np.float32)


@pytest.mark.parametrize("C", [6, 8, 13])   # non-powers and a power of 2
def test_hsigmoid_matches_bit_code_reference(C):
    rng = np.random.RandomState(C)
    B, D = 5, 4
    x = rng.randn(B, D).astype(np.float32)
    w = rng.randn(C - 1, D).astype(np.float32)
    bias = rng.randn(C - 1, 1).astype(np.float32)
    labels = np.arange(B).astype(np.int64) % C
    out = run_op("hierarchical_sigmoid",
                 {"X": x, "W": w, "Bias": bias,
                  "Label": labels[:, None]},
                 {"num_classes": C})
    np.testing.assert_allclose(np.asarray(out["Out"]).ravel(),
                               oracle(x, w, bias.ravel(), labels, C),
                               atol=1e-4, rtol=1e-4)
