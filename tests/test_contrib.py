"""contrib Trainer / QuantizeTranspiler / evaluators / debugger tests
(reference unittests test_trainer*, test_quantize_transpiler.py,
test_chunk_eval_op.py + evaluator usage, debugger smoke)."""

import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program


def test_trainer_events_and_checkpoint(tmp_path):
    rng = np.random.RandomState(0)
    data = [(rng.randn(4).astype(np.float32),
             np.array([rng.randn() * 0.1 + x.sum()], np.float32))
            for x in [rng.randn(4).astype(np.float32) for _ in range(8)]]
    # simple regression samples: y ~ sum(x)
    data = [(x, np.array([x.sum()], np.float32))
            for x, _ in data]

    def train_func():
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, size=1)
        return fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))

    def optimizer_func():
        return fluid.optimizer.SGD(learning_rate=0.05)

    ckpt_dir = str(tmp_path / "ckpt")
    cfg = fluid.contrib.CheckpointConfig(checkpoint_dir=ckpt_dir,
                                         step_interval=4)
    trainer = fluid.contrib.Trainer(train_func, optimizer_func,
                                    place=fluid.CPUPlace(),
                                    checkpoint_config=cfg)
    events = []
    losses = []

    def handler(ev):
        events.append(type(ev).__name__)
        if isinstance(ev, fluid.contrib.EndStepEvent):
            losses.append(float(np.asarray(ev.metrics[0]).flatten()[0]))

    def reader():
        for x, y in data:
            yield [(x, y)]

    trainer.train(num_epochs=2, event_handler=handler, reader=reader,
                  feed_order=["x", "y"])
    assert "BeginEpochEvent" in events and "EndStepEvent" in events
    assert losses[-1] < losses[0]
    assert os.path.isdir(ckpt_dir)
    # resume: new trainer picks up the checkpoint without error
    t2 = fluid.contrib.Trainer(train_func, optimizer_func,
                               place=fluid.CPUPlace(),
                               checkpoint_config=fluid.contrib.
                               CheckpointConfig(checkpoint_dir=ckpt_dir))
    assert t2.checkpoint_cfg.step_id > 0


def test_quantize_transpiler_training():
    rng = np.random.RandomState(1)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu")
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)

    qt = fluid.contrib.QuantizeTranspiler()
    qt.training_transpile(main, startup)
    qops = [op for op in main.global_block().ops
            if op.type == "fake_quantize_dequantize_abs_max"]
    assert len(qops) >= 2   # at least both mul inputs quantized
    # mul ops consume the quantized names
    for op in main.global_block().ops:
        if op.type == "mul":
            assert op.inputs["Y"][0].endswith(".quantized.dequantized")

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xs = rng.randn(16, 8).astype(np.float32)
    ys = xs.sum(axis=1, keepdims=True)
    losses = []
    for _ in range(12):
        (l,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        losses.append(float(np.asarray(l).flatten()[0]))
        assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0]   # STE gradients train through quant


def test_memory_usage():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[100], dtype="float32")
        fluid.layers.fc(x, size=50)
    lo, hi = fluid.contrib.memory_usage(main, batch_size=32)
    assert 0 < lo < hi


def test_weighted_average():
    wa = fluid.average.WeightedAverage()
    wa.add(2.0, 1.0)
    wa.add(4.0, 3.0)
    np.testing.assert_allclose(wa.eval(), 3.5)
    wa.reset()
    with pytest.raises(ValueError):
        wa.eval()


def test_debugger_dot_output(tmp_path):
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        fluid.layers.fc(x, size=2)
    path = str(tmp_path / "g.dot")
    dot = fluid.debugger.draw_block_graphviz(main.global_block(), path=path)
    assert os.path.exists(path)
    assert "digraph G" in dot and "mul" in dot
    code = fluid.debugger.pprint_program_codes(main)
    assert "mul" in code and "var x" in code


def test_edit_distance_evaluator():
    from paddle_tpu.fluid.lod import create_lod_tensor
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        hyp = fluid.layers.data("hyp", shape=[1], dtype="int64",
                                lod_level=1)
        ref = fluid.layers.data("ref", shape=[1], dtype="int64",
                                lod_level=1)
        ed = fluid.evaluator.EditDistance(hyp, ref)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    ed.reset(exe)
    h = np.array([[1], [2], [3]], np.int64)
    r = np.array([[1], [2], [4]], np.int64)
    exe.run(main, feed={"hyp": create_lod_tensor(h, [[3]]),
                        "ref": create_lod_tensor(r, [[3]])},
            fetch_list=[])
    avg_dist, err_rate = ed.eval(exe)
    np.testing.assert_allclose(avg_dist, [1.0 / 3.0], atol=1e-5)
    np.testing.assert_allclose(err_rate, [1.0], atol=1e-5)
