"""paddle.utils parity (reference python/paddle/utils/): config dump,
model merge round-trip, Ploter, image_util."""

import numpy as np

import paddle_tpu.v2 as paddle
import paddle_tpu.utils as utils
from paddle_tpu.trainer_config_helpers import layers as v1


def _tiny_net():
    x = v1.data_layer(name="ux", size=4)
    h = v1.fc_layer(input=x, size=6, act=paddle.activation.Tanh())
    return v1.fc_layer(input=h, size=2, act=paddle.activation.Softmax())


def test_dump_v2_config_round_trips():
    out = _tiny_net()
    text = utils.dump_v2_config(out)
    from paddle_tpu.fluid.framework import Program
    prog = Program.parse_from_string(text)
    types = [op.type for blk in prog.blocks for op in blk.ops]
    assert "softmax" in types and any("mul" in t or "matmul" in t
                                      for t in types), types


def test_merge_v2_model_round_trip(tmp_path):
    out = _tiny_net()
    params = paddle.parameters.create(out)
    pf = str(tmp_path / "params.tar")
    with open(pf, "wb") as f:
        params.to_tar(f)
    merged = str(tmp_path / "model.paddle")
    utils.merge_v2_model(out, pf, merged)

    from paddle_tpu.utils.merge_model import load_merged_model
    prog, params2 = load_merged_model(merged)
    assert set(params.names()) == set(params2.names())
    for n in params.names():
        np.testing.assert_array_equal(params.get(n), params2.get(n))
    # merged program carries the same parameter names
    pvars = {p.name for p in prog.all_parameters()}
    assert set(params.names()) <= pvars


def test_ploter_and_image_util_importable():
    p = utils.Ploter("train", "test")
    p.append("train", 0, 1.0)
    p.append("train", 1, 0.5)
    assert hasattr(utils.image_util, "load_image")
