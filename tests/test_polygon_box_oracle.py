"""polygon_box_transform reference oracle: the parity that picks the
4*w vs 4*h base is the reference's COMBINED n*C + c loop counter
(polygon_box_transform_op.cc:39-47), which differs from channel parity
whenever C is odd — pinned bug-for-bug."""

import numpy as np
import pytest

from tests.test_op_tail import run_op


def oracle(x):
    N, C, H, W = x.shape
    out = np.empty_like(x)
    for n in range(N):
        for c in range(C):
            for h in range(H):
                for w in range(W):
                    base = w * 4 if (n * C + c) % 2 == 0 else h * 4
                    out[n, c, h, w] = base - x[n, c, h, w]
    return out


@pytest.mark.parametrize("C", [8, 3])   # even (real geometry) and odd
def test_polygon_box_transform_matches_reference(C):
    x = np.random.RandomState(2).randn(2, C, 3, 4).astype(np.float32)
    out = run_op("polygon_box_transform", {"Input": x}, {})
    np.testing.assert_allclose(np.asarray(out["Output"]), oracle(x),
                               atol=1e-5)
