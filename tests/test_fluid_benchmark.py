"""The benchmark harness (reference benchmark/fluid/fluid_benchmark.py)
drives every zoo model end to end with synthetic data and reports
examples/sec as one JSON line."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(*extra):
    cmd = [sys.executable, os.path.join(REPO, "tools", "fluid_benchmark.py"),
           "--device", "CPU", "--iterations", "3", "--skip_batch_num", "1",
           *extra]
    out = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                         cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    line = out.stdout.strip().splitlines()[-1]
    return json.loads(line)


def test_benchmark_mnist_local():
    r = _run("--model", "mnist", "--batch_size", "16")
    assert r["model"] == "mnist" and r["device"] == "cpu"
    assert r["examples_per_sec"] > 0
    assert r["last_loss"] == r["last_loss"]  # finite (json would be null)


def test_benchmark_lstm_ragged_feeds():
    r = _run("--model", "stacked_dynamic_lstm", "--batch_size", "8")
    assert r["examples_per_sec"] > 0


def test_benchmark_parallel_mode():
    r = _run("--model", "mnist", "--batch_size", "16", "--parallel")
    assert r["parallel"] is True
    assert r["examples_per_sec"] > 0


def test_benchmark_pserver_mode_cluster():
    """--update_method pserver: the harness reads the reference's
    PADDLE_* env-var role wiring (fluid_benchmark.py:84-86) — launch one
    pserver + one trainer as real subprocesses."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    ep = "127.0.0.1:%d" % port
    base_env = dict(os.environ, PADDLE_PSERVER_EPS=ep,
                    PADDLE_TRAINERS="1", JAX_PLATFORMS="cpu")
    cmd = [sys.executable,
           os.path.join(REPO, "tools", "fluid_benchmark.py"),
           "--device", "CPU", "--model", "mnist", "--batch_size", "8",
           "--iterations", "3", "--skip_batch_num", "1",
           "--update_method", "pserver"]
    ps = subprocess.Popen(
        cmd, env=dict(base_env, PADDLE_TRAINING_ROLE="PSERVER",
                      PADDLE_CURRENT_ENDPOINT=ep),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        cwd=REPO)
    try:
        tr = subprocess.run(
            cmd, env=dict(base_env, PADDLE_TRAINING_ROLE="TRAINER",
                          PADDLE_TRAINER_ID="0"),
            capture_output=True, text=True, timeout=420, cwd=REPO)
        assert tr.returncode == 0, tr.stderr[-2000:]
        rec = json.loads(tr.stdout.strip().splitlines()[-1])
        assert rec["update_method"] == "pserver"
        assert rec["examples_per_sec"] > 0
        ps.wait(timeout=60)   # trainer 0's exit notification stops it
    finally:
        if ps.poll() is None:
            ps.kill()
