"""The benchmark harness (reference benchmark/fluid/fluid_benchmark.py)
drives every zoo model end to end with synthetic data and reports
examples/sec as one JSON line."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(*extra):
    cmd = [sys.executable, os.path.join(REPO, "tools", "fluid_benchmark.py"),
           "--device", "CPU", "--iterations", "3", "--skip_batch_num", "1",
           *extra]
    out = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                         cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    line = out.stdout.strip().splitlines()[-1]
    return json.loads(line)


def test_benchmark_mnist_local():
    r = _run("--model", "mnist", "--batch_size", "16")
    assert r["model"] == "mnist" and r["device"] == "cpu"
    assert r["examples_per_sec"] > 0
    assert r["last_loss"] == r["last_loss"]  # finite (json would be null)


def test_benchmark_lstm_ragged_feeds():
    r = _run("--model", "stacked_dynamic_lstm", "--batch_size", "8")
    assert r["examples_per_sec"] > 0


def test_benchmark_parallel_mode():
    r = _run("--model", "mnist", "--batch_size", "16", "--parallel")
    assert r["parallel"] is True
    assert r["examples_per_sec"] > 0
