"""v1 trainer_config_helpers DSL as a REAL layer (VERDICT r3 #5):
ExtraLayerAttribute kwarg translation, the mixed_layer projection/
operator model, and the round-4 gserver layer tail — exercised through
v1 spellings end to end (reference
python/paddle/trainer_config_helpers/layers.py)."""

import numpy as np

import paddle_tpu.v2 as paddle
import paddle_tpu.trainer_config_helpers as tch
from paddle_tpu.trainer_config_helpers import layers as v1


def _train(cost, feeder, passes=6, lr=0.1):
    params = paddle.parameters.create(cost)
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=lr))
    losses = []

    def on_event(event):
        if isinstance(event, paddle.event.EndIteration):
            losses.append(float(event.cost))

    tr.train(reader=feeder, num_passes=passes, event_handler=on_event)
    return losses, params


def test_v1_extra_attr_and_mixed_projections_train():
    """THE round-3 done-criterion: a v1-spelling model using
    ExtraLayerAttribute(drop_rate=...) on a layer plus mixed_layer with
    full_matrix + dotmul projections trains and converges."""
    x = v1.data_layer(name="x", type=paddle.data_type.dense_vector(6))
    hid = v1.fc_layer(
        input=x, size=12,
        act=paddle.activation.Tanh(),
        layer_attr=v1.ExtraLayerAttribute(drop_rate=0.05,
                                          error_clipping_threshold=5.0))
    mix = v1.mixed_layer(
        size=12,
        input=[v1.full_matrix_projection(input=hid, size=12),
               v1.dotmul_projection(input=hid)],
        act=paddle.activation.Relu(),
        bias_attr=v1.ParamAttr(name="mix_b"))
    out = v1.fc_layer(input=mix, size=2,
                      act=paddle.activation.Softmax())
    lbl = v1.data_layer(name="lbl",
                        type=paddle.data_type.integer_value(2))
    cost = v1.classification_cost(input=out, label=lbl)

    rng = np.random.RandomState(0)

    def reader():
        for _ in range(64):
            v = rng.randn(6).astype(np.float32)
            yield v, int(v.sum() > 0)

    losses, params = _train(cost, paddle.batch(reader, 16), passes=10)
    # dropout is live, so compare epoch means
    first = np.mean(losses[: len(losses) // 3])
    last = np.mean(losses[-len(losses) // 3:])
    assert last < 0.7 * first, (first, last)
    # the mixed_layer projections own parameters; the dotmul weight is
    # a [1, 12] vector
    shapes = {n: params.get_shape(n) for n in params.keys()}
    assert any(s == (1, 12) for s in shapes.values()), shapes


def test_v1_dropout_attr_emits_dropout_op():
    x = v1.data_layer(name="xa", type=paddle.data_type.dense_vector(4))
    h = v1.fc_layer(input=x, size=3,
                    layer_attr=v1.ExtraAttr(drop_rate=0.5))
    topo = paddle.topology.Topology([h])
    types = [op.type for op in topo.main_program.global_block().ops]
    assert "dropout" in types


def test_v1_error_clip_attr_clips_gradient():
    import paddle_tpu.fluid as fluid
    x = v1.data_layer(name="xc", type=paddle.data_type.dense_vector(4))
    h = v1.fc_layer(input=x, size=3,
                    layer_attr=v1.ExtraAttr(
                        error_clipping_threshold=0.25))
    out = v1.fc_layer(input=h, size=1)
    lbl = v1.data_layer(name="yc", type=paddle.data_type.dense_vector(1))
    cost = v1.square_error_cost(input=out, label=lbl)
    topo = paddle.topology.Topology([cost])
    main = topo.main_program
    types = [op.type for op in main.global_block().ops]
    assert "clip" not in types  # forward has no clip...
    with fluid.program_guard(main, topo.startup_program):
        fluid.backward.append_backward(topo.var_for(cost))
    types = [op.type for op in main.global_block().ops]
    assert "clip" in types      # ...backward clips the layer's error


def test_v1_mixed_operators_and_more_projections():
    """conv-free operator/projection coverage: dotmul_operator,
    scaling/trans/context/slice projections all build and run."""
    x = v1.data_layer(name="xo", type=paddle.data_type.dense_vector(8))
    a = v1.fc_layer(input=x, size=8, act=paddle.activation.Tanh())
    b = v1.fc_layer(input=x, size=8, act=paddle.activation.Tanh())
    mix = v1.mixed_layer(
        size=8,
        input=[v1.dotmul_operator(a=a, b=b, scale=0.5),
               v1.scaling_projection(input=a),
               v1.trans_full_matrix_projection(input=b, size=8),
               v1.slice_projection(input=a, slices=[(0, 4), (4, 8)])])
    out = v1.fc_layer(input=mix, size=2,
                      act=paddle.activation.Softmax())
    params = paddle.parameters.create(out)
    probs = paddle.infer(
        output_layer=out, parameters=params,
        input=[(np.random.RandomState(1).randn(8).astype(np.float32),)])
    assert probs.shape == (1, 2)
    assert np.allclose(probs.sum(), 1.0, atol=1e-4)


def test_v1_gserver_tail_layers_run():
    """The newly-added tail, built and executed through paddle.infer."""
    rng = np.random.RandomState(2)
    x = v1.data_layer(name="xt", type=paddle.data_type.dense_vector(12))
    y = v1.data_layer(name="yt", type=paddle.data_type.dense_vector(12))
    w = v1.data_layer(name="wt", type=paddle.data_type.dense_vector(1))
    outs = [
        v1.cos_sim(a=x, b=y),
        v1.interpolation_layer(input=[x, y], weight=w),
        v1.sum_to_one_norm_layer(input=x),
        v1.dot_prod_layer(a=x, b=y),
        v1.l2_distance_layer(a=x, b=y),
        v1.out_prod_layer(a=w, b=w),
        v1.clip_layer(input=x, min=-0.5, max=0.5),
        v1.resize_layer(input=x, size=6),
        v1.repeat_layer(input=w, num_repeats=3),
        v1.scale_shift_layer(input=x),
        v1.gated_unit_layer(input=x, size=5),
        v1.linear_comb_layer(weights=v1.fc_layer(input=x, size=3),
                             vectors=x, size=4),
    ]
    xv = rng.randn(12).astype(np.float32)
    yv = rng.randn(12).astype(np.float32)
    wv = np.array([0.3], np.float32)
    vals = {"xt": xv, "yt": yv, "wt": wv}

    def run(layer):
        topo = paddle.topology.Topology([layer])
        names = [n for n, _ in topo.data_type()]
        p = paddle.parameters.create(layer)
        return paddle.infer(output_layer=layer, parameters=p,
                            input=[tuple(vals[n] for n in names)])

    for layer in outs:
        got = run(layer)
        assert np.all(np.isfinite(got)), layer
    # power needs a positive base (x**0.3 is NaN for x<0, as in the
    # reference's PowerLayer)
    vals["xt"] = np.abs(xv) + 0.1
    pw = run(v1.power_layer(input=x, weight=w))
    np.testing.assert_allclose(np.asarray(pw).ravel(),
                               (np.abs(xv) + 0.1) ** 0.3, rtol=1e-4)
    vals["xt"] = xv
    # maxout wants conv-shaped [C, H, W] input (reference MaxOutLayer)
    xi = v1.data_layer(name="xi",
                       type=paddle.data_type.dense_vector(16),
                       height=2, width=2)
    mo = v1.maxout_layer(input=xi, groups=2, num_channels=4)
    p = paddle.parameters.create(mo)
    got = paddle.infer(output_layer=mo, parameters=p,
                       input=[(rng.randn(16).astype(np.float32),)])
    assert np.all(np.isfinite(got)) and np.asarray(got).size == 8
    # numeric spot checks
    cs = run(outs[0])
    want = xv.dot(yv) / (np.linalg.norm(xv) * np.linalg.norm(yv))
    np.testing.assert_allclose(np.asarray(cs).ravel()[0], want,
                               rtol=1e-4)
    sn = run(outs[2])
    np.testing.assert_allclose(np.asarray(sn).sum(), 1.0, rtol=1e-4)


def test_v1_conv_projections_and_image_tail():
    """conv_projection/conv_operator inside mixed_layer + the image tail
    (bilinear_interp, pad, crop, block_expand, prelu, norm)."""
    rng = np.random.RandomState(4)
    img = v1.data_layer(name="im",
                        type=paddle.data_type.dense_vector(2 * 8 * 8),
                        height=8, width=8)
    # conv_projection: conv with its own filter param as a projection
    mix = v1.mixed_layer(
        input=[v1.conv_projection(input=img, filter_size=3,
                                  num_filters=4, num_channels=2,
                                  padding=1)])
    bi = v1.bilinear_interp_layer(input=mix, out_size_x=4, out_size_y=4)
    pd = v1.pad_layer(input=bi, pad_c=[0, 1], pad_h=[1, 1],
                      pad_w=[0, 0])
    pr = v1.prelu_layer(input=mix)
    nm = v1.cross_channel_norm_layer(input=mix)
    be = v1.block_expand_layer(input=mix, block_x=2, block_y=2,
                               num_channels=4)
    cr = v1.crop_layer(input=mix, offset=[0, 0, 2, 2],
                       shape=[-1, 4, 4, 4])
    # conv_operator: filter values produced by another LAYER
    filt = v1.fc_layer(input=v1.data_layer(
        name="fseed", type=paddle.data_type.dense_vector(4)),
        size=4 * 2 * 3 * 3)
    co = v1.mixed_layer(
        input=[v1.conv_operator(img=img, filter=filt, filter_size=3,
                                num_filters=4, num_channels=2,
                                padding=1)])
    imv = rng.randn(2 * 8 * 8).astype(np.float32)
    fsv = rng.randn(4).astype(np.float32)
    for layer in [mix, bi, pd, pr, nm, be, cr, co]:
        topo = paddle.topology.Topology([layer])
        names = [n for n, _ in topo.data_type()]
        vals = {"im": imv, "fseed": fsv}
        p = paddle.parameters.create(layer)
        got = paddle.infer(output_layer=layer, parameters=p,
                           input=[tuple(vals[n] for n in names)])
        assert np.all(np.isfinite(np.asarray(got))), layer


def test_v1_context_projection_window():
    """context_projection concatenates the +-1 word window with zero
    padding at sequence edges (reference ContextProjection)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.lod import create_lod_tensor
    seq = v1.data_layer(
        name="s", type=paddle.data_type.dense_vector_sequence(2))
    ctx = v1.mixed_layer(
        input=[v1.context_projection(input=seq, context_len=3)])
    topo = paddle.topology.Topology([ctx])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(topo.startup_program)
        vals = np.arange(8, dtype=np.float32).reshape(4, 2)
        feed = {"s": create_lod_tensor(vals, [[3, 1]])}
        (out,) = exe.run(topo.main_program, feed=feed,
                         fetch_list=[topo.var_for(ctx)],
                         return_numpy=False)
    got = np.asarray(out).reshape(-1, 6)   # ragged flat [sum_len, 6]
    # sequence 1 = rows 0..2; window at t=0: [zeros, row0, row1]
    np.testing.assert_allclose(got[0], np.r_[0, 0, vals[0], vals[1]])
    np.testing.assert_allclose(got[1],
                               np.r_[vals[0], vals[1], vals[2]])
    np.testing.assert_allclose(got[2], np.r_[vals[1], vals[2], 0, 0])
    # sequence 2 = row 3, a single step: both context slots zero
    np.testing.assert_allclose(got[3], np.r_[0, 0, vals[3], 0, 0])


def test_v1_tch_namespace_exports_tail():
    for name in ["cos_sim", "interpolation_layer", "power_layer",
                 "maxout_layer", "block_expand_layer", "crop_layer",
                 "prelu_layer", "row_conv_layer", "context_projection",
                 "dotmul_operator", "conv_operator", "conv_projection",
                 "ExtraLayerAttribute"]:
        assert hasattr(tch, name) or hasattr(v1, name), name
        assert getattr(v1, name) is not None


def test_conv_operator_per_sample_filters_batch2():
    """conv_operator uses PER-SAMPLE dynamic filters (reference
    ConvOperator): with batch 2 each sample must be convolved with its
    own filter values, and the whole batch runs as one grouped conv."""
    rng = np.random.RandomState(11)
    img = v1.data_layer(name="im2",
                        type=paddle.data_type.dense_vector(2 * 5 * 5),
                        height=5, width=5)
    filt = v1.fc_layer(input=v1.data_layer(
        name="fs2", type=paddle.data_type.dense_vector(6)),
        size=3 * 2 * 3 * 3, bias_attr=False)
    co = v1.mixed_layer(
        input=[v1.conv_operator(img=img, filter=filt, filter_size=3,
                                num_filters=3, num_channels=2,
                                padding=1)])
    p = paddle.parameters.create(co)
    ims = rng.randn(2, 2 * 5 * 5).astype(np.float32)
    fss = rng.randn(2, 6).astype(np.float32)
    got = np.asarray(paddle.infer(
        output_layer=co, parameters=p,
        input=[(ims[0], fss[0]), (ims[1], fss[1])])).reshape(2, 3, 5, 5)

    # oracle: per-sample scipy-style conv via explicit numpy
    w_fc = p.get(sorted(n for n in p.names() if "fc" in n or "w" in n)[0])
    filt_vals = fss @ np.asarray(w_fc, np.float32)      # [2, 54]
    import jax.numpy as jnp
    import jax
    for b in range(2):
        w = filt_vals[b].reshape(3, 2, 3, 3)
        want = jax.lax.conv_general_dilated(
            jnp.asarray(ims[b].reshape(1, 2, 5, 5)), jnp.asarray(w),
            (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        np.testing.assert_allclose(got[b], np.asarray(want)[0],
                                   rtol=2e-4, atol=2e-5)
