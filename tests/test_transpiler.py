"""Transpiler tests.

Mirrors the reference's test_dist_transpiler.py (asserts transpiled program
structure) and test_memory_optimization_transpiler.py, plus an executable
in-process pserver cluster (the reference needed subprocesses + real gRPC;
the TCP variable server here runs fine in threads) checking loss parity with
local training — the test_dist_base.py:299 _run_cluster strategy.
"""

import threading

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.transpiler import (
    DistributeTranspiler, DistributeTranspilerConfig, slice_variable,
    memory_optimize, release_memory, InferenceTranspiler)
from paddle_tpu.fluid.transpiler.ps_dispatcher import RoundRobin, HashName


def _build_net(seed=7):
    main = fluid.Program()
    startup = fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        fc = fluid.layers.fc(input=x, size=8, act="relu")
        pred = fluid.layers.fc(input=fc, size=1, act=None)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        sgd = fluid.optimizer.SGD(learning_rate=0.05)
        sgd.minimize(loss)
    return main, startup, loss


class TestSliceVariable:
    def test_small_vars_one_block(self):
        main, _, _ = _build_net()
        params = main.all_parameters()
        blocks = slice_variable(params, 4, 8192)
        # all vars are tiny -> one block each
        for bs in blocks:
            assert len(bs) == 1

    def test_large_var_splits(self):
        p = fluid.Program()
        with fluid.program_guard(p, fluid.Program()):
            v = fluid.layers.create_parameter(shape=[1000, 100],
                                              dtype="float32", name="bigw")
        blocks = slice_variable([v], 4, 8192)[0]
        assert len(blocks) > 1
        assert sum(b.size for b in blocks) == 1000 * 100
        # row alignment: every block but the last is a multiple of dim1
        for b in blocks[:-1]:
            assert b.size % 100 == 0


class TestDispatchers:
    def test_round_robin(self):
        eps = ["127.0.0.1:6170", "127.0.0.1:6171"]

        class V:
            def __init__(self, n):
                self._n = n

            def name(self):
                return self._n

        d = RoundRobin(eps)
        got = d.dispatch([V("a"), V("b"), V("c")])
        assert got == [eps[0], eps[1], eps[0]]

    def test_hash_name_deterministic(self):
        eps = ["127.0.0.1:6170", "127.0.0.1:6171"]

        class V:
            def __init__(self, n):
                self._n = n

            def name(self):
                return self._n

        d = HashName(eps)
        a = d.dispatch([V("w1"), V("w2")])
        b = d.dispatch([V("w1"), V("w2")])
        assert a == b


class TestDistTranspilerStructure:
    def test_trainer_program(self):
        main, startup, _ = _build_net()
        config = DistributeTranspilerConfig()
        t = DistributeTranspiler(config=config)
        t.transpile(trainer_id=0, program=main,
                    pservers="127.0.0.1:6174,127.0.0.1:6175", trainers=2,
                    startup_program=startup)
        trainer = t.get_trainer_program()
        types = [op.type for op in trainer.global_block().ops]
        # optimizer ops moved out
        assert "sgd" not in types
        # rpc ops appended in protocol order
        assert types[-4:] == ["send", "send_barrier", "recv",
                              "fetch_barrier"]
        send_op = trainer.global_block().ops[-4]
        assert all(n.endswith("@GRAD") for n in send_op.input("X"))

    def test_oversize_var_fails_at_transpile(self, monkeypatch):
        """A param bigger than the RPC frame cap travels whole-var over
        the wire; the transpiler must fail up front naming the variable
        and the env var, not deep in the socket layer at step time."""
        import paddle_tpu.distributed.rpc as rpc
        monkeypatch.setattr(rpc, "_MAX_FRAME", 1 << 10)
        main, startup, _ = _build_net()
        t = DistributeTranspiler()
        with pytest.raises(ValueError) as ei:
            t.transpile(trainer_id=0, program=main,
                        pservers="127.0.0.1:6174", trainers=2,
                        startup_program=startup)
        assert "PADDLE_TPU_MAX_RPC_FRAME" in str(ei.value)

    def test_pserver_program(self):
        main, startup, _ = _build_net()
        t = DistributeTranspiler()
        eps = "127.0.0.1:6176,127.0.0.1:6177"
        t.transpile(trainer_id=0, program=main, pservers=eps, trainers=2,
                    startup_program=startup)
        total_params = 0
        for ep in eps.split(","):
            ps = t.get_pserver_program(ep)
            ops = ps.global_block().ops
            assert ops[-1].type == "listen_and_serv"
            blocks = ops[-1].attr("optimize_blocks")
            params = ops[-1].attr("param_names")
            total_params += len(params)
            for bid in blocks:
                btypes = [op.type for op in ps.blocks[bid].ops]
                assert "sgd" in btypes
            # startup program creates exactly the assigned params (+state)
            sp = t.get_startup_program(ep, ps, startup_program=startup)
            created = set()
            for op in sp.global_block().ops:
                created.update(op.output_arg_names)
            for p in params:
                assert p in created
        # every param assigned somewhere
        assert total_params == len(main.all_parameters())

    def test_collective_mode(self):
        main, startup, _ = _build_net()
        config = DistributeTranspilerConfig()
        config.mode = "collective"
        t = DistributeTranspiler(config=config)
        t.transpile(trainer_id=1, program=main, trainers=4,
                    startup_program=startup)
        types = [op.type for op in startup.global_block().ops]
        assert "gen_collective_id" in types
        assert main._num_trainers == 4
        assert main._trainer_id == 1
        # trainer program unchanged (grads reduced by mesh collectives)
        ttypes = [op.type for op in t.get_trainer_program()
                  .global_block().ops]
        assert "send" not in ttypes and "sgd" in ttypes


class TestDistTrainingParity:
    """In-process 2-pserver x 2-trainer sync cluster vs local run
    (reference test_dist_mnist.py:26 check_with_place, delta loss check)."""

    def _local_losses(self, steps, data):
        main, startup, loss = _build_net(seed=11)
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            losses = []
            for i in range(steps):
                x, y = data[i]
                # average of two half-batch grads == full-batch grad for
                # this loss; feed the full batch locally
                lv, = exe.run(main, feed={"x": x, "y": y},
                              fetch_list=[loss])
                losses.append(float(lv))
        return losses

    def test_sync_pserver_matches_local(self):
        rng = np.random.RandomState(3)
        steps = 4
        data = []
        for _ in range(steps):
            x = rng.randn(8, 4).astype(np.float32)
            w = np.array([[1.0], [-2.0], [0.5], [0.3]], np.float32)
            y = x.dot(w) + 0.1
            data.append((x, y))

        local = self._local_losses(steps, data)

        # --- build + transpile one program per role
        eps = "127.0.0.1:0"  # port 0: server picks a free port
        main, startup, loss = _build_net(seed=11)
        t = DistributeTranspiler()
        t.transpile(trainer_id=0, program=main, pservers="127.0.0.1:6199",
                    trainers=2, startup_program=startup)
        del eps

        # start pserver in a thread: run startup then listen_and_serv
        ps_prog = t.get_pserver_program("127.0.0.1:6199")
        ps_startup = t.get_startup_program("127.0.0.1:6199", ps_prog,
                                           startup_program=startup)

        ps_scope = fluid.Scope()
        server_exc = []

        def run_pserver():
            try:
                exe = fluid.Executor(fluid.CPUPlace())
                with fluid.scope_guard(ps_scope):
                    exe.run(ps_startup)
                    exe.run(ps_prog)
            except Exception as e:  # pragma: no cover
                server_exc.append(e)

        th = threading.Thread(target=run_pserver, daemon=True)
        th.start()
        from paddle_tpu.distributed.rpc import wait_server_ready
        wait_server_ready(["127.0.0.1:6199"])

        trainer_prog = t.get_trainer_program()

        # trainers share the same init (params broadcast from startup)
        def run_trainer(tid, out):
            exe = fluid.Executor(fluid.CPUPlace())
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe.run(startup)
                for i in range(steps):
                    x, y = data[i]
                    half = slice(tid * 4, (tid + 1) * 4)
                    lv, = exe.run(trainer_prog,
                                  feed={"x": x[half], "y": y[half]},
                                  fetch_list=[loss])
                    out.append(float(lv))

        out0, out1 = [], []
        t1 = threading.Thread(target=run_trainer, args=(1, out1),
                              daemon=True)
        t1.start()
        run_trainer(0, out0)
        t1.join(timeout=60)

        from paddle_tpu.distributed.rpc import global_client
        global_client().send_exit("127.0.0.1:6199")
        th.join(timeout=10)
        assert not server_exc, server_exc

        # after the first step params diverge from init identically to the
        # local full-batch run; check the loss trajectory (mean of the two
        # half-batch losses) stays close to local losses
        assert len(out0) == steps and len(out1) == steps
        for i in range(1, steps):
            dist_loss = 0.5 * (out0[i] + out1[i])
            assert abs(dist_loss - local[i]) < 1e-3, (
                i, dist_loss, local[i])


class TestLrScheduleOnPserver:
    def _build(self):
        main = fluid.Program()
        startup = fluid.Program()
        main.random_seed = 9
        startup.random_seed = 9
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(input=x, size=1, act=None)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pred, label=y))
            lr = fluid.layers.exponential_decay(
                learning_rate=0.1, decay_steps=1, decay_rate=0.5,
                staircase=True)
            fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
        return main, startup, loss

    def test_lr_ops_move_to_pserver(self):
        main, startup, _ = self._build()
        t = DistributeTranspiler()
        t.transpile(trainer_id=0, program=main, pservers="127.0.0.1:6396",
                    trainers=1, startup_program=startup)
        trainer = t.get_trainer_program()
        ttypes = [op.type for op in trainer.global_block().ops]
        assert "increment" not in ttypes, "LR counter must move to pserver"
        ps = t.get_pserver_program("127.0.0.1:6396")
        ls = ps.global_block().ops[-1]
        lr_bid = ls.attr("lr_decay_block_id")
        assert lr_bid >= 0
        lr_types = [op.type for op in ps.blocks[lr_bid].ops]
        assert "increment" in lr_types

    def test_lr_actually_decays_on_pserver(self):
        main, startup, loss = self._build()
        t = DistributeTranspiler()
        ep = "127.0.0.1:6397"
        t.transpile(trainer_id=0, program=main, pservers=ep, trainers=1,
                    startup_program=startup)
        ps_prog = t.get_pserver_program(ep)
        ps_startup = t.get_startup_program(ep, ps_prog,
                                           startup_program=startup)
        ps_scope = fluid.Scope()

        def run_ps():
            exe = fluid.Executor(fluid.CPUPlace())
            with fluid.scope_guard(ps_scope):
                exe.run(ps_startup)
                exe.run(ps_prog)

        th = threading.Thread(target=run_ps, daemon=True)
        th.start()
        from paddle_tpu.distributed.rpc import wait_server_ready
        wait_server_ready([ep])

        trainer_prog = t.get_trainer_program()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        rng = np.random.RandomState(0)
        # param trajectory under decaying LR: per-step deltas must shrink
        # by the decay factor
        deltas = []
        with fluid.scope_guard(scope):
            exe.run(startup)
            x = rng.randn(4, 4).astype(np.float32)
            y = np.ones((4, 1), np.float32)
            prev = np.asarray(scope.get("fc_4.w_0")
                              if scope.get("fc_4.w_0") is not None
                              else list(scope.keys())).copy() \
                if False else None
            wname = [v.name for v in main.all_parameters()
                     if v.name.endswith(".w_0")][0]
            prev = np.asarray(scope.get(wname)).copy()
            for i in range(3):
                exe.run(trainer_prog, feed={"x": x, "y": y},
                        fetch_list=[loss])
                cur = np.asarray(scope.get(wname)).copy()
                deltas.append(np.abs(cur - prev).max())
                prev = cur
        from paddle_tpu.distributed.rpc import global_client
        global_client().send_exit(ep)
        th.join(timeout=10)
        # decay_rate 0.5 staircase with decay_steps=1: LR halves per step;
        # same feed -> delta ratio approx <= ~0.6
        assert deltas[1] < deltas[0] * 0.75, deltas
        assert deltas[2] < deltas[1] * 0.75, deltas


class TestMemoryOptimize:
    def test_reuse_plan_found(self):
        main, startup, loss = _build_net()
        plan = memory_optimize(main)
        # a fwd+bwd program has dead intermediates of equal size -> reuse
        assert isinstance(plan, list)
        assert main._memory_reuse_plan is plan

    def test_release_memory(self):
        main, startup, loss = _build_net()
        drop = release_memory(main)
        assert drop, "expected early-deletable vars in fwd+bwd program"
        names = [n for vs in drop.values() for n in vs]
        assert all(not n.startswith("fc") or "@" in n or "tmp" in n
                   for n in names) or names


class TestInferenceTranspiler:
    def test_conv_bn_fold(self):
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name="img", shape=[3, 8, 8],
                                    dtype="float32")
            conv = fluid.layers.conv2d(input=img, num_filters=4,
                                       filter_size=3, padding=1, act=None,
                                       bias_attr=False)
            bn = fluid.layers.batch_norm(input=conv)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            # give BN non-trivial frozen statistics
            import jax.numpy as jnp
            rng = np.random.RandomState(0)
            for op in main.global_block().ops:
                if op.type == "batch_norm":
                    scope.set(op.input("Mean")[0],
                              jnp.asarray(rng.randn(4).astype(np.float32)))
                    scope.set(op.input("Variance")[0], jnp.asarray(
                        np.abs(rng.randn(4)).astype(np.float32) + 0.5))
            infer = main.clone(for_test=True)
            x = rng.randn(2, 3, 8, 8).astype(np.float32)
            ref, = exe.run(infer, feed={"img": x}, fetch_list=[bn.name])

            InferenceTranspiler().transpile(infer, scope=scope)
            types = [op.type for op in infer.global_block().ops]
            assert "batch_norm" not in types
            assert "elementwise_add" in types
            got, = exe.run(infer, feed={"img": x}, fetch_list=[bn.name])
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)
