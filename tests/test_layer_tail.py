"""Layer-API parity tail (the last reference fluid.layers names):
add_position_encoding, similarity_focus, hash, stanh, lod_reset,
logical_*, lstm_unit, sum, tensor_array_to_tensor, image_resize_short,
detection_map / generate_proposal_labels / roi_perspective_transform,
open_files / shuffle, autoincreased_step_counter / append_LARS."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid.lod import create_lod_tensor


def _run(build, feeds=None, n_fetch=1):
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        outs = build()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    fetch = outs if isinstance(outs, (list, tuple)) else [outs]
    return exe.run(main, feed=feeds or {}, fetch_list=list(fetch))


def test_positional_encoding_stanh_logical_sum():
    def build():
        x = fluid.layers.data("x", shape=[4, 6])
        pe = fluid.layers.add_position_encoding(x, alpha=1.0, beta=1.0)
        st = fluid.layers.stanh(x)
        a = fluid.layers.data("a", shape=[2], dtype="bool")
        b = fluid.layers.data("b", shape=[2], dtype="bool")
        land = fluid.layers.logical_and(a, b)
        lor = fluid.layers.logical_or(a, b)
        s = fluid.layers.sum([x, x])
        return pe, st, land, lor, s

    xv = np.zeros((1, 4, 6), np.float32)
    av = np.array([[True, False]])
    bv = np.array([[True, True]])
    pe, st, land, lor, s = _run(build, {"x": xv, "a": av, "b": bv})
    np.testing.assert_allclose(np.asarray(pe)[0, 0, 3:],
                               np.ones(3), atol=1e-6)
    np.testing.assert_allclose(np.asarray(st), np.zeros_like(xv),
                               atol=1e-6)
    np.testing.assert_array_equal(np.asarray(land)[0], [True, False])
    np.testing.assert_array_equal(np.asarray(lor)[0], [True, True])
    np.testing.assert_allclose(np.asarray(s), 2 * xv, atol=1e-6)


def test_hash_and_similarity_focus_layers():
    def build():
        ids = fluid.layers.data("ids", shape=[2], dtype="int64")
        h = fluid.layers.hash(ids, hash_size=100, num_hash=2)
        img = fluid.layers.data("img", shape=[1, 2, 2])
        sf = fluid.layers.similarity_focus(img, axis=1, indexes=[0])
        return h, sf

    ids = np.array([[3, 7], [3, 7]], np.int64)
    img = np.array([[[[3.0, 2.0], [1.0, 0.0]]]], np.float32)
    h, sf = _run(build, {"ids": ids, "img": img})
    h = np.asarray(h)
    # reference hash output layout: [N, num_hash, 1]
    assert h.shape[-2:] == (2, 1) and (h >= 0).all() and (h < 100).all()
    # same input rows -> same hashes (deterministic)
    np.testing.assert_array_equal(h[0], h[1])
    np.testing.assert_allclose(np.asarray(sf)[0, 0],
                               [[1, 0], [0, 1]], atol=1e-6)


def test_lstm_unit_layer_steps_state():
    def build():
        x = fluid.layers.data("x", shape=[3])
        h0 = fluid.layers.data("h0", shape=[5])
        c0 = fluid.layers.data("c0", shape=[5])
        h, c = fluid.layers.lstm_unit(x, h0, c0, forget_bias=1.0)
        return h, c

    rng = np.random.RandomState(0)
    h, c = _run(build, {"x": rng.randn(2, 3).astype(np.float32),
                        "h0": np.zeros((2, 5), np.float32),
                        "c0": np.zeros((2, 5), np.float32)})
    assert np.asarray(h).shape == (2, 5)
    assert np.isfinite(np.asarray(c)).all()


def test_lod_reset_reseats_lengths():
    def build():
        x = fluid.layers.data("x", shape=[2], lod_level=1)
        y = fluid.layers.data("y", shape=[1], lod_level=1)
        r = fluid.layers.lod_reset(x, y)
        return fluid.layers.sequence_pool(r, "sum")

    data = np.ones((4, 2), np.float32)
    x = create_lod_tensor(data, [[2, 2]])
    y = create_lod_tensor(np.zeros((4, 1), np.float32), [[1, 3]])
    (pooled,) = _run(build, {"x": x, "y": y})
    # after reset to lengths [1, 3]: sums are 1 row and 3 rows
    np.testing.assert_allclose(np.asarray(pooled),
                               [[1, 1], [3, 3]], atol=1e-5)


def test_roi_perspective_transform_identity_quad():
    def build():
        img = fluid.layers.data("img", shape=[1, 4, 4])
        rois = fluid.layers.data("rois", shape=[8])
        return fluid.layers.roi_perspective_transform(img, rois, 4, 4)

    img = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    # axis-aligned quad covering the full image
    rois = np.array([[0, 0, 4, 0, 4, 4, 0, 4]], np.float32)
    (out,) = _run(build, {"img": img, "rois": rois})
    out = np.asarray(out)
    assert out.shape == (1, 1, 4, 4)
    # identity-ish warp on interior cells (borders zero-pad): values
    # increase left-to-right and top-to-bottom
    assert out[0, 0, 1, 1] < out[0, 0, 1, 2]
    assert out[0, 0, 1, 1] < out[0, 0, 2, 1]


def test_generate_proposal_labels_samples():
    def build():
        rois = fluid.layers.data("rois", shape=[4])
        gtc = fluid.layers.data("gtc", shape=[1], dtype="int64")
        gtb = fluid.layers.data("gtb", shape=[4])
        return fluid.layers.generate_proposal_labels(
            rois, gtc, None, gtb, batch_size_per_im=8,
            fg_fraction=0.5, fg_thresh=0.5)[0:2]

    rois = np.array([[0, 0, 10, 10], [0, 0, 9, 9], [50, 50, 60, 60]],
                    np.float32)
    gtc = np.array([[3]], np.int64)
    gtb = np.array([[0, 0, 10, 10]], np.float32)
    out_rois, labels = _run(build, {"rois": rois, "gtc": gtc,
                                    "gtb": gtb})
    labels = np.asarray(labels).reshape(-1)
    assert (labels == 3).sum() >= 1          # fg got the gt class
    assert (labels == 0).sum() >= 1          # bg sampled too


def test_open_files_and_shuffle_roundtrip(tmp_path):
    from paddle_tpu.fluid.recordio_writer import \
        convert_reader_to_recordio_file

    path = str(tmp_path / "data.recordio")

    def samples():
        for i in range(6):
            yield (np.full((2,), i, np.float32),)

    convert_reader_to_recordio_file(path, samples)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        reader = fluid.layers.open_files(
            [path], shapes=[[-1, 2]], lod_levels=[0],
            dtypes=["float32"])
        reader = fluid.layers.shuffle(reader, buffer_size=6)
        slot = reader.output_vars[0]
        out = fluid.layers.scale(slot, scale=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    reader.start()
    seen = []
    for _ in range(6):
        (v,) = exe.run(main, feed=reader.next_feed(), fetch_list=[out])
        seen.append(float(np.asarray(v).ravel()[0]))
    reader.reset()
    assert sorted(seen) == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]


def test_step_counter_and_append_LARS():
    def build():
        x = fluid.layers.data("x", shape=[2])
        ctr = fluid.layers.autoincreased_step_counter()
        w = fluid.layers.create_parameter([2, 2], "float32", name="lw")
        g = fluid.layers.scale(w, scale=0.1)
        lrs = fluid.layers.append_LARS([(w, g)], learning_rate=0.5,
                                       weight_decay=0.01)
        return ctr, lrs[0]

    ctr, lr = _run(build, {"x": np.zeros((1, 2), np.float32)})
    assert np.isfinite(np.asarray(lr)).all()


def test_generate_proposal_labels_per_image_segmentation():
    """Batch of 2 images via LoD: proposals must only match ground truth
    from their OWN image, and crowd gt never serves as a target."""
    def build():
        rois = fluid.layers.data("rois", shape=[4], lod_level=1)
        gtc = fluid.layers.data("gtc", shape=[1], dtype="int64",
                                lod_level=1)
        crowd = fluid.layers.data("crowd", shape=[1], dtype="int64",
                                  lod_level=1)
        gtb = fluid.layers.data("gtb", shape=[4], lod_level=1)
        return fluid.layers.generate_proposal_labels(
            rois, gtc, crowd, gtb, batch_size_per_im=8,
            fg_fraction=0.5, fg_thresh=0.5)[0:2]

    # image 0: roi overlapping IMAGE 1's gt location but not its own
    rois = create_lod_tensor(
        np.array([[50, 50, 60, 60],       # img0 roi (matches img1's gt!)
                  [0, 0, 10, 10]],        # img1 roi (matches img1 gt? no)
                 np.float32), [[1, 1]])
    gtb = create_lod_tensor(
        np.array([[0, 0, 10, 10],         # img0 gt at origin
                  [50, 50, 60, 60]],      # img1 gt at 50..60
                 np.float32), [[1, 1]])
    gtc = create_lod_tensor(
        np.array([[3], [7]], np.int64), [[1, 1]])
    crowd = create_lod_tensor(
        np.array([[0], [0]], np.int64), [[1, 1]])
    out_rois, labels = _run(build, {"rois": rois, "gtb": gtb,
                                    "gtc": gtc, "crowd": crowd})
    labels = np.asarray(labels).reshape(-1)
    # cross-image matches are impossible: neither sampled roi may carry
    # the OTHER image's class via its roi (gt boxes join their own pool,
    # so classes 3 and 7 appear only via same-image candidates)
    assert set(labels.tolist()) <= {0, 3, 7}
    # img0's roi at (50,50) must NOT be labeled 7 (that gt is in img1)
    rois_np = np.asarray(out_rois)
    for r, l in zip(rois_np, labels):
        if l == 7:
            # any class-7 row must be img1's own candidate (gt join)
            assert r[0] >= 50


def test_detection_map_metric_streaming():
    """fluid.metrics.DetectionMAP: per-batch and accumulative mAP vars,
    states threading across runs, reset()."""
    from paddle_tpu.fluid.metrics import DetectionMAP

    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        det = fluid.layers.data("det", shape=[6], lod_level=1)
        gt_label = fluid.layers.data("gl", shape=[1], dtype="int64",
                                     lod_level=1)
        gt_box = fluid.layers.data("gb", shape=[4], lod_level=1)
        m = DetectionMAP(det, gt_label, gt_box, class_num=3)
        cur_map, accum_map = m.get_map_var()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.executor.Scope()

    def batch(hit):
        d = np.array([[1, 0.9, 0, 0, 1, 1]], np.float32) if hit else \
            np.array([[1, 0.9, 5, 5, 6, 6]], np.float32)
        return {
            "det": create_lod_tensor(d, [[1]]),
            "gl": create_lod_tensor(np.array([[1]], np.int64), [[1]]),
            "gb": create_lod_tensor(
                np.array([[0, 0, 1, 1]], np.float32), [[1]]),
        }

    with fluid.executor.scope_guard(scope):
        exe.run(startup)
        c1, a1 = exe.run(main, feed=batch(True),
                         fetch_list=[cur_map, accum_map])
        assert abs(float(np.asarray(c1)[0]) - 1.0) < 1e-6
        assert abs(float(np.asarray(a1)[0]) - 1.0) < 1e-6
        # a miss lowers the STREAM mAP below the current-batch value
        c2, a2 = exe.run(main, feed=batch(False),
                         fetch_list=[cur_map, accum_map])
        assert float(np.asarray(c2)[0]) == 0.0
        assert 0.0 < float(np.asarray(a2)[0]) < 1.0
        # reset clears the accumulators
        m.reset(exe)
        c3, a3 = exe.run(main, feed=batch(True),
                         fetch_list=[cur_map, accum_map])
        assert abs(float(np.asarray(a3)[0]) - 1.0) < 1e-6
