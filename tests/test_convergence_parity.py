"""Convergence parity across execution strategies (reference
test_parallel_executor_mnist.py / test_parallel_executor_seresnext.py via
TestParallelExecutorBase.check_network_convergence, and
test_dist_mnist.py:26 check_with_place)."""

import numpy as np

import paddle_tpu.fluid as fluid
from convergence_base import check_network_convergence


def _mnist_build():
    from paddle_tpu.models import mnist
    main, startup, feeds, loss, acc, predict = mnist.get_model(
        batch_size=16, lr=0.01, use_adam=False)
    return main, startup, loss


def _mnist_feeds(steps, global_bs=16):
    rng = np.random.RandomState(5)
    out = []
    for _ in range(steps):
        out.append({
            "pixel": rng.randn(global_bs, 1, 28, 28).astype(np.float32),
            "label": rng.randint(0, 10, (global_bs, 1)).astype(np.int64),
        })
    return out


def test_mnist_convergence_parity():
    losses = check_network_convergence(
        _mnist_build, _mnist_feeds(4), steps=4, delta=1e-5,
        pserver_endpoint="127.0.0.1:6298")
    assert np.isfinite(losses).all()


def _se_resnext_build():
    from paddle_tpu.models import se_resnext
    main, startup, feeds, loss, acc, prob = se_resnext.get_model(
        batch_size=8, class_dim=8, layers=50, img_size=32, lr=0.01)
    return main, startup, loss


def _se_resnext_feeds(steps, global_bs=8):
    rng = np.random.RandomState(6)
    out = []
    for _ in range(steps):
        out.append({
            "data": rng.randn(global_bs, 3, 32, 32).astype(np.float32),
            "label": rng.randint(0, 8, (global_bs, 1)).astype(np.int64),
        })
    return out


def test_se_resnext_convergence_parity():
    losses = check_network_convergence(
        _se_resnext_build, _se_resnext_feeds(3), steps=3, delta=1e-4)
    assert np.isfinite(losses).all()
