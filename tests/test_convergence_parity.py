"""Convergence parity across execution strategies (reference
test_parallel_executor_mnist.py / test_parallel_executor_seresnext.py via
TestParallelExecutorBase.check_network_convergence, and
test_dist_mnist.py:26 check_with_place).

SE-ResNeXt methodology mirrors the reference exactly
(test_parallel_executor_seresnext.py): its Executor-vs-ParallelExecutor
convergence check `_check_resnet_convergence` (:280) sets
`remove_dropout = True; remove_bn = True` (:289-:292) before comparing,
because — per the FIXME(zcd) comments at :28-:38 — per-device replication
makes dropout masks and BN statistics diverge between the two executors.
Our SPMD design actually computes GLOBAL batch-norm statistics (identical
semantics to the single-device run — stronger than the reference, whose PE
computes per-device stats), so the only residual divergence is reduction
reassociation noise under sharding; a 50-deep BN stack amplifies that
~1e-7 noise chaotically (measured: 5e-5 in the step-0 loss, ~3% in
gradients), so like the reference we compare the BN-free model tightly and
add a BN-kept guard at small lr that still catches semantic bugs (wrong
per-shard stats would diverge at step 0 by O(0.1))."""

import numpy as np

import paddle_tpu.fluid as fluid
from convergence_base import (check_network_convergence, run_executor,
                              run_parallel_executor)


def _mnist_build():
    from paddle_tpu.models import mnist
    main, startup, feeds, loss, acc, predict = mnist.get_model(
        batch_size=16, lr=0.01, use_adam=False)
    return main, startup, loss


def _mnist_feeds(steps, global_bs=16):
    rng = np.random.RandomState(5)
    out = []
    for _ in range(steps):
        out.append({
            "pixel": rng.randn(global_bs, 1, 28, 28).astype(np.float32),
            "label": rng.randint(0, 10, (global_bs, 1)).astype(np.int64),
        })
    return out


def test_mnist_convergence_parity():
    losses = check_network_convergence(
        _mnist_build, _mnist_feeds(8), steps=8, delta=1e-5,
        pserver_endpoint="127.0.0.1:6298")
    assert np.isfinite(losses).all()


def _se_resnext_build(remove_bn=True, remove_dropout=True, lr=0.01):
    from paddle_tpu.models import se_resnext
    main, startup, feeds, loss, acc, prob = se_resnext.get_model(
        batch_size=8, class_dim=8, layers=50, img_size=32, lr=lr,
        remove_bn=remove_bn, remove_dropout=remove_dropout)
    return main, startup, loss


def _se_resnext_feeds(steps, global_bs=8):
    rng = np.random.RandomState(6)
    out = []
    for _ in range(steps):
        out.append({
            "data": rng.randn(global_bs, 3, 32, 32).astype(np.float32),
            "label": rng.randint(0, 8, (global_bs, 1)).astype(np.int64),
        })
    return out


def test_se_resnext_convergence_parity():
    """reference _check_resnet_convergence (:280): BN + dropout removed,
    Executor vs ParallelExecutor trajectories must match tightly (we hold
    atol 1e-4 over 5 steps where the reference holds 1e-3 over 2 CPU
    iterations — and unlike the reference, which also strips activations
    when remove_bn is set, our remove_bn model keeps every relu, so the
    compared network stays fully nonlinear)."""
    losses = check_network_convergence(
        lambda: _se_resnext_build(remove_bn=True, remove_dropout=True),
        _se_resnext_feeds(5), steps=5, delta=1e-4)
    assert np.isfinite(losses).all()


def test_se_resnext_bn_semantic_parity():
    """BN + dropout KEPT — beyond the reference, possible here because the
    SPMD batch_norm computes global statistics. Guards against per-shard
    stats/masks: those would diverge at step 0 by O(0.1). Small lr bounds
    the chaotic amplification of reduction-reassociation noise so a
    meaningful multi-step tolerance exists."""
    build = lambda: _se_resnext_build(remove_bn=False, remove_dropout=False,
                                      lr=1e-4)
    feeds = _se_resnext_feeds(2)
    local = run_executor(build, feeds, None, 2)
    pe = run_parallel_executor(build, feeds, None, 2)
    # The semantic guard is STEP 0: a per-shard-stats/mask bug diverges
    # by O(0.1) before any update lands, while correct global stats
    # agree to reduction-reassociation noise (measured ~6e-5). Later
    # steps only bound the chaotic amplification of that noise through
    # the BN stack, which moves whenever XLA's fusion schedule does
    # (e.g. the inert remat_tag identity shifted step-1 from 1.1e-3 to
    # 1.06e-2) — so step 1+ gets the loose bound, step 0 the tight one.
    assert abs(local[0] - pe[0]) < 1e-3, (local, pe)
    np.testing.assert_allclose(local, pe, atol=3e-2, err_msg=
                               "BN-kept Executor vs PE diverged beyond the "
                               "reassociation-noise bound")


def _transformer_build():
    from paddle_tpu.models import transformer
    main, startup, feeds, loss, acc, logits = transformer.get_model(
        batch_size=8, seq_len=16, vocab_size=128, d_model=64, n_heads=4,
        n_layers=2, d_ff=128, lr=1e-3)
    return main, startup, loss


def _transformer_feeds(steps, global_bs=8, seq_len=16, vocab=128):
    rng = np.random.RandomState(7)
    out = []
    for _ in range(steps):
        toks = rng.randint(0, vocab, (global_bs, seq_len)).astype(np.int64)
        labs = rng.randint(0, vocab, (global_bs, seq_len)).astype(np.int64)
        out.append({"tokens": toks, "labels": labs})
    return out


def test_transformer_convergence_parity():
    """VERDICT r2 task #1: a transformer parity case. LayerNorm is
    per-sample (no cross-batch statistics), so sharding reassociation noise
    stays small and the trajectories match tightly."""
    losses = check_network_convergence(
        _transformer_build, _transformer_feeds(4), steps=4, delta=1e-4)
    assert np.isfinite(losses).all()


def test_gradient_scale_strategy_one():
    """BuildStrategy.GradientScaleStrategy.One (reference
    build_strategy.h:55 + scale_loss_grad_op_handle): per-device seed 1.0
    with sum-reduce == grads num_devices x CoeffNumDevice's. With SGD the
    first parameter delta must scale by exactly the device count."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.framework import Program

    def build():
        main, startup = Program(), Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            y = fluid.layers.data("y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(x, size=1, bias_attr=False)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(13)
    feed = {"x": rng.randn(8, 4).astype(np.float32),
            "y": rng.randn(8, 1).astype(np.float32)}

    deltas = {}
    for strat_name in ("coeff", "one"):
        with fluid.unique_name.guard():
            main, startup, loss = build()
        pname = main.global_block().all_parameters()[0].name
        strategy = fluid.BuildStrategy()
        if strat_name == "one":
            strategy.gradient_scale_strategy = \
                fluid.BuildStrategy.GradientScaleStrategy.One
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            before = np.asarray(scope.get(pname)).copy()
            pe = fluid.ParallelExecutor(use_cuda=False,
                                        loss_name=loss.name,
                                        main_program=main,
                                        build_strategy=strategy)
            pe.run(fetch_list=[loss.name], feed=feed)
            after = np.asarray(scope.get(pname))
            deltas[strat_name] = after - before
    ratio = deltas["one"] / deltas["coeff"]
    np.testing.assert_allclose(ratio, 8.0, rtol=1e-4)  # 8 virtual devices
