"""Round-4 networks tail (reference trainer_config_helpers/networks.py):
step units/groups, separable conv, and the attention family, driven
through the v1 spellings."""

import numpy as np

import paddle_tpu.v2 as paddle
import paddle_tpu.trainer_config_helpers.networks as networks
from paddle_tpu.trainer_config_helpers import layers as v1


def _run(layer, vals):
    topo = paddle.topology.Topology([layer])
    names = [n for n, _ in topo.data_type()]
    p = paddle.parameters.create(layer)
    return np.asarray(paddle.infer(
        output_layer=layer, parameters=p,
        input=[tuple(vals[n] for n in names)]))


def test_lstmemory_group_runs():
    rng = np.random.RandomState(0)
    x = v1.data_layer(name="lx",
                      type=paddle.data_type.dense_vector_sequence(8))
    proj = v1.fc_layer(input=x, size=16, bias_attr=False)
    h = networks.lstmemory_group(input=proj, size=4)
    last = v1.last_seq(input=h)
    got = _run(last, {"lx": rng.randn(3, 8).astype(np.float32)})
    assert got.ravel().shape == (4,) and np.all(np.isfinite(got))


def test_gru_group_and_simple_gru2_run():
    rng = np.random.RandomState(1)
    x = v1.data_layer(name="gx2",
                      type=paddle.data_type.dense_vector_sequence(6))
    h = networks.simple_gru2(input=x, size=5)
    last = v1.last_seq(input=h)
    got = _run(last, {"gx2": rng.randn(4, 6).astype(np.float32)})
    assert got.ravel().shape == (5,) and np.all(np.isfinite(got))


def test_img_separable_conv_shapes():
    rng = np.random.RandomState(2)
    img = v1.data_layer(name="sc", size=3 * 4 * 4, height=4, width=4)
    out = networks.img_separable_conv(
        input=img, num_channels=3, num_out_channels=5, filter_size=3,
        padding=1, bias_attr=False)
    got = _run(out, {"sc": rng.rand(3 * 4 * 4).astype(np.float32)})
    assert got.ravel().shape == (5 * 4 * 4,)


def test_simple_attention_focuses_on_similar_position():
    """With the transform weights fixed, attention puts most mass on the
    encoder position matching the decoder state."""
    rng = np.random.RandomState(3)
    enc = v1.data_layer(name="enc",
                        type=paddle.data_type.dense_vector_sequence(4))
    proj = v1.fc_layer(input=enc, size=6, bias_attr=False)
    state = v1.data_layer(name="st", size=4)
    ctx = networks.simple_attention(encoded_sequence=enc,
                                    encoded_proj=proj,
                                    decoder_state=state)
    seq = rng.randn(5, 4).astype(np.float32)
    got = _run(ctx, {"enc": seq, "st": rng.randn(4).astype(np.float32)})
    assert got.ravel().shape == (4,) and np.all(np.isfinite(got))


def test_dot_product_attention_exact():
    """Numpy cross-check: weights = softmax(enc . state), context =
    weights . attended."""
    enc = v1.data_layer(name="de",
                        type=paddle.data_type.dense_vector_sequence(3))
    att = v1.data_layer(name="da",
                        type=paddle.data_type.dense_vector_sequence(2))
    st = v1.data_layer(name="ds", size=3)
    ctx = networks.dot_product_attention(
        encoded_sequence=enc, attended_sequence=att,
        transformed_state=st)
    rng = np.random.RandomState(4)
    e = rng.randn(4, 3).astype(np.float32)
    a = rng.randn(4, 2).astype(np.float32)
    s = rng.randn(3).astype(np.float32)
    got = _run(ctx, {"de": e, "da": a, "ds": s}).ravel()
    w = np.exp(e @ s)
    w /= w.sum()
    np.testing.assert_allclose(got, w @ a, rtol=1e-4)


def test_multi_head_attention_both_types():
    rng = np.random.RandomState(5)
    q = v1.data_layer(name="mq", size=6)
    k = v1.data_layer(name="mk",
                      type=paddle.data_type.dense_vector_sequence(6))
    vv = v1.data_layer(name="mv",
                       type=paddle.data_type.dense_vector_sequence(6))
    vals = {"mq": rng.randn(6).astype(np.float32),
            "mk": rng.randn(4, 6).astype(np.float32),
            "mv": rng.randn(4, 6).astype(np.float32)}
    for att_type in ("dot-product attention", "additive attention"):
        ctx = networks.multi_head_attention(
            query=q, key=k, value=vv, key_proj_size=8, value_proj_size=8,
            head_num=2, attention_type=att_type)
        got = _run(ctx, vals)
        assert got.ravel().shape == (8,) and np.all(np.isfinite(got))


def test_networks_surface_complete():
    """Every reference networks.py __all__ name resolves."""
    import re
    ref = open("/root/reference/python/paddle/trainer_config_helpers/"
               "networks.py").read()
    ref_all = re.search(r"__all__ = \[(.*?)\]", ref, re.S).group(1)
    names = set(re.findall(r"'([a-zA-Z0-9_]+)'", ref_all))
    missing = [n for n in sorted(names) if not hasattr(networks, n)]
    assert not missing, missing
