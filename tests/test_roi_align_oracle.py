"""roi_align reference-kernel oracle (roi_align_op.h restated).

Pins the details the generic description misses: coords scaled with NO
rounding, roi w/h floored at 1.0, per-bin sample grid of
sampling_ratio^2 points at (i+0.5)/n offsets — or, when
sampling_ratio <= 0, an ADAPTIVE per-roi grid of ceil(roi_h/ph) x
ceil(roi_w/pw) points — each bilinearly interpolated with the
reference's edge handling (oob beyond [-1, size] -> 0, negatives
clamped to 0, high edge collapsed), averaged over the FULL grid count.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid.lod import create_lod_tensor


def _run(build_fn, feed):
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        fetches = build_fn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    res = exe.run(main, feed=feed, fetch_list=list(fetches))
    return [np.asarray(r) for r in res]


def _bilinear(feat, y, x):
    """roi_align_op.h PreCalcForBilinearInterpolate, one point."""
    C, H, W = feat.shape
    if y < -1.0 or y > H or x < -1.0 or x > W:
        return np.zeros(C, feat.dtype)
    y = max(y, 0.0)
    x = max(x, 0.0)
    y_low, x_low = int(y), int(x)
    if y_low >= H - 1:
        y_high = y_low = H - 1
        y = float(y_low)
    else:
        y_high = y_low + 1
    if x_low >= W - 1:
        x_high = x_low = W - 1
        x = float(x_low)
    else:
        x_high = x_low + 1
    ly, lx = y - y_low, x - x_low
    hy, hx = 1.0 - ly, 1.0 - lx
    return (feat[:, y_low, x_low] * hy * hx +
            feat[:, y_low, x_high] * hy * lx +
            feat[:, y_high, x_low] * ly * hx +
            feat[:, y_high, x_high] * ly * lx)


def roi_align_oracle(x, rois, batch_ids, ph, pw, scale, ratio):
    B, C, H, W = x.shape
    out = np.zeros((len(rois), C, ph, pw), x.dtype)
    for n, (roi, b) in enumerate(zip(rois, batch_ids)):
        xmin, ymin, xmax, ymax = (v * scale for v in roi)
        rw = max(xmax - xmin, 1.0)
        rh = max(ymax - ymin, 1.0)
        bh, bw = rh / ph, rw / pw
        gh = ratio if ratio > 0 else int(np.ceil(rh / ph))
        gw = ratio if ratio > 0 else int(np.ceil(rw / pw))
        count = gh * gw
        for i in range(ph):
            for j in range(pw):
                acc = np.zeros(C, x.dtype)
                for iy in range(gh):
                    yy = ymin + i * bh + (iy + 0.5) * bh / gh
                    for ix in range(gw):
                        xx = xmin + j * bw + (ix + 0.5) * bw / gw
                        acc += _bilinear(x[b], yy, xx)
                out[n, :, i, j] = acc / count
    return out


@pytest.mark.parametrize("ratio", [-1, 2, 3])
def test_roi_align_matches_reference(ratio):
    rng = np.random.RandomState(5)
    x = rng.randn(2, 3, 12, 16).astype(np.float32)
    # mix of small, large (adaptive grid > 2), edge-hugging and
    # out-of-range rois; raw coords (spatial_scale rescales them)
    rois = np.array([[1.2, 2.1, 9.7, 8.8],
                     [0.0, 0.0, 31.0, 23.0],     # big: ceil grid 4x4
                     [14.5, 10.2, 15.9, 11.9],   # tiny: w/h floor at 1
                     [-3.0, -2.0, 4.0, 35.0],    # spills every edge
                     [30.0, 20.0, 30.5, 20.5]], np.float32)
    lens = [3, 2]
    batch_ids = [0, 0, 0, 1, 1]
    ph, pw, scale = 3, 4, 0.5

    def build():
        xv = fluid.layers.data("x", shape=[3, 12, 16], dtype="float32")
        rv = fluid.layers.data("rois", shape=[4], dtype="float32",
                               lod_level=1)
        return [fluid.layers.roi_align(
            xv, rv, pooled_height=ph, pooled_width=pw,
            spatial_scale=scale, sampling_ratio=ratio)]

    rois_lod = create_lod_tensor(rois, [lens])
    (got,) = _run(build, {"x": x, "rois": rois_lod})
    want = roi_align_oracle(x, rois, batch_ids, ph, pw, scale, ratio)
    # repo returns [B, R, C, ph, pw] padded; flatten valid rows
    if got.ndim == 5:
        got = np.concatenate([got[b, :l] for b, l in enumerate(lens)])
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)
