"""Unified oracle/parity harness for the tiled-contraction kernel
substrate (ROOFLINE.md "Kernel substrate") + the int8 KV-cache decode
path (QUANTIZE.md "Quantized KV cache").

Every Pallas family — flash fwd/bwd, decode attention (fp32 AND int8
cache), fused dequant-matmul — instantiates ONE driver
(ops/pallas_kernels.tiled_contraction); this file sweeps each family
against its plain-XLA oracle across dtypes x geometries (tileable,
untileable-fallback, batch-1), then pins the int8 KV-cache contracts:
cache bytes <= 0.27x fp32 at equal slots, greedy self-bit-stability,
fp32-vs-int8 top-1 agreement >= 0.99 on the tiny fixture, slot-reuse
zero-leakage, rollback bit-identity, and spec-decode accept rate 1.0
for the same-cache-dtype twin.

The *_smoke tests are the ci_checks.sh `kernels` gate (exit 15)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture
def store(tmp_path):
    from paddle_tpu import compile_cache as cc
    old = fluid.get_flags(["compile_cache", "compile_cache_dir"])
    root = str(tmp_path / "cc_store")
    fluid.set_flags({"compile_cache": True, "compile_cache_dir": root})
    cc.reset_stats()
    yield root
    fluid.set_flags(old)
    cc.reset_stats()


def _qkv(B, S, H, D, dtype, seed=0):
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.randn(B, S, H, D).astype(np.float32) * 0.3).astype(dtype)
    return mk(), mk(), mk()


def _decode_operands(N, S, H, D, kv_dtype, seed=1):
    """(q, k_cache, v_cache, lengths, kv_scales) for one decode shape;
    int8 caches come with matching per-head scales."""
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(N, H, D).astype(np.float32))
    kf = rng.randn(N, S, H, D).astype(np.float32)
    vf = rng.randn(N, S, H, D).astype(np.float32)
    lengths = np.concatenate([[S], rng.randint(1, S + 1, size=N - 1)]) \
        .astype(np.int32) if N > 1 else np.array([S], np.int32)
    if kv_dtype != "int8":
        return q, jnp.asarray(kf), jnp.asarray(vf), lengths, None
    ks = np.abs(kf).max(axis=(0, 1, 3)) * 1.25 / 127.0
    vs = np.abs(vf).max(axis=(0, 1, 3)) * 1.25 / 127.0
    k8 = jnp.asarray(np.clip(np.round(
        kf / ks[None, None, :, None]), -127, 127).astype(np.int8))
    v8 = jnp.asarray(np.clip(np.round(
        vf / vs[None, None, :, None]), -127, 127).astype(np.int8))
    return q, k8, v8, lengths, np.stack([ks, vs]).astype(np.float32)


# ---------------------------------------------------------------------------
# the parity matrix: every family x dtype x geometry vs its oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype,causal,S,blocks", [
    ("float32", False, 64, (16, 16)),
    ("float32", True, 64, (16, 32)),
    ("bfloat16", True, 64, (32, 16)),
    ("float32", True, 63, None),       # prime-ish S: XLA fallback path
    ("float32", False, 64, (64, 64)),  # single-tile degenerate grid
])
def test_flash_family_parity(dtype, causal, S, blocks):
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels import flash_attention
    from paddle_tpu.parallel.ring_attention import local_attention
    q, k, v = _qkv(2, S, 2, 16, dtype)
    kw = dict(zip(("block_q", "block_kv"), blocks)) if blocks else {}
    out = flash_attention(q, k, v, causal=causal, **kw)
    ref = local_attention(q, k, v, causal=causal)
    tol = 2e-2 if dtype == "bfloat16" else 2e-5
    assert float(jnp.abs(out.astype(jnp.float32)
                         - ref.astype(jnp.float32)).max()) < tol


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bwd_family_parity(causal):
    """The two transposed-stationarity bwd instantiations against the
    XLA-autodiff oracle."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels import flash_attention
    from paddle_tpu.parallel.ring_attention import local_attention
    q, k, v = _qkv(1, 32, 2, 8, "float32", seed=3)

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v, causal=causal).astype(jnp.float32) ** 2)

    gk = jax.grad(loss(lambda *a, **kw: flash_attention(
        *a, block_q=8, block_kv=8, **kw)), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(local_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gr):
        assert float(jnp.abs(a - b).max()) < 5e-4


@pytest.mark.parametrize("kv_dtype,N,S,bkv", [
    ("float32", 3, 32, 8),
    ("float32", 1, 32, 16),            # batch-1 slot table
    ("float32", 3, 31, None),          # untileable S: fallback
    ("int8", 3, 32, 8),
    ("int8", 1, 32, 32),               # batch-1, whole-cache tile
    ("int8", 3, 31, None),             # int8 fallback path
])
def test_decode_family_parity(kv_dtype, N, S, bkv):
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels import (
        decode_attention, decode_attention_reference)
    q, kc, vc, lengths, scales = _decode_operands(N, S, 2, 8, kv_dtype)
    out = decode_attention(q, kc, vc, lengths, block_kv=bkv,
                           kv_scales=scales)
    ref = decode_attention_reference(q, kc, vc, lengths,
                                     kv_scales=scales)
    assert out.shape == (N, 2, 8)
    assert float(jnp.abs(out - ref).max()) < 2e-5


def test_decode_int8_requires_scales():
    from paddle_tpu.ops.pallas_kernels import decode_attention
    q, kc, vc, lengths, _ = _decode_operands(2, 32, 2, 8, "int8")
    with pytest.raises(ValueError, match="kv_scales"):
        decode_attention(q, kc, vc, lengths)


@pytest.mark.parametrize("M,K,N,blocks,act", [
    (8, 16, 32, (4, 8, 16), "float32"),
    (1, 32, 16, (1, 16, 8), "float32"),   # batch-1 serving bucket
    (8, 32, 64, (4, 16, 32), "bfloat16"),
    (3, 7, 13, None, "float32"),          # nothing tiles: fallback
])
def test_dequant_family_parity(M, K, N, blocks, act):
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels import (
        dequant_matmul, dequant_matmul_reference)
    rng = np.random.RandomState(M + N)
    x = jnp.asarray(rng.randn(M, K).astype(np.float32)).astype(act)
    wq = jnp.asarray(rng.randint(-127, 128, (K, N)).astype(np.int8))
    s = jnp.asarray(rng.rand(N).astype(np.float32) * 0.1 + 0.01)
    kw = dict(zip(("block_m", "block_k", "block_n"), blocks)) \
        if blocks else {}
    out = dequant_matmul(x, wq, s, out_dtype=np.float32, **kw)
    ref = dequant_matmul_reference(x, wq, s, out_dtype=np.float32)
    assert float(jnp.abs(out - ref).max()) < 1e-3


def test_substrate_parity_smoke():
    """The ci_checks `kernels` gate body: one tileable pass per family
    against its oracle on the shared core — fast, no fixtures."""
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels import (
        decode_attention, decode_attention_reference, dequant_matmul,
        dequant_matmul_reference, flash_attention)
    from paddle_tpu.parallel.ring_attention import local_attention
    q, k, v = _qkv(1, 32, 2, 8, "float32", seed=9)
    assert float(jnp.abs(
        flash_attention(q, k, v, causal=True, block_q=8, block_kv=8)
        - local_attention(q, k, v, causal=True)).max()) < 2e-5
    for kv_dtype in ("float32", "int8"):
        dq, kc, vc, lengths, scales = _decode_operands(
            2, 32, 2, 8, kv_dtype)
        assert float(jnp.abs(
            decode_attention(dq, kc, vc, lengths, block_kv=8,
                             kv_scales=scales)
            - decode_attention_reference(dq, kc, vc, lengths,
                                         kv_scales=scales)).max()) \
            < 2e-5
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(4, 16).astype(np.float32))
    wq = jnp.asarray(rng.randint(-127, 128, (16, 32)).astype(np.int8))
    s = jnp.asarray(np.full(32, 0.02, np.float32))
    assert float(jnp.abs(
        dequant_matmul(x, wq, s, block_m=4, block_k=8, block_n=16)
        - dequant_matmul_reference(x, wq, s)).max()) < 1e-4


# ---------------------------------------------------------------------------
# tuned block-geometry entries resolve across every namespace
# ---------------------------------------------------------------------------


def test_tuned_entries_resolve_every_namespace(store):
    """The substrate consolidation must not orphan the tuning
    registry: a recorded winner in each namespace (flash, DEC_* fp32,
    DEC_* int8, dequant) still resolves at trace time."""
    from paddle_tpu.ops import attention_tuning as at
    cfg = at.AttentionConfig(16, 32, 8, 8)
    at.record(64, 16, True, "float32", cfg)
    assert at.get_config(64, 16, True, "float32") == cfg
    at.record_decode(32, 8, "float32", 16)
    assert at.get_decode_config(32, 8, "float32") == 16
    at.record_decode(32, 8, "int8", 32)
    assert at.get_decode_config(32, 8, "int8") == 32
    # the two cache dtypes tune independently (distinct key families)
    assert at.get_decode_config(32, 8, "float32") == 16
    at.record_dequant(8, 32, 16, "float32", 4, 16, 8)
    assert at.get_dequant_config(8, 32, 16, "float32") == (4, 16, 8)


@pytest.mark.slow
def test_tune_kernels_driver_smoke(tmp_path):
    """The unified autotuner sweeps all three families, records
    winners into the registry, and each resolves (`"resolves": true`
    rows + DEC_*_int8 keys present).  slow-marked subprocess (the
    PR 12 rule) — the ci_checks `kernels` gate still runs it."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tune_kernels.py"),
         "--smoke", "--cache_dir", str(tmp_path / "reg")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    rows = [json.loads(ln) for ln in out.stdout.splitlines() if ln]
    tuned = [r for r in rows if r.get("metric") == "tuned"]
    assert {r["family"] for r in tuned} == {"flash", "decode",
                                            "dequant"}
    assert all(r["resolves"] for r in tuned)
    assert any(r.get("kv_dtype") == "int8" for r in tuned
               if r["family"] == "decode")


# ---------------------------------------------------------------------------
# int8 KV cache: the session-level contracts
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_lm(tmp_path_factory):
    from paddle_tpu.inference.decode import build_tiny_decode_model
    d = str(tmp_path_factory.mktemp("kvlm") / "lm")
    build_tiny_decode_model(d, vocab_size=32, d_model=16, n_heads=2,
                            n_layers=2, max_seq_len=64)
    return d


_PRED_CACHE = {}


def _open(tiny_lm, kv):
    """Module-cached predictors: every phase compiles once per
    (artifact, cache dtype) across the whole file — tier-1 budget is
    tight (the compile, not the math, is the cost here)."""
    from paddle_tpu.inference.decode import GenerativePredictor
    key = (tiny_lm, kv)
    if key not in _PRED_CACHE:
        _PRED_CACHE[key] = GenerativePredictor(tiny_lm,
                                               kv_cache_dtype=kv)
    return _PRED_CACHE[key]


def test_kv_dtype_resolution_and_normalize(tiny_lm):
    from paddle_tpu.inference.decode import (GenerativePredictor,
                                             normalize_kv_dtype)
    assert normalize_kv_dtype(None) == "float32"
    assert normalize_kv_dtype("fp32") == "float32"
    assert normalize_kv_dtype("int8") == "int8"
    with pytest.raises(ValueError):
        normalize_kv_dtype("int4")
    # artifact default is fp32; the explicit knob wins; clones inherit
    assert GenerativePredictor(tiny_lm).kv_cache_dtype == "float32"
    q8 = _open(tiny_lm, "int8")
    assert q8.kv_cache_dtype == "int8"
    assert q8.clone_to(None).kv_cache_dtype == "int8"
    # the FLAGS default kicks in when nothing pins the dtype
    old = fluid.get_flags(["serving_kv_cache_dtype"])
    try:
        fluid.set_flags({"serving_kv_cache_dtype": "int8"})
        assert GenerativePredictor(tiny_lm).kv_cache_dtype == "int8"
    finally:
        fluid.set_flags(old)


def test_int8_cache_bytes_smoke(tiny_lm):
    """Static AND measured cache bytes <= 0.27x fp32 at equal slots
    (the acceptance bound), and the closed form matches the live
    session's arrays."""
    fp, q8 = _open(tiny_lm, "float32"), _open(tiny_lm, "int8")
    assert q8.kv_cache_bytes(8) <= 0.27 * fp.kv_cache_bytes(8)
    sf, s8 = fp.new_session(8), q8.new_session(8)
    assert s8.cache_bytes() <= 0.27 * sf.cache_bytes()
    assert s8.cache_bytes() == q8.kv_cache_bytes(8)
    assert sf.cache_bytes() == fp.kv_cache_bytes(8)


def test_int8_top1_agreement_and_bit_stability_smoke(tiny_lm):
    """fp32-vs-int8 greedy top-1 agreement >= 0.99 on the tiny decode
    fixture, and the int8 stream is bit-stable against itself."""
    from paddle_tpu.inference.decode import greedy_decode
    fp, q8 = _open(tiny_lm, "float32"), _open(tiny_lm, "int8")
    prompts = [[3, 5, 7], [9, 4], [1, 2, 3, 4, 5], [8], [6, 6, 2, 9],
               [12, 30], [21, 7, 14]]
    agree = total = 0
    for p in prompts:
        a, _ = greedy_decode(fp, p, 16)
        b, _ = greedy_decode(q8, p, 16)
        assert b == greedy_decode(q8, p, 16)[0], \
            "int8 stream not bit-stable against itself"
        m = 0
        for x, y in zip(a, b):
            if x != y:
                break
            m += 1
        agree += m
        total += max(len(a), len(b))
    assert agree / total >= 0.99, \
        "fp32-vs-int8 top-1 agreement %.3f < 0.99" % (agree / total)


def test_int8_slot_reuse_zero_leakage(tiny_lm):
    """A freed int8 slot holds exact int8 zeros and its next occupant
    streams bit-exactly vs a fresh single-slot session — the chaos
    decode-disconnect invariant under the quantized cache."""
    from paddle_tpu.inference.decode import greedy_decode
    q8 = _open(tiny_lm, "int8")
    sess = q8.new_session(2)
    # occupy, advance, free — then check exact zeros at the byte level
    sess.prefill(0, [3, 5, 7])
    sess.prefill(1, [4, 4])
    for _ in range(3):
        sess.decode()
    sess.free(0)
    assert sess.slot_is_zero(0)
    assert np.asarray(sess._kc).dtype == np.int8
    # reuse slot 0 while slot 1 keeps decoding; parity vs fresh session
    t0 = sess.prefill(0, [9, 4])
    out = [t0]
    while len(out) < 6:
        out.append(int(sess.decode()[0]))
    ref, _ = greedy_decode(q8, [9, 4], 6)
    assert out == ref


def test_int8_rollback_bit_identity(tiny_lm):
    """DecodeSession.rollback under the quantized cache: rolled-back
    slots are bit-identical to never-advanced ones (the spec-decode
    draft-sync primitive survives quantization)."""
    q8 = _open(tiny_lm, "int8")
    sess = q8.new_session(2)
    sess.prefill(0, [3, 5, 7])
    kc0 = np.asarray(sess._kc).copy()
    vc0 = np.asarray(sess._vc).copy()
    last0 = int(sess.last_tokens[0])
    sess.decode()
    sess.decode()
    sess.rollback(0, 2, last_token=last0)
    assert (np.asarray(sess._kc) == kc0).all()
    assert (np.asarray(sess._vc) == vc0).all()
    assert int(sess.lengths[0]) == 3


def test_int8_spec_twin_accept_rate_one(tiny_lm):
    """The spec-decode accept-rate probe: with target AND draft on the
    int8 cache (same artifact twin) every drafted token verifies —
    accept rate reads exactly 1.0, streams match target-only decode."""
    from paddle_tpu.inference.decode import (SpeculativeDecodeSession,
                                             greedy_decode)
    q8 = _open(tiny_lm, "int8")
    twin = _open(tiny_lm, "int8")
    sess = SpeculativeDecodeSession(q8, twin, 2, 3)
    sess.prefill(0, [3, 5, 7])
    sess.prefill(1, [9, 4])
    committed = {0: [], 1: []}
    for _ in range(4):
        toks, counts = sess.step()
        for slot in (0, 1):
            committed[slot] += list(toks[slot, :counts[slot]])
    assert not sess.degraded
    assert sess.proposed > 0 and sess.accepted == sess.proposed
    # the committed stream (after the prefill token) must be the plain
    # greedy continuation of the same prompt on the same cache dtype
    for slot, prompt in ((0, [3, 5, 7]), (1, [9, 4])):
        ref, _ = greedy_decode(q8, prompt, 32)
        n = min(len(committed[slot]), len(ref) - 1)
        assert n > 0 and committed[slot][:n] == ref[1:1 + n]


# ---------------------------------------------------------------------------
# static pricing + serving surfaces
# ---------------------------------------------------------------------------


def test_resources_price_kv_dtype(tiny_lm):
    """Satellite pin: the decode KV closed form prices the cache dtype
    — analyze_artifact statically reads ~0.25x KV bytes for an
    int8-cache load, exactly matching the predictor's accounting."""
    from paddle_tpu.analysis import analyze_artifact
    r_fp = analyze_artifact(tiny_lm, decode_slots=4)
    r_q8 = analyze_artifact(tiny_lm, decode_slots=4,
                            kv_cache_dtype="int8")
    # fp32: 2 * L * slots * S * H * Dh * 4; int8: /4 + scale table
    assert r_fp.kv_cache_bytes == 2 * 2 * 4 * 64 * 2 * 8 * 4
    assert r_q8.kv_cache_bytes == 2 * 2 * 4 * 64 * 2 * 8 + 2 * 2 * 2 * 4
    assert r_q8.kv_cache_bytes <= 0.27 * r_fp.kv_cache_bytes
    assert r_q8.peak_bytes < r_fp.peak_bytes
    # both closed forms agree with the predictor's own accounting
    assert _open(tiny_lm, "float32").kv_cache_bytes(4) \
        == r_fp.kv_cache_bytes
    assert _open(tiny_lm, "int8").kv_cache_bytes(4) \
        == r_q8.kv_cache_bytes
    # a decode_meta pin prices itself with no override
    from paddle_tpu.inference.decode import (build_tiny_decode_model,
                                             save_decode_model)
    from paddle_tpu.native import wire
    import tempfile
    d2 = os.path.join(tempfile.mkdtemp(), "lm8")
    build_tiny_decode_model(d2, vocab_size=32, d_model=16, n_heads=2,
                            n_layers=2, max_seq_len=64)
    with open(os.path.join(d2, "decode_meta.bin"), "rb") as f:
        meta = wire.decode(f.read())
    meta["kv_cache_dtype"] = "int8"
    with open(os.path.join(d2, "decode_meta.bin"), "wb") as f:
        f.write(wire.encode(meta))
    assert analyze_artifact(d2, decode_slots=4).kv_cache_bytes \
        == r_q8.kv_cache_bytes


def test_serving_int8_kv_end_to_end(tiny_lm, tmp_path):
    """The full wire: load_model(kv_cache_dtype='int8') -> reply +
    describe carry the dtype, stats carry measured cache bytes at
    ~0.25x, streams are bit-exact vs a direct int8 session, and the
    fp32 twin loaded beside it stays fp32 (no collision)."""
    from paddle_tpu.inference.decode import greedy_decode
    from paddle_tpu.serving import InferenceServer, ServingClient
    server = InferenceServer().start()
    cli = ServingClient(server.endpoint)
    try:
        loaded = cli.load_model("lm8", tiny_lm, decode_slots=2,
                                kv_cache_dtype="int8")
        assert loaded["kv_cache_dtype"] == "int8"
        loaded_fp = cli.load_model("lmfp", tiny_lm, decode_slots=2)
        assert loaded_fp["kv_cache_dtype"] == "float32"
        reply = cli.stats()
        assert reply["models"]["lm8"]["kv_cache_dtype"] == "int8"
        assert reply["models"]["lmfp"]["kv_cache_dtype"] == "float32"
        stats = reply["stats"]["models"]
        q8 = _open(tiny_lm, "int8")
        fp = _open(tiny_lm, "float32")
        assert stats["lm8"]["kv_cache_dtype"] == "int8"
        assert stats["lm8"]["kv_cache_bytes"] == q8.kv_cache_bytes(2)
        assert stats["lmfp"]["kv_cache_bytes"] == fp.kv_cache_bytes(2)
        assert stats["lm8"]["kv_cache_bytes"] \
            <= 0.27 * stats["lmfp"]["kv_cache_bytes"]
        # served int8 stream == direct int8 session, token for token
        got = [t for ch in cli.infer_stream("lm8", [3, 5, 7],
                                            max_new_tokens=8,
                                            deadline_ms=60000.0)
               for t in ch]
        ref, _ = greedy_decode(q8, [3, 5, 7], 8)
        assert got == ref
        with pytest.raises(Exception):
            cli.load_model("bad", tiny_lm, kv_cache_dtype="int4")
    finally:
        cli.close()
        server.shutdown(drain=False, timeout=10.0)


def test_int8_kv_phase_fingerprints_isolated(tiny_lm, store):
    """fp32 and int8 executables never collide in the persistent
    compile cache: the same artifact opened both ways produces
    disjoint fingerprints (kv_dtype is a fingerprint field)."""
    fp, q8 = _open(tiny_lm, "float32"), _open(tiny_lm, "int8")
    import jax
    L, H, Dh, _ = fp._dims()
    specs = (jax.ShapeDtypeStruct((1, 8), np.dtype(np.int32)),
             jax.ShapeDtypeStruct((), np.dtype(np.int32)))
    fp_a = fp._fingerprint(("prefill", 8), specs)
    fp_b = q8._fingerprint(("prefill", 8), specs)
    assert fp_a != fp_b and fp_a["kv_dtype"] == "float32" \
        and fp_b["kv_dtype"] == "int8"


@pytest.mark.slow
def test_chaos_decode_disconnect_int8_smoke():
    """The chaos scenario under the quantized cache, as a subprocess
    (the CI re-run satellite): freed slots zeroed, zero leakage.
    slow-marked (the PR 12 rule) — runs in the ci_checks `kernels`
    gate, which invokes pytest without -m."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "--scenario", "decode-disconnect-int8"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "PASS decode-disconnect (kv=int8)" in out.stdout


@pytest.mark.slow
def test_bench_kv_dtype_ab_smoke():
    """bench_serving --decode --kv_dtype both: records carry the
    kv columns with the ratio and agreement bounds met."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "bench_serving.py"),
         "--decode", "--decode_mode", "cb", "--kv_dtype", "both",
         "--decode_slots", "2", "--qps", "6", "--duration", "2",
         "--smoke"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900)
    assert out.returncode == 0, out.stdout + out.stderr
    rows = [json.loads(ln) for ln in out.stdout.splitlines()
            if ln.startswith("{")]
    by_kv = {r.get("kv_cache_dtype"): r for r in rows
             if r.get("metric") == "serving_decode"}
    assert set(by_kv) == {"float32", "int8"}
    for r in by_kv.values():
        assert r["bit_exact"] is True
    q8 = by_kv["int8"]
    assert q8["kv_bytes_ratio_vs_fp32"] <= 0.27
    assert q8["kv_measured_ratio_vs_fp32"] <= 0.27
    assert q8["kv_top1_agreement"] >= 0.99
