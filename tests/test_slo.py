"""SLO engine + flight recorder tests (paddle_tpu/obs/slo.py,
paddle_tpu/obs/flightrec.py — OBSERVABILITY.md "SLOs & burn rates" /
"Flight recorder").

Pins the judgment-layer contracts on SYNTHETIC metric timelines (the
monitor's tick() is driven directly, no thread, no sleeps): fast burn
trips within two evaluations of a hard outage, slow burn needs a full
slow window (trips late, by design), hysteresis prevents state
flapping, and a recovery emits exactly one `slo_recovered`.  The
flight recorder's cooldown survives a 4-thread trigger hammer
(exactly one bundle), bundles validate deeply (manifest CRC walk) and
corruption is named, keep-N rotates, and the serving surfaces (`health`
RPC + ServingClient.health, serving_top SLO/LIVE columns, Prometheus
slo_*/events_* families, metrics_dump ring-health row) carry the new
signals.  Everything CPU-safe under JAX_PLATFORMS=cpu.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.flags import FLAGS, set_flags
from paddle_tpu.obs import events as obs_events
from paddle_tpu.obs import flightrec
from paddle_tpu.obs import slo as obs_slo
from paddle_tpu.obs import tracing as obs_tracing
from paddle_tpu.serving import (InferenceServer, ServingClient,
                                ServingMetrics, set_dispatch_delay)
from paddle_tpu.serving.batcher import _guarded

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, os.path.join(REPO, "tools"))

import flight_inspect  # noqa: E402
import serving_top  # noqa: E402

_OBS_DEFAULTS = {"trace": True, "trace_buffer_events": 4096,
                 "trace_slow_ms": 0.0, "event_log": "",
                 "event_log_max_kb": 1024, "serving_slo": "",
                 "slo_monitor": True, "slo_eval_interval_ms": 1000.0,
                 "flight_dir": "", "flight_keep": 8,
                 "flight_cooldown_s": 30.0}


@pytest.fixture(autouse=True)
def _obs_reset():
    set_flags(dict(_OBS_DEFAULTS))
    obs_tracing.configure()
    obs_tracing.clear()
    obs_events.configure()
    flightrec.configure()
    yield
    set_dispatch_delay(0.0)
    set_flags(dict(_OBS_DEFAULTS))
    obs_tracing.configure()
    obs_tracing.clear()
    obs_events.configure()
    flightrec.configure()


def _mk_monitor(**slo_kwargs):
    """A monitor over one synthetic model lane, stepped by hand."""
    sm = ServingMetrics()
    mm = sm.model("m")
    kwargs = dict(error_rate=0.1, fast_window=4, slow_window=12,
                  fast_burn=10.0, slow_burn=2.0, breach_evals=2,
                  recover_evals=3)
    kwargs.update(slo_kwargs)
    mon = obs_slo.SLOMonitor(sm, slos={"m": obs_slo.SLO(**kwargs)},
                             interval_s=0.05)
    return mon, mm


# ---------------------------------------------------------------------------
# burn-rate math on synthetic timelines
# ---------------------------------------------------------------------------

class TestBurnRate:
    def test_fast_burn_trips_early_on_hard_outage(self):
        """100% errors against a 10% budget burns at 10x: degraded on
        the first evaluatable tick, breach on the second (breach_evals
        hysteresis) — detection within 2 evaluation windows."""
        mon, mm = _mk_monitor()
        kinds = []
        for i in range(4):
            mm.requests.add(10)
            mm.errors.add(10)
            kinds += [k for k, _ in mon.tick()]
        assert kinds == ["slo_degraded", "slo_breach"]
        st = mon.state()["m"]
        assert st["state"] == "breach"
        assert st["tripped_by"] == "error_rate"
        assert st["burn"]["error_rate"]["fast"] == pytest.approx(10.0)

    def test_slow_burn_trips_late_needs_full_window(self):
        """A 30% error rate (burn 3x: under fast_burn 10, over
        slow_burn 2) must NOT trip until the slow window is full —
        low-grade burns prove themselves over the whole window."""
        mon, mm = _mk_monitor(slow_window=10)
        states = []
        for i in range(14):
            mm.requests.add(10)
            mm.errors.add(3)
            mm.responses.add(7)
            mon.tick()
            states.append(mon.state()["m"]["state"])
        # ticks 1..10 (9 intervals < slow_window samples): still ok
        assert set(states[:10]) == {"ok"}, states
        # once the slow window fills, the 3x burn trips -> escalates
        assert states[-1] == "breach", states

    def test_no_traffic_is_not_a_burn(self):
        mon, mm = _mk_monitor()
        for _ in range(8):
            assert mon.tick() == []
        assert mon.state()["m"]["state"] == "ok"

    def test_latency_objective_uses_windowed_p95(self):
        """The p95 SLI is the interval window's percentile, not the
        lifetime reservoir: a fresh regression trips even after a long
        healthy history."""
        # budget 0.2 caps the indicator burn at 1/0.2 = 5x, so
        # fast_burn must sit at or under that to be reachable
        mon, mm = _mk_monitor(error_rate=None, p95_ms=50.0, budget=0.2,
                              fast_burn=5.0)
        for i in range(6):   # healthy history
            mm.note_completion(latency_ms=5.0)
            mon.tick()
        assert mon.state()["m"]["state"] == "ok"
        kinds = []
        for i in range(7):   # regression: every completion 200ms
            mm.note_completion(latency_ms=200.0)
            mm.note_completion(latency_ms=210.0)
            kinds += [k for k, _ in mon.tick()]
        assert "slo_breach" in kinds
        assert mon.state()["m"]["tripped_by"] == "p95_ms"

    def test_hysteresis_prevents_flapping(self):
        """A flapping workload — breach bursts separated by clean gaps
        shorter than recover_evals — must produce ONE degraded + ONE
        breach event and ZERO recoveries: no event storm, state pinned
        at breach until a real sustained recovery."""
        mon, mm = _mk_monitor(fast_window=2, breach_evals=2,
                              recover_evals=3)
        # bad=True marks the counters mutated before that tick
        pattern = [True, True, True, True,   # burst: degraded, breach
                   False, True, True,        # 1-clean gap, burst again
                   False, True, True]        # ... and again
        events = []
        for bad in pattern:
            mm.requests.add(10)
            (mm.errors if bad else mm.responses).add(10)
            events += [k for k, _ in mon.tick()]
        assert events == ["slo_degraded", "slo_breach"], events
        assert mon.state()["m"]["state"] == "breach"

    def test_recovery_emits_exactly_one_slo_recovered(self):
        mon, mm = _mk_monitor(recover_evals=3)
        for _ in range(4):   # tick 1 is the baseline sample
            mm.requests.add(10)
            mm.errors.add(10)
            mon.tick()
        assert mon.state()["m"]["state"] == "breach"
        kinds = []
        for _ in range(10):
            mm.requests.add(10)
            mm.responses.add(10)
            kinds += [k for k, _ in mon.tick()]
        assert kinds == ["slo_recovered"], kinds
        st = mon.state()["m"]
        assert st["state"] == "ok" and st["recoveries"] == 1

    def test_shed_rate_and_spec_accept_objectives(self):
        mon, mm = _mk_monitor(error_rate=None, shed_rate=0.05,
                              spec_accept=0.8, fast_window=3)
        for _ in range(4):   # half the offered load sheds: burn 10x
            mm.requests.add(10)
            mm.shed.add(10)
            mm.draft_tokens.add(10)
            mm.accepted_tokens.add(3)  # accept 0.3 < 0.8 floor
            mon.tick()
        st = mon.state()["m"]
        assert st["state"] == "breach"
        burns = st["burn"]
        assert burns["shed_rate"]["fast"] == pytest.approx(10.0)
        # spec accept is an indicator objective against SLO.budget
        assert burns["spec_accept"]["fast"] == pytest.approx(10.0)

    def test_parse_slo_spec_forms(self):
        spec = ("p95_ms=250,error_rate=0.01;"
                "llm:ttft_p95_ms=400,spec_accept=0.5,fast_window=8")
        slos = obs_slo.parse_slo_spec(spec)
        assert slos["*"].p95_ms == 250.0
        assert slos["*"].error_rate == 0.01
        assert slos["llm"].ttft_p95_ms == 400.0
        assert slos["llm"].fast_window == 8
        assert obs_slo.parse_slo_spec("") == {}
        with pytest.raises(ValueError):
            obs_slo.parse_slo_spec("bogus_key=1")

    def test_lane_key_resolution_prefers_specific(self):
        mon = obs_slo.SLOMonitor(
            ServingMetrics(),
            slos={"*": obs_slo.SLO(p95_ms=1),
                  "m": obs_slo.SLO(p95_ms=2),
                  "m@int8": obs_slo.SLO(p95_ms=3)},
            interval_s=0.05)
        assert mon.slo_for("m@int8").p95_ms == 3
        assert mon.slo_for("m").p95_ms == 2
        assert mon.slo_for("other").p95_ms == 1

    def test_timeline_ring_is_bounded(self):
        sm = ServingMetrics()
        sm.model("m")
        mon = obs_slo.SLOMonitor(sm, slos={}, interval_s=0.01,
                                 timeline_samples=16)
        for _ in range(40):
            mon.tick()
        tl = mon.timeline()["m"]
        assert len(tl) == 16
        assert set(tl[-1]) >= {"ts", "requests", "responses", "errors"}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_bundle_complete_and_valid(self, tmp_path):
        rec = flightrec.FlightRecorder(str(tmp_path), keep=4,
                                       cooldown_s=30.0)
        rec.add_provider("demo", lambda: {"answer": 42})
        with obs_tracing.trace("t", kind="serving"):
            pass
        obs_events.emit("probe", x=1)
        path = rec.trigger("watchdog_fire", what="step")
        assert path and os.path.isdir(path)
        assert flightrec.validate_bundle(path) == []
        manifest = flightrec.read_manifest(path)
        assert manifest["reason"] == "watchdog_fire"
        assert manifest["context"]["what"] == "step"
        names = set(manifest["files"])
        assert set(flightrec.REQUIRED_FILES) <= names
        assert "demo.json" in names
        with open(os.path.join(path, "demo.json")) as f:
            assert json.load(f) == {"answer": 42}
        with open(os.path.join(path, "threads.txt")) as f:
            assert "--- thread" in f.read()
        # the trigger also lands a flight_dumped event
        assert obs_events.recent_events(kind="flight_dumped")

    def test_cooldown_under_4_thread_trigger_hammer(self, tmp_path):
        """The breach-storm contract: 4 threads x 25 triggers of one
        reason within the cooldown produce exactly ONE bundle; a
        different reason gets its own."""
        rec = flightrec.FlightRecorder(str(tmp_path), keep=16,
                                       cooldown_s=60.0)
        paths = []
        lock = threading.Lock()

        def hammer():
            for _ in range(25):
                p = rec.trigger("slo_breach", model="m")
                if p is not None:
                    with lock:
                        paths.append(p)

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads)
        assert len(paths) == 1, \
            "cooldown leaked: %d bundles from one storm" % len(paths)
        assert len(rec.list_bundles()) == 1
        # a different reason has its own cooldown bucket
        assert rec.trigger("thread_death") is not None
        assert len(rec.list_bundles()) == 2

    def test_keep_n_rotation(self, tmp_path):
        rec = flightrec.FlightRecorder(str(tmp_path), keep=2,
                                       cooldown_s=0.0)
        paths = [rec.dump("r%d" % i) for i in range(4)]
        kept = rec.list_bundles()
        assert len(kept) == 2
        assert kept == sorted(paths[-2:])

    def test_validation_names_corruption(self, tmp_path):
        rec = flightrec.FlightRecorder(str(tmp_path))
        path = rec.dump("probe")
        target = os.path.join(path, "flags.json")
        with open(target, "ab") as f:
            f.write(b"tampered")
        problems = flightrec.validate_bundle(path)
        assert any("flags.json" in p for p in problems)

    def test_disabled_trigger_is_noop(self):
        assert flightrec.get_recorder() is None
        assert flightrec.trigger("slo_breach") is None

    def test_flag_configures_default_recorder(self, tmp_path):
        set_flags({"flight_dir": str(tmp_path / "fl"),
                   "flight_cooldown_s": 0.0, "flight_keep": 3})
        rec = flightrec.get_recorder()
        assert rec is not None and rec.keep == 3
        p = flightrec.trigger("manual")
        assert p is not None and flightrec.validate_bundle(p) == []

    def test_thread_death_guard_emits_and_triggers(self, tmp_path):
        """A batcher thread dying un-handled must land a
        server_thread_death event and a flight bundle before
        re-raising — the wedge post-mortem."""
        set_flags({"flight_dir": str(tmp_path / "fl"),
                   "flight_cooldown_s": 0.0})

        def boom():
            raise RuntimeError("lane exploded")

        wrapped = _guarded(boom, lambda: "m", "lane")

        def runner():
            try:
                wrapped()
            except RuntimeError:
                pass  # the guard re-raises after recording

        t = threading.Thread(target=runner, daemon=True)
        t.start()
        t.join(timeout=30)
        (ev,) = obs_events.recent_events(kind="server_thread_death")
        assert ev["model"] == "m" and "lane exploded" in ev["error"]
        bundles = flightrec.get_recorder().list_bundles()
        assert len(bundles) == 1
        manifest = flightrec.read_manifest(bundles[0])
        assert manifest["reason"] == "thread_death"


# ---------------------------------------------------------------------------
# serving surfaces
# ---------------------------------------------------------------------------

def _export_fc(tmp_path, seed=3, name="m", size=6):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        h = fluid.layers.fc(input=x, size=size, act="relu")
        pred = fluid.layers.fc(input=h, size=size, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / name)
        fluid.save_inference_model(md, ["x"], [pred], exe,
                                   main_program=main)
    return md


@pytest.fixture()
def slo_server(tmp_path):
    set_flags({"serving_slo": ("m:p95_ms=25,budget=0.2,fast_window=3,"
                               "slow_window=10,fast_burn=5,"
                               "breach_evals=2,recover_evals=2"),
               "slo_eval_interval_ms": 80.0,
               "flight_dir": str(tmp_path / "flight"),
               "flight_cooldown_s": 30.0})
    md = _export_fc(tmp_path)
    srv = InferenceServer(endpoint="127.0.0.1:0").start()
    srv.registry.load_model("m", md, buckets=[2, 4])
    cli = ServingClient(srv.endpoint)
    try:
        yield srv, cli, md
    finally:
        set_dispatch_delay(0.0)
        cli.close()
        srv.shutdown(drain=False, timeout=5.0)


class TestServingHealth:
    def test_health_rpc_shape_and_liveness(self, slo_server):
        srv, cli, md = slo_server
        cli.infer("m", {"x": np.zeros((1, 4), np.float32)},
                  deadline_ms=10000)
        h = cli.health()
        assert set(h) >= {"draining", "models", "slo", "slo_monitor",
                          "flight"}
        assert h["draining"] is False
        assert h["slo_monitor"]["running"] is True
        lane = h["models"]["m"]["lanes"]["fp32"]
        assert lane["decode"] is False
        live = lane["liveness"]
        assert live["kind"] == "batch" and live["router_alive"]
        assert live["lanes"][0]["alive"] >= 1
        assert live["lanes"][0]["last_dispatch_age_s"] is not None
        assert h["flight"]["bundles"] == 0

    def test_breach_detected_and_bundle_fires_end_to_end(
            self, slo_server):
        """The acceptance loop in-process: injected latency -> breach
        within 2 evaluation windows -> exactly one valid bundle ->
        recovery emits one slo_recovered -> replies bit-exact."""
        srv, cli, md = slo_server
        x = np.linspace(-1, 1, 4, dtype=np.float32).reshape(1, 4)
        ref = cli.infer("m", {"x": x}, deadline_ms=10000)
        set_dispatch_delay(0.06)
        budget_s = (2 * 3 + 1) * 0.08  # 2 fast windows + 1 tick slack
        t0 = time.monotonic()
        breach_at = None
        while time.monotonic() - t0 < budget_s + 3.0:
            cli.infer("m", {"x": x}, deadline_ms=10000)
            if obs_events.recent_events(kind="slo_breach"):
                breach_at = time.monotonic() - t0
                break
        assert breach_at is not None, "breach never detected"
        assert breach_at <= budget_s, \
            "detected after %.2fs > 2-window budget %.2fs" \
            % (breach_at, budget_s)
        assert cli.health()["slo"]["m"]["state"] == "breach"
        deadline = time.monotonic() + 10.0
        bundles = []
        while time.monotonic() < deadline and not bundles:
            bundles = flightrec.get_recorder().list_bundles()
            time.sleep(0.02)
        assert len(bundles) == 1
        assert flightrec.validate_bundle(bundles[0]) == []
        set_dispatch_delay(0.0)
        t0 = time.monotonic()
        while time.monotonic() - t0 < 10.0:
            cli.infer("m", {"x": x}, deadline_ms=10000)
            if obs_events.recent_events(kind="slo_recovered"):
                break
            time.sleep(0.04)
        assert len(obs_events.recent_events(kind="slo_recovered")) == 1
        assert cli.health()["slo"]["m"]["state"] == "ok"
        out = cli.infer("m", {"x": x}, deadline_ms=10000)
        assert np.array_equal(out[0], ref[0]), \
            "SLO monitoring changed reply bits"

    def test_flight_rpc_manual_dump(self, slo_server):
        srv, cli, md = slo_server
        path = cli.flight(reason="operator_probe")
        assert path is not None
        assert flightrec.validate_bundle(path) == []
        manifest = flightrec.read_manifest(path)
        assert manifest["reason"] == "operator_probe"
        # the bundle carries this server's snapshot provider file
        assert any(n.startswith("serving_") for n in manifest["files"])

    def test_prometheus_families_and_serving_top_columns(
            self, slo_server):
        srv, cli, md = slo_server
        cli.infer("m", {"x": np.zeros((1, 4), np.float32)},
                  deadline_ms=10000)
        time.sleep(0.3)  # a couple of monitor ticks
        text = cli.metrics_text()
        assert 'paddle_tpu_slo_state{model="m"}' in text
        assert "paddle_tpu_events_dropped_total" in text
        assert "paddle_tpu_events_sink_dead" in text
        assert "paddle_tpu_events_rotations_total" in text
        table = serving_top.render(cli.stats(), health=cli.health())
        hdr = table.splitlines()[2]
        assert "SLO" in hdr and "LIVE" in hdr
        row = next(l for l in table.splitlines() if l.startswith("m "))
        assert " ok " in row or row.rstrip().endswith("ok") \
            or "1/1" in row

    def test_slo_monitor_flag_off_no_thread(self, tmp_path):
        set_flags({"slo_monitor": False})
        md = _export_fc(tmp_path, name="m2")
        srv = InferenceServer(endpoint="127.0.0.1:0").start()
        try:
            srv.registry.load_model("m2", md, buckets=[2])
            cli = ServingClient(srv.endpoint)
            h = cli.health()
            assert "slo" not in h
            assert "models" in h  # liveness still served
            cli.close()
        finally:
            srv.shutdown(drain=False, timeout=5.0)


class TestEventAttribution:
    def test_deadline_and_slow_events_carry_replica(self, slo_server):
        srv, cli, md = slo_server
        set_flags({"trace_slow_ms": 1.0})
        x = np.zeros((1, 4), np.float32)
        set_dispatch_delay(0.05)
        cli.infer("m", {"x": x}, deadline_ms=10000)
        (slow,) = obs_events.recent_events(n=1, kind="slow")
        assert slow["replica"] == 0 and "device" in slow
        # deadline so short the dispatch screen expires it in-lane
        set_dispatch_delay(0.15)
        with pytest.raises(Exception):
            cli.infer("m", {"x": x}, deadline_ms=60.0,
                      retry_sheds=False)
        deadline = time.monotonic() + 10.0
        evs = []
        while time.monotonic() < deadline and not evs:
            evs = obs_events.recent_events(kind="deadline_expired")
            time.sleep(0.02)
        assert evs and evs[-1]["replica"] == 0
        set_dispatch_delay(0.0)

    def test_shed_event_carries_lane_occupancy(self, tmp_path):
        md = _export_fc(tmp_path, name="m3")
        srv = InferenceServer(endpoint="127.0.0.1:0",
                              max_queue=1).start()
        try:
            srv.registry.load_model("m3", md, buckets=[2])
            set_dispatch_delay(0.2)
            x = np.zeros((1, 4), np.float32)
            futs = []
            from paddle_tpu.serving import ServerOverloaded
            with pytest.raises(ServerOverloaded):
                for _ in range(8):
                    futs.append(srv.registry.submit("m3", {"x": x}))
            (shed,) = obs_events.recent_events(n=1, kind="shed")
            assert "inflight" in shed and "queue" in shed
            set_dispatch_delay(0.0)
            for f in futs:
                f.result(timeout=30)
        finally:
            set_dispatch_delay(0.0)
            srv.shutdown(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# CLIs + chaos
# ---------------------------------------------------------------------------

def _run_cli(args, **kw):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(kw.pop("env", {}))
    return subprocess.run([sys.executable] + args, cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=300,
                          **kw)


class TestCLIs:
    def test_flight_inspect_list_validate_show_exit_codes(
            self, tmp_path):
        rec = flightrec.FlightRecorder(str(tmp_path), cooldown_s=0.0)
        rec.add_provider("demo", lambda: {"n": 1})
        p1 = rec.dump("probe_a")
        rec.dump("probe_b")
        # in-process main(): list + validate clean
        assert flight_inspect.main([str(tmp_path)]) == 0
        assert flight_inspect.main([str(tmp_path), "--validate"]) == 0
        assert flight_inspect.main([p1, "--show"]) == 0
        # corrupt one payload: validate exits 2 and names it
        with open(os.path.join(p1, "demo.json"), "ab") as f:
            f.write(b"x")
        assert flight_inspect.main([str(tmp_path), "--validate"]) == 2
        assert flight_inspect.main(
            [str(tmp_path / "nowhere")]) == 1

    def test_flight_inspect_cli_subprocess_json(self, tmp_path):
        rec = flightrec.FlightRecorder(str(tmp_path), cooldown_s=0.0)
        rec.dump("probe")
        proc = _run_cli([os.path.join("tools", "flight_inspect.py"),
                         str(tmp_path), "--validate", "--json"])
        assert proc.returncode == 0, proc.stderr
        rows = json.loads(proc.stdout)
        assert rows and rows[0]["reason"] == "probe"
        assert rows[0]["valid"] is True

    def test_metrics_dump_local_ring_health_row(self):
        proc = _run_cli([os.path.join("tools", "metrics_dump.py"),
                         "--local"])
        assert proc.returncode == 0, proc.stderr
        assert "# ring-health: spans buffered=" in proc.stdout
        assert "sink=none" in proc.stdout

    def test_chaos_slo_breach_scenario_inprocess(self, tmp_path):
        """The tier-1 subset of the acceptance scenario (the SIGKILL
        phase runs in the ci_checks `slo` gate)."""
        import chaos
        res = chaos.scenario_slo_breach(str(tmp_path), verbose=False,
                                        kill_phase=False)
        assert res["breach_s"] <= res["budget_s"]

    def test_ci_checks_has_slo_gate(self):
        with open(os.path.join(REPO, "tools", "ci_checks.sh")) as f:
            src = f.read()
        assert "slo)" in src and "exit 14" in src
        assert "flight_inspect.py" in src
