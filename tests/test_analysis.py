"""Program verifier + runtime concurrency lint (ANALYSIS.md).

Seeded defect corpus: every checker class — use-before-def, shape/dtype
mismatch, dead op, unexportable op, fetch reachability on the program
side; notify-on-shared-cv, non-atomic vault write, non-monotonic
timing, unlocked shared mutation on the runtime side — has a fixture it
flags with block/op-index/var (or file:line), and the clean repo / model
zoo passes with exit 0 (suppressions documented in the tools).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.analysis import (ProgramVerificationError, check_program,
                                 verify_program, verify_program_cached)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")


def _checks(diags):
    return [d.check for d in diags]


def _find(diags, check):
    out = [d for d in diags if d.check == check]
    assert out, "no %r finding in %s" % (check, list(map(str, diags)))
    return out[0]


# ---------------------------------------------------------------------------
# program verifier — seeded defects, one per checker class
# ---------------------------------------------------------------------------

def test_use_before_def_names_block_op_var():
    p = Program()
    blk = p.global_block()
    blk.create_var(name="a", shape=[4], dtype="float32")
    blk.create_var(name="b", shape=[4], dtype="float32")
    blk.append_op(type="relu", inputs={"X": ["a"]},
                  outputs={"Out": ["b"]}, infer_shape=False)
    d = _find(verify_program(p), "use-before-def")
    assert (d.block, d.op_index, d.op_type, d.var) == (0, 0, "relu", "a")
    assert d.is_error


def test_undefined_var_flagged():
    p = Program()
    blk = p.global_block()
    blk.create_var(name="out", shape=[4], dtype="float32")
    blk.append_op(type="relu", inputs={"X": ["ghost"]},
                  outputs={"Out": ["out"]}, infer_shape=False)
    d = _find(verify_program(p), "undefined-var")
    assert d.var == "ghost" and d.is_error


def test_feeds_and_persistables_are_defined():
    p = Program()
    blk = p.global_block()
    blk.create_var(name="x", shape=[4], dtype="float32")  # via feeds=
    blk.create_var(name="w", shape=[4], dtype="float32", persistable=True)
    blk.create_var(name="o", shape=[4], dtype="float32")
    blk.append_op(type="elementwise_add", inputs={"X": ["x"], "Y": ["w"]},
                  outputs={"Out": ["o"]}, infer_shape=False)
    assert verify_program(p, feeds=["x"], fetches=["o"]) == []


def test_shape_mismatch_on_broadcast_reject():
    p = Program()
    blk = p.global_block()
    blk.create_var(name="x", shape=[4, 8], dtype="float32", is_data=True)
    blk.create_var(name="y", shape=[4, 7], dtype="float32", is_data=True)
    blk.create_var(name="z", shape=[4, 8], dtype="float32")
    blk.append_op(type="elementwise_add", inputs={"X": ["x"], "Y": ["y"]},
                  outputs={"Out": ["z"]}, infer_shape=False)
    d = _find(verify_program(p, feeds=["x", "y"], fetches=["z"]),
              "shape-mismatch")
    assert (d.block, d.op_index, d.op_type) == (0, 0, "elementwise_add")


def test_shape_mismatch_recorded_vs_inferred():
    p = Program()
    blk = p.global_block()
    blk.create_var(name="x", shape=[4, 8], dtype="float32", is_data=True)
    blk.create_var(name="z", shape=[4, 9], dtype="float32")  # lie: relu keeps 8
    blk.append_op(type="relu", inputs={"X": ["x"]},
                  outputs={"Out": ["z"]}, infer_shape=False)
    d = _find(verify_program(p, feeds=["x"], fetches=["z"]),
              "shape-mismatch")
    assert d.var == "z" and "(4, 9)" in d.message


def test_dtype_mismatch():
    p = Program()
    blk = p.global_block()
    blk.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    blk.create_var(name="z", shape=[4], dtype="int32")
    blk.append_op(type="relu", inputs={"X": ["x"]},
                  outputs={"Out": ["z"]}, infer_shape=False)
    d = _find(verify_program(p, feeds=["x"], fetches=["z"]),
              "dtype-mismatch")
    assert d.var == "z" and d.is_error


def test_dead_op_and_unused_var():
    p = Program()
    blk = p.global_block()
    blk.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    blk.create_var(name="z", shape=[4], dtype="float32")
    blk.create_var(name="dead", shape=[4], dtype="float32")
    blk.create_var(name="stale", shape=[2], dtype="float32")
    blk.append_op(type="relu", inputs={"X": ["x"]},
                  outputs={"Out": ["z"]}, infer_shape=False)
    blk.append_op(type="scale", inputs={"X": ["x"]},
                  outputs={"Out": ["dead"]}, attrs={"scale": 2.0},
                  infer_shape=False)
    diags = verify_program(p, feeds=["x"], fetches=["z"])
    d = _find(diags, "dead-op")
    assert (d.op_index, d.op_type, d.var) == (1, "scale", "dead")
    assert not d.is_error                  # warnings: report, don't fail
    assert _find(diags, "unused-var").var == "stale"
    # the same program with BOTH outputs fetched is clean
    diags2 = verify_program(p, feeds=["x"], fetches=["z", "dead"])
    assert "dead-op" not in _checks(diags2)


def test_dead_op_spares_side_effects_and_persistable_writers():
    p = Program()
    blk = p.global_block()
    blk.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    blk.create_var(name="z", shape=[4], dtype="float32")
    blk.create_var(name="buf", shape=[4], dtype="float32",
                   persistable=True)
    blk.append_op(type="relu", inputs={"X": ["x"]},
                  outputs={"Out": ["z"]}, infer_shape=False)
    # writes a persistable: live even though nothing fetches it
    blk.append_op(type="assign", inputs={"X": ["z"]},
                  outputs={"Out": ["buf"]}, infer_shape=False)
    assert "dead-op" not in _checks(
        verify_program(p, feeds=["x"], fetches=["z"]))


def test_fetch_reachability_and_unused_feed():
    p = Program()
    blk = p.global_block()
    blk.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    blk.create_var(name="orphan", shape=[4], dtype="float32")
    diags = verify_program(p, feeds=["x"], fetches=["nope", "orphan"])
    assert _find(diags, "unknown-fetch").var == "nope"
    assert _find(diags, "unreachable-fetch").var == "orphan"
    assert _find(diags, "unused-feed").var == "x"


def test_aot_export_lint_predicts_unexportable_and_ineligible():
    # host op -> _UNEXPORTABLE prediction
    p = Program()
    blk = p.global_block()
    blk.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    blk.create_var(name="z", shape=[4], dtype="float32")
    blk.append_op(type="py_func", inputs={"X": ["x"]},
                  outputs={"Out": ["z"]}, infer_shape=False)
    d = _find(verify_program(p, feeds=["x"], fetches=["z"]),
              "aot-unexportable")
    assert d.op_type == "py_func" and not d.is_error

    # training program -> executor _aot_cache_eligible gate prediction
    from paddle_tpu.models import mnist
    main, _s, feeds, loss, acc, _p = mnist.get_model(batch_size=4)
    diags = verify_program(main, feeds=[f.name for f in feeds],
                           fetches=[loss.name, acc.name])
    assert "aot-ineligible" in _checks(diags)
    # and that is the ONLY finding class on the zoo training program
    assert set(_checks(diags)) == {"aot-ineligible"}


def test_cross_block_def_use():
    # a conditional_block's sub-block reads a parent var defined BEFORE
    # the op (ok) and one defined only AFTER it (flagged, cross-block)
    p = Program()
    blk = p.global_block()
    blk.create_var(name="cond", shape=[1], dtype="bool", is_data=True)
    blk.create_var(name="before", shape=[4], dtype="float32",
                   is_data=True)
    blk.create_var(name="late", shape=[4], dtype="float32")
    sub = p._create_block()
    sub.create_var(name="tmp", shape=[4], dtype="float32")
    sub.append_op(type="elementwise_add",
                  inputs={"X": ["before"], "Y": ["late"]},
                  outputs={"Out": ["tmp"]}, infer_shape=False)
    p._rollback()
    blk.append_op(type="conditional_block", inputs={"Cond": ["cond"]},
                  outputs={}, attrs={"sub_block": sub},
                  infer_shape=False)
    blk.append_op(type="scale", inputs={"X": ["before"]},
                  outputs={"Out": ["late"]}, attrs={"scale": 1.0},
                  infer_shape=False)
    d = _find(verify_program(p, feeds=["cond", "before"]),
              "use-before-def")
    assert d.var == "late" and d.block == 1


def test_while_loop_body_not_false_positive():
    """Loop bodies read carries written later in the body (iteration
    N-1 -> N); the walker must not flag them."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
        n = fluid.layers.fill_constant(shape=[1], dtype="int64", value=3)
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        acc = fluid.layers.fill_constant(shape=[1, 4], dtype="float32",
                                         value=0.0)
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond, max_iters=3)
        with w.block():
            acc2 = fluid.layers.elementwise_add(acc, x)
            fluid.layers.assign(acc2, acc)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(i, n, cond=cond)
    diags = verify_program(main, feeds=["x"], fetches=[acc.name])
    assert not any(d.is_error for d in diags), list(map(str, diags))


def test_dynamic_rnn_recurrent_injected_vars_not_flagged():
    from paddle_tpu.models import machine_translation as mt
    out = mt.get_model(batch_size=2, embedding_dim=16, encoder_size=16,
                       decoder_size=16, dict_size=64)
    main, _, feeds, loss, _, pred = out
    diags = verify_program(
        main, feeds=[f if isinstance(f, str) else f.name for f in feeds],
        fetches=[loss.name, pred.name])
    assert not any(d.is_error for d in diags), list(map(str, diags))


# ---------------------------------------------------------------------------
# policy surfaces: check_program / memoized cache / executor flag /
# artifact boundaries
# ---------------------------------------------------------------------------

def _broken_program():
    p = Program()
    blk = p.global_block()
    blk.create_var(name="a", shape=[4], dtype="float32")
    blk.create_var(name="b", shape=[4], dtype="float32")
    blk.append_op(type="relu", inputs={"X": ["a"]},
                  outputs={"Out": ["b"]}, infer_shape=False)
    return p


def test_check_program_raises_with_locations():
    with pytest.raises(ProgramVerificationError) as ei:
        check_program(_broken_program(), fetches=["b"], what="seeded")
    msg = str(ei.value)
    assert "use-before-def" in msg and "block 0 op 0" in msg
    assert "'a'" in msg
    assert any(d.check == "use-before-def"
               for d in ei.value.diagnostics)


def test_verify_memo_caches_and_invalidates_on_version():
    p = Program()
    blk = p.global_block()
    blk.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    blk.create_var(name="z", shape=[4], dtype="float32")
    blk.append_op(type="relu", inputs={"X": ["x"]},
                  outputs={"Out": ["z"]}, infer_shape=False)
    d1 = verify_program_cached(p, feeds=["x"], fetches=["z"])
    assert verify_program_cached(p, feeds=["x"], fetches=["z"]) is d1
    # mutating the program bumps the version -> fresh analysis
    blk.append_op(type="scale", inputs={"X": ["ghost"]},
                  outputs={"Out": ["z2"]}, attrs={"scale": 1.0},
                  infer_shape=False)
    blk.create_var(name="z2", shape=[4], dtype="float32")
    with pytest.raises(ProgramVerificationError):
        verify_program_cached(p, feeds=["x"], fetches=["z"])
    # the failure is memoized too: same error object class on repeat
    with pytest.raises(ProgramVerificationError):
        verify_program_cached(p, feeds=["x"], fetches=["z"])


def test_flag_gates_executor_and_raises_on_broken_program():
    exe = fluid.Executor(fluid.CPUPlace())
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        h = fluid.layers.fc(x, size=4)
    fluid.set_flags({"verify_program": True})
    try:
        exe.run(startup)
        (out,) = exe.run(main, feed={"x": np.ones((2, 8), np.float32)},
                         fetch_list=[h])
        assert np.asarray(out).shape == (2, 4)
        with pytest.raises(ProgramVerificationError):
            exe.run(_broken_program(), feed={}, fetch_list=["b"])
    finally:
        fluid.set_flags({"verify_program": False})


def test_verify_events_land_in_obs_log():
    from paddle_tpu.obs import events as obs_events
    before = obs_events.events_total()
    verify_program(_broken_program(), fetches=["b"], what="evt-test")
    evs = [e for e in obs_events.recent_events(kind="verify_finding")
           if e.get("what") == "evt-test"]
    assert obs_events.events_total() > before
    assert any(e.get("check") == "use-before-def" and
               e.get("op_type") == "relu" for e in evs)


def test_save_inference_model_rejects_broken_graph(tmp_path):
    # build a valid program, then surgically break the pruned subgraph:
    # the op computing the fetch reads a var nothing defines
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        h = fluid.layers.fc(x, size=4)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    gb = main.global_block()
    mul_op = next(op for op in gb.ops if op.type == "mul")
    mul_op.inputs["X"] = ["never_defined"]
    with pytest.raises(ProgramVerificationError):
        fluid.io.save_inference_model(str(tmp_path / "m"), ["x"],
                                      [gb.var(h.name)], exe,
                                      main_program=main)


def test_load_inference_model_rejects_tampered_artifact(tmp_path):
    # a good artifact round-trips; hand-tampering its program JSON to
    # read an undefined var is rejected AT LOAD with named diagnostics
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        h = fluid.layers.fc(x, size=4)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    d = str(tmp_path / "art")
    fluid.io.save_inference_model(d, ["x"], [main.global_block().var(h.name)],
                                  exe, main_program=main)
    prog, feeds, fetch_vars = fluid.io.load_inference_model(d, exe)
    assert feeds == ["x"]
    meta = json.load(open(os.path.join(d, "__model__")))
    pdata = json.loads(meta["program"])
    for op in pdata["blocks"][0]["ops"]:
        if op["type"] == "mul":
            op["inputs"]["X"] = ["never_defined"]
    meta["program"] = json.dumps(pdata)
    with open(os.path.join(d, "__model__"), "w") as f:
        json.dump(meta, f)
    with pytest.raises(ProgramVerificationError) as ei:
        fluid.io.load_inference_model(d, exe)
    assert "never_defined" in str(ei.value)


# ---------------------------------------------------------------------------
# debugger annotations (satellite)
# ---------------------------------------------------------------------------

def _dead_and_mismatch_program():
    p = Program()
    blk = p.global_block()
    blk.create_var(name="x", shape=[4, 8], dtype="float32", is_data=True)
    blk.create_var(name="y", shape=[4, 7], dtype="float32", is_data=True)
    blk.create_var(name="z", shape=[4, 8], dtype="float32")
    blk.create_var(name="dead", shape=[4, 8], dtype="float32")
    blk.append_op(type="elementwise_add", inputs={"X": ["x"], "Y": ["y"]},
                  outputs={"Out": ["z"]}, infer_shape=False)
    blk.append_op(type="scale", inputs={"X": ["x"]},
                  outputs={"Out": ["dead"]}, attrs={"scale": 2.0},
                  infer_shape=False)
    return p, verify_program(p, feeds=["x", "y"], fetches=["z"])


def test_pprint_annotates_findings():
    p, diags = _dead_and_mismatch_program()
    txt = fluid.debugger.pprint_program_codes(p, diagnostics=diags)
    assert "# [dead] scale" in txt                      # dimmed dead op
    assert "!error[shape-mismatch]" in txt              # mismatch marker
    # without diagnostics the output is the bare program (old contract)
    bare = fluid.debugger.pprint_program_codes(p)
    assert "dead" in bare and "[dead]" not in bare


def test_graphviz_annotates_findings(tmp_path):
    p, diags = _dead_and_mismatch_program()
    path = str(tmp_path / "g.dot")
    dot = fluid.debugger.draw_block_graphviz(
        p.global_block(), path=path, diagnostics=diags)
    assert os.path.exists(path)
    assert 'fillcolor="gray90"' in dot          # dead op dimmed
    assert "dashed" in dot
    assert 'fillcolor="lightcoral"' in dot      # mismatch highlighted
    assert '[color="red", penwidth=2]' in dot   # mismatch edges painted


# ---------------------------------------------------------------------------
# CLIs (tier-1 exit-code pins): 0 on the clean repo/zoo, 2 with the
# offending file:line / block/op on the seeded-defect fixtures
# ---------------------------------------------------------------------------

def _run_tool(args, timeout=600):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run([sys.executable] + args, capture_output=True,
                          text=True, timeout=timeout, cwd=REPO, env=env)


def test_lint_runtime_cli_clean_repo_exit_0():
    r = _run_tool([os.path.join(REPO, "tools", "lint_runtime.py"),
                   "--smoke"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout
    # the suppression table is in force, not empty-by-accident
    assert "suppressed" in r.stdout


def test_lint_runtime_cli_flags_seeded_defects_exit_2():
    fixtures = [os.path.join(FIXTURES, f) for f in
                ("bad_notify.py", "bad_vault_write.py",
                 "bad_wallclock.py", "bad_unlocked.py")]
    r = _run_tool([os.path.join(REPO, "tools", "lint_runtime.py")]
                  + fixtures)
    assert r.returncode == 2, r.stdout + r.stderr
    out = r.stdout
    for check, path in (
            ("notify-shared-cv", "bad_notify.py"),
            ("nonatomic-vault-write", "bad_vault_write.py"),
            ("nonmonotonic-time", "bad_wallclock.py"),
            ("unlocked-shared-mutation", "bad_unlocked.py")):
        line = next((ln for ln in out.splitlines() if check in ln), None)
        assert line and path in line, (check, out)
        # file:line format
        assert ":" in line.split(" ", 1)[0]
        assert line.split(":")[1].isdigit(), line


def test_lint_runtime_cli_flags_lock_order_fixture_exit_2():
    # nested-lock-order: two locks taken in opposite orders across
    # methods — the deadlock-shape check added with the resource
    # analyzer PR; the repo itself must stay clean of it (the --smoke
    # exit-0 test above covers that side)
    r = _run_tool([os.path.join(REPO, "tools", "lint_runtime.py"),
                   os.path.join(FIXTURES, "bad_lock_order.py")])
    assert r.returncode == 2, r.stdout + r.stderr
    line = next((ln for ln in r.stdout.splitlines()
                 if "nested-lock-order" in ln), None)
    assert line and "bad_lock_order.py" in line, r.stdout
    assert line.split(":")[1].isdigit()
    # the message names BOTH sites of the inversion
    assert "transfer_out" in line and "Account.transfer_in" in line


# ---------------------------------------------------------------------------
# tools/ci_checks.sh — the one-command CI gate; its per-gate exit codes
# are the contract a CI wrapper keys on (10 lint_runtime,
# 11 lint_program, 12 apispec, 1 usage, 0 clean)
# ---------------------------------------------------------------------------

def _run_ci(args, env_extra=None, timeout=600):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(env_extra or {})
    return subprocess.run(
        ["bash", os.path.join(REPO, "tools", "ci_checks.sh")] + args,
        capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env=env)


def test_ci_checks_clean_gate_exit_0():
    r = _run_ci(["lint_runtime"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ci_checks: OK" in r.stdout


def test_ci_checks_unknown_gate_exit_1():
    r = _run_ci(["no_such_gate"])
    assert r.returncode == 1, r.stdout + r.stderr


def test_ci_checks_apispec_drift_exit_12(tmp_path):
    # point the gate at a stale spec copy: drift must exit 12 and name
    # the regeneration command — the committed API.spec itself is
    # covered by test_api_spec.py
    stale = tmp_path / "API.stale"
    with open(os.path.join(REPO, "API.spec")) as f:
        lines = f.read().splitlines()
    stale.write_text("\n".join(lines[:-1] + ["ghost.symbol (x)"]) + "\n")
    r = _run_ci(["apispec"], env_extra={"API_SPEC": str(stale)})
    assert r.returncode == 12, r.stdout + r.stderr
    assert "drifted" in r.stdout


def test_lint_program_cli_smoke_zoo_clean_exit_0():
    r = _run_tool([os.path.join(REPO, "tools", "lint_program.py"),
                   "--smoke"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "zoo:mnist:main" in r.stdout
    assert "FAIL" not in r.stdout


def test_lint_program_cli_flags_bad_artifact_exit_2(tmp_path):
    # seeded-defect artifact: a program whose only op reads an
    # undefined var, written in the save_inference_model layout
    p = Program()
    blk = p.global_block()
    blk.create_var(name="x", shape=[4], dtype="float32", is_data=True)
    blk.create_var(name="z", shape=[4], dtype="float32")
    blk.append_op(type="relu", inputs={"X": ["ghost"]},
                  outputs={"Out": ["z"]}, infer_shape=False)
    art = tmp_path / "bad_art"
    art.mkdir()
    with open(str(art / "__model__"), "w") as f:
        json.dump({"program": p.serialize_to_string(),
                   "feed_names": ["x"], "fetch_names": ["z"]}, f)
    r = _run_tool([os.path.join(REPO, "tools", "lint_program.py"),
                   str(art)])
    assert r.returncode == 2, r.stdout + r.stderr
    assert "undefined-var" in r.stdout
    assert "block 0 op 0" in r.stdout and "ghost" in r.stdout
