"""pad2d reference oracle (pad2d_op.cc): [top,bottom,left,right]
padding in constant/reflect/edge mode under BOTH data formats — the
NHWC kernel pads the spatial axes 1-2, not 2-3."""

import numpy as np
import pytest

from tests.test_op_tail import run_op


def oracle(x, p, mode, value, fmt):
    hw = ((p[0], p[1]), (p[2], p[3]))
    pads = (((0, 0), (0, 0)) + hw if fmt == "NCHW"
            else ((0, 0),) + hw + ((0, 0),))
    if mode == "constant":
        return np.pad(x, pads, constant_values=value)
    return np.pad(x, pads, mode={"reflect": "reflect",
                                 "edge": "edge"}[mode])


@pytest.mark.parametrize("fmt", ["NCHW", "NHWC"])
@pytest.mark.parametrize("mode", ["constant", "reflect", "edge"])
def test_pad2d_matches_reference(fmt, mode):
    x = np.random.RandomState(0).randn(2, 3, 4, 5).astype(np.float32)
    p = [1, 2, 2, 1]
    out = run_op("pad2d", {"X": x},
                 {"paddings": p, "mode": mode, "pad_value": 1.5,
                  "data_format": fmt})
    np.testing.assert_allclose(np.asarray(out["Out"]),
                               oracle(x, p, mode, 1.5, fmt), atol=1e-6)
