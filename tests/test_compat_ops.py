"""Final op-tail parity sweep (ops/compat_ops.py) — numpy-diff checks in
the OpTest style (reference unittests/op_test.py)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.ops  # noqa: F401 — registers everything
from paddle_tpu.ops.registry import ExecContext


class _FakeOp:
    def __init__(self, type, inputs=None, outputs=None, attrs=None):
        self.type = type
        self.inputs = inputs or {}
        self.outputs = outputs or {}
        self.attrs = attrs or {}


def _run(op_type, inputs, attrs=None, outputs=None):
    from paddle_tpu.ops.registry import get_op_def
    import jax.numpy as jnp
    vals = {k: [jnp.asarray(v) for v in (vs if isinstance(vs, list)
                                         else [vs])]
            for k, vs in inputs.items()}
    op = _FakeOp(op_type, outputs=outputs or {}, attrs=attrs or {})
    return get_op_def(op_type).lower(ExecContext(op, vals))


def test_conv2d_fusion_matches_conv_bias_relu():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 8, 8).astype("float32")
    w = rng.randn(4, 3, 3, 3).astype("float32")
    b = rng.randn(4).astype("float32")
    attrs = {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
             "groups": 1, "activation": "relu"}
    fused = np.asarray(_run("conv2d_fusion",
                            {"Input": x, "Filter": w, "Bias": b},
                            attrs)["Output"])
    plain = np.asarray(_run("conv2d", {"Input": x, "Filter": w},
                            attrs)["Output"])
    ref = np.maximum(plain + b.reshape(1, -1, 1, 1), 0)
    np.testing.assert_allclose(fused, ref, atol=1e-5)


def test_add_position_encoding():
    x = np.zeros((1, 4, 6), np.float32)
    out = np.asarray(_run("add_position_encoding", {"X": x},
                          {"alpha": 1.0, "beta": 1.0})["Out"])
    # position 0: sin part 0, cos part 1
    np.testing.assert_allclose(out[0, 0, :3], np.zeros(3), atol=1e-6)
    np.testing.assert_allclose(out[0, 0, 3:], np.ones(3), atol=1e-6)
    # sin(1) at pos 1, first frequency
    assert abs(out[0, 1, 0] - np.sin(1.0)) < 1e-5


def test_conv_shift_circular():
    x = np.arange(5, dtype=np.float32).reshape(1, 5)
    y = np.array([[1.0, 2.0, 3.0]], np.float32)
    out = np.asarray(_run("conv_shift", {"X": x, "Y": y})["Out"])
    ref = np.zeros(5, np.float32)
    for i in range(5):
        for j in range(3):
            ref[i] += x[0, (i + j - 1) % 5] * y[0, j]
    np.testing.assert_allclose(out[0], ref, atol=1e-5)


def test_cos_sim_maxout_prelu_minus():
    rng = np.random.RandomState(1)
    a = rng.randn(3, 4).astype("float32")
    b = rng.randn(3, 4).astype("float32")
    r = _run("cos_sim", {"X": a, "Y": b})
    ref = (a * b).sum(1) / (np.linalg.norm(a, axis=1)
                            * np.linalg.norm(b, axis=1))
    np.testing.assert_allclose(np.asarray(r["Out"]).ravel(), ref,
                               atol=1e-5)

    x = rng.randn(2, 6, 3, 3).astype("float32")
    mo = np.asarray(_run("maxout", {"X": x}, {"groups": 3})["Out"])
    assert mo.shape == (2, 2, 3, 3)
    np.testing.assert_allclose(
        mo, x.reshape(2, 2, 3, 3, 3).max(axis=2), atol=1e-6)

    alpha = np.array([0.1, 0.2, 0.3], np.float32)
    xp = rng.randn(2, 3, 2, 2).astype("float32")
    pr = np.asarray(_run("prelu", {"X": xp, "Alpha": alpha},
                         {"mode": "channel"})["Out"])
    ref = np.where(xp >= 0, xp, alpha.reshape(1, 3, 1, 1) * xp)
    np.testing.assert_allclose(pr, ref, atol=1e-6)

    mn = np.asarray(_run("minus", {"X": a, "Y": b})["Out"])
    np.testing.assert_allclose(mn, a - b, atol=1e-6)


def test_modified_huber_and_l1_norm_and_multiplex():
    x = np.array([[2.0], [0.5], [-3.0]], np.float32)
    y = np.array([[1.0], [0.0], [1.0]], np.float32)
    out = np.asarray(_run("modified_huber_loss",
                          {"X": x, "Y": y})["Out"]).ravel()
    z = (2 * y - 1).ravel() * x.ravel()
    ref = np.where(z < -1, -4 * z, np.maximum(1 - z, 0) ** 2)
    np.testing.assert_allclose(out, ref, atol=1e-5)

    l1 = np.asarray(_run("l1_norm", {"X": x})["Out"])
    np.testing.assert_allclose(l1, [5.5], atol=1e-6)

    c0 = np.full((3, 2), 0.0, np.float32)
    c1 = np.full((3, 2), 1.0, np.float32)
    ids = np.array([[1], [0], [1]], np.int32)
    mx = np.asarray(_run("multiplex", {"Ids": ids, "X": [c0, c1]})["Out"])
    np.testing.assert_allclose(mx[:, 0], [1, 0, 1], atol=1e-6)


def test_max_pool2d_with_index():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    r = _run("max_pool2d_with_index", {"X": x},
             {"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0]})
    out, mask = np.asarray(r["Out"]), np.asarray(r["Mask"])
    np.testing.assert_allclose(out[0, 0], [[5, 7], [13, 15]], atol=1e-6)
    np.testing.assert_array_equal(mask[0, 0], [[5, 7], [13, 15]])


def test_lod_rank_table_and_reorder():
    import jax.numpy as jnp
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    lens = np.array([2, 4, 3], np.int32)
    perm = np.asarray(_run("lod_rank_table",
                           {"X": x, "X@LOD_LEN": lens})["Out"])
    np.testing.assert_array_equal(perm, [1, 2, 0])  # lengths 4,3,2
    r = _run("reorder_lod_tensor_by_rank",
             {"X": x, "X@LOD_LEN": lens, "RankTable": perm})
    np.testing.assert_allclose(np.asarray(r["Out"])[0], x[1], atol=1e-6)
    np.testing.assert_array_equal(np.asarray(r["Out@LOD_LEN"]), [4, 3, 2])


def test_split_merge_lod_tensor_roundtrip():
    x = np.arange(8, dtype=np.float32).reshape(4, 2)
    mask = np.array([[1], [0], [1], [0]], np.int32)
    s = _run("split_lod_tensor", {"X": x, "Mask": mask})
    m = _run("merge_lod_tensor",
             {"InTrue": np.asarray(s["OutTrue"]),
              "InFalse": np.asarray(s["OutFalse"]), "Mask": mask,
              "X": x})
    np.testing.assert_allclose(np.asarray(m["Out"]), x, atol=1e-6)


def test_split_ids_merge_ids_roundtrip():
    ids = np.array([[1], [2], [3], [4], [5], [6]], np.int64)
    s = _run("split_ids", {"Ids": ids},
             outputs={"Out": ["o0", "o1", "o2"]})
    shards = [np.asarray(p).ravel().tolist() for p in s["Out"]]
    assert shards == [[3, 6], [1, 4], [2, 5]]
    rows = [np.asarray([[v / 10.0, v / 10.0] for v in shard],
                       dtype=np.float32) for shard in shards]
    m = _run("merge_ids", {"Ids": ids, "X": rows})
    np.testing.assert_allclose(
        np.asarray(m["Out"])[:, 0], np.arange(1, 7) / 10.0, atol=1e-6)


def test_split_byref_and_tensor_array_to_tensor():
    x = np.arange(10, dtype=np.float32).reshape(5, 2)
    s = _run("split_byref", {"X": x}, {"height_sections": [2, 3]},
             outputs={"Out": ["a", "b"]})
    assert np.asarray(s["Out"][0]).shape == (2, 2)
    assert np.asarray(s["Out"][1]).shape == (3, 2)

    r = _run("tensor_array_to_tensor",
             {"X": [x[:2], x[2:]]}, {"axis": 0, "use_stack": False})
    np.testing.assert_allclose(np.asarray(r["Out"]), x, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(r["OutIndex"]), [2, 3])


def test_detection_map_perfect_predictions():
    # one image, two gt boxes of class 1, perfectly detected
    gt = np.array([[1, 0, 0, 1, 1], [1, 2, 2, 3, 3]], np.float32)
    det = np.array([[1, 0.9, 0, 0, 1, 1], [1, 0.8, 2, 2, 3, 3]],
                   np.float32)
    r = _run("detection_map", {"DetectRes": det, "Label": gt},
             {"overlap_threshold": 0.5, "ap_type": "integral"})
    assert abs(float(np.asarray(r["MAP"])[0]) - 1.0) < 1e-6
    # a wrong detection lowers mAP
    det2 = np.array([[1, 0.9, 5, 5, 6, 6], [1, 0.8, 2, 2, 3, 3]],
                    np.float32)
    r2 = _run("detection_map", {"DetectRes": det2, "Label": gt},
              {"overlap_threshold": 0.5, "ap_type": "integral"})
    assert float(np.asarray(r2["MAP"])[0]) < 1.0


def test_fill_fake_init_get_places_interpolate():
    from paddle_tpu.fluid import core as fcore
    f = np.asarray(_run("fill", {}, {
        "shape": [2, 2], "dtype": fcore.VarDesc.VarType.FP32,
        "value": [1.0, 2.0, 3.0, 4.0]})["Out"])
    np.testing.assert_allclose(f, [[1, 2], [3, 4]], atol=1e-6)

    z = np.asarray(_run("fake_init", {}, {"shape": [3]})["Out"])
    np.testing.assert_allclose(z, np.zeros(3), atol=1e-6)

    p = np.asarray(_run("get_places", {}, {"device_count": 4})["Out"])
    np.testing.assert_array_equal(p, [0, 1, 2, 3])

    x = np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2)
    up = np.asarray(_run("interpolate", {"X": x},
                         {"interp_method": "nearest",
                          "out_h": 4, "out_w": 4})["Out"])
    assert up.shape == (1, 1, 4, 4)


def test_depthwise_conv2d_transpose_and_lookup_sparse_table():
    rng = np.random.RandomState(2)
    x = rng.randn(1, 3, 4, 4).astype("float32")
    w = rng.randn(3, 1, 2, 2).astype("float32")
    out = np.asarray(_run(
        "depthwise_conv2d_transpose", {"Input": x, "Filter": w},
        {"strides": [2, 2], "paddings": [0, 0], "dilations": [1, 1]}
    )["Output"])
    assert out.shape == (1, 3, 8, 8)

    table = rng.randn(10, 4).astype("float32")
    ids = np.array([[1], [7]], np.int64)
    r = np.asarray(_run("lookup_sparse_table",
                        {"W": table, "Ids": ids})["Out"])
    np.testing.assert_allclose(r.reshape(2, 4), table[[1, 7]], atol=1e-6)


def test_detection_map_difficult_and_accumulation():
    # 6-column labels: [label, difficult, xmin, ymin, xmax, ymax]
    gt = np.array([[1, 0, 0, 0, 1, 1], [1, 1, 2, 2, 3, 3]], np.float32)
    det = np.array([[1, 0.9, 0, 0, 1, 1]], np.float32)
    # difficult box excluded -> npos=1, the one detection matches: mAP 1
    r = _run("detection_map", {"DetectRes": det, "Label": gt},
             {"overlap_threshold": 0.5, "ap_type": "integral",
              "evaluate_difficult": False})
    assert abs(float(np.asarray(r["MAP"])[0]) - 1.0) < 1e-6
    # accumulation: feed batch-1 accumulators into batch 2
    gt2 = np.array([[1, 0, 5, 5, 6, 6]], np.float32)
    det2 = np.array([[1, 0.8, 9, 9, 10, 10]], np.float32)  # miss
    r2 = _run("detection_map",
              {"DetectRes": det2, "Label": gt2,
               "PosCount": np.asarray(r["AccumPosCount"]),
               "TruePos": np.asarray(r["AccumTruePos"]),
               "FalsePos": np.asarray(r["AccumFalsePos"])},
              {"overlap_threshold": 0.5, "ap_type": "integral",
               "evaluate_difficult": False})
    m = float(np.asarray(r2["MAP"])[0])
    assert 0.0 < m < 1.0   # one hit of two positives + one false positive


def test_similarity_focus_greedy_unique():
    x = np.array([[[[3.0, 2.0], [1.0, 0.0]]]], np.float32)  # [1,1,2,2]
    r = np.asarray(_run("similarity_focus", {"X": x},
                        {"axis": 1, "indexes": [0]})["Out"])
    np.testing.assert_allclose(r[0, 0], [[1, 0], [0, 1]], atol=1e-6)


def test_multiplex_rank3_still_works():
    # the general lowering (loss_ops) must not be shadowed
    c0 = np.zeros((2, 2, 2), np.float32)
    c1 = np.ones((2, 2, 2), np.float32)
    ids = np.array([[1], [0]], np.int32)
    out = np.asarray(_run("multiplex", {"Ids": ids, "X": [c0, c1]})["Out"])
    np.testing.assert_allclose(out[0], np.ones((2, 2)), atol=1e-6)
    np.testing.assert_allclose(out[1], np.zeros((2, 2)), atol=1e-6)


def test_detection_map_counts_fp_for_unlabeled_class():
    gt = np.array([[1, 0, 0, 1, 1]], np.float32)        # only class 1
    det = np.array([[1, 0.9, 0, 0, 1, 1],               # hit class 1
                    [2, 0.8, 0, 0, 1, 1]], np.float32)  # class 2: FP
    r = _run("detection_map", {"DetectRes": det, "Label": gt},
             {"overlap_threshold": 0.5, "ap_type": "integral"})
    # class-2 FP must be recorded in the accumulators
    fp = np.asarray(r["AccumFalsePos"])
    assert any(int(row[0]) == 2 for row in fp), fp


# ---------------------------------------------------------------------------
# detection_map randomized oracle audit (r5): restatement of
# detection_map_op.h CalcTrueAndFalsePositive + CalcMAP
# ---------------------------------------------------------------------------

def _ref_map(images, overlap_t, ap_type, evaluate_difficult):
    """images: list of (gt_rows [label, difficult, x1,y1,x2,y2],
    det_rows [label, score, x1,y1,x2,y2])."""
    def iou(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    npos, tp, fp = {}, {}, {}
    for gt_rows, det_rows in images:
        for r in gt_rows:
            c, diff = int(r[0]), bool(r[1])
            if evaluate_difficult or not diff:
                npos[c] = npos.get(c, 0) + 1
        for c in sorted({int(r[0]) for r in det_rows}):
            gts = [r for r in gt_rows if int(r[0]) == c]
            dets = sorted([r for r in det_rows if int(r[0]) == c],
                          key=lambda r: -r[1])
            visited = [False] * len(gts)
            for d in dets:
                best, bi = -1.0, 0
                for j, g in enumerate(gts):
                    o = iou(d[2:6], g[2:6])
                    if o > best:
                        best, bi = o, j
                if best > overlap_t and gts:
                    if not (evaluate_difficult or not bool(gts[bi][1])):
                        continue                      # ignored entirely
                    if not visited[bi]:
                        visited[bi] = True
                        tp.setdefault(c, []).append((d[1], 1))
                        fp.setdefault(c, []).append((d[1], 0))
                    else:
                        tp.setdefault(c, []).append((d[1], 0))
                        fp.setdefault(c, []).append((d[1], 1))
                else:
                    tp.setdefault(c, []).append((d[1], 0))
                    fp.setdefault(c, []).append((d[1], 1))

    m_ap, count = 0.0, 0
    for c, n in sorted(npos.items()):
        if n == 0 or c not in tp:
            continue
        rows_tp = sorted(tp[c], key=lambda t: -t[0])
        rows_fp = sorted(fp[c], key=lambda t: -t[0])
        tps = np.cumsum([t[1] for t in rows_tp])
        fps = np.cumsum([t[1] for t in rows_fp])
        rec = tps / float(n)
        prec = tps / np.maximum(tps + fps, 1e-12)
        if ap_type == "11point":
            ap = sum(max([p for r_, p in zip(rec, prec)
                          if r_ >= j / 10.0] or [0.0])
                     for j in range(11)) / 11.0
        else:
            ap, prev = 0.0, 0.0
            for r_, p in zip(rec, prec):
                if abs(r_ - prev) > 1e-6:
                    ap += p * abs(r_ - prev)
                prev = r_
        m_ap += ap
        count += 1
    return m_ap / count if count else 0.0


@pytest.mark.parametrize("ap_type", ["integral", "11point"])
@pytest.mark.parametrize("evaluate_difficult", [True, False])
def test_detection_map_matches_reference_oracle(ap_type,
                                                evaluate_difficult):
    rng = np.random.RandomState(11 if ap_type == "integral" else 13)
    for trial in range(6):
        n_img = int(rng.randint(1, 4))
        images, det_rows, gt_rows, det_lens, gt_lens = [], [], [], [], []
        for _ in range(n_img):
            ng, nd = int(rng.randint(0, 5)), int(rng.randint(0, 6))
            g = []
            for _ in range(ng):
                c = int(rng.randint(1, 4))
                x, y = rng.rand(2) * 4
                w, h = 0.5 + rng.rand(2)
                g.append([c, int(rng.rand() < 0.3), x, y, x + w, y + h])
            d = []
            for _ in range(nd):
                c = int(rng.randint(1, 4))
                if g and rng.rand() < 0.6:        # near a gt box
                    base = g[rng.randint(len(g))]
                    x, y = base[2] + rng.randn() * 0.2, \
                        base[3] + rng.randn() * 0.2
                    x2, y2 = base[4] + rng.randn() * 0.2, \
                        base[5] + rng.randn() * 0.2
                else:
                    x, y = rng.rand(2) * 4
                    x2, y2 = x + 0.5 + rng.rand(), y + 0.5 + rng.rand()
                d.append([c, float(rng.rand()), x, y, max(x2, x + .01),
                          max(y2, y + .01)])
            images.append((g, d))
            gt_rows += g
            det_rows += d
            gt_lens.append(len(g))
            det_lens.append(len(d))
        if not det_rows or not gt_rows:
            continue
        want = _ref_map(images, 0.5, ap_type, evaluate_difficult)
        r = _run("detection_map",
                 {"DetectRes": np.array(det_rows, np.float32),
                  "Label": np.array(gt_rows, np.float32),
                  "DetectRes@LOD_LEN": np.array(det_lens, np.int32),
                  "Label@LOD_LEN": np.array(gt_lens, np.int32)},
                 {"overlap_threshold": 0.5, "ap_type": ap_type,
                  "evaluate_difficult": evaluate_difficult})
        got = float(np.asarray(r["MAP"])[0])
        assert abs(got - want) < 1e-5, (ap_type, evaluate_difficult,
                                        trial, got, want, images)
