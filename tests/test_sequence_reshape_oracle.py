"""sequence_reshape reference oracle (sequence_reshape_op.h restated):
each sequence's flat payload (seq_len * in_width values, row-major) is
re-chunked into rows of new_dim; the only requirement is per-sequence
divisibility of seq_len * in_width by new_dim — in_width itself need
not divide (e.g. D=3 -> new_dim=2 with even-length sequences)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid.lod import create_lod_tensor, LoDTensor


def _run(build_fn, feed):
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        fetches = build_fn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=list(fetches))


def oracle(rows, lens, new_dim):
    outs, new_lens, start = [], [], 0
    for l in lens:
        flat = rows[start:start + l].reshape(-1)
        assert flat.size % new_dim == 0
        outs.append(flat.reshape(-1, new_dim))
        new_lens.append(flat.size // new_dim)
        start += l
    return np.concatenate(outs, axis=0), new_lens


@pytest.mark.parametrize("D,new_dim,lens", [
    (6, 2, [3, 1]),    # widening factor: D % new_dim == 0
    (2, 6, [3, 6]),    # narrowing: new_dim % D == 0, lens divisible
    (3, 2, [4, 2]),    # NEITHER divides; per-sequence payload does
    (4, 4, [2, 3]),    # identity
])
def test_sequence_reshape_matches_reference(D, new_dim, lens):
    rng = np.random.RandomState(1)
    rows = rng.randn(sum(lens), D).astype(np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[D], dtype="float32",
                               lod_level=1)
        return [fluid.layers.sequence_reshape(xv, new_dim)]

    (got,) = _run(build, {"x": create_lod_tensor(rows, [lens])})
    want_rows, want_lens = oracle(rows, lens, new_dim)
    assert isinstance(got, LoDTensor)
    assert got.recursive_sequence_lengths()[0] == want_lens
    np.testing.assert_allclose(got.numpy(), want_rows, atol=1e-6)
