"""Executor.run_loop: K training steps as one device computation.

The device-side loop (lax.fori_loop over the jitted step) must produce
the same parameter trajectory as K individual Executor.run calls —
including the per-op RNG streams folding the step counter, so dropout
masks differ across loop iterations exactly as under run(). Host-op
programs are rejected loudly. Reference analogue: the reader-op
training loops that kept the device busy without per-step feeds
(benchmark/fluid fluid_benchmark.py --use_reader_op).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def _build(with_dropout=False):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=16, act="relu")
        if with_dropout:
            h = fluid.layers.dropout(h, dropout_prob=0.3)
        pred = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square(fluid.layers.elementwise_sub(pred, y)))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def _feed():
    rng = np.random.RandomState(0)
    return {"x": rng.randn(4, 8).astype("float32"),
            "y": rng.randn(4, 1).astype("float32")}


def test_matches_per_step_trajectory():
    feed = _feed()
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(5):
            per_step = exe.run(main, feed=feed, fetch_list=[loss])[0]

    main2, startup2, loss2 = _build()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe2.run(startup2)
        looped = exe2.run_loop(main2, feed=feed, fetch_list=[loss2],
                               steps=5)[0]
    np.testing.assert_allclose(np.asarray(per_step), np.asarray(looped),
                               rtol=1e-5, atol=1e-6)


def test_steps_one_equals_single_run():
    feed = _feed()
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        single = exe.run(main, feed=feed, fetch_list=[loss])[0]

    main2, startup2, loss2 = _build()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe2.run(startup2)
        looped = exe2.run_loop(main2, feed=feed, fetch_list=[loss2],
                               steps=1)[0]
    np.testing.assert_allclose(np.asarray(single), np.asarray(looped),
                               rtol=1e-6, atol=1e-7)


def test_dropout_steps_see_distinct_rng():
    """Two consecutive run_loop dispatches continue the step counter, and
    a dropout model's loop trajectory matches per-step runs (same
    per-step RNG folding)."""
    feed = _feed()
    main, startup, loss = _build(with_dropout=True)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        traj = [exe.run(main, feed=feed, fetch_list=[loss])[0]
                for _ in range(4)]

    main2, startup2, loss2 = _build(with_dropout=True)
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe2.run(startup2)
        l2 = exe2.run_loop(main2, feed=feed, fetch_list=[loss2], steps=2)
        l4 = exe2.run_loop(main2, feed=feed, fetch_list=[loss2], steps=2)
    np.testing.assert_allclose(np.asarray(traj[1]), np.asarray(l2[0]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(traj[3]), np.asarray(l4[0]),
                               rtol=1e-5, atol=1e-6)


def test_host_op_program_rejected():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        out = fluid.layers.fc(input=x, size=2)
        # save is a host op (file IO side effect)
        main.global_block().append_op(
            type="save", inputs={"X": [out]}, outputs={},
            attrs={"file_path": "/tmp/run_loop_reject.bin"})
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with pytest.raises(RuntimeError, match="host op"):
            exe.run_loop(main, feed={"x": np.zeros((2, 4), "float32")},
                         fetch_list=[out], steps=3)


def test_check_nan_inf_rejected():
    """FLAGS.check_nan_inf needs per-op attribution; run_loop refuses
    rather than silently skip the checks run() would perform."""
    from paddle_tpu.flags import FLAGS
    feed = _feed()
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        FLAGS.check_nan_inf = True
        try:
            with pytest.raises(RuntimeError, match="check_nan_inf"):
                exe.run_loop(main, feed=feed, fetch_list=[loss], steps=2)
        finally:
            FLAGS.check_nan_inf = False


def test_parallel_executor_run_loop_matches_per_step():
    """SPMD device-loop: K looped steps over an 8-device dp mesh must
    reproduce K per-step ParallelExecutor.run calls (the gradient
    all-reduce stays inside the single XLA computation)."""
    feed = _feed()   # batch 4; pad to 8 so it shards over 8 devices
    feed = {"x": np.concatenate([feed["x"]] * 2),
            "y": np.concatenate([feed["y"]] * 2)}

    def build_pe():
        main, startup, loss = _build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name,
                                    main_program=main)
        return pe, loss

    with fluid.scope_guard(fluid.Scope()):
        pe, loss = build_pe()
        for _ in range(4):
            per_step = pe.run(fetch_list=[loss], feed=feed)[0]

    with fluid.scope_guard(fluid.Scope()):
        pe2, loss2 = build_pe()
        looped = pe2.run_loop(fetch_list=[loss2], feed=feed, steps=4)[0]

    np.testing.assert_allclose(np.asarray(per_step), np.asarray(looped),
                               rtol=1e-5, atol=1e-6)


def test_lod_program_device_loop():
    """Ragged (LoD) feeds ride run_loop too: the padded-dense encoding +
    @LOD_LEN companions are constants across loop iterations, so the
    dynamic-LSTM training trajectory matches per-step execution."""
    from paddle_tpu.fluid.lod import LoDTensor

    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32",
                                  lod_level=1)
            fc = fluid.layers.fc(input=x, size=16 * 4)
            h, c = fluid.layers.dynamic_lstm(input=fc, size=16 * 4)
            pool = fluid.layers.sequence_pool(h, pool_type="max")
            pred = fluid.layers.fc(input=pool, size=1)
            loss = fluid.layers.mean(pred)
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(0)
    lens = [3, 5, 2]
    flat = rng.randn(sum(lens), 8).astype("float32")
    t = LoDTensor(flat)
    t.set_recursive_sequence_lengths([lens])
    feed = {"x": t}

    with fluid.scope_guard(fluid.Scope()):
        main, startup, loss = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for _ in range(3):
            per_step = exe.run(main, feed=feed, fetch_list=[loss])[0]

    with fluid.scope_guard(fluid.Scope()):
        main2, startup2, loss2 = build()
        exe2 = fluid.Executor(fluid.CPUPlace())
        exe2.run(startup2)
        looped = exe2.run_loop(main2, feed=feed, fetch_list=[loss2],
                               steps=3)[0]
    np.testing.assert_allclose(np.asarray(per_step), np.asarray(looped),
                               rtol=1e-5, atol=1e-6)


def test_parallel_executor_whole_graph_remat():
    """Remat (whole-graph AD) composes with the SPMD ParallelExecutor:
    the mesh-sharded remat step trains with the same trajectory as the
    per-op PE baseline (jax.checkpoint only trades memory for
    recompute). The benchmark's --remat_policy + --parallel path rides
    this."""
    from paddle_tpu.flags import FLAGS
    feed = _feed()
    feed = {"x": np.concatenate([feed["x"]] * 2),
            "y": np.concatenate([feed["y"]] * 2)}

    def run(remat):
        main, startup, loss = _build()
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            old = FLAGS.whole_graph_ad, FLAGS.remat_policy
            if remat:
                FLAGS.whole_graph_ad = True
                FLAGS.remat_policy = "dots"
            try:
                pe = fluid.ParallelExecutor(
                    use_cuda=False, loss_name=loss.name,
                    main_program=main)
                traj = [np.asarray(pe.run(fetch_list=[loss],
                                          feed=feed)[0]).ravel()[0]
                        for _ in range(3)]
            finally:
                FLAGS.whole_graph_ad, FLAGS.remat_policy = old
        return traj

    base = run(remat=False)
    remat = run(remat=True)
    np.testing.assert_allclose(base, remat, rtol=1e-4, atol=1e-5)


def test_parallel_executor_handles_ragged_lod_feed():
    """PE shares the Executor's feed preparation, so ragged LoDTensor
    feeds pad + carry @LOD_LEN companions and shard over the mesh —
    pe.run and pe.run_loop train a dynamic-LSTM model with trajectories
    matching the single-device Executor."""
    from paddle_tpu.fluid.lod import LoDTensor

    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32",
                                  lod_level=1)
            fc = fluid.layers.fc(input=x, size=16 * 4)
            h, c = fluid.layers.dynamic_lstm(input=fc, size=16 * 4)
            pool = fluid.layers.sequence_pool(h, pool_type="max")
            pred = fluid.layers.fc(input=pool, size=1)
            loss = fluid.layers.mean(pred)
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(0)
    lens = [3, 5, 2, 4, 1, 2, 3, 4]     # 8 sequences -> shards over 8
    flat = rng.randn(sum(lens), 8).astype("float32")
    t = LoDTensor(flat)
    t.set_recursive_sequence_lengths([lens])
    feed = {"x": t}

    with fluid.scope_guard(fluid.Scope()):
        main, startup, loss = build()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        ref = [np.asarray(exe.run(main, feed=feed,
                                  fetch_list=[loss])[0]).ravel()[0]
               for _ in range(3)]

    with fluid.scope_guard(fluid.Scope()):
        main2, startup2, loss2 = build()
        fluid.Executor(fluid.CPUPlace()).run(startup2)
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss2.name,
                                    main_program=main2)
        got = [np.asarray(pe.run(fetch_list=[loss2],
                                 feed=feed)[0]).ravel()[0]
               for _ in range(3)]
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-6)

    with fluid.scope_guard(fluid.Scope()):
        main3, startup3, loss3 = build()
        fluid.Executor(fluid.CPUPlace()).run(startup3)
        pe3 = fluid.ParallelExecutor(use_cuda=False, loss_name=loss3.name,
                                     main_program=main3)
        looped = pe3.run_loop(fetch_list=[loss3], feed=feed, steps=3)[0]
    np.testing.assert_allclose(ref[-1], np.asarray(looped).ravel()[0],
                               rtol=1e-5, atol=1e-6)


def test_parallel_executor_per_device_lod_feed_list():
    """The classic PE per-device feed style (list of dicts) must merge
    LoDTensor entries data+lod — a plain np.concatenate would silently
    strip the ragged structure via __array__ and feed garbage."""
    from paddle_tpu.fluid.lod import LoDTensor

    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32",
                                  lod_level=1)
            fc = fluid.layers.fc(input=x, size=16 * 4)
            h, c = fluid.layers.dynamic_lstm(input=fc, size=16 * 4)
            pool = fluid.layers.sequence_pool(h, pool_type="max")
            pred = fluid.layers.fc(input=pool, size=1)
            loss = fluid.layers.mean(pred)
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(1)
    lens = [3, 5, 2, 4, 1, 2, 3, 4]
    flat = rng.randn(sum(lens), 8).astype("float32")

    def lod_slice(seq_lo, seq_hi):
        row_lo = sum(lens[:seq_lo])
        row_hi = sum(lens[:seq_hi])
        t = LoDTensor(flat[row_lo:row_hi])
        t.set_recursive_sequence_lengths([lens[seq_lo:seq_hi]])
        return t

    whole = LoDTensor(flat)
    whole.set_recursive_sequence_lengths([lens])

    with fluid.scope_guard(fluid.Scope()):
        main, startup, loss = build()
        fluid.Executor(fluid.CPUPlace()).run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name,
                                    main_program=main)
        ref = [np.asarray(pe.run(fetch_list=[loss],
                                 feed={"x": whole})[0]).ravel()[0]
               for _ in range(2)]

    with fluid.scope_guard(fluid.Scope()):
        main2, startup2, loss2 = build()
        fluid.Executor(fluid.CPUPlace()).run(startup2)
        pe2 = fluid.ParallelExecutor(use_cuda=False, loss_name=loss2.name,
                                     main_program=main2)
        split = [{"x": lod_slice(0, 4)}, {"x": lod_slice(4, 8)}]
        got = [np.asarray(pe2.run(fetch_list=[loss2],
                                  feed=split)[0]).ravel()[0]
               for _ in range(2)]
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-6)
