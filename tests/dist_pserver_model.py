"""Role script for the 4-process parameter-server cluster test (the
analogue of the reference's dist_mnist.py model scripts driven by
test_dist_base.py:219 start_pserver / :299 _run_cluster).

Invoked as:  python dist_pserver_model.py ROLE ...
  PSERVER <my_endpoint> <all_endpoints> <trainers> <sync:0|1>
  TRAINER <trainer_id>  <all_endpoints> <trainers> <sync:0|1> <steps>
  LOCAL   <steps>                      (single-process baseline)

Trainers print one line 'LOSSES <json>'. Deterministic everywhere: fixed
seeds, fixed data, two fc layers so the round-robin dispatcher puts
param blocks on BOTH pservers.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu.fluid as fluid  # noqa: E402
from paddle_tpu.fluid.transpiler import DistributeTranspiler

STEPS_DEFAULT = 5
GLOBAL_BATCH = 8


def build_net(seed=17):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=6, act="tanh",
                            param_attr=fluid.ParamAttr(name="w1"),
                            bias_attr=fluid.ParamAttr(name="b1"))
        pred = fluid.layers.fc(input=h, size=1, act=None,
                               param_attr=fluid.ParamAttr(name="w2"),
                               bias_attr=fluid.ParamAttr(name="b2"))
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def make_data(steps):
    rng = np.random.RandomState(3)
    w = np.array([[1.0], [-2.0], [0.5], [0.3]], np.float32)
    out = []
    for _ in range(steps):
        x = rng.randn(GLOBAL_BATCH, 4).astype(np.float32)
        out.append((x, np.tanh(x).dot(w) + 0.1))
    return out


def transpile(role_id, endpoints, trainers, sync, current_endpoint=""):
    main, startup, loss = build_net()
    t = DistributeTranspiler()
    t.transpile(trainer_id=role_id, program=main, pservers=endpoints,
                trainers=trainers, sync_mode=sync,
                startup_program=startup,
                current_endpoint=current_endpoint)
    return t, main, startup, loss


def run_pserver(my_ep, endpoints, trainers, sync):
    t, _, startup, _ = transpile(0, endpoints, trainers, sync,
                                 current_endpoint=my_ep)
    prog, ps_startup = t.get_pserver_programs(my_ep)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(ps_startup)
        exe.run(prog)          # blocks in listen_and_serv until exit


def run_trainer(tid, endpoints, trainers, sync, steps):
    from paddle_tpu.distributed.rpc import wait_server_ready
    wait_server_ready(endpoints.split(","))
    t, _, startup, loss = transpile(tid, endpoints, trainers, sync)
    trainer_prog = t.get_trainer_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    data = make_data(steps)
    half = GLOBAL_BATCH // 2
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for i in range(steps):
            x, y = data[i]
            sl = slice(tid * half, (tid + 1) * half)
            (lv,) = exe.run(trainer_prog, feed={"x": x[sl], "y": y[sl]},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
    print("LOSSES %s" % json.dumps(losses), flush=True)


def run_local(steps=STEPS_DEFAULT):
    main, startup, loss = build_net()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    data = make_data(steps)
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for x, y in data:
            (lv,) = exe.run(main, feed={"x": x, "y": y},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv).ravel()[0]))
    return losses


def main():
    role = sys.argv[1]
    if role == "PSERVER":
        run_pserver(sys.argv[2], sys.argv[3], int(sys.argv[4]),
                    bool(int(sys.argv[5])))
    elif role == "TRAINER":
        run_trainer(int(sys.argv[2]), sys.argv[3], int(sys.argv[4]),
                    bool(int(sys.argv[5])), int(sys.argv[6]))
    elif role == "LOCAL":
        print("LOSSES %s" % json.dumps(run_local(int(sys.argv[2]))),
              flush=True)
    else:
        raise SystemExit("unknown role %r" % role)


if __name__ == "__main__":
    main()
