"""Expert parallelism (parallel/moe.py): top-1 MoE dispatch over the
`expert` mesh axis — exact parity against per-token reference semantics
on the virtual 8-device mesh, plus gradient flow."""

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.moe import moe_ffn_sharded, top1_dispatch

E = 8       # experts (1 per device on the 8-dev mesh)
D = 6
F = 10
T = 64      # tokens, sharded 8 per device


def _weights(rng):
    gate_w = rng.randn(D, E).astype(np.float32) * 0.5
    w_in = rng.randn(E, D, F).astype(np.float32) * 0.3
    w_out = rng.randn(E, F, D).astype(np.float32) * 0.3
    return gate_w, w_in, w_out


def _reference(x, gate_w, w_in, w_out, n_shards, capacity_factor=1.25):
    """Per-token semantics: expert = argmax softmax gate; token kept if
    its arrival rank within (shard, expert) < capacity; output =
    gate_prob * FFN_expert(x)."""
    T_loc = x.shape[0] // n_shards
    capacity = int(np.ceil(capacity_factor * T_loc / E)) or 1
    out = np.zeros_like(x)
    for s in range(n_shards):
        counts = np.zeros(E, np.int64)
        for t in range(s * T_loc, (s + 1) * T_loc):
            logits = x[t] @ gate_w
            p = np.exp(logits - logits.max())
            p = p / p.sum()
            e = int(np.argmax(p))
            if counts[e] < capacity:
                h = np.maximum(x[t] @ w_in[e], 0.0)
                out[t] = p[e] * (h @ w_out[e])
            counts[e] += 1
    return out


def test_moe_sharded_matches_reference_semantics():
    rng = np.random.RandomState(0)
    mesh = make_mesh({"expert": 8})
    gate_w, w_in, w_out = _weights(rng)
    x = rng.randn(T, D).astype(np.float32)
    got = np.asarray(moe_ffn_sharded(
        jnp.asarray(x), jnp.asarray(gate_w), jnp.asarray(w_in),
        jnp.asarray(w_out), mesh))
    ref = _reference(x, gate_w, w_in, w_out, n_shards=8)
    np.testing.assert_allclose(got, ref, atol=2e-5)
    # routing is non-degenerate: several experts active, some output mass
    assert np.abs(got).sum() > 0


def test_top1_dispatch_capacity_drops_overflow():
    logits = jnp.asarray(np.tile([[5.0, 0.0, 0.0, 0.0]], (6, 1)))
    dispatch, combine, probs = top1_dispatch(logits, 4, capacity=2)
    d = np.asarray(dispatch)
    # all six tokens route to expert 0; only the first two fit
    assert d[:, 0].sum() == 2.0
    assert d[0, 0, 0] == 1.0 and d[1, 0, 1] == 1.0
    assert d[2:].sum() == 0.0


def test_moe_gradients_flow_through_dispatch():
    rng = np.random.RandomState(1)
    mesh = make_mesh({"expert": 8})
    gate_w, w_in, w_out = _weights(rng)
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))

    def loss_fn(wi, wo, gw):
        y = moe_ffn_sharded(x, gw, wi, wo, mesh)
        return jnp.mean(jnp.square(y))

    g_in, g_out, g_gate = jax.grad(loss_fn, argnums=(0, 1, 2))(
        jnp.asarray(w_in), jnp.asarray(w_out), jnp.asarray(gate_w))
    for g in (g_in, g_out, g_gate):
        assert np.isfinite(np.asarray(g)).all()
    # the expert weights that served tokens must receive gradient
    assert float(jnp.abs(g_in).sum()) > 0
    assert float(jnp.abs(g_out).sum()) > 0
    # gate grads flow via the combine weights (prob-scaled outputs)
    assert float(jnp.abs(g_gate).sum()) > 0


def test_moe_multiple_experts_per_device():
    """E_loc > 1: 8 experts on a 4-device expert axis exercises the
    block-major all_to_all reshapes (a wrong ordering is invisible when
    E_loc == 1)."""
    rng = np.random.RandomState(2)
    mesh = make_mesh({"expert": 4})
    gate_w, w_in, w_out = _weights(rng)
    x = rng.randn(T, D).astype(np.float32)
    got = np.asarray(moe_ffn_sharded(
        jnp.asarray(x), jnp.asarray(gate_w), jnp.asarray(w_in),
        jnp.asarray(w_out), mesh))
    ref = _reference(x, gate_w, w_in, w_out, n_shards=4)
    np.testing.assert_allclose(got, ref, atol=2e-5)


def test_top1_dispatch_bf16_ranks_do_not_collide():
    """Rank bookkeeping must be integer: a bf16 cumsum saturates past 256
    and collides capacity slots."""
    T_big = 400
    logits = jnp.asarray(
        np.tile([[5.0, 0.0]], (T_big, 1)), dtype=jnp.bfloat16)
    dispatch, _, _ = top1_dispatch(logits, 2, capacity=T_big)
    d = np.asarray(dispatch, np.float32)
    # every token gets its own slot: each occupied slot holds exactly 1
    per_slot = d[:, 0, :].sum(axis=0)
    assert per_slot.max() == 1.0
    assert d[:, 0, :].sum() == T_big
