"""Control-flow tests (reference unittests/test_while_op.py,
test_conditional_block.py, test_dyn_rnn.py, test_rnn_memory_helper_op.py)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid.lod import create_lod_tensor


def test_while_loop_sums():
    """while i < 10: s += i; i += 1"""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.fill_constant([1], "float32", 0)
        n = fluid.layers.fill_constant([1], "float32", 10)
        s = fluid.layers.fill_constant([1], "float32", 0)
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond)
        with w.block():
            new_s = fluid.layers.elementwise_add(s, i)
            fluid.layers.assign(new_s, s)
            fluid.layers.increment(i, 1.0, in_place=True)
            fluid.layers.less_than(i, n, cond=cond)
    exe = fluid.Executor(fluid.CPUPlace())
    (res,) = exe.run(main, fetch_list=[s])
    assert float(np.asarray(res)) == 45.0


def _build_while_net(B=3):
    """loss = mean(h) where h = W @ (W @ (W @ x)) computed by a While loop
    reading parameter W each iteration (the reference's train-through-While
    pattern, while_op.cc:119 while_grad)."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.assign(x)
        i = fluid.layers.fill_constant([1], "int64", 0)
        n = fluid.layers.fill_constant([1], "int64", 3)
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond, max_iters=3)
        with w.block():
            h2 = fluid.layers.fc(
                h, size=4, bias_attr=False,
                param_attr=fluid.ParamAttr(
                    name="loop_w",
                    initializer=fluid.initializer.Constant(0.4)))
            fluid.layers.assign(h2, h)
            fluid.layers.increment(i, 1.0, in_place=True)
            fluid.layers.less_than(i, n, cond=cond)
        loss = fluid.layers.mean(h)
    return main, startup, loss


def test_while_gradient_finite_difference():
    """OpTest-grade numeric check of d loss / d W through the loop."""
    main, startup, loss = _build_while_net()
    with fluid.program_guard(main, startup):
        (wgrad,) = fluid.backward.gradients(
            loss, [main.global_block().var("loop_w")])
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    scope = fluid.global_scope()
    rng = np.random.RandomState(3)
    xb = rng.randn(3, 4).astype(np.float32)
    (g,) = exe.run(main, feed={"x": xb}, fetch_list=[wgrad])
    g = np.asarray(g)

    def loss_at(wval):
        scope.set("loop_w", wval)
        (lv,) = exe.run(main, feed={"x": xb}, fetch_list=[loss])
        return float(np.asarray(lv).flatten()[0])

    w0 = np.array(np.asarray(scope.get("loop_w")))
    eps = 1e-3
    num = np.zeros_like(w0)
    for r in range(w0.shape[0]):
        for c in range(w0.shape[1]):
            wp = w0.copy(); wp[r, c] += eps
            wm = w0.copy(); wm[r, c] -= eps
            num[r, c] = (loss_at(wp) - loss_at(wm)) / (2 * eps)
    scope.set("loop_w", w0)
    np.testing.assert_allclose(g, num, atol=1e-3, rtol=1e-2)


def test_while_trains():
    """Training through a While loop: loss decreases."""
    main, startup, loss = _build_while_net()
    with fluid.program_guard(main, startup):
        sq = fluid.layers.square(loss)      # minimize mean(h)^2 -> 0
        fluid.optimizer.SGD(0.02).minimize(sq)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    xb = np.abs(rng.randn(3, 4)).astype(np.float32) + 0.5
    vals = []
    for _ in range(15):
        (lv,) = exe.run(main, feed={"x": xb}, fetch_list=[sq])
        vals.append(float(np.asarray(lv).flatten()[0]))
    assert vals[-1] < 0.05 * vals[0], vals


def test_while_bound_auto_derived_trains():
    """VERDICT r2 weak #4: the canonical counter loop (constant init and
    limit, single positive increment) gets its trip bound derived
    automatically, so backward works WITHOUT an explicit max_iters."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.assign(x)
        i = fluid.layers.fill_constant([1], "int64", 0)
        n = fluid.layers.fill_constant([1], "int64", 3)
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond)          # no max_iters: derived
        with w.block():
            h2 = fluid.layers.fc(h, size=4, bias_attr=False,
                                 param_attr=fluid.ParamAttr(name="w2"))
            fluid.layers.assign(h2, h)
            fluid.layers.increment(i, 1.0, in_place=True)
            fluid.layers.less_than(i, n, cond=cond)
        loss = fluid.layers.mean(fluid.layers.square(h))
        fluid.optimizer.SGD(0.05).minimize(loss)
    wop = [op for op in main.global_block().ops if op.type == "while"][0]
    assert wop.attrs.get("max_iters") == 3
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(2)
    xb = rng.randn(3, 4).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        vals = []
        for _ in range(10):
            (lv,) = exe.run(main, feed={"x": xb}, fetch_list=[loss])
            vals.append(float(np.asarray(lv).flatten()[0]))
    assert vals[-1] < vals[0], vals


def _build_dynamic_while_program():
    """Loop whose limit is a runtime feed — no derivable static bound."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        n = fluid.layers.data("n", shape=[1], dtype="int64")
        h = fluid.layers.assign(x)
        i = fluid.layers.fill_constant([1], "int64", 0)
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond)          # bound is a runtime feed
        with w.block():
            h2 = fluid.layers.fc(h, size=4, bias_attr=False,
                                 param_attr=fluid.ParamAttr(name="w3"))
            fluid.layers.assign(h2, h)
            fluid.layers.increment(i, 1.0, in_place=True)
            fluid.layers.less_than(i, n, cond=cond)
        loss = fluid.layers.mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def test_while_dynamic_bound_takes_jit_native_grad_path():
    """A genuinely data-dependent limit (fed at runtime) cannot derive a
    static bound: backward marks the forward op for in-graph carry
    recording (record_for_grad) and differentiates via the generic vjp
    machinery — the program stays FULLY jitted, no host-path replay op
    and no SegmentedProgramRunner (VERDICT r3 #3; reference
    while_op.cc:119 ran while-grad in-graph too)."""
    main, startup, loss = _build_dynamic_while_program()
    types = [op.type for op in main.global_block().ops]
    assert "while_grad_dynamic" not in types
    assert "while_grad" in types
    wop = next(op for op in main.global_block().ops if op.type == "while")
    assert wop.attrs.get("record_for_grad") is True
    assert wop.attrs.get("grad_max_iters") == \
        fluid.flags.FLAGS.while_grad_max_iters
    # ... and it actually trains on the fully-jitted executor path
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.executor.Scope()
    with fluid.executor.scope_guard(scope):
        exe.run(startup)
        (l0,) = exe.run(
            main, feed={"x": np.ones((2, 4), np.float32),
                        "n": np.array([[3]], np.int64)},
            fetch_list=[loss])
    assert np.isfinite(float(np.asarray(l0).ravel()[0]))
    assert exe.segmented_runner(main) is None, \
        "dynamic-while training program must not engage the host path"


def test_while_dynamic_host_replay_flag_matches_jit_native():
    """FLAGS.dynamic_while_host_grad=True restores the round-3 host-path
    replay (while_grad_dynamic + initial-carry snapshots); losses over a
    training trajectory match the jit-native recorded path."""
    from paddle_tpu.flags import FLAGS

    def run_losses(n_steps=6):
        main, startup, loss = _build_dynamic_while_program()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.executor.Scope()
        rng = np.random.RandomState(11)
        losses = []
        with fluid.executor.scope_guard(scope):
            exe.run(startup)
            scope.set("w3", (np.eye(4) * 0.5).astype(np.float32))
            for step in range(n_steps):
                xv = rng.randn(2, 4).astype(np.float32)
                nv = np.array([[1 + step % 3]], np.int64)
                (l,) = exe.run(main, feed={"x": xv, "n": nv},
                               fetch_list=[loss])
                losses.append(float(np.asarray(l).ravel()[0]))
        return main, losses

    _, jit_losses = run_losses()
    FLAGS.dynamic_while_host_grad = True
    try:
        host_main, host_losses = run_losses()
    finally:
        FLAGS.dynamic_while_host_grad = False
    types = [op.type for op in host_main.global_block().ops]
    assert "while_grad_dynamic" in types
    widx = types.index("while")
    # initial-carry snapshots precede the forward loop on the host path
    assert types[widx - 1] == "assign"
    np.testing.assert_allclose(jit_losses, host_losses, rtol=2e-4,
                               err_msg="jit-native while grad diverged "
                                       "from the host replay path")


def test_ifelse_cross_row_op_warns():
    """ADVICE r3: the dense-masking IfElse lowering diverges from the
    reference's row-split semantics for batch-coupled ops — that must
    surface as a warning at build time, not only in a docstring."""
    import warnings
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1])
        limit = fluid.layers.fill_constant([1], "float32", 0.0)
        cond = fluid.layers.less_than(x, limit)
        ie = fluid.layers.IfElse(cond)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            with ie.true_block():
                xt = ie.input(x)
                fluid.layers.mean(xt)          # couples rows
                ie.output(xt)
        assert any("cross-row" in str(x.message) for x in w)
        # row-wise branches stay silent
        ie2 = fluid.layers.IfElse(cond)
        with warnings.catch_warnings(record=True) as w2:
            warnings.simplefilter("always")
            with ie2.true_block():
                xt = ie2.input(x)
                ie2.output(fluid.layers.scale(xt, scale=2.0))
        assert not any("cross-row" in str(x.message) for x in w2)


def test_while_grad_cap_overflow_is_loud():
    """A dynamic loop still running at FLAGS.while_grad_max_iters must
    poison its carries with NaN — never a silently-truncated forward."""
    from paddle_tpu.flags import FLAGS
    old = FLAGS.while_grad_max_iters
    FLAGS.while_grad_max_iters = 4
    try:
        main, startup, loss = _build_dynamic_while_program()
    finally:
        FLAGS.while_grad_max_iters = old
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.executor.Scope()
    with fluid.executor.scope_guard(scope):
        exe.run(startup)
        feed = {"x": np.ones((2, 4), np.float32)}
        (ok,) = exe.run(main, feed=dict(feed, n=np.array([[3]], np.int64)),
                        fetch_list=[loss])       # 3 < cap: fine
        (bad,) = exe.run(main, feed=dict(feed, n=np.array([[9]], np.int64)),
                         fetch_list=[loss])      # 9 > cap: poisoned
    assert np.isfinite(float(np.asarray(ok).ravel()[0]))
    assert np.isnan(float(np.asarray(bad).ravel()[0])), \
        "truncated while forward must fail loudly"


def test_conditional_block():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1], dtype="float32",
                              append_batch_size=False)
        out = fluid.layers.fill_constant([1], "float32", 0)
        limit = fluid.layers.fill_constant([1], "float32", 5.0)
        cond = fluid.layers.less_than(x, limit)
        cb = fluid.layers.ConditionalBlock([cond])
        with cb.block():
            doubled = fluid.layers.scale(x, scale=2.0)
            fluid.layers.assign(doubled, out)
    exe = fluid.Executor(fluid.CPUPlace())
    (r1,) = exe.run(main, feed={"x": np.array([3.0], np.float32)},
                    fetch_list=[out])
    (r2,) = exe.run(main, feed={"x": np.array([7.0], np.float32)},
                    fetch_list=[out])
    assert float(np.asarray(r1)) == 6.0
    assert float(np.asarray(r2)) == 0.0


def test_static_rnn_accumulator():
    """StaticRNN computing cumulative sums over [T, B, D]."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3, 2, 4], dtype="float32",
                              append_batch_size=False)
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            mem = rnn.memory(shape=[2, 4], batch_ref=x)
            acc = fluid.layers.elementwise_add(mem, xt)
            rnn.update_memory(mem, acc)
            rnn.step_output(acc)
        out = rnn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.random.RandomState(0).randn(3, 2, 4).astype("float32")
    (res,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(res), np.cumsum(xv, axis=0),
                               atol=1e-5)


def test_static_rnn_trains():
    """simple RNN classifier built from StaticRNN is differentiable."""
    main, startup = Program(), Program()
    T, B, D, H = 4, 8, 5, 6
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[T, B, D], dtype="float32",
                              append_batch_size=False)
        label = fluid.layers.data("label", shape=[B, 1], dtype="int64",
                                  append_batch_size=False)
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            mem = rnn.memory(shape=[B, H], batch_ref=x)
            h = fluid.layers.fc(input=[xt, mem], size=H, act="tanh")
            rnn.update_memory(mem, h)
            rnn.step_output(h)
        outs = rnn()
        last = fluid.layers.slice(outs, axes=[0], starts=[T - 1], ends=[T])
        last = fluid.layers.squeeze(last, axes=[0])
        pred = fluid.layers.fc(input=last, size=3, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    losses = []
    lab = rng.randint(0, 3, (B, 1)).astype("int64")
    xv = rng.randn(T, B, D).astype("float32") + lab.reshape(1, B, 1)
    for _ in range(30):
        (l,) = exe.run(main, feed={"x": xv, "label": lab},
                       fetch_list=[loss])
        losses.append(float(np.asarray(l)))
    assert losses[-1] < 0.3 * losses[0], losses


def test_dynamic_rnn_ragged_sum():
    """DynamicRNN accumulating ragged sequences -> final states match
    per-sequence sums."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3], dtype="float32", lod_level=1)
        init = fluid.layers.data("init", shape=[3], dtype="float32")
        rnn = fluid.layers.DynamicRNN()
        with rnn.block():
            xt = rnn.step_input(x)
            mem = rnn.memory(init=init)
            acc = fluid.layers.elementwise_add(mem, xt)
            rnn.update_memory(mem, acc)
            rnn.output(acc)
        outs = rnn()
        pooled = fluid.layers.sequence_pool(outs, "last")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    data = np.random.RandomState(0).randn(5, 3).astype("float32")
    lod_in = create_lod_tensor(data, [[2, 3]])
    init_v = np.zeros((2, 3), np.float32)
    (res,) = exe.run(main, feed={"x": lod_in, "init": init_v},
                     fetch_list=[pooled])
    expect = np.stack([data[0:2].sum(0), data[2:5].sum(0)])
    np.testing.assert_allclose(np.asarray(res), expect, atol=1e-5)


def test_dynamic_rnn_with_params_trains():
    """DynamicRNN step using an fc (external params) gets gradients."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32", lod_level=1)
        init = fluid.layers.data("init", shape=[6], dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        rnn = fluid.layers.DynamicRNN()
        with rnn.block():
            xt = rnn.step_input(x)
            mem = rnn.memory(init=init)
            h = fluid.layers.fc(input=[xt, mem], size=6, act="tanh")
            rnn.update_memory(mem, h)
            rnn.output(h)
        outs = rnn()
        last = fluid.layers.sequence_pool(outs, "last")
        pred = fluid.layers.fc(input=last, size=2, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(25):
        seqs, labels = [], []
        for b in range(8):
            L = int(rng.randint(2, 6))
            lab = int(rng.randint(0, 2))
            seqs.append((rng.randn(L, 4) + 2 * lab).astype("float32"))
            labels.append(lab)
        lod_in = create_lod_tensor(np.concatenate(seqs, 0),
                                   [[len(s) for s in seqs]])
        (l,) = exe.run(main, feed={
            "x": lod_in, "init": np.zeros((8, 6), np.float32),
            "label": np.array(labels, "int64").reshape(-1, 1)},
            fetch_list=[loss])
        losses.append(float(np.asarray(l)))
    assert losses[-1] < 0.6 * losses[0], losses


def test_ifelse_rowwise_branches():
    """IfElse (reference control_flow.py:1412): rows route through the
    true/false branches and merge in original order."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2], dtype="float32")
        limit = fluid.layers.fill_constant([1], "float32", 0.0)
        # per-row condition: first feature < 0
        feat = fluid.layers.slice(x, axes=[1], starts=[0], ends=[1])
        cond = fluid.layers.cast(
            fluid.layers.less_than(feat, limit), "int32")
        ie = fluid.layers.IfElse(cond)
        with ie.true_block():
            d = ie.input(x)
            ie.output(fluid.layers.scale(d, scale=-1.0))   # negate
        with ie.false_block():
            d = ie.input(x)
            ie.output(fluid.layers.scale(d, scale=10.0))   # x10
        out = ie()[0]   # reference contract: always a list
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.array([[1.0, 2.0], [-3.0, 4.0], [5.0, -6.0]], np.float32)
    (res,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    expect = np.array([[10.0, 20.0], [3.0, -4.0], [50.0, -60.0]],
                      np.float32)
    np.testing.assert_allclose(np.asarray(res), expect, atol=1e-5)


def test_lod_rank_table_layer_and_reorder():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2], dtype="float32", lod_level=1)
        table = fluid.layers.lod_rank_table(x)
        reordered = fluid.layers.reorder_lod_tensor_by_rank(x, table)
        pooled = fluid.layers.sequence_pool(reordered, "last")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    data = np.arange(10, dtype=np.float32).reshape(5, 2)
    lod = create_lod_tensor(data, [[1, 3, 1]])
    (res,) = exe.run(main, feed={"x": lod}, fetch_list=[pooled])
    # order by length desc: seq1 (len 3, last row idx3), seq0, seq2
    np.testing.assert_allclose(np.asarray(res)[0], data[3], atol=1e-5)


def test_dynamic_while_grad_trains_without_bound():
    """reference while_grad (while_op.cc:119): a loop whose trip count
    depends on runtime DATA (no derivable static bound) trains on the
    host execution path via the replay-based while_grad op."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        n_steps = fluid.layers.data("n", shape=[1])  # data-dependent!
        w = fluid.layers.create_parameter([4, 4], "float32", name="dw_w")
        i = fluid.layers.fill_constant([1], "float32", 0.0)
        state = fluid.layers.elementwise_add(
            x, fluid.layers.fill_constant([1], "float32", 0.0))
        cond = fluid.layers.less_than(i, n_steps)
        loop = fluid.layers.While(cond)
        with loop.block():
            nxt = fluid.layers.tanh(fluid.layers.mul(state, w))
            fluid.layers.assign(nxt, state)
            fluid.layers.increment(i)
            fluid.layers.less_than(i, n_steps, cond=cond)
        target = fluid.layers.data("t", shape=[4])
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(state, target))
        fluid.optimizer.SGDOptimizer(0.2).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.executor.Scope()
    rng = np.random.RandomState(0)
    with fluid.executor.scope_guard(scope):
        exe.run(startup)
        losses = []
        for step in range(30):
            # trip count varies per step: 1..3 iterations, decided by DATA
            k = 1 + (step % 3)
            n = np.array([[float(k)]], np.float32)
            xv = rng.randn(2, 4).astype(np.float32)
            tv = xv
            for _ in range(k):   # target iterates the SAME trip count
                tv = np.tanh(tv @ np.full((4, 4), 0.1, np.float32))
            (l,) = exe.run(main, feed={"x": xv, "n": n, "t": tv},
                           fetch_list=[loss])
            losses.append(float(np.asarray(l).ravel()[0]))
    # trainable: parameters receive gradients through the dynamic loop
    assert np.mean(losses[-5:]) < 0.5 * np.mean(losses[:5]), \
        losses[::6]


def test_dynamic_while_grad_fan_in_and_producer_grads():
    """The review repros: (a) a parameter consumed both inside an
    unbounded loop and outside it receives the SUM of both
    contributions; (b) a trainable producer feeding the loop gets the
    true chained gradient, not a double-counted one. Both checked
    against numeric finite differences."""
    def build():
        main, startup = Program(), Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[3])
            n = fluid.layers.data("n", shape=[1])
            w0 = fluid.layers.create_parameter(
                [3, 3], "float32", name="fw0",
                default_initializer=fluid.initializer.Normal(scale=0.3))
            w = fluid.layers.create_parameter(
                [3, 3], "float32", name="fw",
                default_initializer=fluid.initializer.Normal(scale=0.3))
            state = fluid.layers.mul(x, w0)     # trainable producer
            i = fluid.layers.fill_constant([1], "float32", 0.0)
            cond = fluid.layers.less_than(i, n)
            loop = fluid.layers.While(cond)
            with loop.block():
                nxt = fluid.layers.tanh(fluid.layers.mul(state, w))
                fluid.layers.assign(nxt, state)
                fluid.layers.increment(i)
                fluid.layers.less_than(i, n, cond=cond)
            outside = fluid.layers.mean(fluid.layers.mul(x, w))
            loss = fluid.layers.elementwise_add(
                fluid.layers.mean(state), outside)
            pg = fluid.backward.append_backward(loss)
        return main, startup, loss, pg

    main, startup, loss, pg = build()
    names = {p.name for p, g in pg}
    assert names == {"fw0", "fw"}, names
    gmap = {p.name: g.name for p, g in pg}
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.executor.Scope()
    rng = np.random.RandomState(3)
    xv = rng.randn(2, 3).astype(np.float32)
    nv = np.array([[2.0]], np.float32)
    with fluid.executor.scope_guard(scope):
        exe.run(startup)
        l0, gw0, gw = exe.run(
            main, feed={"x": xv, "n": nv},
            fetch_list=[loss, gmap["fw0"], gmap["fw"]])
        w0_val = np.asarray(scope.get("fw0")).copy()
        w_val = np.asarray(scope.get("fw")).copy()

        # finite differences against the same program
        def loss_at(w0_new, w_new):
            scope.set("fw0", w0_new.astype(np.float32))
            scope.set("fw", w_new.astype(np.float32))
            (lv,) = exe.run(main, feed={"x": xv, "n": nv},
                            fetch_list=[loss])
            return float(np.asarray(lv).ravel()[0])

        eps = 1e-3
        for pname, gval, base0, base1 in (
                ("fw0", np.asarray(gw0), w0_val, w_val),
                ("fw", np.asarray(gw), w0_val, w_val)):
            for idx in [(0, 0), (1, 2)]:
                d0 = w0_val.copy()
                d1 = w_val.copy()
                tgt = d0 if pname == "fw0" else d1
                tgt[idx] += eps
                lp = loss_at(d0, d1)
                tgt[idx] -= 2 * eps
                lm = loss_at(d0, d1)
                tgt[idx] += eps
                num = (lp - lm) / (2 * eps)
                np.testing.assert_allclose(gval[idx], num, atol=5e-3,
                                           err_msg="%s%s" % (pname, idx))
            loss_at(w0_val, w_val)   # restore


def test_step_counter_no_double_increment_with_lr_schedule():
    """A program using BOTH an LR decay schedule and
    autoincreased_step_counter must not double-step either counter."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2])
        lr = fluid.layers.exponential_decay(0.1, decay_steps=10,
                                            decay_rate=0.5)
        ctr = fluid.layers.autoincreased_step_counter()
        out = fluid.layers.mean(x)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.executor.Scope()
    with fluid.executor.scope_guard(scope):
        exe.run(startup)
        vals = []
        for _ in range(3):
            (c,) = exe.run(main, feed={"x": np.zeros((1, 2), np.float32)},
                           fetch_list=[ctr])
            vals.append(float(np.asarray(c).ravel()[0]))
    assert vals == [1.0, 2.0, 3.0], vals


def test_dynamic_while_grad_with_pre_loop_consumer():
    """The carry is consumed BEFORE the loop too (pending fan-in): the
    while's gradient contribution must not be dropped. Checked against
    finite differences."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3])
        n = fluid.layers.data("n", shape=[1])
        w0 = fluid.layers.create_parameter(
            [3, 3], "float32", name="pw0",
            default_initializer=fluid.initializer.Normal(scale=0.3))
        state = fluid.layers.mul(x, w0)
        pre = fluid.layers.mean(state)          # PRE-loop consumer
        i = fluid.layers.fill_constant([1], "float32", 0.0)
        cond = fluid.layers.less_than(i, n)
        loop = fluid.layers.While(cond)
        with loop.block():
            nxt = fluid.layers.tanh(fluid.layers.scale(state, scale=0.9))
            fluid.layers.assign(nxt, state)
            fluid.layers.increment(i)
            fluid.layers.less_than(i, n, cond=cond)
        loss = fluid.layers.elementwise_add(
            fluid.layers.mean(state), pre)
        pg = fluid.backward.append_backward(loss)
    types = [op.type for op in main.global_block().ops]
    assert "while_grad" in types, types   # jit-native recorded path
    gmap = {p.name: g.name for p, g in pg}
    assert "pw0" in gmap
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.executor.Scope()
    rng = np.random.RandomState(7)
    xv = rng.randn(2, 3).astype(np.float32)
    nv = np.array([[2.0]], np.float32)
    with fluid.executor.scope_guard(scope):
        exe.run(startup)
        (g,) = exe.run(main, feed={"x": xv, "n": nv},
                       fetch_list=[gmap["pw0"]])
        g = np.asarray(g)
        w_val = np.asarray(scope.get("pw0")).copy()

        def loss_at(wv):
            scope.set("pw0", wv.astype(np.float32))
            (lv,) = exe.run(main, feed={"x": xv, "n": nv},
                            fetch_list=[loss])
            return float(np.asarray(lv).ravel()[0])

        eps = 1e-3
        for idx in [(0, 0), (2, 1)]:
            d = w_val.copy()
            d[idx] += eps
            lp = loss_at(d)
            d[idx] -= 2 * eps
            lm = loss_at(d)
            num = (lp - lm) / (2 * eps)
            np.testing.assert_allclose(g[idx], num, atol=5e-3)
        loss_at(w_val)


def test_bounded_while_grad_with_pre_loop_consumer():
    """Same pre-loop-consumer topology on the BOUNDED (max_iters) path:
    the generic vjp while_grad must receive the force-finalized out-grad
    rather than empty slots. Finite-difference checked."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3])
        w0 = fluid.layers.create_parameter(
            [3, 3], "float32", name="bw0",
            default_initializer=fluid.initializer.Normal(scale=0.3))
        state = fluid.layers.mul(x, w0)
        pre = fluid.layers.mean(state)          # PRE-loop consumer
        i = fluid.layers.fill_constant([1], "float32", 0.0)
        n = fluid.layers.fill_constant([1], "float32", 2.0)
        cond = fluid.layers.less_than(i, n)
        loop = fluid.layers.While(cond, max_iters=2)
        with loop.block():
            nxt = fluid.layers.tanh(fluid.layers.scale(state, scale=0.9))
            fluid.layers.assign(nxt, state)
            fluid.layers.increment(i)
            fluid.layers.less_than(i, n, cond=cond)
        loss = fluid.layers.elementwise_add(
            fluid.layers.mean(state), pre)
        pg = fluid.backward.append_backward(loss)
    gmap = {p.name: g.name for p, g in pg}
    assert "bw0" in gmap
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.executor.Scope()
    rng = np.random.RandomState(11)
    xv = rng.randn(2, 3).astype(np.float32)
    with fluid.executor.scope_guard(scope):
        exe.run(startup)
        (g,) = exe.run(main, feed={"x": xv}, fetch_list=[gmap["bw0"]])
        g = np.asarray(g)
        w_val = np.asarray(scope.get("bw0")).copy()

        def loss_at(wv):
            scope.set("bw0", wv.astype(np.float32))
            (lv,) = exe.run(main, feed={"x": xv}, fetch_list=[loss])
            return float(np.asarray(lv).ravel()[0])

        eps = 1e-3
        for idx in [(0, 0), (1, 2)]:
            d = w_val.copy()
            d[idx] += eps
            lp = loss_at(d)
            d[idx] -= 2 * eps
            lm = loss_at(d)
            np.testing.assert_allclose(g[idx], (lp - lm) / (2 * eps),
                                       atol=5e-3)
        loss_at(w_val)


def test_bounded_while_grad_no_pre_loop_consumer():
    """The mirror topology (carry has an upstream producer but NO
    pre-loop consumer) — a rename-clause regression once produced stale
    out-grads here. Finite-difference checked."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3])
        w0 = fluid.layers.create_parameter(
            [3, 3], "float32", name="nbw0",
            default_initializer=fluid.initializer.Normal(scale=0.3))
        state = fluid.layers.mul(x, w0)
        i = fluid.layers.fill_constant([1], "float32", 0.0)
        n = fluid.layers.fill_constant([1], "float32", 2.0)
        cond = fluid.layers.less_than(i, n)
        loop = fluid.layers.While(cond, max_iters=2)
        with loop.block():
            nxt = fluid.layers.tanh(fluid.layers.scale(state, scale=0.9))
            fluid.layers.assign(nxt, state)
            fluid.layers.increment(i)
            fluid.layers.less_than(i, n, cond=cond)
        loss = fluid.layers.mean(state)
        pg = fluid.backward.append_backward(loss)
    gmap = {p.name: g.name for p, g in pg}
    assert "nbw0" in gmap
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.executor.Scope()
    rng = np.random.RandomState(13)
    xv = rng.randn(2, 3).astype(np.float32)
    with fluid.executor.scope_guard(scope):
        exe.run(startup)
        (g,) = exe.run(main, feed={"x": xv}, fetch_list=[gmap["nbw0"]])
        g = np.asarray(g)
        w_val = np.asarray(scope.get("nbw0")).copy()

        def loss_at(wv):
            scope.set("nbw0", wv.astype(np.float32))
            (lv,) = exe.run(main, feed={"x": xv}, fetch_list=[loss])
            return float(np.asarray(lv).ravel()[0])

        eps = 1e-3
        for idx in [(0, 0), (1, 2)]:
            d = w_val.copy()
            d[idx] += eps
            lp = loss_at(d)
            d[idx] -= 2 * eps
            lm = loss_at(d)
            np.testing.assert_allclose(g[idx], (lp - lm) / (2 * eps),
                                       atol=5e-3)
        loss_at(w_val)
