"""Native wire codec tests (native/wire.cc + paddle_tpu/native/wire.py).

Reference analogue: grpc_serde_test.cc — serialize a variable into the
wire format, parse it back, compare; plus the hostile-input cases the
reference's typed protobuf parsing gave for free and pickle never did.
"""

import socket
import struct

import numpy as np
import pytest

from paddle_tpu.native import wire


VALUES = [
    None, True, False, 0, 42, -(1 << 62), 3.25, float("inf"),
    "", "héllo ∆", b"", b"\x00\xff raw",
    [], [1, [2, [3]]], (), ("a", (None, 1.5)),
    {}, {"k": 1, "nested": {"arr": [1, 2]}},
    np.arange(12, dtype=np.float32).reshape(3, 4),
    np.array(7, dtype=np.int64),
    np.zeros((0, 3), dtype=np.int32),
    np.random.RandomState(0).randn(2, 3, 4).astype(np.float16),
    {"cmd": "send", "name": "w@GRAD",
     "var": np.random.RandomState(1).randn(8).astype(np.float64)},
]


def _deep_eq(a, b):
    if isinstance(a, np.ndarray):
        return (isinstance(b, np.ndarray) and a.dtype == b.dtype
                and a.shape == b.shape and np.array_equal(a, b))
    if isinstance(a, dict):
        return (isinstance(b, dict) and set(a) == set(b)
                and all(_deep_eq(a[k], b[k]) for k in a))
    if isinstance(a, (list, tuple)):
        return (type(a) is type(b) and len(a) == len(b)
                and all(_deep_eq(x, y) for x, y in zip(a, b)))
    return type(a) is type(b) and a == b


@pytest.mark.parametrize("value", VALUES,
                         ids=[str(i) for i in range(len(VALUES))])
def test_roundtrip_all_codec_pairs(value):
    # native and pure-python codecs must produce interchangeable frames
    encoders = [wire._encode_py]
    decoders = [wire._decode_py]
    if wire._HAS_NATIVE:
        encoders.append(wire._encode_native)
        decoders.append(wire._decode_native)
    for enc in encoders:
        frame = enc(value)
        for dec in decoders:
            assert _deep_eq(dec(frame), value)


def test_native_codec_is_loaded():
    # the build environment has g++; the native path must actually be
    # exercised here, not silently fall back
    assert wire._HAS_NATIVE


@pytest.mark.parametrize("frame", [
    b"",
    b"short",
    b"XXXX\x01\x00\x00\x00\x00",                    # bad magic
    b"PTW1\x63\x00\x00\x00\x00",                    # bad version
    b"PTW1\x01\x00\x00\x00\x63",                    # unknown tag
    b"PTW1\x01\x00\x00\x00\x04\xff\xff\xff\xff",    # str claiming 4GB
    b"PTW1\x01\x00\x00\x00\x06\xff\xff\xff\xffaa",  # list claiming 4G items
    b"PTW1\x01\x00\x00\x00\x00\x00",                # trailing junk
    b"PTW1\x01\x00\x00\x00\x09\x00\x00\x00\x00\x02\x00\x00\x00"
    + b"\xff" * 40,                                  # tensor bad dims
])
def test_malformed_frames_rejected(frame):
    with pytest.raises(wire.WireError):
        wire.decode(frame)
    with pytest.raises(wire.WireError):
        wire._decode_py(frame)


def test_hostile_container_count_no_oom():
    """A count field claiming 4G entries must be rejected up front — not
    turned into a multi-GB reserve that aborts the process
    (std::bad_alloc through the C ABI)."""
    for tag in (6, 7, 8):  # LIST, TUPLE, DICT
        frame = b"PTW1\x01\x00\x00\x00" + bytes([tag]) + b"\xff" * 4
        with pytest.raises(wire.WireError):
            wire.decode(frame)
        with pytest.raises(wire.WireError):
            wire._decode_py(frame)


def test_non_utf8_dict_key_raises_wire_error():
    # DICT, 1 entry, klen=1, key=0xff (invalid utf-8), value NONE
    frame = (b"PTW1\x01\x00\x00\x00\x08\x01\x00\x00\x00"
             b"\x01\x00\x00\x00\xff\x00")
    with pytest.raises(wire.WireError):
        wire.decode(frame)
    with pytest.raises(wire.WireError):
        wire._decode_py(frame)


def test_non_dict_protocol_message_rejected():
    """Valid frames that are not dicts are malformed at the protocol
    layer — servers must reply/close cleanly, not crash on msg['cmd']."""
    from paddle_tpu.distributed.rpc import VariableServer, _HDR
    server = VariableServer("127.0.0.1:0").start()
    try:
        host, port = server.endpoint.rsplit(":", 1)
        for payload in (wire.encode(42), wire.encode([1, 2]),
                        wire.encode({})):  # dict without "cmd"
            s = socket.create_connection((host, int(port)), timeout=5)
            s.sendall(_HDR.pack(len(payload)) + payload)
            s.settimeout(5)
            got = s.recv(1 << 16)
            if got:  # {} decodes: server replies an error message
                n = _HDR.unpack(got[:8])[0]
                reply = wire.decode(got[8:8 + n])
                assert "error" in reply
            s.close()
        # the server still works for well-formed clients
        from paddle_tpu.distributed.rpc import RPCClient
        client = RPCClient()
        client.put_var(server.endpoint, "v", np.zeros(2, np.float32))
        assert client.async_get_var(server.endpoint, "v").shape == (2,)
        client.close()
    finally:
        server.stop()


def test_oversize_outgoing_frame_names_env_var(monkeypatch):
    """The sender fails with a message naming PADDLE_TPU_MAX_RPC_FRAME
    instead of shipping a frame the peer's cap will reject mid-stream."""
    import paddle_tpu.distributed.rpc as rpc
    monkeypatch.setattr(rpc, "_MAX_FRAME", 1 << 10)

    class _Sock:
        def sendall(self, data):
            raise AssertionError("oversize frame must not hit the socket")

    with pytest.raises(wire.WireError) as ei:
        rpc._send_msg(_Sock(), {"cmd": "send",
                                "var": np.zeros(4096, np.float32)})
    assert "PADDLE_TPU_MAX_RPC_FRAME" in str(ei.value)


def test_server_oversize_reply_sends_error_not_drop(monkeypatch):
    """A Get whose reply frame exceeds the cap must come back as an
    error message naming the env var — the stream is still in sync, so
    dropping the connection would hide the actionable diagnostic."""
    import paddle_tpu.distributed.rpc as rpc
    server = rpc.VariableServer("127.0.0.1:0").start()
    try:
        # plant a var directly on the server (never crossed the wire),
        # then shrink the cap so only the reply trips it
        server.store["jumbo"] = np.zeros(4096, np.float32)
        monkeypatch.setattr(rpc, "_MAX_FRAME", 1 << 10)
        client = rpc.RPCClient()
        with pytest.raises(RuntimeError) as ei:
            client.async_get_var(server.endpoint, "jumbo")
        assert "PADDLE_TPU_MAX_RPC_FRAME" in str(ei.value)
        client.close()
    finally:
        server.stop()


def test_truncated_valid_frame_rejected():
    frame = wire.encode({"cmd": "send", "var": np.arange(100.0)})
    for cut in (9, len(frame) // 2, len(frame) - 1):
        with pytest.raises(wire.WireError):
            wire.decode(frame[:cut])


def test_lying_container_count_rejected():
    # a dict header claiming more entries than the payload carries
    frame = bytearray(wire.encode({"a": 1}))
    # dict tag is right after the 8-byte magic/version header
    assert frame[8] == 8
    struct.pack_into("<I", frame, 9, 5)  # count 1 -> 5
    with pytest.raises(wire.WireError):
        wire.decode(bytes(frame))


def test_tensor_shape_bytes_mismatch_rejected():
    frame = bytearray(wire.encode(np.arange(6, dtype=np.float32)))
    # bump dims[0] without adding bytes: shape*itemsize != nbytes
    assert frame[8] == 9
    struct.pack_into("<Q", frame, 8 + 1 + 4 + 4, 7)
    with pytest.raises(wire.WireError):
        wire.decode(bytes(frame))


def test_int64_range_and_numpy_bool():
    # out-of-range ints must raise, not silently wrap through c_int64
    for v in (1 << 63, -(1 << 63) - 1, 1 << 64 | 5):
        with pytest.raises(wire.WireError):
            wire.encode(v)
        with pytest.raises(wire.WireError):
            wire._encode_py(v)
    assert wire.decode(wire.encode((1 << 63) - 1)) == (1 << 63) - 1
    assert wire.decode(wire.encode(-(1 << 63))) == -(1 << 63)
    # np.bool_ (numpy comparison results) encodes as BOOL
    got = wire.decode(wire.encode({"done": np.bool_(True)}))
    assert got == {"done": True} and isinstance(got["done"], bool)


def test_master_ignores_unreadable_snapshot(tmp_path):
    """A corrupt/pre-wire snapshot must not wedge the master at boot."""
    import warnings
    from paddle_tpu.distributed.elastic import MasterService
    snap = str(tmp_path / "m.snap")
    with open(snap, "wb") as f:
        f.write(b"\x00\x01\x02corrupt-not-a-snapshot")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        m = MasterService("127.0.0.1:0", snapshot_path=snap)
        assert any("unreadable master snapshot" in str(x.message)
                   for x in w)
    assert m.todo == [] and not m.dataset_set  # fresh queue


def test_wire_error_is_value_error():
    # load_state_snapshot documents ValueError on corruption
    assert issubclass(wire.WireError, ValueError)


def test_no_pickle_on_socket_paths():
    import paddle_tpu.distributed.rpc as rpc
    import paddle_tpu.distributed.elastic as elastic
    for mod in (rpc, elastic):
        src = open(mod.__file__.rstrip("c")).read()
        assert "import pickle" not in src
        assert "pickle.loads" not in src


def test_malformed_frame_does_not_crash_server():
    """A hostile client sending garbage must not take the server down or
    poison other connections (the clean-error half of VERDICT Next #4)."""
    from paddle_tpu.distributed.rpc import RPCClient, VariableServer
    server = VariableServer("127.0.0.1:0").start()
    try:
        ep = server.endpoint
        host, port = ep.rsplit(":", 1)
        # 1. raw garbage with a plausible length prefix
        s = socket.create_connection((host, int(port)), timeout=5)
        s.sendall(struct.pack("<Q", 16) + b"\xde\xad\xbe\xef" * 4)
        # server drops the connection instead of replying
        s.settimeout(5)
        assert s.recv(1) == b""
        s.close()
        # 2. absurd length prefix must not OOM — connection dropped
        s = socket.create_connection((host, int(port)), timeout=5)
        s.sendall(struct.pack("<Q", 1 << 62))
        s.sendall(b"x" * 64)
        s.settimeout(5)
        assert s.recv(1) == b""
        s.close()
        # 3. a well-formed client still gets service afterwards
        client = RPCClient()
        client.put_var(ep, "w", np.ones(3, dtype=np.float32))
        out = client.async_get_var(ep, "w")
        np.testing.assert_array_equal(out, np.ones(3, dtype=np.float32))
        client.close()
    finally:
        server.stop()


def test_master_resend_dedup_by_req_id():
    """get_task replay is keyed by request id: a RESEND of the same
    request returns the same lease; a NEW request from the same worker
    leases fresh work (ADVICE r3: held[-1] replay duplicated tasks)."""
    from paddle_tpu.distributed.elastic import MasterService
    master = MasterService("127.0.0.1:0", lease_timeout=60.0)
    master.set_dataset(["a", "b", "c"])
    r1 = master.get_task(worker="w0", req_id="w0/1")
    # lost-reply retry: same req_id -> same task
    r1b = master.get_task(worker="w0", resend=True, req_id="w0/1")
    assert r1b["task_id"] == r1["task_id"]
    # next logical request (reply WAS delivered): new req_id -> new task,
    # even though the connection flapped and resend is set
    r2 = master.get_task(worker="w0", resend=True, req_id="w0/2")
    assert r2["task_id"] != r1["task_id"]
    # and the first lease is still pending exactly once
    assert sorted(master.pending) == sorted([r1["task_id"], r2["task_id"]])


def test_dc_asgd_delay_compensation():
    """Delay-compensated async SGD (reference request_handler_impl.cc
    enable_dc_asgd + transpiler _append_dc_asgd_ops): the server
    snapshots each trainer's pulled params at Get time; a later grad is
    corrected by +lambda*g*g*(w_now - w_pulled) before the optimize
    block runs — a stale trainer's update is pushed toward where the
    params have moved meanwhile (Zheng et al. 2017)."""
    from paddle_tpu.distributed.rpc import RPCClient, VariableServer
    lam = 0.1
    applied = []

    def sgd(pname, gname, grad, store):
        applied.append(np.array(grad))
        store[pname] = store[pname] - 0.5 * grad

    srv = VariableServer("127.0.0.1:0", sync_mode=False, dc_asgd=True,
                         dc_lambda=lam, optimize_fn=sgd,
                         grad_to_param={"w@GRAD": "w"}).start()
    try:
        cli = RPCClient()
        w0 = np.array([1.0, 2.0], np.float32)
        cli.put_var(srv.endpoint, "w", w0)
        # trainer 0 pulls (snapshot w0), then trainer 1 pushes a grad
        # that moves w — trainer 0's grad is now stale
        cli.async_get_var(srv.endpoint, "w", trainer_id=0)
        g1 = np.array([0.2, -0.4], np.float32)
        cli.async_get_var(srv.endpoint, "w", trainer_id=1)
        cli.async_send_var(srv.endpoint, "w@GRAD", g1, trainer_id=1)
        w_after1 = cli.async_get_var(srv.endpoint, "w", trainer_id=1)
        # trainer 0 sends its stale grad g0: correction uses w_now - w0
        g0 = np.array([1.0, 1.0], np.float32)
        cli.async_send_var(srv.endpoint, "w@GRAD", g0, trainer_id=0)
        want_corrected = g0 + lam * g0 * g0 * (w_after1 - w0)
        np.testing.assert_allclose(applied[-1], want_corrected,
                                   rtol=1e-6)
        # trainer 1 was NOT stale (pulled right before sending): its
        # correction term is zero
        np.testing.assert_allclose(applied[0], g1, rtol=1e-6)
        cli.send_exit(srv.endpoint)
        cli.close()
    finally:
        srv.stop()


def test_transpiler_dc_asgd_attr_flows():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.transpiler import (DistributeTranspiler,
                                             DistributeTranspilerConfig)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        loss = fluid.layers.mean(fluid.layers.fc(x, size=2))
        fluid.optimizer.SGD(0.1).minimize(loss)
        cfg = DistributeTranspilerConfig()
        cfg.enable_dc_asgd = True
        t = DistributeTranspiler(config=cfg)
        t.transpile(trainer_id=0, program=main,
                    pservers="127.0.0.1:6170", trainers=2,
                    sync_mode=False, startup_program=startup)
        prog = t.get_pserver_program("127.0.0.1:6170")
    ls = next(op for op in prog.global_block().ops
              if op.type == "listen_and_serv")
    assert ls.attrs.get("dc_asgd") is True
    assert ls.attrs.get("sync_mode") is False
