"""Watcher sweep semantics (tools/tpu_watch.py).

The watcher is the single recovery path for every chip-gated
measurement (VERDICT r4 weak #5), so its resume logic is pinned here
with simulated transports: completed stages must survive a mid-sweep
wedge, failed stages must be retried up to the cap and then skipped,
and every completed stage must be flushed to the tracked record before
the next stage runs."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))
import tpu_watch


def _stage_key(cmd, env_extra):
    """Canonical stage name for a run_logged invocation."""
    joined = " ".join(cmd)
    if "profile_step.py" in joined:
        return "profile"
    if env_extra.get("BENCH_REMAT_POLICY") == "block_out":
        return "remat_blk"
    if env_extra.get("BENCH_REMAT") == "1":
        return "remat"
    if "bench_zoo" in joined:
        return "bench_zoo"
    for tool in ("bench_infer", "bench_serving", "convergence_run",
                 "tune_bottleneck", "tune_kernels", "bench_attention",
                 "trace_top"):
        if tool in joined:
            return tool
    return "bench.py"


class _Script:
    """Scripted run_logged: maps canonical stage key -> list of
    outcomes per attempt (True=ok, False=fail)."""

    def __init__(self, script):
        self.script = dict(script)
        self.calls = []

    def __call__(self, cmd, env_extra, log, timeout):
        key = _stage_key(cmd, env_extra)
        self.calls.append(key)
        outcomes = self.script.get(key)
        ok = outcomes.pop(0) if outcomes else True
        return ok, ('{"metric": "%s", "value": 1}' % key if ok else "")


def _run(monkeypatch, tmp_path, script, probes):
    """Run main() with scripted stages and probe outcomes; returns
    (calls, recovery_record)."""
    sc = _Script(script)
    probe_seq = list(probes)

    def fake_probe(timeout=120):
        return probe_seq.pop(0) if probe_seq else "tpu"

    monkeypatch.setattr(tpu_watch, "run_logged", sc)
    monkeypatch.setattr(tpu_watch, "probe", fake_probe)
    monkeypatch.setattr(tpu_watch.time, "sleep", lambda s: None)
    monkeypatch.setattr(sys, "argv", [
        "tpu_watch.py", "--interval", "1",
        "--log", str(tmp_path / "w.log"),
        "--lock", str(tmp_path / "w.lock"),
        "--results_dir", str(tmp_path)])
    tpu_watch.main()
    rec_path = tmp_path / "BENCH_recovery_r05.json"
    rec = json.loads(rec_path.read_text()) if rec_path.exists() else []
    return sc.calls, rec


def test_clean_sweep_runs_all_stages_in_priority_order(monkeypatch,
                                                       tmp_path):
    calls, rec = _run(monkeypatch, tmp_path, {}, ["tpu"])
    # remat runs BEFORE the zoo (VERDICT r4 #1 priority), profile last
    zoo_i = calls.index("bench_zoo")
    remat_i = calls.index("remat")
    assert remat_i < zoo_i
    assert calls[-1] == "profile"
    sweeps = {r["sweep"] for r in rec}
    assert {"nhwc", "nhwc+remat", "nhwc+remat_blk"} <= sweeps


def test_wedge_resumes_at_first_incomplete_stage(monkeypatch, tmp_path):
    # remat fails once (wedge), recovery retries it without redoing
    # the flagship stage
    calls, rec = _run(monkeypatch, tmp_path,
                      {"remat": [False, True]}, ["tpu", "tpu"])
    assert calls.count("bench.py") == 1          # flagship ran ONCE
    assert calls.count("remat") == 2             # failed then retried
    assert {"nhwc", "nhwc+remat"} <= {r["sweep"] for r in rec}


def test_persistent_failure_skips_after_cap(monkeypatch, tmp_path):
    calls, rec = _run(monkeypatch, tmp_path,
                      {"remat": [False, False, False]},
                      ["tpu"] * 4)
    assert calls.count("remat") == 3             # capped
    # the rest of the sweep still completed
    assert "bench_zoo" in calls
    assert "nhwc+remat" not in {r["sweep"] for r in rec}


@pytest.mark.parametrize("watchdog,expect", [("123", "123.0"),
                                             ("0", None)])
def test_watchdog_exported_to_every_stage(monkeypatch, tmp_path,
                                          watchdog, expect):
    """Satellite of PR 3 (ROADMAP open item from PR 2): every sweep
    stage runs with FLAGS.step_watchdog_secs exported so a wedged
    dispatch self-reports via StepWatchdogTimeout; 0 disables."""
    captured = []

    class Cap(_Script):
        def __call__(self, cmd, env_extra, log, timeout):
            captured.append(dict(env_extra))
            return _Script.__call__(self, cmd, env_extra, log, timeout)

    sc = Cap({})
    monkeypatch.setattr(tpu_watch, "run_logged", sc)
    monkeypatch.setattr(tpu_watch, "probe", lambda timeout=120: "tpu")
    monkeypatch.setattr(tpu_watch.time, "sleep", lambda s: None)
    monkeypatch.setattr(sys, "argv", [
        "tpu_watch.py", "--log", str(tmp_path / "w.log"),
        "--lock", str(tmp_path / "w.lock"),
        "--results_dir", str(tmp_path),
        "--watchdog_secs", watchdog])
    tpu_watch.main()
    assert captured
    for env_extra in captured:
        assert env_extra.get("PADDLE_TPU_FLAGS_step_watchdog_secs") \
            == expect
    # stage-specific env vars must survive the merge
    assert any(e.get("BENCH_REMAT") == "1" for e in captured)


def test_serving_stage_in_sweep_after_infer(monkeypatch, tmp_path):
    calls, _ = _run(monkeypatch, tmp_path, {}, ["tpu"])
    assert "bench_serving" in calls
    assert calls.index("bench_serving") > calls.index("bench_infer")
    assert calls.index("bench_serving") < calls.index("profile")


def test_obs_capture_stage_in_sweep(monkeypatch, tmp_path):
    """The obs stage (traced resnet serving run + traced train step,
    merged chrome trace archived — OBSERVABILITY.md) rides the sweep
    after serving_mc and its JSON summary lands in the record."""
    calls, rec = _run(monkeypatch, tmp_path, {}, ["tpu"])
    assert "trace_top" in calls
    serving_calls = [i for i, c in enumerate(calls)
                     if c == "bench_serving"]
    assert calls.index("trace_top") > max(serving_calls)
    assert calls.index("trace_top") < calls.index("profile")
    assert "obs" in {r["sweep"] for r in rec}


def test_flagship_flushed_before_zoo_runs(monkeypatch, tmp_path):
    flushed = {}

    class Chk(_Script):
        def __call__(self, cmd, env_extra, log, timeout):
            if any("bench_zoo" in c for c in cmd):
                p = tmp_path / "BENCH_recovery_r05.json"
                flushed["at_zoo"] = [r["sweep"] for r in
                                     json.loads(p.read_text())]
            return _Script.__call__(self, cmd, env_extra, log, timeout)

    sc = Chk({})
    monkeypatch.setattr(tpu_watch, "run_logged", sc)
    monkeypatch.setattr(tpu_watch, "probe", lambda timeout=120: "tpu")
    monkeypatch.setattr(tpu_watch.time, "sleep", lambda s: None)
    monkeypatch.setattr(sys, "argv", [
        "tpu_watch.py", "--log", str(tmp_path / "w.log"),
        "--lock", str(tmp_path / "w.lock"),
        "--results_dir", str(tmp_path)])
    tpu_watch.main()
    assert "nhwc" in flushed["at_zoo"]
    assert "nhwc+remat" in flushed["at_zoo"]
