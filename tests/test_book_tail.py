"""Book e2e tail (VERDICT r2 task #10; reference
python/paddle/fluid/tests/book/test_label_semantic_roles.py and
test_rnn_encoder_decoder.py): SRL with embeddings + LSTM + CRF over the
conll05 reader, and a seq2seq encoder-decoder over wmt16 — both train
end-to-end through the ragged-LoD pipeline TO A THRESHOLD.

The reference book tests train on real data until an accuracy/cost gate
(test_recognize_digits.py stops at avg_cost < 100 / acc > 0.01). The
datasets here are synthetic (no egress), so the analogous contract is
overfit-to-threshold on a fixed batch: each config below must reach its
recorded loss (and accuracy, where defined) gate within max_steps, with
early stopping — "last < first" alone would pass a broken optimizer that
merely twitched downhill."""

import numpy as np

# Per-config convergence contracts. Margins are ~25-40% above measured
# convergence (SRL/Adam reaches 0.36x initial in 40 steps; seq2seq
# reaches CE 0.38 and ~0.9 next-token accuracy in 60).
THRESHOLDS = {
    "label_semantic_roles": {"max_steps": 40, "loss_ratio": 0.5},
    "rnn_encoder_decoder": {"max_steps": 60, "loss_abs": 1.0,
                            "token_acc": 0.75},
}

import paddle_tpu.fluid as fluid
import paddle_tpu.dataset as dataset
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid.lod import LoDTensor


def _to_lod(seqs, dtype=np.int64, extra_dim=True):
    """list of python lists -> LoDTensor ([sum, 1] like fluid int feeds)."""
    flat = np.concatenate([np.asarray(s, dtype) for s in seqs])
    if extra_dim:
        flat = flat.reshape(-1, 1)
    lod = [0]
    for s in seqs:
        lod.append(lod[-1] + len(s))
    t = LoDTensor(flat)
    t.set_lod([lod])
    return t


def test_label_semantic_roles_trains():
    """db_lstm-style SRL (book/test_label_semantic_roles.py): 8 feature
    embeddings + LSTM + fc emission + linear-chain CRF loss, fed by the
    conll05 reader."""
    word_dict, verb_dict, label_dict = dataset.conll05.get_dict()
    word_v, verb_v = 200, 50     # small synthetic slices of the vocabs
    n_labels = len(label_dict)
    emb_dim, hidden = 16, 32

    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        feats = []
        names = ["word", "ctx_n2", "ctx_n1", "ctx_0", "ctx_p1", "ctx_p2",
                 "verb", "mark"]
        for nm in names:
            v = fluid.layers.data(nm, shape=[1], dtype="int64",
                                  lod_level=1)
            vocab = verb_v if nm == "verb" else (2 if nm == "mark"
                                                 else word_v)
            feats.append(fluid.layers.embedding(
                v, size=[vocab, emb_dim], dtype="float32"))
        concat = fluid.layers.concat(feats, axis=-1)
        proj = fluid.layers.fc(concat, size=4 * hidden)
        h, c = fluid.layers.dynamic_lstm(proj, size=4 * hidden,
                                         use_peepholes=False)
        emission = fluid.layers.fc(h, size=n_labels)
        label = fluid.layers.data("label", shape=[1], dtype="int64",
                                  lod_level=1)
        crf_cost = fluid.layers.linear_chain_crf(
            emission, label,
            param_attr=fluid.ParamAttr(name="crfw"))
        loss = fluid.layers.mean(crf_cost)
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())

    rng = np.random.RandomState(0)
    reader = dataset.conll05.test()()
    batch = [next(reader) for _ in range(8)]
    feed = {}
    for i, nm in enumerate(names):
        seqs = [[min(t, (verb_v if nm == "verb" else
                         (1 if nm == "mark" else word_v)) - 1)
                 for t in sample[i]] for sample in batch]
        feed[nm] = _to_lod(seqs)
    feed["label"] = _to_lod(
        [[min(t, n_labels - 1) for t in s[8]] for s in batch])

    gate = THRESHOLDS["label_semantic_roles"]
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(gate["max_steps"]):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).flatten()[0]))
            assert np.isfinite(losses[-1]), losses
            if losses[-1] < losses[0] * gate["loss_ratio"]:
                break
    assert losses[-1] < losses[0] * gate["loss_ratio"], \
        "CRF loss did not reach %.2fx initial within %d steps: %s" % (
            gate["loss_ratio"], gate["max_steps"],
            [round(l, 2) for l in losses])


def test_rnn_encoder_decoder_trains():
    """seq2seq encoder-decoder (book/test_rnn_encoder_decoder.py): LSTM
    encoder (last step) seeds a DynamicRNN decoder; wmt16 feeds."""
    src_v, trg_v = 64, 64
    emb_dim, hidden = 16, 24

    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data("src", shape=[1], dtype="int64",
                                lod_level=1)
        trg = fluid.layers.data("trg", shape=[1], dtype="int64",
                                lod_level=1)
        nxt = fluid.layers.data("nxt", shape=[1], dtype="int64",
                                lod_level=1)
        src_emb = fluid.layers.embedding(src, size=[src_v, emb_dim],
                                         dtype="float32")
        proj = fluid.layers.fc(src_emb, size=4 * hidden)
        enc_h, enc_c = fluid.layers.dynamic_lstm(proj, size=4 * hidden,
                                                 use_peepholes=False)
        enc_last = fluid.layers.sequence_last_step(enc_h)

        trg_emb = fluid.layers.embedding(trg, size=[trg_v, emb_dim],
                                         dtype="float32")
        rnn = fluid.layers.DynamicRNN()
        with rnn.block():
            cur = rnn.step_input(trg_emb)
            prev = rnn.memory(init=enc_last)
            out = fluid.layers.fc(
                fluid.layers.concat([cur, prev], axis=-1), size=hidden,
                act="tanh")
            rnn.update_memory(prev, out)
            rnn.output(out)
        dec = rnn()
        logits = fluid.layers.fc(dec, size=trg_v)
        prob = fluid.layers.softmax(logits)
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=prob, label=nxt))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())

    reader = dataset.wmt16.train(src_v, trg_v)()
    batch = [next(reader) for _ in range(6)]
    feed = {
        "src": _to_lod([[min(t, src_v - 1) for t in s[0]]
                        for s in batch]),
        "trg": _to_lod([[min(t, trg_v - 1) for t in s[1]]
                        for s in batch]),
        "nxt": _to_lod([[min(t, trg_v - 1) for t in s[2]]
                        for s in batch]),
    }
    gate = THRESHOLDS["rnn_encoder_decoder"]
    want = np.asarray(feed["nxt"]._data).flatten()
    losses, acc = [], 0.0
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(gate["max_steps"]):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).flatten()[0]))
            assert np.isfinite(losses[-1]), losses
            if losses[-1] >= gate["loss_abs"]:
                continue
            # both gates must hold before stopping: the overfit model
            # must actually predict the next tokens, not just shave CE
            (pv,) = exe.run(main, feed=feed, fetch_list=[prob.name])
            acc = float(np.mean(np.argmax(np.asarray(pv), -1) == want))
            if acc >= gate["token_acc"]:
                break
    assert losses[-1] < gate["loss_abs"] and acc >= gate["token_acc"], \
        "did not reach CE<%.2f with acc>=%.2f within %d steps " \
        "(CE %.3f, acc %.3f): %s" % (
            gate["loss_abs"], gate["token_acc"], gate["max_steps"],
            losses[-1], acc, [round(l, 2) for l in losses])


def test_new_dataset_readers_shapes():
    """The 7 round-3 readers produce reference-shaped samples."""
    s = next(dataset.conll05.test()())
    assert len(s) == 9 and len(s[0]) == len(s[8])
    s = next(dataset.imikolov.train(dataset.imikolov.build_dict(), 5)())
    assert len(s) == 5
    s = next(dataset.imikolov.train(
        dataset.imikolov.build_dict(), -1,
        dataset.imikolov.DataType.SEQ)())
    assert len(s[0]) == len(s[1])
    s = next(dataset.sentiment.train()())
    assert s[1] in (0, 1) and len(s[0]) >= 10
    s = next(dataset.wmt16.train(100, 100)())
    assert s[0][0] == 0 and s[0][-1] == 1          # <s> ... <e>
    assert len(s[1]) == len(s[2])
    img, lab = next(dataset.flowers.train()())
    assert img.shape == (3, 224, 224) and 0 <= lab < 102
    s = next(dataset.mq2007.train(format="pairwise")())
    assert s[1].shape == (46,) and s[2].shape == (46,)
    rel, feats = next(dataset.mq2007.train(format="listwise")())
    assert feats.shape == (len(rel), 46)
    img, seg = next(dataset.voc2012.train()())
    assert img.shape[0] == 3 and seg.shape == img.shape[1:]
    assert seg.max() < 21
    # determinism
    a = next(dataset.sentiment.train()())
    b = next(dataset.sentiment.train()())
    assert a[0] == b[0] and a[1] == b[1]
