"""Op unit tests: conv/pool/norm/dropout/embedding vs numpy references
(reference unittests/test_conv2d_op.py, test_pool2d_op.py,
test_batch_norm_op.py, test_lookup_table_op.py)."""

import numpy as np

from op_test import OpTest


def _rand(*shape, seed=3):
    return np.random.RandomState(seed).uniform(-1, 1, shape).astype("float32")


def _conv2d_ref(x, w, stride, pad):
    n, c, h, wd = x.shape
    oc, ic, kh, kw = w.shape
    oh = (h + 2 * pad[0] - kh) // stride[0] + 1
    ow = (wd + 2 * pad[1] - kw) // stride[1] + 1
    xp = np.pad(x, ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])))
    out = np.zeros((n, oc, oh, ow), dtype=np.float64)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride[0]:i * stride[0] + kh,
                       j * stride[1]:j * stride[1] + kw]
            out[:, :, i, j] = np.tensordot(patch, w,
                                           axes=([1, 2, 3], [1, 2, 3]))
    return out.astype("float32")


class TestConv2d(OpTest):
    def setup_method(self, m):
        self.op_type = "conv2d"
        x = _rand(2, 3, 8, 8)
        w = _rand(4, 3, 3, 3, seed=5)
        self.inputs = {"Input": x, "Filter": w}
        self.outputs = {"Output": _conv2d_ref(x, w, (1, 1), (1, 1))}
        self.attrs = {"strides": [1, 1], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": 1}

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)

    def test_grad(self):
        self.check_grad(["Input", "Filter"], "Output", atol=2e-2, rtol=2e-2)


class TestPool2dMax(OpTest):
    def setup_method(self, m):
        self.op_type = "pool2d"
        x = _rand(2, 3, 8, 8)
        out = x.reshape(2, 3, 4, 2, 4, 2).max(axis=(3, 5))
        self.inputs = {"X": x}
        self.outputs = {"Out": out}
        self.attrs = {"pooling_type": "max", "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0]}

    def test_output(self):
        self.check_output()


class TestPool2dAvg(OpTest):
    def setup_method(self, m):
        self.op_type = "pool2d"
        x = _rand(2, 3, 8, 8)
        out = x.reshape(2, 3, 4, 2, 4, 2).mean(axis=(3, 5))
        self.inputs = {"X": x}
        self.outputs = {"Out": out}
        self.attrs = {"pooling_type": "avg", "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0]}

    def test_output(self):
        self.check_output()


class TestBatchNormTrain(OpTest):
    def setup_method(self, m):
        self.op_type = "batch_norm"
        x = _rand(4, 3, 5, 5)
        scale = _rand(3, seed=11)
        bias = _rand(3, seed=12)
        mean = np.zeros(3, np.float32)
        var = np.ones(3, np.float32)
        mu = x.mean(axis=(0, 2, 3))
        v = x.var(axis=(0, 2, 3))
        xhat = (x - mu.reshape(1, 3, 1, 1)) / np.sqrt(
            v.reshape(1, 3, 1, 1) + 1e-5)
        y = xhat * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": mean, "Variance": var}
        self.outputs = {"Y": y,
                        "MeanOut": 0.9 * mean + 0.1 * mu,
                        "VarianceOut": 0.9 * var + 0.1 * v}
        self.attrs = {"momentum": 0.9, "epsilon": 1e-5, "is_test": False}

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)


class TestLayerNorm(OpTest):
    def setup_method(self, m):
        self.op_type = "layer_norm"
        x = _rand(4, 10)
        scale = _rand(10, seed=21)
        bias = _rand(10, seed=22)
        mu = x.mean(-1, keepdims=True)
        v = x.var(-1, keepdims=True)
        y = (x - mu) / np.sqrt(v + 1e-5) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.outputs = {"Y": y}
        self.attrs = {"begin_norm_axis": 1, "epsilon": 1e-5}

    def test_output(self):
        self.check_output(atol=1e-4, rtol=1e-4)


class TestLookupTable(OpTest):
    def setup_method(self, m):
        self.op_type = "lookup_table"
        w = _rand(10, 4)
        ids = np.array([[1], [3], [5], [1]], dtype=np.int64)
        self.inputs = {"W": w, "Ids": ids}
        self.outputs = {"Out": w[ids.flatten()]}
        self.attrs = {"padding_idx": -1}

    def test_output(self):
        self.check_output()


class TestDropoutTestMode(OpTest):
    def setup_method(self, m):
        self.op_type = "dropout"
        x = _rand(4, 5)
        self.inputs = {"X": x}
        self.outputs = {"Out": x * 0.7}
        self.attrs = {"dropout_prob": 0.3, "is_test": True}

    def test_output(self):
        self.check_output()


class TestOneHot(OpTest):
    def setup_method(self, m):
        self.op_type = "one_hot"
        ids = np.array([[0], [2], [1]], dtype=np.int64)
        out = np.eye(4, dtype=np.float32)[ids.flatten()]
        self.inputs = {"X": ids}
        self.outputs = {"Out": out}
        self.attrs = {"depth": 4}

    def test_output(self):
        self.check_output()


class TestReshape(OpTest):
    def setup_method(self, m):
        self.op_type = "reshape"
        x = _rand(2, 6)
        self.inputs = {"X": x}
        self.outputs = {"Out": x.reshape(3, 4)}
        self.attrs = {"shape": [3, -1]}

    def test_output(self):
        self.check_output()


class TestTranspose(OpTest):
    def setup_method(self, m):
        self.op_type = "transpose"
        x = _rand(2, 3, 4)
        self.inputs = {"X": x}
        self.outputs = {"Out": x.transpose(2, 0, 1)}
        self.attrs = {"axis": [2, 0, 1]}

    def test_output(self):
        self.check_output()


class TestConcat(OpTest):
    def setup_method(self, m):
        self.op_type = "concat"
        a, b = _rand(2, 3), _rand(2, 5)
        self.inputs = {"X": [("ca", a), ("cb", b)]}
        self.outputs = {"Out": np.concatenate([a, b], axis=1)}
        self.attrs = {"axis": 1}

    def test_output(self):
        self.check_output()


class TestGather(OpTest):
    def setup_method(self, m):
        self.op_type = "gather"
        x = _rand(6, 3)
        idx = np.array([0, 2, 5], dtype=np.int64)
        self.inputs = {"X": x, "Index": idx}
        self.outputs = {"Out": x[idx]}
        self.attrs = {}

    def test_output(self):
        self.check_output()


def test_lrn_matches_reference_oracle():
    """lrn_op.cc restated: window [c-(n-1)//2, c+n-1-(n-1)//2], MidOut
    is the pre-power scale, Out = x * mid^-beta."""
    from paddle_tpu.ops.registry import get_op_def, ExecContext
    import jax.numpy as jnp
    rng = np.random.RandomState(67)
    N, C, H, W = 2, 7, 3, 3
    n, k, alpha, beta = 5, 2.0, 1e-2, 0.75
    x = rng.randn(N, C, H, W).astype(np.float32)

    sq = x ** 2
    mid = np.full_like(x, k)
    pre = (n - 1) // 2
    for c in range(C):
        lo, hi = c - pre, c - pre + n
        for cc in range(max(lo, 0), min(hi, C)):
            mid[:, c] += alpha * sq[:, cc]
    want_out = x * mid ** (-beta)

    class _Op:
        type = "lrn"
        outputs = {}
        attrs = {"n": n, "k": k, "alpha": alpha, "beta": beta}
    r = get_op_def("lrn").lower(ExecContext(_Op(), {"X": [jnp.asarray(x)]}))
    np.testing.assert_allclose(np.asarray(r["MidOut"]), mid, atol=1e-5)
    np.testing.assert_allclose(np.asarray(r["Out"]), want_out, atol=1e-5)
