"""Mesh-replica tests (SERVING.md "Mesh replicas").

One serving replica = a device mesh: params and the decode KV slot
table live SHARDED across the member chips (NamedSharding over the 1-D
"model" axis), compute runs replicated, so a mesh replica's replies
are bit-exact vs a single-device replica by construction.  Pins:

* placement grammar — 'mesh:N' / 'mesh:RxC' host packing, explicit
  'a+b' member lists, the 1-member/mesh:1 collapse to the legacy plain
  -device path, duplicate/unknown-member rejection, and the
  device_labels() -> resolve_placement round trip the fleet replay
  rides;
* params actually sharded — per-member addressable bytes strictly
  below the whole model, KV slot-table shards exactly 1/mesh;
* per-member fit pricing — analyze_artifact(mesh_size=m) /
  ResourceReport.per_device_bytes: a model whose static estimate
  exceeds one device's budget is REJECTED single-device and ADMITTED
  + served on a 2-chip mesh, stream bit-exact vs direct
  single-process execution (the ISSUE 19 acceptance pin);
* sharded int8 KV decode parity + the spec-twin accept==1.0 invariant
  riding the sharded program unchanged;
* mesh lanes in the serving stack — registry streams bit-exact, lane
  death on member loss is typed (sibling lanes unaffected), stats
  carry mesh shape, hot swap of a whole mesh lane set under hammer
  keeps every reply exactly one version's output.

Everything CPU-safe under JAX_PLATFORMS=cpu + the conftest's 8 forced
host devices.
"""

import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.analysis.resources import (ResourceFitError,
                                           analyze_artifact)
from paddle_tpu.flags import get_flags, set_flags
from paddle_tpu.inference.decode import (GenerativePredictor,
                                         SpeculativeDecodeSession,
                                         build_tiny_decode_model,
                                         greedy_decode)
from paddle_tpu.parallel.mesh import (MeshGroup, MeshMemberLost,
                                      as_mesh_group, set_member_poison)
from paddle_tpu.serving import ModelRegistry, resolve_placement

import jax


@pytest.fixture(autouse=True)
def _clear_poison():
    yield
    set_member_poison(None)


def _lm(tmp_path, name="lm", seed=7, **kw):
    kw.setdefault("vocab_size", 32)
    kw.setdefault("d_model", 16)
    kw.setdefault("n_heads", 2)
    kw.setdefault("n_layers", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("eos_id", -1)
    return build_tiny_decode_model(str(tmp_path / name), seed=seed, **kw)


def _export_fc(tmp_path, seed, name="m"):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        h = fluid.layers.fc(input=x, size=6, act="relu")
        pred = fluid.layers.fc(input=h, size=6, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / name)
        fluid.save_inference_model(md, ["x"], [pred], exe,
                                   main_program=main)
    return md


def _flat(stream_result):
    """DecodeStream.result() returns token-chunk arrays; flatten to a
    plain int list for comparison against greedy_decode."""
    chunks = [np.atleast_1d(np.asarray(c)) for c in stream_result]
    if not chunks:
        return []
    return [int(t) for t in np.concatenate(chunks)]


# ---------------------------------------------------------------------------
# placement grammar
# ---------------------------------------------------------------------------

class TestMeshPlacement:
    def test_mesh_string_packs_whole_host(self):
        groups = resolve_placement("mesh:2")
        assert len(groups) == jax.device_count() // 2
        assert all(isinstance(g, MeshGroup) and g.mesh_size == 2
                   for g in groups)
        # members partition the host: no chip serves two replicas
        labels = [l for g in groups for l in g.member_labels()]
        assert len(labels) == len(set(labels)) == jax.device_count()

    def test_mesh_rxc_dims(self):
        groups = resolve_placement("mesh:2x2")
        assert len(groups) == jax.device_count() // 4
        assert all(g.mesh_size == 4 for g in groups)

    def test_mesh_1_is_the_legacy_plain_path(self):
        # a 1-device mesh IS the pre-mesh behavior: plain jax.Device
        # replicas, no MeshGroup wrapper anywhere
        groups = resolve_placement("mesh:1")
        assert groups == list(jax.local_devices())
        assert all(as_mesh_group(d) is None for d in groups)

    def test_explicit_member_list(self):
        groups = resolve_placement("cpu:0+cpu:1,cpu:2+cpu:3")
        assert [g.mesh_size for g in groups] == [2, 2]
        assert groups[0].member_labels() == ["cpu:0", "cpu:1"]
        assert groups[1].member_labels() == ["cpu:2", "cpu:3"]
        # the mesh label is the "+"-joined member list — what
        # device_labels()/load specs persist
        assert groups[0].label() == "cpu:0+cpu:1"

    def test_single_member_collapses_to_plain_device(self):
        groups = resolve_placement("cpu:0,cpu:1+cpu:2")
        assert as_mesh_group(groups[0]) is None  # plain jax.Device
        assert groups[0].platform == "cpu" and groups[0].id == 0
        assert as_mesh_group(groups[1]).mesh_size == 2

    def test_label_round_trips_through_resolve(self):
        # the fleet fault-in/resize replay path: persisted labels must
        # rebuild the SAME mesh shape
        first = resolve_placement("cpu:0+cpu:1,cpu:2+cpu:3")
        labels = ",".join(g.label() for g in first)
        again = resolve_placement(labels)
        assert [g.member_labels() for g in again] \
            == [g.member_labels() for g in first]

    def test_rejects_overlapping_members(self):
        with pytest.raises(ValueError):
            resolve_placement("cpu:0+cpu:1,cpu:1+cpu:2")

    def test_rejects_member_doubling_as_plain_replica(self):
        with pytest.raises(ValueError):
            resolve_placement("cpu:0+cpu:1,cpu:1")

    def test_rejects_unknown_member_device(self):
        with pytest.raises(ValueError):
            resolve_placement("cpu:0+nope:7")

    def test_rejects_mesh_wider_than_host(self):
        with pytest.raises(ValueError):
            resolve_placement("mesh:%d" % (jax.device_count() * 2))

    def test_rejects_mesh_token_inside_a_list(self):
        with pytest.raises(ValueError):
            resolve_placement("mesh:2,cpu:0")


# ---------------------------------------------------------------------------
# params + KV actually sharded (not replicated) across members
# ---------------------------------------------------------------------------

class TestActuallySharded:
    def test_param_bytes_per_member_below_whole_model(self, tmp_path):
        md = _lm(tmp_path)
        devs = jax.devices()
        pm = GenerativePredictor(md, device=MeshGroup(devs[:2]))
        total = sum(int(np.asarray(v).nbytes)
                    for v in pm._state_host.values())
        per = sum(int(s.data.nbytes) for v in pm._state.values()
                  for s in v.addressable_shards if s.device == devs[0])
        assert per < total, \
            "mesh member holds the WHOLE model (%d of %d bytes) — " \
            "params are replicated, not sharded" % (per, total)

    def test_kv_slot_table_shards_exactly_1_over_mesh(self, tmp_path):
        md = _lm(tmp_path)
        devs = jax.devices()
        pm = GenerativePredictor(md, device=MeshGroup(devs[:2]))
        sess = pm.new_session(4)
        per = sum(int(s.data.nbytes) for s in sess._kc.addressable_shards
                  if s.device == devs[0])
        assert per * 2 == int(sess._kc.nbytes)


# ---------------------------------------------------------------------------
# per-member fit pricing (the ISSUE 19 acceptance pin)
# ---------------------------------------------------------------------------

class TestMeshFitCheck:
    # big enough that the estimate straddles an MB-granular budget:
    # ~5.7 MiB whole, ~2.9 MiB per 2-mesh member
    BIG = dict(vocab_size=64, d_model=128, n_heads=4, n_layers=2,
               max_seq_len=256)
    SLOTS = 8
    BUDGET_MB = 4

    def test_static_per_device_pricing(self, tmp_path):
        md = _lm(tmp_path, name="big", **self.BIG)
        rep = analyze_artifact(md, decode_slots=self.SLOTS)
        # mesh_size=1 is EXACTLY the legacy estimate
        assert rep.per_device_bytes(1) == rep.peak_bytes
        # sharded-at-rest bytes (params + KV slot table) price at
        # ceil(1/m); the replicated-compute activation peak does not
        sharded = rep.param_bytes + rep.kv_cache_bytes
        for m in (2, 4):
            assert rep.per_device_bytes(m) \
                == -(-sharded // m) + rep.activation_peak_bytes
        # analyze_artifact(mesh_size=) stamps the report: to_dict and
        # downstream consumers read per-device numbers directly
        rep2 = analyze_artifact(md, decode_slots=self.SLOTS,
                                mesh_size=2)
        d = rep2.to_dict()
        assert d["mesh_size"] == 2
        assert d["per_device_bytes"] == rep.per_device_bytes(2)
        # per-member KV bytes ~1/mesh statically
        assert rep.kv_cache_bytes // 2 \
            <= rep2.per_device_bytes() - rep2.activation_peak_bytes \
            - rep2.param_bytes // 2 + 1

    def test_rejected_single_device_admitted_on_2_mesh_bit_exact(
            self, tmp_path):
        md = _lm(tmp_path, name="big", **self.BIG)
        old = get_flags(["serving_device_mem_mb"])
        set_flags({"serving_device_mem_mb": self.BUDGET_MB})
        reg = ModelRegistry()
        try:
            with pytest.raises(ResourceFitError):
                reg.load_model("big", md, devices=["cpu:0"],
                               decode_slots=self.SLOTS)
            # the SAME model admits when the replica is a 2-chip mesh:
            # each member is priced at ~half the sharded bytes
            reg.load_model("big", md, devices=["cpu:0+cpu:1"],
                           decode_slots=self.SLOTS)
            info = reg.describe()["big"]
            assert info["mesh"] == [2]
            assert info["est_per_device_mb"] < self.BUDGET_MB \
                < info["est_peak_mb"]
            # ...and SERVES bit-exact vs direct single-process
            # execution on the unsharded artifact
            prompt = [3, 5, 7]
            ref, _ = greedy_decode(GenerativePredictor(md), prompt, 8,
                                   n_slots=self.SLOTS, slot=0)
            out = _flat(reg.submit_stream("big", prompt,
                                          max_new_tokens=8).result(
                                              timeout=300))
            assert out == ref
        finally:
            reg.close_all()
            set_flags(old)

    def test_draft_twin_priced_per_member_too(self, tmp_path):
        md = _lm(tmp_path, name="big", **self.BIG)
        old = get_flags(["serving_device_mem_mb"])
        # both target and twin draft shard across the mesh: 2x the
        # per-member bytes must still overflow a budget sized for one
        set_flags({"serving_device_mem_mb": self.BUDGET_MB})
        reg = ModelRegistry()
        try:
            with pytest.raises(ResourceFitError):
                reg.load_model("big", md, devices=["cpu:0+cpu:1"],
                               decode_slots=self.SLOTS, draft=md,
                               spec_k=2)
        finally:
            reg.close_all()
            set_flags(old)


# ---------------------------------------------------------------------------
# sharded decode parity: int8 KV + speculative twin ride unchanged
# ---------------------------------------------------------------------------

class TestShardedDecodeParity:
    def test_int8_kv_mesh_stream_bit_exact(self, tmp_path):
        md = _lm(tmp_path)
        devs = jax.devices()
        prompt = [3, 5, 7, 9, 11]
        ref, _ = greedy_decode(
            GenerativePredictor(md, device=devs[0],
                                kv_cache_dtype="int8"),
            prompt, 12, n_slots=4, slot=1)
        out, _ = greedy_decode(
            GenerativePredictor(md, device=MeshGroup(devs[:2]),
                                kv_cache_dtype="int8"),
            prompt, 12, n_slots=4, slot=1)
        assert out == ref

    def test_spec_twin_on_mesh_accepts_exactly_all(self, tmp_path):
        md = _lm(tmp_path)
        devs = jax.devices()
        group = MeshGroup(devs[:2])
        pm = GenerativePredictor(md, device=group)
        p8 = GenerativePredictor(md, device=group,
                                 kv_cache_dtype="int8")
        prompt = [3, 5, 7, 9, 11]
        ref, _ = greedy_decode(GenerativePredictor(md, device=devs[0]),
                               prompt, 12, n_slots=4, slot=1)
        spec = SpeculativeDecodeSession(pm, p8, 4, 2)
        got = [spec.prefill(1, prompt)]
        while len(got) < 12 and got[-1] != pm.eos_id:
            toks, counts = spec.step()
            got.extend(int(t) for t in toks[1][:counts[1]])
        assert got[:12] == ref
        # int8-twin drafting for the fp32 target on the SAME mesh:
        # accept rate must be exactly 1.0
        assert spec.proposed > 0 and spec.accepted == spec.proposed


# ---------------------------------------------------------------------------
# mesh lanes in the serving stack
# ---------------------------------------------------------------------------

class TestMeshServing:
    def test_streams_bit_exact_and_stats_carry_mesh(self, tmp_path):
        md = _lm(tmp_path)
        reg = ModelRegistry()
        try:
            reg.load_model("lm", md, devices=["cpu:0+cpu:1",
                                              "cpu:2+cpu:3"],
                           decode_slots=2)
            entry = reg._models["lm"]["versions"][1]
            assert entry.mesh_sizes() == [2, 2]
            assert entry.device_labels() == ["cpu:0+cpu:1",
                                             "cpu:2+cpu:3"]
            pred = GenerativePredictor(md)
            prompts = [[3, 5, 7], [9, 4], [11, 12, 13, 14], [2, 6]]
            refs = [greedy_decode(pred, p, 10)[0] for p in prompts]
            streams = [reg.submit_stream("lm", p, max_new_tokens=10)
                       for p in prompts]
            for s, ref in zip(streams, refs):
                assert _flat(s.result(timeout=300)) == ref
            rows = entry.batcher.replica_stats()
            assert [r["mesh"] for r in rows] == [2, 2]
            assert all(r["dead"] is None for r in rows)
            assert reg.describe()["lm"]["mesh"] == [2, 2]
        finally:
            reg.close_all()

    def test_member_loss_kills_lane_typed_sibling_survives(
            self, tmp_path):
        md = _lm(tmp_path)
        reg = ModelRegistry()
        try:
            reg.load_model("lm", md, devices=["cpu:0+cpu:1",
                                              "cpu:2+cpu:3"],
                           decode_slots=2)
            pred = GenerativePredictor(md)
            prompt = [3, 5, 7]
            ref, _ = greedy_decode(pred, prompt, 10)
            set_member_poison("cpu:3")
            # drive until the poisoned lane has eaten a stream: lane
            # assignment is least-loaded, so a few streams cover both
            outcomes = []
            for _ in range(4):
                s = reg.submit_stream("lm", prompt, max_new_tokens=10)
                try:
                    outcomes.append(("ok", _flat(s.result(timeout=300))))
                except MeshMemberLost as e:
                    outcomes.append(("dead", str(e)))
            kinds = [k for k, _ in outcomes]
            assert "dead" in kinds, \
                "poisoned lane never took a stream: %s" % (outcomes,)
            assert "ok" in kinds, \
                "member loss killed the SIBLING lane too"
            for k, v in outcomes:
                if k == "ok":
                    assert v == ref
                else:
                    assert "cpu:3" in v  # typed, naming the member
            entry = reg._models["lm"]["versions"][1]
            rows = entry.batcher.replica_stats()
            dead = [r for r in rows if r["dead"]]
            assert len(dead) == 1 and "cpu:3" in dead[0]["device"]
            # post-loss traffic rides the survivor, still bit-exact
            out = _flat(reg.submit_stream(
                "lm", prompt, max_new_tokens=10).result(timeout=300))
            assert out == ref
        finally:
            reg.close_all()

    def test_resize_grows_mesh_lanes(self, tmp_path):
        md = _lm(tmp_path)
        reg = ModelRegistry()
        try:
            reg.load_model("lm", md, devices=["cpu:0+cpu:1",
                                              "cpu:2+cpu:3"],
                           decode_slots=2)
            reg.resize_model("lm", 3)
            entry = reg._models["lm"]["versions"][2]
            assert entry.mesh_sizes() == [2, 2, 2]
            assert entry.device_labels()[2] == "cpu:4+cpu:5"
            pred = GenerativePredictor(md)
            prompt = [5, 9, 2]
            ref, _ = greedy_decode(pred, prompt, 8)
            out = _flat(reg.submit_stream(
                "lm", prompt, max_new_tokens=8).result(timeout=300))
            assert out == ref
        finally:
            reg.close_all()


class TestMeshHotSwap:
    def test_swap_mesh_lane_set_under_hammer(self, tmp_path):
        """Hammer one model from 4 threads while hot-swapping a
        2x2-chip mesh lane set for another: every request resolves
        exactly once, every answer is exactly v1's or v2's output, and
        post-swap traffic serves v2 from mesh lanes."""
        md1 = _export_fc(tmp_path, seed=31, name="v1")
        md2 = _export_fc(tmp_path, seed=32, name="v2")
        x = np.random.RandomState(6).randn(2, 4).astype(np.float32)
        from paddle_tpu.inference import AnalysisConfig, Predictor
        cfg = AnalysisConfig(model_dir=md1)
        cfg.batch_size_buckets = (2, 4)
        r1 = Predictor(cfg).run({"x": x})[0]
        cfg2 = AnalysisConfig(model_dir=md2)
        cfg2.batch_size_buckets = (2, 4)
        r2 = Predictor(cfg2).run({"x": x})[0]
        placement = "cpu:0+cpu:1,cpu:2+cpu:3"
        reg = ModelRegistry(deadline_ms=2)
        reg.load_model("m", md1, buckets=(2, 4), replicas=placement)
        stop = threading.Event()
        wrong, errors, answered = [], [], [0]
        lock = threading.Lock()

        def hammer():
            while not stop.is_set():
                try:
                    out = reg.infer("m", {"x": x}, timeout=60)[0]
                except Exception as e:
                    errors.append(e)
                    return
                with lock:
                    answered[0] += 1
                    if not (np.array_equal(out, r1)
                            or np.array_equal(out, r2)):
                        wrong.append(out)

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        try:
            for t in threads:
                t.start()
            time.sleep(0.25)
            # the swap builds + warms the WHOLE mesh set before the flip
            reg.load_model("m", md2, buckets=(2, 4), replicas=placement)
            time.sleep(0.25)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=60)
        assert not errors, errors[:3]
        assert not wrong, "%d responses matched neither version" \
            % len(wrong)
        assert answered[0] > 10
        out_after = reg.infer("m", {"x": x}, timeout=60)[0]
        assert np.array_equal(out_after, r2)
        entry = reg._models["m"]["versions"][2]
        assert entry.mesh_sizes() == [2, 2]
        reg.close_all()
