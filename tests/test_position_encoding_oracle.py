"""add_position_encoding reference oracle
(add_position_encoding_op.h restated): dst[k] = x*alpha +
sin(j / 10000^(k/(half-1)))*beta for the first half, cos of the same
for the second half — the exponent divides by half_size-1, not half."""

import numpy as np
import pytest

from tests.test_op_tail import run_op


def oracle(x, alpha, beta):
    B, T, D = x.shape
    half = D // 2
    out = np.empty_like(x)
    for j in range(T):
        for k in range(half):
            val = (j / (10000.0 ** (k / (half - 1))) if half > 1
                   else j / 10000.0)
            out[:, j, k] = x[:, j, k] * alpha + np.sin(val) * beta
            out[:, j, half + k] = (x[:, j, half + k] * alpha
                                   + np.cos(val) * beta)
    return out


@pytest.mark.parametrize("D", [8, 2])    # half > 1 and half == 1
def test_add_position_encoding_matches_reference(D):
    x = np.random.RandomState(0).randn(2, 5, D).astype(np.float32)
    out = run_op("add_position_encoding", {"X": x},
                 {"alpha": 0.7, "beta": 1.3})
    np.testing.assert_allclose(np.asarray(out["Out"]),
                               oracle(x, 0.7, 1.3), atol=1e-5)


def test_odd_encode_size_rejected():
    x = np.zeros((1, 2, 3), np.float32)
    with pytest.raises(Exception):
        run_op("add_position_encoding", {"X": x}, {})
