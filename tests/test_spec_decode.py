"""Speculative decoding tests (SERVING.md "Speculative decoding",
paddle_tpu/inference/decode.py SpeculativeDecodeSession + the serving
DecodeBatcher's variable-accept lanes).

The load-bearing contracts, in rough dependency order:

* `DecodeSession.rollback(slot, n, last_token=)` leaves the slot
  BIT-IDENTICAL to one that never advanced — the primitive the draft
  sync is built on;
* the speculative stream is bit-identical to the fp32-only greedy
  stream: with a same-weights twin draft accept rate is exactly 1.0
  (any verify-vs-step numeric drift would reject a draft), with a
  mismatched draft accepts drop but tokens never change;
* nearly-full slots fall back to plain rounds (progress is never
  blocked), and a draft failure degrades the session to target-only
  decode within the same round, stream intact (`spec_degraded`);
* prefill prompts past every configured bucket fall through to an
  exact-length compile with a once-per-size warning (the Predictor
  batch-bucket overflow parity);
* serving wiring end to end: load_model(draft=, spec_k=) over the
  wire, drafts/accepts telemetry (stats, Prometheus, serving_top ACC%),
  draft+verify spans tiling serving/decode_step, the admission fit
  check covering target + draft together, and the verify executable
  riding the persistent compile cache.

Everything CPU-safe under JAX_PLATFORMS=cpu.
"""

import os
import subprocess
import sys
import threading
import time
import warnings

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.inference.decode import (DecodeSession,
                                         GenerativePredictor,
                                         SpeculativeDecodeSession,
                                         build_tiny_decode_model,
                                         greedy_decode,
                                         save_decode_model,
                                         set_draft_poison)
from paddle_tpu.serving import (DecodeBatcher, InferenceServer,
                                ServingClient, ServingMetrics,
                                set_dispatch_delay, set_draft_delay)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture(autouse=True)
def _clear_chaos():
    yield
    set_dispatch_delay(0.0)
    set_draft_delay(0.0)
    set_draft_poison(None)


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("spec_model") / "lm")
    build_tiny_decode_model(d, vocab_size=32, d_model=16, n_heads=2,
                            n_layers=2, max_seq_len=64, eos_id=0,
                            seed=7)
    return d


@pytest.fixture(scope="module")
def other_artifact(tmp_path_factory):
    """Same vocab/eos/geometry family, DIFFERENT weights — the
    low-accept draft."""
    d = str(tmp_path_factory.mktemp("spec_model_alt") / "lm2")
    build_tiny_decode_model(d, vocab_size=32, d_model=16, n_heads=2,
                            n_layers=1, max_seq_len=64, eos_id=0,
                            seed=101)
    return d


@pytest.fixture(scope="module")
def predictor(artifact):
    return GenerativePredictor(artifact)


def _drain_spec(sess, prompts, max_new, fused=False):
    """Drive a SpeculativeDecodeSession to completion for `prompts`
    (slot i = prompt i); returns the per-prompt token streams with the
    same per-token EOS/max-new cuts the serving loop applies.  `fused`
    runs every round through the single-dispatch fused program."""
    eos = sess.predictor.eos_id
    streams = {i: [sess.prefill(i, p)] for i, p in enumerate(prompts)}
    done = {i for i, s in streams.items()
            if s[-1] == eos or len(s) >= max_new}
    for i in done:
        sess.free(i)
    rounds = 0
    while len(done) < len(prompts):
        rounds += 1
        assert rounds < 500, "speculative session wedged"
        toks, counts = sess.step(fused=fused)
        for i in list(streams):
            if i in done:
                continue
            for j in range(int(counts[i])):
                streams[i].append(int(toks[i, j]))
                if streams[i][-1] == eos or len(streams[i]) >= max_new:
                    break
            if streams[i][-1] == eos or len(streams[i]) >= max_new:
                done.add(i)
                sess.free(i)
    return [streams[i] for i in range(len(prompts))]


# ---------------------------------------------------------------------------
# the rollback primitive
# ---------------------------------------------------------------------------

class TestRollback:
    def test_rollback_bit_identical_to_never_advanced(self, predictor):
        a = predictor.new_session(2)
        b = predictor.new_session(2)
        first_a = a.prefill(0, [3, 5, 7])
        first_b = b.prefill(0, [3, 5, 7])
        assert first_a == first_b
        for _ in range(3):
            a.decode()
        a.rollback(0, 3, last_token=first_b)
        # the whole slot table — cache bits, length pointers, pending
        # tokens — must equal the session that never advanced
        assert np.array_equal(np.asarray(a._kc), np.asarray(b._kc))
        assert np.array_equal(np.asarray(a._vc), np.asarray(b._vc))
        assert a.lengths.tolist() == b.lengths.tolist()
        assert a.last_tokens.tolist() == b.last_tokens.tolist()
        # and decode identically afterwards
        for _ in range(4):
            ta, tb = a.decode(), b.decode()
            assert int(ta[0]) == int(tb[0])

    def test_rollback_partial_keeps_prefix_rows(self, predictor):
        a = predictor.new_session(1)
        a.prefill(0, [3, 5, 7])
        t1 = int(a.decode()[0])
        kc_after_one = np.asarray(a._kc).copy()
        len_after_one = int(a.lengths[0])
        for _ in range(2):
            a.decode()
        a.rollback(0, 2, last_token=t1)
        assert int(a.lengths[0]) == len_after_one
        assert np.array_equal(np.asarray(a._kc), kc_after_one)

    def test_rollback_validation(self, predictor):
        a = predictor.new_session(1)
        a.prefill(0, [3, 5])
        with pytest.raises(ValueError):
            a.rollback(0, -1)
        with pytest.raises(ValueError):
            a.rollback(0, int(a.lengths[0]) + 1)
        # n=0 with a pin only retargets the pending token
        a.rollback(0, 0, last_token=9)
        assert int(a.last_tokens[0]) == 9


# ---------------------------------------------------------------------------
# the speculative session: bit-exactness is the whole contract
# ---------------------------------------------------------------------------

class TestSpeculativeSession:
    def test_twin_draft_full_accept_bit_exact(self, artifact,
                                              predictor):
        prompts = [[3, 5, 7], [9, 4]]
        refs = [greedy_decode(predictor, p, 24)[0] for p in prompts]
        draft = GenerativePredictor(artifact)
        sess = SpeculativeDecodeSession(predictor, draft, 2, spec_k=3)
        streams = _drain_spec(sess, prompts, 24)
        assert streams == refs
        # same weights -> the draft IS the sequential stream, so any
        # verify-vs-step numeric drift would show as a reject first
        assert sess.proposed > 0
        assert sess.accepted == sess.proposed
        assert sess.rounds > 0 and sess.plain_steps == 0

    def test_fused_round_twin_draft_bit_exact(self, artifact,
                                              predictor):
        """The fused speculative round (SERVING.md "Fused multi-step
        decode"): k draft steps + the batched verify + in-graph
        commit/rollback/catch-up compile into ONE dispatch.  Streams
        must equal the host-driven rounds AND the N=1 greedy oracle,
        with the twin draft accepting EXACTLY 1.0 — the bar that proves
        the in-graph bookkeeping moved no token."""
        prompts = [[3, 5, 7], [9, 4]]
        refs = [greedy_decode(predictor, p, 24)[0] for p in prompts]
        draft = GenerativePredictor(artifact)
        sess = SpeculativeDecodeSession(predictor, draft, 2, spec_k=3)
        streams = _drain_spec(sess, prompts, 24, fused=True)
        assert streams == refs
        assert sess.proposed > 0
        assert sess.accepted == sess.proposed, \
            "twin-draft accept under fusion must be exactly 1.0"
        assert sess.rounds > 0 and sess.plain_steps == 0

    def test_fused_round_mismatched_draft_rollback_bit_exact(
            self, artifact, other_artifact, predictor):
        """Fused rounds with a DISAGREEING draft: the in-graph rollback
        (stale draft rows zeroed, pointers rewound) must keep streams
        bit-exact, and the draft table must end IDENTICAL to the
        host-driven session's after the same rounds."""
        prompts = [[11, 12, 13, 14], [2]]
        refs = [greedy_decode(predictor, p, 16)[0] for p in prompts]
        draft = GenerativePredictor(other_artifact)
        sess = SpeculativeDecodeSession(predictor, draft, 2, spec_k=2)
        streams = _drain_spec(sess, prompts, 16, fused=True)
        assert streams == refs
        assert sess.accepted < sess.proposed

    def test_mismatched_draft_low_accept_still_bit_exact(
            self, artifact, other_artifact, predictor):
        prompts = [[11, 12, 13, 14], [2]]
        refs = [greedy_decode(predictor, p, 16)[0] for p in prompts]
        draft = GenerativePredictor(other_artifact)
        sess = SpeculativeDecodeSession(predictor, draft, 2, spec_k=2)
        streams = _drain_spec(sess, prompts, 16)
        assert streams == refs
        # a different model mostly disagrees — but tokens never moved
        assert sess.accepted < sess.proposed

    def test_near_full_slot_falls_back_to_plain_rounds(self, artifact,
                                                       predictor):
        draft = GenerativePredictor(artifact)
        sess = SpeculativeDecodeSession(predictor, draft, 1, spec_k=4)
        # prompt of 57 on a 64-cache: the first spec round (room 7)
        # fits, but it pushes the slot past room < k+1 — the session
        # must switch to plain rounds mid-stream and still finish
        # exactly
        prompt = (list(range(1, 30)) * 2)[:57]
        ref, _ = greedy_decode(predictor, prompt, 8)
        streams = _drain_spec(sess, [prompt], 8)
        assert streams[0] == ref
        assert sess.plain_steps > 0, \
            "a nearly-full slot must decode via plain fallback rounds"

    def test_draft_poison_degrades_same_round_bit_exact(
            self, artifact, predictor):
        prompts = [[3, 5, 7], [9, 4]]
        refs = [greedy_decode(predictor, p, 20)[0] for p in prompts]
        draft = GenerativePredictor(artifact)
        sess = SpeculativeDecodeSession(predictor, draft, 2, spec_k=3)
        streams = {i: [sess.prefill(i, p)]
                   for i, p in enumerate(prompts)}
        toks, counts = sess.step()   # one healthy speculative round
        for i in streams:
            streams[i] += [int(toks[i, j])
                           for j in range(int(counts[i]))]
        set_draft_poison(0)
        rounds = 0
        while any(len(s) < 20 for s in streams.values()):
            rounds += 1
            assert rounds < 100
            toks, counts = sess.step()
            for i in streams:
                for j in range(int(counts[i])):
                    if len(streams[i]) < 20:
                        streams[i].append(int(toks[i, j]))
        assert sess.degraded
        assert "poison" in sess.degrade_error
        for i, r in enumerate(refs):
            assert streams[i] == r[:len(streams[i])] and \
                len(streams[i]) == 20

    def test_incompatible_draft_rejected(self, predictor, tmp_path):
        bad = str(tmp_path / "bad_vocab")
        build_tiny_decode_model(bad, vocab_size=16, d_model=16,
                                n_heads=2, n_layers=1, max_seq_len=64,
                                eos_id=0, seed=3)
        with pytest.raises(ValueError, match="vocab"):
            SpeculativeDecodeSession(predictor,
                                     GenerativePredictor(bad), 2, 2)
        short = str(tmp_path / "bad_len")
        build_tiny_decode_model(short, vocab_size=32, d_model=16,
                                n_heads=2, n_layers=1, max_seq_len=32,
                                eos_id=0, seed=3)
        with pytest.raises(ValueError, match="max_seq_len"):
            SpeculativeDecodeSession(predictor,
                                     GenerativePredictor(short), 2, 2)
        with pytest.raises(ValueError, match="spec_k"):
            SpeculativeDecodeSession(predictor, predictor, 2, 0)


# ---------------------------------------------------------------------------
# prefill bucket overflow: warn-once fall-through (Predictor parity)
# ---------------------------------------------------------------------------

class TestPrefillOverflowWarn:
    def test_overflow_warns_once_per_size_and_serves(self, tmp_path):
        # custom meta whose buckets stop well short of max_seq_len
        d = str(tmp_path / "smallbuckets")
        base = str(tmp_path / "base")
        build_tiny_decode_model(base, vocab_size=32, d_model=16,
                                n_heads=2, n_layers=1, max_seq_len=64,
                                eos_id=0, seed=5)
        from paddle_tpu.native import wire
        with open(os.path.join(base, "decode_state.bin"), "rb") as f:
            state = wire.decode(f.read())
        with open(os.path.join(base, "decode_meta.bin"), "rb") as f:
            meta = wire.decode(f.read())
        meta["prefill_buckets"] = [8]
        save_decode_model(d, state, meta)
        pred = GenerativePredictor(d)
        prompt = list(range(1, 13))   # 12 tokens > bucket 8
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert pred.prompt_bucket(12) == 12
            assert pred.prompt_bucket(12) == 12   # second call silent
        overflow = [x for x in w if "prefill" in str(x.message)]
        assert len(overflow) == 1, [str(x.message) for x in w]
        assert "12" in str(overflow[0].message)
        # and the fall-through actually serves, matching a same-length
        # decode on the untouched artifact (same weights)
        ref, _ = greedy_decode(GenerativePredictor(base), prompt, 4)
        got, _ = greedy_decode(pred, prompt, 4)
        assert got == ref
        with pytest.raises(ValueError, match="max_seq_len"):
            pred.prompt_bucket(65)


# ---------------------------------------------------------------------------
# the serving batcher: variable-accept lanes
# ---------------------------------------------------------------------------

class TestSpecBatcher:
    def test_spec_streams_bit_exact_join_leave(self, artifact,
                                               predictor):
        metrics = ServingMetrics().model("lm")
        draft = GenerativePredictor(artifact)
        b = DecodeBatcher(predictor, n_slots=2, metrics=metrics,
                          draft=draft, spec_k=2)
        try:
            prompts = [[3, 5, 7], [9, 4], [11, 12, 13, 14], [2],
                       [7, 7, 7]]
            budgets = [12, 7, 16, 9, 5]
            streams = [b.submit(p, max_new_tokens=n)
                       for p, n in zip(prompts, budgets)]
            outs = [s.result(timeout=120)[0].tolist() for s in streams]
            for p, n, out in zip(prompts, budgets, outs):
                assert out == greedy_decode(predictor, p, n)[0]
            snap = metrics.snapshot()
            assert snap["spec_rounds"] > 0
            assert snap["draft_tokens"] > 0
            assert snap["spec_accept_rate"] == 1.0
            assert snap["accept_rate"]["count"] == snap["spec_rounds"]
            assert snap["spec_degraded"] == 0
        finally:
            b.close(drain=False, timeout=5.0)

    def test_spec_rides_fused_batcher_bit_exact(self, artifact,
                                                predictor):
        """spec_k>0 + fuse_steps>1: the lane routes rounds through the
        fused spec program (one dispatch per round) and streams stay
        bit-exact with accept exactly 1.0 on the twin draft."""
        metrics = ServingMetrics().model("lm")
        draft = GenerativePredictor(artifact)
        b = DecodeBatcher(predictor, n_slots=2, metrics=metrics,
                          draft=draft, spec_k=2, fuse_steps=4)
        try:
            prompts = [[3, 5, 7], [9, 4], [11, 12, 13, 14]]
            budgets = [12, 7, 9]
            streams = [b.submit(p, max_new_tokens=n)
                       for p, n in zip(prompts, budgets)]
            outs = [s.result(timeout=120)[0].tolist() for s in streams]
            for p, n, out in zip(prompts, budgets, outs):
                assert out == greedy_decode(predictor, p, n)[0]
            snap = metrics.snapshot()
            assert snap["spec_rounds"] > 0
            assert snap["spec_accept_rate"] == 1.0
            assert snap["spec_degraded"] == 0
            assert snap["decode_dispatches"] > 0
        finally:
            b.close(drain=False, timeout=5.0)

    def test_draft_and_verify_spans_tile_decode_step(self, artifact,
                                                     predictor):
        from paddle_tpu.obs import tracing as obs_tracing
        if not obs_tracing.enabled():
            pytest.skip("tracing disabled")
        draft = GenerativePredictor(artifact)
        b = DecodeBatcher(predictor, n_slots=2, draft=draft, spec_k=2)
        try:
            b.submit([3, 5, 7], max_new_tokens=8).result(timeout=120)
        finally:
            b.close(drain=False, timeout=5.0)
        spans = obs_tracing.recent_spans(limit=4096, kind="serving")
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        assert by_name.get("serving/draft"), "no draft spans"
        assert by_name.get("serving/verify"), "no verify spans"
        steps = [s for s in by_name.get("serving/decode_step", [])]
        assert steps, "no decode_step spans"
        # the last round's draft + verify must tile its decode_step
        d, v, st = (by_name["serving/draft"][-1],
                    by_name["serving/verify"][-1], steps[-1])
        assert abs((d["dur_ms"] + v["dur_ms"]) - st["dur_ms"]) < 0.05, \
            (d["dur_ms"], v["dur_ms"], st["dur_ms"])
        assert d["attrs"]["spec_k"] == 2
        assert "accepted" in v["attrs"]

    def test_draft_death_degrades_with_event(self, artifact,
                                             predictor):
        from paddle_tpu.obs import events as obs_events
        metrics = ServingMetrics().model("lm")
        draft = GenerativePredictor(artifact)
        b = DecodeBatcher(predictor, n_slots=2, metrics=metrics,
                          draft=draft, spec_k=2)
        try:
            first = b.submit([3, 5, 7], max_new_tokens=6)
            first.result(timeout=120)
            set_draft_poison(0)
            out = b.submit([9, 4], max_new_tokens=10).result(
                timeout=120)[0].tolist()
            assert out == greedy_decode(predictor, [9, 4], 10)[0]
            snap = metrics.snapshot()
            assert snap["spec_degraded"] == 1
            ev = obs_events.recent_events(kind="spec_degraded")
            assert ev and "poison" in str(ev[-1].get("error"))
        finally:
            b.close(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# registry + wire + admission fit + compile cache
# ---------------------------------------------------------------------------

class TestSpecServing:
    def test_wire_roundtrip_spec_fields_and_acc_column(self, artifact,
                                                       capsys):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import serving_top
        pred = GenerativePredictor(artifact)
        server = InferenceServer().start()
        cli = ServingClient(server.endpoint)
        try:
            r = cli.load_model("lm", artifact, decode_slots=2,
                               draft=artifact, spec_k=2)
            assert r["spec_k"] == 2 and r["draft"] == artifact
            got = [t for ch in cli.infer_stream(
                "lm", [3, 5, 7], max_new_tokens=10,
                deadline_ms=60000.0) for t in ch]
            assert got == greedy_decode(pred, [3, 5, 7], 10)[0]
            stats = cli.stats()
            snap = stats["stats"]["models"]["lm"]
            assert snap["spec_accept_rate"] == 1.0
            assert snap["spec_rounds"] > 0
            desc = stats["models"]["lm"]
            assert desc["spec_k"] == 2 and desc["draft"] == artifact
            txt = cli.metrics_text()
            assert "paddle_tpu_serving_spec_rounds" in txt
            assert "paddle_tpu_serving_spec_accept_rate" in txt
            serving_top.main([server.endpoint])
            out = capsys.readouterr().out
            assert "ACC%" in out and "spec_k=2" in out
            assert "100.0" in out
        finally:
            cli.close()
            server.shutdown(drain=True)

    def test_fit_check_covers_target_plus_draft(self, artifact):
        from paddle_tpu.analysis import ResourceFitError
        from paddle_tpu.serving import ModelRegistry
        from paddle_tpu import compile_cache as cc
        # size the budget so the target's KV table fits alone but
        # target + draft together do not: KV bytes dominate at large
        # slot counts (2*L*slots*S*H*Dh*4 = 32 MiB per model here)
        slots = 2048
        old = fluid.get_flags(["serving_device_mem_mb"])
        fluid.set_flags({"serving_device_mem_mb": 40})
        try:
            reg = ModelRegistry()
            before = cc.stats()
            with pytest.raises(ResourceFitError) as ei:
                reg.load_model("lm", artifact, decode_slots=slots,
                               draft=artifact, spec_k=2)
            assert "draft" in str(ei.value)
            # rejected BEFORE any build/compile work
            assert reg.model_names() == []
            delta = cc.stats_delta(before)
            assert delta["misses"] == 0 and delta["hits"] == 0, delta
            # without the draft the same placement fits
            entry = reg.load_model("lm", artifact, decode_slots=slots,
                                   warm=False)
            assert entry.batcher.spec_k == 0
            reg.close_all(drain=False, timeout=5.0)
        finally:
            fluid.set_flags(old)

    def test_verify_executable_rides_compile_cache(self, artifact,
                                                   tmp_path):
        from paddle_tpu import compile_cache as cc
        from paddle_tpu.serving import ModelRegistry
        old = fluid.get_flags(["compile_cache", "compile_cache_dir"])
        fluid.set_flags({"compile_cache": True,
                         "compile_cache_dir": str(tmp_path / "cc")})
        cc.reset_stats()
        try:
            reg = ModelRegistry()
            reg.load_model("lm", artifact, decode_slots=2,
                           draft=artifact, spec_k=2)
            cold = cc.stats()
            # prefill buckets + step + VERIFY on the target, prefill
            # buckets + step on the draft
            assert cold["misses"] >= 3, cold
            reg.close_all(drain=False, timeout=5.0)
            before = cc.stats()
            reg2 = ModelRegistry()
            reg2.load_model("lm", artifact, decode_slots=2,
                            draft=artifact, spec_k=2)
            delta = cc.stats_delta(before)
            assert delta["misses"] == 0, delta
            assert delta["hits"] >= cold["misses"], delta
            out = reg2.submit("lm", {"tokens": [5, 9, 3]},
                              max_new_tokens=6).result(timeout=120)
            ref, _ = greedy_decode(GenerativePredictor(artifact),
                                   [5, 9, 3], 6)
            assert out[0].tolist() == ref
            reg2.close_all(drain=False, timeout=5.0)
        finally:
            fluid.set_flags(old)
            cc.reset_stats()


# ---------------------------------------------------------------------------
# tools: bench sweep subprocess (the ci_checks `specdec` gate) + chaos
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_spec_bench_smoke_subprocess():
    """Fresh-process proof of the whole speculative lane: the --spec_k
    sweep's k>0 point must beat the k=0 baseline tokens/sec per slot
    at equal step cost, accept ~1.0 with the twin draft, bit-exact
    replay at every point.  Slow-marked (subprocess + open-loop load,
    the test_quantize bench-smoke precedent): the ci_checks.sh
    `specdec` gate runs it as its own tier — tier-1 covers the same
    path in-process via TestSpecBatcher/TestSpecServing."""
    import json
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_serving.py"),
         "--decode", "--decode_mode", "cb", "--decode_slots", "2",
         "--spec_k", "0,2", "--step_cost_ms", "20", "--qps", "20",
         "--duration", "3"],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = [json.loads(l) for l in proc.stdout.splitlines()
            if l.startswith("{")]
    by_k = {r["spec_k"]: r for r in recs}
    assert set(by_k) == {0, 2}, sorted(by_k)
    for r in recs:
        assert r["bit_exact"] is True, r
        assert r["errors"] == 0, r
    assert by_k[2]["accept_rate"] == 1.0, by_k[2]
    assert by_k[2]["spec_degraded"] == 0
    assert by_k[2]["draft_cost_ms"] == pytest.approx(6.0)
    ratio = by_k[2]["tokens_per_sec_per_slot"] \
        / by_k[0]["tokens_per_sec_per_slot"]
    assert ratio > 1.1, \
        "spec_k=2 should beat the k=0 baseline (got %.2fx)" % ratio


@pytest.mark.slow
def test_chaos_spec_fallback_scenario():
    """The chaos scenario doubles as the draft-failure acceptance test
    (degrade within one step, zero dropped/corrupted streams); run it
    in-process — it asserts internally.  Slow-marked: the in-tier-1
    TestSpecBatcher.test_draft_death_degrades_with_event pins the same
    degrade contract in-process; `python tools/chaos.py --scenario
    spec-fallback` and this test cover the full wire shape."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import chaos
    res = chaos.scenario_spec_fallback(verbose=False)
    assert res["victim_tokens"] == 32
    assert res["accept_rate"] == 1.0
