"""Trainer child process for test_dist_collective.py (reference pattern:
test_dist_base.py:219,:299 — subprocess localhost cluster, per-step losses
compared against a local run).

Usage: python dist_collective_trainer.py <trainer_id> <num_trainers> <port>
Prints one line: ``LOSSES <json list>``.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_model():
    import paddle_tpu.fluid as fluid
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu")
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def global_batches(steps=5, global_bs=8):
    import numpy as np
    rng = np.random.RandomState(7)
    w = rng.randn(4, 1).astype(np.float32)
    out = []
    for _ in range(steps):
        xb = rng.randn(global_bs, 4).astype(np.float32)
        yb = (xb @ w + 0.1 * rng.randn(global_bs, 1)).astype(np.float32)
        out.append((xb, yb))
    return out


def run_local():
    """Single-process full-batch baseline (invoked by the parent test)."""
    import numpy as np
    import paddle_tpu.fluid as fluid
    main, startup, loss = build_model()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for xb, yb in global_batches():
        (lv,) = exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        losses.append(float(np.asarray(lv).flatten()[0]))
    return losses


def run_trainer(tid, n, port):
    import numpy as np
    import paddle_tpu.fluid as fluid
    os.environ["PADDLE_COORDINATOR"] = "127.0.0.1:%s" % port

    main, startup, loss = build_model()
    config = fluid.DistributeTranspilerConfig()
    config.mode = "collective"
    t = fluid.DistributeTranspiler(config=config)
    t.transpile(tid, program=main, trainers=n, startup_program=startup)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)       # gen_collective_id -> jax.distributed.initialize
    pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name,
                                main_program=main, num_trainers=n,
                                trainer_id=tid)
    losses = []
    for xb, yb in global_batches():
        lo = xb.shape[0] // n
        sl = slice(tid * lo, (tid + 1) * lo)   # this trainer's local shard
        (lv,) = pe.run(fetch_list=[loss.name],
                       feed={"x": xb[sl], "y": yb[sl]})
        losses.append(float(np.asarray(lv).flatten()[0]))
    print("LOSSES " + json.dumps(losses), flush=True)


def main():
    tid, n, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    run_trainer(tid, n, port)


if __name__ == "__main__":
    main()
