"""Persistent compile/artifact cache tests (paddle_tpu/compile_cache —
COMPILE_CACHE.md).

Pins the subsystem's contracts: content-addressed put/get with CRC
verification, silent rejection+recompile of corrupt entries, size-capped
LRU eviction, cross-process reuse (a second boot performs ZERO fresh
compilations for previously-seen (program, bucket, device-kind) triples
— the warm server boot / hot-swap flip acceptance), kill-mid-commit
crash safety (via tools/chaos.py's cache-commit scenario), the repo-wide
kernel-tuning registry with atomic record commits and the legacy JSON
fallback, cache observability through serving metrics / stats / the
load_model reply / serving_top, and the verify_compile_cache CLI.
Everything CPU-safe under JAX_PLATFORMS=cpu.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import compile_cache as cc
from paddle_tpu.ops import attention_tuning

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture
def store(tmp_path):
    """Point the compile cache at a fresh per-test store and reset the
    process counters; restore the previous flags afterwards."""
    old = fluid.get_flags(["compile_cache", "compile_cache_dir",
                           "compile_cache_max_mb"])
    root = str(tmp_path / "cc_store")
    fluid.set_flags({"compile_cache": True, "compile_cache_dir": root,
                     "compile_cache_max_mb": 1024})
    cc.reset_stats()
    yield root
    fluid.set_flags(old)
    cc.reset_stats()


def _export_fc(tmp_path, seed, name="m", buckets=None):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[6], dtype="float32")
        h = fluid.layers.fc(input=x, size=8, act="relu")
        pred = fluid.layers.fc(input=h, size=4, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / name)
        fluid.save_inference_model(md, ["x"], [pred], exe,
                                   main_program=main)
    return md


def _predictor(md, buckets=(2, 4)):
    from paddle_tpu.inference import AnalysisConfig, Predictor
    cfg = AnalysisConfig(model_dir=md)
    cfg.batch_size_buckets = tuple(buckets)
    return Predictor(cfg)


# ---------------------------------------------------------------------------
# store primitives: put/get, corruption rejection, eviction
# ---------------------------------------------------------------------------

def test_store_put_get_roundtrip(store):
    s = cc.CompileCache(root=store, xla_cache=False)
    fp = {"kind": "t", "program": "abc", "env": {"jax": "x"}}
    blob = b"executable-bytes" * 10
    assert s.get(fp) is None          # miss on empty store
    path = s.put(fp, blob)
    assert path and os.path.isdir(path)
    assert s.get(fp) == blob          # hit round-trips the bytes
    assert s.get({"kind": "other"}) is None  # different fingerprint
    st = cc.stats()
    assert st["hits"] == 1 and st["misses"] == 2 and st["puts"] == 1
    # committed entry passes verification
    assert [e for _, e, _ in s.verify()] == [None]


def test_fingerprint_key_canonical():
    a = cc.fingerprint_key({"b": 1, "a": [1, 2]})
    b = cc.fingerprint_key({"a": [1, 2], "b": 1})
    assert a == b and len(a) == 64
    assert cc.fingerprint_key({"a": [2, 1], "b": 1}) != a


def test_corrupted_entry_is_silent_miss_and_quarantined(store):
    s = cc.CompileCache(root=store, xla_cache=False)
    fp = {"kind": "t", "program": "corrupt-me"}
    path = s.put(fp, b"Z" * 256)
    # bit-flip the executable
    ep = os.path.join(path, cc.EXEC_NAME)
    raw = bytearray(open(ep, "rb").read())
    raw[len(raw) // 2] ^= 0x10
    open(ep, "wb").write(bytes(raw))
    assert s.get(fp) is None          # rejected, not raised
    assert not os.path.isdir(path)    # quarantined
    assert cc.stats()["errors"] == 1
    # truncation is rejected the same way
    path = s.put(fp, b"Z" * 256)
    with open(os.path.join(path, cc.EXEC_NAME), "wb") as f:
        f.write(b"Z" * 100)
    assert s.get(fp) is None
    # an unparsable manifest is rejected too
    path = s.put(fp, b"Z" * 256)
    with open(os.path.join(path, cc.MANIFEST_NAME), "w") as f:
        f.write("{not json")
    assert s.get(fp) is None
    assert s.entries() == []


def test_eviction_lru_cap(store):
    # cap at 1 MiB; three ~400 KiB entries -> the least-recently-USED
    # one is evicted, never the entry just written
    s = cc.CompileCache(root=store, max_mb=1, xla_cache=False)
    fps = [{"kind": "t", "i": i} for i in range(3)]
    s.put(fps[0], b"a" * 400_000)
    time.sleep(0.02)
    s.put(fps[1], b"b" * 400_000)
    time.sleep(0.02)
    assert s.get(fps[0]) is not None  # touch 0: now 1 is the LRU
    time.sleep(0.02)
    s.put(fps[2], b"c" * 400_000)     # over cap -> evict 1
    assert s.get(fps[1]) is None
    assert s.get(fps[0]) is not None
    assert s.get(fps[2]) is not None
    assert cc.stats()["evictions"] >= 1


# ---------------------------------------------------------------------------
# predictor wiring: cold miss -> warm hit, clone sharing, parity
# ---------------------------------------------------------------------------

def test_predictor_cold_miss_then_warm_hit_bit_exact(store, tmp_path):
    md = _export_fc(tmp_path, seed=5)
    x = np.random.RandomState(0).randn(2, 6).astype(np.float32)
    p1 = _predictor(md)
    out1, = p1.run({"x": x})
    st = cc.stats()
    assert st["misses"] == 1 and st["puts"] == 1 and st["hits"] == 0
    # a FRESH predictor over the same artifact deserializes the stored
    # executable: no retrace, no fresh compile, bit-identical replies
    p2 = _predictor(md)
    out2, = p2.run({"x": x})
    st = cc.stats()
    assert st["hits"] == 1 and st["misses"] == 1
    assert np.array_equal(out1, out2)
    # the cached-executable path is bit-exact vs the legacy direct path
    fluid.set_flags({"compile_cache": False})
    try:
        p3 = _predictor(md)
        out3, = p3.run({"x": x})
    finally:
        fluid.set_flags({"compile_cache": True})
    assert np.array_equal(out1, out3)


def test_clone_to_shares_one_executable(store, tmp_path):
    md = _export_fc(tmp_path, seed=6)
    x = np.zeros((2, 6), np.float32)
    p = _predictor(md)
    out, = p.run({"x": x})
    before = cc.stats()
    # replicas of the same device kind ride the SHARED deserialized
    # executable: zero additional store traffic, zero retraces
    clones = [p.clone_to(None) for _ in range(3)]
    for q in clones:
        oq, = q.run({"x": x})
        assert np.array_equal(out, oq)
        assert q._shared_exports is p._shared_exports
    d = cc.stats_delta(before)
    assert d["hits"] == 0 and d["misses"] == 0 and d["compile_ms"] == 0


def test_registry_hot_swap_flip_zero_fresh_compiles(store, tmp_path):
    from paddle_tpu.serving import ModelRegistry
    md = _export_fc(tmp_path, seed=7)
    reg = ModelRegistry()
    try:
        e1 = reg.load_model("m", md, buckets=(2, 4))
        assert e1.compile_cache["misses"] == 2   # cold: one per bucket
        assert e1.compile_cache["hits"] == 0
        # the hot-swap flip of the same artifact: every (bucket,
        # device-kind) executable comes from the store — ZERO fresh
        # compilations (the autoscaling acceptance pin)
        e2 = reg.load_model("m", md, buckets=(2, 4))
        assert e2.version == e1.version + 1
        assert e2.compile_cache["misses"] == 0
        assert e2.compile_cache["hits"] == 2
        assert e2.compile_cache["compile_ms"] == 0
        # per-model metrics accumulated both loads
        snap = reg.metrics.model("m").snapshot()["compile_cache"]
        assert snap["hits"] == 2 and snap["misses"] == 2
    finally:
        reg.close_all(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# observability: load_model reply, stats RPC, serving_top column
# ---------------------------------------------------------------------------

def test_server_surfaces_compile_cache_counters(store, tmp_path, capsys):
    from paddle_tpu.serving import InferenceServer, ServingClient
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import serving_top
    md = _export_fc(tmp_path, seed=8)
    server = InferenceServer(buckets=(2, 4)).start()
    try:
        cli = ServingClient(server.endpoint)
        reply = cli.load_model("m", md, buckets=[2, 4])
        assert reply["compile_cache"]["misses"] == 2
        reply2 = cli.load_model("m", md, buckets=[2, 4])
        assert reply2["compile_cache"]["misses"] == 0
        assert reply2["compile_cache"]["hits"] == 2
        stats = cli.stats()
        m = stats["stats"]["models"]["m"]
        assert m["compile_cache"] == {"hits": 2, "misses": 2,
                                      "compile_ms":
                                      m["compile_cache"]["compile_ms"]}
        assert stats["stats"]["compile_cache"]["puts"] >= 2
        serving_top.main([server.endpoint])
        out = capsys.readouterr().out
        assert "CCH/M" in out and "2/2" in out
        cli.close()
    finally:
        server.shutdown(drain=False, timeout=5.0)


# ---------------------------------------------------------------------------
# cross-process reuse: a second boot performs no compilation at all
# ---------------------------------------------------------------------------

_CHILD = r"""
import os, sys, json, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
store, md, out_npz, poison = sys.argv[1], sys.argv[2], sys.argv[3], \
    sys.argv[4] == "poison"
os.environ["PADDLE_TPU_FLAGS_compile_cache_dir"] = store
from paddle_tpu import compile_cache as cc
from paddle_tpu.fluid import functionalizer
if poison:
    # a warm boot must not rebuild/trace the step function AT ALL —
    # only fingerprinting, deserialization, and XLA may run
    def _no_trace(*a, **k):
        raise AssertionError("warm boot must not trace the program")
    functionalizer.build_step_fn = _no_trace
from paddle_tpu.inference import AnalysisConfig, Predictor
cfg = AnalysisConfig(model_dir=md)
cfg.batch_size_buckets = (2, 4)
t0 = time.monotonic()
p = Predictor(cfg)
rng = np.random.RandomState(3)
outs = [p.run({"x": rng.randn(b, 6).astype(np.float32)})[0]
        for b in (2, 4)]
elapsed_ms = (time.monotonic() - t0) * 1000.0
np.savez(out_npz, o0=outs[0], o1=outs[1])
print("RESULT " + json.dumps({"stats": cc.stats(),
                              "elapsed_ms": elapsed_ms}))
"""


def _run_child(store, md, out_npz, poison):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PADDLE_TPU_FLAGS_compile_cache_dir", None)
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD, store, md, out_npz,
         "poison" if poison else "no"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-3000:]
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("RESULT ")][0]
    return json.loads(line[len("RESULT "):])


def test_cross_process_reuse_skips_compilation(store, tmp_path):
    """The tentpole acceptance: a SECOND process booting the same model
    over the same store performs zero fresh compilations (hit counters)
    and never traces (build_step_fn poisoned), with bit-identical
    replies and a warm boot at least as fast as the cold one."""
    md = _export_fc(tmp_path, seed=9)
    cold = _run_child(store, md, str(tmp_path / "cold.npz"),
                      poison=False)
    assert cold["stats"]["misses"] == 2
    assert cold["stats"]["puts"] == 2
    warm = _run_child(store, md, str(tmp_path / "warm.npz"),
                      poison=True)
    assert warm["stats"]["hits"] == 2
    assert warm["stats"]["misses"] == 0
    assert warm["stats"]["compile_ms"] == 0
    # wall-clock sanity: skipping trace+lower+compile cannot be slower
    assert warm["elapsed_ms"] < cold["elapsed_ms"], \
        "warm boot %.1fms not faster than cold %.1fms" \
        % (warm["elapsed_ms"], cold["elapsed_ms"])
    a = np.load(str(tmp_path / "cold.npz"))
    b = np.load(str(tmp_path / "warm.npz"))
    assert np.array_equal(a["o0"], b["o0"])
    assert np.array_equal(a["o1"], b["o1"])


# ---------------------------------------------------------------------------
# crash safety: SIGKILL mid-commit never corrupts the store
# ---------------------------------------------------------------------------

def test_kill_mid_cache_commit_recovers(tmp_path):
    """tools/chaos.py cache-commit scenario (deterministic exit at the
    cc_exec_written point): the interrupted commit leaves only a stale
    tmp next to the intact first entry; the next boot serves the same
    bits, recompiles ONLY the interrupted entry, sweeps the tmp."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import chaos
    st = chaos.scenario_cache_commit(str(tmp_path), real_kill=False,
                                     verbose=False)
    assert st["hits"] == 1 and st["misses"] == 1


# ---------------------------------------------------------------------------
# kernel-tuning registry
# ---------------------------------------------------------------------------

def test_tuning_registry_roundtrip_and_store_layout(store):
    path = cc.tuning_record("flash_attention", "S128_D64_c1_bfloat16",
                            {"block_q": 64, "block_kv": 64})
    assert path.startswith(store)
    assert cc.tuning_lookup("flash_attention",
                            "S128_D64_c1_bfloat16")["block_q"] == 64
    assert cc.tuning_lookup("flash_attention", "nope") is None
    # a second record merges (read-modify-write), does not clobber
    cc.tuning_record("flash_attention", "S256_D64_c0_float32",
                     {"block_q": 128, "block_kv": 128})
    assert len(cc.tuning_entries("flash_attention")) == 2
    with pytest.raises(ValueError):
        cc.tuning_path("../escape")


def test_attention_tuning_rides_registry(store):
    """With no legacy override, attention_tuning records into and reads
    from the repo-wide registry namespace."""
    old = fluid.get_flags(["attention_tune_cache"])
    fluid.set_flags({"attention_tune_cache": ""})
    try:
        cfg = attention_tuning.AttentionConfig(32, 64, 16, 32)
        path = attention_tuning.record(512, 64, True, "bfloat16", cfg)
        assert path == cc.tuning_path(attention_tuning.TUNING_NAMESPACE)
        assert attention_tuning.lookup(512, 64, True, "bfloat16") == cfg
        assert attention_tuning.lookup(512, 64, False, "bfloat16") is None
    finally:
        fluid.set_flags(old)


def test_attention_tuning_legacy_json_read_only_fallback(
        store, tmp_path, monkeypatch):
    """A pre-registry tune JSON at the legacy default path still
    resolves (read-only) when the registry has no entry; a registry
    entry for the same key wins."""
    old = fluid.get_flags(["attention_tune_cache"])
    fluid.set_flags({"attention_tune_cache": ""})
    legacy = str(tmp_path / "legacy_tune.json")
    with open(legacy, "w") as f:
        json.dump({"S1024_D64_c1_bfloat16":
                   {"block_q": 8, "block_kv": 8}}, f)
    monkeypatch.setattr(attention_tuning, "cache_path", lambda: legacy)
    try:
        got = attention_tuning.lookup(1024, 64, True, "bfloat16")
        assert got == attention_tuning.AttentionConfig(8, 8)
        # registry beats legacy for the same key
        attention_tuning.record(
            1024, 64, True, "bfloat16",
            attention_tuning.AttentionConfig(16, 16))
        got = attention_tuning.lookup(1024, 64, True, "bfloat16")
        assert got == attention_tuning.AttentionConfig(16, 16)
        # the legacy file was never rewritten
        with open(legacy) as f:
            assert json.load(f)["S1024_D64_c1_bfloat16"]["block_q"] == 8
    finally:
        fluid.set_flags(old)


def test_tuning_record_atomic_under_kill(store, tmp_path):
    """A tuner killed between the durable temp write and the rename
    (chaos point tuning_tmp_written) leaves the PREVIOUS registry
    intact — never a truncated JSON that poisons later traces.  Covers
    both the registry path and the legacy FLAGS-pinned path."""
    from paddle_tpu.fluid import checkpoint as ckpt

    class Boom(RuntimeError):
        pass

    def bomb(point):
        if point == "tuning_tmp_written":
            raise Boom(point)

    # registry path
    cc.tuning_record("flash_attention", "k1", {"block_q": 64,
                                               "block_kv": 64})
    ckpt.set_chaos_hook(bomb)
    try:
        with pytest.raises(Boom):
            cc.tuning_record("flash_attention", "k2", {"block_q": 128,
                                                       "block_kv": 128})
    finally:
        ckpt.set_chaos_hook(None)
    ents = cc.tuning_entries("flash_attention")
    assert ents.get("k1", {}).get("block_q") == 64 and "k2" not in ents

    # legacy path (FLAGS.attention_tune_cache override)
    legacy = str(tmp_path / "tune.json")
    old = fluid.get_flags(["attention_tune_cache"])
    fluid.set_flags({"attention_tune_cache": legacy})
    try:
        cfg = attention_tuning.AttentionConfig(32, 32)
        attention_tuning.record(64, 64, False, "float32", cfg)
        ckpt.set_chaos_hook(bomb)
        try:
            with pytest.raises(Boom):
                attention_tuning.record(
                    128, 64, False, "float32",
                    attention_tuning.AttentionConfig(64, 64))
        finally:
            ckpt.set_chaos_hook(None)
        with open(legacy) as f:
            data = json.load(f)
        assert "S64_D64_c0_float32" in data      # old record intact
        assert "S128_D64_c0_float32" not in data  # aborted one absent
        assert attention_tuning.lookup(64, 64, False, "float32") == cfg
    finally:
        fluid.set_flags(old)


# ---------------------------------------------------------------------------
# verify_compile_cache CLI
# ---------------------------------------------------------------------------

def test_verify_compile_cache_cli(store, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import verify_compile_cache
    s = cc.CompileCache(root=store, xla_cache=False)
    fp = {"kind": "t", "program": "cli"}
    path = s.put(fp, b"E" * 512)
    cc.tuning_record("flash_attention", "k", {"block_q": 8,
                                              "block_kv": 8})
    assert verify_compile_cache.main([store]) == 0
    out = capsys.readouterr().out
    assert "OK" in out and "tuning/flash_attention.json" in out
    # corrupt the entry: exit 2, message NAMES it
    ep = os.path.join(path, cc.EXEC_NAME)
    raw = bytearray(open(ep, "rb").read())
    raw[0] ^= 0xFF
    open(ep, "wb").write(bytes(raw))
    assert verify_compile_cache.main([store]) == 2
    err = capsys.readouterr().err
    assert os.path.basename(path) in err and "CRC32" in err
    # empty root: exit 1
    assert verify_compile_cache.main([store + "_nope"]) == 1


# ---------------------------------------------------------------------------
# executor inference-side compile cache (opt-in flag)
# ---------------------------------------------------------------------------

def test_executor_compile_cache_inference_program(store):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 4
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[6], dtype="float32")
        pred = fluid.layers.fc(input=x, size=3, act="softmax")
    xv = np.random.RandomState(2).randn(2, 6).astype(np.float32)
    # baseline: flag off
    with fluid.scope_guard(fluid.Scope()):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        ref, = exe.run(main, feed={"x": xv}, fetch_list=[pred])
    fluid.set_flags({"executor_compile_cache": True})
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe1 = fluid.Executor(fluid.CPUPlace())
            exe1.run(startup)
            before = cc.stats()
            out1, = exe1.run(main, feed={"x": xv}, fetch_list=[pred])
            d1 = cc.stats_delta(before)
            assert d1["misses"] >= 1 and d1["puts"] >= 1
            # a FRESH executor on the same program rides the store
            exe2 = fluid.Executor(fluid.CPUPlace())
            before = cc.stats()
            out2, = exe2.run(main, feed={"x": xv}, fetch_list=[pred])
            d2 = cc.stats_delta(before)
            assert d2["hits"] >= 1 and d2["misses"] == 0
        assert np.array_equal(ref, out1) and np.array_equal(out1, out2)
    finally:
        fluid.set_flags({"executor_compile_cache": False})


def test_executor_compile_cache_skips_training_programs(store):
    """A program with grad/optimizer ops must NOT ride the export path
    (donation, in-place update semantics) — the gate filters it out and
    the store stays untouched."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 4
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        p = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(p, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    xv = np.ones((2, 4), np.float32)
    yv = np.ones((2, 1), np.float32)
    fluid.set_flags({"executor_compile_cache": True})
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            before = cc.stats()
            l1, = exe.run(main, feed={"x": xv, "y": yv},
                          fetch_list=[loss])
            l2, = exe.run(main, feed={"x": xv, "y": yv},
                          fetch_list=[loss])
            d = cc.stats_delta(before)
            assert not exe._aot_cache_eligible(main)
            # the training program never touched the store (the startup
            # program legitimately may)
            assert float(l2) < float(l1)
    finally:
        fluid.set_flags({"executor_compile_cache": False})
