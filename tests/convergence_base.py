"""Convergence-parity harness (reference
parallel_executor_test_base.py:31 TestParallelExecutorBase.
check_network_convergence + test_dist_base.py loss-delta checks).

The north-star convergence requirement: the SAME model trained under
different execution strategies — single-device Executor, multi-device
ParallelExecutor, parameter-server distribution — must follow the SAME
per-step loss trajectory (identical seeds/feeds), not merely "loss goes
down"."""

import threading

import numpy as np

import paddle_tpu.fluid as fluid


def run_executor(build_fn, feeds, loss_getter, steps):
    """Single-device baseline trajectory."""
    with fluid.unique_name.guard():
        main, startup, loss = build_fn()
    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for i in range(steps):
            (lv,) = exe.run(main, feed=feeds[i], fetch_list=[loss])
            losses.append(float(np.asarray(lv).flatten()[0]))
    return losses


def run_parallel_executor(build_fn, feeds, loss_getter, steps):
    """ParallelExecutor over every virtual device (conftest forces 8 CPU
    devices); full global batch fed, split across devices."""
    with fluid.unique_name.guard():
        main, startup, loss = build_fn()
    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name,
                                    main_program=main)
        assert pe.device_count > 1, "need a multi-device mesh"
        for i in range(steps):
            (lv,) = pe.run(fetch_list=[loss.name], feed=feeds[i])
            losses.append(float(np.asarray(lv).flatten()[0]))
    return losses


def run_pserver_dist(build_fn, feeds, loss_getter, steps, endpoint,
                     n_trainers=2):
    """Sync parameter-server cluster, in-process (1 pserver, n trainers
    splitting each global batch). Returns the mean per-step trainer loss."""
    from paddle_tpu.fluid.transpiler import DistributeTranspiler
    from paddle_tpu.distributed.rpc import wait_server_ready, global_client

    with fluid.unique_name.guard():
        main, startup, loss = build_fn()
    t = DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, pservers=endpoint,
                trainers=n_trainers, startup_program=startup)
    ps_prog = t.get_pserver_program(endpoint)
    ps_startup = t.get_startup_program(endpoint, ps_prog,
                                       startup_program=startup)
    trainer_prog = t.get_trainer_program()

    server_exc = []

    def run_pserver():
        try:
            exe = fluid.Executor(fluid.CPUPlace())
            with fluid.scope_guard(fluid.Scope()):
                exe.run(ps_startup)
                exe.run(ps_prog)
        except Exception as e:      # pragma: no cover
            server_exc.append(e)

    th = threading.Thread(target=run_pserver, daemon=True)
    th.start()
    wait_server_ready([endpoint])

    results = [[] for _ in range(n_trainers)]

    def run_trainer(tid):
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            for i in range(steps):
                feed = {}
                for name, arr in feeds[i].items():
                    n = arr.shape[0] // n_trainers
                    feed[name] = arr[tid * n:(tid + 1) * n]
                (lv,) = exe.run(trainer_prog, feed=feed, fetch_list=[loss])
                results[tid].append(float(np.asarray(lv).flatten()[0]))

    threads = [threading.Thread(target=run_trainer, args=(tid,),
                                daemon=True) for tid in range(1, n_trainers)]
    for th2 in threads:
        th2.start()
    run_trainer(0)
    for th2 in threads:
        th2.join(timeout=120)
    global_client().send_exit(endpoint)
    th.join(timeout=10)
    assert not server_exc, server_exc
    return [float(np.mean([results[t][i] for t in range(n_trainers)]))
            for i in range(steps)]


def check_network_convergence(build_fn, feeds, steps=4, delta=1e-5,
                              pserver_endpoint=None, ps_delta=1e-3):
    """Compare per-step loss trajectories across strategies.

    build_fn() -> (main, startup, loss); must build deterministically
    (seeded initializers) so every strategy starts from identical params.
    feeds: list of per-step full-batch feed dicts.
    """
    local = run_executor(build_fn, feeds, None, steps)
    pe = run_parallel_executor(build_fn, feeds, None, steps)
    np.testing.assert_allclose(local, pe, atol=delta, err_msg=
                               "Executor vs ParallelExecutor diverged")
    if pserver_endpoint is not None:
        # step 0's loss is computed from identical init params in both
        # runs; later steps see PS-updated params
        ps = run_pserver_dist(build_fn, feeds, None, steps,
                              pserver_endpoint)
        np.testing.assert_allclose(local, ps, atol=ps_delta, err_msg=
                                   "Executor vs pserver run diverged")
    return local
