"""Program/Block/Operator IR tests (reference unittests/test_program.py,
test_operator_desc.py, test_variable.py)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program


def test_program_blocks():
    p = Program()
    assert p.num_blocks == 1
    with p._block_guard():
        assert p.current_block().idx == 1
        assert p.current_block().parent_idx == 0
    assert p.current_block().idx == 0


def test_variable_shape_dtype():
    p = Program()
    with fluid.program_guard(p):
        x = fluid.layers.data("x", shape=[3, 4], dtype="float32")
        assert x.shape == (-1, 3, 4)
        assert x.np_dtype == np.float32


def test_infer_shape_through_layers():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[8], dtype="float32")
        h = fluid.layers.fc(input=x, size=16, act="relu")
        assert h.shape == (-1, 16)
        img = fluid.layers.data("img", shape=[3, 32, 32], dtype="float32")
        c = fluid.layers.conv2d(img, num_filters=6, filter_size=5)
        assert c.shape == (-1, 6, 28, 28)
        pl = fluid.layers.pool2d(c, pool_size=2, pool_stride=2)
        assert pl.shape == (-1, 6, 14, 14)


def test_program_serialize_roundtrip():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(input=x, size=2)
        loss = fluid.layers.mean(y)
    s = main.serialize_to_string()
    p2 = Program.parse_from_string(s)
    assert len(p2.global_block().ops) == len(main.global_block().ops)
    assert sorted(p2.global_block().vars) == sorted(main.global_block().vars)
    # parameters keep their class
    assert len(p2.global_block().all_parameters()) == \
        len(main.global_block().all_parameters())


def test_clone_for_test_sets_is_test():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        d = fluid.layers.dropout(x, dropout_prob=0.5)
    t = main.clone(for_test=True)
    dropout_ops = [op for op in t.global_block().ops
                   if op.type == "dropout"]
    assert dropout_ops and all(op.attrs["is_test"] for op in dropout_ops)


def test_prune():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        h = fluid.layers.fc(input=x, size=8)
        y = fluid.layers.fc(input=h, size=2)
        z = fluid.layers.fc(input=h, size=3)  # dead branch for y
    pruned = main._prune(["x"], [y.name])
    types = [op.type for op in pruned.global_block().ops]
    # z's second mul should be gone
    assert len([t for t in types if t == "mul"]) == 2


def test_operator_accessors():
    main = Program()
    with fluid.program_guard(main):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.relu(x)
        op = main.global_block().ops[-1]
        assert op.type == "relu"
        assert op.input("X") == [x.name]
        assert op.output("Out") == [y.name]
