"""C inference API (native/pd_capi.h, native/capi.cc): a PURE C client
(native/capi_demo.c — no Python of its own) serves a save_aot artifact
and must produce the same numbers as AotPredictor.run in-process.
Reference analogue: paddle_api.h:134 PaddlePredictor::Run and the legacy
capi examples (paddle/legacy/capi/examples/model_inference)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
NATIVE = os.path.join(REPO, "native")


@pytest.fixture(scope="module")
def capi_demo_bin():
    import shutil
    # skip only when the toolchain genuinely isn't there; a compile
    # error with the toolchain present must FAIL, not skip
    for tool in ("make", "g++", "gcc", "python3-config"):
        if shutil.which(tool) is None:
            pytest.skip("native toolchain unavailable: no %s" % tool)
    proc = subprocess.run(
        ["make", "libpaddle_tpu_capi.so", "capi_demo"], cwd=NATIVE,
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, "capi build failed:\n" + proc.stderr[-2000:]
    return os.path.join(NATIVE, "capi_demo")


@pytest.fixture(scope="module")
def aot_model(tmp_path_factory):
    """Train a tiny conv net a few steps, save_inference_model, AOT-export
    for batch 4, and return (aot_dir, reference outputs for the demo's
    deterministic input)."""
    from paddle_tpu.inference import (NativeConfig, create_paddle_predictor,
                                      load_aot_predictor)
    tmp = tmp_path_factory.mktemp("capi")
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[1, 8, 8],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        conv = fluid.layers.conv2d(input=img, num_filters=4, filter_size=3,
                                   padding=1, act="relu")
        pool = fluid.layers.pool2d(input=conv, pool_size=2, pool_stride=2)
        pred = fluid.layers.fc(input=pool, size=10, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _ in range(2):
            exe.run(main, feed={
                "img": rng.randn(4, 1, 8, 8).astype(np.float32),
                "label": rng.randint(0, 10, (4, 1)).astype(np.int64)},
                fetch_list=[loss])
        model_dir = str(tmp / "model")
        fluid.save_inference_model(model_dir, ["img"], [pred], exe,
                                   main_program=main)
    aot_dir = str(tmp / "aot")
    p = create_paddle_predictor(NativeConfig(model_dir=model_dir))
    p.save_aot(aot_dir, batch_sizes=(4,))

    # the demo's deterministic input: ((i*37 % 65) - 32) / 32
    n = 4 * 1 * 8 * 8
    x = ((np.arange(n) * 37 % 65) - 32.0).astype(np.float32) / 32.0
    x = x.reshape(4, 1, 8, 8)
    (ref,) = load_aot_predictor(aot_dir).run({"img": x})
    return aot_dir, np.asarray(ref)


def test_c_client_matches_python_predictor(capi_demo_bin, aot_model):
    aot_dir, ref = aot_model
    env = dict(os.environ)
    env["PD_CAPI_PLATFORM"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [capi_demo_bin, aot_dir, "4", "1", "8", "8"],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, (proc.stdout[-500:], proc.stderr[-2000:])
    assert "CAPI-DEMO-OK" in proc.stdout
    assert "second run ok" in proc.stdout

    lines = proc.stdout.strip().splitlines()
    assert lines[0] == "n_out 1", lines[0]
    hdr = lines[1].split()
    # "out <name> ndim 2 dims 4 10"
    assert hdr[0] == "out" and hdr[2] == "ndim"
    dims = [int(d) for d in hdr[hdr.index("dims") + 1:]]
    assert tuple(dims) == ref.shape, (dims, ref.shape)
    vals = np.array([float(v) for v in lines[2].split()],
                    np.float32).reshape(ref.shape)
    np.testing.assert_allclose(vals, ref, rtol=1e-4, atol=2e-6)


def test_c_client_reports_clean_error_for_bad_dir(capi_demo_bin, tmp_path):
    env = dict(os.environ)
    env["PD_CAPI_PLATFORM"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [capi_demo_bin, str(tmp_path / "nope"), "4", "1", "8", "8"],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == 1
    assert "create failed:" in proc.stderr
