"""Host-op program segmentation (SURVEY §7 step 3; VERDICT r2 task #4).

A training program containing host IO ops (save) must still run its
compute from the XLA jit cache: the Executor partitions the block at
HOST_OPS boundaries, jits each compute segment, and runs host ops eagerly
between — with a loss trajectory identical to the same program without
host ops. Reference analogue: save_op.cc/load_op.cc kernels executed on
CPU inside Executor::Run's op loop."""

import os
import tempfile

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program


def _build(with_save=False, save_path=None):
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(x, size=16, act="relu")
        logits = fluid.layers.fc(h, size=3)
        prob = fluid.layers.softmax(logits)
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=prob, label=label))
        fluid.layers.Print(loss, message="step loss")
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        if with_save:
            gb = main.global_block()
            gb.append_op(type="save", inputs={"X": [loss.name]},
                         outputs={},
                         attrs={"file_path": save_path},
                         infer_shape=False)
    return main, startup, loss


def _feeds(steps):
    rng = np.random.RandomState(3)
    return [{"x": rng.randn(8, 4).astype(np.float32),
             "label": rng.randint(0, 3, (8, 1)).astype(np.int64)}
            for _ in range(steps)]


def _run(with_save, save_path=None, steps=4):
    with fluid.unique_name.guard():
        main, startup, loss = _build(with_save, save_path)
    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for f in _feeds(steps):
            (lv,) = exe.run(main, feed=f, fetch_list=[loss])
            losses.append(float(np.asarray(lv).flatten()[0]))
    return losses, exe, main


def test_segmented_save_program_matches_pure_jit():
    path = os.path.join(tempfile.mkdtemp(), "loss.ckpt")
    base, _, _ = _run(with_save=False)
    seg, exe, main = _run(with_save=True, save_path=path)
    np.testing.assert_allclose(base, seg, atol=1e-6,
                               err_msg="segmented host program diverged")
    # the save op actually wrote the fetched loss each step
    with open(path, "rb") as f:
        assert abs(float(np.load(f)) - seg[-1]) < 1e-6

    runner = exe.segmented_runner(main)
    assert runner is not None, "host program should use segmented runner"
    assert runner.num_compute_segments >= 1
    # 4 steps: first step compiles (miss per segment), steps 2-4 hit
    assert runner.cache_misses == runner.num_compute_segments
    assert runner.cache_hits >= 3 * runner.num_compute_segments


def test_save_mid_block_splits_segments():
    """A host op in the MIDDLE of the block produces >=2 compute segments
    and still trains identically (grad ops recompute their forward via
    the vjp fallback across the boundary)."""
    path = os.path.join(tempfile.mkdtemp(), "mid.ckpt")
    with fluid.unique_name.guard():
        main, startup = Program(), Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4], dtype="float32")
            label = fluid.layers.data("label", shape=[1], dtype="int64")
            h = fluid.layers.fc(x, size=16, act="relu")
            logits = fluid.layers.fc(h, size=3)
            prob = fluid.layers.softmax(logits)
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(input=prob, label=label))
            # save the FORWARD activation: sits between fwd and bwd ops
            gb = main.global_block()
            gb.append_op(type="save", inputs={"X": [h.name]}, outputs={},
                         attrs={"file_path": path}, infer_shape=False)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for f in _feeds(3):
            (lv,) = exe.run(main, feed=f, fetch_list=[loss])
            losses.append(float(np.asarray(lv).flatten()[0]))
    runner = exe.segmented_runner(main)
    assert runner.num_compute_segments >= 2
    assert os.path.exists(path)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_load_op_roundtrip():
    """save -> load round-trip through in-graph ops (reference
    save_op.cc / load_op.cc)."""
    path = os.path.join(tempfile.mkdtemp(), "t.ckpt")
    val = np.arange(12, dtype=np.float32).reshape(3, 4)

    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        gb = main.global_block()
        gb.append_op(type="save", inputs={"X": [x.name]}, outputs={},
                     attrs={"file_path": path}, infer_shape=False)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(main, feed={"x": val}, fetch_list=[])

    main2, startup2 = Program(), Program()
    with fluid.program_guard(main2, startup2):
        gb = main2.global_block()
        out = gb.create_var(name="loaded", dtype="float32", shape=[3, 4])
        gb.append_op(type="load", inputs={}, outputs={"Out": [out.name]},
                     attrs={"file_path": path}, infer_shape=False)
    with fluid.scope_guard(fluid.Scope()):
        (got,) = exe.run(main2, fetch_list=["loaded"])
    np.testing.assert_array_equal(np.asarray(got), val)


def test_subblock_host_op_falls_back_to_eager():
    """A host op inside a while BODY cannot be partitioned out at block-0
    boundaries — the Executor must fall back to fully-eager interpretation
    (host op sees concrete values) instead of tracing it under jit."""
    path = os.path.join(tempfile.mkdtemp(), "inner.ckpt")
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        from paddle_tpu.fluid.layers import control_flow as cf
        from paddle_tpu.fluid.layers import tensor as tl
        i = tl.fill_constant(shape=[1], dtype="int64", value=0)
        n = tl.fill_constant(shape=[1], dtype="int64", value=3)
        acc = tl.fill_constant(shape=[1], dtype="float32", value=0.0)
        cond = cf.less_than(i, n)
        w = cf.While(cond, is_test=True)
        with w.block():
            acc2 = fluid.layers.elementwise_add(
                acc, tl.fill_constant([1], "float32", 1.0))
            fluid.layers.assign(acc2, acc)
            gb = main.current_block()
            gb.append_op(type="save", inputs={"X": [acc.name]}, outputs={},
                         attrs={"file_path": path}, infer_shape=False)
            cf.increment(i)
            cf.less_than(i, n, cond=cond)
        # ALSO a block-0 host op: a sub-block host op must force the
        # eager path even when block 0 has its own (segmentable) host op
        outer = os.path.join(os.path.dirname(path), "outer.ckpt")
        main.global_block().append_op(
            type="save", inputs={"X": [acc.name]}, outputs={},
            attrs={"file_path": outer}, infer_shape=False)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (out,) = exe.run(main, fetch_list=[acc])
    assert float(np.asarray(out).flatten()[0]) == 3.0
    assert os.path.exists(path)
    assert os.path.exists(outer)
    # no segmented runner: the program went down the eager path
    assert exe.segmented_runner(main) is None


def test_segmented_conditional_block_env_flow():
    """A ConditionalBlock declares only Cond in op.inputs — its real data
    flow is env-introspected at trace time. The segmented runner must
    still feed the sub-block's reads into the jitted segment and export
    its writes (regression: block-0 save + ConditionalBlock reading a
    value produced before the save)."""
    path = os.path.join(tempfile.mkdtemp(), "cb.ckpt")
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        from paddle_tpu.fluid.layers import control_flow as cf
        from paddle_tpu.fluid.layers import tensor as tl
        x = fluid.layers.data("x", shape=[2], dtype="float32")
        y = fluid.layers.scale(x, scale=2.0)        # produced pre-save
        gb = main.global_block()
        gb.append_op(type="save", inputs={"X": [y.name]}, outputs={},
                     attrs={"file_path": path}, infer_shape=False)
        # post-save segment: conditional block reads y, writes out
        out = tl.fill_constant(shape=[1, 2], dtype="float32", value=0.0)
        flag = tl.fill_constant(shape=[1], dtype="bool", value=True)
        cb = cf.ConditionalBlock([flag], is_scalar_condition=True)
        with cb.block():
            doubled = fluid.layers.scale(y, scale=3.0)
            fluid.layers.assign(doubled, out)
    exe = fluid.Executor(fluid.CPUPlace())
    val = np.array([[1.0, 2.0]], np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (got,) = exe.run(main, feed={"x": val}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(got), val * 6.0, atol=1e-6)
    assert exe.segmented_runner(main) is not None
    assert os.path.exists(path)


def test_dynamic_sequence_mask_auto_segments():
    """sequence_mask with maxlen=None has a data-dependent output shape
    (reference sequence_mask_op.cc computes max(x) at kernel time). The
    attr-conditional host routing (_HOST_IF) must divert it to the
    segmented path automatically — surrounded by jit-clean compute —
    instead of raising under trace (VERDICT r3 weak #6)."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        lens = fluid.layers.data("lens", shape=[1], dtype="int64")
        doubled = fluid.layers.scale(lens, scale=2.0)  # pre-mask segment
        mask = fluid.layers.sequence_mask(doubled, maxlen=None,
                                          dtype="float32")
        total = fluid.layers.reduce_sum(mask)          # post-mask segment
    exe = fluid.Executor(fluid.CPUPlace())
    feed = {"lens": np.array([[1], [3], [2]], np.int64)}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        got_mask, got_total = exe.run(main, feed=feed,
                                      fetch_list=[mask, total])
    # doubled lengths 2,6,4 -> width 6, row sums = the lengths
    m = np.asarray(got_mask)
    assert m.shape[-1] == 6, m.shape
    np.testing.assert_allclose(m.reshape(3, -1, 6).sum(axis=(1, 2)),
                               [2.0, 6.0, 4.0])
    assert float(np.asarray(got_total)) == 12.0
    assert exe.segmented_runner(main) is not None
