"""Frozen v2 package surface: every name in the reference's v2 module
__all__ lists resolves here (the v2 analogue of the fluid API.spec
freeze). Reference: python/paddle/v2/*.py."""

import ast
import os

import pytest

REF = "/root/reference/python/paddle/v2"


def _ref_all(path):
    try:
        tree = ast.parse(open(path).read())
    except SyntaxError:  # py2-only module (e.g. op.py's print statements)
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", "") == "__all__":
                    try:
                        return [ast.literal_eval(e)
                                for e in node.value.elts]
                    except Exception:
                        return None
    return None


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_v2_module_all_names_resolve():
    import warnings
    warnings.filterwarnings("ignore")
    import paddle_tpu.v2 as p

    gaps = {}
    checked = 0
    for f in sorted(os.listdir(REF)):
        if not f.endswith(".py") or f.startswith("test") \
                or f == "__init__.py":
            continue
        names = _ref_all(os.path.join(REF, f))
        if not names:
            continue
        mod = getattr(p, f[:-3], None)
        if mod is None:
            gaps[f[:-3]] = ["<module absent>"]
            continue
        missing = [n for n in names if not hasattr(mod, n)]
        if missing:
            gaps[f[:-3]] = missing
        checked += len(names)
    assert not gaps, gaps
    # most reference v2 modules are py2-only or build __all__
    # dynamically; ~29 literal names are checkable today
    assert checked >= 25, checked


@pytest.mark.skipif(
    not os.path.isdir("/root/reference/python/paddle/dataset"),
    reason="reference not mounted")
def test_dataset_and_reader_all_names_resolve():
    """Same freeze for the dataset and reader packages (reference
    python/paddle/dataset/*.py, python/paddle/reader/*.py)."""
    import importlib
    import warnings
    warnings.filterwarnings("ignore")
    gaps = {}
    for pkg, ref in (("paddle_tpu.dataset",
                      "/root/reference/python/paddle/dataset"),
                     ("paddle_tpu.reader",
                      "/root/reference/python/paddle/reader")):
        for f in sorted(os.listdir(ref)):
            if not f.endswith(".py") or f.startswith("test") \
                    or f == "__init__.py":
                continue
            names = _ref_all(os.path.join(ref, f))
            if not names:
                continue
            # the reference conll05 __all__ contains a malformed
            # 'test, get_dict' single entry — treat as two names
            flat = [p.strip() for n in names for p in n.split(",")]
            try:
                mod = importlib.import_module(pkg + "." + f[:-3])
            except ImportError:
                gaps[f] = ["<module absent>"]
                continue
            missing = [n for n in flat if not hasattr(mod, n)]
            if missing:
                gaps[f[:-3]] = missing
    assert not gaps, gaps


def test_dataset_convert_recordio_roundtrip(tmp_path):
    """convert() writes recordio shards whose pickled records round-trip
    (reference dataset/common.py:210)."""
    import glob
    import pickle
    from paddle_tpu.dataset import common, mnist
    from paddle_tpu.native.pyrio import PyScanner
    mnist.convert(str(tmp_path))
    files = sorted(glob.glob(str(tmp_path / "minist_train-*")))
    assert files
    s = PyScanner(files[0])
    img, lab = pickle.loads(s.next())
    s.close()
    assert img.shape == (784,) and 0 <= int(lab) < 10

    # split + cluster_files_reader partition losslessly
    n = common.split(mnist.test(), 37,
                     suffix=str(tmp_path / "mn-%05d.pickle"))
    total = 0
    for tid in range(3):
        total += sum(1 for _ in common.cluster_files_reader(
            str(tmp_path / "mn-*.pickle"), 3, tid)())
    assert total == sum(1 for _ in mnist.test()())
    assert len(n) >= 3


def test_reader_decorator_tail():
    """PipeReader / Fake / multiprocess_reader (reference
    decorator.py:338,:438,:509)."""
    import paddle_tpu.reader as R
    fake = R.Fake()(lambda: iter([5, 6]), 3)
    assert list(fake()) == [5, 5, 5]
    assert list(R.PipeReader("echo hi").get_line()) == ["hi"]
    got = sorted(R.multiprocess_reader(
        [lambda: iter(range(3)), lambda: iter(range(10, 13))])())
    assert got == [0, 1, 2, 10, 11, 12]
    got = sorted(R.multiprocess_reader(
        [lambda: iter(range(3))], use_pipe=False)())
    assert got == [0, 1, 2]
    # a reader legitimately yielding None must not truncate the stream
    got = list(R.multiprocess_reader([lambda: iter([None, 1, None])])())
    assert got.count(None) == 2 and 1 in got
    # an empty source yields an empty fake stream, not a RuntimeError
    assert list(R.Fake()(lambda: iter([]), 5)()) == []
