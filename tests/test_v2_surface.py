"""Frozen v2 package surface: every name in the reference's v2 module
__all__ lists resolves here (the v2 analogue of the fluid API.spec
freeze). Reference: python/paddle/v2/*.py."""

import ast
import os

import pytest

REF = "/root/reference/python/paddle/v2"


def _ref_all(path):
    try:
        tree = ast.parse(open(path).read())
    except SyntaxError:  # py2-only module (e.g. op.py's print statements)
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", "") == "__all__":
                    try:
                        return [ast.literal_eval(e)
                                for e in node.value.elts]
                    except Exception:
                        return None
    return None


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
def test_v2_module_all_names_resolve():
    import warnings
    warnings.filterwarnings("ignore")
    import paddle_tpu.v2 as p

    gaps = {}
    checked = 0
    for f in sorted(os.listdir(REF)):
        if not f.endswith(".py") or f.startswith("test") \
                or f == "__init__.py":
            continue
        names = _ref_all(os.path.join(REF, f))
        if not names:
            continue
        mod = getattr(p, f[:-3], None)
        if mod is None:
            gaps[f[:-3]] = ["<module absent>"]
            continue
        missing = [n for n in names if not hasattr(mod, n)]
        if missing:
            gaps[f[:-3]] = missing
        checked += len(names)
    assert not gaps, gaps
    # most reference v2 modules are py2-only or build __all__
    # dynamically; ~29 literal names are checkable today
    assert checked >= 25, checked
