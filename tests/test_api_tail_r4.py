"""Round-4 API.spec tail: the 15 symbols VERDICT r3 #6 listed as
unresolved, each exercised functionally (not just importable).
Reference: paddle/fluid/API.spec lines 20, 196-203, 318-322, 331, 392,
408, 412."""

import os

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program


def test_scope_new_scope_parent_fallback():
    s = fluid.executor.Scope()
    s.set("w", np.ones(2))
    kid = s.new_scope()
    # reads fall through to the parent; writes stay local
    np.testing.assert_array_equal(kid.get("w"), np.ones(2))
    assert kid.has("w")
    kid.set("w", np.zeros(2))
    np.testing.assert_array_equal(kid.get("w"), np.zeros(2))
    np.testing.assert_array_equal(s.get("w"), np.ones(2))
    assert kid.find_var("w") is not None
    s.drop_kids()


def test_layers_load_roundtrip(tmp_path):
    path = str(tmp_path / "t.npy")
    val = np.arange(6, dtype=np.float32).reshape(2, 3)
    with open(path, "wb") as f:
        np.save(f, val)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        out = fluid.layers.create_tensor(dtype="float32")
        fluid.layers.load(out, file_path=path)
    exe = fluid.Executor(fluid.CPUPlace())
    (got,) = exe.run(main, fetch_list=[out])
    np.testing.assert_array_equal(np.asarray(got), val)


def test_random_data_generator_and_preprocessor():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        reader = fluid.layers.random_data_generator(
            low=0.0, high=1.0, shapes=[[4, 3], [4, 1]], lod_levels=[0, 0])
        pre = fluid.layers.Preprocessor(reader=reader)
        with pre.block():
            img, lbl = pre.inputs()
            img_out = fluid.layers.scale(img, scale=2.0)
            lbl_out = fluid.layers.scale(lbl, scale=1.0, bias=1.0)
            pre.outputs(img_out, lbl_out)
        img_v, lbl_v = fluid.layers.read_file(pre())
        s = fluid.layers.reduce_mean(img_v)
    exe = fluid.Executor(fluid.CPUPlace())
    reader.start()
    vals = []
    for _ in range(3):
        feed = reader.next_feed()
        (img_np, lbl_np, sv) = exe.run(main, feed=feed,
                                       fetch_list=[img_v, lbl_v, s])
        img_np = np.asarray(img_np)
        lbl_np = np.asarray(lbl_np)
        # scaled uniforms: img in [0,2), lbl in [1,2)
        assert img_np.shape == (4, 3) and lbl_np.shape == (4, 1)
        assert (img_np >= 0).all() and (img_np < 2).all()
        assert (lbl_np >= 1).all() and (lbl_np < 2).all()
        vals.append(float(np.asarray(sv)))
    reader.reset()
    assert len(set(vals)) > 1   # actually random, not constant


def test_data_feeder_decorate_reader():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("dx", shape=[2])
        y = fluid.layers.data("dy", shape=[1], dtype="int64")
    feeder = fluid.DataFeeder(feed_list=[x, y], program=main)

    def rdr():
        for i in range(6):
            yield [(np.full(2, i, np.float32),
                    np.array([i], np.int64))]

    single = list(feeder.decorate_reader(rdr, multi_devices=False)())
    assert len(single) == 6 and set(single[0]) == {"dx", "dy"}
    multi = list(feeder.decorate_reader(rdr, multi_devices=True,
                                        num_places=2)())
    assert len(multi) == 3            # 6 batches -> 3 steps of 2 devices
    assert isinstance(multi[0], list) and len(multi[0]) == 2
    assert float(np.asarray(multi[1][0]["dx"])[0, 0]) == 2.0


def test_transpiler_get_pserver_programs():
    import paddle_tpu.fluid.transpiler as transpiler
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.fc(x, size=2)
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(0.1).minimize(loss)
        t = transpiler.DistributeTranspiler()
        t.transpile(trainer_id=0,
                    pservers="127.0.0.1:6174,127.0.0.1:6175", trainers=2)
        prog, start = t.get_pserver_programs("127.0.0.1:6174")
    types = [op.type for op in prog.global_block().ops]
    assert "listen_and_serv" in types
    assert start.global_block().ops  # startup initializes assigned params


def test_convert_reader_to_recordio_files(tmp_path):
    from paddle_tpu.fluid import recordio_writer

    def rdr():
        for i in range(7):
            yield (np.full((2,), i, np.float32),)

    files = recordio_writer.convert_reader_to_recordio_files(
        str(tmp_path / "d.recordio"), batch_per_file=3,
        reader_creator=rdr)
    assert [os.path.basename(f) for f in files] == \
        ["d-00000.recordio", "d-00001.recordio", "d-00002.recordio"]
    got = []
    for f in files:
        for rec in recordio_writer.recordio_reader(f)():
            got.append(float(np.asarray(rec)[0]))
    assert got == [float(i) for i in range(7)]
