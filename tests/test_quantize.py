"""Quantized inference path (QUANTIZE.md): PTQ pass, fused
dequant-matmul kernel parity, tamper rejection, the serving precision
axis (A/B routing + per-precision metrics), compile-cache fingerprint
isolation, and the CLI / chaos surfaces."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.flags import FLAGS

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture
def no_cc():
    """Compile cache off: these tests measure numerics/routing, not the
    store (the store-facing tests manage their own fresh root)."""
    old = fluid.get_flags(["compile_cache"])
    fluid.set_flags({"compile_cache": False})
    yield
    fluid.set_flags(old)


@pytest.fixture
def store(tmp_path):
    from paddle_tpu import compile_cache as cc
    old = fluid.get_flags(["compile_cache", "compile_cache_dir"])
    root = str(tmp_path / "cc_store")
    fluid.set_flags({"compile_cache": True, "compile_cache_dir": root})
    cc.reset_stats()
    yield root
    fluid.set_flags(old)
    cc.reset_stats()


def _export_fc(tmp_path, name="fc", seed=7, in_dim=16, hidden=64,
               classes=10):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[in_dim], dtype="float32")
        h = fluid.layers.fc(input=x, size=hidden, act="relu")
        pred = fluid.layers.fc(input=h, size=classes, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / name)
        fluid.save_inference_model(md, ["x"], [pred], exe,
                                   main_program=main)
    return md, (in_dim,)


def _export_mnist_cnn(tmp_path, name="cnn", seed=11):
    """conv2d + fc: exercises the dequant_conv2d path too."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[1, 12, 12],
                              dtype="float32")
        conv = fluid.layers.conv2d(input=x, num_filters=8,
                                   filter_size=3, padding=1, act="relu")
        pool = fluid.layers.pool2d(input=conv, pool_size=2,
                                   pool_stride=2)
        pred = fluid.layers.fc(input=pool, size=10, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / name)
        fluid.save_inference_model(md, ["x"], [pred], exe,
                                   main_program=main)
    return md, (1, 12, 12)


def _calib(shape, n=3, batch=8, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.randn(batch, *shape).astype(np.float32)}
            for _ in range(n)]


# ---------------------------------------------------------------------------
# kernel parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("M,K,N,bm,bk,bn,act", [
    (8, 16, 32, 4, 8, 16, np.float32),
    (16, 64, 128, 8, 32, 64, "bfloat16"),
    (4, 24, 10, 2, 8, 2, np.float32),     # tiny-lane channel count
    (1, 32, 16, 1, 16, 8, np.float32),    # batch-1 serving bucket
])
def test_dequant_matmul_kernel_parity(M, K, N, bm, bk, bn, act):
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels import (dequant_matmul,
                                               dequant_matmul_reference)
    rng = np.random.RandomState(M * 31 + N)
    x = jnp.asarray(rng.randn(M, K).astype(np.float32)).astype(act)
    wq = jnp.asarray(rng.randint(-127, 128, (K, N)).astype(np.int8))
    s = jnp.asarray(rng.rand(N).astype(np.float32) * 0.1 + 0.01)
    out_k = dequant_matmul(x, wq, s, block_m=bm, block_k=bk,
                           block_n=bn, out_dtype=np.float32)
    out_r = dequant_matmul_reference(x, wq, s, out_dtype=np.float32)
    assert out_k.shape == (M, N)
    assert float(jnp.abs(out_k - out_r).max()) < 1e-3


def test_dequant_matmul_non_divisible_falls_back():
    """Channel counts no candidate block divides must take the XLA
    reference path and still be exact."""
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels import (dequant_matmul,
                                               dequant_matmul_reference)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(3, 7).astype(np.float32))
    wq = jnp.asarray(rng.randint(-127, 128, (7, 13)).astype(np.int8))
    s = jnp.asarray(np.full(13, 0.02, np.float32))
    assert np.array_equal(
        np.asarray(dequant_matmul(x, wq, s)),
        np.asarray(dequant_matmul_reference(x, wq, s)))


def test_dequant_tuning_registry_roundtrip(store):
    from paddle_tpu.ops import attention_tuning as at
    assert at.get_dequant_config(16, 64, 128, "float32") is not None
    at.record_dequant(16, 64, 128, "float32", 8, 32, 64,
                      extra={"ms": 1.5})
    assert at.get_dequant_config(16, 64, 128, "float32") == (8, 32, 64)
    # a tuned record that no longer tiles the shape is ignored
    at.record_dequant(16, 64, 128, "float32", 7, 32, 64)
    cfg = at.get_dequant_config(16, 64, 128, "float32")
    assert cfg is not None and cfg != (7, 32, 64)
    # the namespace is its own file in the shared registry
    from paddle_tpu import compile_cache as cc
    assert os.path.exists(cc.tuning_path(at.DEQUANT_NAMESPACE))


# ---------------------------------------------------------------------------
# the PTQ pass
# ---------------------------------------------------------------------------

def test_quantize_roundtrip_and_bytes(tmp_path, no_cc):
    from paddle_tpu.inference import (AnalysisConfig, Predictor,
                                      quantize_inference_model,
                                      read_quant_meta)
    md, shape = _export_fc(tmp_path)
    s = quantize_inference_model(md, calib_feeds=_calib(shape),
                                 min_weight_elems=64)
    assert s["n_quantized"] == 2
    # acceptance: quantized artifact weight bytes <= 0.5x fp32
    assert s["bytes"]["ratio"] <= 0.5
    meta = read_quant_meta(s["dst"])
    assert meta["schema"] == 1 and meta["precision"] == "int8"
    assert meta["crc32"]  # payload CRC table non-empty
    assert meta["calibration"]["batches"] == 3
    cfg = AnalysisConfig(model_dir=md)
    cfg.batch_size_buckets = (4, 8)
    cfgq = AnalysisConfig(model_dir=s["dst"])
    cfgq.batch_size_buckets = (4, 8)
    p32, pq = Predictor(cfg), Predictor(cfgq)
    assert p32.precision == "fp32" and pq.precision == "int8"
    x = np.random.RandomState(5).randn(4, 16).astype(np.float32)
    o32, = p32.run({"x": x})
    oq, = pq.run({"x": x})
    # pinned accuracy delta: softmax outputs within 0.05, top-1 agrees
    assert float(np.abs(o32 - oq).max()) < 0.05
    assert (o32.argmax(1) == oq.argmax(1)).all()
    # bit-stable per lane: the same request twice is identical
    oq2, = pq.run({"x": x})
    assert np.array_equal(oq, oq2)


def test_quantize_mnist_cnn_pinned_delta(tmp_path, no_cc):
    """The conv path (dequant_conv2d): per-model pinned accuracy delta
    on a conv+fc zoo-shaped model."""
    from paddle_tpu.inference import (AnalysisConfig, Predictor,
                                      quantize_inference_model)
    md, shape = _export_mnist_cnn(tmp_path)
    s = quantize_inference_model(md, calib_feeds=_calib(shape, batch=4),
                                 min_weight_elems=64)
    kinds = {l["op_type"] for l in s["layers"]}
    assert "conv2d" in kinds and "mul" in kinds
    assert s["bytes"]["ratio"] <= 0.5
    cfg = AnalysisConfig(model_dir=md)
    cfgq = AnalysisConfig(model_dir=s["dst"])
    x = np.random.RandomState(9).randn(4, 1, 12, 12).astype(np.float32)
    o32, = Predictor(cfg).run({"x": x})
    oq, = Predictor(cfgq).run({"x": x})
    assert float(np.abs(o32 - oq).max()) < 0.1
    assert (o32.argmax(1) == oq.argmax(1)).mean() >= 0.75


def test_quantize_size_floor(tmp_path, no_cc):
    from paddle_tpu.inference import quantize_inference_model
    md, shape = _export_fc(tmp_path)
    # floor between the two layers: 64*10=640 < 1024 <= 16*64
    s = quantize_inference_model(md, min_weight_elems=1024,
                                 dst_dir=str(tmp_path / "q_floor"))
    assert s["n_quantized"] == 1
    # floor above everything: nothing to quantize is an explicit error
    with pytest.raises(ValueError, match="floor"):
        quantize_inference_model(md, min_weight_elems=10 ** 9,
                                 dst_dir=str(tmp_path / "q_none"))


def test_tampered_payload_rejected_at_load(tmp_path, no_cc):
    from paddle_tpu.inference import (AnalysisConfig, Predictor,
                                      QuantizedArtifactError,
                                      quantize_inference_model,
                                      read_quant_meta,
                                      verify_quantized_dir)
    md, shape = _export_fc(tmp_path)
    s = quantize_inference_model(md, min_weight_elems=64)
    meta = read_quant_meta(s["dst"])
    victim = sorted(meta["crc32"])[0]
    path = os.path.join(s["dst"], victim)
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0x10
    with open(path, "wb") as f:
        f.write(raw)
    bad = [(f_, e) for f_, e in verify_quantized_dir(s["dst"]) if e]
    assert bad and bad[0][0] == victim
    with pytest.raises(QuantizedArtifactError, match=victim):
        Predictor(AnalysisConfig(model_dir=s["dst"]))


def test_verifier_clean_on_quantized_artifact(tmp_path, no_cc):
    """The PR 9 verifier runs the dequant lowerings abstractly: no
    unregistered-op, no shape findings on a quantized artifact — and
    lint_artifact CRCs the payloads on top."""
    from paddle_tpu.inference import quantize_inference_model
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from tools.lint_program import lint_artifact
    md, shape = _export_fc(tmp_path)
    s = quantize_inference_model(md, min_weight_elems=64)
    diags = lint_artifact(s["dst"], verbose=False)
    assert not [d for d in diags if d.is_error], diags


# ---------------------------------------------------------------------------
# serving precision axis
# ---------------------------------------------------------------------------

def test_registry_ab_routing_and_metrics(tmp_path, no_cc):
    from paddle_tpu.inference import quantize_inference_model
    from paddle_tpu.serving.metrics import ServingMetrics
    from paddle_tpu.serving.model_registry import ModelRegistry
    md, shape = _export_fc(tmp_path)
    s = quantize_inference_model(md, min_weight_elems=64)
    metrics = ServingMetrics()
    reg = ModelRegistry(metrics=metrics)
    try:
        e32 = reg.load_model("fc", md, buckets=(4,))
        eq = reg.load_model("fc", s["dst"], buckets=(4,))
        assert e32.precision == "fp32" and eq.precision == "int8"
        x = np.random.RandomState(2).randn(4, 16).astype(np.float32)
        r32 = reg.infer("fc", {"x": x}, precision="fp32", timeout=60)
        rq = reg.infer("fc", {"x": x}, precision="int8", timeout=60)
        rdef = reg.infer("fc", {"x": x}, timeout=60)
        # explicit lanes are bit-stable; default stays on fp32
        assert np.array_equal(
            r32[0], reg.infer("fc", {"x": x}, precision="fp32",
                              timeout=60)[0])
        assert np.array_equal(
            rq[0], reg.infer("fc", {"x": x}, precision="int8",
                             timeout=60)[0])
        assert np.array_equal(rdef[0], r32[0])
        assert not np.array_equal(rq[0], r32[0])
        # missing lane is a named error
        with pytest.raises(KeyError, match="precision lane"):
            reg.infer("fc", {"x": x}, precision="bf16", timeout=60)
        # weighted default split: 50/50 over 8 requests = 4/4
        reg.set_ab_weights("fc", {"fp32": 0.5, "int8": 0.5})
        before32 = metrics.model("fc").requests.value
        before8 = metrics.model("fc", "int8").requests.value
        for _ in range(8):
            reg.infer("fc", {"x": x}, timeout=60)
        assert metrics.model("fc").requests.value - before32 == 4
        assert metrics.model("fc", "int8").requests.value - before8 == 4
        snap = metrics.snapshot()["models"]
        assert snap["fc"]["precision"] == "fp32"
        assert snap["fc@int8"]["precision"] == "int8"
        assert snap["fc@int8"]["model"] == "fc"
        desc = reg.describe()["fc"]
        assert desc["precisions"] == {"fp32": e32.version,
                                      "int8": eq.version}
        assert desc["ab_weights"] == {"fp32": 0.5, "int8": 0.5}
        # unload drops BOTH metric lanes
        reg.unload_model("fc")
        assert "fc@int8" not in metrics.snapshot()["models"]
    finally:
        reg.close_all(timeout=10)


def test_ab_canary_weight_leaves_remainder_on_fp32(tmp_path, no_cc):
    """load_model(ab_weight=0.25) on the int8 lane alone must canary
    int8 at 25% with fp32 keeping the unassigned 75% — NOT shift all
    default traffic to the only weighted lane (the bug the end-to-end
    drive caught)."""
    from paddle_tpu.inference import quantize_inference_model
    from paddle_tpu.serving.metrics import ServingMetrics
    from paddle_tpu.serving.model_registry import ModelRegistry
    md, shape = _export_fc(tmp_path)
    s = quantize_inference_model(md, min_weight_elems=64)
    metrics = ServingMetrics()
    reg = ModelRegistry(metrics=metrics)
    try:
        reg.load_model("fc", md, buckets=(4,))
        reg.load_model("fc", s["dst"], buckets=(4,), ab_weight=0.25)
        x = np.random.RandomState(8).randn(4, 16).astype(np.float32)
        for _ in range(8):
            reg.infer("fc", {"x": x}, timeout=60)
        assert metrics.model("fc", "int8").requests.value == 2
        assert metrics.model("fc").requests.value == 6
    finally:
        reg.close_all(timeout=10)


def test_hot_swap_is_per_lane(tmp_path, no_cc):
    """Reloading the int8 lane must not drain/retire the fp32 lane —
    the A/B sibling is not a hot-swap target."""
    from paddle_tpu.inference import quantize_inference_model
    from paddle_tpu.serving.model_registry import ModelRegistry
    md, shape = _export_fc(tmp_path)
    s = quantize_inference_model(md, min_weight_elems=64)
    reg = ModelRegistry()
    try:
        e32 = reg.load_model("fc", md, buckets=(4,))
        eq1 = reg.load_model("fc", s["dst"], buckets=(4,))
        eq2 = reg.load_model("fc", s["dst"], buckets=(4,))  # lane swap
        desc = reg.describe()["fc"]
        # fp32 version survives; int8 lane flipped to the new version
        assert desc["precisions"]["fp32"] == e32.version
        assert desc["precisions"]["int8"] == eq2.version
        assert eq1.version not in desc["versions"]
        x = np.random.RandomState(4).randn(2, 16).astype(np.float32)
        assert reg.infer("fc", {"x": x}, precision="fp32",
                         timeout=60) is not None
    finally:
        reg.close_all(timeout=10)


def test_wire_precision_and_serving_top(tmp_path, no_cc):
    from paddle_tpu.inference import quantize_inference_model
    from paddle_tpu.serving import InferenceServer, ServingClient
    md, shape = _export_fc(tmp_path)
    s = quantize_inference_model(md, min_weight_elems=64)
    srv = InferenceServer(buckets=(4,)).start()
    cli = ServingClient(srv.endpoint)
    try:
        l32 = cli.load_model("fc", md, buckets=[4])
        lq = cli.load_model("fc", s["dst"], buckets=[4], ab_weight=0.5)
        assert l32["precision"] == "fp32" and lq["precision"] == "int8"
        x = np.random.RandomState(6).randn(2, 16).astype(np.float32)
        a = cli.infer("fc", {"x": x}, precision="int8",
                      deadline_ms=60000)
        b = cli.infer("fc", {"x": x}, precision="int8",
                      deadline_ms=60000)
        assert np.array_equal(a[0], b[0])
        st = cli.stats()
        models = st["stats"]["models"]
        assert "fc@int8" in models and models["fc@int8"]["requests"] >= 2
        # per-precision rows render in serving_top and Prometheus
        from tools.serving_top import render
        text = render(st)
        assert "int8" in text and "PREC" in text
        prom = cli.metrics_text()
        assert 'precision="int8"' in prom
        assert 'model="fc"' in prom
    finally:
        cli.shutdown_server()
        cli.close()


# ---------------------------------------------------------------------------
# compile-cache fingerprint isolation + warm reload
# ---------------------------------------------------------------------------

def test_precision_in_fingerprint_and_warm_reload(tmp_path, store):
    from paddle_tpu import compile_cache as cc
    from paddle_tpu.inference import (AnalysisConfig, Predictor,
                                      quantize_inference_model)
    md, shape = _export_fc(tmp_path)
    s = quantize_inference_model(md, min_weight_elems=64)

    def load(path):
        cfg = AnalysisConfig(model_dir=path)
        cfg.batch_size_buckets = (4,)
        p = Predictor(cfg)
        p.run({"x": np.zeros((4, 16), np.float32)})
        return p

    p32 = load(md)
    fp = p32._aot_fingerprint({"x": np.zeros((4, 16), np.float32)})
    assert fp["precision"] == "fp32"
    cold32 = cc.stats()
    assert cold32["misses"] >= 1
    # the int8 build must MISS (no cross-lane executable collision)
    pq = load(s["dst"])
    fpq = pq._aot_fingerprint({"x": np.zeros((4, 16), np.float32)})
    assert fpq["precision"] == "int8"
    delta = cc.stats_delta(cold32)
    assert delta["misses"] >= 1 and delta["hits"] == 0
    # warm reload of the quantized artifact: hits:N, misses:0
    warm_before = cc.stats()
    load(s["dst"])
    warm = cc.stats_delta(warm_before)
    assert warm["hits"] >= 1 and warm["misses"] == 0, warm


# ---------------------------------------------------------------------------
# CLI + chaos surfaces
# ---------------------------------------------------------------------------

def _run_tool(argv, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PADDLE_TPU_CHAOS", None)
    return subprocess.run([sys.executable] + argv, capture_output=True,
                          text=True, timeout=timeout, cwd=REPO, env=env)


def test_quantize_model_cli(tmp_path, no_cc):
    md, shape = _export_fc(tmp_path)
    out = str(tmp_path / "cli_int8")
    proc = _run_tool(["tools/quantize_model.py", md, "--out", out,
                      "--calib_random", "2", "--min_elems", "64"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["dst"] == out and os.path.isdir(out)
    assert summary["bytes"]["ratio"] <= 0.5
    # not-an-artifact dir is a usage error, not a traceback
    proc = _run_tool(["tools/quantize_model.py", str(tmp_path)])
    assert proc.returncode == 1


def test_verify_quantized_cli_exit_codes(tmp_path, no_cc):
    from paddle_tpu.inference import (quantize_inference_model,
                                      read_quant_meta)
    md, shape = _export_fc(tmp_path)
    s = quantize_inference_model(md, min_weight_elems=64)
    proc = _run_tool(["tools/verify_quantized.py", s["dst"]])
    assert proc.returncode == 0, proc.stderr[-2000:]
    # not a quantized dir -> 1
    proc = _run_tool(["tools/verify_quantized.py", md])
    assert proc.returncode == 1
    # corrupt one scale table -> 2, naming the file
    victim = sorted(read_quant_meta(s["dst"])["crc32"])[-1]
    path = os.path.join(s["dst"], victim)
    raw = bytearray(open(path, "rb").read())
    raw[-1] ^= 0x01
    with open(path, "wb") as f:
        f.write(raw)
    proc = _run_tool(["tools/verify_quantized.py", s["dst"]])
    assert proc.returncode == 2
    assert victim in proc.stderr


def test_lint_program_cli_on_quantized_dir(tmp_path, no_cc):
    from paddle_tpu.inference import (quantize_inference_model,
                                      read_quant_meta)
    md, shape = _export_fc(tmp_path)
    s = quantize_inference_model(md, min_weight_elems=64)
    proc = _run_tool(["tools/lint_program.py", s["dst"]])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "quantized artifact" in proc.stdout
    victim = sorted(read_quant_meta(s["dst"])["crc32"])[0]
    path = os.path.join(s["dst"], victim)
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0x40
    with open(path, "wb") as f:
        f.write(raw)
    proc = _run_tool(["tools/lint_program.py", s["dst"]])
    assert proc.returncode == 2
    assert "quant-payload" in proc.stdout


def test_chaos_quantize_commit_scenario():
    proc = _run_tool(["tools/chaos.py", "--scenario", "quantize-commit",
                      "--no-real-kill"], timeout=400)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    assert "PASS quantize-commit" in proc.stdout


@pytest.mark.slow
def test_bench_serving_precision_smoke():
    proc = _run_tool(["tools/bench_serving.py", "--precision", "both",
                      "--smoke", "--qps", "30", "--duration", "1.5"],
                     timeout=500)
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = [json.loads(l) for l in proc.stdout.splitlines()
            if l.startswith("{")]
    precs = {r["precision"] for r in recs}
    assert precs == {"fp32", "int8"}
    for r in recs:
        assert r["bit_stable"] is True
        assert r["quant_bytes"]["ratio"] <= 0.5
        if r["precision"] == "int8":
            assert r["accuracy_delta"]["top1_agreement"] >= 0.9
