"""Vision + misc op tests (reference unittests/test_grid_sampler_op.py,
test_affine_grid_op.py, test_pool3d_op.py, test_unpool_op.py, test_spp_op.py,
test_row_conv_op.py, test_label_smooth_op.py, test_fake_quantize_op.py
family) — numpy references."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid.lod import create_lod_tensor, LoDTensor


def _run(build_fn, feed):
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        fetches = build_fn()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    res = exe.run(main, feed=feed, fetch_list=list(fetches))
    return [r.numpy() if isinstance(r, LoDTensor) else np.asarray(r)
            for r in res]


def test_affine_grid_identity_and_grid_sampler():
    # identity theta -> grid covers [-1,1]; sampling with it reproduces x
    x = np.random.RandomState(0).randn(2, 3, 5, 7).astype(np.float32)
    theta = np.tile(np.array([[1.0, 0, 0], [0, 1.0, 0]], np.float32),
                    (2, 1, 1))

    def build():
        xv = fluid.layers.data("x", shape=[3, 5, 7], dtype="float32")
        tv = fluid.layers.data("t", shape=[2, 3], dtype="float32")
        grid = fluid.layers.affine_grid(tv, out_shape=[2, 3, 5, 7])
        out = fluid.layers.grid_sampler(xv, grid)
        return [grid, out]

    grid, out = _run(build, {"x": x, "t": theta})
    assert grid.shape == (2, 5, 7, 2)
    np.testing.assert_allclose(grid[0, 0, 0], [-1.0, -1.0], atol=1e-6)
    np.testing.assert_allclose(grid[0, -1, -1], [1.0, 1.0], atol=1e-6)
    np.testing.assert_allclose(out, x, atol=1e-4)


def test_affine_channel():
    x = np.random.RandomState(1).randn(2, 3, 4, 4).astype(np.float32)
    scale = np.array([1.0, 2.0, 3.0], np.float32)
    bias = np.array([0.5, -0.5, 0.0], np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[3, 4, 4], dtype="float32")
        s = fluid.layers.data("s", shape=[3], dtype="float32",
                              append_batch_size=False)
        b = fluid.layers.data("b", shape=[3], dtype="float32",
                              append_batch_size=False)
        return [fluid.layers.affine_channel(xv, s, b)]

    (out,) = _run(build, {"x": x, "s": scale, "b": bias})
    ref = x * scale[None, :, None, None] + bias[None, :, None, None]
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_pool3d_max_avg():
    x = np.random.RandomState(2).randn(1, 2, 4, 4, 4).astype(np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[2, 4, 4, 4], dtype="float32")
        mx = fluid.layers.pool3d(xv, pool_size=2, pool_type="max",
                                 pool_stride=2)
        av = fluid.layers.pool3d(xv, pool_size=2, pool_type="avg",
                                 pool_stride=2)
        return [mx, av]

    mx, av = _run(build, {"x": x})
    ref_mx = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).max(axis=(3, 5, 7))
    ref_av = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).mean(axis=(3, 5, 7))
    np.testing.assert_allclose(mx, ref_mx, atol=1e-5)
    np.testing.assert_allclose(av, ref_av, atol=1e-5)


def test_conv3d_transpose_identity():
    # 1x1x1 filter with identity weights = channel mix only
    x = np.random.RandomState(3).randn(1, 2, 3, 3, 3).astype(np.float32)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", shape=[2, 3, 3, 3], dtype="float32")
        out = fluid.layers.conv3d_transpose(
            xv, num_filters=2, filter_size=1,
            param_attr=fluid.ParamAttr(
                name="w3dt",
                initializer=fluid.initializer.Constant(1.0)),
            bias_attr=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (res,) = exe.run(main, feed={"x": x}, fetch_list=[out])
    ref = np.tile(x.sum(axis=1, keepdims=True), (1, 2, 1, 1, 1))
    np.testing.assert_allclose(np.asarray(res), ref, atol=1e-5)


def test_unpool():
    # 2x2 max pool indices then unpool restores values at argmax positions
    x = np.array([[[[5.0, 1.0], [2.0, 3.0]]]], np.float32)  # pooled [1,1,2,2]?
    pooled = np.array([[[[9.0]]]], np.float32)
    indices = np.array([[[[3]]]], np.int32)   # flat pos 3 in 2x2 plane

    def build():
        p = fluid.layers.data("p", shape=[1, 1, 1], dtype="float32")
        i = fluid.layers.data("i", shape=[1, 1, 1], dtype="int32")
        return [fluid.layers.unpool(p, i, ksize=[2, 2], strides=[2, 2])]

    (out,) = _run(build, {"p": pooled, "i": indices})
    ref = np.zeros((1, 1, 2, 2), np.float32)
    ref[0, 0, 1, 1] = 9.0
    np.testing.assert_allclose(out, ref)


def test_spp():
    x = np.random.RandomState(4).randn(2, 3, 4, 4).astype(np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[3, 4, 4], dtype="float32")
        return [fluid.layers.spp(xv, pyramid_height=2, pool_type="max")]

    (out,) = _run(build, {"x": x})
    # level0: global max [2,3]; level1: 2x2 adaptive max [2,12] -> 15 per C
    assert out.shape == (2, 3 * (1 + 4))
    np.testing.assert_allclose(out[:, :3], x.max(axis=(2, 3)), atol=1e-5)
    blk = x.reshape(2, 3, 2, 2, 2, 2).max(axis=(3, 5)).reshape(2, -1)
    np.testing.assert_allclose(out[:, 3:], blk, atol=1e-5)


def test_shuffle_channel():
    x = np.arange(8, dtype=np.float32).reshape(1, 8, 1, 1)

    def build():
        xv = fluid.layers.data("x", shape=[8, 1, 1], dtype="float32")
        return [fluid.layers.shuffle_channel(xv, group=2)]

    (out,) = _run(build, {"x": x})
    np.testing.assert_allclose(out[0, :, 0, 0], [0, 4, 1, 5, 2, 6, 3, 7])


def test_psroi_pool_constant():
    oc, ph, pw = 2, 2, 2
    x = np.full((1, oc * ph * pw, 8, 8), 3.0, np.float32)
    rois = np.array([[[0.0, 0.0, 7.0, 7.0]]], np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[oc * ph * pw, 8, 8],
                               dtype="float32")
        rv = fluid.layers.data("r", shape=[1, 4], dtype="float32")
        return [fluid.layers.psroi_pool(xv, rv, oc, 1.0, ph, pw)]

    (out,) = _run(build, {"x": x, "r": rois})
    assert out.shape == (1, 1, oc, ph, pw)
    np.testing.assert_allclose(out, 3.0, atol=1e-5)


def test_crop_and_pad_constant_like():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    y = np.ones((1, 2, 2), np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[2, 3, 4], dtype="float32",
                               append_batch_size=False)
        yv = fluid.layers.data("y", shape=[1, 2, 2], dtype="float32",
                               append_batch_size=False)
        c = fluid.layers.crop(xv, shape=[1, 2, 2], offsets=[1, 0, 1])
        p = fluid.layers.pad_constant_like(xv, yv, pad_value=7.0)
        return [c, p]

    c, p = _run(build, {"x": x, "y": y})
    np.testing.assert_allclose(c, x[1:2, 0:2, 1:3])
    ref = np.full((2, 3, 4), 7.0, np.float32)
    ref[:1, :2, :2] = 1.0
    np.testing.assert_allclose(p, ref)


def test_random_crop():
    x = np.random.RandomState(5).randn(4, 3, 10, 10).astype(np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[3, 10, 10], dtype="float32")
        return [fluid.layers.random_crop(xv, shape=[3, 6, 6])]

    (out,) = _run(build, {"x": x})
    assert out.shape == (4, 3, 6, 6)
    # crop content must come from x: every output plane is a sub-window
    flat = x.reshape(4, -1)
    assert np.all(np.isin(np.round(out, 5), np.round(flat, 5)))


def test_im2sequence():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)

    def build():
        xv = fluid.layers.data("x", shape=[1, 4, 4], dtype="float32")
        return [fluid.layers.im2sequence(xv, filter_size=2, stride=2)]

    (out,) = _run(build, {"x": x})
    # 4 patches of 4 values each
    assert out.shape == (4, 4)
    np.testing.assert_allclose(out[0], [0, 1, 4, 5])
    np.testing.assert_allclose(out[3], [10, 11, 14, 15])


def test_selu():
    x = np.array([[-1.0, 0.0, 2.0]], np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[3], dtype="float32")
        return [fluid.layers.selu(xv)]

    (out,) = _run(build, {"x": x})
    scale, alpha = 1.0507009873554805, 1.6732632423543772
    ref = scale * np.where(x > 0, x, alpha * (np.exp(x) - 1))
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_norm_and_squared_l2_distance():
    x = np.random.RandomState(6).randn(3, 5).astype(np.float32)
    y = np.random.RandomState(7).randn(3, 5).astype(np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[5], dtype="float32")
        yv = fluid.layers.data("y", shape=[5], dtype="float32")
        n = fluid.layers.l2_norm_layer(xv, axis=1)
        d = fluid.layers.squared_l2_distance(xv, yv)
        return [n, d]

    n, d = _run(build, {"x": x, "y": y})
    ref_n = x / np.sqrt((x ** 2).sum(axis=1, keepdims=True) + 1e-10)
    np.testing.assert_allclose(n, ref_n, atol=1e-5)
    np.testing.assert_allclose(d[:, 0], ((x - y) ** 2).sum(axis=1),
                               atol=1e-5)


def test_label_smooth():
    onehot = np.eye(4, dtype=np.float32)[None]

    def build():
        xv = fluid.layers.data("x", shape=[4, 4], dtype="float32")
        return [fluid.layers.label_smooth(xv, epsilon=0.1)]

    (out,) = _run(build, {"x": onehot})
    ref = 0.9 * onehot + 0.1 / 4
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_bilinear_tensor_product_shape_and_grad():
    rng = np.random.RandomState(8)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", shape=[3], dtype="float32")
        yv = fluid.layers.data("y", shape=[4], dtype="float32")
        out = fluid.layers.bilinear_tensor_product(xv, yv, size=5)
        loss = fluid.layers.mean(out)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    feed = {"x": rng.randn(2, 3).astype(np.float32),
            "y": rng.randn(2, 4).astype(np.float32)}
    (o1,) = exe.run(main, feed=feed, fetch_list=[out])
    assert np.asarray(o1).shape == (2, 5)
    (l1,) = exe.run(main, feed=feed, fetch_list=[loss])
    assert np.isfinite(float(np.asarray(l1).flatten()[0]))


def test_scatter_nd_add():
    x = np.zeros((3, 4), np.float32)
    idx = np.array([[0, 1], [2, 3], [0, 1]], np.int32)
    upd = np.array([1.0, 2.0, 3.0], np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[3, 4], dtype="float32",
                               append_batch_size=False)
        iv = fluid.layers.data("i", shape=[3, 2], dtype="int32",
                               append_batch_size=False)
        uv = fluid.layers.data("u", shape=[3], dtype="float32",
                               append_batch_size=False)
        return [fluid.layers.scatter_nd_add(xv, iv, uv)]

    (out,) = _run(build, {"x": x, "i": idx, "u": upd})
    ref = x.copy()
    ref[0, 1] += 4.0
    ref[2, 3] += 2.0
    np.testing.assert_allclose(out, ref)


def test_sequence_expand_as():
    x = np.array([[1.0], [2.0]], np.float32)
    y_rows = np.zeros((5, 1), np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[1], dtype="float32")
        yv = fluid.layers.data("y", shape=[1], dtype="float32", lod_level=1)
        return [fluid.layers.sequence_expand_as(xv, yv)]

    (out,) = _run(build, {"x": x,
                          "y": create_lod_tensor(y_rows, [[2, 3]])})
    # row0 repeated 2x, row1 repeated 3x -> packed [5, 1]
    np.testing.assert_allclose(out[:, 0], [1, 1, 2, 2, 2])


def test_sequence_scatter():
    x = np.zeros((2, 6), np.float32)
    ids = np.array([[0], [2], [1], [5]], np.int32)
    upd = np.array([[1.0], [2.0], [3.0], [4.0]], np.float32)
    lens = [2, 2]

    def build():
        xv = fluid.layers.data("x", shape=[6], dtype="float32")
        iv = fluid.layers.data("i", shape=[1], dtype="int32", lod_level=1)
        uv = fluid.layers.data("u", shape=[1], dtype="float32", lod_level=1)
        return [fluid.layers.sequence_scatter(xv, iv, uv)]

    (out,) = _run(build, {"x": x, "i": create_lod_tensor(ids, [lens]),
                          "u": create_lod_tensor(upd, [lens])})
    ref = np.zeros((2, 6), np.float32)
    ref[0, 0] = 1.0
    ref[0, 2] = 2.0
    ref[1, 1] = 3.0
    ref[1, 5] = 4.0
    np.testing.assert_allclose(out, ref)


def test_gather_tree():
    T, B, W = 3, 1, 2
    ids = np.array([[[1, 2]], [[3, 4]], [[5, 6]]], np.int32)
    parents = np.array([[[0, 0]], [[0, 1]], [[1, 0]]], np.int32)

    def build():
        iv = fluid.layers.data("i", shape=[B, W], dtype="int32",
                               append_batch_size=False)
        pv = fluid.layers.data("p", shape=[B, W], dtype="int32",
                               append_batch_size=False)
        return [fluid.layers.gather_tree(iv, pv)]

    feed_shape_fix = {"i": ids, "p": parents}
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        iv = fluid.layers.data("i", shape=[T, B, W], dtype="int32",
                               append_batch_size=False)
        pv = fluid.layers.data("p", shape=[T, B, W], dtype="int32",
                               append_batch_size=False)
        out = fluid.layers.gather_tree(iv, pv)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (res,) = exe.run(main, feed=feed_shape_fix, fetch_list=[out])
    res = np.asarray(res)
    # beam 0 final: id 5 at t2 with parent 1 -> t1 beam1 id 4, its parent 1
    # -> wait: parents[1]=[0,1]: t1 beam1 parent=1 -> t0 beam1 id 2
    np.testing.assert_array_equal(res[:, 0, 0], [2, 4, 5])
    # beam 1 final: id 6 at t2, parent 0 -> t1 beam0 id 3, parent 0 -> id 1
    np.testing.assert_array_equal(res[:, 0, 1], [1, 3, 6])


def test_row_conv():
    rows = np.random.RandomState(9).randn(5, 3).astype(np.float32)
    lens = [2, 3]
    k = 2  # future_context 1 -> filter [2, 3]

    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", shape=[3], dtype="float32", lod_level=1)
        out = fluid.layers.row_conv(
            xv, future_context_size=1,
            param_attr=fluid.ParamAttr(
                name="rc_w", initializer=fluid.initializer.Constant(0.5)))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (res,) = exe.run(main,
                     feed={"x": create_lod_tensor(rows, [lens])},
                     fetch_list=[out])
    res = res.numpy() if isinstance(res, LoDTensor) else np.asarray(res)
    w = np.full((2, 3), 0.5, np.float32)
    ref = np.zeros_like(rows)
    seqs = [rows[0:2], rows[2:5]]
    outs = []
    for s in seqs:
        o = np.zeros_like(s)
        T = len(s)
        for t in range(T):
            for j in range(2):
                if t + j < T:
                    o[t] += w[j] * s[t + j]
        outs.append(o)
    ref = np.concatenate(outs, axis=0)
    np.testing.assert_allclose(res, ref, atol=1e-5)


def test_fake_quantize_roundtrip():
    x = np.array([[0.5, -1.0, 0.25, 0.99]], np.float32)

    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", shape=[4], dtype="float32")
        blk = main.global_block()
        q = blk.create_var(name="q", dtype="float32")
        sc = blk.create_var(name="qs", dtype="float32")
        blk.append_op(type="fake_quantize_abs_max", inputs={"X": xv},
                      outputs={"Out": q, "OutScale": sc},
                      attrs={"bit_length": 8})
        dq = blk.create_var(name="dq", dtype="float32")
        blk.append_op(type="fake_dequantize_max_abs",
                      inputs={"X": q, "Scale": sc},
                      outputs={"Out": dq}, attrs={"max_range": 127.0})
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    qv, scv, dqv = exe.run(main, feed={"x": x}, fetch_list=["q", "qs", "dq"])
    np.testing.assert_allclose(np.asarray(scv), [1.0], atol=1e-6)
    assert np.all(np.abs(np.asarray(qv)) <= 127)
    np.testing.assert_allclose(np.asarray(dqv), x, atol=1.0 / 127)


def test_fake_quantize_range_abs_max_window():
    """Sliding-window scale: an outlier batch must age out of the max
    after window_size steps (reference FindRangeAbsMax). Regression: the
    lowering used to keep a monotone running max that never forgot."""
    window = 2
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", shape=[4], dtype="float32")
        blk = main.global_block()
        for name, shape in [("q", None), ("qscale", None)]:
            blk.create_var(name=name, dtype="float32")
        scales = blk.create_var(name="scales_w", dtype="float32",
                                shape=[window], persistable=True)
        itv = blk.create_var(name="it", dtype="int32", shape=[1],
                             persistable=True)
        blk.append_op(type="fake_quantize_range_abs_max",
                      inputs={"X": xv, "InScales": scales, "Iter": itv},
                      outputs={"Out": "q", "OutScale": "qscale",
                               "OutScales": scales, "IterOut": itv},
                      attrs={"bit_length": 8, "window_size": window})
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    scope = fluid.global_scope()
    scope.set("scales_w", np.zeros(window, np.float32))
    scope.set("it", np.zeros(1, np.int32))
    batches = [10.0, 1.0, 1.0]
    seen = []
    for mx in batches:
        x = np.array([[mx, -0.5, 0.25, 0.1]], np.float32)
        (sc,) = exe.run(main, feed={"x": x}, fetch_list=["qscale"])
        seen.append(float(np.asarray(sc).flatten()[0]))
    assert seen[0] == 10.0 and seen[1] == 10.0   # outlier still in window
    assert seen[2] == 1.0                        # aged out after `window`


def test_conv2d_transpose_output_size_and_values():
    # reference deconv: H_out = (H-1)*s - 2p + k
    x = np.ones((1, 1, 4, 4), np.float32)
    for p, want in [(0, 9), (1, 7)]:
        main, startup = Program(), Program()
        with fluid.program_guard(main, startup):
            xv = fluid.layers.data("x", shape=[1, 4, 4], dtype="float32")
            out = fluid.layers.conv2d_transpose(
                xv, num_filters=1, filter_size=3, stride=2, padding=p,
                param_attr=fluid.ParamAttr(
                    name="w_dc_%d" % p,
                    initializer=fluid.initializer.Constant(1.0)),
                bias_attr=False)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        (res,) = exe.run(main, feed={"x": x}, fetch_list=[out])
        res = np.asarray(res)
        assert res.shape == (1, 1, want, want), (p, res.shape)
        # each output = count of contributing inputs; corner of p=0 is 1
        if p == 0:
            np.testing.assert_allclose(res[0, 0, 0, 0], 1.0)
            np.testing.assert_allclose(res[0, 0, 2, 2], 4.0)


def test_conv3d_transpose_expands():
    x = np.ones((1, 1, 3, 3, 3), np.float32)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        xv = fluid.layers.data("x", shape=[1, 3, 3, 3], dtype="float32")
        out = fluid.layers.conv3d_transpose(
            xv, num_filters=1, filter_size=3, stride=2,
            bias_attr=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    (res,) = exe.run(main, feed={"x": x}, fetch_list=[out])
    # (3-1)*2 - 0 + 3 = 7
    assert np.asarray(res).shape == (1, 1, 7, 7, 7)


def test_pool2d_pool3d_ceil_mode():
    x = np.random.RandomState(10).randn(1, 1, 5, 5).astype(np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[1, 5, 5], dtype="float32")
        c = fluid.layers.pool2d(xv, pool_size=2, pool_stride=2,
                                pool_type="max", ceil_mode=True)
        f = fluid.layers.pool2d(xv, pool_size=2, pool_stride=2,
                                pool_type="max", ceil_mode=False)
        return [c, f]

    c, f = _run(build, {"x": x})
    assert c.shape == (1, 1, 3, 3)   # ceil((5-2)/2)+1 = 3
    assert f.shape == (1, 1, 2, 2)
    np.testing.assert_allclose(c[0, 0, 2, 2], x[0, 0, 4, 4])  # partial win

    x3 = np.random.RandomState(11).randn(1, 1, 5, 5, 5).astype(np.float32)

    def build3():
        xv = fluid.layers.data("x", shape=[1, 5, 5, 5], dtype="float32")
        c = fluid.layers.pool3d(xv, pool_size=2, pool_stride=2,
                                pool_type="avg", ceil_mode=True)
        return [c]

    (c3,) = _run(build3, {"x": x3})
    assert c3.shape == (1, 1, 3, 3, 3)
    # last cell averages only the single valid element
    np.testing.assert_allclose(c3[0, 0, 2, 2, 2], x3[0, 0, 4, 4, 4],
                               atol=1e-6)


def test_affine_channel_defaults_and_nhwc():
    x = np.random.RandomState(12).randn(1, 2, 2, 3).astype(np.float32)

    def build():
        xv = fluid.layers.data("x", shape=[2, 2, 3], dtype="float32")
        plain = fluid.layers.affine_channel(xv)   # no scale/bias: identity
        s = fluid.layers.data("s", shape=[3], dtype="float32",
                              append_batch_size=False)
        nhwc = fluid.layers.affine_channel(xv, scale=s,
                                           data_layout="NHWC")
        return [plain, nhwc]

    scale = np.array([1.0, 2.0, 3.0], np.float32)
    plain, nhwc = _run(build, {"x": x, "s": scale})
    np.testing.assert_allclose(plain, x, atol=1e-6)
    np.testing.assert_allclose(nhwc, x * scale[None, None, None, :],
                               atol=1e-6)


def test_crop_with_tensor_offsets():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    offs = np.array([1, 0, 1], np.int32)

    def build():
        xv = fluid.layers.data("x", shape=[2, 3, 4], dtype="float32",
                               append_batch_size=False)
        ov = fluid.layers.data("o", shape=[3], dtype="int32",
                               append_batch_size=False)
        return [fluid.layers.crop(xv, shape=[1, 2, 2], offsets=ov)]

    (out,) = _run(build, {"x": x, "o": offs})
    np.testing.assert_allclose(out, x[1:2, 0:2, 1:3])
