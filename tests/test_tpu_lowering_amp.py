"""Off-chip TPU-lowering guards for AMP-mode recurrent programs.

Under bf16 AMP the activations are bf16 while weights stay fp32
masters, so an RNN scan body promotes to fp32 — a carry initialized at
the activation dtype then trips lax.scan's carry-type check. This was
invisible to the CPU suite (AMP only engages on TPU in the benchmarks)
until the cross-platform jax.export sweep (tools/check_tpu_lowering.py)
caught it on machine_translation. These are the fast in-suite guards.
"""

import numpy as np
import pytest

import jax

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import functionalizer


def _export_for_tpu(main, startup, feed_specs, loss):
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        fluid.Executor(fluid.CPUPlace()).run(startup)
        sn = tuple(functionalizer.persistable_names(main))
        state = {n: scope.get(n) for n in sn if scope.get(n) is not None}
    step_fn = functionalizer.build_step_fn(
        main, tuple(sorted(feed_specs)), (loss.name,),
        tuple(state.keys()))
    return functionalizer.export_step_for_tpu(step_fn, state, feed_specs)


@pytest.mark.parametrize("cell", ["lstm", "gru"])
def test_amp_dynamic_rnn_lowers_for_tpu(cell):
    fluid.set_amp(True)
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32",
                                  lod_level=1)
            fc = fluid.layers.fc(input=x, size=16 * (4 if cell == "lstm"
                                                     else 3))
            if cell == "lstm":
                h, c = fluid.layers.dynamic_lstm(input=fc, size=16 * 4)
            else:
                h = fluid.layers.dynamic_gru(input=fc, size=16)
            pool = fluid.layers.sequence_pool(h, pool_type="max")
            loss = fluid.layers.mean(fluid.layers.fc(input=pool, size=1))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        # padded ragged feed: dense [B, T, 8] + @LOD_LEN companion
        feed_specs = {
            "x": ((4, 16, 8), np.float32),
            "x" + functionalizer.LOD_LEN_SUFFIX: ((4,), np.int32),
        }
        exp = _export_for_tpu(main, startup, feed_specs, loss)
        assert len(exp.mlir_module_serialized) > 0
    finally:
        fluid.set_amp(False)


def test_amp_dynamic_rnn_block_lowers_for_tpu():
    """DynamicRNN (the generic `recurrent` op): the user block's fc
    promotes against bf16 boot states — the carry must stay stable."""
    fluid.set_amp(True)
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[8], dtype="float32",
                                  lod_level=1)
            boot = fluid.layers.data("boot", shape=[16], dtype="float32")
            rnn = fluid.layers.DynamicRNN()
            with rnn.block():
                step = rnn.step_input(x)
                mem = rnn.memory(init=boot)
                nxt = fluid.layers.fc(input=[step, mem], size=16,
                                      act="tanh")
                rnn.update_memory(mem, nxt)
                rnn.output(nxt)
            out = rnn()
            pool = fluid.layers.sequence_pool(out, pool_type="last")
            loss = fluid.layers.mean(fluid.layers.fc(input=pool, size=1))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        feed_specs = {
            "x": ((4, 16, 8), np.float32),
            "x" + functionalizer.LOD_LEN_SUFFIX: ((4,), np.int32),
            "boot": ((4, 16), np.float32),
        }
        exp = _export_for_tpu(main, startup, feed_specs, loss)
        assert len(exp.mlir_module_serialized) > 0
    finally:
        fluid.set_amp(False)
