"""Op attribute defaults match the reference OpMakers.

Parses every reference operator .cc for AddAttr(...).SetDefault(...) and
every repo lowering for ctx.attr("name", default), then compares where
both exist. A wrong default only bites programs built WITHOUT the attr
(raw construction, loaded older programs) — exactly the case no layer
test exercises — so this cross-check is its own test. The r05 audit
found 8 real mismatches this way (edit_distance.normalized,
lstm-family use_peepholes, sequence_conv contextStart,
mine_hard_examples.neg_pos_ratio, prior_box clip/flip,
roi_perspective_transform sizes).
"""

import glob
import os
import re

import pytest

REF = "/root/reference/paddle/fluid/operators"
REPO = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddle_tpu", "ops")

# cosmetic or deliberate differences, verified by hand (see module
# docstrings at the op lowerings)
ALLOW = {
    ("affine_channel", "data_layout"),   # AnyLayout == NCHW behavior
    ("depthwise_conv2d_transpose", "data_format"),  # same AnyLayout case
    ("fill", "dtype"),                   # proto enum spelled via core
    ("print", "print_phase"),            # kBoth constant == "both"
    ("lookup_table", "padding_idx"),     # kNoPadding constant == -1
    ("gru_unit", "activation"),          # C++ enum index vs name string
    ("gru_unit", "gate_activation"),
}


def _norm(v):
    v = v.strip().rstrip("fL")
    v = re.sub(r"static_cast<[^>]*>\(", "", v).strip("()")
    v = {"true": "True", "false": "False"}.get(v, v)
    try:
        return repr(float(v))
    except ValueError:
        return v.strip('"')


@pytest.mark.skipif(not os.path.isdir(REF),
                    reason="reference tree not mounted")
def test_defaults_match_reference():
    ref = {}
    for cc in glob.glob(REF + "/**/*.cc", recursive=True):
        try:
            s = open(cc, errors="ignore").read()
        except OSError:
            continue
        ops = re.findall(r"REGISTER_OPERATOR\(\s*(\w+)", s)
        attrs = {m.group(1): m.group(2).strip() for m in re.finditer(
            r'AddAttr<[^>]+>\(\s*"(\w+)"[^;]*?SetDefault\(([^)]*)\)',
            s, re.S)}
        for op in ops:
            ref.setdefault(op, {}).update(attrs)

    bad = []
    for py in glob.glob(REPO + "/*.py"):
        s = open(py).read()
        blocks = re.split(r'@register_op\(\s*"(\w+)"', s)
        for i in range(1, len(blocks) - 1, 2):
            op, body = blocks[i], blocks[i + 1]
            if op not in ref:
                continue
            for m in re.finditer(
                    r'ctx\.attr\(\s*"(\w+)"\s*,\s*([^)]+)\)', body):
                a, dv = m.group(1), m.group(2)
                rv = ref[op].get(a)
                if rv is None or (op, a) in ALLOW:
                    continue
                if _norm(rv) != _norm(dv):
                    bad.append((op, a, rv.strip(), dv.strip()))
    assert not bad, "op attr defaults diverge from the reference:\n%s" % (
        "\n".join("  %s.%s: ref=%s repo=%s" % t for t in sorted(bad)))
