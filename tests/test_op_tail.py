"""Op-tail coverage (VERDICT r2 task #5): lstmp, attention_lstm,
fusion_lstm/gru, hash, sequence_erase, ragged sequence_expand, dynamic
sequence_mask, grouped conv2d/3d_transpose, unique_with_counts, nce
custom_dist, ModelAverage. Numeric references: torch (CPU) for the conv
transposes, hand-rolled numpy scans for the RNs, np.unique for uniques."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.ops as ops
from paddle_tpu.ops.registry import ExecContext
from paddle_tpu.fluid.lod import create_lod_tensor


class _FakeOp:
    def __init__(self, type, attrs=None, inputs=None, outputs=None, uid=0):
        self.type = type
        self.attrs = attrs or {}
        self.inputs = inputs or {}
        self.outputs = outputs or {}
        self.uid = uid


def run_op(type, inputs, attrs=None, lod=None):
    """Directly invoke a lowering with concrete arrays (OpTest-style).
    A list/tuple input value feeds a multi-tensor slot (e.g.
    sequence_concat's X); `lod` values follow the same convention."""
    import jax.numpy as jnp

    def dev(v):
        if isinstance(v, (list, tuple)):
            return [jnp.asarray(x) for x in v]
        return [jnp.asarray(v)]
    vals = {k: dev(v) for k, v in inputs.items()}
    if lod:
        for k, lens in lod.items():
            vals[k + "@LOD_LEN"] = dev(lens)
    op = _FakeOp(type, attrs=dict(attrs or {}),
                 inputs={k: [k + "_%d" % i for i in range(len(vals[k]))]
                         if len(vals[k]) > 1 else [k] for k in inputs})
    od = ops.get_op_def(type)
    return ops.call_lower(od, ExecContext(op, vals))


# ---------------------------------------------------------------------------
# sequence ops
# ---------------------------------------------------------------------------

def test_sequence_erase():
    x = np.array([[2, 1, 3, 1, 5], [1, 1, 9, 0, 0]], np.int64)
    lens = np.array([5, 3], np.int32)
    out = run_op("sequence_erase", {"X": x}, {"tokens": [1]},
                 lod={"X": lens})
    np.testing.assert_array_equal(np.asarray(out["Out"]),
                                  [[2, 3, 5, 0, 0], [9, 0, 0, 0, 0]])
    np.testing.assert_array_equal(np.asarray(out["Out@LOD_LEN"]), [3, 1])


def test_sequence_mask_dynamic_maxlen():
    x = np.array([2, 4, 1], np.int64)
    out = run_op("sequence_mask", {"X": x}, {"maxlen": -1})
    y = np.asarray(out["Y"])
    assert y.shape == (3, 4)
    np.testing.assert_array_equal(
        y, [[1, 1, 0, 0], [1, 1, 1, 1], [1, 0, 0, 0]])


def test_sequence_expand_ragged_static_multiple():
    x = np.arange(12, dtype=np.float32).reshape(2, 3, 2)
    xlens = np.array([3, 2], np.int32)
    y = np.zeros((4, 3, 1), np.float32)    # By = 2*Bx -> k=2
    ylens = np.array([2, 3, 1, 2], np.int32)
    out = run_op("sequence_expand", {"X": x, "Y": y},
                 lod={"X": xlens, "Y": ylens})
    o = np.asarray(out["Out"])
    lens = np.asarray(out["Out@LOD_LEN"])
    # reference semantics (sequence_expand_op.h:114 — each repeat keeps
    # x_i's own length): out lens are X's lengths repeated k=2 times
    np.testing.assert_array_equal(lens, [3, 3, 2, 2])
    # rows 0,1 replicate x[0]; rows 2,3 replicate x[1]
    np.testing.assert_allclose(o[0, :3], x[0, :3])
    np.testing.assert_allclose(o[1, :3], x[0, :3])
    np.testing.assert_allclose(o[2, :2], x[1, :2])
    np.testing.assert_allclose(o[3, :2], x[1, :2])


# ---------------------------------------------------------------------------
# hash / unique
# ---------------------------------------------------------------------------

def test_hash_deterministic_in_range():
    x = np.random.RandomState(0).randint(0, 10000, (7, 2)).astype(np.int64)
    out = run_op("hash", {"X": x}, {"num_hash": 4, "mod_by": 1000})
    o = np.asarray(out["Out"])
    assert o.shape == (7, 4, 1)
    assert o.min() >= 0 and o.max() < 1000
    o2 = np.asarray(run_op("hash", {"X": x},
                           {"num_hash": 4, "mod_by": 1000})["Out"])
    np.testing.assert_array_equal(o, o2)            # deterministic
    assert not np.array_equal(o[:, 0], o[:, 1])     # seeds differ
    # identical rows hash identically
    x2 = np.vstack([x[:1], x[:1]])
    h2 = np.asarray(run_op("hash", {"X": x2},
                           {"num_hash": 2, "mod_by": 1000})["Out"])
    np.testing.assert_array_equal(h2[0], h2[1])


def test_unique_with_counts():
    x = np.array([2, 3, 3, 1, 5, 2, 2], np.int64)
    out = run_op("unique_with_counts", {"X": x}, {})
    uniq = np.asarray(out["Out"])
    index = np.asarray(out["Index"])
    count = np.asarray(out["Count"])
    ref_u, ref_i, ref_c = np.unique(x, return_inverse=True,
                                    return_counts=True)
    np.testing.assert_array_equal(uniq, ref_u)
    np.testing.assert_array_equal(index, ref_i)
    np.testing.assert_array_equal(count, ref_c)
    np.testing.assert_array_equal(uniq[index], x)


# ---------------------------------------------------------------------------
# grouped conv transposes vs torch
# ---------------------------------------------------------------------------

def test_grouped_conv2d_transpose_matches_torch():
    import torch
    import torch.nn.functional as F
    rng = np.random.RandomState(1)
    x = rng.randn(2, 4, 5, 5).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)  # [in_c, out_c/g, kh, kw]
    for groups, stride, pad in [(2, 2, 1), (4, 1, 0)]:
        out = run_op("conv2d_transpose", {"Input": x, "Filter": w},
                     {"strides": [stride, stride], "paddings": [pad, pad],
                      "groups": groups})
        ref = F.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                 stride=stride, padding=pad,
                                 groups=groups).numpy()
        np.testing.assert_allclose(np.asarray(out["Output"]), ref,
                                   atol=1e-4, err_msg="groups=%d" % groups)


def test_grouped_conv3d_transpose_matches_torch():
    import torch
    import torch.nn.functional as F
    rng = np.random.RandomState(2)
    x = rng.randn(1, 4, 3, 4, 4).astype(np.float32)
    w = rng.randn(4, 2, 2, 2, 2).astype(np.float32)
    out = run_op("conv3d_transpose", {"Input": x, "Filter": w},
                 {"strides": [1, 1, 1], "paddings": [0, 0, 0],
                  "groups": 2})
    ref = F.conv_transpose3d(torch.tensor(x), torch.tensor(w),
                             groups=2).numpy()
    np.testing.assert_allclose(np.asarray(out["Output"]), ref, atol=1e-4)


# ---------------------------------------------------------------------------
# RNN tail: lstmp / fusion_lstm / fusion_gru / attention_lstm
# ---------------------------------------------------------------------------

def _np_lstmp(x, lens, w, w_proj, bias, D, P):
    B, T, _ = x.shape
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    r = np.zeros((B, P), np.float32)
    c = np.zeros((B, D), np.float32)
    projs = np.zeros((B, T, P), np.float32)
    for t in range(T):
        gates = x[:, t] + r @ w + bias[:, :4 * D]
        # reference gate columns {c, i, f, o} (lstm_cpu_kernel.h:44-47)
        cand, i, f, o = np.split(gates, 4, axis=-1)
        i, f, o = sig(i), sig(f), sig(o)
        c_new = f * c + i * np.tanh(cand)
        h_new = o * np.tanh(c_new)
        r_new = np.tanh(h_new @ w_proj)
        mt = (t < lens).astype(np.float32)[:, None]
        r = mt * r_new + (1 - mt) * r
        c = mt * c_new + (1 - mt) * c
        projs[:, t] = r * mt
    return projs


def test_lstmp_matches_numpy():
    rng = np.random.RandomState(3)
    B, T, D, P = 3, 5, 4, 2
    x = rng.randn(B, T, 4 * D).astype(np.float32) * 0.5
    w = rng.randn(P, 4 * D).astype(np.float32) * 0.3
    w_proj = rng.randn(D, P).astype(np.float32) * 0.3
    bias = rng.randn(1, 4 * D).astype(np.float32) * 0.1
    lens = np.array([5, 3, 1], np.int32)
    out = run_op("lstmp", {"Input": x, "Weight": w, "ProjWeight": w_proj,
                           "Bias": bias},
                 {"use_peepholes": False}, lod={"Input": lens})
    ref = _np_lstmp(x, lens, w, w_proj, bias, D, P)
    np.testing.assert_allclose(np.asarray(out["Projection"]), ref,
                               atol=1e-5)


def test_fusion_lstm_equals_fc_plus_lstm():
    rng = np.random.RandomState(4)
    B, T, M, D = 2, 4, 3, 5
    x = rng.randn(B, T, M).astype(np.float32)
    wx = rng.randn(M, 4 * D).astype(np.float32) * 0.4
    wh = rng.randn(D, 4 * D).astype(np.float32) * 0.4
    bias = rng.randn(1, 4 * D).astype(np.float32) * 0.1
    lens = np.array([4, 2], np.int32)
    fused = run_op("fusion_lstm", {"X": x, "WeightX": wx, "WeightH": wh,
                                   "Bias": bias},
                   {"use_peepholes": False}, lod={"X": lens})
    plain = run_op("lstm", {"Input": np.einsum("btm,mh->bth", x, wx) +
                            bias.reshape(1, 1, -1) * 0.0,
                            "Weight": wh, "Bias": bias},
                   {"use_peepholes": False}, lod={"Input": lens})
    np.testing.assert_allclose(np.asarray(fused["Hidden"]),
                               np.asarray(plain["Hidden"]), atol=1e-5)


def test_fusion_gru_equals_fc_plus_gru():
    rng = np.random.RandomState(5)
    B, T, M, D = 2, 4, 3, 5
    x = rng.randn(B, T, M).astype(np.float32)
    wx = rng.randn(M, 3 * D).astype(np.float32) * 0.4
    wh = rng.randn(D, 3 * D).astype(np.float32) * 0.4
    bias = rng.randn(1, 3 * D).astype(np.float32) * 0.1
    lens = np.array([4, 3], np.int32)
    fused = run_op("fusion_gru", {"X": x, "WeightX": wx, "WeightH": wh,
                                  "Bias": bias}, {}, lod={"X": lens})
    xx = np.einsum("btm,mh->bth", x, wx)
    plain = run_op("gru", {"Input": xx, "Weight": wh, "Bias": bias},
                   {}, lod={"Input": lens})
    np.testing.assert_allclose(np.asarray(fused["Hidden"]),
                               np.asarray(plain["Hidden"]), atol=1e-5)


def test_attention_lstm_shapes_and_masking():
    rng = np.random.RandomState(6)
    B, T, M, D = 2, 5, 3, 4
    x = rng.randn(B, T, M).astype(np.float32) * 0.5
    c0 = rng.randn(B, D).astype(np.float32) * 0.3
    att_w = rng.randn(M + D, 1).astype(np.float32) * 0.4
    lstm_w = rng.randn(D + M, 4 * D).astype(np.float32) * 0.3
    lstm_b = rng.randn(1, 4 * D).astype(np.float32) * 0.1
    lens = np.array([5, 2], np.int32)
    out = run_op("attention_lstm",
                 {"X": x, "C0": c0, "AttentionWeight": att_w,
                  "LSTMWeight": lstm_w, "LSTMBias": lstm_b},
                 {}, lod={"X": lens})
    h = np.asarray(out["Hidden"])
    assert h.shape == (B, T, D)
    # padded steps of the short sequence must be zeroed
    assert np.all(h[1, 2:] == 0)
    assert np.all(np.isfinite(h))
    # changing x BEYOND a sequence's length must not change its outputs
    x2 = x.copy()
    x2[1, 2:] += 100.0
    out2 = run_op("attention_lstm",
                  {"X": x2, "C0": c0, "AttentionWeight": att_w,
                   "LSTMWeight": lstm_w, "LSTMBias": lstm_b},
                  {}, lod={"X": lens})
    np.testing.assert_allclose(np.asarray(out2["Hidden"])[1, :2],
                               h[1, :2], atol=1e-5)


# ---------------------------------------------------------------------------
# nce custom_dist
# ---------------------------------------------------------------------------

def test_nce_custom_dist_respects_support():
    rng = np.random.RandomState(7)
    B, D, C = 6, 4, 10
    x = rng.randn(B, D).astype(np.float32)
    label = rng.randint(0, 3, (B, 1)).astype(np.int64)
    w = rng.randn(C, D).astype(np.float32)
    b = rng.randn(C, 1).astype(np.float32)
    # probability mass only on classes 0..4
    probs = [0.2] * 5 + [0.0] * 5
    out = run_op("nce", {"Input": x, "Label": label, "Weight": w,
                         "Bias": b},
                 {"num_total_classes": C, "num_neg_samples": 20,
                  "sampler": 2, "custom_dist_probs": probs})
    cost = np.asarray(out["Cost"])
    samples = np.asarray(out["SampleLabels"])
    assert np.all(np.isfinite(cost)) and cost.shape == (B, 1)
    neg = samples[:, 1:]                      # first col = true label
    assert neg.max() < 5, "sampled a zero-probability class"


# ---------------------------------------------------------------------------
# ModelAverage end to end
# ---------------------------------------------------------------------------

def test_model_average_applies_window_mean():
    from paddle_tpu.fluid.framework import Program
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        ma = fluid.optimizer.ModelAverage(
            average_window_rate=1.0, min_average_window=1,
            max_average_window=100)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(8)
    pname = [p.name for p in main.global_block().all_parameters()
             if "w" in p.name][0]
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        history = []
        for i in range(6):
            feed = {"x": rng.randn(8, 3).astype(np.float32),
                    "y": rng.randn(8, 1).astype(np.float32)}
            exe.run(main, feed=feed, fetch_list=[loss])
            history.append(np.asarray(scope.get(pname)))
        trained = np.asarray(scope.get(pname))
        with ma.apply(exe):
            averaged = np.asarray(scope.get(pname))
            # window covers all 6 updates: averaged == mean of the
            # post-update parameter trajectory
            np.testing.assert_allclose(averaged,
                                       np.mean(history, axis=0), atol=1e-5)
        # restored afterwards
        np.testing.assert_allclose(np.asarray(scope.get(pname)), trained,
                                   atol=0)


# ---------------------------------------------------------------------------
# distributed lookup-table remote prefetch (prefetch_op.cc + the
# transpiler's distribute_lookup_table path): the table is row-sharded
# round-robin across 2 pservers; a training loop that prefetches rows,
# computes a loss, and pushes sparse row grads must track a local
# full-table run exactly.
# ---------------------------------------------------------------------------

def test_distributed_lookup_table_prefetch_parity():
    from paddle_tpu.distributed.rpc import (VariableServer, RPCClient,
                                            wait_server_ready)
    from paddle_tpu.fluid.framework import Program

    rng = np.random.RandomState(9)
    V, D = 10, 4
    table = rng.randn(V, D).astype(np.float32)
    LR = 0.5

    servers = [VariableServer("127.0.0.1:0").start() for _ in range(2)]
    for s in servers:
        wait_server_ready([s.endpoint])
    eps = [s.endpoint for s in servers]
    cli = RPCClient()
    try:
        # shard the table: server s holds rows {id : id % 2 == s} at
        # local index id // 2
        for s_i, srv in enumerate(servers):
            rows = table[np.arange(V) % 2 == s_i]
            cli.put_var(srv.endpoint, "emb", rows)

        # program: prefetch rows for the id batch, then push grads back
        main, startup = Program(), Program()
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data("ids", shape=[1], dtype="int64")
            gb = main.global_block()
            rows_v = gb.create_var(name="rows", dtype="float32",
                                   shape=[-1, D])
            gb.append_op(type="prefetch", inputs={"X": [ids.name]},
                         outputs={"Out": [rows_v.name]},
                         attrs={"table_name": "emb", "epmap": eps},
                         infer_shape=False)
        exe = fluid.Executor(fluid.CPUPlace())

        local = table.copy()
        id_batches = [rng.randint(0, V, (6,)).astype(np.int64)
                      for _ in range(3)]
        with fluid.scope_guard(fluid.Scope()):
            for batch in id_batches:
                (rows,) = exe.run(main, feed={"ids": batch.reshape(-1, 1)},
                                  fetch_list=["rows"])
                rows = np.asarray(rows)
                np.testing.assert_allclose(rows, local[batch], atol=1e-6,
                                           err_msg="prefetch rows wrong")
                # loss = 0.5*sum(rows^2) -> grad = rows; push to servers
                grad = rows
                from paddle_tpu.distributed.rpc import global_client
                c = global_client()
                ns = len(eps)
                for s_i, ep in enumerate(eps):
                    sel = np.nonzero(batch % ns == s_i)[0]
                    if sel.size:
                        c.sparse_push(ep, "emb", batch[sel], grad[sel],
                                      lr=LR, num_shards=ns)
                # local reference applies the same sparse SGD
                np.subtract.at(local, batch, LR * grad)
        # final shards match the local table
        for s_i, srv in enumerate(servers):
            got = np.asarray(srv.store["emb"])
            np.testing.assert_allclose(got, local[np.arange(V) % 2 == s_i],
                                       atol=1e-5)
    finally:
        for s in servers:
            cli.send_exit(s.endpoint)
            s.stop()
        cli.close()


def test_lstmp_is_reverse():
    """is_reverse must scan the valid prefix backwards (regression: the
    attr was silently ignored). For a full-length sequence, reversed
    lstmp(x) == reverse(lstmp(reverse(x)))."""
    rng = np.random.RandomState(12)
    B, T, D, P = 2, 4, 3, 2
    x = rng.randn(B, T, 4 * D).astype(np.float32) * 0.5
    w = rng.randn(P, 4 * D).astype(np.float32) * 0.3
    w_proj = rng.randn(D, P).astype(np.float32) * 0.3
    bias = rng.randn(1, 4 * D).astype(np.float32) * 0.1
    lens = np.array([T, T], np.int32)
    rev = run_op("lstmp", {"Input": x, "Weight": w, "ProjWeight": w_proj,
                           "Bias": bias},
                 {"use_peepholes": False, "is_reverse": True},
                 lod={"Input": lens})
    fwd_of_flipped = run_op(
        "lstmp", {"Input": x[:, ::-1].copy(), "Weight": w,
                  "ProjWeight": w_proj, "Bias": bias},
        {"use_peepholes": False}, lod={"Input": lens})
    np.testing.assert_allclose(
        np.asarray(rev["Projection"]),
        np.asarray(fwd_of_flipped["Projection"])[:, ::-1], atol=1e-5)
    # and it differs from the forward scan
    fwd = run_op("lstmp", {"Input": x, "Weight": w, "ProjWeight": w_proj,
                           "Bias": bias},
                 {"use_peepholes": False}, lod={"Input": lens})
    assert not np.allclose(np.asarray(rev["Projection"]),
                           np.asarray(fwd["Projection"]))


# ---------------------------------------------------------------------------
# remaining fused/ family (reference operators/fused/)
# ---------------------------------------------------------------------------

def test_fusion_seqconv_eltadd_relu_matches_unfused():
    """fused seqconv+bias+relu == sequence_conv -> +bias -> relu chain."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32", lod_level=1)
        ref = fluid.layers.sequence_conv(
            x, num_filters=5, filter_size=3,
            param_attr=fluid.ParamAttr(name="scw"))
        w = main.global_block().var("scw")
        helper_out = main.global_block().create_var(
            name="fused_out", dtype="float32", lod_level=1)
        col = main.global_block().create_var(
            name="fused_col", dtype="float32")
        bvar = fluid.layers.fill_constant([1, 5], "float32", 0.25)
        main.global_block().append_op(
            type="fusion_seqconv_eltadd_relu",
            inputs={"X": [x.name], "Filter": [w.name],
                    "Bias": [bvar.name]},
            outputs={"Out": [helper_out.name], "ColMat": [col.name]},
            attrs={"contextLength": 3, "contextStart": -1},
            infer_shape=False)
        ref_act = fluid.layers.relu(
            fluid.layers.elementwise_add(ref, bvar))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    data = rng.randn(7, 4).astype("float32")
    lod = create_lod_tensor(data, [[3, 4]])
    fused, ref_v = exe.run(main, feed={"x": lod},
                           fetch_list=["fused_out", ref_act])
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref_v),
                               atol=1e-5)


def test_fusion_seqexpand_concat_fc():
    """seq + per-sequence row broadcast + concat + fc + relu, vs numpy."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3], dtype="float32", lod_level=1)
        s = fluid.layers.data("s", shape=[2], dtype="float32")
        wv = fluid.layers.fill_constant([5, 4], "float32", 0.1)
        bv = fluid.layers.fill_constant([1, 4], "float32", 0.5)
        out = main.global_block().create_var(
            name="secf_out", dtype="float32", lod_level=1)
        fco = main.global_block().create_var(
            name="secf_fco", dtype="float32")
        main.global_block().append_op(
            type="fusion_seqexpand_concat_fc",
            inputs={"X": [x.name, s.name], "FCWeight": [wv.name],
                    "FCBias": [bv.name]},
            outputs={"Out": [out.name], "FCOut": [fco.name]},
            attrs={"fc_activation": "relu"}, infer_shape=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(1)
    data = rng.randn(5, 3).astype("float32")
    srows = rng.randn(2, 2).astype("float32")
    lod = create_lod_tensor(data, [[2, 3]])
    (res,) = exe.run(main, feed={"x": lod, "s": srows},
                     fetch_list=["secf_out"])
    res = np.asarray(res)
    # manual: rows of seq i concat srows[i], @ 0.1 + 0.5, relu
    flat = []
    for i, (a, b) in enumerate([(0, 2), (2, 5)]):
        for r in range(a, b):
            cat = np.concatenate([data[r], srows[i]])
            flat.append(np.maximum(cat @ np.full((5, 4), 0.1) + 0.5, 0))
    np.testing.assert_allclose(res.reshape(-1, 4)[:len(flat)],
                               np.array(flat), atol=1e-5)


def test_fused_embedding_fc_lstm_matches_fusion_lstm():
    """gathering a pre-folded embedding == embedding + fc + fusion_lstm."""
    V, H = 9, 4
    rng = np.random.RandomState(2)
    emb4h = rng.randn(V, 4 * H).astype("float32") * 0.1
    wh = rng.randn(H, 4 * H).astype("float32") * 0.1
    bias = rng.randn(1, 4 * H).astype("float32") * 0.1

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[1], dtype="int64",
                                lod_level=1)
        embv = fluid.layers.assign(emb4h)
        whv = fluid.layers.assign(wh)
        bv = fluid.layers.assign(bias)
        hid = main.global_block().create_var(
            name="fel_hid", dtype="float32", lod_level=1)
        cell = main.global_block().create_var(
            name="fel_cell", dtype="float32", lod_level=1)
        xx = main.global_block().create_var(
            name="fel_xx", dtype="float32")
        main.global_block().append_op(
            type="fused_embedding_fc_lstm",
            inputs={"Ids": [ids.name], "Embeddings": [embv.name],
                    "WeightH": [whv.name], "Bias": [bv.name]},
            outputs={"Hidden": [hid.name], "Cell": [cell.name],
                     "XX": [xx.name]},
            attrs={"use_peepholes": False}, infer_shape=False)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    seqs = [[1, 3, 5], [2, 8]]
    flat = np.array([i for s in seqs for i in s], np.int64).reshape(-1, 1)
    lod = create_lod_tensor(flat, [[3, 2]])
    (hv,) = exe.run(main, feed={"ids": lod}, fetch_list=["fel_hid"])
    hv = np.asarray(hv)

    # reference chain: one-hot @ emb4h == gather; lstm via fusion_lstm
    main2, startup2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, startup2):
        x = fluid.layers.data("x", shape=[4 * H], dtype="float32",
                              lod_level=1)
        whv = fluid.layers.assign(wh)
        bv = fluid.layers.assign(bias)
        hid2 = main2.global_block().create_var(
            name="fl_hid", dtype="float32", lod_level=1)
        cell2 = main2.global_block().create_var(
            name="fl_cell", dtype="float32", lod_level=1)
        xx2 = main2.global_block().create_var(
            name="fl_xx", dtype="float32")
        main2.global_block().append_op(
            type="fusion_lstm",
            inputs={"X": [x.name],
                    "WeightX": [fluid.layers.assign(
                        np.eye(4 * H, dtype=np.float32)).name],
                    "WeightH": [whv.name], "Bias": [bv.name]},
            outputs={"Hidden": [hid2.name], "Cell": [cell2.name],
                     "XX": [xx2.name]},
            attrs={"use_peepholes": False}, infer_shape=False)
    exe2 = fluid.Executor(fluid.CPUPlace())
    exe2.run(startup2)
    xflat = emb4h[flat.reshape(-1)]
    lod2 = create_lod_tensor(xflat, [[3, 2]])
    (hv2,) = exe2.run(main2, feed={"x": lod2}, fetch_list=["fl_hid"])
    np.testing.assert_allclose(hv, np.asarray(hv2), atol=1e-5)
