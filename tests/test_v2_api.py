"""Legacy v2 API layer (SURVEY §2.8): the paddle.v2 surface —
layer DSL / Parameters / SGD trainer / events / inference — served by the
fluid/XLA substrate. Mirrors the reference's v2 usage contract
(python/paddle/v2/tests/test_layer.py, test_parameters.py and the v2
book demos)."""

import io

import numpy as np
import pytest

import paddle_tpu.v2 as paddle


def _mlp(with_softmax=True):
    images = paddle.layer.data("pixel", paddle.data_type.dense_vector(16))
    label = paddle.layer.data("label", paddle.data_type.integer_value(4))
    hidden = paddle.layer.fc(images, size=8,
                             act=paddle.activation.Tanh())
    out = paddle.layer.fc(hidden, size=4,
                          act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=out, label=label)
    return images, label, out, cost


def _sample_reader(n=64, dim=16, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n, dim).astype(np.float32)
    # learnable rule: class = argmax of 4 fixed random projections
    w = np.random.RandomState(7).randn(dim, classes)
    ys = np.argmax(xs @ w, axis=1).astype(np.int64)

    def reader():
        for i in range(n):
            yield xs[i], int(ys[i])

    return reader


def test_v2_train_decreases_cost_and_fires_events():
    _, _, out, cost = _mlp()
    params = paddle.parameters.create(cost)
    optimizer = paddle.optimizer.Momentum(
        momentum=0.9, learning_rate=0.1,
        regularization=paddle.optimizer.L2Regularization(rate=1e-4))
    trainer = paddle.trainer.SGD(cost=cost, parameters=params,
                                 update_equation=optimizer)
    seen = {"costs": [], "events": set(), "metrics": []}

    def handler(event):
        seen["events"].add(type(event).__name__)
        if isinstance(event, paddle.event.EndIteration):
            seen["costs"].append(event.cost)
            seen["metrics"].append(event.metrics)

    trainer.train(paddle.batch(_sample_reader(), 16), num_passes=6,
                  event_handler=handler)
    assert {"BeginPass", "BeginIteration", "EndIteration",
            "EndPass"} <= seen["events"]
    # cost must drop substantially on a learnable synthetic rule
    assert np.mean(seen["costs"][-4:]) < 0.7 * np.mean(seen["costs"][:4])
    assert "classification_error_evaluator" in seen["metrics"][-1]
    # error rate must improve too
    assert (seen["metrics"][-1]["classification_error_evaluator"]
            < seen["metrics"][0]["classification_error_evaluator"] + 1e-9)


def test_v2_infer_matches_training_topology():
    _, _, out, cost = _mlp()
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(momentum=0.0,
                                                  learning_rate=0.05))
    trainer.train(paddle.batch(_sample_reader(), 16), num_passes=2)
    xs = [(np.ones(16, dtype=np.float32) * 0.1,),
          (np.zeros(16, dtype=np.float32),)]
    probs = paddle.infer(output_layer=out, parameters=params, input=xs)
    assert probs.shape == (2, 4)
    np.testing.assert_allclose(probs.sum(axis=1), np.ones(2), atol=1e-5)
    # the trained parameters actually drive inference: perturbing a weight
    # must change the output
    key = [k for k in params.keys() if "w" in k][0]
    w = params.get(key).copy()
    params.set(key, w + 1.0)
    probs2 = paddle.infer(output_layer=out, parameters=params, input=xs)
    assert not np.allclose(probs, probs2)


def test_v2_parameters_tar_roundtrip_and_shape_check():
    _, _, out, cost = _mlp()
    params = paddle.parameters.create(cost)
    assert len(params.keys()) >= 4  # 2 weights + 2 biases
    buf = io.BytesIO()
    params.to_tar(buf)
    buf.seek(0)
    restored = paddle.parameters.Parameters.from_tar(buf)
    assert sorted(restored.keys()) == sorted(params.keys())
    for k in params.keys():
        np.testing.assert_array_equal(restored.get(k), params.get(k))
    with pytest.raises(ValueError):
        params.set(params.keys()[0],
                   np.zeros((1, 1), dtype=np.float32))
    # init_from_tar overwrites matching entries
    k0 = params.keys()[0]
    params.set(k0, params.get(k0) + 5.0)
    buf.seek(0)
    params.init_from_tar(buf)
    np.testing.assert_array_equal(params.get(k0), restored.get(k0))


def test_v2_parameters_reference_tar_format_interop():
    """The tar layout matches the reference byte-for-byte (ADVICE r3):
    payload header = (version u32, elem_size u32, NUM_ELEMENTS u64) + raw
    fp32 (reference parameters.py:306), plus a '<name>.protobuf'
    ParameterConfig member whose dims field recovers the shape (:348).
    Construct a tar exactly as the reference writer would and load it."""
    import struct
    import tarfile
    rng = np.random.RandomState(0)
    w = rng.randn(3, 5).astype(np.float32)
    b = rng.randn(7).astype(np.float32)
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        def add(name, data):
            info = tarfile.TarInfo(name=name)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
        for name, arr in (("ref_w", w), ("ref_b", b)):
            add(name, struct.pack("<IIQ", 0, 4, arr.size) + arr.tobytes())
            # ParameterConfig: name=1 (len-delim), size=2 (varint),
            # momentum=4 (fixed64, must be SKIPPED), dims=9 (varints)
            conf = (b"\x0a" + bytes([len(name)]) + name.encode()
                    + b"\x10" + bytes([arr.size])
                    + b"\x21" + struct.pack("<d", 0.9)
                    + b"".join(b"\x48" + bytes([d]) for d in arr.shape))
            add(name + ".protobuf", conf)
    buf.seek(0)
    params = paddle.parameters.Parameters.from_tar(buf)
    assert sorted(params.keys()) == ["ref_b", "ref_w"]
    np.testing.assert_array_equal(params.get("ref_w"), w)
    np.testing.assert_array_equal(params.get("ref_b"), b)
    assert params.get_shape("ref_w") == (3, 5)
    # ... and our writer emits '.protobuf' members the reference expects
    out = io.BytesIO()
    params.to_tar(out)
    out.seek(0)
    with tarfile.open(fileobj=out, mode="r") as tar:
        names = sorted(m.name for m in tar.getmembers())
    assert names == ["ref_b", "ref_b.protobuf", "ref_w", "ref_w.protobuf"]


def test_v2_parameters_tar_edge_cases():
    import tarfile
    # 0-d parameter survives a round trip with shape () intact
    p = paddle.parameters.Parameters()
    p.set("scalar", np.float32(3.5))
    p.set("vec1", np.ones((1,), np.float32))
    buf = io.BytesIO()
    p.to_tar(buf)
    buf.seek(0)
    r = paddle.parameters.Parameters.from_tar(buf)
    assert r.get_shape("scalar") == ()
    assert r.get_shape("vec1") == (1,)
    buf.seek(0)
    p.init_from_tar(buf)  # must not raise shape mismatch
    # extra non-parameter members are ignored (reference iterates
    # configs, not all members)
    buf2 = io.BytesIO()
    with tarfile.open(fileobj=buf2, mode="w") as tar:
        buf.seek(0)
        with tarfile.open(fileobj=buf, mode="r") as src:
            for m in src.getmembers():
                tar.addfile(m, src.extractfile(m))
        info = tarfile.TarInfo(name="README")
        info.size = 5
        tar.addfile(info, io.BytesIO(b"hello"))
    buf2.seek(0)
    r2 = paddle.parameters.Parameters.from_tar(buf2)
    assert sorted(r2.keys()) == ["scalar", "vec1"]
    # a config without its payload is a loud error, not a None entry
    buf3 = io.BytesIO()
    with tarfile.open(fileobj=buf3, mode="w") as tar:
        conf = b"\x0a\x01w\x10\x04\x48\x02\x48\x02"
        info = tarfile.TarInfo(name="w.protobuf")
        info.size = len(conf)
        tar.addfile(info, io.BytesIO(conf))
    buf3.seek(0)
    with pytest.raises(ValueError, match="missing the payload"):
        paddle.parameters.Parameters.from_tar(buf3)


def test_v2_parameters_rejects_non_model_tar():
    """A tar with no ParameterConfig members (e.g. the pre-round-4 rank
    format) is rejected with a clear error, not misparsed."""
    import tarfile
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        data = b"\x00" * 32
        info = tarfile.TarInfo(name="w")
        info.size = len(data)
        tar.addfile(info, io.BytesIO(data))
    buf.seek(0)
    with pytest.raises(ValueError, match="protobuf"):
        paddle.parameters.Parameters.from_tar(buf)


def test_v2_conv_network_trains():
    images = paddle.layer.data(
        "image", paddle.data_type.dense_vector(64), height=8, width=8)
    label = paddle.layer.data("l", paddle.data_type.integer_value(2))
    conv_pool = paddle.networks.simple_img_conv_pool(
        input=images, filter_size=3, num_filters=4, num_channel=1,
        pool_size=2, pool_stride=2, act=paddle.activation.Relu(),
        conv_padding=1)
    out = paddle.layer.fc(conv_pool, size=2,
                          act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=out, label=label)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.01))

    rng = np.random.RandomState(3)

    def reader():
        for _ in range(32):
            y = rng.randint(0, 2)
            x = rng.randn(64).astype(np.float32) + (2.0 * y - 1.0)
            yield x, y

    costs = []
    trainer.train(
        paddle.batch(reader, 8), num_passes=4,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    assert np.mean(costs[-4:]) < np.mean(costs[:4])
    result = trainer.test(paddle.batch(reader, 8))
    assert np.isfinite(result.cost)


def test_v2_sequence_model_builds_and_trains():
    words = paddle.layer.data(
        "words", paddle.data_type.integer_value_sequence(20))
    label = paddle.layer.data("lbl", paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(words, size=6)
    gru = paddle.networks.simple_gru(input=emb, size=5)
    pooled = paddle.layer.pooling(gru,
                                  pooling_type=paddle.pooling.Max())
    out = paddle.layer.fc(pooled, size=2,
                          act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=out, label=label)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.02))
    rng = np.random.RandomState(5)

    def reader():
        for _ in range(24):
            y = rng.randint(0, 2)
            n = rng.randint(2, 6)
            # class-dependent vocab halves -> learnable
            seq = rng.randint(10 * y, 10 * y + 10, size=n).tolist()
            yield seq, y

    costs = []
    trainer.train(
        paddle.batch(reader, 8), num_passes=3,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    assert all(np.isfinite(c) for c in costs)
    assert np.mean(costs[-3:]) < np.mean(costs[:3])


def test_trainer_config_helpers_dsl():
    import paddle_tpu.trainer_config_helpers as tch

    def net():
        d = tch.data_layer("in", type=paddle.data_type.dense_vector(8))
        h = tch.fc_layer(d, size=4, act=tch.TanhActivation())
        return tch.fc_layer(h, size=2, act=tch.SoftmaxActivation())

    proto = tch.parse_network_config(net)
    assert proto and isinstance(proto, (bytes, str))

    opt = tch.settings(batch_size=32, learning_rate=0.1,
                       learning_method=tch.MomentumOptimizer(momentum=0.9))
    assert opt.learning_rate == 0.1
    cfg = tch.parse_optimizer_config(
        lambda: tch.settings(batch_size=8, learning_rate=0.01))
    assert cfg["batch_size"] == 8


def test_v2_optimizer_lr_schedules_lower():
    opt = paddle.optimizer.Momentum(
        momentum=0.9, learning_rate=0.1, learning_rate_schedule="poly",
        learning_rate_decay_a=0.5, learning_rate_decay_b=0.75)
    _, _, out, cost = _mlp()
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(cost=cost, parameters=params,
                                 update_equation=opt)
    costs = []
    trainer.train(
        paddle.batch(_sample_reader(16), 8), num_passes=1,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    assert costs and all(np.isfinite(c) for c in costs)


def test_v2_op_overloading_and_evaluator():
    import paddle_tpu.v2.op as v2op

    a = paddle.layer.data("a", paddle.data_type.dense_vector(4))
    label = paddle.layer.data("y", paddle.data_type.integer_value(2))
    scaled = 2.0 * a + 1.0          # slope_intercept chain
    neg = -scaled
    s = v2op.tanh(neg)
    out = paddle.layer.fc(s, size=2, act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=out, label=label)
    err = paddle.evaluator.classification_error(input=out, label=label)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params, extra_layers=[err],
        update_equation=paddle.optimizer.Adam(learning_rate=0.01))
    rng = np.random.RandomState(11)

    def reader():
        for _ in range(16):
            y = rng.randint(0, 2)
            yield rng.randn(4).astype(np.float32) + y, y

    costs = []
    trainer.train(
        paddle.batch(reader, 8), num_passes=2,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    assert costs and all(np.isfinite(c) for c in costs)
    # the overloaded arithmetic must actually be in the graph: feeding
    # through infer must equal the manual computation chain
    x = np.full((1, 4), 0.25, dtype=np.float32)
    probs = paddle.infer(output_layer=out, parameters=params,
                        input=[(x[0],)])
    assert probs.shape == (1, 2)


def test_v2_plot_and_data_feeder():
    import os
    os.environ["DISABLE_PLOT"] = "True"
    from paddle_tpu.v2.plot import Ploter

    p = Ploter("train", "test")
    p.append("train", 0, 1.0)
    p.append("train", 1, 0.5)
    p.plot()
    p.reset()
    assert not p.__plot_data__["train"].step

    feeder = paddle.data_feeder.DataFeeder(
        [("img", paddle.data_type.dense_vector(4)),
         ("lbl", paddle.data_type.integer_value(2))],
        feeding={"img": 0, "lbl": 1})
    assert feeder.feed_order == ["img", "lbl"]


def test_v2_recurrent_group_trains_and_matches_memory_semantics():
    """recurrent_group + memory + StaticInput: a custom RNN cell written
    v1-style (reference trainer_config_helpers recurrent_group) trains
    and threads state across timesteps."""
    words = paddle.layer.data(
        "w", paddle.data_type.integer_value_sequence(12))
    ctx_in = paddle.layer.data("ctx", paddle.data_type.dense_vector(3))
    label = paddle.layer.data("y", paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(words, size=4)

    def step(wt, static_ctx):
        mem = paddle.layer.memory(name="rg_h", size=6)
        h = paddle.layer.fc([wt, mem, static_ctx], size=6,
                            act=paddle.activation.Tanh(), name="rg_h")
        return h

    rnn_out = paddle.layer.recurrent_group(
        step=step, input=[emb, paddle.layer.StaticInput(ctx_in)])
    last = paddle.layer.last_seq(rnn_out)
    out = paddle.layer.fc(last, size=2, act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=out, label=label)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))
    rng = np.random.RandomState(9)

    def reader():
        for _ in range(24):
            y = rng.randint(0, 2)
            n = rng.randint(2, 5)
            seq = rng.randint(6 * y, 6 * y + 6, size=n).tolist()
            yield seq, np.zeros(3, dtype=np.float32), y

    costs = []
    trainer.train(
        paddle.batch(reader, 8), num_passes=6,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    assert all(np.isfinite(c) for c in costs)
    assert np.mean(costs[-3:]) < 0.8 * np.mean(costs[:3])
    # inference through the same group works and is deterministic
    probs = paddle.infer(output_layer=out, parameters=params,
                         input=[([1, 2, 3], np.zeros(3, np.float32)),
                                ([7, 8], np.zeros(3, np.float32))])
    assert probs.shape == (2, 2)


def test_v2_recurrent_group_boot_layer_and_reverse():
    seq = paddle.layer.data(
        "s", paddle.data_type.dense_vector_sequence(4))
    boot = paddle.layer.data("boot", paddle.data_type.dense_vector(4))

    def step(xt):
        mem = paddle.layer.memory(name="acc2", size=4,
                                  boot_layer=boot)
        s = paddle.layer.addto([xt, mem], name="acc2")
        return s

    out = paddle.layer.recurrent_group(step=step, input=seq, reverse=True)
    first = paddle.layer.first_seq(out)
    params = paddle.parameters.create(first)
    # reverse accumulation: first position of output (reversed back) holds
    # boot + sum of all timesteps
    import paddle_tpu.v2.inference as v2inf
    inf = v2inf.Inference(parameters=params, output_layer=first)
    x = np.arange(8, dtype=np.float32).reshape(2, 4)
    b = np.full(4, 0.5, dtype=np.float32)
    res = inf.infer(input=[(x, b)])
    np.testing.assert_allclose(
        np.asarray(res)[0], x.sum(0) + 0.5, atol=1e-5)


def test_v2_recurrent_group_outer_reference_is_static_link():
    """A layer referenced inside the step without being declared as an
    input acts as a read-only outer link (v1 semantics), not a rebuilt
    sub-block node."""
    seq = paddle.layer.data(
        "s2", paddle.data_type.dense_vector_sequence(3))
    outer = paddle.layer.data("outer_ctx",
                              paddle.data_type.dense_vector(3))
    outer_scaled = 2.0 * outer        # derived outer layer

    def step(xt):
        return paddle.layer.addto([xt, outer_scaled], name="rg_o")

    out = paddle.layer.recurrent_group(step=step, input=seq)
    first = paddle.layer.first_seq(out)
    params = paddle.parameters.create(first)
    import paddle_tpu.v2.inference as v2inf
    inf = v2inf.Inference(parameters=params, output_layer=first)
    x = np.ones((2, 3), dtype=np.float32)
    c = np.full(3, 1.5, dtype=np.float32)
    res = inf.infer(input=[(x, c)])
    # first timestep: x[0] + 2*outer = 1 + 3 = 4
    np.testing.assert_allclose(np.asarray(res)[0],
                               np.full(3, 4.0), atol=1e-5)


def test_v2_memory_rejects_unsupported_v1_args():
    with pytest.raises(NotImplementedError):
        paddle.layer.memory(name="m", size=4, is_seq=True)
    with pytest.raises(NotImplementedError):
        paddle.layer.memory(name="m", size=4, boot_with_const_id=3)
