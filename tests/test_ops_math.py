"""Op unit tests: math/elementwise/reduction ops vs numpy (reference
unittests/test_elementwise_*_op.py, test_mul_op.py, test_softmax_op.py...)."""

import numpy as np
import pytest

from op_test import OpTest


def _rand(*shape):
    return np.random.RandomState(42).uniform(-1, 1, shape).astype("float32")


class TestElementwiseAdd(OpTest):
    def setup_method(self, m):
        self.op_type = "elementwise_add"
        x, y = _rand(3, 4), _rand(3, 4)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x + y}
        self.attrs = {}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out")


class TestElementwiseAddBroadcastAxis(OpTest):
    def setup_method(self, m):
        self.op_type = "elementwise_add"
        x, y = _rand(2, 3, 4), _rand(3)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x + y.reshape(1, 3, 1)}
        self.attrs = {"axis": 1}

    def test_output(self):
        self.check_output()


class TestMul(OpTest):
    def setup_method(self, m):
        self.op_type = "mul"
        x, y = _rand(4, 6), _rand(6, 5)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x @ y}
        self.attrs = {"x_num_col_dims": 1, "y_num_col_dims": 1}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X", "Y"], "Out")


class TestMulFlatten(OpTest):
    def setup_method(self, m):
        self.op_type = "mul"
        x, y = _rand(3, 2, 4), _rand(8, 5)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x.reshape(3, 8) @ y}
        self.attrs = {"x_num_col_dims": 1, "y_num_col_dims": 1}

    def test_output(self):
        self.check_output()


class TestMatmulTranspose(OpTest):
    def setup_method(self, m):
        self.op_type = "matmul"
        x, y = _rand(4, 6), _rand(5, 6)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x @ y.T}
        self.attrs = {"transpose_X": False, "transpose_Y": True}

    def test_output(self):
        self.check_output()


class TestSoftmax(OpTest):
    def setup_method(self, m):
        self.op_type = "softmax"
        x = _rand(5, 7)
        e = np.exp(x - x.max(-1, keepdims=True))
        self.inputs = {"X": x}
        self.outputs = {"Out": e / e.sum(-1, keepdims=True)}
        self.attrs = {}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestReduceSum(OpTest):
    def setup_method(self, m):
        self.op_type = "reduce_sum"
        x = _rand(3, 4, 5)
        self.inputs = {"X": x}
        self.outputs = {"Out": x.sum(axis=1)}
        self.attrs = {"dim": [1], "keep_dim": False}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], "Out")


class TestReduceMeanAll(OpTest):
    def setup_method(self, m):
        self.op_type = "reduce_mean"
        x = _rand(3, 4)
        self.inputs = {"X": x}
        self.outputs = {"Out": np.asarray(x.mean())}
        self.attrs = {"reduce_all": True}

    def test_output(self):
        self.check_output()


class TestScale(OpTest):
    def setup_method(self, m):
        self.op_type = "scale"
        x = _rand(4, 5)
        self.inputs = {"X": x}
        self.outputs = {"Out": x * 2.5 + 1.0}
        self.attrs = {"scale": 2.5, "bias": 1.0}

    def test_output(self):
        self.check_output()


class TestSum3(OpTest):
    def setup_method(self, m):
        self.op_type = "sum"
        a, b, c = _rand(3, 4), _rand(3, 4), _rand(3, 4)
        self.inputs = {"X": [("a", a), ("b", b), ("c", c)]}
        self.outputs = {"Out": a + b + c}
        self.attrs = {}

    def test_output(self):
        self.check_output()


@pytest.mark.parametrize("act,fn", [
    ("relu", lambda x: np.maximum(x, 0)),
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x))),
    ("tanh", np.tanh),
    ("exp", np.exp),
    ("square", np.square),
    ("abs", np.abs),
    ("softsign", lambda x: x / (1 + np.abs(x))),
    ("leaky_relu", lambda x: np.where(x >= 0, x, 0.02 * x)),
])
def test_activation(act, fn):
    class T(OpTest):
        pass
    t = T()
    t.op_type = act
    x = _rand(4, 5)
    t.inputs = {"X": x}
    t.outputs = {"Out": fn(x)}
    t.attrs = {}
    t.check_output(atol=1e-5)


def test_activation_grads():
    for act in ["relu", "sigmoid", "tanh", "square"]:
        class T(OpTest):
            pass
        t = T()
        t.op_type = act
        x = _rand(3, 4) + 0.1  # avoid relu kink at 0
        t.inputs = {"X": x}
        t.outputs = {}
        t.outputs = {"Out": x}  # unused by check_grad
        t.check_grad(["X"], "Out")


class TestCrossEntropy(OpTest):
    def setup_method(self, m):
        self.op_type = "cross_entropy"
        probs = np.random.RandomState(7).dirichlet(
            np.ones(5), size=4).astype("float32")
        label = np.array([[0], [2], [4], [1]], dtype=np.int64)
        expect = -np.log(probs[np.arange(4), label.flatten()]).reshape(4, 1)
        self.inputs = {"X": probs, "Label": label}
        self.outputs = {"Y": expect}
        self.attrs = {}

    def test_output(self):
        self.check_output()


class TestSoftmaxWithCE(OpTest):
    def setup_method(self, m):
        self.op_type = "softmax_with_cross_entropy"
        logits = _rand(4, 5)
        label = np.array([[0], [2], [4], [1]], dtype=np.int64)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        loss = -np.log(sm[np.arange(4), label.flatten()]).reshape(4, 1)
        self.inputs = {"Logits": logits, "Label": label}
        self.outputs = {"Softmax": sm, "Loss": loss}
        self.attrs = {}

    def test_output(self):
        self.check_output()


class TestTopK(OpTest):
    def setup_method(self, m):
        self.op_type = "top_k"
        x = _rand(4, 10)
        idx = np.argsort(-x, axis=1)[:, :3]
        vals = np.take_along_axis(x, idx, axis=1)
        self.inputs = {"X": x}
        self.outputs = {"Out": vals, "Indices": idx.astype(np.int64)}
        self.attrs = {"k": 3}

    def test_output(self):
        self.check_output()
