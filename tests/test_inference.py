"""Inference tests: save_inference_model -> load -> Predictor parity.

Mirrors the reference's book tests (train -> save_inference_model -> C++
predictor round-trip, python/paddle/fluid/tests/book/) and the
NativePaddlePredictor/AnalysisPredictor API (inference/api/api_impl.cc:95,
analysis_predictor.cc).
"""

import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid

HERE = os.path.dirname(os.path.abspath(__file__))
from paddle_tpu.inference import (
    NativeConfig, AnalysisConfig, PaddleTensor, create_paddle_predictor)


@pytest.fixture
def trained_model(tmp_path):
    main = fluid.Program()
    startup = fluid.Program()
    main.random_seed = 5
    startup.random_seed = 5
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[1, 8, 8],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        conv = fluid.layers.conv2d(input=img, num_filters=4, filter_size=3,
                                   padding=1, act=None, bias_attr=False)
        bn = fluid.layers.batch_norm(input=conv, act="relu")
        pool = fluid.layers.pool2d(input=bn, pool_size=2, pool_stride=2)
        pred = fluid.layers.fc(input=pool, size=10, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed={
                "img": rng.randn(8, 1, 8, 8).astype(np.float32),
                "label": rng.randint(0, 10, (8, 1)).astype(np.int64)},
                fetch_list=[loss])
        model_dir = str(tmp_path / "model")
        fluid.save_inference_model(model_dir, ["img"], [pred], exe,
                                   main_program=main)
        # reference output for parity checks
        x = rng.randn(4, 1, 8, 8).astype(np.float32)
        infer_prog = main.clone(for_test=True)._prune(["img"], [pred.name])
        ref, = exe.run(infer_prog, feed={"img": x},
                       fetch_list=[pred.name])
    return model_dir, x, ref


def test_load_inference_model_roundtrip(trained_model):
    model_dir, x, ref = trained_model
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        program, feed_names, fetch_vars = fluid.load_inference_model(
            model_dir, exe)
        assert feed_names == ["img"]
        got, = exe.run(program, feed={"img": x},
                       fetch_list=[fetch_vars[0].name])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_native_predictor(trained_model):
    model_dir, x, ref = trained_model
    pred = create_paddle_predictor(NativeConfig(model_dir=model_dir))
    out, = pred.run({"img": x})
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_native_predictor_positional_tensors(trained_model):
    model_dir, x, ref = trained_model
    pred = create_paddle_predictor(NativeConfig(model_dir=model_dir))
    out, = pred.Run([PaddleTensor(x)])
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_analysis_predictor_folds_bn(trained_model):
    model_dir, x, ref = trained_model
    pred = create_paddle_predictor(AnalysisConfig(model_dir=model_dir))
    types = [op.type for op in pred._program.global_block().ops]
    assert "batch_norm" not in types, "analysis pass should fold BN"
    out, = pred.run({"img": x})
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=1e-5)


def test_analysis_predictor_batch_bucketing(trained_model):
    model_dir, x, ref = trained_model
    pred = create_paddle_predictor(AnalysisConfig(model_dir=model_dir))
    out3, = pred.run({"img": x[:3]})  # batch 3 pads to bucket 4
    assert out3.shape[0] == 3
    np.testing.assert_allclose(out3, ref[:3], rtol=2e-4, atol=1e-5)
    # batch 4 lands in the same bucket as padded batch 3 -> no new compile
    n_compiled = len(pred._compiled)
    out4, = pred.run({"img": x})
    assert out4.shape[0] == 4
    assert len(pred._compiled) == n_compiled


def test_predictor_clone(trained_model):
    model_dir, x, ref = trained_model
    pred = create_paddle_predictor(NativeConfig(model_dir=model_dir))
    clone = pred.clone()
    out, = clone.run({"img": x})
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_aot_export_serves_in_fresh_process_without_retrace(
        trained_model, tmp_path):
    """VERDICT r3 #8: save model -> AOT-export -> NEW process serves with
    NO Program rebuild and NO jax trace (build_step_fn is poisoned in the
    child; only jax.export deserialization + XLA compile may run)."""
    import subprocess
    import sys
    model_dir, x, ref = trained_model
    aot_dir = str(tmp_path / "aot")
    pred = create_paddle_predictor(NativeConfig(model_dir=model_dir))
    pred.save_aot(aot_dir, batch_sizes=(4, 8))
    np.save(str(tmp_path / "x.npy"), x)
    np.save(str(tmp_path / "ref.npy"), ref)
    code = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
# poison tracing: serving an AOT artifact must NEVER build/trace a step
from paddle_tpu.fluid import functionalizer
def _no_trace(*a, **k):
    raise AssertionError("AOT serving must not rebuild/trace the program")
functionalizer.build_step_fn = _no_trace
from paddle_tpu.inference import load_aot_predictor
p = load_aot_predictor(%r)
x = np.load(%r)
(out,) = p.run({"img": x})
ref = np.load(%r)
np.testing.assert_allclose(out, ref, rtol=2e-4, atol=1e-5)
# a smaller batch pads up to the nearest exported bucket
(out2,) = p.run({"img": x[:2]})
np.testing.assert_allclose(out2, ref[:2], rtol=2e-4, atol=1e-5)
print("AOT-SERVE-OK")
""" % (aot_dir, str(tmp_path / "x.npy"), str(tmp_path / "ref.npy"))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(HERE) + os.pathsep + \
        env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300,
                          cwd=os.path.dirname(HERE))
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "AOT-SERVE-OK" in proc.stdout


def test_aot_unpad_spares_global_fetches(tmp_path):
    """Un-padding must only apply to batch-major fetches: a global
    (reduced) output whose leading dim coincidentally equals the padded
    batch bucket must come back whole."""
    main = fluid.Program()
    startup = fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[4], dtype="float32")
        pred = fluid.layers.fc(input=img, size=8, act="softmax")
        # [8]-vector: leading dim == the padded bucket below, NOT batch
        colsum = fluid.layers.reduce_sum(pred, dim=0)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        model_dir = str(tmp_path / "m")
        fluid.save_inference_model(model_dir, ["img"], [pred, colsum],
                                   exe, main_program=main)
        p = create_paddle_predictor(NativeConfig(model_dir=model_dir))
        aot = str(tmp_path / "aot")
        p.save_aot(aot, batch_sizes=(8,))
    from paddle_tpu.inference import load_aot_predictor
    q = load_aot_predictor(aot)
    x = rng.randn(1, 4).astype(np.float32)     # b=1, padded to cap=8
    got_pred, got_colsum = q.run({"img": x})
    assert got_pred.shape == (1, 8)            # batch-major: un-padded
    assert got_colsum.shape == (8,), got_colsum.shape  # global: whole


def test_aot_fixed_shape_side_feed_not_padded(tmp_path):
    """Batch padding must only touch batch-major feeds; a fixed-shape
    side feed (append_batch_size=False) goes through whole, and the
    request batch is inferred from a batch-major feed regardless of
    dict order."""
    main = fluid.Program()
    startup = fluid.Program()
    main.random_seed = startup.random_seed = 21
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[4], dtype="float32")
        aux = fluid.layers.data(name="aux", shape=[4], dtype="float32",
                                append_batch_size=False)
        out = fluid.layers.elementwise_add(
            fluid.layers.fc(input=img, size=4), aux, axis=-1)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(1)
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / "m")
        fluid.save_inference_model(md, ["img", "aux"], [out], exe,
                                   main_program=main)
        p = create_paddle_predictor(NativeConfig(model_dir=md))
        aot = str(tmp_path / "aot")
        p.save_aot(aot, batch_sizes=(8,))
    from paddle_tpu.inference import load_aot_predictor
    q = load_aot_predictor(aot)
    x = rng.randn(1, 4).astype(np.float32)
    a = rng.randn(4).astype(np.float32)
    # aux first: batch must still come from the batch-major img feed
    res, = q.run({"aux": a, "img": x})
    assert res.shape == (1, 4)


def test_predictor_fixed_shape_side_feed_not_padded(tmp_path):
    """The live Predictor honors the same batch-major markers as the AOT
    path (PR 3 satellite): a fixed-shape side feed must NOT be bucket-
    padded, and the request batch comes from a batch-major feed
    regardless of dict order."""
    main = fluid.Program()
    startup = fluid.Program()
    main.random_seed = startup.random_seed = 23
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[4], dtype="float32")
        aux = fluid.layers.data(name="aux", shape=[4], dtype="float32",
                                append_batch_size=False)
        out = fluid.layers.elementwise_add(
            fluid.layers.fc(input=img, size=4), aux, axis=-1)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(1)
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / "m")
        fluid.save_inference_model(md, ["img", "aux"], [out], exe,
                                   main_program=main)
    p = create_paddle_predictor(AnalysisConfig(model_dir=md))
    x = rng.randn(1, 4).astype(np.float32)  # pads to bucket 1... batch 1
    a = rng.randn(4).astype(np.float32)
    # aux first in dict order: the old code read the batch from (and
    # padded) the first feed seen — a silently padded side feed
    res, = p.run({"aux": a, "img": x})
    assert res.shape == (1, 4)
    res3, = p.run({"aux": a, "img": rng.randn(3, 4).astype(np.float32)})
    assert res3.shape == (3, 4)  # batch 3 pads to bucket 4, unpads back


def test_predictor_unpad_spares_global_fetches(tmp_path):
    """Un-padding in the live Predictor keys off the program-var -1
    marker, not the shape>=batch heuristic: a reduced output whose
    leading dim equals the padded bucket comes back whole."""
    main = fluid.Program()
    startup = fluid.Program()
    main.random_seed = startup.random_seed = 24
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[4], dtype="float32")
        pred = fluid.layers.fc(input=img, size=8, act="softmax")
        colsum = fluid.layers.reduce_sum(pred, dim=0)  # shape [8]
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / "m")
        fluid.save_inference_model(md, ["img"], [pred, colsum], exe,
                                   main_program=main)
    cfg = AnalysisConfig(model_dir=md)
    cfg.batch_size_buckets = (8,)
    p = create_paddle_predictor(cfg)
    x = rng.randn(1, 4).astype(np.float32)  # b=1, padded to cap=8
    got_pred, got_colsum = p.run({"img": x})
    assert got_pred.shape == (1, 8)      # batch-major: un-padded
    assert got_colsum.shape == (8,), got_colsum.shape  # global: whole


def test_predictor_bucket_overflow_warns_once(trained_model):
    """A batch above every bucket falls through to a per-size compile;
    serving observability demands a one-time warning naming the size."""
    import warnings
    model_dir, x, ref = trained_model
    cfg = AnalysisConfig(model_dir=model_dir)
    cfg.batch_size_buckets = (2,)
    pred = create_paddle_predictor(cfg)
    big = np.concatenate([x, x], axis=0)  # batch 8 > bucket cap 2
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        out, = pred.run({"img": big})
        pred.run({"img": big})  # same size again: no second warning
    assert out.shape[0] == 8
    msgs = [str(w.message) for w in caught
            if "exceeds every configured bucket" in str(w.message)]
    assert len(msgs) == 1, msgs
    assert "batch 8" in msgs[0]
    # a different overflow size warns again (it names each size once)
    bigger = np.concatenate([big, x], axis=0)
    with warnings.catch_warnings(record=True) as caught2:
        warnings.simplefilter("always")
        pred.run({"img": bigger})
    assert any("batch 12" in str(w.message) for w in caught2)


def test_aot_export_rejects_non_batch_dynamic_dims(tmp_path):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        seq = fluid.layers.data(name="s", shape=[-1, 4], dtype="float32")
        out = fluid.layers.relu(seq)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / "m")
        fluid.save_inference_model(md, ["s"], [out], exe,
                                   main_program=main)
        p = create_paddle_predictor(NativeConfig(model_dir=md))
        with pytest.raises(ValueError, match="non-batch dynamic"):
            p.save_aot(str(tmp_path / "aot"), batch_sizes=(4,))


def test_multi_platform_aot_predictor(tmp_path):
    from jax import export as jax_export
    main = fluid.Program()
    startup = fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[4], dtype="float32")
        pred = fluid.layers.fc(input=img, size=3, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / "m")
        fluid.save_inference_model(md, ["img"], [pred], exe,
                                   main_program=main)
        p = create_paddle_predictor(NativeConfig(model_dir=md))
        aot = str(tmp_path / "aot")
        p.save_aot(aot, batch_sizes=(4,), platforms=("cpu", "tpu"))
        x = rng.randn(4, 4).astype(np.float32)
        ref, = p.run({"img": x})
    with open(os.path.join(aot, "aot_b4.bin"), "rb") as f:
        exp = jax_export.deserialize(f.read())
    assert set(pl.lower() for pl in exp.platforms) == {"cpu", "tpu"}
    from paddle_tpu.inference import load_aot_predictor
    got, = load_aot_predictor(aot).run({"img": x})
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-6)
