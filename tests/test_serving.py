"""Serving runtime tests (paddle_tpu/serving — SERVING.md).

Pins the subsystem's contracts: cross-request coalescing with bit-exact
padding parity vs a direct Predictor.run, registry hot swap that never
drops or double-answers a request, admission-control shedding that
never hangs (including under FlakyProxy transport chaos), graceful
drain on shutdown, and wire-encodable metrics.  Everything CPU-safe
under JAX_PLATFORMS=cpu.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.serving import (
    BatcherClosed, DeadlineExceeded, DynamicBatcher, InferenceServer,
    ModelRegistry, ServerOverloaded, ServingClient, ServingMetrics,
    set_dispatch_delay)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture(autouse=True)
def _clear_chaos():
    yield
    set_dispatch_delay(0.0)


def _export_fc(tmp_path, seed, name="m", size=6, with_aux=False):
    """Tiny fc model -> save_inference_model dir; returns its path."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        feeds = ["x"]
        h = fluid.layers.fc(input=x, size=size, act="relu")
        if with_aux:
            aux = fluid.layers.data(name="aux", shape=[size],
                                    dtype="float32",
                                    append_batch_size=False)
            h = fluid.layers.elementwise_add(h, aux, axis=-1)
            feeds.append("aux")
        pred = fluid.layers.fc(input=h, size=size, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / name)
        fluid.save_inference_model(md, feeds, [pred], exe,
                                   main_program=main)
    return md


def _direct(md, buckets=(2, 4, 8)):
    from paddle_tpu.inference import AnalysisConfig, Predictor
    cfg = AnalysisConfig(model_dir=md)
    cfg.batch_size_buckets = tuple(buckets)
    return Predictor(cfg)


# ---------------------------------------------------------------------------
# batcher
# ---------------------------------------------------------------------------

class TestBatcher:
    def test_coalesces_and_matches_direct_run_bit_exact(self, tmp_path):
        md = _export_fc(tmp_path, seed=3)
        direct = _direct(md)
        pred = _direct(md)
        metrics = ServingMetrics().model("m")
        batcher = DynamicBatcher(pred, max_queue=64, deadline_ms=50,
                                 metrics=metrics)
        rng = np.random.RandomState(0)
        inputs = [rng.randn(b, 4).astype(np.float32)
                  for b in (1, 2, 3, 1, 1)]
        refs = [direct.run({"x": xi})[0] for xi in inputs]
        try:
            futures = [batcher.submit({"x": xi}) for xi in inputs]
            outs = [f.result(timeout=30)[0] for f in futures]
        finally:
            batcher.close()
        for xi, out, ref in zip(inputs, outs, refs):
            assert out.shape == ref.shape
            assert np.array_equal(out, ref), \
                "coalesced+padded result differs from direct run"
        # all 5 requests (total 8 rows) fit the largest bucket and were
        # queued before the window closed: strictly fewer dispatches
        assert metrics.dispatches.value < len(inputs)
        assert metrics.requests.value == len(inputs)
        assert metrics.responses.value == len(inputs)

    def test_side_feed_compatibility_grouping(self, tmp_path):
        """Requests sharing a byte-identical side feed coalesce; ones
        with a different side feed dispatch separately but correctly."""
        md = _export_fc(tmp_path, seed=4, with_aux=True)
        direct = _direct(md)
        pred = _direct(md)
        batcher = DynamicBatcher(pred, max_queue=64, deadline_ms=50)
        rng = np.random.RandomState(1)
        aux_a = rng.randn(6).astype(np.float32)
        aux_b = rng.randn(6).astype(np.float32)
        reqs = [(rng.randn(1, 4).astype(np.float32), aux)
                for aux in (aux_a, aux_a, aux_b, aux_a)]
        refs = [direct.run({"x": x, "aux": a})[0] for x, a in reqs]
        try:
            futs = [batcher.submit({"x": x, "aux": a}) for x, a in reqs]
            outs = [f.result(timeout=30)[0] for f in futs]
        finally:
            batcher.close()
        for out, ref in zip(outs, refs):
            assert np.array_equal(out, ref)

    def test_oversize_request_rejected_synchronously(self, tmp_path):
        md = _export_fc(tmp_path, seed=5)
        batcher = DynamicBatcher(_direct(md, buckets=(2, 4)),
                                 max_queue=8, deadline_ms=1)
        try:
            with pytest.raises(ValueError, match="largest servable"):
                batcher.submit({"x": np.zeros((9, 4), np.float32)})
        finally:
            batcher.close()

    def test_inconsistent_batch_rejected(self, tmp_path):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 5
        with fluid.program_guard(main, startup):
            a = fluid.layers.data(name="a", shape=[4], dtype="float32")
            b = fluid.layers.data(name="b", shape=[4], dtype="float32")
            out = fluid.layers.elementwise_add(a, b)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            md = str(tmp_path / "two_feed")
            fluid.save_inference_model(md, ["a", "b"], [out], exe,
                                       main_program=main)
        batcher = DynamicBatcher(_direct(md), max_queue=8, deadline_ms=1)
        try:
            with pytest.raises(ValueError, match="inconsistent"):
                batcher.submit({"a": np.zeros((2, 4), np.float32),
                                "b": np.zeros((3, 4), np.float32)})
        finally:
            batcher.close()

    def test_deadline_zero_dispatches_immediately(self, tmp_path):
        md = _export_fc(tmp_path, seed=6)
        batcher = DynamicBatcher(_direct(md), max_queue=8, deadline_ms=0)
        try:
            t0 = time.monotonic()
            out = batcher.submit(
                {"x": np.zeros((1, 4), np.float32)}).result(timeout=30)
            assert out[0].shape == (1, 6)
            assert time.monotonic() - t0 < 5.0
        finally:
            batcher.close()

    def test_overload_sheds_and_counts(self, tmp_path):
        md = _export_fc(tmp_path, seed=7)
        metrics = ServingMetrics().model("m")
        batcher = DynamicBatcher(_direct(md), max_queue=3, deadline_ms=5,
                                 metrics=metrics)
        set_dispatch_delay(0.2)
        x = np.zeros((1, 4), np.float32)
        accepted, shed = [], 0
        try:
            for _ in range(16):
                try:
                    accepted.append(batcher.submit({"x": x}))
                except ServerOverloaded:
                    shed += 1
            assert shed > 0
            assert metrics.shed.value == shed
            set_dispatch_delay(0.0)
            for f in accepted:  # accepted requests still complete
                f.result(timeout=30)
        finally:
            set_dispatch_delay(0.0)
            batcher.close()

    def test_request_deadline_expires_in_queue(self, tmp_path):
        md = _export_fc(tmp_path, seed=8)
        batcher = DynamicBatcher(_direct(md), max_queue=32, deadline_ms=1)
        set_dispatch_delay(0.3)
        x = np.zeros((1, 4), np.float32)
        try:
            batcher.submit({"x": x})  # occupies the slow worker
            fut = batcher.submit(
                {"x": x}, deadline=time.monotonic() + 0.05)
            with pytest.raises(DeadlineExceeded):
                fut.result(timeout=30)
        finally:
            set_dispatch_delay(0.0)
            batcher.close()

    def test_close_drains_queued_requests(self, tmp_path):
        md = _export_fc(tmp_path, seed=9)
        batcher = DynamicBatcher(_direct(md), max_queue=64, deadline_ms=2)
        set_dispatch_delay(0.05)
        x = np.zeros((2, 4), np.float32)
        futs = [batcher.submit({"x": x}) for _ in range(10)]
        set_dispatch_delay(0.0)
        batcher.close(drain=True, timeout=60)
        for f in futs:
            assert f.result(timeout=1)[0].shape == (2, 6)
        with pytest.raises(BatcherClosed):
            batcher.submit({"x": x})


# ---------------------------------------------------------------------------
# registry / hot swap
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_aot_artifact_detection_and_serving(self, tmp_path):
        md = _export_fc(tmp_path, seed=10)
        direct = _direct(md)
        aot = str(tmp_path / "aot")
        direct.save_aot(aot, batch_sizes=(2, 4))
        reg = ModelRegistry(deadline_ms=5)
        try:
            entry = reg.load_model("m", aot)
            from paddle_tpu.inference import AotPredictor
            assert isinstance(entry.predictor, AotPredictor)
            assert entry.predictor.batch_buckets() == (2, 4)
            x = np.random.RandomState(2).randn(3, 4).astype(np.float32)
            out = reg.infer("m", {"x": x}, timeout=60)[0]
            ref = direct.run({"x": x})[0]
            np.testing.assert_allclose(out, ref, rtol=2e-5, atol=1e-6)
        finally:
            reg.close_all()

    def test_versioning_and_explicit_version_routing(self, tmp_path):
        md1 = _export_fc(tmp_path, seed=11, name="v1")
        md2 = _export_fc(tmp_path, seed=22, name="v2")
        reg = ModelRegistry(deadline_ms=1)
        try:
            e1 = reg.load_model("m", md1, buckets=(2, 4))
            e2 = reg.load_model("m", md2, buckets=(2, 4), version=7)
            assert (e1.version, e2.version) == (1, 7)
            x = np.random.RandomState(3).randn(1, 4).astype(np.float32)
            r1 = _direct(md1, (2, 4)).run({"x": x})[0]
            latest = reg.infer("m", {"x": x}, timeout=60)[0]
            assert not np.array_equal(latest, r1)
            # the displaced version is retired: explicit routing to it
            # now fails rather than silently serving stale weights
            with pytest.raises(KeyError):
                reg.submit("m", {"x": x}, version=1)
        finally:
            reg.close_all()

    def test_hot_swap_under_concurrent_inference(self, tmp_path):
        """The no-dropped-no-doubled guarantee: hammer one model name
        from 3 threads while hot-swapping versions; every response must
        be exactly v1's or v2's output, every submit must resolve."""
        md1 = _export_fc(tmp_path, seed=31, name="v1")
        md2 = _export_fc(tmp_path, seed=32, name="v2")
        x = np.random.RandomState(4).randn(2, 4).astype(np.float32)
        r1 = _direct(md1, (2, 4)).run({"x": x})[0]
        r2 = _direct(md2, (2, 4)).run({"x": x})[0]
        reg = ModelRegistry(deadline_ms=2)
        reg.load_model("m", md1, buckets=(2, 4))
        stop = threading.Event()
        wrong, errors, answered = [], [], [0]
        lock = threading.Lock()

        def hammer():
            while not stop.is_set():
                try:
                    out = reg.infer("m", {"x": x}, timeout=30)[0]
                except Exception as e:  # no exception is acceptable
                    errors.append(e)
                    return
                with lock:
                    answered[0] += 1
                    if not (np.array_equal(out, r1)
                            or np.array_equal(out, r2)):
                        wrong.append(out)

        threads = [threading.Thread(target=hammer) for _ in range(3)]
        try:
            for t in threads:
                t.start()
            time.sleep(0.2)
            reg.load_model("m", md2, buckets=(2, 4))  # hot swap mid-load
            time.sleep(0.2)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        assert not errors, errors[:3]
        assert not wrong, "%d responses matched neither version" \
            % len(wrong)
        assert answered[0] > 10
        out_after = reg.infer("m", {"x": x}, timeout=30)[0]
        assert np.array_equal(out_after, r2), \
            "post-swap traffic must serve the new version"
        reg.close_all()

    def test_unload_refuses_new_traffic(self, tmp_path):
        md = _export_fc(tmp_path, seed=12)
        reg = ModelRegistry(deadline_ms=1)
        reg.load_model("m", md, buckets=(2,))
        reg.unload_model("m")
        with pytest.raises(KeyError):
            reg.submit("m", {"x": np.zeros((1, 4), np.float32)})
        reg.close_all()


# ---------------------------------------------------------------------------
# server end-to-end
# ---------------------------------------------------------------------------

class TestServer:
    def test_e2e_concurrent_clients_bit_exact_and_coalesced(
            self, tmp_path):
        """The acceptance demo: in-process server on a saved model, 3+
        concurrent clients with mixed batch sizes, bit-exact vs direct
        Predictor.run, batch-fill > 1 request/dispatch."""
        md = _export_fc(tmp_path, seed=13)
        direct = _direct(md)
        server = InferenceServer(buckets=(2, 4, 8),
                                 deadline_ms=20).start()
        rng = np.random.RandomState(5)
        inputs = [rng.randn(b, 4).astype(np.float32)
                  for b in (1, 2, 3, 1, 2, 1)]
        refs = [direct.run({"x": xi})[0] for xi in inputs]
        outs = [None] * len(inputs)
        errs = []
        try:
            boot = ServingClient(server.endpoint)
            boot.load_model("fc", md, buckets=[2, 4, 8])

            def worker(i):
                cli = ServingClient(server.endpoint)
                try:
                    outs[i] = cli.infer("fc", {"x": inputs[i]},
                                        deadline_ms=30000.0)[0]
                except Exception as e:
                    errs.append(e)
                finally:
                    cli.close()

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(len(inputs))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not errs, errs[:3]
            for out, ref in zip(outs, refs):
                assert np.array_equal(out, ref), \
                    "served result differs from direct Predictor.run"
            stats = boot.stats()["stats"]["models"]["fc"]
            assert stats["responses"] == len(inputs)
            assert stats["batch_fill"] > 1.0, \
                "no cross-request coalescing happened: %r" % stats
            assert stats["latency_ms"]["count"] == len(inputs)
        finally:
            server.shutdown(drain=True)

    def test_overload_sheds_not_hangs_under_flaky_proxy(self, tmp_path):
        """Chaos acceptance: tiny admission queue + slow worker + a
        connection-killing proxy; every request resolves (ok / shed /
        deadline / connection error), none hang."""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        from chaos import FlakyProxy
        md = _export_fc(tmp_path, seed=14)
        server = InferenceServer(max_queue=3, buckets=(2, 4)).start()
        proxy = FlakyProxy(server.endpoint, drop_first=2,
                           drop_after_bytes=32).start()
        x = np.zeros((1, 4), np.float32)
        outcomes = {"ok": 0, "shed": 0, "deadline": 0, "conn": 0}
        lock = threading.Lock()

        def one(i):
            cli = ServingClient(proxy.endpoint)
            try:
                cli.infer("m", {"x": x}, deadline_ms=400.0,
                          retry_sheds=False)
                key = "ok"
            except ServerOverloaded:
                key = "shed"
            except DeadlineExceeded:
                key = "deadline"
            except Exception:
                key = "conn"
            finally:
                cli.close()
            with lock:
                outcomes[key] += 1

        try:
            boot = ServingClient(server.endpoint)
            boot.load_model("m", md, buckets=[2, 4])
            boot.infer("m", {"x": x})  # warm directly
            set_dispatch_delay(0.15)
            threads = [threading.Thread(target=one, args=(i,))
                       for i in range(24)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in threads), \
                "requests hung under overload"
            assert sum(outcomes.values()) == 24
            assert outcomes["ok"] >= 1
            assert outcomes["shed"] >= 1, outcomes
            assert boot.stats()["stats"]["models"]["m"]["shed"] >= 1
        finally:
            set_dispatch_delay(0.0)
            proxy.stop()
            server.shutdown(drain=False, timeout=5.0)

    def test_shutdown_drains_inflight_requests(self, tmp_path):
        md = _export_fc(tmp_path, seed=15)
        server = InferenceServer(buckets=(2,), deadline_ms=2).start()
        x = np.zeros((1, 4), np.float32)
        results, errs = [], []
        boot = ServingClient(server.endpoint)
        boot.load_model("m", md, buckets=[2])
        boot.infer("m", {"x": x})
        set_dispatch_delay(0.05)

        def worker():
            cli = ServingClient(server.endpoint)
            try:
                results.append(cli.infer("m", {"x": x},
                                         deadline_ms=60000.0))
            except Exception as e:
                errs.append(e)
            finally:
                cli.close()

        threads = [threading.Thread(target=worker) for _ in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.1)  # let requests land in the queue
        set_dispatch_delay(0.0)
        boot.shutdown_server(drain=True)
        for t in threads:
            t.join(timeout=60)
        assert not errs, errs[:3]
        assert len(results) == 6, \
            "drain-on-shutdown dropped %d in-flight requests" \
            % (6 - len(results))

    def test_unknown_model_and_bad_request_codes(self, tmp_path):
        server = InferenceServer().start()
        cli = ServingClient(server.endpoint)
        try:
            from paddle_tpu.serving import ServingError
            with pytest.raises(ServingError, match="no_model"):
                cli.infer("ghost", {"x": np.zeros((1, 2), np.float32)})
            with pytest.raises(ServingError, match="bad_request"):
                cli._call_once({"cmd": "bogus"})
        finally:
            cli.close()
            server.shutdown(drain=False, timeout=5.0)

    def test_model_root_autoload(self, tmp_path):
        root = tmp_path / "zoo"
        root.mkdir()
        _export_fc(root, seed=16, name="alpha")
        _export_fc(root, seed=17, name="beta")
        server = InferenceServer(model_root=str(root),
                                 buckets=(2,), deadline_ms=1).start()
        cli = ServingClient(server.endpoint)
        try:
            reply = cli.stats()
            assert set(reply["models"]) == {"alpha", "beta"}
            out = cli.infer("beta",
                            {"x": np.zeros((1, 4), np.float32)})[0]
            assert out.shape == (1, 6)
        finally:
            cli.close()
            server.shutdown(drain=True)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

class TestMetrics:
    def test_reservoir_histogram_percentiles(self):
        from paddle_tpu.serving import ReservoirHistogram
        h = ReservoirHistogram(capacity=2048)
        for v in range(1, 1001):
            h.record(float(v))
        assert h.count == 1000
        assert abs(h.percentile(50) - 500.5) < 1.0
        assert abs(h.percentile(99) - 990.0) < 2.0
        s = h.summary()
        assert s["min"] == 1.0 and s["max"] == 1000.0

    def test_reservoir_bounded_memory(self):
        from paddle_tpu.serving import ReservoirHistogram
        h = ReservoirHistogram(capacity=64)
        for v in range(10000):
            h.record(v)
        assert len(h._samples) == 64
        assert h.count == 10000
        # sampled percentiles stay in the data's range and ordered
        p50, p95 = h.percentile(50), h.percentile(95)
        assert 0 <= p50 <= p95 <= 9999

    def test_snapshot_is_wire_encodable(self, tmp_path):
        from paddle_tpu.native import wire
        md = _export_fc(tmp_path, seed=18)
        reg = ModelRegistry(deadline_ms=1)
        try:
            reg.load_model("m", md, buckets=(2,))
            reg.infer("m", {"x": np.zeros((1, 4), np.float32)},
                      timeout=60)
            snap = reg.metrics.snapshot()
            decoded = wire.decode(wire.encode(snap))
            assert decoded["models"]["m"]["responses"] == 1
            assert decoded["models"]["m"]["latency_ms"]["count"] == 1
        finally:
            reg.close_all()


# ---------------------------------------------------------------------------
# tools
# ---------------------------------------------------------------------------

def test_bench_serving_smoke_subprocess():
    """Tier-1 CI proof of the whole stack in a fresh process: export,
    serve, open-loop load, JSON lane output."""
    import json
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_serving.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert lines, proc.stdout[-500:]
    rec = json.loads(lines[-1])
    assert rec["metric"] == "serving_qps"
    assert rec["ok"] > 0 and rec["errors"] == 0
    assert rec["backend"].startswith("cpu")


def test_serving_top_renders_stats(tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import serving_top
    md = _export_fc(tmp_path, seed=19)
    server = InferenceServer(buckets=(2,), deadline_ms=1).start()
    cli = ServingClient(server.endpoint)
    try:
        cli.load_model("demo", md, buckets=[2])
        cli.infer("demo", {"x": np.zeros((1, 4), np.float32)})
        serving_top.main([server.endpoint])
        out = capsys.readouterr().out
        assert "demo" in out and "QPS" in out and "SHED" in out
    finally:
        cli.close()
        server.shutdown(drain=True)
