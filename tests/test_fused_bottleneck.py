"""Fused ResNet bottleneck: Pallas kernel parity + the inference-graph
fusion pass.

The kernel (ops/pallas_kernels.py fused_bottleneck) runs a whole BN-folded
residual block — three convs, both relus, shortcut add — in one
VMEM-resident pallas_call, the "cross-layer fused conv pipeline" lever from
ROOFLINE.md. Reference analogue: the conv+bn+act fusion pass family
(paddle/fluid/framework/ir/conv_bn_fuse_pass.cc) which stops at per-conv
epilogues; fusing across the block is TPU-specific.

Interpret mode makes every test here exact on the CPU mesh.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.ops.pallas_kernels import (fused_bottleneck,
                                           bottleneck_reference)


def _params(rng, C, F, C4, branch):
    t = lambda *s: rng.randn(*s).astype(np.float32) * 0.1
    p = dict(w0=t(C, F), b0=t(F), w1=t(3, 3, F, F), b1=t(F),
             w2=t(F, C4), b2=t(C4))
    p["ws"], p["bs"] = (t(C, C4), t(C4)) if branch else (None, None)
    return p


@pytest.mark.parametrize(
    "N,H,W,C,F,stride,branch",
    [(2, 8, 8, 32, 16, 1, False),      # identity shortcut
     (2, 8, 8, 32, 16, 1, True),       # projection, stride 1
     (2, 8, 8, 32, 16, 2, True),       # projection, stride 2
     (1, 14, 14, 64, 32, 2, True),     # odd output rows (Ho=7)
     (1, 7, 7, 128, 32, 1, False)])    # odd everything
def test_kernel_matches_reference(N, H, W, C, F, stride, branch):
    rng = np.random.RandomState(0)
    C4 = F * 4 if branch else C
    p = _params(rng, C, F, C4, branch)
    x = rng.randn(N, H, W, C).astype(np.float32)
    got = fused_bottleneck(x, p["w0"], p["b0"], p["w1"], p["b1"], p["w2"],
                           p["b2"], p["ws"], p["bs"], stride=stride,
                           interpret=True)
    want = bottleneck_reference(x, p["w0"], p["b0"], p["w1"], p["b1"],
                                p["w2"], p["b2"], p["ws"], p["bs"], stride)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


def test_kernel_bf16():
    import jax.numpy as jnp
    rng = np.random.RandomState(1)
    p = _params(rng, 32, 16, 64, True)
    x = rng.randn(2, 8, 8, 32).astype(np.float32)
    cast = lambda a: None if a is None else jnp.asarray(a, jnp.bfloat16)
    got = fused_bottleneck(cast(x), *(cast(p[k]) for k in
                                      ("w0", "b0", "w1", "b1", "w2", "b2",
                                       "ws", "bs")),
                           stride=1, interpret=True)
    want = bottleneck_reference(x, p["w0"], p["b0"], p["w1"], p["b1"],
                                p["w2"], p["b2"], p["ws"], p["bs"], 1)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want), atol=0.12, rtol=0.12)


def test_untileable_falls_back():
    # odd W under stride 2 cannot reshape-decimate: the wrapper must
    # return the plain-XLA composition rather than fail
    rng = np.random.RandomState(2)
    p = _params(rng, 16, 8, 32, True)
    x = rng.randn(1, 9, 9, 16).astype(np.float32)
    got = fused_bottleneck(x, p["w0"], p["b0"], p["w1"], p["b1"], p["w2"],
                           p["b2"], p["ws"], p["bs"], stride=2,
                           interpret=True)
    want = bottleneck_reference(x, p["w0"], p["b0"], p["w1"], p["b1"],
                                p["w2"], p["b2"], p["ws"], p["bs"], 2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# graph-level: InferenceTranspiler folds BN then collapses NHWC blocks
# ---------------------------------------------------------------------------

@pytest.fixture
def fusion_enabled():
    """Fusion is opt-in (FLAGS.fuse_bottleneck_max_width defaults to 0:
    the r05 chip runs measured the fused graph slower end-to-end at
    every width gate) — graph tests that exercise the pass itself
    enable it explicitly."""
    from paddle_tpu.flags import set_flags, get_flags
    old = get_flags("fuse_bottleneck_max_width")
    set_flags({"fuse_bottleneck_max_width": 128})
    yield
    set_flags(old)


def _build_resnet_tail(layout):
    """data -> bottleneck(stride 2, projection) -> bottleneck(identity)."""
    from paddle_tpu.models.resnet import bottleneck_block
    main = fluid.Program()
    startup = fluid.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with fluid.program_guard(main, startup):
        shape = [8, 8, 16] if layout == "NHWC" else [16, 8, 8]
        img = fluid.layers.data(name="img", shape=shape, dtype="float32")
        out = bottleneck_block(img, 8, 2, is_train=False, layout=layout)
        out = bottleneck_block(out, 8, 1, is_train=False, layout=layout)
    return main, startup, out


@pytest.mark.parametrize("layout", ["NHWC", "NCHW"])
def test_transpiler_fuses_nhwc_blocks(layout, fusion_enabled):
    main, startup, out = _build_resnet_tail(layout)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(3)
    shape = (4, 8, 8, 16) if layout == "NHWC" else (4, 16, 8, 8)
    x = rng.randn(*shape).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        want, = exe.run(main, feed={"img": x}, fetch_list=[out.name])
        infer = main.clone(for_test=True)
        from paddle_tpu.fluid.transpiler import InferenceTranspiler
        InferenceTranspiler().transpile(infer, scope=scope)
        types = [op.type for op in infer.global_block().ops]
        if layout == "NHWC":
            # both blocks collapse: no loose conv/add/relu remain
            assert types.count("fused_bottleneck") == 2, types
            assert "conv2d" not in types and "relu" not in types, types
        else:
            # NCHW stays on the XLA path (kernel is lane-aligned NHWC)
            assert "fused_bottleneck" not in types, types
        got, = exe.run(infer, feed={"img": x}, fetch_list=[out.name])
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_wide_bottleneck_declines_fusion():
    """Measured-geometry gate: the r05 chip sweep (tune_bottleneck
    stages in BENCH_recovery_r05.json) showed the Pallas kernel LOSES
    to XLA for wide bottlenecks (F=256/512), so the pass must fuse only
    blocks with F <= FLAGS.fuse_bottleneck_max_width and leave wide
    ones (numerically intact) to XLA."""
    from paddle_tpu.flags import set_flags, get_flags
    main, startup, out = _build_resnet_tail("NHWC")   # width F = 8
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(11)
    x = rng.randn(4, 8, 8, 16).astype(np.float32)
    old = get_flags("fuse_bottleneck_max_width")
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            want, = exe.run(main, feed={"img": x}, fetch_list=[out.name])
            from paddle_tpu.fluid.transpiler import InferenceTranspiler
            # cap below this model's width: nothing may fuse
            set_flags({"fuse_bottleneck_max_width": 4})
            infer = main.clone(for_test=True)
            InferenceTranspiler().transpile(infer, scope=scope)
            types = [op.type for op in infer.global_block().ops]
            assert "fused_bottleneck" not in types, types
            got, = exe.run(infer, feed={"img": x}, fetch_list=[out.name])
            np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)
            # cap at the width: both blocks fuse again
            set_flags({"fuse_bottleneck_max_width": 8})
            infer2 = main.clone(for_test=True)
            InferenceTranspiler().transpile(infer2, scope=scope)
            types2 = [op.type for op in infer2.global_block().ops]
            assert types2.count("fused_bottleneck") == 2, types2
    finally:
        set_flags(old)


def test_nhwc_bn_fold_bias_axis():
    # regression: the folded BN bias add must broadcast over the channel
    # axis of the conv's layout — for NHWC that is the trailing dim, and
    # H != C here so a wrong axis is a loud shape error (or silent
    # corruption when H == C)
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[6, 6, 5],
                                dtype="float32")
        conv = fluid.layers.conv2d(input=img, num_filters=7, filter_size=3,
                                   padding=1, act=None, bias_attr=False,
                                   data_format="NHWC")
        out = fluid.layers.batch_norm(input=conv, act=None, is_test=True,
                                      data_layout="NHWC")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(4)
    x = rng.randn(2, 6, 6, 5).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        want, = exe.run(main, feed={"img": x}, fetch_list=[out.name])
        infer = main.clone(for_test=True)
        from paddle_tpu.fluid.transpiler import InferenceTranspiler
        InferenceTranspiler().transpile(infer, scope=scope)
        got, = exe.run(infer, feed={"img": x}, fetch_list=[out.name])
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_biased_conv_declines_fusion(fusion_enabled):
    """A conv2d carrying an inline Bias input has no slot in the fused
    kernel; the PASS must leave that block unfused (and numerically
    intact) instead of silently dropping the bias. The transpiler's own
    BN fold absorbs inline biases before the pass runs (tested below),
    so this models a LOADED, already-folded program with a stray inline
    bias — the pass is applied directly."""
    main, startup, out = _build_resnet_tail("NHWC")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(6)
    x = rng.randn(4, 8, 8, 16).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        infer = main.clone(for_test=True)
        from paddle_tpu.fluid.transpiler import InferenceTranspiler
        it = InferenceTranspiler()
        it._remove_dropout(infer)
        it._fuse_batch_norm(infer, scope)   # folded, not yet fused
        blk = infer.global_block()
        conv = next(op for op in blk.ops if op.type == "conv2d")
        w = blk._find_var_recursive(conv.inputs["Filter"][0])
        bias_name = "inline_conv_bias"
        blk.create_var(name=bias_name, shape=(int(w.shape[0]),),
                       dtype="float32", persistable=True)
        scope.set(bias_name,
                  rng.randn(int(w.shape[0])).astype(np.float32))
        conv.inputs["Bias"] = [bias_name]
        want, = exe.run(infer, feed={"img": x}, fetch_list=[out.name])
        from paddle_tpu.fluid.ir_passes import apply_passes
        apply_passes(infer, ["fuse_bottleneck_pass"])
        types = [op.type for op in infer.global_block().ops]
        # the biased block stays on loose ops; the clean block still fuses
        assert types.count("fused_bottleneck") == 1, types
        assert "conv2d" in types, types
        got, = exe.run(infer, feed={"img": x}, fetch_list=[out.name])
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_bn_fold_absorbs_inline_conv_bias(fusion_enabled):
    """BN(conv + b) folds to inv_std*conv + (beta + (b - mean)*inv_std):
    the inline bias must be scaled into the folded add and removed from
    the conv, not left to double-apply (or silently drop)."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[6, 6, 5],
                                dtype="float32")
        conv = fluid.layers.conv2d(input=img, num_filters=7, filter_size=3,
                                   padding=1, act=None, bias_attr=False,
                                   data_format="NHWC")
        out = fluid.layers.batch_norm(input=conv, act=None, is_test=True,
                                      data_layout="NHWC")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(9)
    x = rng.randn(2, 6, 6, 5).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        blk = main.global_block()
        conv_op = next(op for op in blk.ops if op.type == "conv2d")
        blk.create_var(name="cb", shape=(7,), dtype="float32",
                       persistable=True)
        scope.set("cb", rng.randn(7).astype(np.float32))
        conv_op.inputs["Bias"] = ["cb"]
        # non-trivial running stats so a wrong fold is numerically loud
        for v in blk.vars.values():
            n, a = v.name, scope.get(v.name)
            if a is None or np.asarray(a).ndim != 1 or n == "cb" or \
                    "batch_norm" not in n:
                continue
            a = np.asarray(a)
            if n.split(".")[-1].startswith("var"):
                scope.set(n, (0.05 + rng.rand(*a.shape) * 2.0)
                          .astype(a.dtype))
            else:
                scope.set(n, rng.randn(*a.shape).astype(a.dtype) * 0.5)
        want, = exe.run(main, feed={"img": x}, fetch_list=[out.name])
        infer = main.clone(for_test=True)
        from paddle_tpu.fluid.transpiler import InferenceTranspiler
        InferenceTranspiler().transpile(infer, scope=scope)
        iblk = infer.global_block()
        itypes = [op.type for op in iblk.ops]
        assert "batch_norm" not in itypes, itypes
        iconv = next(op for op in iblk.ops if op.type == "conv2d")
        assert not iconv.inputs.get("Bias"), iconv.inputs
        got, = exe.run(infer, feed={"img": x}, fetch_list=[out.name])
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_fused_program_exports_aot(tmp_path, fusion_enabled):
    """The AnalysisPredictor path (BN fold + block fusion) must still
    AOT-export and serve in a fresh predictor: the fused op's kernel has
    to survive jax.export serialization."""
    main, startup, out = _build_resnet_tail("NHWC")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(5)
    x = rng.randn(4, 8, 8, 16).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / "model")
        fluid.save_inference_model(md, ["img"], [out], exe,
                                   main_program=main)
        from paddle_tpu.inference import (AnalysisConfig,
                                          create_paddle_predictor,
                                          load_aot_predictor)
        p = create_paddle_predictor(AnalysisConfig(model_dir=md))
        types = [op.type for op in p._program.global_block().ops]
        assert types.count("fused_bottleneck") == 2, types
        ref, = p.run({"img": x})
        ad = str(tmp_path / "aot")
        p.save_aot(ad, batch_sizes=(4,))
        got, = load_aot_predictor(ad).run({"img": x})
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


@pytest.mark.parametrize(
    "C,F,stride,branch,dtype",
    [(32, 16, 1, False, "bfloat16"),
     (32, 16, 2, True, "bfloat16"),
     (64, 32, 2, True, "float32"),
     (128, 32, 1, False, "bfloat16")])
def test_kernel_lowers_for_tpu_offchip(C, F, stride, branch, dtype):
    """Pallas -> Mosaic conversion happens at LOWERING time, so the
    kernel's TPU path is checkable without a chip: cross-platform
    jax.export must produce a tpu_custom_call carrying the serialized
    Mosaic module. Catches Mosaic-side regressions (unsupported ops,
    layout constraints) from the CPU suite."""
    import jax
    import jax.numpy as jnp
    from jax import export as jax_export

    C4 = F * 4 if branch else C
    H = 16
    dt = jnp.dtype(dtype)

    def fn(x, w0, b0, w1, b1, w2, b2, ws, bs):
        return fused_bottleneck(
            x, w0, b0, w1, b1, w2, b2,
            ws if branch else None, bs if branch else None,
            stride=stride, interpret=False)

    shapes = [(4, H, H, C), (C, F), (F,), (3, 3, F, F), (F,), (F, C4),
              (C4,), (C, C4), (C4,)]
    specs = [jax.ShapeDtypeStruct(s, dt) for s in shapes]
    exp = jax_export.export(jax.jit(fn), platforms=["tpu"])(*specs)
    mlir = exp.mlir_module()
    assert "tpu_custom_call" in mlir, \
        "fused kernel fell back instead of lowering to Mosaic"


def test_flash_attention_lowers_for_tpu_offchip():
    import jax
    import jax.numpy as jnp
    from jax import export as jax_export
    from paddle_tpu.ops.pallas_kernels import flash_attention

    def fn(q, k, v):
        return flash_attention(q, k, v, causal=True, interpret=False)

    spec = jax.ShapeDtypeStruct((2, 512, 4, 128), jnp.bfloat16)
    exp = jax_export.export(jax.jit(fn), platforms=["tpu"])(
        spec, spec, spec)
    assert "tpu_custom_call" in exp.mlir_module()


def test_transpiled_program_embeds_mosaic_kernel_for_tpu(fusion_enabled):
    """The DEFAULT path (interpret unspecified) must choose per lowering
    platform: a TPU export of the fusion-transpiled serving program from
    this CPU host embeds the real Mosaic kernels, while CPU execution
    keeps the interpret branch (exercised by the parity tests above)."""
    from paddle_tpu.fluid import functionalizer
    main, startup, out = _build_resnet_tail("NHWC")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        infer = main.clone(for_test=True)
        from paddle_tpu.fluid.transpiler import InferenceTranspiler
        InferenceTranspiler().transpile(infer, scope=scope)
        sn = tuple(functionalizer.persistable_names(infer))
        state = {n: scope.get(n) for n in sn
                 if scope.get(n) is not None}
    step_fn = functionalizer.build_step_fn(
        infer, ("img",), (out.name,), tuple(state.keys()))
    exp = functionalizer.export_step_for_tpu(
        step_fn, state, {"img": ((4, 8, 8, 16), np.float32)})
    assert exp.mlir_module().count("tpu_custom_call") >= 2


def test_fused_artifact_cross_compiles_for_tpu(tmp_path, fusion_enabled):
    """save_aot(platforms=("tpu",)) from this CPU build host: the
    artifact must embed the REAL Mosaic kernels (not interpret
    emulation) for the TPU target. cpu+tpu multi-platform with Pallas
    is NOT supported (jax lowers every platform_dependent branch on
    every platform when the index is dynamic; pallas has no
    non-interpret CPU lowering) — the save_aot docstring records that;
    single-target cross-compilation is the supported build-host
    story."""
    from jax import export as jax_export
    import os as _os
    main, startup, out = _build_resnet_tail("NHWC")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        md = str(tmp_path / "m")
        fluid.save_inference_model(md, ["img"], [out], exe,
                                   main_program=main)
        from paddle_tpu.inference import (AnalysisConfig,
                                          create_paddle_predictor)
        p = create_paddle_predictor(AnalysisConfig(model_dir=md))
        types = [op.type for op in p._program.global_block().ops]
        assert types.count("fused_bottleneck") == 2, types
        ad = str(tmp_path / "aot")
        p.save_aot(ad, batch_sizes=(4,), platforms=("tpu",))
    with open(_os.path.join(ad, "aot_b4.bin"), "rb") as f:
        exp = jax_export.deserialize(f.read())
    assert [pl.lower() for pl in exp.platforms] == ["tpu"]
    assert exp.mlir_module().count("tpu_custom_call") >= 2
