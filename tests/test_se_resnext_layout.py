"""SE-ResNeXt NHWC layout: numerical parity with the NCHW build.

The TPU-preferred channels-last layout (dist_se_resnext.py analogue of
resnet.py's `layout` param) must compute the same function — same
initializers apply to the layout-independent OIHW filters, so feeding
the transposed image through the NHWC program must reproduce the NCHW
logits and the training trajectory.
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.models import se_resnext


def _run(layout, img_nchw, lab, steps=2, **kw):
    main, startup, feeds, loss, acc, prob = se_resnext.get_model(
        batch_size=2, img_size=48, class_dim=5, lr=0.01, layout=layout,
        **kw)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    img = img_nchw if layout == "NCHW" else \
        np.transpose(img_nchw, (0, 2, 3, 1)).copy()
    traj = []
    for _ in range(steps):
        l = exe.run(main, feed={"data": img, "label": lab},
                    fetch_list=[loss])[0]
        traj.append(float(np.asarray(l).flatten()[0]))
    return traj


def test_nhwc_matches_nchw_trajectory():
    """Tight parity under the reference's own remove_bn methodology
    (test_parallel_executor_seresnext.py:38): a 50-layer BN stack
    amplifies the layout-dependent reduction-order noise chaotically
    (their FIXME(zcd) rationale), so the strict trajectory comparison
    drops BN; the full model is pinned at step 0 (forward + loss
    identical) and sanity-bounded after one update."""
    rng = np.random.RandomState(0)
    img = rng.randn(2, 3, 48, 48).astype("float32")
    lab = rng.randint(0, 5, (2, 1)).astype("int64")
    t_nchw = _run("NCHW", img, lab, remove_bn=True, remove_dropout=True)
    t_nhwc = _run("NHWC", img, lab, remove_bn=True, remove_dropout=True)
    np.testing.assert_allclose(t_nchw, t_nhwc, atol=2e-4, rtol=2e-4)


def test_nhwc_full_model_step0_exact():
    rng = np.random.RandomState(1)
    img = rng.randn(2, 3, 48, 48).astype("float32")
    lab = rng.randint(0, 5, (2, 1)).astype("int64")
    t_nchw = _run("NCHW", img, lab)
    t_nhwc = _run("NHWC", img, lab)
    assert abs(t_nchw[0] - t_nhwc[0]) < 1e-5, (t_nchw, t_nhwc)
    assert abs(t_nchw[1] - t_nhwc[1]) < 0.1 * max(1.0, abs(t_nchw[1]))
