"""reader.creator (reference python/paddle/reader/creator.py) and the
contrib HDFSClient (reference contrib/utils/hdfs_utils.py, local-backend
mode)."""

import os

import numpy as np

import paddle_tpu.reader as reader_pkg
from paddle_tpu.fluid.contrib import HDFSClient, multi_upload, \
    multi_download


def test_np_array_and_text_file_creators(tmp_path):
    arr = np.arange(12).reshape(4, 3)
    rows = list(reader_pkg.creator.np_array(arr)())
    assert len(rows) == 4
    np.testing.assert_array_equal(rows[2], arr[2])

    p = tmp_path / "lines.txt"
    p.write_text("alpha\nbeta\ngamma\n")
    lines = list(reader_pkg.creator.text_file(str(p))())
    assert lines == ["alpha", "beta", "gamma"]


def test_recordio_creator_roundtrip(tmp_path):
    from paddle_tpu.native import RecordIOWriter
    path = str(tmp_path / "data.recordio")
    with RecordIOWriter(path) as w:
        for i in range(5):
            w.write(b"rec-%d" % i)
    recs = list(reader_pkg.creator.recordio(path)())
    assert recs == [b"rec-%d" % i for i in range(5)]


def test_hdfs_client_local_backend(tmp_path):
    client = HDFSClient(configs={"fs.local.root": str(tmp_path / "hdfs")})
    src = tmp_path / "model.bin"
    src.write_bytes(b"weights")

    assert client.upload("/ckpt/model.bin", str(src))
    assert client.is_exist("/ckpt/model.bin")
    assert client.is_dir("/ckpt")
    assert not client.upload("/ckpt/model.bin", str(src))  # no overwrite
    assert client.upload("/ckpt/model.bin", str(src), overwrite=True)

    dst = tmp_path / "restored.bin"
    assert client.download("/ckpt/model.bin", str(dst))
    assert dst.read_bytes() == b"weights"

    assert client.ls("/ckpt") == ["/ckpt/model.bin"]
    assert client.rename("/ckpt/model.bin", "/ckpt/model2.bin")
    assert not client.is_exist("/ckpt/model.bin")
    assert client.delete("/ckpt/model2.bin")
    assert not client.is_exist("/ckpt/model2.bin")


def test_hdfs_multi_upload_download_shards(tmp_path):
    client = HDFSClient(configs={"fs.local.root": str(tmp_path / "hdfs")})
    local = tmp_path / "out"
    (local / "sub").mkdir(parents=True)
    for i in range(4):
        (local / "sub" / ("f%d" % i)).write_text(str(i))
    multi_upload(client, "/data", str(local))
    files = client.lsr("/data")
    assert len(files) == 4

    got0 = multi_download(client, "/data", str(tmp_path / "t0"),
                          trainer_id=0, trainers=2)
    got1 = multi_download(client, "/data", str(tmp_path / "t1"),
                          trainer_id=1, trainers=2)
    assert len(got0) == 2 and len(got1) == 2
    assert {os.path.basename(p) for p in got0} | \
        {os.path.basename(p) for p in got1} == {"f0", "f1", "f2", "f3"}


def test_contrib_inferencer_roundtrip(tmp_path):
    """contrib.Inferencer loads params saved by a training run and serves
    the same predictions (reference contrib/inferencer.py)."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.contrib import Inferencer

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.data("y", shape=[1])
        pred = fluid.layers.fc(x, size=1, name="infer_fc")
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGDOptimizer(0.05).minimize(loss)
    scope = fluid.executor.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    with fluid.executor.scope_guard(scope):
        exe.run(startup)
        for _ in range(10):
            xv = rng.randn(8, 4).astype(np.float32)
            yv = (xv.sum(1, keepdims=True) > 0).astype(np.float32)
            exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
        fluid.io.save_params(exe, str(tmp_path / "params"),
                             main_program=main)
        expected = np.asarray(exe.run(
            main.clone(for_test=True),
            feed={"x": np.ones((2, 4), np.float32),
                  "y": np.zeros((2, 1), np.float32)},
            fetch_list=[pred])[0])

    def infer_func():
        xi = fluid.layers.data("x", shape=[4])
        return fluid.layers.fc(xi, size=1, name="infer_fc")

    inf = Inferencer(infer_func, str(tmp_path / "params"),
                     place=fluid.CPUPlace())
    got = inf.infer({"x": np.ones((2, 4), np.float32)})[0]
    np.testing.assert_allclose(got, expected, atol=1e-5)


def test_contrib_op_freq_statistic():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid.contrib import op_freq_statistic

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        h = fluid.layers.fc(x, size=4, act="relu")
        h = fluid.layers.fc(h, size=4, act="relu")
        fluid.layers.mean(h)
    uni, adj = op_freq_statistic(main)
    assert uni["relu"] == 2
    assert any(k.endswith("->relu") for k in adj)
