"""Async training pipeline: device prefetch, in-flight dispatch,
deferred host sync (PIPELINE.md).

The contracts pinned here:

* prefetch_to_device — parity with the sync feed path, bounded-depth
  backpressure, clean worker shutdown on early exit, worker-death
  propagation as ReaderWorkerFailed, and the slow-host injection
  (tools/chaos.slow_host_reader) actually hidden by the queue;
* Executor.run(as_future=True) / ParallelExecutor.run(as_future=True) —
  FetchFuture results bit-equal to the sync return, one-shot resolution,
  watchdog wrapping the DRAIN;
* the Trainer's pipelined loop — bit-exact loss trajectory vs sync at
  depth >= 2 (same RNG folds: the parity net includes dropout), events
  in order, checkpointing at flush boundaries, and the depth-aware
  sentinel catching an injected NaN (skip + re-dispatch, rollback).
"""

import threading
import time
import warnings

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import reader as reader_mod
from paddle_tpu.fluid import sentinel as sentinel_mod
from paddle_tpu.fluid.executor import StepWatchdogTimeout
from paddle_tpu.fluid.pipeline import DispatchPipeline, FetchFuture
from paddle_tpu.reader import ReaderWorkerFailed

from tools import chaos


@pytest.fixture(autouse=True)
def _reset_pipeline_flags():
    yield
    fluid.set_flags({"async_dispatch_depth": 0,
                     "reader_prefetch_depth": 0,
                     "step_watchdog_secs": 0.0,
                     "sentinel_nan_check": False,
                     "sentinel_policy": "skip",
                     "sentinel_max_bad_steps": 3})


# ---------------------------------------------------------------------------
# prefetch_to_device
# ---------------------------------------------------------------------------

def test_prefetch_parity_and_device_staging():
    """Every item arrives, in order, and dict array values are staged
    as device arrays by the default prepare."""
    import jax

    def src():
        for i in range(16):
            yield {"x": np.full((3,), i, np.float32), "tag": i}

    out = list(reader_mod.prefetch_to_device(src, 3)())
    assert [o["tag"] for o in out] == list(range(16))
    for i, o in enumerate(out):
        assert isinstance(o["x"], jax.Array)
        np.testing.assert_array_equal(np.asarray(o["x"]),
                                      np.full((3,), i, np.float32))


def test_prefetch_backpressure_bounded_depth():
    """A stalled consumer bounds the producer: at most depth (queued)
    + 1 (in the worker's hand) + 1 (already yielded) items are ever
    pulled from the source."""
    pulled = []

    def src():
        for i in range(100):
            pulled.append(i)
            yield {"x": np.zeros(2, np.float32)}

    gen = reader_mod.prefetch_to_device(src, 2)()
    try:
        next(gen)
        deadline = time.time() + 2.0
        while time.time() < deadline and len(pulled) < 4:
            time.sleep(0.02)
        time.sleep(0.2)  # would overrun here if the bound leaked
        assert len(pulled) <= 4, \
            "prefetch ran %d items ahead of a stalled consumer" % \
            len(pulled)
    finally:
        gen.close()


def test_prefetch_clean_shutdown_on_early_exit():
    """Closing the generator mid-epoch (trainer exit, break) stops and
    joins the worker thread — no leak, no hang."""
    def src():
        i = 0
        while True:
            i += 1
            yield {"x": np.full((2,), i, np.float32)}

    gen = reader_mod.prefetch_to_device(src, 2)()
    next(gen)
    next(gen)
    gen.close()
    deadline = time.time() + 5.0
    while time.time() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.name == "paddle-tpu-prefetch" and t.is_alive()]
        if not alive:
            break
        time.sleep(0.05)
    assert not alive, "prefetch worker leaked after generator close"


def test_prefetch_source_death_raises():
    def src():
        yield {"x": np.zeros(2, np.float32)}
        raise RuntimeError("shard read failed")

    gen = reader_mod.prefetch_to_device(src, 2)()
    next(gen)
    with pytest.raises(ReaderWorkerFailed) as ei:
        for _ in gen:
            pass
    assert "shard read failed" in str(ei.value)
    assert ei.value.cause_repr is not None


def test_prefetch_prepare_death_raises():
    def src():
        for i in range(4):
            yield {"x": np.zeros(2, np.float32)}

    def bad_prepare(item):
        raise ValueError("prepare exploded")

    gen = reader_mod.prefetch_to_device(src, 2, prepare=bad_prepare)()
    with pytest.raises(ReaderWorkerFailed):
        list(gen)


def test_prefetch_hides_slow_host_stall():
    """The chaos slow-host injection: a reader costing ~35ms/batch fed
    to a consumer costing ~35ms/step runs ~2x faster through the
    prefetch queue (stall overlapped) than directly (serialized)."""
    stall_ms, n = 35.0, 8

    def src():
        for i in range(n):
            yield {"x": np.zeros(4, np.float32)}

    slowed = chaos.slow_host_reader(src, stall_ms)

    def consume(creator):
        t0 = time.perf_counter()
        for _ in creator():
            time.sleep(stall_ms / 1000.0)  # the "device step"
        return time.perf_counter() - t0

    t_sync = consume(slowed)
    t_pre = consume(reader_mod.prefetch_to_device(slowed, 4))
    assert t_pre < t_sync * 0.8, \
        "prefetch did not hide the host stall: %.3fs vs %.3fs" % \
        (t_pre, t_sync)


def test_prefetch_mesh_mode_commits_sharded_arrays():
    """Sharded prefetch (PIPELINE.md follow-up): with a mesh, the
    prefetch thread commits each batch array as a mesh-global sharded
    jax.Array (make_array_from_process_local_data) — batch dim on the
    data axis, scalars replicated, values bit-equal to the source."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.parallel.mesh import data_parallel_mesh, DATA_AXIS

    mesh = data_parallel_mesh(4, use_cuda=False)

    def src():
        for i in range(6):
            yield {"x": np.arange(8 * 3, dtype=np.float32)
                        .reshape(8, 3) + i,
                   "lr": np.float32(0.5)}

    out = list(reader_mod.prefetch_to_device(src, 2, mesh=mesh)())
    assert len(out) == 6
    for i, item in enumerate(out):
        x = item["x"]
        assert isinstance(x, jax.Array)
        assert x.sharding == NamedSharding(mesh, P(DATA_AXIS, None)), \
            "batch feed not sharded on the mesh data axis: %r" \
            % (x.sharding,)
        np.testing.assert_array_equal(
            np.asarray(x),
            np.arange(8 * 3, dtype=np.float32).reshape(8, 3) + i)
        lr = item["lr"]
        assert isinstance(lr, jax.Array)
        assert lr.sharding == NamedSharding(mesh, P())


def test_pe_run_accepts_presharded_prefetch_feeds():
    """ParallelExecutor fed pre-sharded arrays (prefetch mesh mode)
    computes the same losses as host feeds, and its feed prep passes
    the already-committed array through unchanged (no per-dispatch
    re-commit — the point of sharding on the prefetch thread)."""
    xs, ys = _xy(8)

    def build_pe():
        main, startup, loss = _build_program()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name,
                                    main_program=main)
        return pe, loss

    with fluid.scope_guard(fluid.Scope()):
        pe, loss = build_pe()
        host = [pe.run(fetch_list=[loss], feed={"x": xs, "y": ys})[0]
                for _ in range(3)]
    with fluid.scope_guard(fluid.Scope()):
        pe2, loss2 = build_pe()

        def src():
            for _ in range(3):
                yield {"x": xs, "y": ys}

        feeds = list(reader_mod.prefetch_to_device(
            src, 2, mesh=pe2.mesh)())
        prepped = pe2._prepare_feeds(feeds[0])
        assert prepped["x"] is feeds[0]["x"], \
            "pre-sharded feed was re-committed on the dispatch path"
        sharded = [pe2.run(fetch_list=[loss2], feed=f)[0]
                   for f in feeds]
    for a, b in zip(host, sharded):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# FetchFuture / executor futures
# ---------------------------------------------------------------------------

def _build_net():
    def train_func():
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, size=1)
        return fluid.layers.mean(fluid.layers.square_error_cost(pred, y))

    def optimizer_func():
        return fluid.optimizer.SGD(learning_rate=0.05)

    return train_func, optimizer_func


def _build_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def _xy(batch=8):
    rng = np.random.RandomState(0)
    xs = rng.randn(batch, 4).astype(np.float32)
    return xs, xs.sum(axis=1, keepdims=True)


def test_executor_future_matches_sync_bit_exact():
    xs, ys = _xy()
    main, startup, loss = _build_program()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        sync = [exe.run(main, feed={"x": xs, "y": ys},
                        fetch_list=[loss])[0] for _ in range(4)]
    main2, startup2, loss2 = _build_program()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe2.run(startup2)
        futs = [exe2.run(main2, feed={"x": xs, "y": ys},
                         fetch_list=[loss2], as_future=True)
                for _ in range(4)]
        assert all(isinstance(f, FetchFuture) for f in futs)
        got = [f.result()[0] for f in futs]
    for a, b in zip(sync, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fetch_future_resolves_once_and_caches():
    calls = []

    def post(vals, rn):
        calls.append(1)
        return list(vals)

    fut = FetchFuture([np.float32(3.0)], post=post)
    assert not fut.done()
    a = fut.result()
    b = fut.result()
    assert a is b and len(calls) == 1 and fut.done() and fut.ready()


def test_fetch_future_watchdog_wraps_drain():
    """The watchdog guards the DRAIN: a resolve that wedges raises
    StepWatchdogTimeout out of result() instead of hanging the loop."""
    def wedged(vals, rn):
        time.sleep(5.0)
        return list(vals)

    fluid.set_flags({"step_watchdog_secs": 0.2})
    fut = FetchFuture([np.float32(1.0)], post=wedged, what="test drain")
    t0 = time.perf_counter()
    with pytest.raises(StepWatchdogTimeout):
        fut.result()
    assert time.perf_counter() - t0 < 3.0


def test_async_dispatch_skips_per_step_watchdog_sync():
    """With the watchdog flag set, as_future dispatch must NOT force a
    per-step block (that was the sync-mode cost the pipeline removes):
    the future resolves fine afterwards."""
    xs, ys = _xy()
    main, startup, loss = _build_program()
    exe = fluid.Executor(fluid.CPUPlace())
    fluid.set_flags({"step_watchdog_secs": 30.0})
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fut = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss],
                      as_future=True)
        val = fut.result(watchdog_scale=2)[0]
    assert np.isfinite(np.asarray(val)).all()


def test_dispatch_pipeline_backpressure_and_flush():
    resolved = []

    def mk(i):
        return FetchFuture([np.float32(i)],
                           post=lambda vals, rn, i=i: resolved.append(i)
                           or [i])

    p = DispatchPipeline(2)
    drained = []
    for i in range(5):
        drained += p.submit(mk(i), step=i)
    # depth 2: submits 0..4 force drains of 0,1,2 (oldest first)
    assert [m["step"] for _, m in drained] == [0, 1, 2]
    assert resolved == [0, 1, 2] and len(p) == 2
    rest = p.drain_all()
    assert [m["step"] for _, m in rest] == [3, 4] and len(p) == 0
    # discard path: nothing resolved
    p2 = DispatchPipeline(3)
    p2.submit(mk(10))
    p2.submit(mk(11))
    dropped = p2.discard_inflight()
    assert len(dropped) == 2 and len(p2) == 0
    assert 10 not in resolved and 11 not in resolved


def test_parallel_executor_future_and_batched_fetch():
    """PE as_future matches the sync return; both ride the batched
    device_get path."""
    xs, ys = _xy(8)
    feed = {"x": xs, "y": ys}

    def build_pe():
        main, startup, loss = _build_program()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name,
                                    main_program=main)
        return pe, loss

    with fluid.scope_guard(fluid.Scope()):
        pe, loss = build_pe()
        sync = [pe.run(fetch_list=[loss], feed=feed)[0]
                for _ in range(3)]
    with fluid.scope_guard(fluid.Scope()):
        pe2, loss2 = build_pe()
        futs = [pe2.run(fetch_list=[loss2], feed=feed, as_future=True)
                for _ in range(3)]
        got = [f.result()[0] for f in futs]
    for a, b in zip(sync, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# Trainer: pipelined loop
# ---------------------------------------------------------------------------

def _dropout_net():
    """Parity net WITH dropout so the trajectory check also pins the
    RNG step folds (a fold skew would flip masks and split the paths)."""
    def train_func():
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.data("y", shape=[1], dtype="float32")
        h = fluid.layers.fc(x, size=8, act="relu")
        h = fluid.layers.dropout(h, dropout_prob=0.3)
        pred = fluid.layers.fc(h, size=1)
        return fluid.layers.mean(fluid.layers.square_error_cost(pred, y))

    def optimizer_func():
        return fluid.optimizer.SGD(learning_rate=0.05)

    return train_func, optimizer_func


def _regression_data(n=10, seed=0):
    rng = np.random.RandomState(seed)
    return [(x, np.array([x.sum()], np.float32))
            for x in [rng.randn(4).astype(np.float32) for _ in range(n)]]


def _run_trainer_pipeline(data, depth, prefetch=0, num_epochs=2,
                          net=_dropout_net, ckpt_dir=None,
                          step_interval=4):
    """Train in a fresh scope under the given pipeline config; returns
    (losses in EndStepEvent order, (epoch, step) event ids, params)."""
    train_func, optimizer_func = net()

    def reader():
        for x, y in data:
            yield [(x, y)]

    fluid.set_flags({"async_dispatch_depth": depth,
                     "reader_prefetch_depth": prefetch})
    try:
        with fluid.scope_guard(fluid.Scope()) as scope:
            cfg = None
            if ckpt_dir is not None:
                cfg = fluid.contrib.CheckpointConfig(
                    checkpoint_dir=ckpt_dir, step_interval=step_interval)
            trainer = fluid.contrib.Trainer(
                train_func, optimizer_func, place=fluid.CPUPlace(),
                checkpoint_config=cfg)
            losses, ids = [], []

            def handler(ev):
                if isinstance(ev, fluid.contrib.EndStepEvent):
                    losses.append(np.asarray(ev.metrics[0]).copy())
                    ids.append((ev.epoch, ev.step))

            trainer.train(num_epochs=num_epochs, event_handler=handler,
                          reader=reader, feed_order=["x", "y"])
            from paddle_tpu.fluid import functionalizer
            names = functionalizer.persistable_names(
                trainer.train_program)
            params = {n: np.asarray(scope.get(n)) for n in names
                      if scope.get(n) is not None}
            return losses, ids, params
    finally:
        fluid.set_flags({"async_dispatch_depth": 0,
                         "reader_prefetch_depth": 0})


def test_trainer_async_trajectory_bit_exact():
    """Acceptance: async (depth >= 2) reproduces the sync loss
    trajectory BIT-EXACTLY — dropout included, so RNG step folds and
    dispatch order must match, not just converge."""
    data = _regression_data()
    l0, ids0, p0 = _run_trainer_pipeline(data, depth=0)
    l3, ids3, p3 = _run_trainer_pipeline(data, depth=3)
    assert len(l0) == len(l3) == 2 * len(data)
    assert ids0 == ids3   # EndStepEvents in step order, lag <= depth
    for i, (a, b) in enumerate(zip(l0, l3)):
        np.testing.assert_array_equal(
            a, b, err_msg="loss diverged at drained step %d" % i)
    for n in p0:
        np.testing.assert_array_equal(
            p0[n], p3[n], err_msg="param %r diverged" % n)


def test_trainer_async_plus_prefetch_trajectory_bit_exact():
    """Both pipeline stages on at once (prefetch staging + in-flight
    dispatch) — still bit-exact."""
    data = _regression_data()
    l0, _, p0 = _run_trainer_pipeline(data, depth=0)
    lp, _, pp = _run_trainer_pipeline(data, depth=2, prefetch=3)
    for a, b in zip(l0, lp):
        np.testing.assert_array_equal(a, b)
    for n in p0:
        np.testing.assert_array_equal(p0[n], pp[n])


def test_trainer_async_checkpoints_at_flush_boundaries(tmp_path):
    """Checkpointing under async dispatch: saves land at pipeline-flush
    boundaries (scope state == saved step ids), the vault verifies, and
    the trajectory is unchanged by the saves."""
    from paddle_tpu.fluid import checkpoint as ckpt
    data = _regression_data(8)
    l0, _, p0 = _run_trainer_pipeline(data, depth=0)
    vault = str(tmp_path / "vault")
    l3, _, p3 = _run_trainer_pipeline(data, depth=3, ckpt_dir=vault,
                                      step_interval=4)
    for a, b in zip(l0, l3):
        np.testing.assert_array_equal(a, b)
    for n in p0:
        np.testing.assert_array_equal(p0[n], p3[n])
    latest = ckpt.latest_checkpoint(vault)
    assert latest is not None
    manifest = ckpt.verify_checkpoint_dir(latest)
    meta = ckpt.normalize_meta(manifest["meta"])
    assert meta["step"] >= 4  # at least the first flush-boundary save


def test_trainer_async_sentinel_skip_redispatches_inflight():
    """Depth-aware skip: the bad step reverts, the in-flight window is
    discarded un-observed and its batches re-dispatched — every batch
    still gets an EndStepEvent, params stay finite."""
    data = _regression_data()

    def reader():
        for x, y in data:
            yield [(x, y)]

    poisoned = chaos.nan_poison_reader(reader, poison_steps={4})
    train_func, optimizer_func = _build_net()
    fluid.set_flags({"sentinel_nan_check": True,
                     "sentinel_policy": "skip",
                     "sentinel_max_bad_steps": 5,
                     "async_dispatch_depth": 3})
    with fluid.scope_guard(fluid.Scope()) as scope:
        trainer = fluid.contrib.Trainer(train_func, optimizer_func,
                                        place=fluid.CPUPlace())
        steps = []

        def handler(ev):
            if isinstance(ev, fluid.contrib.EndStepEvent):
                steps.append(ev.step)

        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            trainer.train(num_epochs=1, event_handler=handler,
                          reader=poisoned, feed_order=["x", "y"])
        msgs = [str(w.message) for w in caught]
        assert any("reverted" in m for m in msgs), msgs
        assert any("re-dispatched" in m for m in msgs), msgs
        assert sorted(steps) == list(range(len(data))), steps
        from paddle_tpu.fluid import functionalizer
        for n in functionalizer.persistable_names(trainer.train_program):
            v = scope.get(n)
            if v is not None:
                assert np.all(np.isfinite(np.asarray(v))), n


def test_trainer_async_sentinel_rollback(tmp_path):
    """Acceptance: with dispatch depth > 1 the sentinel still catches
    an injected NaN streak and rolls back to the last-good checkpoint."""
    data = _regression_data(12)

    def reader():
        for x, y in data:
            yield [(x, y)]

    poisoned = chaos.nan_poison_reader(reader, poison_steps={5, 6})
    train_func, optimizer_func = _build_net()
    fluid.set_flags({"sentinel_nan_check": True,
                     "sentinel_policy": "rollback",
                     "sentinel_max_bad_steps": 2,
                     "async_dispatch_depth": 3})
    with fluid.scope_guard(fluid.Scope()) as scope:
        cfg = fluid.contrib.CheckpointConfig(
            checkpoint_dir=str(tmp_path / "vault"), step_interval=3)
        trainer = fluid.contrib.Trainer(train_func, optimizer_func,
                                        place=fluid.CPUPlace(),
                                        checkpoint_config=cfg)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            trainer.train(num_epochs=1, event_handler=lambda ev: None,
                          reader=poisoned, feed_order=["x", "y"])
        msgs = [str(w.message) for w in caught]
        assert any("reverted" in m for m in msgs), msgs
        assert any("rolled back" in m for m in msgs), msgs
        from paddle_tpu.fluid import functionalizer
        for n in functionalizer.persistable_names(trainer.train_program):
            v = scope.get(n)
            if v is not None:
                assert np.all(np.isfinite(np.asarray(v))), n


def test_trainer_test_deferred_drain_parity():
    """Trainer.test rides the deferred-drain path: async depth changes
    neither the result nor the per-batch float64 accumulation."""
    data = _regression_data(6)

    def reader():
        for x, y in data:
            yield [(x, y)]

    def eval_once(depth):
        # fresh trainer + scope per call: Trainer.test mutates scope
        # state across calls (pre-existing), so parity must compare two
        # identically-constructed runs, not two sequential calls
        train_func, optimizer_func = _build_net()
        fluid.set_flags({"async_dispatch_depth": depth})
        try:
            with fluid.scope_guard(fluid.Scope()):
                trainer = fluid.contrib.Trainer(
                    train_func, optimizer_func, place=fluid.CPUPlace())
                return trainer.test(reader, feed_order=["x", "y"])
        finally:
            fluid.set_flags({"async_dispatch_depth": 0})

    base = eval_once(0)
    deferred = eval_once(3)
    assert len(base) == len(deferred) == 1
    np.testing.assert_array_equal(base[0], deferred[0])


def test_sentinel_depth_bookkeeping():
    s = sentinel_mod.AnomalySentinel(max_bad_steps=3, policy="skip",
                                     pipeline_depth=4)
    assert s.pipeline_depth == 4
    assert s.observe([("loss", np.float32(1.0))], step=0) == \
        sentinel_mod.OK
    assert s.last_step_observed == 0 and s.steps_observed == 1
    assert s.observe([("loss", np.float32(np.nan))], step=1) == \
        sentinel_mod.SKIP
    assert s.note_inflight_discarded(3) == 3
    assert s.total_discarded == 3 and s.max_observe_lag == 3
    # discards never touch the consecutive-bad streak
    assert s.consecutive_bad == 1
    assert s.observe([("loss", np.float32(1.0))], step=2) == \
        sentinel_mod.OK
    assert s.consecutive_bad == 0
