"""AOT training export: train from a saved artifact with no Program and
no trace — in Python (AotTrainer) and from pure C (capi_train_demo).

Reference analogue: the C++ train/demo
(paddle/fluid/train/demo/demo_trainer.cc, train/test_train_recognize_
digits.cc) — training driven from a saved program by a non-Python host.
Here the artifact is a versioned StableHLO module of the WHOLE optimizer
step plus wire-encoded state; parity is exact against the live Executor.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.train_export import save_aot_trainer, load_aot_trainer

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
NATIVE = os.path.join(REPO, "native")


def _build():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=16, act="relu")
        pred = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
        fluid.optimizer.Momentum(learning_rate=0.05,
                                 momentum=0.9).minimize(loss)
    return main, startup, loss


def _feeds(n, batch=4, rng_seed=0):
    rng = np.random.RandomState(rng_seed)
    return [{"x": rng.randn(batch, 8).astype(np.float32),
             "y": rng.randn(batch, 1).astype(np.float32)}
            for _ in range(n)]


def test_aot_trainer_matches_executor(tmp_path):
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feeds = _feeds(6)
    art = str(tmp_path / "art")
    with fluid.scope_guard(scope):
        exe.run(startup)
        save_aot_trainer(art, main, ["x", "y"], [loss], scope=scope,
                         batch_size=4)
        ref = [float(np.asarray(exe.run(main, feed=f,
                                        fetch_list=[loss])[0]).ravel()[0])
               for f in feeds]

    t = load_aot_trainer(art)
    got = [float(t.step(f)[0].ravel()[0]) for f in feeds[:3]]
    np.testing.assert_allclose(ref[:3], got, rtol=1e-5)

    # checkpoint mid-trajectory, resume in a new handle: exact continuation
    ck = str(tmp_path / "ck")
    t.save(ck)
    t2 = load_aot_trainer(ck)
    assert t2.step_count == 3
    got2 = [float(t2.step(f)[0].ravel()[0]) for f in feeds[3:]]
    np.testing.assert_allclose(ref[3:], got2, rtol=1e-5)


def test_aot_trainer_fresh_process_no_trace(tmp_path):
    """A new process must train from the artifact WITHOUT tracing: jit
    compilation of new computations is poisoned in the child."""
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feeds = _feeds(3)
    art = str(tmp_path / "art")
    with fluid.scope_guard(scope):
        exe.run(startup)
        save_aot_trainer(art, main, ["x", "y"], [loss], scope=scope,
                         batch_size=4)
        ref = [float(np.asarray(exe.run(main, feed=f,
                                        fetch_list=[loss])[0]).ravel()[0])
               for f in feeds]

    child = r"""
import sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
# poison tracing: deserialized-module calls must not build new jaxprs
import jax._src.interpreters.partial_eval as pe
def _no_trace(*a, **k):
    raise AssertionError("tracing happened in the AOT child")
pe.trace_to_jaxpr_dynamic = _no_trace
from paddle_tpu.fluid.train_export import load_aot_trainer
t = load_aot_trainer(sys.argv[1])
rng = np.random.RandomState(0)
for _ in range(3):
    f = {"x": rng.randn(4, 8).astype(np.float32),
         "y": rng.randn(4, 1).astype(np.float32)}
    print("%.6f" % float(t.step(f)[0].ravel()[0]))
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run([sys.executable, "-c", child, art],
                          capture_output=True, text=True, timeout=600,
                          env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    got = [float(v) for v in proc.stdout.strip().splitlines()]
    np.testing.assert_allclose(ref, got, rtol=1e-5)


def test_feed_validation(tmp_path):
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    art = str(tmp_path / "art")
    with fluid.scope_guard(scope):
        exe.run(startup)
        save_aot_trainer(art, main, ["x", "y"], [loss], scope=scope,
                         batch_size=4)
    t = load_aot_trainer(art)
    with pytest.raises(ValueError):
        t.step({"x": np.zeros((2, 8), np.float32),
                "y": np.zeros((2, 1), np.float32)})   # wrong batch
    with pytest.raises(KeyError):
        t.step({"x": np.zeros((4, 8), np.float32)})   # missing feed


@pytest.fixture(scope="module")
def train_demo_bin():
    if not os.path.exists("/usr/bin/gcc") and not os.path.exists(
            "/usr/bin/cc") and not os.path.exists("/usr/local/bin/gcc"):
        pytest.skip("no C toolchain")
    subprocess.run(["make", "libpaddle_tpu_capi.so", "capi_train_demo"],
                   cwd=NATIVE, check=True, capture_output=True,
                   timeout=600)
    return os.path.join(NATIVE, "capi_train_demo")


def test_c_trainer_matches_python(train_demo_bin, tmp_path):
    """The pure-C client trains the artifact, checkpoints halfway,
    resumes from the checkpoint, and every loss matches an in-process
    AotTrainer driven with the same deterministic feeds."""
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    art = str(tmp_path / "art")
    with fluid.scope_guard(scope):
        exe.run(startup)
        save_aot_trainer(art, main, ["x", "y"], [loss], scope=scope,
                         batch_size=4)

    steps, batch, feat = 6, 4, 8

    def c_batch(step):
        # mirrors fill_batch() in capi_train_demo.c
        x = np.array([((i + 13 * step) * 37 % 65) - 32.0
                      for i in range(batch * feat)],
                     np.float32).reshape(batch, feat) / 32.0
        y = np.array([((i + 7 * step) * 29 % 33) - 16.0
                      for i in range(batch)],
                     np.float32).reshape(batch, 1) / 16.0
        return {"x": x, "y": y}

    t = load_aot_trainer(art)
    ref = [float(t.step(c_batch(s))[0].ravel()[0]) for s in range(steps)]

    ck = str(tmp_path / "ck")
    env = dict(os.environ)
    env["PD_CAPI_PLATFORM"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [train_demo_bin, art, str(steps), str(batch), str(feat), ck],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, (proc.stdout[-500:], proc.stderr[-2000:])
    assert "CAPI-TRAIN-OK" in proc.stdout
    assert "resumed" in proc.stdout

    got = {}
    for line in proc.stdout.splitlines():
        if line.startswith("loss "):
            _, s, v = line.split()
            got[int(s)] = float(v)
    assert sorted(got) == list(range(steps))
    np.testing.assert_allclose(ref, [got[s] for s in range(steps)],
                               rtol=1e-4, atol=1e-6)


def test_multi_platform_artifact_serves_on_cpu(tmp_path):
    """platforms=("cpu","tpu") embeds both lowerings in ONE artifact:
    exported on this CPU host it must still train here, and the stored
    module must declare both platforms (so a TPU host accepts it)."""
    from jax import export as jax_export
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feeds = _feeds(2)
    art = str(tmp_path / "art")
    with fluid.scope_guard(scope):
        exe.run(startup)
        save_aot_trainer(art, main, ["x", "y"], [loss], scope=scope,
                         batch_size=4, platforms=("cpu", "tpu"))
        ref = [float(np.asarray(exe.run(main, feed=f,
                                        fetch_list=[loss])[0]).ravel()[0])
               for f in feeds]
    with open(os.path.join(art, "train_step.bin"), "rb") as f:
        exp = jax_export.deserialize(f.read())
    assert set(p.lower() for p in exp.platforms) == {"cpu", "tpu"}
    t = load_aot_trainer(art)
    got = [float(t.step(f)[0].ravel()[0]) for f in feeds]
    np.testing.assert_allclose(ref, got, rtol=1e-5)
