"""Test config: force a virtual 8-device CPU platform so multi-chip sharding
paths run without TPU hardware (SURVEY.md §4 fixtures note — the analogue of
the reference's fake multi-device contexts in op-handle tests).

Note: the environment's axon site hook imports jax at interpreter start, so
JAX_PLATFORMS in os.environ is read too early to help — we must go through
jax.config. XLA_FLAGS is still honored at backend init, which happens later.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("CPU_NUM", "8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`: the marker gates subprocess-heavy
    # bench smokes that have their own standalone entry points
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 sweep")
