"""bench_zoo --resume retention invariants.

The zoo sweep's tracked JSON holds hour-scale real-chip records; the
resume/preserve/supersede logic guards them across filtered passes,
mid-sweep aborts, and mixed feed-staging sweeps (reference discipline:
benchmark/README.md published-numbers contract). These tests stub the
per-config subprocess and the backend probe so the invariants run
in-suite without a chip.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

import bench_zoo


def _run(monkeypatch, tmp_path, argv, backend="tpu", fail=()):
    """Drive bench_zoo.main with stubbed probe + per-config runner."""
    out = tmp_path / "zoo.json"
    ran = []

    def fake_run_config(name, extra, batch, iterations, force_cpu):
        ran.append(name)
        if name in fail:
            return {"config": name, "error": "boom", "wall_sec": 0.1}
        rec = {"config": name, "model": name.split("_")[0],
               "batch_size": batch, "examples_per_sec": 100.0,
               "wall_sec": 0.1}
        if "--staged_feed" in extra:
            rec["staged_feed"] = int(
                extra[extra.index("--staged_feed") + 1])
            rec["staged_transfer"] = True
        return rec

    monkeypatch.setattr(bench_zoo, "probe_backend", lambda **kw: backend)
    monkeypatch.setattr(bench_zoo, "run_config", fake_run_config)
    monkeypatch.setattr(sys, "argv",
                        ["bench_zoo.py", "--out", str(out)] + argv)
    try:
        bench_zoo.main()
        code = 0
    except SystemExit as e:
        code = e.code or 0
    with open(out) as f:
        data = json.load(f)
    return data, ran, code


def _rows(data):
    return sorted((r["config"], r.get("staged_feed", 0),
                   bool(r.get("error"))) for r in data["configs"])


def test_only_filter_preserves_unreached_records(monkeypatch, tmp_path):
    data, ran, _ = _run(monkeypatch, tmp_path,
                        ["--only", "mnist_cnn,vgg16_cifar10"])
    assert len(data["configs"]) == 2
    # a second, filtered pass must not delete the other completed row
    data, ran, _ = _run(monkeypatch, tmp_path,
                        ["--only", "mnist_cnn", "--resume"])
    assert ran == []          # same staging: kept, not re-run
    assert len(data["configs"]) == 2


def test_staged_resume_remeasures_but_keeps_hostfeed_rows(
        monkeypatch, tmp_path):
    data, _, _ = _run(monkeypatch, tmp_path, ["--only", "mnist_cnn"])
    assert _rows(data) == [("mnist_cnn", 0, False)]
    # staged resume: host-feed row is NOT a match (re-measure) and NOT
    # discarded (different measurement, kept alongside)
    data, ran, _ = _run(monkeypatch, tmp_path,
                        ["--only", "mnist_cnn", "--resume",
                         "--staged", "4"])
    assert ran == ["mnist_cnn"]
    assert _rows(data) == [("mnist_cnn", 0, False),
                           ("mnist_cnn", 4, False)]
    # resuming the staged sweep again: both rows survive, nothing re-runs
    data, ran, _ = _run(monkeypatch, tmp_path,
                        ["--only", "mnist_cnn", "--resume",
                         "--staged", "4"])
    assert ran == []
    assert _rows(data) == [("mnist_cnn", 0, False),
                           ("mnist_cnn", 4, False)]


def test_failed_rerun_supersedes_nothing(monkeypatch, tmp_path):
    data, _, _ = _run(monkeypatch, tmp_path, ["--only", "mnist_cnn"])
    # the re-measure fails: the completed row must survive next to the
    # error row, and --require_tpu must exit nonzero
    data, _, code = _run(monkeypatch, tmp_path,
                         ["--only", "mnist_cnn", "--resume",
                          "--staged", "4", "--require_tpu"],
                         fail={"mnist_cnn"})
    assert code == 5
    assert _rows(data) == [("mnist_cnn", 0, False),
                           ("mnist_cnn", 0, True)]


def test_fresh_rerun_supersedes_same_staging_row(monkeypatch, tmp_path):
    data, _, _ = _run(monkeypatch, tmp_path, ["--only", "mnist_cnn"])
    # same-staging re-measure WITHOUT --resume: one row, not two
    data, ran, _ = _run(monkeypatch, tmp_path, ["--only", "mnist_cnn"])
    assert ran == ["mnist_cnn"]
    assert _rows(data) == [("mnist_cnn", 0, False)]
