"""average_accumulates reference-kernel oracle
(average_accumulates_op.h restated, stepped over a trajectory).

The subtle part: the roll condition is
  num_accumulates >= min_average_window AND
  num_accumulates >= std::min<int64_t>(max_average_window,
                                       num_updates * average_window)
where the C++ min FORCES the float product to int64, truncating toward
zero — so the window rolls at num_acc == floor(num_updates *
average_window), one step earlier than an un-truncated float compare.
"""

import numpy as np

from tests.test_op_tail import run_op


def oracle_step(state, p, avg_window, max_w, min_w, k_max=16384):
    s1, s2, s3, num_acc, old_num, num_upd = state
    num_upd += 1
    num_acc += 1
    s1 = s1 + p
    if num_upd % k_max == 0:
        s2 = s2 + s1
        s1 = np.zeros_like(s1)
    window = min(max_w, int(num_upd * avg_window))   # int64 truncation
    if num_acc >= min_w and num_acc >= window:
        s3 = s1 + s2
        s1 = np.zeros_like(s1)
        s2 = np.zeros_like(s2)
        old_num = num_acc
        num_acc = 0
    return s1, s2, s3, num_acc, old_num, num_upd


def test_trajectory_matches_reference_including_truncation():
    rng = np.random.RandomState(3)
    n = 5
    s1 = np.zeros(n, np.float32)
    s2 = np.zeros(n, np.float32)
    s3 = np.zeros(n, np.float32)
    num_acc = old_num = num_upd = 0
    attrs = {"average_window": 0.5, "max_average_window": 100,
             "min_average_window": 1}
    state = (s1, s2, s3, num_acc, old_num, num_upd)
    for step in range(14):
        p = rng.randn(n).astype(np.float32)
        out = run_op("average_accumulates", {
            "param": p,
            "in_sum_1": state[0], "in_sum_2": state[1],
            "in_sum_3": state[2],
            "in_num_accumulates": np.array([state[3]], np.int64),
            "in_old_num_accumulates": np.array([state[4]], np.int64),
            "in_num_updates": np.array([state[5]], np.int64),
        }, attrs)
        state = oracle_step(state, p, 0.5, 100, 1)
        for got, want, name in [
                (out["out_sum_1"], state[0], "sum_1"),
                (out["out_sum_2"], state[1], "sum_2"),
                (out["out_sum_3"], state[2], "sum_3")]:
            np.testing.assert_allclose(
                np.asarray(got), want, atol=1e-5,
                err_msg="%s diverged at step %d" % (name, step))
        assert int(np.asarray(out["out_num_accumulates"])) == state[3], step
        assert int(np.asarray(out["out_old_num_accumulates"])) == state[4]
        assert int(np.asarray(out["out_num_updates"])) == state[5]
