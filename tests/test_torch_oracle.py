"""Independent-oracle semantics audit: core layers vs torch (CPU).

Most tests in this suite validate against numpy restatements written
from the same understanding of the spec — an independent framework
catches wrong-default bugs those can't (padding/dilation conventions,
avg-pool exclusive vs count_include_pad, LRN's alpha scaling, BN eps
placement). Reference kernels: conv_op.cc, pool_op.cc, batch_norm_op.cc,
lrn_op.cc, conv_transpose_op.cc."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as TF  # noqa: E402

import paddle_tpu.fluid as fluid  # noqa: E402


def _run(build, feed, weights=None):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        out = build()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        sc = fluid.executor.global_scope()
        for k, v in (weights or {}).items():
            sc.set(k, v)
        (o,) = exe.run(main, feed=feed, fetch_list=[out])
    return np.asarray(o)


@pytest.fixture(scope="module")
def x():
    return np.random.RandomState(0).randn(2, 4, 8, 8).astype(np.float32)


def test_conv2d_stride_pad_dilation(x):
    w = np.random.RandomState(1).randn(6, 4, 3, 3).astype(np.float32)

    def b():
        xi = fluid.layers.data("x", shape=[4, 8, 8], dtype="float32")
        return fluid.layers.conv2d(
            xi, num_filters=6, filter_size=3, stride=2, padding=1,
            dilation=2, bias_attr=False,
            param_attr=fluid.ParamAttr(name="w"))

    got = _run(b, {"x": x}, {"w": w})
    ref = TF.conv2d(torch.tensor(x), torch.tensor(w), stride=2,
                    padding=1, dilation=2).numpy()
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_conv2d_groups(x):
    w = np.random.RandomState(2).randn(6, 2, 3, 3).astype(np.float32)

    def b():
        xi = fluid.layers.data("x", shape=[4, 8, 8], dtype="float32")
        return fluid.layers.conv2d(
            xi, num_filters=6, filter_size=3, groups=2, padding=1,
            bias_attr=False, param_attr=fluid.ParamAttr(name="w"))

    got = _run(b, {"x": x}, {"w": w})
    ref = TF.conv2d(torch.tensor(x), torch.tensor(w), padding=1,
                    groups=2).numpy()
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_conv2d_transpose(x):
    w = np.random.RandomState(3).randn(4, 3, 3, 3).astype(np.float32)

    def b():
        xi = fluid.layers.data("x", shape=[4, 8, 8], dtype="float32")
        return fluid.layers.conv2d_transpose(
            xi, num_filters=3, filter_size=3, stride=2, padding=1,
            bias_attr=False, param_attr=fluid.ParamAttr(name="w"))

    got = _run(b, {"x": x}, {"w": w})
    ref = TF.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                              stride=2, padding=1).numpy()
    np.testing.assert_allclose(got, ref, atol=1e-5)


@pytest.mark.parametrize("exclusive,ceil_mode",
                         [(True, False), (False, False), (True, True)])
def test_avg_pool_exclusive_and_ceil(x, exclusive, ceil_mode):
    """paddle `exclusive` is torch's count_include_pad INVERTED; ceil_mode
    changes the output grid."""

    def b():
        xi = fluid.layers.data("x", shape=[4, 8, 8], dtype="float32")
        return fluid.layers.pool2d(
            xi, pool_size=3, pool_stride=2, pool_padding=1,
            pool_type="avg", ceil_mode=ceil_mode, exclusive=exclusive)

    got = _run(b, {"x": x})
    ref = TF.avg_pool2d(torch.tensor(x), 3, 2, 1, ceil_mode=ceil_mode,
                        count_include_pad=not exclusive).numpy()
    np.testing.assert_allclose(got, ref, atol=1e-6)


def test_max_pool(x):
    def b():
        xi = fluid.layers.data("x", shape=[4, 8, 8], dtype="float32")
        return fluid.layers.pool2d(xi, pool_size=2, pool_stride=2,
                                   pool_type="max")

    got = _run(b, {"x": x})
    np.testing.assert_allclose(
        got, TF.max_pool2d(torch.tensor(x), 2, 2).numpy(), atol=1e-6)


def test_batch_norm_inference_stats(x):
    rng = np.random.RandomState(4)
    gamma = rng.rand(4).astype(np.float32) + 0.5
    beta = rng.randn(4).astype(np.float32)
    mean = rng.randn(4).astype(np.float32)
    var = rng.rand(4).astype(np.float32) + 0.5

    def b():
        xi = fluid.layers.data("x", shape=[4, 8, 8], dtype="float32")
        return fluid.layers.batch_norm(
            xi, is_test=True, epsilon=1e-5,
            param_attr=fluid.ParamAttr(name="g"),
            bias_attr=fluid.ParamAttr(name="b"),
            moving_mean_name="m", moving_variance_name="v")

    got = _run(b, {"x": x},
               {"g": gamma, "b": beta, "m": mean, "v": var})
    ref = TF.batch_norm(torch.tensor(x), torch.tensor(mean),
                        torch.tensor(var), torch.tensor(gamma),
                        torch.tensor(beta), False, 0.9, 1e-5).numpy()
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_lrn_alpha_convention(x):
    """paddle lrn alpha is PER-ELEMENT; torch's is divided by size —
    alpha_torch = alpha_paddle * n (lrn_op.cc)."""

    def b():
        xi = fluid.layers.data("x", shape=[4, 8, 8], dtype="float32")
        return fluid.layers.lrn(xi, n=5, k=1.0, alpha=1e-4, beta=0.75)

    got = _run(b, {"x": x})
    ref = TF.local_response_norm(torch.tensor(x), size=5, alpha=1e-4 * 5,
                                 beta=0.75, k=1.0).numpy()
    np.testing.assert_allclose(got, ref, atol=1e-6)


def test_softmax_last_dim(x):
    def b():
        xi = fluid.layers.data("x", shape=[4, 8, 8], dtype="float32")
        return fluid.layers.softmax(xi)

    got = _run(b, {"x": x})
    np.testing.assert_allclose(
        got, TF.softmax(torch.tensor(x), dim=-1).numpy(), atol=1e-6)


def test_layer_norm_affine():
    rng = np.random.RandomState(5)
    h = rng.randn(4, 10).astype(np.float32)
    g = rng.rand(10).astype(np.float32) + 0.5
    bb = rng.randn(10).astype(np.float32)

    def b():
        xi = fluid.layers.data("h", shape=[10], dtype="float32")
        return fluid.layers.layer_norm(
            xi, scale=True, shift=True, epsilon=1e-5,
            param_attr=fluid.ParamAttr(name="lg"),
            bias_attr=fluid.ParamAttr(name="lb"))

    got = _run(b, {"h": h}, {"lg": g, "lb": bb})
    ref = TF.layer_norm(torch.tensor(h), (10,), torch.tensor(g),
                        torch.tensor(bb), 1e-5).numpy()
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_warpctc_vs_torch_ctc_loss():
    """CTC forward algorithm vs torch.nn.functional.ctc_loss — exact
    per-sequence negative log-likelihoods (reference warpctc_op.cc)."""
    from tests.test_op_tail import run_op
    rng = np.random.RandomState(0)
    B, T, C = 2, 6, 5
    logits = rng.randn(B, T, C).astype(np.float32)
    labels = np.zeros((B, 3), np.int64)
    labels[0, :2] = [1, 2]
    labels[1, :3] = [3, 1, 4]
    out = run_op("warpctc", {"Logits": logits, "Label": labels},
                 {"blank": 0, "norm_by_times": False},
                 lod={"Logits": np.array([6, 6], np.int32),
                      "Label": np.array([2, 3], np.int32)})
    got = np.asarray(out["Loss"]).ravel()
    lp = TF.log_softmax(torch.tensor(logits).permute(1, 0, 2), dim=-1)
    ref = TF.ctc_loss(lp, torch.tensor([1, 2, 3, 1, 4]),
                      torch.tensor([6, 6]), torch.tensor([2, 3]),
                      blank=0, reduction="none").numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_softmax_with_cross_entropy_vs_torch():
    rng = np.random.RandomState(6)
    logits = rng.randn(5, 7).astype(np.float32)
    lab = rng.randint(0, 7, (5, 1)).astype(np.int64)

    def b():
        xi = fluid.layers.data("l", shape=[7], dtype="float32")
        yi = fluid.layers.data("y", shape=[1], dtype="int64")
        return fluid.layers.softmax_with_cross_entropy(xi, yi)

    got = np.asarray(_run(b, {"l": logits, "y": lab})).ravel()
    ref = TF.cross_entropy(torch.tensor(logits), torch.tensor(lab[:, 0]),
                           reduction="none").numpy()
    np.testing.assert_allclose(got, ref, atol=1e-6)


def test_lod_feed_rejects_lengths_passed_as_offsets():
    """LoDTensor carries OFFSETS (pybind convention); feeding lengths
    used to silently select wrong rows — now it raises (reference
    lod_tensor.cc CheckLoD)."""
    from paddle_tpu.fluid.lod import LoDTensor, pad_lod_feed
    data = np.arange(12, dtype=np.float32).reshape(12, 1)
    ok = pad_lod_feed(LoDTensor(data, [[0, 6, 12]]))
    assert ok[0].shape[0] == 2
    with pytest.raises(ValueError, match="OFFSETS"):
        pad_lod_feed(LoDTensor(data, [[6, 6]]))
    # ndarray levels stay accepted (pybind returns lists, tests use both)
    assert pad_lod_feed(LoDTensor(data, [np.array([0, 6, 12])]))[0].shape[0] == 2
    # 2-level: the OUTER level must be offsets too
    assert list(pad_lod_feed(
        LoDTensor(data, [[0, 2, 3], [0, 4, 6, 12]]))[2]) == [2, 1]
    with pytest.raises(ValueError, match="OFFSETS"):
        pad_lod_feed(LoDTensor(data, [[2], [0, 6, 12]]))


def test_dynamic_lstm_gate_layout_vs_torch():
    """The lstm op consumes gate columns in the REFERENCE order
    {candidate, input, forget, output} (math/detail/lstm_cpu_kernel.h:
    44-47). torch.nn.LSTM uses rows {i, f, g, o}; remapping torch's
    weights into the reference layout must reproduce torch exactly — a
    wrong column order shows O(1) divergence."""
    from tests.test_op_tail import run_op
    rng = np.random.RandomState(7)
    B, T, I, H = 2, 5, 3, 4
    x = rng.randn(B, T, I).astype(np.float32)
    lstm = torch.nn.LSTM(I, H, batch_first=True)
    with torch.no_grad():
        ref_out, _ = lstm(torch.tensor(x))
    ref = ref_out.numpy()

    w_ih = lstm.weight_ih_l0.detach().numpy()   # [4H, I] rows i,f,g,o
    w_hh = lstm.weight_hh_l0.detach().numpy()   # [4H, H]
    b = (lstm.bias_ih_l0 + lstm.bias_hh_l0).detach().numpy()  # [4H]
    ti, tf, tg, to = [np.arange(k * H, (k + 1) * H) for k in range(4)]
    order = np.concatenate([tg, ti, tf, to])    # torch rows -> {c,i,f,o}
    x_proj = np.einsum("bti,hi->bth", x, w_ih[order])   # [B,T,4H]
    weight = w_hh[order].T.astype(np.float32)           # [H, 4H]
    bias = b[order].reshape(1, 4 * H).astype(np.float32)
    out = run_op("lstm", {"Input": x_proj.astype(np.float32),
                          "Weight": weight, "Bias": bias},
                 {"use_peepholes": False},
                 lod={"Input": np.full(B, T, np.int32)})
    np.testing.assert_allclose(np.asarray(out["Hidden"]), ref, atol=2e-6)


def test_dynamic_gru_update_gate_vs_torch():
    """GRU output is out = (1-u)*prev + u*cand (math/detail/gru_kernel.h:
    62-63) with gate columns {u, r, c}. torch's z plays the keep-previous
    role (h' = (1-z)n + z h), so u = sigmoid(-z_logits): negating
    torch's z weights must reproduce torch exactly. Two documented
    semantic gaps are neutralized to isolate the update-gate direction:
    paddle resets hidden BEFORE the candidate matmul (gru_unit_op.h:104
    r_h_p = r*h then GEMM) while torch resets after — equal iff W_hn is
    diagonal — and torch couples b_hn inside r*(...), so b_hn = 0."""
    from tests.test_op_tail import run_op
    rng = np.random.RandomState(8)
    B, T, I, H = 2, 5, 3, 4
    x = rng.randn(B, T, I).astype(np.float32)
    gru = torch.nn.GRU(I, H, batch_first=True)
    with torch.no_grad():
        gru.bias_hh_l0[2 * H:] = 0.0    # b_hn = 0 (see docstring)
        gru.weight_hh_l0[2 * H:] = torch.diag(
            torch.tensor(rng.rand(H).astype(np.float32) + 0.5))
        ref_out, _ = gru(torch.tensor(x))
    ref = ref_out.numpy()

    w_ih = gru.weight_ih_l0.detach().numpy()    # [3H, I] rows r,z,n
    w_hh = gru.weight_hh_l0.detach().numpy()
    b_ih = gru.bias_ih_l0.detach().numpy()
    b_hh = gru.bias_hh_l0.detach().numpy()
    r_, z_, n_ = [np.arange(k * H, (k + 1) * H) for k in range(3)]

    # our columns {u, r, c}: u = -z (logit negation), r = r, c = n
    wx = np.concatenate([-w_ih[z_], w_ih[r_], w_ih[n_]]).astype(np.float32)
    wh = np.concatenate([-w_hh[z_], w_hh[r_], w_hh[n_]]).astype(np.float32)
    bx = np.concatenate([-(b_ih[z_] + b_hh[z_]), b_ih[r_] + b_hh[r_],
                         b_ih[n_]]).astype(np.float32)
    x_proj = (np.einsum("bti,hi->bth", x, wx)
              + bx.reshape(1, 1, 3 * H)).astype(np.float32)
    out = run_op("gru", {"Input": x_proj, "Weight": wh.T.copy()},
                 {}, lod={"Input": np.full(B, T, np.int32)})
    np.testing.assert_allclose(np.asarray(out["Hidden"]), ref, atol=2e-6)


def test_bilinear_interp_align_corners_vs_torch():
    """The reference interpolate op uses the align-corners grid
    (interpolate_op.h:171-174), matching torch align_corners=True —
    jax.image.resize's half-pixel mapping diverges O(0.1)."""
    from tests.test_op_tail import run_op
    x = np.random.RandomState(0).randn(2, 3, 5, 7).astype(np.float32)
    out = np.asarray(run_op("bilinear_interp", {"X": x},
                            {"out_h": 9, "out_w": 4})["Out"])
    ref = TF.interpolate(torch.tensor(x), size=(9, 4), mode="bilinear",
                         align_corners=True).numpy()
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_nearest_interp_reference_rounding():
    """Nearest uses round(align-corners grid) (interpolate_op.h:33)."""
    from tests.test_op_tail import run_op
    x = np.random.RandomState(1).randn(1, 2, 5, 7).astype(np.float32)
    out = np.asarray(run_op("nearest_interp", {"X": x},
                            {"out_h": 9, "out_w": 4})["Out"])
    rh, rw = (5 - 1) / (9 - 1), (7 - 1) / (4 - 1)
    for k in range(9):
        for l in range(4):
            np.testing.assert_array_equal(
                out[:, :, k, l],
                x[:, :, min(int(rh * k + 0.5), 4),
                  min(int(rw * l + 0.5), 6)])


def test_bilinear_interp_half_pixel_mode():
    """align_corners=False + align_mode=0 is the half-pixel grid —
    matches torch align_corners=False."""
    from tests.test_op_tail import run_op
    x = np.random.RandomState(2).randn(2, 3, 5, 7).astype(np.float32)
    out = np.asarray(run_op(
        "bilinear_interp", {"X": x},
        {"out_h": 9, "out_w": 4, "align_corners": False,
         "align_mode": 0})["Out"])
    ref = TF.interpolate(torch.tensor(x), size=(9, 4), mode="bilinear",
                         align_corners=False).numpy()
    np.testing.assert_allclose(out, ref, atol=2e-6)
