"""The public API surface is frozen in API.spec (reference
paddle/fluid/API.spec + the CI signature diff check): regenerating the
inventory must match the committed file, so accidental signature or
symbol removals fail loudly."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_api_spec_is_current():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "gen_api_spec.py")],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    generated = out.stdout.strip().splitlines()
    with open(os.path.join(REPO, "API.spec")) as f:
        committed = f.read().strip().splitlines()
    gen_set, com_set = set(generated), set(committed)
    removed = sorted(com_set - gen_set)[:10]
    added = sorted(gen_set - com_set)[:10]
    assert gen_set == com_set, (
        "API surface drifted from API.spec.\n"
        "Removed/changed: %s\nAdded: %s\n"
        "If intentional, regenerate: python tools/gen_api_spec.py > "
        "API.spec" % (removed, added))
    # sanity: the surface is substantial (reference: 413 entries)
    assert len(generated) > 400
