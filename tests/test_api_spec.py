"""The public API surface is frozen in API.spec (reference
paddle/fluid/API.spec + the CI signature diff check): regenerating the
inventory must match the committed file, so accidental signature or
symbol removals fail loudly."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_api_spec_is_current():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "gen_api_spec.py")],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    generated = out.stdout.strip().splitlines()
    with open(os.path.join(REPO, "API.spec")) as f:
        committed = f.read().strip().splitlines()
    gen_set, com_set = set(generated), set(committed)
    removed = sorted(com_set - gen_set)[:10]
    added = sorted(gen_set - com_set)[:10]
    assert gen_set == com_set, (
        "API surface drifted from API.spec.\n"
        "Removed/changed: %s\nAdded: %s\n"
        "If intentional, regenerate: python tools/gen_api_spec.py > "
        "API.spec" % (removed, added))
    # sanity: the surface is substantial (reference: 413 entries)
    assert len(generated) > 400


REFERENCE_SPEC = "/root/reference/paddle/fluid/API.spec"

# Symbols in the reference's frozen API.spec that are INTENTIONALLY absent
# from this framework, each with its justification. Keep this empty unless
# a reference API is fundamentally meaningless on TPU — anything else is a
# coverage gap that belongs in the tree, not here.
REFERENCE_ALLOWLIST = {
    # (currently empty: all 413 reference symbols resolve)
}


def test_reference_api_spec_parity():
    """Every symbol in the reference's frozen API.spec resolves in this
    package (VERDICT r3 #6: diff against the REFERENCE spec, not just the
    self-generated one)."""
    if not os.path.exists(REFERENCE_SPEC):
        import pytest
        pytest.skip("reference tree not present")
    import importlib
    import paddle_tpu.fluid as fluid
    symbols = set()
    with open(REFERENCE_SPEC) as f:
        for line in f:
            sym = line.split(" ", 1)[0].strip()
            if sym.startswith("paddle.fluid"):
                symbols.add(sym)
    assert len(symbols) >= 400   # the frozen spec has 413 entries
    missing = []
    for sym in sorted(symbols):
        if sym in REFERENCE_ALLOWLIST:
            continue
        obj = fluid
        for part in sym.split(".")[2:]:
            try:
                obj = getattr(obj, part)
            except AttributeError:
                try:
                    obj = importlib.import_module(
                        "paddle_tpu.fluid." + part)
                except ImportError:
                    missing.append(sym)
                    break
    assert not missing, (
        "%d reference API.spec symbols unresolved (add the capability or "
        "an explicitly justified REFERENCE_ALLOWLIST entry): %s"
        % (len(missing), missing[:20]))
