"""Seq2seq attention model + beam search tests (reference
unittests/test_machine_translation.py book test, test_beam_search_op.py,
test_beam_search_decode_op.py)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid.lod import create_lod_tensor, LoDTensor
from paddle_tpu.models import machine_translation as mt


def _rand_seq_batch(rng, lens, vocab):
    rows = sum(lens)
    return create_lod_tensor(
        rng.randint(1, vocab, (rows, 1)).astype(np.int64), [lens])


def test_mt_attention_train_converges():
    rng = np.random.RandomState(0)
    dict_size = 30
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        avg_cost, prediction, feeds = mt.seq_to_seq_net(
            embedding_dim=16, encoder_size=16, decoder_size=16,
            source_dict_dim=dict_size, target_dict_dim=dict_size,
            is_generating=False)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(avg_cost)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    src_lens, trg_lens = [3, 4], [4, 3]
    feed = {"source_sequence": _rand_seq_batch(rng, src_lens, dict_size),
            "target_sequence": _rand_seq_batch(rng, trg_lens, dict_size),
            "label_sequence": _rand_seq_batch(rng, trg_lens, dict_size)}
    losses = []
    for _ in range(30):
        (l,) = exe.run(main, feed=feed, fetch_list=[avg_cost])
        losses.append(float(np.asarray(l).flatten()[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_beam_search_op_selects_top_candidates():
    """1 source, beam 2, vocab 4: hand-checked expansion with a finished
    beam (reference beam_search_op.cc semantics)."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        pre_ids = fluid.layers.data("pre_ids", shape=[1], dtype="int64")
        pre_scores = fluid.layers.data("pre_scores", shape=[1],
                                       dtype="float32")
        scores = fluid.layers.data("scores", shape=[4], dtype="float32")
        sel_ids, sel_scores, parent = fluid.layers.beam_search(
            pre_ids, pre_scores, None, scores, beam_size=2, end_id=0,
            return_parent_idx=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    # beam 0 unfinished (id 2), beam 1 finished (id 0 == end_id, score -0.5)
    res = exe.run(
        main,
        feed={"pre_ids": np.array([[2], [0]], np.int64),
              "pre_scores": np.array([[-1.0], [-0.5]], np.float32),
              "scores": np.array([[-9., -2., -3., -1.5],
                                  [-9., -9., -9., -9.]], np.float32)},
        fetch_list=[sel_ids, sel_scores, parent])
    ids_v = np.asarray(res[0]).reshape(-1)
    scores_v = np.asarray(res[1]).reshape(-1)
    parent_v = np.asarray(res[2]).reshape(-1)
    # candidates: beam0 expands {-1.5 (id 3), -2.0 (id 1), ...}; beam1 is
    # frozen at -0.5 with end token. top2 = frozen -0.5, then -1.5.
    np.testing.assert_array_equal(ids_v, [0, 3])
    np.testing.assert_allclose(scores_v, [-0.5, -1.5])
    np.testing.assert_array_equal(parent_v, [1, 0])


def test_beam_search_decode_backtracks():
    T, BW = 3, 2
    ids = np.array([[5, 5], [6, 7], [8, 1]], np.int64)       # [T, BW]
    parents = np.array([[0, 1], [0, 0], [1, 0]], np.int32)
    scores = np.array([[-1, -1], [-2, -2], [-3, -2.5]], np.float32)
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.data("i", shape=[T, BW], dtype="int64",
                              append_batch_size=False)
        p = fluid.layers.data("p", shape=[T, BW], dtype="int32",
                              append_batch_size=False)
        s = fluid.layers.data("s", shape=[T, BW], dtype="float32",
                              append_batch_size=False)
        sent_ids, sent_scores = fluid.layers.beam_search_decode(
            i, s, beam_size=2, end_id=1, parent_idx=p)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    res_ids, res_scores = exe.run(main, feed={"i": ids, "p": parents,
                                              "s": scores},
                                  fetch_list=[sent_ids, sent_scores])
    assert isinstance(res_ids, LoDTensor)
    # fetch returns the packed LoD form: rows of all beams concatenated
    # beam 0 at t2: token 8, parent 1 -> t1 token 7, parent(t1,1)=0 ->
    # t0 token 5. beam 1 at t2: token 1(end), parent 0 -> t1 token 6 -> 5.
    np.testing.assert_array_equal(res_ids.numpy().reshape(-1),
                                  [5, 7, 8, 5, 6, 1])
    assert res_ids.recursive_sequence_lengths() == [[3, 3]]
    np.testing.assert_allclose(np.asarray(res_scores).reshape(-1),
                               [-3.0, -2.5])


def test_mt_generation_beam_search():
    """The unrolled dense beam-search generator runs and emits beam_size
    ranked hypotheses per source."""
    rng = np.random.RandomState(1)
    dict_size = 12
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        sent_ids, sent_scores, feeds = mt.seq_to_seq_net(
            embedding_dim=8, encoder_size=8, decoder_size=8,
            source_dict_dim=dict_size, target_dict_dim=dict_size,
            is_generating=True, beam_size=3, max_length=5)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    src = _rand_seq_batch(rng, [3, 2], dict_size)
    res_ids, res_scores = exe.run(main, feed={"source_sequence": src},
                                  fetch_list=[sent_ids, sent_scores])
    assert isinstance(res_ids, LoDTensor)
    lens = res_ids.recursive_sequence_lengths()[0]
    scores_np = np.asarray(res_scores).reshape(-1)
    assert len(lens) == 6                # 2 sources x 3 beams
    assert all(1 <= l <= 5 for l in lens)
    assert np.isfinite(scores_np).all()
    # per-source beams come out ranked best-first
    assert scores_np[0] >= scores_np[1] >= scores_np[2]
    assert scores_np[3] >= scores_np[4] >= scores_np[5]


def test_train_and_generation_share_parameter_shapes():
    """Trained weights must be loadable into the generation program: every
    parameter name that appears in both programs must have the same shape
    (build each under a fresh unique_name.guard, the reference idiom)."""
    from paddle_tpu.models.machine_translation import seq_to_seq_net

    def build(is_gen):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.unique_name.guard():
            with fluid.program_guard(main, startup):
                seq_to_seq_net(16, 32, 32, 40, 40, is_generating=is_gen,
                               beam_size=2, max_length=3)
        return main

    train = build(False)
    gen = build(True)
    tparams = {p.name: tuple(p.shape) for p in train.all_parameters()}
    gparams = {p.name: tuple(p.shape) for p in gen.all_parameters()}
    shared = set(tparams) & set(gparams)
    # decoder params must all be shared (lstm gates, output fc, attention,
    # target embedding)
    for needle in ("decoder_lstm_g0_w0", "decoder_out_w", "att_score_w",
                   "att_state_w", "trg_emb"):
        assert any(needle in n for n in shared), "missing shared " + needle
    for name in shared:
        assert tparams[name] == gparams[name], (
            "shape mismatch for %s: train %s vs gen %s"
            % (name, tparams[name], gparams[name]))
