"""Enforce-style error context (VERDICT r2 task #8; reference
platform/enforce.h): a failing op lowering must surface the op type and
its inputs' names/shapes/dtypes instead of a bare JAX trace."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.ops.registry import OpError


def test_mis_shaped_feed_names_the_op():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with pytest.raises(OpError) as ei:
            # feed has 5 features, the fc weight expects 4
            exe.run(main, feed={"x": np.zeros((2, 5), np.float32)},
                    fetch_list=[y])
    msg = str(ei.value)
    assert "op 'mul'" in msg or "op 'fc'" in msg, msg
    assert "(2, 5)" in msg, msg          # the offending input shape
    assert "float32" in msg, msg
    # actionable, not a wall of backend trace
    assert len(msg.splitlines()) <= 8, msg


def test_bad_dtype_op_context():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        a = fluid.layers.data("a", shape=[2, 3], dtype="float32")
        b = fluid.layers.data("b", shape=[4, 5], dtype="float32")
        out = fluid.layers.matmul(a, b)   # 3 != 4: contraction mismatch
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        with pytest.raises(OpError) as ei:
            exe.run(main, feed={"a": np.zeros((1, 2, 3), np.float32),
                                "b": np.zeros((1, 4, 5), np.float32)},
                    fetch_list=[out])
    msg = str(ei.value)
    assert "matmul" in msg, msg
    assert "'a'" in msg and "'b'" in msg, msg
