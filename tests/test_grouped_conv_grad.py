"""Grouped-conv custom VJP: parity with jax's builtin gradient.

The conv2d lowering replaces the builtin filter-gradient of
feature-grouped convs (a pathological `batch_group_count` conv on XLA)
with a patches+einsum contraction (`ops/nn_ops.py _grouped_conv`).
These tests pin the custom rule to the builtin one across layouts,
strides, dilations, group counts (incl. depthwise) and dtypes.
Reference analogue: conv_op.cc grad kernels / conv_cudnn_op.cu grouped
algo selection.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.nn_ops import _grouped_conv


def _builtin(strides, padding, dilations, groups, layout):
    def f(x, w):
        return jax.lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            rhs_dilation=dilations, feature_group_count=groups,
            dimension_numbers=(layout, "OIHW", layout))
    return f


CASES = [
    # (layout, N, C, H, W, O, k, stride, pad, dil, groups)
    ("NCHW", 2, 16, 10, 10, 16, 3, 1, 1, 1, 4),
    ("NCHW", 2, 16, 11, 9, 32, 3, 2, 1, 1, 8),
    ("NCHW", 1, 12, 8, 8, 12, 3, 1, 2, 2, 3),
    ("NHWC", 2, 16, 10, 10, 16, 3, 1, 1, 1, 4),
    ("NHWC", 2, 16, 9, 11, 32, 3, 2, 1, 1, 8),
    ("NCHW", 2, 8, 8, 8, 8, 3, 1, 1, 1, 8),    # depthwise
    ("NCHW", 2, 8, 8, 8, 16, 3, 1, 1, 1, 8),   # depthwise, multiplier 2
    ("NCHW", 2, 16, 7, 7, 16, 1, 1, 0, 1, 4),  # 1x1 grouped
]


@pytest.mark.parametrize(
    "layout,n,c,h,w,o,k,st,pd,dl,g", CASES,
    ids=["%s_g%d_k%d_s%d_d%d" % (t[0], t[-1], t[6], t[7], t[9])
         for t in CASES])
def test_grad_matches_builtin(layout, n, c, h, w, o, k, st, pd, dl, g):
    rng = np.random.RandomState(0)
    if layout == "NCHW":
        x = jnp.asarray(rng.randn(n, c, h, w), jnp.float32)
    else:
        x = jnp.asarray(rng.randn(n, h, w, c), jnp.float32)
    wt = jnp.asarray(rng.randn(o, c // g, k, k), jnp.float32)
    strides, dil = (st, st), (dl, dl)
    padding = [(pd, pd), (pd, pd)]

    custom = _grouped_conv(strides, padding, dil, g, layout)
    builtin = _builtin(strides, padding, dil, g, layout)

    y1 = custom(x, wt)
    y2 = builtin(x, wt)
    np.testing.assert_allclose(y1, y2, atol=1e-5, rtol=1e-5)

    def loss(f):
        # non-uniform cotangent so a transposed/misordered dw shows up
        def inner(x, wt):
            out = f(x, wt)
            return (out * jnp.arange(out.size, dtype=out.dtype)
                    .reshape(out.shape)).sum()
        return inner

    g1 = jax.grad(loss(custom), argnums=(0, 1))(x, wt)
    g2 = jax.grad(loss(builtin), argnums=(0, 1))(x, wt)
    scale = max(1.0, float(jnp.abs(g2[1]).max()))
    np.testing.assert_allclose(g1[0], g2[0], atol=1e-3, rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(g1[1]) / scale, np.asarray(g2[1]) / scale,
        atol=5e-5, rtol=1e-4)


def test_grad_bf16_accumulates_fp32():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 16, 10, 10), jnp.bfloat16)
    wt = jnp.asarray(rng.randn(32, 4, 3, 3), jnp.bfloat16)
    custom = _grouped_conv((1, 1), [(1, 1), (1, 1)], (1, 1), 4, "NCHW")

    def loss(x, wt):
        return custom(x, wt).astype(jnp.float32).sum()

    dx, dw = jax.grad(loss, argnums=(0, 1))(x, wt)
    assert dx.dtype == jnp.bfloat16 and dw.dtype == jnp.bfloat16
    # fp32 reference
    ref = _builtin((1, 1), [(1, 1), (1, 1)], (1, 1), 4, "NCHW")
    dxr, dwr = jax.grad(
        lambda x, wt: ref(x, wt).sum(), argnums=(0, 1))(
            x.astype(jnp.float32), wt.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(dw, np.float32), dwr,
                               atol=0.5, rtol=0.05)
    np.testing.assert_allclose(np.asarray(dx, np.float32), dxr,
                               atol=0.5, rtol=0.05)


def test_grad_matches_torch_oracle():
    """Independent oracle: torch autograd, cardinality-32 SE-ResNeXt
    shape with stride 2 — catches a systematically wrong convention the
    builtin-vs-custom comparison could share."""
    torch = pytest.importorskip("torch")
    TF = torch.nn.functional
    rng = np.random.RandomState(3)
    x = rng.randn(2, 64, 14, 14).astype(np.float32)
    w = rng.randn(64, 2, 3, 3).astype(np.float32)
    dy_seed = rng.randn(2, 64, 7, 7).astype(np.float32)

    custom = _grouped_conv((2, 2), [(1, 1), (1, 1)], (1, 1), 32, "NCHW")

    def loss(x_, w_):
        return (custom(x_, w_) * jnp.asarray(dy_seed)).sum()

    dx, dw = jax.grad(loss, argnums=(0, 1))(jnp.asarray(x),
                                            jnp.asarray(w))

    xt = torch.tensor(x, requires_grad=True)
    wt = torch.tensor(w, requires_grad=True)
    (TF.conv2d(xt, wt, stride=2, padding=1, groups=32)
     * torch.tensor(dy_seed)).sum().backward()
    np.testing.assert_allclose(np.asarray(dx), xt.grad.numpy(),
                               atol=2e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(dw), wt.grad.numpy(),
                               atol=2e-4, rtol=1e-4)


def test_conv2d_op_training_uses_custom_path():
    """End-to-end: a grouped-conv training program differentiates, and its
    lowered step-function HLO contains no batch_group_count conv — the
    pathological builtin filter-gradient form the custom VJP replaces. A
    regression of the `groups > 1` dispatch in nn_ops.py trips the HLO
    assertion even though training still converges either way."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import functionalizer
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[16, 8, 8], dtype="float32")
        conv = fluid.layers.conv2d(input=img, num_filters=16,
                                   filter_size=3, padding=1, groups=4)
        loss = fluid.layers.mean(conv)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x = np.random.RandomState(2).randn(2, 16, 8, 8).astype("float32")
    l0 = exe.run(main, feed={"img": x}, fetch_list=[loss])[0]
    l1 = exe.run(main, feed={"img": x}, fetch_list=[loss])[0]
    assert np.isfinite(l0) and np.isfinite(l1) and l1 != l0

    # lower the same step function the Executor jits and inspect its HLO
    scope = fluid.global_scope()
    persistables = tuple(functionalizer.persistable_names(main))
    state = {n: scope.get(n) for n in persistables
             if scope.has(n) and scope.get(n) is not None}
    feeds = {"img": jnp.asarray(x)}
    step_fn = functionalizer.build_step_fn(
        main, tuple(sorted(feeds)), (loss.name,), persistables)
    hlo = jax.jit(step_fn).lower(
        state, feeds, np.uint32(0)).as_text()
    # every conv prints `batch_group_count = 1`; the pathological builtin
    # filter-gradient is the one with batch_group_count = groups (> 1)
    import re
    bgc = [int(m) for m in re.findall(r"batch_group_count = (\d+)", hlo)]
    assert bgc and all(v == 1 for v in bgc), \
        "builtin grouped filter-gradient form leaked into the step HLO: " \
        "batch_group_counts %s" % sorted(set(bgc))
    # sanity: the custom dw path (patches via a feature-grouped conv +
    # dot contraction) is actually present
    fgc = [int(m) for m in re.findall(r"feature_group_count = (\d+)", hlo)]
    assert any(v > 1 for v in fgc)
