"""Nested (2-level) LoD: feeding, companion propagation, and the
kmax_seq_score -> sub_nested_seq selection pipeline (reference
lod_tensor.h multi-level LoD; legacy KmaxSeqScoreLayer /
SubNestedSequenceLayer). Encoding: inner sequences ride the standard
padded [N, T, ...] + @LOD_LEN, with @LOD_SEG carrying each inner
sequence's outer-group id."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid.lod import LoDTensor


def _nested_feed():
    """2 outer groups: group 0 has 2 inner seqs (lens 2, 1), group 1 has
    3 inner seqs (lens 1, 3, 2). Feature dim 1, values encode identity:
    value = 10*inner_index + position."""
    lens = [2, 1, 1, 3, 2]
    rows = []
    for i, l in enumerate(lens):
        for p in range(l):
            rows.append([10.0 * i + p])
    t = LoDTensor(np.asarray(rows, np.float32))
    t.set_recursive_sequence_lengths([[2, 3], lens])
    return t, lens


def test_nested_feed_round_trip():
    """A nested LoD feed passes through an elementwise op and fetches
    back with BOTH levels intact."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1], dtype="float32",
                              lod_level=2)
        y = fluid.layers.scale(x, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    t, lens = _nested_feed()
    out, = exe.run(main, feed={"x": t}, fetch_list=[y])
    assert isinstance(out, LoDTensor)
    got_lens = out.recursive_sequence_lengths()
    assert got_lens == [[2, 3], lens], got_lens
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.asarray(t))


def test_kmax_then_sub_nested_seq_selects_top_subsequences():
    """Rank inner sequences per outer group by their first score, select
    the top-1 of each group (the reference kmax+sub_nested pipeline)."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1], dtype="float32",
                              lod_level=2)
        scores = fluid.layers.data("s", shape=[1], dtype="float32",
                                   lod_level=2)
        from paddle_tpu.fluid.layer_helper import LayerHelper
        helper = LayerHelper("kmax_seq_score")
        idx = helper.create_variable_for_type_inference("int64")
        helper.append_op(type="kmax_seq_score", inputs={"X": scores},
                         outputs={"Out": idx},
                         attrs={"beam_size": 1, "force_host": True},
                         infer_shape=False)
        sel = helper.create_variable_for_type_inference("float32")
        sel.lod_level = 2
        helper.append_op(type="sub_nested_seq",
                         inputs={"X": x, "Indices": idx},
                         outputs={"Out": sel}, infer_shape=False)
    exe = fluid.Executor(fluid.CPUPlace())
    t, lens = _nested_feed()
    # per-inner-seq scores: group 0 -> [0.1, 0.9]; group 1 ->
    # [0.5, 0.2, 0.8]: winners are inner seq 1 and inner seq 4
    srows = []
    for i, (l, s) in enumerate(zip(lens, [0.1, 0.9, 0.5, 0.2, 0.8])):
        srows += [[s]] * l
    st = LoDTensor(np.asarray(srows, np.float32))
    st.set_recursive_sequence_lengths([[2, 3], lens])
    out, = exe.run(main, feed={"x": t, "s": st}, fetch_list=[sel])
    assert isinstance(out, LoDTensor)
    got = out.recursive_sequence_lengths()
    # 1 selected inner seq per group, lengths of inner seqs 1 and 4
    assert got == [[1, 1], [lens[1], lens[4]]], got
    vals = np.asarray(out).ravel()
    # inner seq 1 = [10.0], inner seq 4 = [40.0, 41.0]
    np.testing.assert_allclose(vals, [10.0, 40.0, 41.0])


def test_v2_sub_nested_pipeline():
    """The v1 spelling: data(sub_sequence) -> kmax_seq_score_layer ->
    sub_nested_seq_layer through the v2 trainer machinery."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.trainer_config_helpers import layers as v1

    x = v1.data_layer(
        name="nx", type=paddle.data_type.dense_vector_sub_sequence(1))
    sc = v1.data_layer(
        name="ns", type=paddle.data_type.dense_vector_sub_sequence(1))
    idx = v1.kmax_seq_score_layer(input=sc, beam_size=1)
    sel = v1.sub_nested_seq_layer(input=x, selected_indices=idx)

    topo = paddle.topology.Topology([sel])
    p = paddle.parameters.create(sel)
    sample_x = [[[0.0], [1.0]], [[5.0]]]          # 2 inner seqs
    sample_s = [[[0.2], [0.2]], [[0.9]]]          # second wins
    got = paddle.infer(output_layer=sel, parameters=p,
                       input=[(sample_x, sample_s)])
    np.testing.assert_allclose(np.asarray(got).ravel(), [5.0])


def test_seg_companion_survives_compute_segment():
    """A device op (scale) between the feed and the nested host ops: the
    jitted compute segment must carry @LOD_SEG across its boundary."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1], dtype="float32",
                              lod_level=2)
        scores = fluid.layers.data("s", shape=[1], dtype="float32",
                                   lod_level=2)
        xs = fluid.layers.scale(x, scale=1.0)        # device segment
        ss = fluid.layers.scale(scores, scale=2.0)   # device segment
        from paddle_tpu.fluid.layer_helper import LayerHelper
        helper = LayerHelper("kmax_seq_score")
        assert ss.lod_level == 2     # build-time propagation
        idx = helper.create_variable_for_type_inference("int64")
        helper.append_op(type="kmax_seq_score", inputs={"X": ss},
                         outputs={"Out": idx},
                         attrs={"beam_size": 1, "force_host": True},
                         infer_shape=False)
        sel = helper.create_variable_for_type_inference("float32")
        sel.lod_level = 2
        helper.append_op(type="sub_nested_seq",
                         inputs={"X": xs, "Indices": idx},
                         outputs={"Out": sel}, infer_shape=False)
    exe = fluid.Executor(fluid.CPUPlace())
    t, lens = _nested_feed()
    srows = []
    for l, s in zip(lens, [0.1, 0.9, 0.5, 0.2, 0.8]):
        srows += [[s]] * l
    st = LoDTensor(np.asarray(srows, np.float32))
    st.set_recursive_sequence_lengths([[2, 3], lens])
    out, = exe.run(main, feed={"x": t, "s": st}, fetch_list=[sel])
    got = out.recursive_sequence_lengths()
    assert got == [[1, 1], [lens[1], lens[4]]], got
    np.testing.assert_allclose(np.asarray(out).ravel(),
                               [10.0, 40.0, 41.0])


def test_trailing_empty_outer_group_survives():
    """Outer groups that contribute no inner sequences must round-trip
    (counts encoding; an id encoding would drop them)."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1], dtype="float32",
                              lod_level=2)
        y = fluid.layers.scale(x, scale=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    t = LoDTensor(np.asarray([[1.0], [2.0]], np.float32))
    t.set_recursive_sequence_lengths([[2, 0], [1, 1]])
    out, = exe.run(main, feed={"x": t}, fetch_list=[y])
    assert out.recursive_sequence_lengths() == [[2, 0], [1, 1]]


def test_kmax_pads_unfilled_slots_with_minus_one():
    """beam_size larger than a group's inner count: unfilled slots are
    -1 (reference padding) and sub_nested_seq skips them."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.trainer_config_helpers import layers as v1

    x = v1.data_layer(
        name="px", type=paddle.data_type.dense_vector_sub_sequence(1))
    sc = v1.data_layer(
        name="ps", type=paddle.data_type.dense_vector_sub_sequence(1))
    idx = v1.kmax_seq_score_layer(input=sc, beam_size=3)
    sel = v1.sub_nested_seq_layer(input=x, selected_indices=idx)
    p = paddle.parameters.create(sel)
    # one outer group with only 2 inner sequences, beam 3
    sample_x = [[[1.0]], [[2.0], [3.0]]]
    sample_s = [[[0.1]], [[0.9], [0.9]]]
    got = paddle.infer(output_layer=sel, parameters=p,
                       input=[(sample_x, sample_s)])
    vals = sorted(np.asarray(got).ravel().tolist())
    # both real inner seqs selected exactly once, no duplicate of seq 0
    assert vals == [1.0, 2.0, 3.0], vals


def test_subsequence_input_recurrent_group():
    """Hierarchical RNN (reference SubsequenceInput): the group iterates
    OUTER groups; each step sees one inner sequence, pools it, and
    updates a memory. Cross-checked against a numpy restatement."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.trainer_config_helpers import layers as v1

    x = v1.data_layer(
        name="hx", type=paddle.data_type.dense_vector_sub_sequence(3))

    def step(inner_seq):
        mem = v1.memory(name="acc", size=3)
        pooled = v1.pooling_layer(input=inner_seq,
                                  pooling_type=paddle.pooling.Sum())
        nxt = v1.addto_layer(input=[pooled, mem], name="acc",
                             bias_attr=False)
        return nxt

    h = v1.recurrent_group(step=step, input=v1.SubsequenceInput(x))
    last = v1.last_seq(input=h)

    p = paddle.parameters.create(last)
    # 2 outer groups: [[a,b],[c]] and [[d],[e,f],[g]]
    rng = np.random.RandomState(6)
    s1 = [rng.randn(2, 3).astype(np.float32),
          rng.randn(1, 3).astype(np.float32)]
    s2 = [rng.randn(1, 3).astype(np.float32),
          rng.randn(2, 3).astype(np.float32),
          rng.randn(3, 3).astype(np.float32)]
    got = np.asarray(paddle.infer(output_layer=last, parameters=p,
                                  input=[(s1,), (s2,)]))
    # running sum of inner-sequence sums -> last = total sum per group
    want = np.stack([sum(a.sum(0) for a in s1),
                     sum(a.sum(0) for a in s2)])
    np.testing.assert_allclose(got.reshape(2, 3), want, rtol=1e-5)


def test_subsequence_input_max_pool_pins_inner_lengths():
    """Max pooling reads the exact inner lengths — a wrong length matrix
    (e.g. full-T) would pick up pad positions."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.trainer_config_helpers import layers as v1

    x = v1.data_layer(
        name="mx", type=paddle.data_type.dense_vector_sub_sequence(2))

    def step(inner_seq):
        mem = v1.memory(name="mmax", size=2)
        pooled = v1.pooling_layer(input=inner_seq,
                                  pooling_type=paddle.pooling.Max())
        return v1.addto_layer(input=[pooled, mem], name="mmax",
                              bias_attr=False)

    h = v1.recurrent_group(step=step, input=v1.SubsequenceInput(x))
    last = v1.last_seq(input=h)
    p = paddle.parameters.create(last)
    # ALL-NEGATIVE values: if padded zeros leaked into the max, the
    # result would be 0 instead of the true (negative) maxima
    s1 = [np.array([[-3.0, -1.0], [-2.0, -5.0]], np.float32),
          np.array([[-4.0, -6.0]], np.float32)]
    got = np.asarray(paddle.infer(output_layer=last, parameters=p,
                                  input=[(s1,)])).ravel()
    want = (np.array([-2.0, -1.0]) + np.array([-4.0, -6.0]))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_subsequence_input_trains_through_upstream_layer():
    """A trainable fc BEFORE the SubsequenceInput: gradients flow back
    through nested_to_outer (the explicit host-side grad op)."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.trainer_config_helpers import layers as v1

    x = v1.data_layer(
        name="tx", type=paddle.data_type.dense_vector_sub_sequence(3))
    proj = v1.fc_layer(input=x, size=4, act=paddle.activation.Linear(),
                       bias_attr=False)

    def step(inner_seq):
        mem = v1.memory(name="tacc", size=4)
        pooled = v1.pooling_layer(input=inner_seq,
                                  pooling_type=paddle.pooling.Sum())
        return v1.addto_layer(input=[pooled, mem], name="tacc",
                              bias_attr=False)

    h = v1.recurrent_group(step=step, input=v1.SubsequenceInput(proj))
    pred = v1.fc_layer(input=v1.last_seq(input=h), size=1,
                       act=paddle.activation.Linear())
    y = v1.data_layer(name="ty", size=1)
    cost = v1.regression_cost(input=pred, label=y)

    params = paddle.parameters.create(cost)
    w0 = {n: np.array(params.get(n)) for n in params.names()}
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=0.05))
    rng = np.random.RandomState(7)
    tgt_w = np.array([1.0, -2.0, 0.5], np.float32)

    def reader():
        for _ in range(32):
            groups = [rng.randn(rng.randint(1, 4), 3).astype(np.float32)
                      for _ in range(rng.randint(2, 4))]
            tot = sum(g.sum(0) for g in groups)
            yield groups, np.array([float(tot @ tgt_w)], np.float32)

    losses = []

    def on_event(ev):
        if isinstance(ev, paddle.event.EndIteration):
            losses.append(float(ev.cost))

    tr.train(paddle.batch(reader, 8), num_passes=12,
             event_handler=on_event, feeding={"tx": 0, "ty": 1})
    assert losses[-1] < 0.05 * losses[0], (losses[0], losses[-1])
    # the UPSTREAM projection learned, so gradients crossed
    # nested_to_outer's explicit host-side grad
    assert any(np.abs(np.array(params.get(n)) - w0[n]).max() > 0.05
               for n in params.names() if n.startswith("fc"))


def test_sequence_expand_general_counts_on_host():
    """General ref_level expansion (sequence_expand_op.h): a nested Y
    carries per-group repeat counts that are NOT a uniform multiple —
    served with concrete arrays (host path)."""
    from tests.test_op_tail import run_op
    x = np.array([[[1.0], [2.0]],
                  [[3.0], [0.0]],
                  [[5.0], [6.0]]], np.float32)        # 3 seqs, T=2
    xlens = np.array([2, 1, 2], np.int32)
    # counts 2,1,3 -> 6 output rows (not a multiple of 3)
    By, Ty = 6, 2
    y = np.zeros((By, Ty, 1), np.float32)
    ylens = np.array([2, 1, 1, 2, 2, 2], np.int32)
    # run_op has no @LOD_SEG plumbing: drive via ExecContext with seg
    import jax.numpy as jnp
    from tests.test_op_tail import _FakeOp, ops, ExecContext
    vals = {"X": [jnp.asarray(x)], "Y": [jnp.asarray(y)],
            "X@LOD_LEN": [jnp.asarray(xlens)],
            "Y@LOD_LEN": [jnp.asarray(ylens)],
            "Y@LOD_SEG": [jnp.asarray(np.array([2, 1, 3], np.int32))]}
    op = _FakeOp("sequence_expand", attrs={},
                 inputs={"X": ["X"], "Y": ["Y"]})
    od = ops.get_op_def("sequence_expand")
    got = ops.call_lower(od, ExecContext(op, vals))
    o = np.asarray(got["Out"])
    lens = np.asarray(got["Out@LOD_LEN"])
    assert o.shape[0] == 6
    # x seq0 twice, seq1 once, seq2 three times; lengths are X's own,
    # repeated (Y's ref-level lod only supplies counts)
    np.testing.assert_array_equal(lens, [2, 2, 1, 2, 2, 2])
    np.testing.assert_allclose(o[0, :, 0], [1.0, 2.0])
    np.testing.assert_allclose(o[1, :, 0], [1.0, 2.0])
    np.testing.assert_allclose(o[2, :, 0], [3.0, 0.0])
    np.testing.assert_allclose(o[3, :, 0], [5.0, 6.0])

    # corrupt counts are rejected with a clear error
    import pytest
    bad = dict(vals)
    bad["Y@LOD_SEG"] = [jnp.asarray(np.array([2, 1], np.int32))]
    from paddle_tpu.ops.registry import OpError
    with pytest.raises(OpError, match="outer counts"):
        ops.call_lower(od, ExecContext(op, bad))
