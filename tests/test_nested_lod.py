"""Nested (2-level) LoD: feeding, companion propagation, and the
kmax_seq_score -> sub_nested_seq selection pipeline (reference
lod_tensor.h multi-level LoD; legacy KmaxSeqScoreLayer /
SubNestedSequenceLayer). Encoding: inner sequences ride the standard
padded [N, T, ...] + @LOD_LEN, with @LOD_SEG carrying each inner
sequence's outer-group id."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid.lod import LoDTensor


def _nested_feed():
    """2 outer groups: group 0 has 2 inner seqs (lens 2, 1), group 1 has
    3 inner seqs (lens 1, 3, 2). Feature dim 1, values encode identity:
    value = 10*inner_index + position."""
    lens = [2, 1, 1, 3, 2]
    rows = []
    for i, l in enumerate(lens):
        for p in range(l):
            rows.append([10.0 * i + p])
    t = LoDTensor(np.asarray(rows, np.float32))
    t.set_recursive_sequence_lengths([[2, 3], lens])
    return t, lens


def test_nested_feed_round_trip():
    """A nested LoD feed passes through an elementwise op and fetches
    back with BOTH levels intact."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1], dtype="float32",
                              lod_level=2)
        y = fluid.layers.scale(x, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    t, lens = _nested_feed()
    out, = exe.run(main, feed={"x": t}, fetch_list=[y])
    assert isinstance(out, LoDTensor)
    got_lens = out.recursive_sequence_lengths()
    assert got_lens == [[2, 3], lens], got_lens
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.asarray(t))


def test_kmax_then_sub_nested_seq_selects_top_subsequences():
    """Rank inner sequences per outer group by their first score, select
    the top-1 of each group (the reference kmax+sub_nested pipeline)."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1], dtype="float32",
                              lod_level=2)
        scores = fluid.layers.data("s", shape=[1], dtype="float32",
                                   lod_level=2)
        from paddle_tpu.fluid.layer_helper import LayerHelper
        helper = LayerHelper("kmax_seq_score")
        idx = helper.create_variable_for_type_inference("int64")
        helper.append_op(type="kmax_seq_score", inputs={"X": scores},
                         outputs={"Out": idx},
                         attrs={"beam_size": 1, "force_host": True},
                         infer_shape=False)
        sel = helper.create_variable_for_type_inference("float32")
        sel.lod_level = 2
        helper.append_op(type="sub_nested_seq",
                         inputs={"X": x, "Indices": idx},
                         outputs={"Out": sel}, infer_shape=False)
    exe = fluid.Executor(fluid.CPUPlace())
    t, lens = _nested_feed()
    # per-inner-seq scores: group 0 -> [0.1, 0.9]; group 1 ->
    # [0.5, 0.2, 0.8]: winners are inner seq 1 and inner seq 4
    srows = []
    for i, (l, s) in enumerate(zip(lens, [0.1, 0.9, 0.5, 0.2, 0.8])):
        srows += [[s]] * l
    st = LoDTensor(np.asarray(srows, np.float32))
    st.set_recursive_sequence_lengths([[2, 3], lens])
    out, = exe.run(main, feed={"x": t, "s": st}, fetch_list=[sel])
    assert isinstance(out, LoDTensor)
    got = out.recursive_sequence_lengths()
    # 1 selected inner seq per group, lengths of inner seqs 1 and 4
    assert got == [[1, 1], [lens[1], lens[4]]], got
    vals = np.asarray(out).ravel()
    # inner seq 1 = [10.0], inner seq 4 = [40.0, 41.0]
    np.testing.assert_allclose(vals, [10.0, 40.0, 41.0])


def test_v2_sub_nested_pipeline():
    """The v1 spelling: data(sub_sequence) -> kmax_seq_score_layer ->
    sub_nested_seq_layer through the v2 trainer machinery."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.trainer_config_helpers import layers as v1

    x = v1.data_layer(
        name="nx", type=paddle.data_type.dense_vector_sub_sequence(1))
    sc = v1.data_layer(
        name="ns", type=paddle.data_type.dense_vector_sub_sequence(1))
    idx = v1.kmax_seq_score_layer(input=sc, beam_size=1)
    sel = v1.sub_nested_seq_layer(input=x, selected_indices=idx)

    topo = paddle.topology.Topology([sel])
    p = paddle.parameters.create(sel)
    sample_x = [[[0.0], [1.0]], [[5.0]]]          # 2 inner seqs
    sample_s = [[[0.2], [0.2]], [[0.9]]]          # second wins
    got = paddle.infer(output_layer=sel, parameters=p,
                       input=[(sample_x, sample_s)])
    np.testing.assert_allclose(np.asarray(got).ravel(), [5.0])


def test_seg_companion_survives_compute_segment():
    """A device op (scale) between the feed and the nested host ops: the
    jitted compute segment must carry @LOD_SEG across its boundary."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1], dtype="float32",
                              lod_level=2)
        scores = fluid.layers.data("s", shape=[1], dtype="float32",
                                   lod_level=2)
        xs = fluid.layers.scale(x, scale=1.0)        # device segment
        ss = fluid.layers.scale(scores, scale=2.0)   # device segment
        from paddle_tpu.fluid.layer_helper import LayerHelper
        helper = LayerHelper("kmax_seq_score")
        assert ss.lod_level == 2     # build-time propagation
        idx = helper.create_variable_for_type_inference("int64")
        helper.append_op(type="kmax_seq_score", inputs={"X": ss},
                         outputs={"Out": idx},
                         attrs={"beam_size": 1, "force_host": True},
                         infer_shape=False)
        sel = helper.create_variable_for_type_inference("float32")
        sel.lod_level = 2
        helper.append_op(type="sub_nested_seq",
                         inputs={"X": xs, "Indices": idx},
                         outputs={"Out": sel}, infer_shape=False)
    exe = fluid.Executor(fluid.CPUPlace())
    t, lens = _nested_feed()
    srows = []
    for l, s in zip(lens, [0.1, 0.9, 0.5, 0.2, 0.8]):
        srows += [[s]] * l
    st = LoDTensor(np.asarray(srows, np.float32))
    st.set_recursive_sequence_lengths([[2, 3], lens])
    out, = exe.run(main, feed={"x": t, "s": st}, fetch_list=[sel])
    got = out.recursive_sequence_lengths()
    assert got == [[1, 1], [lens[1], lens[4]]], got
    np.testing.assert_allclose(np.asarray(out).ravel(),
                               [10.0, 40.0, 41.0])


def test_trailing_empty_outer_group_survives():
    """Outer groups that contribute no inner sequences must round-trip
    (counts encoding; an id encoding would drop them)."""
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1], dtype="float32",
                              lod_level=2)
        y = fluid.layers.scale(x, scale=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    t = LoDTensor(np.asarray([[1.0], [2.0]], np.float32))
    t.set_recursive_sequence_lengths([[2, 0], [1, 1]])
    out, = exe.run(main, feed={"x": t}, fetch_list=[y])
    assert out.recursive_sequence_lengths() == [[2, 0], [1, 1]]


def test_kmax_pads_unfilled_slots_with_minus_one():
    """beam_size larger than a group's inner count: unfilled slots are
    -1 (reference padding) and sub_nested_seq skips them."""
    import paddle_tpu.v2 as paddle
    from paddle_tpu.trainer_config_helpers import layers as v1

    x = v1.data_layer(
        name="px", type=paddle.data_type.dense_vector_sub_sequence(1))
    sc = v1.data_layer(
        name="ps", type=paddle.data_type.dense_vector_sub_sequence(1))
    idx = v1.kmax_seq_score_layer(input=sc, beam_size=3)
    sel = v1.sub_nested_seq_layer(input=x, selected_indices=idx)
    p = paddle.parameters.create(sel)
    # one outer group with only 2 inner sequences, beam 3
    sample_x = [[[1.0]], [[2.0], [3.0]]]
    sample_s = [[[0.1]], [[0.9], [0.9]]]
    got = paddle.infer(output_layer=sel, parameters=p,
                       input=[(sample_x, sample_s)])
    vals = sorted(np.asarray(got).ravel().tolist())
    # both real inner seqs selected exactly once, no duplicate of seq 0
    assert vals == [1.0, 2.0, 3.0], vals
