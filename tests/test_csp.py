"""CSP concurrency (reference operators/csp/go_op.cc + the CHANNEL
variable machinery): Go blocks run sub-blocks on concurrent threads,
communicating over blocking channels."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program
from paddle_tpu.fluid.layers import tensor as tl


def test_go_channel_producer_consumer():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        ch = fluid.make_channel(dtype="float32", capacity=4)
        x = fluid.layers.data("x", shape=[1], dtype="float32")
        with fluid.Go():
            # producer: sends x*2 then x*3 into the channel
            a = fluid.layers.scale(x, scale=2.0)
            b = fluid.layers.scale(x, scale=3.0)
            fluid.channel_send(ch, a)
            fluid.channel_send(ch, b)
            fluid.channel_close(ch)
        r1 = tl.fill_constant([1, 1], "float32", 0.0)
        r2 = tl.fill_constant([1, 1], "float32", 0.0)
        s1 = fluid.channel_recv(ch, r1)
        s2 = fluid.channel_recv(ch, r2)
        total = fluid.layers.elementwise_add(r1, r2)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (tot, st1, st2) = exe.run(
            main, feed={"x": np.array([[5.0]], np.float32)},
            fetch_list=[total, s1, s2])
    assert float(np.asarray(tot).flatten()[0]) == 25.0   # 10 + 15
    assert bool(np.asarray(st1).flatten()[0])
    assert bool(np.asarray(st2).flatten()[0])


def test_channel_recv_after_close_reports_status():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        ch = fluid.make_channel(dtype="float32", capacity=1)
        fluid.channel_close(ch)
        r = tl.fill_constant([1], "float32", -1.0)
        status = fluid.channel_recv(ch, r)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        (st, rv) = exe.run(main, fetch_list=[status, r])
    assert not bool(np.asarray(st).flatten()[0])
    # value untouched on failed recv
    assert float(np.asarray(rv).flatten()[0]) == -1.0
