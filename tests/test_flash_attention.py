"""Pallas flash-attention kernel tests (interpret mode on the CPU mesh);
numeric parity + gradient parity against plain attention."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas_kernels import flash_attention
from paddle_tpu.parallel import local_attention


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    rng = np.random.RandomState(0)
    B, S, H, D = 2, 128, 2, 32
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = local_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grads(causal):
    rng = np.random.RandomState(1)
    B, S, H, D = 1, 64, 2, 16
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        return jnp.sum(local_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4,
                                   err_msg="d" + name)


def test_flash_attention_fallback_odd_length():
    rng = np.random.RandomState(2)
    B, S, H, D = 1, 10, 2, 8
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    # explicit block 64 does not divide S=10 -> the local_attention
    # fallback branch must run (and honor causal + scale)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    ref = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_under_jit():
    rng = np.random.RandomState(3)
    B, S, H, D = 1, 64, 2, 16
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    f = jax.jit(lambda a, b, c: flash_attention(a, b, c, causal=True,
                                                block_q=32, block_k=32))
    out = f(q, k, v)
    ref = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
