"""Pallas flash-attention kernel tests (interpret mode on the CPU mesh):
numeric + gradient parity against plain attention, off-chip TPU lowering
of the forward AND fused backward kernels, autotuner-cache semantics,
and the bench_attention --smoke/--tune plumbing."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.flags import FLAGS, set_flags, get_flags
from paddle_tpu.ops import attention_tuning
from paddle_tpu.ops.pallas_kernels import flash_attention
from paddle_tpu.parallel import local_attention


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    rng = np.random.RandomState(0)
    B, S, H, D = 2, 128, 2, 32
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_kv=64)
    ref = local_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grads(causal):
    """The fused backward kernel pair (dq + dkv) against plain-XLA AD —
    asymmetric fwd/bwd blocks so all four geometry knobs engage."""
    rng = np.random.RandomState(1)
    B, S, H, D = 1, 64, 2, 16
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32,
                            block_kv=16, block_q_bwd=16, block_kv_bwd=32)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        return jnp.sum(local_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4,
                                   err_msg="d" + name)


def test_flash_attention_lse_residual():
    """return_lse (the ring-hop merge residual) matches a dense
    logsumexp, and its cotangent flows through the fused backward."""
    rng = np.random.RandomState(7)
    B, S, H, D = 1, 64, 2, 16
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    out, lse = flash_attention(q, k, v, causal=True, return_lse=True,
                               block_q=32, block_kv=32)
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k) / np.sqrt(D)
    mask = jnp.arange(S)[None, :] > jnp.arange(S)[:, None]
    s = jnp.where(mask[None, :, None, :], -1e30, s)
    ref = jax.nn.logsumexp(s, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref), atol=2e-5)

    def loss(q, k, v):
        o, lse = flash_attention(q, k, v, causal=True, return_lse=True,
                                 block_q=32, block_kv=32)
        return jnp.sum(o * o) + jnp.sum(jnp.sin(lse))

    def loss_ref(q, k, v):
        o = local_attention(q, k, v, causal=True)
        s_ = jnp.einsum("bqhd,bkhd->bqhk", q, k) / np.sqrt(D)
        s_ = jnp.where(mask[None, :, None, :], -1e30, s_)
        return jnp.sum(o * o) + jnp.sum(
            jnp.sin(jax.nn.logsumexp(s_, axis=-1)))

    gf = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4,
                                   err_msg="d" + name)


def test_flash_attention_fallback_odd_length():
    rng = np.random.RandomState(2)
    B, S, H, D = 1, 10, 2, 8
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    # explicit block 64 does not divide S=10 -> the local_attention
    # fallback branch must run (and honor causal + scale)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_kv=64)
    ref = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_under_jit():
    rng = np.random.RandomState(3)
    B, S, H, D = 1, 64, 2, 16
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    f = jax.jit(lambda a, b, c: flash_attention(a, b, c, causal=True,
                                                block_q=32, block_kv=32))
    out = f(q, k, v)
    ref = local_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_op_shape_inference():
    """The op's output shape must resolve at BUILD time (jax.eval_shape
    through the lowering): a flash_attention feeding an fc is exactly
    the transformer-block composition, and with the old
    platform_dependent dispatch eval_shape threw, the output var kept
    shape None, and the downstream fc crashed — the transformer could
    not even be built on a CPU host."""
    import paddle_tpu.fluid as fluid
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[16, 32], dtype="float32")
        att = fluid.layers.flash_attention(x, x, x, num_heads=4,
                                           causal=True)
        assert tuple(att.shape) == (-1, 16, 32), att.shape
        proj = fluid.layers.fc(att, size=8, num_flatten_dims=2)
        assert tuple(proj.shape) == (-1, 16, 8), proj.shape


def test_flash_attention_block_k_alias():
    """block_k (pre-tuning API) keeps meaning block_kv."""
    rng = np.random.RandomState(4)
    B, S, H, D = 1, 64, 2, 16
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    a = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    b = flash_attention(q, k, v, causal=True, block_q=32, block_kv=32)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0)


# ---------------------------------------------------------------------------
# off-chip TPU lowering: forward AND fused backward must produce Mosaic
# custom calls across causal/dtype/block-geometry axes
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "causal,dtype,bq,bkv",
    [(True, "bfloat16", 128, 128),
     (True, "bfloat16", 256, 128),
     (True, "bfloat16", 128, 256),
     (False, "bfloat16", 128, 128),
     (True, "float32", 128, 128),
     (False, "float32", 256, 256)])
def test_fwd_and_bwd_kernels_lower_for_tpu_offchip(causal, dtype, bq, bkv):
    """Pallas -> Mosaic conversion happens at LOWERING time, so the whole
    kernel pair is checkable without a chip: a TPU export of the
    gradient must carry THREE tpu_custom_calls (fwd + bwd-dq + bwd-dkv),
    each with a serialized Mosaic module."""
    from jax import export as jax_export

    def fn(q, k, v):
        def loss(q, k, v):
            o = flash_attention(q, k, v, causal=causal, block_q=bq,
                                block_kv=bkv, interpret=False)
            return jnp.sum(o.astype(jnp.float32))
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    spec = jax.ShapeDtypeStruct((2, 512, 2, 128), jnp.dtype(dtype))
    exp = jax_export.export(jax.jit(fn), platforms=["tpu"])(
        spec, spec, spec)
    n = exp.mlir_module().count("tpu_custom_call")
    assert n >= 3, "expected fwd+dq+dkv Mosaic kernels, found %d" % n


# ---------------------------------------------------------------------------
# autotuner cache: hit/miss, flag override, deterministic selection,
# trace-time consultation
# ---------------------------------------------------------------------------


@pytest.fixture
def tune_cache(tmp_path):
    old = get_flags(["attention_tune_cache", "flash_block_q",
                     "flash_block_kv", "flash_block_q_bwd",
                     "flash_block_kv_bwd"])
    path = str(tmp_path / "attn_cache.json")
    set_flags({"attention_tune_cache": path, "flash_block_q": 0,
               "flash_block_kv": 0, "flash_block_q_bwd": 0,
               "flash_block_kv_bwd": 0})
    yield path
    set_flags(old)


def test_tune_cache_miss_falls_back_to_heuristic(tune_cache):
    cfg = attention_tuning.get_config(1024, 128, True, "bfloat16")
    assert cfg == attention_tuning.default_config(1024, 128)
    assert cfg.block_q == 128 and cfg.block_kv == 128
    # heuristic degrades with the sequence, never fails to divide
    assert attention_tuning.get_config(96, 64, False, "float32").block_q \
        == 32
    # nothing divides a prime length -> None (caller takes the XLA path)
    assert attention_tuning.get_config(97, 64, False, "float32") is None


def test_tune_cache_hit_and_invalidation(tune_cache):
    assert attention_tuning.lookup(2048, 128, True, "bfloat16") is None
    cfg = attention_tuning.AttentionConfig(256, 512, 128, 256)
    attention_tuning.record(2048, 128, True, "bfloat16", cfg,
                            extra={"fwd_bwd_ms": 1.0})
    got = attention_tuning.get_config(2048, 128, True, "bfloat16")
    assert got == cfg
    # key is exact: other causal/dtype/shape stay misses
    assert attention_tuning.lookup(2048, 128, False, "bfloat16") is None
    assert attention_tuning.lookup(2048, 128, True, "float32") is None
    assert attention_tuning.lookup(1024, 128, True, "bfloat16") is None
    # a second record (fresh mtime) supersedes without a process restart
    cfg2 = attention_tuning.AttentionConfig(512, 512)
    os.utime(tune_cache, (0, 0))   # force an mtime change on rewrite
    attention_tuning.record(2048, 128, True, "bfloat16", cfg2)
    assert attention_tuning.get_config(2048, 128, True, "bfloat16") == cfg2


def test_tune_cache_flag_override(tune_cache):
    cfg = attention_tuning.AttentionConfig(256, 256, 256, 256)
    attention_tuning.record(4096, 128, True, "bfloat16", cfg)
    set_flags({"flash_block_q": 512})
    got = attention_tuning.get_config(4096, 128, True, "bfloat16")
    # the overridden field wins; the rest still comes from the cache
    assert got.block_q == 512
    assert (got.block_kv, got.block_q_bwd, got.block_kv_bwd) \
        == (256, 256, 256)


def test_tune_cache_deterministic_selection(tune_cache):
    a = attention_tuning.get_config(1024, 64, True, "float32")
    b = attention_tuning.get_config(1024, 64, True, "float32")
    assert a == b and a is not b


def test_flash_attention_consults_cache_at_trace_time(tune_cache,
                                                      monkeypatch):
    """The kernel launch must ride the cached geometry when no explicit
    blocks are passed."""
    import paddle_tpu.ops.pallas_kernels as pk
    attention_tuning.record(
        64, 16, True, "float32",
        attention_tuning.AttentionConfig(16, 32, 32, 16))
    seen = {}
    real = pk._flash_fwd_pallas

    def spy(q, k, v, scale, causal, block_q, block_kv, interpret):
        seen["blocks"] = (block_q, block_kv)
        return real(q, k, v, scale, causal, block_q, block_kv, interpret)

    monkeypatch.setattr(pk, "_flash_fwd_pallas", spy)
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(1, 64, 2, 16).astype(np.float32))
    flash_attention(q, q, q, causal=True)
    assert seen["blocks"] == (16, 32)
    # explicit per-call args still beat the cache
    flash_attention(q, q, q, causal=True, block_q=32, block_kv=32)
    assert seen["blocks"] == (32, 32)


# ---------------------------------------------------------------------------
# bench_attention --smoke: the full bench/tune/cache plumbing on CPU —
# kernel-perf tooling regressions surface in tier-1, chip not required
# ---------------------------------------------------------------------------


def test_bench_attention_smoke_tune_writes_cache(tmp_path):
    cache = str(tmp_path / "cache.json")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "bench_attention.py"),
         "--smoke", "--tune", "--tune_cache", cache, "--seq_lens", "64"],
        capture_output=True, text=True, timeout=420, cwd=repo)
    assert proc.returncode == 0, proc.stderr[-800:]
    recs = [json.loads(l) for l in proc.stdout.splitlines()
            if l.startswith("{")]
    metrics = {r["metric"] for r in recs}
    assert {"attention_tune", "attention_tuned",
            "attention_fwd_bwd_ms"} <= metrics, metrics
    flash_rows = [r for r in recs if r["metric"] == "attention_fwd_bwd_ms"
                  and r["variant"] == "flash"]
    assert flash_rows and all(r["value"] is not None for r in flash_rows)
    with open(cache) as f:
        entries = json.load(f)
    # smoke geometry: B,H,D forced to 2,2,64; one causal f32 entry at S=64
    (key,) = entries.keys()
    assert key == "S64_D64_c1_float32", key
    e = entries[key]
    assert 64 % e["block_q"] == 0 and 64 % e["block_kv"] == 0
    assert e["backend"] == "cpu-interpret"
