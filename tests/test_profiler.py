"""Profiler tests (reference profiler.cc event tables printed by
DisableProfiler; tools/timeline.py chrome-trace export)."""

import json
import os

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.framework import Program


def _tiny_run():
    main, startup = Program(), Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4], dtype="float32")
        y = fluid.layers.fc(x, size=4)
        loss = fluid.layers.mean(y)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
            fetch_list=[loss])


def test_profiler_sorted_table(tmp_path, capsys):
    report = tmp_path / "profile.txt"
    with fluid.profiler.profiler(sorted_key="total",
                                 profile_path=str(report)):
        with fluid.profiler.RecordEvent("forward_and_fetch"):
            _tiny_run()
        with fluid.profiler.RecordEvent("forward_and_fetch"):
            _tiny_run()
    out = capsys.readouterr().out
    assert "Profiling Report" in out
    assert "forward_and_fetch" in out
    # aggregated: 2 calls on one row
    row = [l for l in out.splitlines() if "forward_and_fetch" in l][0]
    assert row.split()[1] == "2"
    assert report.exists() and "forward_and_fetch" in report.read_text()


def test_start_stop_and_timeline_export(tmp_path, capsys):
    trace_dir = str(tmp_path / "trace")
    fluid.profiler.start_profiler("All", output_dir=trace_dir)
    with fluid.profiler.RecordEvent("step"):
        _tiny_run()
    fluid.profiler.stop_profiler(sorted_key="max",
                                 profile_path=str(tmp_path / "p.txt"))
    out = capsys.readouterr().out
    assert "step" in out
    # chrome-trace export (tools/timeline.py analogue)
    try:
        path = fluid.profiler.export_chrome_tracing(trace_dir)
    except FileNotFoundError:
        return  # device tracing unavailable on this backend — table-only
    data = json.load(open(path))
    assert "traceEvents" in data


def test_reset_profiler():
    fluid.profiler.start_profiler("All", output_dir=None)
    with fluid.profiler.RecordEvent("r1"):
        pass
    fluid.profiler.reset_profiler()
    fluid.profiler.stop_profiler()
    # after reset, the r1 event is gone (no output assertion needed; just
    # ensure the internal table is empty)
    from paddle_tpu.fluid.profiler import _host_events
    assert "r1" not in _host_events


def test_concurrent_record_events_no_lost_updates():
    """Thread-safety (OBSERVABILITY.md satellite): _host_events was
    mutated without a lock, so concurrent batcher lanes / prefetch
    threads could lose calls (two threads read the same count, both
    write count+1).  The hammer makes that race near-certain without
    the lock: every call must be counted exactly once."""
    import threading
    from paddle_tpu.fluid import profiler

    profiler.reset_profiler()
    n_threads, n_calls = 4, 400

    def hammer():
        for _ in range(n_calls):
            profiler._record("hammered", 1.0)

    threads = [threading.Thread(target=hammer)
               for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    calls, total_ms, mn, mx = profiler._host_events["hammered"]
    assert calls == n_threads * n_calls, \
        "lost %d updates to the race" % (n_threads * n_calls - calls)
    assert total_ms == float(n_threads * n_calls)
    assert mn == mx == 1.0
    profiler.reset_profiler()
